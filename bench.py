"""Headline benchmark: particle-move throughput of the tallied walk.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workload (BASELINE.json configs[0] analogue): a 48k-tet box mesh —
the scale of the OpenMC pincell's ~10k-tet Gmsh mesh, rounded up — with
500k particles per batch doing tallied MoveToNextLocation steps
(reference PumiTallyImpl.cpp:66-149) along a precomputed random-walk
trajectory that stays strictly inside the mesh.

TWO protocols are measured, both reported:

- ``two_phase``: the reference's actual per-step protocol — origins,
  flying flags and weights passed by the caller EVERY call (f64
  buffers, per the reference's ``double*`` protocol, PumiTally.h:87-89).
  The engine's default ``auto_continue`` detection applies, exactly as
  it would for a physics host app: when the staged origins echo the
  previous destinations and the device proved the committed state
  equals them, the origin upload and phase A are skipped (bit-exact).
- ``two_phase_forced``: the same calls with ``auto_continue=False`` —
  origins staged host→device and the phase-A pass dispatched every
  move; the worst-case protocol cost.
- ``continue``: the TPU-native fast path (``origins=None``) valid when
  no particle was resampled since the last move; phase A and the origin
  upload are skipped.

The headline ``value`` stays ``particle_moves_per_sec`` of the continue
path (the metric recorded in BENCH_r01, so rounds compare
like-for-like); ``two_phase_moves_per_sec`` and ``histories_per_sec``
ride alongside. A "history" is one particle's full MOVES-segment
trajectory: histories/sec = completed trajectories per second of the
two-phase protocol — the number a physics host app experiences.

``vs_baseline`` is apples-to-apples: the IDENTICAL two-phase workload
(same mesh, same N, same moves, same staged buffers) run on the CPU
backend of this same engine in a subprocess — a stand-in for the
reference's Kokkos-Serial path, which cannot be built here (its
dependency stack needs network access). vs_baseline =
tpu_two_phase_rate / cpu_two_phase_rate.

Self-check: sum(flux) must equal the analytic total track length
(every segment stays inside the mesh, so conservation is exact in
exact arithmetic). The comparison accumulates in f64 on the host and
HARD-FAILS (exit 1) beyond 1e-6 relative — a silent tally corruption
cannot report a perf number.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

MESH_DIV = int(os.environ.get("PUMIUMTALLY_BENCH_DIV", 20))  # 20³ cells → 48000 tets
N = int(os.environ.get("PUMIUMTALLY_BENCH_N", 500_000))
MOVES = int(os.environ.get("PUMIUMTALLY_BENCH_MOVES", 8))
MEAN_STEP = 0.25  # mean segment length: ~15 tet crossings per move
CONSERVATION_RTOL = 1e-6

# North-star proxy (BASELINE.json: "match A100 Kokkos-CUDA
# histories/sec"). The reference publishes no number (BASELINE.md), so
# the target is derived, conservatively, from hardware ratios:
#   - the walk is row-gather HBM-bandwidth-bound on both architectures
#     (roofline: docs/PERF_NOTES.md, tools/roofline.py);
#   - v5e measured gather-bound ceiling: 2.9-4.2M moves/s (midpoint
#     3.55M);
#   - A100-80GB HBM2e 2039 GB/s vs v5e HBM2 819 GB/s -> x2.49;
#   - assume the reference's Kokkos-CUDA walk ACHIEVES its A100 gather
#     roofline (an upper bound on the reference — atomics contention
#     and Kokkos overheads mean it realistically doesn't), making this
#     a deliberately hard target: 3.55M x 2.49 ≈ 8.8M moves/s.
# vs_north_star = headline / this. Derivation recorded in BASELINE.md.
NORTH_STAR_MOVES_PER_SEC = 8.8e6


def make_trajectory(rng, n: int, moves: int, box=None) -> list:
    """src + `moves` destination arrays, all strictly inside the box
    (unit cube by default; pass ``box=[lx,ly,lz]`` for other extents)."""
    box = np.ones(3) if box is None else np.asarray(box, np.float64)
    pts = [rng.uniform(0.05, 0.95, (n, 3)) * box]
    for _ in range(moves):
        step = rng.normal(scale=MEAN_STEP / np.sqrt(3.0), size=(n, 3))
        pts.append(np.clip(pts[-1] + step, 0.02 * box, 0.98 * box))
    return pts


def timed_moves(t, pts, moves: int, drive) -> dict:
    """Shared timing scaffold: warmup move 1 (compiles; the scalar
    fetch is the real sync — block_until_ready is lazy on this
    backend), then time moves 2..moves+1 and hard-check conservation
    over ALL moves (flux accumulates from the warmup on).

    Each row also records its COMPILE counts (retrace tripwire,
    docs/STATIC_ANALYSIS.md): ``compiles.total`` is backend compiles
    over the whole workload (warmup included — that is where the one
    expected compile per entry point lands), ``compiles.timed`` the
    compiles inside the measured window (a healthy engine shows 0 —
    every timed move hits the jit cache), and the remaining keys the
    per-entry-point breakdown from profiling.register_entry_point."""
    import jax.numpy as jnp

    from pumiumtally_tpu.utils.profiling import retrace_guard

    n = pts[0].shape[0]
    with retrace_guard(raise_on_exceed=False) as guard:
        drive(1)
        float(jnp.sum(t.flux))
        with retrace_guard(raise_on_exceed=False) as timed_guard:
            t0 = time.perf_counter()
            for m in range(2, moves + 2):
                drive(m)
            total_flux = float(np.float64(jnp.sum(t.flux)))  # forces the pipeline
            dt = time.perf_counter() - t0
    rel = check_conservation(total_flux, pts, 1, moves + 1)
    return {
        "moves_per_sec": n * moves / dt,
        "histories_per_sec": n / dt,
        "conservation_rel_err": rel,
        "compiles": {
            "total": guard.total_compiles,
            "timed": timed_guard.total_compiles,
            **guard.compiles,
        },
    }


def check_conservation(total_flux: float, pts, first_move: int, last_move: int):
    """sum(flux) vs analytic Σ‖dest−src‖ accumulated in f64; hard-fail."""
    expect = 0.0
    for m in range(first_move, last_move + 1):
        d = pts[m].astype(np.float64) - pts[m - 1].astype(np.float64)
        expect += float(np.linalg.norm(d, axis=1).sum())
    rel = abs(total_flux - expect) / expect
    if rel > CONSERVATION_RTOL:
        print(
            f"# FATAL: conservation off by {rel:.2e} "
            f"(got {total_flux!r}, want {expect!r})",
            file=sys.stderr,
        )
        sys.exit(1)
    return rel


_TUNED_KNOBS: dict | None = None


def tuned_knobs() -> dict:
    """Walk-kernel knobs measured ONCE on this backend for the bench
    mesh (utils/autotune.py; disable with PUMIUMTALLY_BENCH_AUTOTUNE=0).
    Tuning cannot change physics: the sweep MEASURES an approximate
    candidate (the bf16 two-tier tables — documented tie-class
    divergence) but the autotuner never ADOPTS it without
    allow_approximate, so the returned knobs always specify a walk
    bitwise-equivalent to the defaults and the conservation gate still
    applies unchanged to the tuned engine."""
    global _TUNED_KNOBS
    if _TUNED_KNOBS is None:
        if os.environ.get("PUMIUMTALLY_BENCH_AUTOTUNE", "1") == "0":
            _TUNED_KNOBS = {}
        else:
            try:
                from pumiumtally_tpu import build_box
                from pumiumtally_tpu.utils.autotune import autotune_walk

                mesh = build_box(1.0, 1.0, 1.0, MESH_DIV, MESH_DIV, MESH_DIV)
                cfg, report = autotune_walk(
                    mesh, n_particles=min(N, 200_000), moves=2,
                    mean_step=MEAN_STEP,
                )
                # walk_kwargs() is already normalized (default-equal
                # knobs dropped), so a winner identical to the kernel
                # defaults yields {} here — and the provenance string
                # below then reports the run as untuned.
                _TUNED_KNOBS = {
                    f"walk_{k}": v for k, v in cfg.walk_kwargs()
                }
                # The ADOPTED entry's rate, not report[0]'s: an
                # approximate-tier candidate may top the raw sweep
                # without being adopted — and an all-approximate sweep
                # adopts nothing (defaults kept), which must not pair
                # the defaults with the approximate rate.
                adopted = next(
                    (r for r in report if r.get("adopted")), None
                )
                note = (
                    f"({adopted['moves_per_sec'] / 1e6:.2f}M moves/s in "
                    "the sweep)" if adopted
                    else "(no adoptable candidate; defaults kept)"
                )
                print(f"# autotuned: {dict(cfg.walk_kwargs())} {note}",
                      file=sys.stderr)
            except Exception as e:  # noqa: BLE001 — tuning is best-effort
                print(f"# autotune failed, using default knobs: {e}",
                      file=sys.stderr)
                _TUNED_KNOBS = {}
    return _TUNED_KNOBS


def run_workload(n: int, moves: int, mode: str) -> dict:
    """Timed rates for `moves` tallied move steps of n particles.

    mode: "two_phase" passes origins+flying+weights per call (the
    reference protocol; the engine's default auto_continue applies);
    "two_phase_forced" disables auto_continue so origins stage and
    phase A dispatches every move; "continue" uses the origins=None
    fast path.
    """
    from pumiumtally_tpu import PumiTally, TallyConfig, build_box

    mesh = build_box(1.0, 1.0, 1.0, MESH_DIV, MESH_DIV, MESH_DIV)
    cfg = TallyConfig(
        check_found_all=False,
        auto_continue=(mode != "two_phase_forced"),
        fenced_timing=False,  # let moves pipeline; timed_moves syncs at the end
        **tuned_knobs(),
    )
    t = PumiTally(mesh, n, cfg)
    rng = np.random.default_rng(0)
    pts = make_trajectory(rng, n, moves + 1)  # +1 warmup move
    t.CopyInitialPosition(pts[0].reshape(-1).copy())

    def drive(m: int) -> None:
        dests = pts[m].reshape(-1).copy()
        if mode.startswith("two_phase"):
            # Full reference protocol: origins (= committed positions —
            # the trajectory never exits, so committed == previous
            # dests), flying and weights staged f64→device every call.
            origins = pts[m - 1].reshape(-1).copy()
            flying = np.ones(n, dtype=np.int8)
            weights = np.ones(n, dtype=np.float64)
            t.MoveToNextLocation(origins, dests, flying, weights)
        else:
            t.MoveToNextLocation(None, dests)

    return timed_moves(t, pts, moves, drive)


def run_vmem_blocked(n: int, moves: int) -> dict:
    """Continue-mode rate of the single-chip VMEM sub-split engine on
    the same box workload (ops/vmem_walk.py): the mesh splits into
    VMEM-sized blocks (PUMIUMTALLY_BENCH_VMEM_BOUND, default 1024
    elements) and the local walk runs as the one-hot MXU Pallas
    kernel. Recorded alongside the headline so the driver captures an
    on-chip number for the blocked path whenever it runs; best-effort
    in main() — a Mosaic lowering failure must not cost the bench."""
    import jax

    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box
    from jax.sharding import Mesh

    bound = int(os.environ.get("PUMIUMTALLY_BENCH_VMEM_BOUND", 1024))
    mesh = build_box(1.0, 1.0, 1.0, MESH_DIV, MESH_DIV, MESH_DIV)
    dm = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(device_mesh=dm, capacity_factor=2.0,
                    walk_vmem_max_elems=bound,
                    check_found_all=False, fenced_timing=False),
    )
    # Seed 0: same trajectories as the other headline candidates (see
    # run_gather_blocked).
    rng = np.random.default_rng(0)
    pts = make_trajectory(rng, n, moves + 1)
    t.CopyInitialPosition(pts[0].reshape(-1).copy())

    def drive(m: int) -> None:
        t.MoveToNextLocation(None, pts[m].reshape(-1).copy())

    res = timed_moves(t, pts, moves, drive)
    res["blocks_per_chip"] = t.engine.blocks_per_chip
    res["block_elems"] = t.engine.part.L
    res["walk_rounds_last_move"] = t.engine.last_walk_rounds
    return res


def run_gather_blocked(n: int, moves: int) -> dict:
    """Continue-mode rate of the single-device GATHER sub-split engine
    (walk_block_kernel='gather'): the mesh splits into small blocks
    (PUMIUMTALLY_BENCH_BLOCK_ELEMS, default 3072 — the measured
    small-table sweet spot, docs/PERF_NOTES.md round 4: 2.2-2.4M
    moves/s at L<=3k) and walk_local runs block-by-block with lax.map,
    keeping each block's table resident on-chip. Pure XLA — no Mosaic
    risk — so it runs in-process. A headline candidate: main() reports
    the best continue-mode engine as the round's value."""
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box

    bound = int(os.environ.get("PUMIUMTALLY_BENCH_BLOCK_ELEMS", 3072))
    mesh = build_box(1.0, 1.0, 1.0, MESH_DIV, MESH_DIV, MESH_DIV)
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(capacity_factor=2.0,
                    walk_vmem_max_elems=bound,
                    walk_block_kernel="gather",
                    check_found_all=False, fenced_timing=False),
    )
    # Seed 0: the IDENTICAL trajectory set as run_workload's continue
    # row, so the headline candidates differ only in engine (knobs stay
    # the engine's defaults — the autotuned knobs target the monolithic
    # cascade and do not transfer).
    rng = np.random.default_rng(0)
    pts = make_trajectory(rng, n, moves + 1)
    t.CopyInitialPosition(pts[0].reshape(-1).copy())

    def drive(m: int) -> None:
        t.MoveToNextLocation(None, pts[m].reshape(-1).copy())

    res = timed_moves(t, pts, moves, drive)
    res["blocks_per_chip"] = t.engine.blocks_per_chip
    res["block_elems"] = t.engine.part.L
    res["walk_rounds_last_move"] = t.engine.last_walk_rounds
    return res


def run_blocked_profile(n: int, moves: int) -> dict:
    """Component budget of the gather-blocked engine: per-round
    walk / migrate / occupancy / bookkeeping ms from the profiled
    phase driver (parallel/partition.py PhaseProfile) plus rounds,
    per-block dispatches, and the frontier-size max/mean — the
    frontier-local-migration evidence row (docs/PERF_NOTES.md
    "Frontier-local migration"). Best-effort in main(): a failure may
    not cost the headline. Reduced shape (200k particles by default)
    like the table_precision row; the profiled driver pays one sync
    per section per round by design, so its absolute rate is NOT the
    engine's throughput — only the per-component ratios are the
    signal. PUMIUMTALLY_BENCH_CAP_FRONTIER sizes the slab (default
    n//8; an overflowing round falls back and is counted in
    fallback_rounds, honestly)."""
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box
    from pumiumtally_tpu.parallel.partition import PhaseProfile

    bound = int(os.environ.get("PUMIUMTALLY_BENCH_BLOCK_ELEMS", 3072))
    cap_frontier = int(
        os.environ.get("PUMIUMTALLY_BENCH_CAP_FRONTIER", max(1, n // 8))
    )
    mesh = build_box(1.0, 1.0, 1.0, MESH_DIV, MESH_DIV, MESH_DIV)
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(capacity_factor=2.0,
                    walk_vmem_max_elems=bound,
                    walk_block_kernel="gather",
                    cap_frontier=cap_frontier,
                    check_found_all=False, fenced_timing=False),
    )
    rng = np.random.default_rng(0)
    pts = make_trajectory(rng, n, moves + 1)
    t.CopyInitialPosition(pts[0].reshape(-1).copy())
    eng = t.engine
    dt = eng.state["x"].dtype

    def profiled_move(m: int, prof: PhaseProfile) -> None:
        import jax.numpy as jnp

        eng.move(None, jnp.asarray(pts[m], dt),
                 jnp.asarray(np.ones(n, np.int8)),
                 jnp.asarray(np.ones(n), dt), profile=prof)

    profiled_move(1, PhaseProfile())  # warmup: compiles the programs
    prof = PhaseProfile()
    for m in range(2, moves + 2):
        profiled_move(m, prof)
    import jax.numpy as jnp

    total_flux = float(np.float64(jnp.sum(t.flux)))
    rel = check_conservation(total_flux, pts, 1, moves + 1)
    rec = prof.as_dict()
    rec.update({
        "conservation_rel_err": rel,
        "blocks_per_chip": eng.blocks_per_chip,
        "block_elems": eng.part.L,
        "particles": n,
        "moves": moves,
    })
    return rec


def run_pincell(n: int, moves: int, tuned: bool = False) -> dict:
    """Continue-mode rate on the pincell O-grid (~22k tets) — the
    BASELINE configs[0-1] geometry: anisotropic tets, curved fuel
    rings, a square cell boundary.

    ``tuned=False`` keeps kernel defaults so the number compares
    round-over-round. ``tuned=True`` (the r5 flagship-tuning row,
    VERDICT r4 #5) runs the autotuner ON THE PINCELL MESH on the
    measured backend first — box-mesh knobs don't transfer (the
    optimum is mesh-dependent, docs/PERF_NOTES.md round 4)."""
    from pumiumtally_tpu import PumiTally, TallyConfig
    from pumiumtally_tpu.mesh.pincell import FLAGSHIP_PINCELL, build_pincell

    pitch = FLAGSHIP_PINCELL["pitch"]
    height = FLAGSHIP_PINCELL["height"]
    mesh, _ = build_pincell(**FLAGSHIP_PINCELL)
    knobs = {}
    if tuned:
        from pumiumtally_tpu.utils.autotune import autotune_walk

        cfg, _report = autotune_walk(
            mesh, n_particles=min(n, 200_000), moves=2,
            mean_step=MEAN_STEP,  # workload derives from the mesh bbox
        )
        knobs = {f"walk_{k}": v for k, v in cfg.walk_kwargs()}
        print(f"# pincell autotuned: {dict(cfg.walk_kwargs())}",
              file=sys.stderr)
    t = PumiTally(mesh, n, TallyConfig(check_found_all=False,
                                       fenced_timing=False, **knobs))
    rng = np.random.default_rng(1)
    pts = make_trajectory(rng, n, moves + 1, box=[pitch, pitch, height])
    t.CopyInitialPosition(pts[0].reshape(-1).copy())

    def drive(m: int) -> None:
        t.MoveToNextLocation(None, pts[m].reshape(-1).copy())

    res = timed_moves(t, pts, moves, drive)
    res["knobs"] = knobs
    return res


def run_table_precision_ab() -> dict | None:
    """Component row: f32 single-tier vs bf16 two-tier walk tables
    (tools/exp_table_precision_ab.py run_ab) — rates interleaved,
    select-tier bytes provenance, flux divergence vs the f32 arm.
    Makes the byte-halving bet (or a regression) visible in every
    round bench; best-effort. The headline engines stay on the f32
    default — this row is the measured evidence for (or against)
    flipping walk_table_dtype. Reduced shape (200k particles, 3 moves)
    so the extra row costs minutes, not a second full bench."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_table_precision_ab

    return exp_table_precision_ab.run_ab(
        n=min(N, 200_000), div=MESH_DIV, moves=3, trials=3
    )


def run_pallas_walk_ab() -> dict | None:
    """Component row: the one-kernel Pallas walk (r17,
    tools/exp_pallas_walk_ab.py run_ab) — fused select/refine/scatter
    with grid-pipelined table streaming (walk_kernel='pallas') vs the
    bf16 gather sub-split on the identical partitioned workload, both
    arms forced into the blocked regime. The tool enforces its gates
    before reporting any rate: a kernel-level INTERPRET-mode bitwise
    pin vs walk_local, bitwise positions/elem_ids between the timed
    arms, flux in the reassociation class, conservation, and the
    compiles-healthy contract (``compiles.timed == 0``). The record
    carries the 80 B vs 52 B modeled bytes/crossing provenance. On CPU
    the pallas arm runs in interpret mode — the row certifies
    correctness and arms the on-chip ship/kill decision
    (docs/PERF_NOTES.md); the CPU "speedup" is NOT that number.
    Reduced shape (interpret mode is slow); best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_pallas_walk_ab

    return exp_pallas_walk_ab.run_ab(
        n=min(N, 8192), div=min(MESH_DIV, 6), moves=2, trials=2,
        block_elems=512,
    )


def run_batch_stats() -> dict | None:
    """Component row: the batch-statistics subsystem's cost and its
    trigger behavior (tools/exp_stats_ab.py run_ab) — stats-on vs
    stats-off rates on the identical workload (flux parity asserted
    bitwise inside the tool), the fenced per-close cost of the lane
    update and of the full close+trigger evaluation (one scalar D2H),
    and the convergence trace (monotone relative-error decay, trigger
    fire point, 1/sqrt(N) batches-remaining projection). The row's
    ``compiles.timed == 0`` is the close-batch/trigger-eval
    compiles-healthy contract (both entry points compile once, in the
    warmup batches). Reduced shape (100k particles) like the other
    component rows; best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_stats_ab

    return exp_stats_ab.run_ab(
        n=min(N, 100_000), div=MESH_DIV, moves=2, batches=10
    )


def run_scoring() -> dict | None:
    """Component row: the filtered-scoring subsystem's cost
    (tools/exp_scoring_ab.py run_ab) — scoring-on (2-bin energy
    filter x flux/heating/events lanes riding every walk) vs
    scoring-off rates on the identical corridor workload, with the
    flux parity AND bin-telescoping gates asserted BITWISE inside the
    tool, the fenced per-move scoring cost, and the compiles-healthy
    contract — ``compiles.timed == 0``: the scoring-armed walk and
    the ``score_bins`` resolution compile once each in warmup.
    Reduced shape (100k particles) like the other component rows;
    best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_scoring_ab

    return exp_scoring_ab.run_ab(n=min(N, 100_000), div=MESH_DIV, moves=4)


def run_resilience_ab() -> dict | None:
    """Component row: the fault-tolerance subsystem's cost
    (tools/exp_resilience_ab.py run_ab) — autosave-on (one atomic
    digest-sealed generation per batch close) vs autosave-off rates on
    the identical workload (flux parity asserted bitwise inside the
    tool: autosave only reads engine state), the fenced per-save cost
    (fetch + compress + sha256 + atomic rename) and on-disk generation
    size, and the host-side-only contract — ``compiles.timed == 0``:
    the resilience layer adds no jitted entry points, so autosave must
    never touch the jit cache. Reduced shape (100k particles) like the
    other component rows; best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_resilience_ab

    return exp_resilience_ab.run_ab(
        n=min(N, 100_000), div=MESH_DIV, moves=2, batches=8
    )


def run_sentinel_ab() -> dict | None:
    """Component row: the runtime-sentinel subsystem's cost
    (tools/exp_sentinel_ab.py run_ab) — sentinel-on (per-move
    on-device audit lanes, one packed scalar fetch) vs sentinel-off
    rates on the identical workload (flux parity asserted bitwise
    inside the tool: the audit only reads engine state and the
    straggler ladder never fires on a healthy run), the fenced
    per-move audit cost, the on-arm health report (zero anomalies
    required), and the compiles-healthy contract —
    ``compiles.timed == 0``: audit_pack compiles once in warmup,
    straggler_retry never on a healthy run. Reduced shape (100k
    particles) like the other component rows; best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_sentinel_ab

    return exp_sentinel_ab.run_ab(
        n=min(N, 100_000), div=MESH_DIV, moves=2, batches=8
    )


def run_service_ab() -> dict | None:
    """Component row: the multi-session service layer's cost
    (tools/exp_service_ab.py run_ab) — a 1-session service vs the
    direct facade on the identical workload (flux parity asserted
    BITWISE inside the tool: the single-session corner of the
    determinism-under-concurrency contract), the fenced-vs-pipelined
    served throughput spread (the measured value of cross-move
    overlap through the futures pipeline), and the compiles-healthy
    contract — ``compiles.timed == 0``: the service adds NO jitted
    entry points, every compile is the facade's own in warmup.
    Reduced shape (100k particles) like the other component rows;
    best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_service_ab

    return exp_service_ab.run_ab(
        n=min(N, 100_000), div=MESH_DIV, moves=2, batches=8
    )


def run_service_fusion_ab() -> dict | None:
    """Component row: cross-session batch fusion (r12,
    tools/exp_fusion_ab.py run_ab) — fused vs unfused serving
    throughput at 1/4/8/32 concurrent sessions on identical
    per-session campaigns, with the per-session BITWISE flux parity
    gate (both arms vs bare-facade solo runs) enforced inside the
    tool, the telemetry-derived device dispatches per move (a K-way
    fused group is ONE dispatch where the unfused arm pays K), and
    the compiles-healthy contract — ``compiles.timed == 0``: the
    fused program compiles once per group composition in the warmup
    pass, never in a measured pass. The ``"streaming"`` sub-row (r20)
    repeats the A/B on StreamingTally facades whose moves coalesce
    CHUNK-WISE (one walk_fused launch per chunk index), at 4/8
    sessions. Reduced per-session shape (pow2 so equal sessions pack
    with zero padding rows); best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_fusion_ab

    # Pow2 FLOOR of the (bounded) per-session batch: equal-sized
    # sessions then pack with zero dead rows (fusion.padded_total).
    n = min(N, 8192)
    n = 1 << (n.bit_length() - 1)
    res = exp_fusion_ab.run_ab(
        n=n, div=min(MESH_DIV, 12), moves=2, batches=8,
    )
    res["streaming"] = exp_fusion_ab.run_ab(
        n=n, div=min(MESH_DIV, 12), moves=2, batches=8,
        facade="stream", chunk_size=max(1, n // 2),
        session_counts=(4, 8),
    )
    return res


def run_service_load() -> dict | None:
    """Headline serving row (r20, tools/exp_service_load.py run_load_row)
    — >= 100 scripted OpenMC-style clients with a DETERMINISTIC seeded
    Poisson arrival schedule (tools/loadgen.py) driven through a
    2-worker SessionRouter: served moves/s, client-observed p50/p99
    submit->resolve latency, per-lane Jain fairness, and refusal
    counts, with the bitwise spot-check parity gate (sampled clients'
    flux vs solo replays of their seeded campaigns) and the
    compiles-healthy contract — ``compiles.timed == 0``: every fused
    group composition the measured run can dispatch is pre-compiled
    by the warmup ladder. Reduced shape; best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_service_load

    return exp_service_load.run_load_row(
        n=min(N, 512), div=min(MESH_DIV, 6), clients=120,
    )


def run_distributed_ab() -> dict | None:
    """Component row: pod-scale distributed campaigns (r13,
    tools/exp_distributed_ab.py run_ab) — the collective particle
    migration (all_gather'd counting-rank keys + ppermute ring) vs the
    global-scatter migrate on the identical partitioned workload, with
    the BITWISE flux-parity gate enforced inside the tool (the
    determinism contract pod campaigns rest on), fenced per-move ms
    for both arms, the modeled per-round migration-collective bytes
    from the engine's actual packed-state layout, and the
    compiles-healthy contract — ``compiles.timed == 0``: the
    collective path is one phase-program variant, compiled in warmup.
    The cross-process subarm (1-proc-x-8 vs 2-proc-x-4 CPU
    subprocesses, global results bitwise) reports
    ``available: false`` honestly on jaxlib builds without
    cross-process CPU collectives. Reduced shape like the other
    component rows; best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_distributed_ab

    return exp_distributed_ab.run_ab(
        n=min(N, 50_000), div=MESH_DIV, moves=2, batches=6
    )


def run_placement_ab() -> dict | None:
    """Component row: topology-aware pod placement (r19,
    tools/exp_placement_ab.py run_ab) — linear vs pod_rcb element
    ownership on the pinned 2-host virtual layout. The tool asserts
    the equal-host degeneracy pin (bitwise), the pinned cross-arm
    equivalence class (positions bitwise, element-id diffs
    boundary-ties only, total flux conserved) and the STRICT modeled
    cross-host byte drop BEFORE timing; then fenced per-move ms both
    arms, interleaved, with the compiles-healthy contract
    (``compiles.timed == 0``). Reduced shape like the other component
    rows; best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_placement_ab

    return exp_placement_ab.run_ab(n=min(N, 50_000), moves=2)


def run_redistribution_ab() -> dict | None:
    """Component row: argsort-vs-counting-rank redistribution cost at
    bench scale (tools/exp_partition_ab.py) — one packed cascade stage
    boundary and one packed migration shuffle, both arms bitwise
    equivalent by construction. Makes the sort-free redistribution win
    (or a regression) visible in every round bench; best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_partition_ab

    return {
        r.pop("row"): r
        for r in (
            exp_partition_ab.bench_cascade_boundary(N),
            exp_partition_ab.bench_migrate_round(N),
        )
    }


def run_frontier_ab() -> dict | None:
    """Component row: full-capacity vs frontier-slab in-loop migration
    (tools/exp_frontier_ab.py bench_migrate_round) at bench capacity,
    at a small (2%) and a large (20%) crossing front — the frontier
    bet's per-round cost on this backend, honest in both regimes (the
    CPU-measured pattern is a win when the front is small and a loss
    when it is a double-digit fraction of capacity; the slab is a
    configured knob precisely because the crossover is workload- and
    backend-dependent). Slab-size invariance is asserted bitwise
    inside the tool before timing. Best-effort."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
    )
    import exp_frontier_ab

    return {
        f"frac_{int(f * 100)}pct": exp_frontier_ab.bench_migrate_round(
            N, frac=f
        )
        for f in (0.02, 0.20)
    }


def preflight_device(max_wait_s: float | None = None) -> None:
    """Fail fast (rc 1) if the accelerator cannot be claimed.

    A killed TPU client can leave the tunnel's device grant stuck, and
    a jax backend init then hangs forever. Probe in SUBPROCESSES (the
    hang is only escapable by killing the process) with retries, so a
    transiently busy tunnel still gets its bench, and a wedged one
    produces a diagnosable failure instead of an eternal hang. The
    default wait is generous (25 min): observed wedges have cleared on
    the scale of tens of minutes to hours, and a late bench beats no
    bench — but the caller (e.g. a round driver with its own budget)
    can cap it via PUMIUMTALLY_BENCH_MAX_WAIT (seconds).
    """
    if max_wait_s is None:
        max_wait_s = float(
            os.environ.get("PUMIUMTALLY_BENCH_MAX_WAIT", 1500.0)
        )
    deadline = time.monotonic() + max_wait_s
    attempt = 0
    fast_failures = 0
    last_err = ""
    while True:
        attempt += 1
        timed_out = False
        # Honor a tight driver budget: a single probe never overshoots
        # the deadline by more than the 30 s floor a live-but-cold
        # tunnel needs to answer.
        probe_timeout = min(150.0, max(30.0, deadline - time.monotonic()))
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp;"
                 "print(float(jnp.sum(jnp.ones(8))))"],
                capture_output=True, text=True, timeout=probe_timeout,
            )
            if r.returncode == 0:
                return
            last_err = r.stderr[-2000:]
            fast_failures += 1
        except subprocess.TimeoutExpired:
            timed_out = True
            last_err = "(probe timed out — wedged device tunnel?)"
        # A quick rc!=0 is deterministic (broken install/driver), not a
        # busy tunnel: don't burn the whole deadline retrying it.
        remaining = deadline - time.monotonic()
        if (not timed_out and fast_failures >= 3) or remaining <= 0:
            print(
                f"# FATAL: accelerator unreachable after {attempt} probe "
                f"attempts; no fresh benchmark number can be measured.\n"
                f"# last probe error:\n{last_err}",
                file=sys.stderr,
            )
            _report_stale_result_or_die()
        # Cap the retry sleep by the remaining budget too (a fixed 30 s
        # would overshoot a tight driver budget between probes).
        time.sleep(min(30.0, remaining))


LAST_SUCCESS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_LAST_SUCCESS.json"
)
# A cached result older than this is infrastructure history, not a
# number for THIS round — die rather than report it. Rounds run ~12 h,
# so 14 h admits any same-round measurement; the round-id check below
# is the primary cross-round guard, this age cap is the backstop when
# no round id is known on either side.
STALE_MAX_AGE_S = 14 * 3600.0


def _current_round() -> int | None:
    """Round number from the driver's progress log, if available."""
    try:
        with open(os.path.join(os.path.dirname(LAST_SUCCESS_PATH),
                               "PROGRESS.jsonl")) as f:
            lines = f.read().strip().splitlines()
        return int(json.loads(lines[-1])["round"])
    except Exception:  # noqa: BLE001 — absent/foreign layout is fine
        return None


def _is_standard_workload() -> bool:
    """Only the canonical headline workload is worth caching as 'this
    round's measurement' — env-resized dev/test runs are not, and
    neither is a run with the walk-table tier flipped to bf16 (the r6
    suite's A/B stage): its headline is not the default-config
    number."""
    if any(os.environ.get(k) for k in (
        "PUMIUMTALLY_BENCH_N", "PUMIUMTALLY_BENCH_DIV",
        "PUMIUMTALLY_BENCH_MOVES",
    )):
        return False
    # Only a NON-default tier makes the run nonstandard — an explicit
    # float32/auto still measures the default-config headline.
    return os.environ.get("PUMIUMTALLY_WALK_TABLE_DTYPE", "float32") in (
        "float32", "auto"
    )


def record_success(rec: dict) -> None:
    """Persist the successful headline so a later same-round run that
    finds the device wedged can report SOMETHING measured rather than
    nothing (see _report_stale_result_or_die)."""
    import datetime

    out = dict(rec)
    out["measured_at_utc"] = datetime.datetime.now(
        datetime.timezone.utc
    ).isoformat(timespec="seconds")
    out["measured_at_epoch"] = time.time()
    rnd = _current_round()
    if rnd is not None:
        out["measured_in_round"] = rnd
    try:
        with open(LAST_SUCCESS_PATH, "w") as f:
            json.dump(out, f)
    except OSError as e:  # best-effort: never cost the bench itself
        print(f"# could not persist bench result: {e}", file=sys.stderr)


def _refuse_stale(reason: str) -> None:
    """Terminal refusal of the stale-result fallback: exit 0 with ONE
    machine-parseable JSON line ``{"stale_refused": true, "reason"}``.

    The r5 round record showed why rc=1-and-no-JSON is the wrong shape
    here: the driver recorded ``parsed: null`` and the refusal's reason
    lived only in stderr nobody keeps. A refusal is a successfully
    reported OUTCOME ("no number exists for this round, and here is
    why"), not a crash — so it parses like every other bench record,
    and consumers key on ``stale_refused`` exactly as they key on
    ``stale``. No ``metric``/``value`` keys ride along: a consumer
    that ignores the flag gets nothing it could mistake for a rate."""
    print(f"# {reason}", file=sys.stderr)
    print(json.dumps({"stale_refused": True, "reason": reason}))
    sys.exit(0)


def _report_stale_result_or_die() -> None:
    """Device unreachable: fall back to this round's last SUCCESSFUL
    on-chip measurement, conspicuously flagged as stale.

    Three consecutive rounds lost their official bench record to a
    wedged device tunnel while genuinely-measured numbers from hours
    earlier sat in logs. Reporting the cached measurement — with
    `stale: true`, its timestamp, and the reason — is strictly more
    honest than an empty record, and the flag keeps it from ever
    being mistaken for a fresh round-end measurement. A cached result
    from another round (round-id mismatch, or past the age backstop
    when no round id is known) is still refused: that would be a
    different round's number — but the refusal itself reports as a
    single ``{"stale_refused": true, ...}`` JSON line with rc 0 (see
    _refuse_stale). PUMIUMTALLY_BENCH_NO_STALE=1 disables the
    fallback entirely (also reporting the refusal record)."""
    if os.environ.get("PUMIUMTALLY_BENCH_NO_STALE") == "1":
        _refuse_stale(
            "device unreachable and PUMIUMTALLY_BENCH_NO_STALE=1: "
            "stale-result fallback disabled"
        )
    try:
        with open(LAST_SUCCESS_PATH) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        _refuse_stale(
            "device unreachable and no cached successful bench result "
            "exists for this round"
        )
    rnd, rec_rnd = _current_round(), rec.get("measured_in_round")
    if rnd is not None and rec_rnd is not None and int(rec_rnd) != rnd:
        _refuse_stale(
            f"cached bench result is from round {rec_rnd}, this is "
            f"round {rnd}; refusing to report it"
        )
    age = time.time() - float(rec.get("measured_at_epoch", 0))
    if age > STALE_MAX_AGE_S:
        _refuse_stale(
            f"cached bench result is {age / 3600:.1f}h old — another "
            "round's number; refusing to report it"
        )
    rec.pop("measured_at_epoch", None)
    rec["stale"] = True
    # Distinct metric name: a consumer keying on metric/value alone
    # must OPT IN to accepting a cached number (ADVICE r4) — the
    # canonical fresh name never carries a stale value.
    rec["metric"] = "particle_moves_per_sec_stale"
    rec["stale_reason"] = (
        "device tunnel unreachable at report time; value is this "
        "round's most recent successful on-chip bench.py run"
    )
    print(f"# WARNING: reporting STALE result measured "
          f"{age/3600:.1f}h ago (device currently unreachable)",
          file=sys.stderr)
    print(json.dumps(rec))
    sys.exit(0)


def measure_link_bandwidth(mb: float = 8.0) -> float | None:
    """Timed host→device put of an `mb`-MB array, MB/s.

    Recorded so vs_baseline numbers are interpretable across
    tunnel-quality changes: the staging-bound protocols scale with this.

    The timed region is the TRANSFER alone: device_put followed by
    block_until_ready on the resulting array. The earlier form summed
    the array and fetched the scalar to force the transfer, which
    charged a reduction kernel launch plus a D2H scalar round-trip to
    the link number — on the remote tunnel that overhead dominated and
    the probe reported 31 MB/s on a ~35 MB/s link as if staging were
    the whole story. block_until_ready on a just-transferred array is
    an honest fence for the transfer itself (the laziness caveat in
    PERF_NOTES r1 §5 concerns COMPUTE dispatched asynchronously; the
    put's completion is what the handle's ready-event tracks), and the
    warmup transfer absorbs any one-time client/allocation setup.
    """
    try:
        import jax

        buf = np.random.default_rng(2).random(int(mb * 1e6 / 8))
        jax.device_put(buf).block_until_ready()  # warmup transfer
        t0 = time.perf_counter()
        jax.device_put(buf).block_until_ready()
        dt = time.perf_counter() - t0
        return buf.nbytes / 1e6 / dt
    except Exception as e:  # noqa: BLE001 — diagnostic only
        print(f"# link bandwidth probe failed: {e}", file=sys.stderr)
        return None


def run_vmem_blocked_subprocess() -> dict | None:
    """run_vmem_blocked in a timeout-capped child process.

    The row involves a Mosaic kernel compile, and the round-4 capture
    showed that compile HANGING the device tunnel's remote compile
    helper (>25 min, no error) — an in-process hang would eat the
    whole bench along with the already-measured headline. A child can
    be killed; its JSON line is the only coupling.

    On hardware the child opens a SECOND device client while the
    parent still holds its own — concurrent clients are observed to
    work on this tunnel (round-4 capture: a stray client ran inside
    bench's window and both completed), but if a grant ever becomes
    exclusive the cap below is the cost, paid once and reported. The
    cap is sized from measured compiles (~40 s chipless, minutes-not-
    tens-of-minutes on the helper) plus the row's runtime."""
    import jax

    tmo = float(os.environ.get("PUMIUMTALLY_BENCH_VMEM_TIMEOUT", "420"))
    env = dict(os.environ)
    env["PUMIUMTALLY_BENCH_VMEM_CHILD"] = "1"
    # A fresh interpreter's startup hook re-points JAX at the device
    # tunnel regardless of env vars (only an in-process config update
    # wins) — so tell the child which backend the PARENT measured on
    # and let it config-update itself. Without this, a CPU test run's
    # child dials the possibly-wedged tunnel and hangs to the cap.
    env["PUMIUMTALLY_BENCH_VMEM_CHILD_PLATFORM"] = jax.default_backend()
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=tmo,
        )
    except subprocess.TimeoutExpired as e:
        # The child's partial stderr is the only triage signal for the
        # wrapper's primary failure mode (wedged helper vs slow
        # compile) — relay it.
        for stream in (e.stdout, e.stderr):
            if stream:
                text = stream if isinstance(stream, str) else stream.decode(
                    "utf-8", "replace")
                sys.stderr.write(text[-2000:])
        print(f"# vmem-blocked child timed out after {tmo:.0f}s "
              "(wedged compile helper?)", file=sys.stderr)
        return None
    sys.stderr.write(out.stderr[-2000:])
    if out.returncode != 0:
        print(f"# vmem-blocked child rc={out.returncode}",
              file=sys.stderr)
        return None
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    return None


def main() -> None:
    if os.environ.get("PUMIUMTALLY_BENCH_CPU") == "1":
        # Subprocess mode: CPU baseline on the IDENTICAL workload.
        res = run_workload(N, MOVES, "two_phase")
        print(json.dumps({"cpu_two_phase_rate": res["moves_per_sec"]}))
        return
    if os.environ.get("PUMIUMTALLY_BENCH_VMEM_CHILD") == "1":
        # Subprocess mode: the blocked-vmem row (see wrapper above).
        want = os.environ.get("PUMIUMTALLY_BENCH_VMEM_CHILD_PLATFORM")
        if want:
            import jax

            jax.config.update("jax_platforms", want)
        # default=float: numpy scalars (block counts etc.) must not
        # kill the only line the parent parses.
        print(json.dumps(run_vmem_blocked(N, MOVES), default=float))
        return

    # Single-client interlock (docs/PERF_NOTES.md: the round-4 capture
    # was contaminated by a second TPU client inside bench's window; a
    # second client has also wedged the tunnel before). Repo tools
    # honor the same lock; see utils/chiplock.py.
    from pumiumtally_tpu.utils.chiplock import chip_lock

    with chip_lock(timeout_s=600) as held:
        if not held:
            print("# WARNING: chip lock busy after 600s; measuring "
                  "anyway (window may be contended)", file=sys.stderr)
        _measure_and_report()


def _measure_and_report() -> None:
    preflight_device()
    link_mb_s = measure_link_bandwidth()
    two = run_workload(N, MOVES, "two_phase")
    forced = run_workload(N, MOVES, "two_phase_forced")
    cont = run_workload(N, MOVES, "continue")
    pincell = run_pincell(N, 4)
    pincell_tuned = None
    if (os.environ.get("PUMIUMTALLY_BENCH_PINCELL_TUNED", "1") != "0"
            and os.environ.get("PUMIUMTALLY_BENCH_AUTOTUNE", "1") != "0"):
        try:
            pincell_tuned = run_pincell(N, 4, tuned=True)
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# tuned pincell failed: {e}", file=sys.stderr)
    gblocked = None
    if os.environ.get("PUMIUMTALLY_BENCH_GATHER_BLOCKED", "1") != "0":
        try:
            gblocked = run_gather_blocked(N, MOVES)
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# gather-blocked workload failed: {e}", file=sys.stderr)
    redistribution = None
    if os.environ.get("PUMIUMTALLY_BENCH_REDISTRIBUTION", "1") != "0":
        try:
            redistribution = run_redistribution_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# redistribution A/B failed: {e}", file=sys.stderr)
    table_precision = None
    if os.environ.get("PUMIUMTALLY_BENCH_TABLE_PRECISION", "1") != "0":
        try:
            table_precision = run_table_precision_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# table-precision A/B failed: {e}", file=sys.stderr)
    blocked_profile = None
    if os.environ.get("PUMIUMTALLY_BENCH_BLOCKED_PROFILE", "1") != "0":
        try:
            blocked_profile = run_blocked_profile(min(N, 200_000), 3)
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# blocked-profile row failed: {e}", file=sys.stderr)
    frontier = None
    if os.environ.get("PUMIUMTALLY_BENCH_FRONTIER", "1") != "0":
        try:
            frontier = run_frontier_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# frontier A/B failed: {e}", file=sys.stderr)
    batch_stats = None
    if os.environ.get("PUMIUMTALLY_BENCH_BATCH_STATS", "1") != "0":
        try:
            batch_stats = run_batch_stats()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# batch-stats A/B failed: {e}", file=sys.stderr)
    scoring = None
    if os.environ.get("PUMIUMTALLY_BENCH_SCORING", "1") != "0":
        try:
            scoring = run_scoring()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# scoring A/B failed: {e}", file=sys.stderr)
    resilience = None
    if os.environ.get("PUMIUMTALLY_BENCH_RESILIENCE", "1") != "0":
        try:
            resilience = run_resilience_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# resilience A/B failed: {e}", file=sys.stderr)
    sentinel = None
    if os.environ.get("PUMIUMTALLY_BENCH_SENTINEL", "1") != "0":
        try:
            sentinel = run_sentinel_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# sentinel A/B failed: {e}", file=sys.stderr)
    service = None
    if os.environ.get("PUMIUMTALLY_BENCH_SERVICE", "1") != "0":
        try:
            service = run_service_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# service A/B failed: {e}", file=sys.stderr)
    service_fusion = None
    if os.environ.get("PUMIUMTALLY_BENCH_SERVICE_FUSION", "1") != "0":
        try:
            service_fusion = run_service_fusion_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# service fusion A/B failed: {e}", file=sys.stderr)
    service_load = None
    if os.environ.get("PUMIUMTALLY_BENCH_SERVICE_LOAD", "1") != "0":
        try:
            service_load = run_service_load()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# service load run failed: {e}", file=sys.stderr)
    distributed = None
    if os.environ.get("PUMIUMTALLY_BENCH_DISTRIBUTED", "1") != "0":
        try:
            distributed = run_distributed_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# distributed A/B failed: {e}", file=sys.stderr)
    placement = None
    if os.environ.get("PUMIUMTALLY_BENCH_PLACEMENT", "1") != "0":
        try:
            placement = run_placement_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# placement A/B failed: {e}", file=sys.stderr)
    pallas_walk = None
    if os.environ.get("PUMIUMTALLY_BENCH_PALLAS_WALK", "1") != "0":
        try:
            pallas_walk = run_pallas_walk_ab()
        except Exception as e:  # noqa: BLE001 — extra row, best-effort
            print(f"# pallas-walk A/B failed: {e}", file=sys.stderr)
    blocked = None
    if os.environ.get("PUMIUMTALLY_BENCH_VMEM", "1") != "0":
        try:
            blocked = run_vmem_blocked_subprocess()
        except Exception as e:  # noqa: BLE001
            # Best-effort EXTRA metric: a spawn/parse failure may not
            # cost the already-measured headline numbers. (Mosaic
            # failures, hangs, and the row's conservation exit all
            # happen inside the child and surface as None above.)
            print(f"# vmem-blocked workload failed: {e}", file=sys.stderr)

    vs_baseline = None
    cpu_rate = None
    # PUMIUMTALLY_BENCH_CPU_BASELINE=0 (quick-window mode) skips the
    # CPU-subprocess baseline — the longest extra — so a short tunnel
    # window still yields a fresh on-chip headline; vs_baseline null.
    if os.environ.get("PUMIUMTALLY_BENCH_CPU_BASELINE", "1") != "0":
        try:
            env = dict(os.environ)
            env["PUMIUMTALLY_BENCH_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            # Baseline stays UNTUNED so vs_baseline's denominator keeps
            # the semantics of earlier rounds (default-knob CPU engine).
            env["PUMIUMTALLY_BENCH_AUTOTUNE"] = "0"
            # Don't let the child's interpreter-startup hook try to
            # claim the TPU tunnel the parent may be holding (it would
            # block).
            env.pop("PALLAS_AXON_POOL_IPS", None)
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=3600,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            cpu_rate = json.loads(out.stdout.strip().splitlines()[-1])[
                "cpu_two_phase_rate"
            ]
            vs_baseline = two["moves_per_sec"] / cpu_rate
        except Exception as e:  # noqa: BLE001 — baseline is best-effort
            print(f"# cpu baseline failed: {e}", file=sys.stderr)

    # Headline = the best CONTINUE-protocol engine on the canonical
    # workload (same mesh, same particles, same protocol — engines are
    # interchangeable behind the facade, so the fastest one is the
    # number a user gets by setting one config knob). Provenance in
    # headline_engine; per-engine rows ride alongside unchanged.
    candidates = {"monolithic": cont["moves_per_sec"]}
    if gblocked is not None:
        candidates["gather_blocked"] = gblocked["moves_per_sec"]
    if blocked is not None:
        candidates["vmem_blocked"] = blocked["moves_per_sec"]
    headline_engine = max(candidates, key=candidates.get)

    rec = {
        "metric": "particle_moves_per_sec",
        "value": candidates[headline_engine],
        "unit": "moves/s",
        "vs_baseline": vs_baseline,
        "vs_north_star": candidates[headline_engine] / NORTH_STAR_MOVES_PER_SEC,
        "north_star_moves_per_sec": NORTH_STAR_MOVES_PER_SEC,
        "headline_engine": headline_engine,
        # Protocol/config semantics of each key, recorded since round 3
        # so longitudinal comparisons are explicit: two_phase changed
        # meaning in round 2 (auto_continue on + unfenced pipelining);
        # the round-1 semantics live in two_phase_forced.
        "protocol": {
            "two_phase": "auto_continue=True, fenced_timing=False",
            "two_phase_forced": "auto_continue=False, fenced_timing=False",
            "continue": "origins=None, fenced_timing=False",
            "headline": (
                "since r5: best continue-protocol engine "
                "(see headline_engine); r1-r4 value == "
                "continue_moves_per_sec (monolithic), still reported"
            ),
            "tuning": (
                (
                    "box workloads used autotuned_knobs (since r3); "
                    "pincell_moves_per_sec and the CPU baseline stay "
                    "on defaults (longitudinal)"
                    if tuned_knobs()
                    else "box autotune off/failed/default-equal: box "
                         "workloads ran default knobs"
                )
                + (
                    "; pincell_tuned (since r5) autotuned on the "
                    "pincell mesh itself (knobs recorded in the row)"
                    if pincell_tuned is not None
                    else "; pincell_tuned row absent this run"
                )
            ),
        },
        "link_mb_per_sec": link_mb_s,
        "autotuned_knobs": tuned_knobs(),
        "two_phase_moves_per_sec": two["moves_per_sec"],
        "two_phase_forced_moves_per_sec": forced["moves_per_sec"],
        "continue_moves_per_sec": cont["moves_per_sec"],
        "pincell_moves_per_sec": pincell["moves_per_sec"],
        "pincell_tuned": None if pincell_tuned is None else {
            "moves_per_sec": pincell_tuned["moves_per_sec"],
            "knobs": pincell_tuned["knobs"],
        },
        # argsort-vs-rank redistribution component (speedup > 1 means
        # the sort-free counting-rank path wins on this backend).
        "redistribution": redistribution,
        # f32-vs-bf16 two-tier walk-table component (select-tier bytes
        # provenance + interleaved rates + flux divergence). The
        # headline engines stay on the f32 default; speedup > 1 with a
        # benign flux_l1_rel_divergence is the evidence for flipping
        # TallyConfig.walk_table_dtype.
        "table_precision": table_precision,
        "gather_blocked": None if gblocked is None else {
            "moves_per_sec": gblocked["moves_per_sec"],
            "blocks_per_chip": gblocked["blocks_per_chip"],
            "block_elems": gblocked["block_elems"],
            "walk_rounds_last_move": gblocked["walk_rounds_last_move"],
        },
        # Component budget of the blocked engine (frontier-local
        # migration instrumentation): per-round walk/migrate/occupancy
        # ms from the profiled phase driver, rounds, per-block
        # dispatches, frontier-size max/mean + slab fallback count.
        # Ratios are the signal (the profiled driver syncs per
        # section); best-effort like the other component rows.
        "blocked_profile": blocked_profile,
        # Full-capacity vs frontier-slab in-loop migrate at two
        # crossing-front sizes (speedup > 1 = the slab wins at that
        # front on this backend; honest in both regimes).
        "frontier_migrate": frontier,
        # Batch-statistics subsystem cost + trigger behavior: stats-on
        # vs stats-off rates (flux parity bitwise), fenced per-close
        # lane-update/trigger ms, convergence trace, and the
        # compiles-healthy contract (compiles.timed == 0).
        "batch_stats": batch_stats,
        # Filtered-scoring subsystem cost: scoring-on vs scoring-off
        # rates (flux parity AND 2-bin telescoping asserted bitwise
        # inside the tool), fenced per-move scoring ms, and the
        # compiles-healthy contract (compiles.timed == 0).
        "scoring": scoring,
        # Fault-tolerance subsystem cost: autosave-on vs autosave-off
        # rates (flux parity bitwise — autosave only reads state), the
        # fenced per-generation save cost and on-disk size, and the
        # host-side-only contract (compiles.timed == 0: resilience
        # never touches the jit cache).
        "resilience": resilience,
        # Runtime-sentinel subsystem cost: sentinel-on vs sentinel-off
        # rates (flux parity bitwise — the audit only reads state; the
        # straggler ladder never fires on a healthy run), the fenced
        # per-move audit cost, the on-arm health report, and the
        # compiles-healthy contract (compiles.timed == 0).
        "sentinel": sentinel,
        # Multi-session service layer cost: 1-session service vs the
        # direct facade (flux parity bitwise inside the tool), the
        # fenced-vs-pipelined served throughput spread, and the
        # compiles-healthy contract (compiles.timed == 0: the service
        # adds no jitted entry points).
        "service": service,
        # Cross-session batch fusion (r12): fused vs unfused serving
        # throughput at 1/4/8 sessions (per-session flux parity
        # bitwise inside the tool, both arms), device dispatches per
        # move (~1/K under fusion), and the compiles-healthy contract
        # (compiles.timed == 0: walk_fused compiles once per group
        # composition, in warmup only). The "streaming" sub-row (r20)
        # is the same A/B on chunk-wise fused StreamingTally facades.
        "service_fusion": service_fusion,
        # Served throughput under load (r20): >= 100 scripted clients
        # with a deterministic seeded arrival schedule through a
        # 2-worker router (tools/exp_service_load.py) — served
        # moves/s, client-observed p50/p99 latency, per-lane Jain
        # fairness, refusal counts, the bitwise spot-check parity
        # gate, and the compiles-healthy contract (compiles.timed ==
        # 0: the warmup ladder pre-compiles every fused composition).
        "service_load": service_load,
        # Pod-scale distributed campaigns (r13): collective vs
        # global-scatter migration (flux parity bitwise inside the
        # tool), fenced per-move ms, modeled migration-collective
        # bytes, the 2-process cross-host parity subarm (honest
        # "available": false without gloo), and the compiles-healthy
        # contract (compiles.timed == 0).
        "distributed": distributed,
        # Topology-aware pod placement (r19): linear vs pod_rcb on the
        # pinned 2-host virtual layout (host chips (3,5)). The class
        # gate runs inside the tool before timing (positions bitwise,
        # elem-id diffs boundary-ties only, total flux conserved), the
        # modeled cross-host migration bytes must STRICTLY drop, and
        # compiles.timed == 0. The CPU rate delta prices every block
        # boundary equally and is expected against pod_rcb — the
        # ship/kill call uses the on-chip suite's placement_ab stage.
        "placement": placement,
        # One-kernel Pallas walk (r17): fused select/refine/scatter
        # with streamed block tables vs the bf16 gather sub-split,
        # interpret-mode bitwise pin + bitwise positions between arms
        # enforced inside the tool, 80 B vs 52 B modeled
        # bytes/crossing, compiles.timed == 0. On CPU the pallas arm
        # is interpret-mode — the on-chip ship/kill call uses the
        # r13 suite's Mosaic-compiled rate, not this row's speedup.
        "pallas_walk": pallas_walk,
        "vmem_blocked": None if blocked is None else {
            "moves_per_sec": blocked["moves_per_sec"],
            "blocks_per_chip": blocked["blocks_per_chip"],
            "block_elems": blocked["block_elems"],
            "walk_rounds_last_move": blocked["walk_rounds_last_move"],
        },
        "histories_per_sec": two["histories_per_sec"],
        "cpu_two_phase_moves_per_sec": cpu_rate,
        "conservation_rel_err": max(
            two["conservation_rel_err"], forced["conservation_rel_err"],
            cont["conservation_rel_err"], pincell["conservation_rel_err"],
            *([] if gblocked is None else [gblocked["conservation_rel_err"]]),
        ),
        # Retrace tripwire column (docs/STATIC_ANALYSIS.md): per-row
        # compile counts from timed_moves — "total" over the whole
        # workload (warmup included), "timed" inside the measured
        # window (healthy == 0: every timed move hits the jit cache),
        # plus the per-entry-point breakdown. A nonzero "timed" means
        # the measured rate paid recompiles it should not have.
        "compiles": {
            "two_phase": two["compiles"],
            "two_phase_forced": forced["compiles"],
            "continue": cont["compiles"],
            "pincell": pincell["compiles"],
            **({} if pincell_tuned is None
               else {"pincell_tuned": pincell_tuned["compiles"]}),
            **({} if gblocked is None
               else {"gather_blocked": gblocked["compiles"]}),
            **({} if blocked is None or "compiles" not in blocked
               else {"vmem_blocked": blocked["compiles"]}),
        },
        "workload": {
            "mesh_tets": 6 * MESH_DIV**3,
            "particles": N,
            "moves": MOVES,
            "mean_step": MEAN_STEP,
        },
    }
    print(json.dumps(rec))
    # Only the canonical full-size accelerator run is worth caching as
    # "this round's measurement" — env-resized or CPU-backend runs are
    # not. (CPU-baseline subprocess mode already returned above.)
    if _is_standard_workload():
        import jax

        if jax.default_backend() != "cpu":
            record_success(rec)


if __name__ == "__main__":
    main()
