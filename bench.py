"""Headline benchmark: particle-move throughput of the tallied walk.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.json configs[0] analogue): a 48k-tet box mesh —
the scale of the OpenMC pincell's ~10k-tet Gmsh mesh, rounded up — with
500k particles per batch doing tallied MoveToNextLocation steps
(reference PumiTallyImpl.cpp:66-149) along a precomputed random-walk
trajectory that stays strictly inside the mesh, so every move's origins
equal the committed positions and the continue-mode fast path applies
(origins=None, api/tally.py). The host stages each move's destination
buffer (f64, per the reference's double* protocol) inside the timed
region; moves dispatch asynchronously and the clock stops at a real
value fetch of the final flux, which is also validated against the
analytic total track length (exact: no particle ever exits).

``value`` is particle-moves/sec on the default backend (the real TPU
chip under the driver).

``vs_baseline``: the reference publishes no numbers in-tree
(BASELINE.md), so the recorded baseline is a measured CPU run of OUR
engine on the same workload (a stand-in for the reference's
Kokkos-Serial path, which cannot be built here: its dependency stack
needs network access). vs_baseline = tpu_rate / cpu_rate.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

MESH_DIV = 20  # 20x20x20 cells → 48000 tets
N = 500_000
MOVES = 8
MEAN_STEP = 0.25  # mean segment length: ~15 tet crossings per move


def make_trajectory(rng, n: int, moves: int) -> list:
    """src + `moves` destination arrays, all strictly inside the box."""
    pts = [rng.uniform(0.05, 0.95, (n, 3))]
    for _ in range(moves):
        step = rng.normal(scale=MEAN_STEP / np.sqrt(3.0), size=(n, 3))
        pts.append(np.clip(pts[-1] + step, 0.02, 0.98))
    return pts


def run_workload(n: int, moves: int) -> float:
    """Particle-moves/sec for `moves` tallied move steps of n particles."""
    import jax.numpy as jnp

    from pumiumtally_tpu import PumiTally, TallyConfig, build_box

    mesh = build_box(1.0, 1.0, 1.0, MESH_DIV, MESH_DIV, MESH_DIV)
    cfg = TallyConfig(check_found_all=False)
    t = PumiTally(mesh, n, cfg)
    rng = np.random.default_rng(0)
    pts = make_trajectory(rng, n, moves + 1)  # +1 warmup move
    t.CopyInitialPosition(pts[0].reshape(-1).copy())

    # Warmup: compile the continue-mode move once; the scalar fetch is
    # the real sync (block_until_ready is lazy on this backend).
    t.MoveToNextLocation(None, pts[1].reshape(-1).copy())
    flux_warm = float(jnp.sum(t.flux))

    t0 = time.perf_counter()
    for m in range(2, moves + 2):
        t.MoveToNextLocation(None, pts[m].reshape(-1).copy())
    total_flux = float(jnp.sum(t.flux))  # forces the whole pipeline
    dt = time.perf_counter() - t0

    # Self-check: sum(flux) must equal the analytic total track length.
    expect = flux_warm + sum(
        float(np.linalg.norm(pts[m] - pts[m - 1], axis=1).sum())
        for m in range(2, moves + 2)
    )
    rel = abs(total_flux - expect) / expect
    if rel > 1e-3:
        print(f"# WARNING: conservation off by {rel:.2e}", file=sys.stderr)
    return n * moves / dt


def main() -> None:
    if os.environ.get("PUMIUMTALLY_BENCH_CPU") == "1":
        # Subprocess mode: CPU stand-in baseline, smaller batch.
        rate = run_workload(N // 10, 4)
        print(json.dumps({"cpu_rate": rate * 1.0}))
        return

    rate = run_workload(N, MOVES)

    vs_baseline = None
    try:
        env = dict(os.environ)
        env["PUMIUMTALLY_BENCH_CPU"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        # Don't let the child's interpreter-startup hook try to claim
        # the TPU tunnel the parent may be holding (it would block).
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=1200,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        cpu_rate = json.loads(out.stdout.strip().splitlines()[-1])["cpu_rate"]
        vs_baseline = rate / cpu_rate
    except Exception as e:  # noqa: BLE001 — baseline is best-effort
        print(f"# cpu baseline failed: {e}", file=sys.stderr)

    print(json.dumps({
        "metric": "particle_moves_per_sec",
        "value": rate,
        "unit": "moves/s",
        "vs_baseline": vs_baseline,
    }))


if __name__ == "__main__":
    main()
