"""Headline benchmark: particle-move throughput of the tallied walk.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.json configs[0] analogue): a 48k-tet box mesh —
the scale of the OpenMC pincell's ~10k-tet Gmsh mesh, rounded up — with
500k particles per batch doing full two-phase MoveToNextLocation steps
(localize + tallied transport; reference PumiTallyImpl.cpp:66-149).
``value`` is particle-moves/sec on the default backend (the real TPU
chip under the driver).

``vs_baseline``: the reference publishes no numbers in-tree
(BASELINE.md), so the recorded baseline is a measured CPU run of OUR
engine on the same workload (a stand-in for the reference's
Kokkos-Serial path, which cannot be built here: its dependency stack
needs network access). vs_baseline = tpu_rate / cpu_rate.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

MESH_DIV = 20  # 20x20x20 cells → 48000 tets
N = 500_000
MOVES = 8
MEAN_STEP = 0.25  # mean segment length: a few tets per move


def run_workload(n: int, moves: int) -> float:
    """Particle-moves/sec for `moves` tallied move steps of n particles."""
    import jax

    from pumiumtally_tpu import PumiTally, TallyConfig, build_box

    mesh = build_box(1.0, 1.0, 1.0, MESH_DIV, MESH_DIV, MESH_DIV)
    cfg = TallyConfig(check_found_all=False)
    t = PumiTally(mesh, n, cfg)
    rng = np.random.default_rng(0)
    pos = rng.uniform(0.05, 0.95, (n, 3))
    t.CopyInitialPosition(pos.reshape(-1).copy())

    def next_dest(p):
        step = rng.normal(scale=MEAN_STEP / np.sqrt(3.0), size=(n, 3))
        return np.clip(p + step, 0.0, 1.0)

    # Warmup: compile the move step once.
    d = next_dest(pos)
    t.MoveToNextLocation(pos.reshape(-1).copy(), d.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    pos = t.positions.astype(np.float64)

    t0 = time.perf_counter()
    for _ in range(moves):
        d = next_dest(pos)
        t.MoveToNextLocation(pos.reshape(-1).copy(), d.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        pos = t.positions.astype(np.float64)
    jax.block_until_ready(t.flux)
    dt = time.perf_counter() - t0
    return n * moves / dt


def main() -> None:
    if os.environ.get("PUMIUMTALLY_BENCH_CPU") == "1":
        # Subprocess mode: CPU stand-in baseline, smaller batch.
        rate = run_workload(N // 10, 4)
        print(json.dumps({"cpu_rate": rate * 1.0}))
        return

    rate = run_workload(N, MOVES)

    vs_baseline = None
    try:
        env = dict(os.environ)
        env["PUMIUMTALLY_BENCH_CPU"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        # Don't let the child's interpreter-startup hook try to claim
        # the TPU tunnel the parent may be holding (it would block).
        env.pop("PALLAS_AXON_POOL_IPS", None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, timeout=1200,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        cpu_rate = json.loads(out.stdout.strip().splitlines()[-1])["cpu_rate"]
        vs_baseline = rate / cpu_rate
    except Exception as e:  # noqa: BLE001 — baseline is best-effort
        print(f"# cpu baseline failed: {e}", file=sys.stderr)

    print(json.dumps({
        "metric": "particle_moves_per_sec",
        "value": rate,
        "unit": "moves/s",
        "vs_baseline": vs_baseline,
    }))


if __name__ == "__main__":
    main()
