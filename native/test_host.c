/* Oracle-grade C-ABI end-to-end test: a pure-C host drives the 6-tet
 * unit cube through libpumiumtally_c.so with the reference white-box
 * test's EXACT 5-particle trajectories
 * (test/test_pumi_tally_impl_methods.cpp, hand-computed expectations
 * recorded in BASELINE.md and tests/test_walk_oracle.py) and asserts
 * every observable to the reference's 1e-8 comparison tolerance:
 *
 *   - localization at (0.1,0.4,0.5): all particles in element 2,
 *     flux identically zero;
 *   - move 1 to (1.2,0.4,0.5): crosses elements 2,3,4 with track
 *     lengths 0.3/0.1/0.5 each => flux[2,3,4] = 1.5/0.5/2.5, flying
 *     zeroed in place across the C boundary, positions clamped to the
 *     x=1.0 boundary, all particles in element 4;
 *   - move 2 (mixed flying/weights): flux[3] += 0.08790490988459178*2,
 *     flux[4] += 0.879049070406094*2 + 0.552268050859363*0.5, final
 *     elements {3,4,4,4,4}.
 *
 * Exits nonzero on ANY mismatch — this is the host-app-eye view of
 * the whole stack (C ABI -> embedded interpreter -> engine), so a
 * silent numerical regression cannot hide behind a green Python tier.
 * "--corrupt" perturbs one expected value by 1e-3 and demands the
 * harness FAIL, proving the assertions are live (tests/test_native.py
 * runs both directions).
 *
 * Usage: test_host <mesh.msh> [--corrupt]
 */
#include <math.h>
#include <stdio.h>
#include <string.h>

#include "pumiumtally_c.h"

#define NUM 5
#define NELEMS 6
#define TOL 1e-8 /* reference test:21-27 */

static int g_failures = 0;

static void check_close(const char* what, double got, double want,
                        double tol) {
  if (!(fabs(got - want) <= tol)) {
    fprintf(stderr, "MISMATCH %s: got %.17g want %.17g (tol %g)\n", what,
            got, want, tol);
    g_failures++;
  }
}

static void check_eq_i(const char* what, long got, long want) {
  if (got != want) {
    fprintf(stderr, "MISMATCH %s: got %ld want %ld\n", what, got, want);
    g_failures++;
  }
}

static void fill3(double* buf, double x, double y, double z) {
  for (int i = 0; i < NUM; ++i) {
    buf[3 * i + 0] = x;
    buf[3 * i + 1] = y;
    buf[3 * i + 2] = z;
  }
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <mesh.msh> [--corrupt]\n", argv[0]);
    return 2;
  }
  int corrupt = argc > 2 && strcmp(argv[2], "--corrupt") == 0;

  pumiumtally_handle* h = pumiumtally_create(argv[1], NUM);
  if (!h) {
    fprintf(stderr, "FAILURE: pumiumtally_create returned NULL\n");
    return 1;
  }

  double init[3 * NUM];
  fill3(init, 0.1, 0.4, 0.5);
  if (pumiumtally_copy_initial_position(h, init, 3 * NUM) != 0) {
    fprintf(stderr, "FAILURE: copy_initial_position rc != 0\n");
    pumiumtally_destroy(h);
    return 1;
  }

  /* -- localization oracle: element 2, zero flux ------------------- */
  int32_t eids[NUM];
  check_eq_i("get_elem_ids count", pumiumtally_get_elem_ids(h, eids, NUM),
             NUM);
  for (int i = 0; i < NUM; ++i)
    check_eq_i("localized element", eids[i], 2);
  double flux[NELEMS];
  check_eq_i("get_flux count", pumiumtally_get_flux(h, flux, NELEMS),
             NELEMS);
  for (int e = 0; e < NELEMS; ++e)
    check_close("initial flux", flux[e], 0.0, TOL);

  /* -- move 1: ray to (1.2,0.4,0.5), exits at x=1.0 ---------------- */
  double dests[3 * NUM];
  fill3(dests, 1.2, 0.4, 0.5);
  int8_t flying[NUM];
  double weights[NUM];
  for (int i = 0; i < NUM; ++i) {
    flying[i] = 1;
    weights[i] = 1.0;
  }
  if (pumiumtally_move_to_next_location(h, init, dests, flying, weights,
                                        3 * NUM) != 0) {
    fprintf(stderr, "FAILURE: move_to_next_location(1) rc != 0\n");
    pumiumtally_destroy(h);
    return 1;
  }
  for (int i = 0; i < NUM; ++i) /* in-place zeroing crossed the ABI */
    check_eq_i("flying zeroed", flying[i], 0);

  double expect1[NELEMS] = {0.0, 0.0, 0.3 * NUM, 0.1 * NUM, 0.5 * NUM,
                            0.0};
  if (corrupt) expect1[2] += 1e-3; /* prove the harness can fail */
  pumiumtally_get_flux(h, flux, NELEMS);
  for (int e = 0; e < NELEMS; ++e)
    check_close("move-1 flux", flux[e], expect1[e], TOL);

  double pos[3 * NUM];
  check_eq_i("get_positions count",
             pumiumtally_get_positions(h, pos, 3 * NUM), 3 * NUM);
  for (int i = 0; i < NUM; ++i) {
    check_close("clamped x", pos[3 * i + 0], 1.0, TOL);
    check_close("clamped y", pos[3 * i + 1], 0.4, TOL);
    check_close("clamped z", pos[3 * i + 2], 0.5, TOL);
  }
  pumiumtally_get_elem_ids(h, eids, NUM);
  for (int i = 0; i < NUM; ++i)
    check_eq_i("move-1 element", eids[i], 4);

  /* -- move 2: mixed flying/weights (reference test:284-390) ------- */
  double origins2[3 * NUM]; /* committed positions (production contract) */
  fill3(origins2, 1.0, 0.4, 0.5);
  double dests2[3 * NUM];
  fill3(dests2, 1.0, 0.4, 0.5);
  int8_t flying2[NUM] = {0, 0, 0, 0, 0};
  double weights2[NUM] = {1.0, 1.0, 1.0, 1.0, 1.0};
  dests2[0] = 0.15;
  dests2[1] = 0.05;
  dests2[2] = 0.20;
  flying2[0] = 1;
  weights2[0] = 2.0;
  dests2[6] = 0.85;
  dests2[7] = 0.05;
  dests2[8] = 0.10;
  flying2[2] = 1;
  weights2[2] = 0.5;
  if (pumiumtally_move_to_next_location(h, origins2, dests2, flying2,
                                        weights2, 3 * NUM) != 0) {
    fprintf(stderr, "FAILURE: move_to_next_location(2) rc != 0\n");
    pumiumtally_destroy(h);
    return 1;
  }

  double expect2[NELEMS];
  memcpy(expect2, expect1, sizeof(expect2));
  if (corrupt) expect2[2] -= 1e-3; /* move-2 increments checked alone */
  expect2[3] += 0.08790490988459178 * 2.0;
  expect2[4] += 0.879049070406094 * 2.0 + 0.552268050859363 * 0.5;
  if (corrupt) expect2[4] += 1e-3;
  pumiumtally_get_flux(h, flux, NELEMS);
  for (int e = 0; e < NELEMS; ++e)
    check_close("move-2 flux", flux[e], expect2[e], TOL);

  int32_t expect_eids[NUM] = {3, 4, 4, 4, 4};
  pumiumtally_get_elem_ids(h, eids, NUM);
  for (int i = 0; i < NUM; ++i)
    check_eq_i("move-2 element", eids[i], expect_eids[i]);
  pumiumtally_get_positions(h, pos, 3 * NUM);
  for (int i = 0; i < 3 * NUM; ++i)
    check_close("move-2 position", pos[i], dests2[i], TOL);

  pumiumtally_destroy(h);
  if (g_failures) {
    fprintf(stderr, "FAILURE: %d oracle mismatches\n", g_failures);
    return 1;
  }
  printf("test_host OK\n");
  return 0;
}
