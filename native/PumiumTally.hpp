/* C++ convenience facade over the C ABI — the drop-in analogue of the
 * reference's PIMPL class (reference src/pumitally/PumiTally.h:34-107):
 * same three-call protocol, builtin-typed parameters, RAII lifetime.
 * Header-only so host apps need only link libpumiumtally_c.
 */
#ifndef PUMIUMTALLY_HPP
#define PUMIUMTALLY_HPP

#include <stdexcept>
#include <string>

#include "pumiumtally_c.h"

namespace pumiumtally {

class PumiTally {
 public:
  PumiTally(const std::string& mesh_filename, int32_t num_particles)
      : h_(pumiumtally_create(mesh_filename.c_str(), num_particles)) {
    if (!h_) throw std::runtime_error("pumiumtally_create failed");
  }
  PumiTally(const PumiTally&) = delete;
  PumiTally& operator=(const PumiTally&) = delete;
  ~PumiTally() { pumiumtally_destroy(h_); }

  /* reference PumiTally.h:66-67 */
  void CopyInitialPosition(const double* positions, int32_t size) {
    check(pumiumtally_copy_initial_position(h_, positions, size),
          "CopyInitialPosition");
  }

  /* reference PumiTally.h:87-89; flying is zeroed in place */
  void MoveToNextLocation(const double* origins, const double* destinations,
                          int8_t* flying, const double* weights,
                          int32_t size) {
    check(pumiumtally_move_to_next_location(h_, origins, destinations,
                                            flying, weights, size),
          "MoveToNextLocation");
  }

  /* Continue-mode fast path (TPU-native extension): transport from the
   * committed positions; flying/weights may be nullptr (all fly / unit
   * weights). */
  void MoveContinue(const double* destinations, int8_t* flying,
                    const double* weights, int32_t size) {
    check(pumiumtally_move_continue(h_, destinations, flying, weights, size),
          "MoveContinue");
  }

  /* reference PumiTally.h:94-95 */
  void WriteTallyResults(const char* filename = nullptr) {
    check(pumiumtally_write_tally_results(h_, filename), "WriteTallyResults");
  }

  int64_t GetFlux(double* out, int64_t capacity) {
    return pumiumtally_get_flux(h_, out, capacity);
  }

  int64_t GetPositions(double* out, int64_t capacity) {
    return pumiumtally_get_positions(h_, out, capacity);
  }

  int64_t GetElemIds(int32_t* out, int64_t capacity) {
    return pumiumtally_get_elem_ids(h_, out, capacity);
  }

 private:
  static void check(int rc, const char* what) {
    if (rc != 0) {
      throw std::runtime_error(std::string("pumiumtally ") + what +
                               " failed");
    }
  }
  pumiumtally_handle* h_;
};

}  // namespace pumiumtally

#endif /* PUMIUMTALLY_HPP */
