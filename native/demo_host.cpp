/* Minimal physics-host stand-in: drives the tally engine through the C
 * ABI exactly as the OpenMC fork drives the reference (ctor →
 * CopyInitialPosition → MoveToNextLocation* → WriteTallyResults;
 * reference images/public_methods_explanation.svg call sites, SURVEY.md
 * §1). Pure C++ — proves a host app needs no Python/JAX toolchain.
 *
 * Usage: demo <mesh.msh> [num_particles]
 */
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "PumiumTally.hpp"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <mesh.msh> [num_particles]\n", argv[0]);
    return 2;
  }
  const char* mesh = argv[1];
  int32_t n = argc > 2 ? std::atoi(argv[2]) : 1000;

  pumiumtally::PumiTally tally(mesh, n);

  std::vector<double> pos(3 * n);
  for (int32_t i = 0; i < n; ++i) {
    pos[3 * i + 0] = 0.1 + 0.8 * (double)i / n;
    pos[3 * i + 1] = 0.4;
    pos[3 * i + 2] = 0.5;
  }
  tally.CopyInitialPosition(pos.data(), 3 * n);

  std::vector<double> dest(3 * n);
  std::vector<int8_t> flying(n, 1);
  std::vector<double> weights(n, 1.0);
  for (int32_t i = 0; i < n; ++i) {
    dest[3 * i + 0] = pos[3 * i + 0];
    dest[3 * i + 1] = pos[3 * i + 1] + 0.3;
    dest[3 * i + 2] = pos[3 * i + 2];
  }
  tally.MoveToNextLocation(pos.data(), dest.data(), flying.data(),
                           weights.data(), 3 * n);
  for (int32_t i = 0; i < n; ++i) {
    if (flying[i] != 0) {
      std::fprintf(stderr, "FAIL: flying[] not zeroed in place\n");
      return 1;
    }
  }

  int64_t ne = tally.GetFlux(nullptr, 0);
  std::vector<double> flux((size_t)ne);
  tally.GetFlux(flux.data(), ne);
  double total = 0.0;
  for (double f : flux) total += f;
  /* every particle flies 0.3 inside the box → sum(flux) = 0.3 * n */
  double expect = 0.3 * n;
  if (total < expect - 1e-6 || total > expect + 1e-6) {
    std::fprintf(stderr, "FAIL: sum(flux)=%.9f expected %.9f\n", total,
                 expect);
    return 1;
  }
  tally.WriteTallyResults("demo_fluxresult.vtk");
  std::printf("demo OK: %lld elements, sum(flux)=%.9f\n", (long long)ne,
              total);
  return 0;
}
