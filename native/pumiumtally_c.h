/* C ABI for the TPU-native tally engine.
 *
 * Mirrors the reference's public facade protocol (reference
 * src/pumitally/PumiTally.h:34-107): an opaque handle plus the three
 * calls CopyInitialPosition / MoveToNextLocation / WriteTallyResults,
 * all parameters builtin types only (reference PumiTally.h:29-30 pins
 * that design so the physics host app needs no GPU/JAX toolchain).
 *
 * The implementation embeds a CPython interpreter hosting the JAX
 * engine; a host app (e.g. the OpenMC --ohMesh fork, reference
 * README.md:84-104) links this library exactly as it links the
 * reference's libpumitally.
 */
#ifndef PUMIUMTALLY_C_H
#define PUMIUMTALLY_C_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct pumiumtally_handle pumiumtally_handle;

/* Create an engine bound to a mesh file (.msh Gmsh ASCII/binary or
 * .osh Omega_h directory; the reference ctor takes its .osh path,
 * PumiTally.h:50). The engine flavor is environment-selected so this
 * signature stays builtin-typed: PUMIUMTALLY_ENGINE = mono (default),
 * streaming, partitioned, or streaming_partitioned, with
 * PUMIUMTALLY_{DEVICES,CHUNK_SIZE,CAPACITY_FACTOR,TOLERANCE,OUTPUT}
 * knobs (see pumiumtally_tpu/api/native.py).
 * Returns NULL on failure (error printed to stderr). */
pumiumtally_handle* pumiumtally_create(const char* mesh_filename,
                                       int32_t num_particles);

/* Localize particles at sampled source points; positions has
 * 3*num_particles doubles (reference PumiTally.h:66-67). Returns 0 on
 * success. */
int pumiumtally_copy_initial_position(pumiumtally_handle* h,
                                      const double* positions,
                                      int32_t size);

/* Two-phase tracked move (reference PumiTally.h:87-89). flying is
 * ZEROED in place after staging, matching the reference's documented
 * host-side side effect (reference PumiTallyImpl.cpp:169-172).
 * Returns 0 on success. */
int pumiumtally_move_to_next_location(pumiumtally_handle* h,
                                      const double* origins,
                                      const double* destinations,
                                      int8_t* flying,
                                      const double* weights,
                                      int32_t size);

/* Continue-mode move (TPU-native extension): transport straight from
 * the committed positions — valid whenever no particle was resampled
 * since the last move. Halves staging and device work versus the
 * two-phase call. flying may be NULL (everyone flies; no zeroing side
 * effect) and weights may be NULL (unit weights). Returns 0 on
 * success. */
int pumiumtally_move_continue(pumiumtally_handle* h,
                              const double* destinations,
                              int8_t* flying,
                              const double* weights,
                              int32_t size);

/* Normalize by element volume and write the VTK file (reference
 * PumiTally.h:94-95; hard-default name fluxresult.vtk). Pass NULL for
 * the default filename. Returns 0 on success. */
int pumiumtally_write_tally_results(pumiumtally_handle* h,
                                    const char* filename);

/* Copy the current per-element flux into out[nelems]; returns the
 * element count (or <0 on error) so hosts can size the buffer with
 * out=NULL first. */
int64_t pumiumtally_get_flux(pumiumtally_handle* h, double* out,
                             int64_t capacity);

/* Copy the committed particle positions into out[3*num_particles];
 * returns the value count 3*num_particles (or <0 on error). */
int64_t pumiumtally_get_positions(pumiumtally_handle* h, double* out,
                                  int64_t capacity);

/* Copy the current element id of each particle into
 * out[num_particles]; returns num_particles (or <0 on error). */
int64_t pumiumtally_get_elem_ids(pumiumtally_handle* h, int32_t* out,
                                 int64_t capacity);

void pumiumtally_destroy(pumiumtally_handle* h);

#ifdef __cplusplus
}
#endif

#endif /* PUMIUMTALLY_C_H */
