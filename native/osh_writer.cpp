// osh_writer — a standalone transcription of Omega_h's binary `.osh`
// serialization logic, used to generate test fixtures this package's
// Python reader (pumiumtally_tpu/io/osh.py) must parse.
//
// WHY THIS EXISTS: the reference library loads meshes with
// `Omega_h::binary::read` (reference PumiTallyImpl.cpp:562), but no
// Omega_h build is obtainable in this environment (no network). The
// Python reader and the Python fixture writer (tools/make_osh_fixture.py)
// were both written against one reading of the public Omega_h sources,
// so a systematic misreading could pass both. This file transcribes the
// WRITE PATH of `Omega_h_file.cpp` into dependency-free C++ (zlib only,
// as upstream) with the same function decomposition the upstream code
// has — write_value / write_array / write_string / write_meta /
// write_tag / write(stream, mesh) — so its bytes are derived from the
// upstream code's structure rather than from this repo's Python
// modules. Fixtures it generates are checked in and parsed by
// tests/test_io.py.
//
// Transcribed layout decisions (each mirrors Omega_h_file.cpp):
//   * canonical byte order is the CPU's when little-endian; values are
//     byte-swapped only on big-endian CPUs (`needs_swapping =
//     !is_little_endian_cpu()`), so streams are little-endian on disk;
//   * the stream does NOT repeat the format version: directories carry
//     it in the `version` file (the in-stream version exists only in
//     pre-version-4 files, which this writer does not emit);
//   * arrays are [int32 count][int64 zbytes][zlib payload] when
//     compressed (compress2 at Z_BEST_SPEED) or raw bytes otherwise;
//   * meta is: compressed?(i8) family(i8) dim(i8) comm_size(i32)
//     comm_rank(i32) parting(i8) nghost(i32) have_hints(i8) [hints],
//     then (version >= 10 only) matched(i8) — this writer emits
//     version 9 and so no matched byte;
//   * per dimension d=1..3 the downward adjacency ab2b (i32) plus,
//     for d>1, the alignment codes (i8, code = rotation<<1 | flip per
//     Omega_h_align.hpp);
//   * per dimension d=0..3: ntags(i32), then each tag as
//     name(i32 len + bytes) ncomps(i8) type(i8) data-array, with the
//     Omega_h_Type codes I8=0, I32=2, I64=3, F64=5; then, only when
//     comm_size > 1, the owner ranks + idxs arrays.
//
// Entity derivation (edges/triangles from tets) follows PUMIPic/Omega_h
// reflect_down semantics: entities numbered by FIRST APPEARANCE while
// scanning parents in order, storing each entity's vertices in the
// order induced by the parent that defined it — which makes the
// alignment codes nontrivial (the Python reader claims insensitivity
// to them; these fixtures exercise that claim with independent bytes).
//
// Build: make -C native osh_writer   (links only -lz)
// Run:   ./native/osh_writer OUTDIR  — writes OUTDIR/cube_omega_cpp.osh
//        (compressed) and OUTDIR/cube_omega_cpp_raw.osh (uncompressed).

#include <zlib.h>

#include <array>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <string>
#include <sys/stat.h>
#include <vector>

namespace osh {

using I8 = std::int8_t;
using I32 = std::int32_t;
using I64 = std::int64_t;
using Real = double;

static_assert(sizeof(I32) == 4, "osh format assumes 32 bit Int");
static_assert(sizeof(I64) == 8, "osh format assumes 64 bit GO");
static_assert(sizeof(Real) == 8, "osh format assumes 64 bit Real");

constexpr I32 latest_version = 9;  // what this writer emits

bool is_little_endian_cpu() {
  std::uint16_t const endian_canary = 0x1;
  std::uint8_t const* p =
      reinterpret_cast<std::uint8_t const*>(&endian_canary);
  return *p == 0x1;
}

template <typename T>
void swap_bytes(T& val) {
  char* p = reinterpret_cast<char*>(&val);
  for (std::size_t i = 0; i < sizeof(T) / 2; ++i) {
    char const t = p[i];
    p[i] = p[sizeof(T) - 1 - i];
    p[sizeof(T) - 1 - i] = t;
  }
}

static bool const needs_swapping = !is_little_endian_cpu();

template <typename T>
void write_value(std::ostream& stream, T val) {
  if (needs_swapping) swap_bytes(val);
  stream.write(reinterpret_cast<const char*>(&val), sizeof(T));
}

template <typename T>
void write_array(std::ostream& stream, std::vector<T> const& array,
                 bool is_compressed) {
  I32 const size = static_cast<I32>(array.size());
  write_value(stream, size);
  std::vector<T> swapped;
  T const* data = array.data();
  if (needs_swapping) {
    swapped = array;
    for (auto& v : swapped) swap_bytes(v);
    data = swapped.data();
  }
  I64 const uncompressed_bytes =
      static_cast<I64>(array.size() * sizeof(T));
  if (is_compressed) {
    uLong const source_bytes = static_cast<uLong>(uncompressed_bytes);
    uLong dest_bytes = ::compressBound(source_bytes);
    std::vector<Bytef> compressed(dest_bytes);
    int const ret = ::compress2(
        compressed.data(), &dest_bytes,
        reinterpret_cast<const Bytef*>(data), source_bytes, Z_BEST_SPEED);
    if (ret != Z_OK) {
      std::fprintf(stderr, "compress2 failed (%d)\n", ret);
      std::exit(1);
    }
    I64 const compressed_bytes = static_cast<I64>(dest_bytes);
    write_value(stream, compressed_bytes);
    stream.write(reinterpret_cast<const char*>(compressed.data()),
                 compressed_bytes);
  } else {
    stream.write(reinterpret_cast<const char*>(data), uncompressed_bytes);
  }
}

void write_string(std::ostream& stream, std::string const& val) {
  I32 const len = static_cast<I32>(val.length());
  write_value(stream, len);
  stream.write(val.c_str(), len);
}

// ---- the mesh we serialize ------------------------------------------------

// Omega_h_Type codes (Omega_h_defines.h).
enum TagType : I8 { OSH_I8 = 0, OSH_I32 = 2, OSH_I64 = 3, OSH_F64 = 5 };

struct Tag {
  std::string name;
  I8 ncomps;
  TagType type;
  std::vector<I8> i8s;
  std::vector<I32> i32s;
  std::vector<I64> i64s;
  std::vector<Real> reals;
};

struct Mesh {
  I8 dim = 3;
  I32 comm_size = 1;
  I32 comm_rank = 0;
  I32 nverts = 0;
  // Downward adjacency chain + alignment codes.
  std::vector<I32> edge2vert;              // [nedges*2]
  std::vector<I32> tri2edge;               // [ntris*3]
  std::vector<I8> tri_codes;               // [ntris*3]
  std::vector<I32> tet2tri;                // [ntets*4]
  std::vector<I8> tet_codes;               // [ntets*4]
  std::array<std::vector<Tag>, 4> tags;    // per dimension
};

// write_meta (Omega_h_file.cpp): everything between the compression
// flag and the vertex count.
void write_meta(std::ostream& stream, Mesh const& mesh) {
  I8 const family = 0;  // OMEGA_H_SIMPLEX
  write_value(stream, family);
  write_value(stream, mesh.dim);
  write_value(stream, mesh.comm_size);
  write_value(stream, mesh.comm_rank);
  I8 const parting = 0;  // OMEGA_H_ELEM_BASED
  write_value(stream, parting);
  I32 const nghost_layers = 0;
  write_value(stream, nghost_layers);
  I8 const have_hints = 0;  // no RIB hints
  write_value(stream, have_hints);
  // version >= 10 would write the matched flag here; we emit 9.
}

void write_tag(std::ostream& stream, Tag const& tag, bool is_compressed) {
  write_string(stream, tag.name);
  write_value(stream, tag.ncomps);
  I8 const type = static_cast<I8>(tag.type);
  write_value(stream, type);
  switch (tag.type) {
    case OSH_I8:
      write_array(stream, tag.i8s, is_compressed);
      break;
    case OSH_I32:
      write_array(stream, tag.i32s, is_compressed);
      break;
    case OSH_I64:
      write_array(stream, tag.i64s, is_compressed);
      break;
    case OSH_F64:
      write_array(stream, tag.reals, is_compressed);
      break;
  }
}

// binary::write(std::ostream&, Mesh*) — the stream body.
void write(std::ostream& stream, Mesh const& mesh, bool is_compressed) {
  unsigned char const magic[2] = {0xa1, 0x1a};
  stream.write(reinterpret_cast<const char*>(magic), sizeof(magic));
  // (the format version was moved out of the stream into the
  //  directory's `version` file at version 4)
  I8 const compressed_flag = is_compressed ? 1 : 0;
  write_value(stream, compressed_flag);
  write_meta(stream, mesh);
  write_value(stream, mesh.nverts);
  // Downward adjacencies, d = 1..dim; codes only for d > 1.
  write_array(stream, mesh.edge2vert, is_compressed);
  write_array(stream, mesh.tri2edge, is_compressed);
  write_array(stream, mesh.tri_codes, is_compressed);
  write_array(stream, mesh.tet2tri, is_compressed);
  write_array(stream, mesh.tet_codes, is_compressed);
  for (int d = 0; d <= mesh.dim; ++d) {
    I32 const ntags = static_cast<I32>(mesh.tags[d].size());
    write_value(stream, ntags);
    for (auto const& tag : mesh.tags[d]) {
      write_tag(stream, tag, is_compressed);
    }
    // comm_size == 1 here: no owner arrays.
  }
}

// ---- entity derivation (reflect_down semantics) ---------------------------

// Canonical simplex templates (Omega_h_simplex.hpp).
constexpr int tet_faces[4][3] = {{0, 2, 1}, {0, 1, 3}, {1, 2, 3}, {2, 0, 3}};
constexpr int tri_edges[3][2] = {{0, 1}, {1, 2}, {2, 0}};

// Alignment code (Omega_h_align.hpp): code = rotation << 1 | flip,
// where `rotation` rotates the STORED vertex order and `flip` swaps
// the last two, reproducing the USE order in the parent.
template <int N>
I8 align_code(std::array<I32, N> const& stored,
              std::array<I32, N> const& use) {
  for (int rot = 0; rot < N; ++rot) {
    std::array<I32, N> r;
    for (int i = 0; i < N; ++i) r[i] = stored[(i + rot) % N];
    if (r == use) return static_cast<I8>(rot << 1);
    std::array<I32, N> fl = r;
    if (N >= 2) {
      I32 const t = fl[N - 2];
      fl[N - 2] = fl[N - 1];
      fl[N - 1] = t;
    }
    if (fl == use) return static_cast<I8>((rot << 1) | 1);
  }
  std::fprintf(stderr, "no alignment code found\n");
  std::exit(1);
}

// First-appearance entity map: key = sorted vertex tuple; value =
// (entity id, stored vertex order = first use's order).
template <int N>
struct EntitySet {
  std::map<std::array<I32, N>, std::pair<I32, std::array<I32, N>>> byKey;
  std::vector<std::array<I32, N>> stored;  // id -> stored vertex order

  // Returns (id, code aligning stored order onto this use's order).
  std::pair<I32, I8> use(std::array<I32, N> const& verts) {
    std::array<I32, N> key = verts;
    for (int i = 0; i < N - 1; ++i)  // tiny N: insertion sort
      for (int j = i + 1; j < N; ++j)
        if (key[j] < key[i]) {
          I32 const t = key[i];
          key[i] = key[j];
          key[j] = t;
        }
    auto it = byKey.find(key);
    if (it == byKey.end()) {
      I32 const id = static_cast<I32>(stored.size());
      byKey.emplace(key, std::make_pair(id, verts));
      stored.push_back(verts);
      return {id, 0};  // defining use: identity alignment
    }
    return {it->second.first, align_code<N>(it->second.second, verts)};
  }
};

Mesh build_mesh(std::vector<Real> const& coords,
                std::vector<std::array<I32, 4>> const& tets) {
  Mesh mesh;
  mesh.nverts = static_cast<I32>(coords.size() / 3);
  EntitySet<3> tris;
  EntitySet<2> edges;
  // Pass 1: triangles from tets, in parent order.
  for (auto const& tet : tets) {
    for (auto const& f : tet_faces) {
      std::array<I32, 3> const fv = {tet[f[0]], tet[f[1]], tet[f[2]]};
      auto const [id, code] = tris.use(fv);
      mesh.tet2tri.push_back(id);
      mesh.tet_codes.push_back(code);
    }
  }
  // Pass 2: edges from triangles, in triangle-id order.
  for (auto const& tv : tris.stored) {
    for (auto const& e : tri_edges) {
      std::array<I32, 2> const ev = {tv[e[0]], tv[e[1]]};
      auto const [id, code] = edges.use(ev);
      mesh.tri2edge.push_back(id);
      mesh.tri_codes.push_back(code);
    }
  }
  for (auto const& ev : edges.stored) {
    mesh.edge2vert.push_back(ev[0]);
    mesh.edge2vert.push_back(ev[1]);
  }

  // Tags: what msh2osh output carries — coordinates + globals on the
  // vertices, class_id/class_dim + globals on the elements.
  I32 const nedges = static_cast<I32>(edges.stored.size());
  I32 const ntris = static_cast<I32>(tris.stored.size());
  I32 const ntets = static_cast<I32>(tets.size());
  {
    Tag t;
    t.name = "coordinates";
    t.ncomps = 3;
    t.type = OSH_F64;
    t.reals = coords;
    mesh.tags[0].push_back(t);
  }
  auto global_tag = [](I32 n) {
    Tag t;
    t.name = "global";
    t.ncomps = 1;
    t.type = OSH_I64;
    for (I32 i = 0; i < n; ++i) t.i64s.push_back(i);
    return t;
  };
  mesh.tags[0].push_back(global_tag(mesh.nverts));
  mesh.tags[1].push_back(global_tag(nedges));
  mesh.tags[2].push_back(global_tag(ntris));
  {
    Tag t;
    t.name = "class_id";
    t.ncomps = 1;
    t.type = OSH_I32;
    for (I32 i = 0; i < ntets; ++i) t.i32s.push_back(1);
    mesh.tags[3].push_back(t);
    Tag d;
    d.name = "class_dim";
    d.ncomps = 1;
    d.type = OSH_I8;
    for (I32 i = 0; i < ntets; ++i) d.i8s.push_back(3);
    mesh.tags[3].push_back(d);
  }
  mesh.tags[3].push_back(global_tag(ntets));
  return mesh;
}

// Directory-level write (binary::write(path, mesh)): the rank streams
// plus the `nparts` and `version` ASCII files.
void write_dir(std::string const& path, Mesh const& mesh,
               bool is_compressed) {
  ::mkdir(path.c_str(), 0755);
  {
    std::ofstream f(path + "/nparts");
    f << mesh.comm_size << '\n';
  }
  {
    std::ofstream f(path + "/version");
    f << latest_version << '\n';
  }
  std::ofstream f(path + "/" + std::to_string(mesh.comm_rank) + ".osh",
                  std::ios::binary);
  write(f, mesh, is_compressed);
}

}  // namespace osh

int main(int argc, char** argv) {
  if (argc != 2) {
    std::fprintf(stderr, "usage: %s OUTDIR\n", argv[0]);
    return 1;
  }
  std::string const out = argv[1];
  // The unit cube split into 6 tets around the main diagonal v0-v6 —
  // the reference test fixture geometry (build_box(1,1,1,1,1,1),
  // reference test_pumi_tally_impl_methods.cpp:34-35).
  std::vector<osh::Real> const coords = {
      0, 0, 0, 1, 0, 0, 1, 1, 0, 0, 1, 0,
      0, 0, 1, 1, 0, 1, 1, 1, 1, 0, 1, 1,
  };
  std::vector<std::array<osh::I32, 4>> const tets = {
      {0, 1, 2, 6}, {0, 2, 3, 6}, {0, 3, 7, 6},
      {0, 7, 4, 6}, {0, 4, 5, 6}, {0, 5, 1, 6},
  };
  auto const mesh = osh::build_mesh(coords, tets);
  osh::write_dir(out + "/cube_omega_cpp.osh", mesh, true);
  osh::write_dir(out + "/cube_omega_cpp_raw.osh", mesh, false);
  std::printf("wrote %s/cube_omega_cpp.osh (+_raw)\n", out.c_str());
  return 0;
}
