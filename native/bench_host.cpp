/* Native-path benchmark host: drives the full two-phase protocol
 * through the C ABI (embedded-interpreter boundary) on the SAME
 * workload shape as bench.py, so the per-call cost of the native
 * facade can be compared against the pure-Python facade (round-3
 * VERDICT item 7; the reference's physics host pays this boundary on
 * every call, reference PumiTally.cpp:16-60).
 *
 * Prints one line:  native_two_phase_moves_per_sec=<rate>
 *
 * Usage: bench_host <mesh file> [num_particles] [moves]
 */
#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

#include "pumiumtally_c.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <mesh> [n] [moves]\n", argv[0]);
    return 2;
  }
  const char* mesh = argv[1];
  const int32_t n = argc > 2 ? std::atoi(argv[2]) : 500000;
  const int moves = argc > 3 ? std::atoi(argv[3]) : 6;

  pumiumtally_handle* h = pumiumtally_create(mesh, n);
  if (!h) return 1;

  /* bench.py's make_trajectory shape: uniform interior source, then
   * clipped gaussian steps of mean length 0.25 (statistically — not
   * bitwise — the Python bench's workload). */
  std::mt19937_64 rng(0);
  std::uniform_real_distribution<double> uni(0.05, 0.95);
  std::normal_distribution<double> step(0.0, 0.25 / std::sqrt(3.0));
  std::vector<std::vector<double>> pts(moves + 2,
                                       std::vector<double>(3 * (size_t)n));
  for (int32_t i = 0; i < 3 * n; ++i) pts[0][i] = uni(rng);
  for (int m = 1; m < moves + 2; ++m)
    for (int32_t i = 0; i < 3 * n; ++i) {
      double v = pts[m - 1][i] + step(rng);
      pts[m][i] = v < 0.02 ? 0.02 : (v > 0.98 ? 0.98 : v);
    }

  if (pumiumtally_copy_initial_position(h, pts[0].data(), 3 * n)) return 1;

  std::vector<int8_t> flying((size_t)n);
  std::vector<double> weights((size_t)n, 1.0);
  auto drive = [&](int m) {
    std::fill(flying.begin(), flying.end(), (int8_t)1);
    return pumiumtally_move_to_next_location(
        h, pts[m - 1].data(), pts[m].data(), flying.data(), weights.data(),
        3 * n);
  };

  if (drive(1)) return 1; /* warmup: compiles the kernels */
  /* a flux fetch is the real sync on a lazy backend */
  std::vector<double> flux;
  int64_t ne = pumiumtally_get_flux(h, nullptr, 0);
  if (ne < 0) return 1;
  flux.resize((size_t)ne);
  pumiumtally_get_flux(h, flux.data(), ne);

  auto t0 = std::chrono::steady_clock::now();
  for (int m = 2; m < moves + 2; ++m)
    if (drive(m)) return 1;
  pumiumtally_get_flux(h, flux.data(), ne); /* sync */
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0)
                  .count();

  double total = 0.0;
  for (double f : flux) total += f;
  std::printf("native_two_phase_moves_per_sec=%.0f (sum normflux %.4f, "
              "%d moves of %d particles in %.3f s)\n",
              (double)n * moves / dt, total, moves, n, dt);
  pumiumtally_destroy(h);
  return 0;
}
