/* C ABI implementation: embeds CPython hosting the JAX tally engine.
 *
 * Native-runtime equivalent of the reference's PIMPL facade
 * (reference src/pumitally/PumiTally.cpp:16-60): the host app sees
 * builtin-typed C calls; device work happens in the embedded
 * interpreter (XLA on TPU). Buffers cross the boundary zero-copy as
 * numpy views over the host pointers — the same trick as the
 * reference's unmanaged Kokkos views over OpenMC's arrays (reference
 * PumiTallyImpl.cpp:159-236) — and the Python layer copies them to
 * device exactly once.
 *
 * Interpreter ownership: if the process already runs Python (e.g. the
 * ctypes test harness), we attach via PyGILState; otherwise we
 * initialize an interpreter on first create and keep it until process
 * exit (finalizing JAX's runtime mid-process is not supported).
 */
#include "pumiumtally_c.h"

#include <Python.h>

#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#define PY_ARRAY_UNIQUE_SYMBOL pumiumtally_ARRAY_API
#include <numpy/arrayobject.h>

#include <cstdio>
#include <cstring>

namespace {

/* numpy C-API table is per-shared-object; resolve lazily. */
bool g_numpy_ready = false;

bool ensure_numpy() {
  if (g_numpy_ready) return true;
  if (_import_array() < 0) {
    PyErr_Print();
    return false;
  }
  g_numpy_ready = true;
  return true;
}

struct GilGuard {
  PyGILState_STATE state;
  GilGuard() : state(PyGILState_Ensure()) {}
  ~GilGuard() { PyGILState_Release(state); }
};

void ensure_interpreter() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    /* Release the GIL acquired by initialization so GilGuard works
     * uniformly afterwards. */
    PyEval_SaveThread();
  }
}

PyObject* np_view_1d(void* data, npy_intp n, int typenum, bool writeable) {
  int flags = NPY_ARRAY_C_CONTIGUOUS | (writeable ? NPY_ARRAY_WRITEABLE : 0);
  return PyArray_New(&PyArray_Type, 1, &n, typenum, nullptr, data, 0, flags,
                     nullptr);
}

int fail_py(const char* what) {
  std::fprintf(stderr, "[ERROR] pumiumtally: %s failed:\n", what);
  PyErr_Print();
  return -1;
}

}  // namespace

struct pumiumtally_handle {
  PyObject* tally;  // pumiumtally_tpu.PumiTally instance
  int32_t num_particles;
};

extern "C" {

pumiumtally_handle* pumiumtally_create(const char* mesh_filename,
                                       int32_t num_particles) {
  ensure_interpreter();
  GilGuard gil;
  if (!ensure_numpy()) return nullptr;

  /* Engine selection (mono / streaming / partitioned / ...) is
   * environment-driven so the C signature stays the reference's;
   * see pumiumtally_tpu/api/native.py for the PUMIUMTALLY_* vars. */
  PyObject* mod = PyImport_ImportModule("pumiumtally_tpu.api.native");
  if (!mod) {
    fail_py("import pumiumtally_tpu.api.native");
    return nullptr;
  }
  PyObject* cls = PyObject_GetAttrString(mod, "native_create");
  Py_DECREF(mod);
  if (!cls) {
    fail_py("native_create lookup");
    return nullptr;
  }
  PyObject* tally = PyObject_CallFunction(cls, "si", mesh_filename,
                                          (int)num_particles);
  Py_DECREF(cls);
  if (!tally) {
    fail_py("native_create()");
    return nullptr;
  }
  auto* h = new pumiumtally_handle{tally, num_particles};
  return h;
}

int pumiumtally_copy_initial_position(pumiumtally_handle* h,
                                      const double* positions,
                                      int32_t size) {
  if (!h) return -1;
  GilGuard gil;
  PyObject* arr =
      np_view_1d(const_cast<double*>(positions), size, NPY_DOUBLE, false);
  if (!arr) return fail_py("position view");
  PyObject* r = PyObject_CallMethod(h->tally, "CopyInitialPosition", "Oi",
                                    arr, (int)size);
  Py_DECREF(arr);
  if (!r) return fail_py("CopyInitialPosition");
  Py_DECREF(r);
  return 0;
}

namespace {

/* Shared body of the two move entry points. origins may be NULL
 * (continue mode); flying may be NULL (all fly; no zeroing side
 * effect); weights may be NULL (unit weights). A NULL pointer becomes
 * Python None, which the engine's MoveToNextLocation interprets the
 * same way (api/tally.py). */
int do_move(pumiumtally_handle* h, const double* origins,
            const double* destinations, int8_t* flying, const double* weights,
            int32_t size) {
  if (!h) return -1;
  GilGuard gil;
  PyObject* o = origins
                    ? np_view_1d(const_cast<double*>(origins), size,
                                 NPY_DOUBLE, false)
                    : (Py_INCREF(Py_None), Py_None);
  PyObject* d =
      np_view_1d(const_cast<double*>(destinations), size, NPY_DOUBLE, false);
  /* flying is writeable: the Python layer zeroes it in place (the
   * reference's documented side effect, PumiTallyImpl.cpp:169-172). */
  PyObject* f = flying ? np_view_1d(flying, h->num_particles, NPY_INT8, true)
                       : (Py_INCREF(Py_None), Py_None);
  PyObject* w = weights
                    ? np_view_1d(const_cast<double*>(weights),
                                 h->num_particles, NPY_DOUBLE, false)
                    : (Py_INCREF(Py_None), Py_None);
  if (!o || !d || !f || !w) {
    Py_XDECREF(o);
    Py_XDECREF(d);
    Py_XDECREF(f);
    Py_XDECREF(w);
    return fail_py("buffer views");
  }
  PyObject* r = PyObject_CallMethod(h->tally, "MoveToNextLocation", "OOOOi",
                                    o, d, f, w, (int)size);
  Py_DECREF(o);
  Py_DECREF(d);
  Py_DECREF(f);
  Py_DECREF(w);
  if (!r) return fail_py("MoveToNextLocation");
  Py_DECREF(r);
  return 0;
}

}  // namespace

int pumiumtally_move_to_next_location(pumiumtally_handle* h,
                                      const double* origins,
                                      const double* destinations,
                                      int8_t* flying,
                                      const double* weights,
                                      int32_t size) {
  return do_move(h, origins, destinations, flying, weights, size);
}

int pumiumtally_move_continue(pumiumtally_handle* h,
                              const double* destinations,
                              int8_t* flying,
                              const double* weights,
                              int32_t size) {
  /* origins=NULL selects the continue-mode fast path (api/tally.py). */
  return do_move(h, nullptr, destinations, flying, weights, size);
}

namespace {

/* Copy a 1-D numpy-convertible attribute of the tally into out. */
int64_t copy_attr(pumiumtally_handle* h, const char* attr, const char* npdtype,
                  void* out, int64_t capacity, size_t elem_size) {
  GilGuard gil;
  PyObject* val = PyObject_GetAttrString(h->tally, attr);
  if (!val) return fail_py(attr);
  PyObject* np = PyImport_ImportModule("numpy");
  if (!np) {
    Py_DECREF(val);
    return fail_py("import numpy");
  }
  PyObject* dtype = PyObject_GetAttrString(np, npdtype);
  PyObject* asarr =
      dtype ? PyObject_CallMethod(np, "ascontiguousarray", "OO", val, dtype)
            : nullptr;
  Py_XDECREF(dtype);
  Py_DECREF(np);
  Py_DECREF(val);
  if (!asarr) return fail_py("ascontiguousarray");
  auto* a = reinterpret_cast<PyArrayObject*>(asarr);
  int64_t n = (int64_t)PyArray_SIZE(a);
  if (out && capacity >= n) {
    std::memcpy(out, PyArray_DATA(a), (size_t)n * elem_size);
  }
  Py_DECREF(asarr);
  return n;
}

}  // namespace

int64_t pumiumtally_get_positions(pumiumtally_handle* h, double* out,
                                  int64_t capacity) {
  if (!h) return -1;
  return copy_attr(h, "positions", "float64", out, capacity, sizeof(double));
}

int64_t pumiumtally_get_elem_ids(pumiumtally_handle* h, int32_t* out,
                                 int64_t capacity) {
  if (!h) return -1;
  return copy_attr(h, "elem_ids", "int32", out, capacity, sizeof(int32_t));
}

int pumiumtally_write_tally_results(pumiumtally_handle* h,
                                    const char* filename) {
  if (!h) return -1;
  GilGuard gil;
  PyObject* r;
  if (filename) {
    r = PyObject_CallMethod(h->tally, "WriteTallyResults", "s", filename);
  } else {
    r = PyObject_CallMethod(h->tally, "WriteTallyResults", nullptr);
  }
  if (!r) return fail_py("WriteTallyResults");
  Py_DECREF(r);
  return 0;
}

int64_t pumiumtally_get_flux(pumiumtally_handle* h, double* out,
                             int64_t capacity) {
  if (!h) return -1;
  return copy_attr(h, "flux", "float64", out, capacity, sizeof(double));
}

void pumiumtally_destroy(pumiumtally_handle* h) {
  if (!h) return;
  {
    GilGuard gil;
    Py_DECREF(h->tally);
  }
  delete h;
}

}  // extern "C"
