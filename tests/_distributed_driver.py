"""Subprocess driver for the multi-process distributed tests
(tests/test_distributed.py, tests/test_multiprocess.py — the pattern
of tests/_service_driver.py).

Two halves:

- ``main()`` — the WORKER: one ``jax.distributed`` process with its
  own virtual CPU devices, joining the localhost coordinator, probing
  that this jaxlib can actually execute cross-process collectives
  (``assert_collectives_available``), running one seeded campaign arm
  against the global mesh, and (process 0) saving the fetched global
  results for the parent to compare bitwise against a single-process
  reference. A backend that cannot run cross-process collectives (CPU
  jaxlib without gloo) exits ``UNAVAILABLE_EXIT_CODE`` (77) with the
  ``DISTRIBUTED-UNAVAILABLE`` marker — the launcher converts that to a
  pytest SKIP, never a failure.

- ``launch_distributed`` / ``launch_or_skip`` — the LAUNCHER: spawns
  the worker pair with a free-port RETRY loop (the coordinator port is
  probed then bound by a different process — a lost race answers
  "address in use" and simply retries on a fresh port), bounds the
  wait with ``PUMIUMTALLY_SUBPROC_TIMEOUT`` (default 280 s; the expiry
  message names the env var), and kills the peer the moment one worker
  reports unavailable so the skip is prompt instead of waiting out the
  peer's collective timeout. These two mechanisms + the clear skip are
  the fix for the pre-existing two-process slow-test flakiness (noted
  environmental since PR 2).

``build_tally``/``run_campaign``/``collect`` are imported by the
parity tests to run the IDENTICAL campaign single-process at the same
global shapes — one code path for both sides of the bitwise contract.

Skip accounting (round 19): ``probe_collectives`` runs ONE tiny
two-process worker pair (``--arm probe``: init + collective probe,
no campaign) the first time any cross-process test launches, and the
verdict is cached for the whole session — so a gloo-less CPU jaxlib
pays one fast probe instead of N full campaign timeouts. Every
``launch_or_skip`` outcome is tallied in ``RAN``/``SKIPPED`` and
``tests/conftest.py`` prints one skipped-vs-run summary line at the
end of the session. The skip reason is EXACTLY the
``DISTRIBUTED-UNAVAILABLE`` marker (asserted by
tests/test_distributed.py) so skip triage greps one token.
"""

from __future__ import annotations

import argparse
import os
import re
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N = 256
MESH_ARGS = (1, 1, 1, 3, 3, 3)
ARMS = ("sharded", "partitioned", "partitioned_scoring")
# "probe" is a worker mode, not a parity arm: init + collective probe,
# then exit — the session-start gloo capability check.
_WORKER_MODES = ARMS + ("probe",)

# Session accounting for the one-line skipped-vs-run summary printed
# by tests/conftest.py::pytest_terminal_summary. Appended by
# launch_or_skip only (the pytest entry point), never by raw
# launch_distributed calls from tools.
RAN: list = []
SKIPPED: list = []
_INIT_FAILED_MARKER = "DISTRIBUTED-INIT-FAILED"
_PORT_RETRY_PATTERNS = ("address already in use", "failed to bind",
                        "address in use", "errno 98")


def _scoring_spec():
    from pumiumtally_tpu import EnergyFilter, ScoringSpec

    return ScoringSpec(filters=[EnergyFilter([0.0, 1.0, 2.0])],
                       scores=["flux", "events"])


def build_tally(arm: str, mesh_dev):
    """The campaign facade for one parity arm — called with the global
    2-process mesh by the worker and the 8-virtual-device
    single-process mesh by the reference side (same global shapes)."""
    from pumiumtally_tpu import (
        PartitionedPumiTally,
        PumiTally,
        TallyConfig,
        build_box,
    )

    mesh = build_box(*MESH_ARGS)
    if arm == "sharded":
        return PumiTally(
            mesh, N,
            TallyConfig(device_mesh=mesh_dev, check_found_all=False),
        )
    kw = dict(device_mesh=mesh_dev, check_found_all=False,
              capacity_factor=8.0, migrate_collective=True)
    if arm == "partitioned_scoring":
        kw["scoring"] = _scoring_spec()
    elif arm != "partitioned":
        raise ValueError(f"unknown arm {arm!r} (one of {ARMS})")
    return PartitionedPumiTally(mesh, N, TallyConfig(**kw))


def run_campaign(t, arm: str) -> None:
    """Two seeded long-step moves (many partition crossings, hence
    cross-process migrations in the partitioned arms)."""
    import numpy as np

    rng = np.random.default_rng(42)
    src = rng.uniform(0.1, 0.9, (N, 3))
    d1 = rng.uniform(0.1, 0.9, (N, 3))
    d2 = rng.uniform(0.1, 0.9, (N, 3))
    w = rng.uniform(0.5, 2.0, N)
    kw = {}
    if arm == "partitioned_scoring":
        kw["energy"] = np.where(np.arange(N) % 2 == 0, 0.5, 1.5)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, d1.reshape(-1).copy(),
                         np.ones(N, np.int8), w, **kw)
    t.MoveToNextLocation(None, d2.reshape(-1).copy(),
                         np.ones(N, np.int8), w, **kw)


def collect(t, arm: str) -> dict:
    """Global results as host numpy — every array the bitwise parity
    contract covers (flux, positions, element ids, score bank)."""
    import numpy as np

    from pumiumtally_tpu.parallel.distributed import fetch_global

    out = {
        "flux": fetch_global(t.flux),
        "positions": np.asarray(t.positions),
        "elem_ids": np.asarray(t.elem_ids),
    }
    if arm == "partitioned_scoring":
        out["score_bank"] = fetch_global(t.score_bank)
    return out


# -- worker -----------------------------------------------------------------

def _looks_unavailable(exc: BaseException) -> bool:
    msg = str(exc)
    return ("Multiprocess computations aren't implemented" in msg
            or "gloo" in msg.lower())


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--arm", choices=_WORKER_MODES, required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--coord-port", type=int, required=True)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--out", default=None,
                    help="process 0: save the collected global "
                         "results (.npz) here")
    args = ap.parse_args(argv)

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags
        + f" --xla_force_host_platform_device_count="
          f"{args.devices_per_proc}"
    ).strip()
    os.environ.setdefault("JAX_ENABLE_X64", "true")

    import numpy as np

    from pumiumtally_tpu.parallel.distributed import (
        DistributedUnavailableError,
        UNAVAILABLE_EXIT_CODE,
        UNAVAILABLE_MARKER,
        assert_collectives_available,
        init_distributed,
    )

    try:
        mesh_dev = init_distributed(
            coordinator_address=f"127.0.0.1:{args.coord_port}",
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    except Exception as e:  # noqa: BLE001 — classified for the launcher
        # Startup failures (port race, peer never came up) get their
        # own marker + code so the launcher can retry the port instead
        # of mis-reading them as collective unavailability.
        print(f"{_INIT_FAILED_MARKER}: {type(e).__name__}: {e}",
              flush=True)
        raise SystemExit(3) from e
    nglobal = args.num_processes * args.devices_per_proc
    assert mesh_dev.devices.size == nglobal, mesh_dev
    print(f"proc {args.process_id}: devices={nglobal}", flush=True)

    try:
        assert_collectives_available(mesh_dev)
        if args.arm == "probe":
            # Capability probe only — no campaign. SystemExit is a
            # BaseException, so it sails past the handlers below.
            print(f"proc {args.process_id}: PROBE-OK", flush=True)
            import jax

            jax.distributed.shutdown()
            raise SystemExit(0)
        t = build_tally(args.arm, mesh_dev)
        t0 = time.perf_counter()
        run_campaign(t, args.arm)
        payload = collect(t, args.arm)  # the fetch fences the device
        dt = time.perf_counter() - t0
    except DistributedUnavailableError as e:
        print(str(e), flush=True)  # carries UNAVAILABLE_MARKER
        # NO jax.distributed.shutdown() here: the shutdown barrier
        # would wait on a peer already dead of the same error.
        raise SystemExit(UNAVAILABLE_EXIT_CODE) from e
    except Exception as e:  # noqa: BLE001 — backend classification
        if _looks_unavailable(e):
            print(f"{UNAVAILABLE_MARKER}: {e}", flush=True)
            raise SystemExit(UNAVAILABLE_EXIT_CODE) from e
        raise
    if args.process_id == 0 and args.out:
        np.savez(args.out, **payload)
    # Wall seconds over the fenced campaign (compiles included — the
    # worker runs cold), parsed by tools/exp_distributed_ab.py.
    print(f"proc {args.process_id}: campaign-seconds={dt:.6f}",
          flush=True)
    print(f"proc {args.process_id}: ARM-OK {args.arm}", flush=True)
    import jax

    jax.distributed.shutdown()
    raise SystemExit(0)


# -- launcher ---------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_timeout() -> float:
    """Worker-pair wait bound in seconds (default 280, under the slow
    tier's per-test budget). PUMIUMTALLY_SUBPROC_TIMEOUT overrides —
    the expiry message names it so the fix is discoverable."""
    raw = os.environ.get("PUMIUMTALLY_SUBPROC_TIMEOUT")
    if raw is None:
        return 280.0
    try:
        t = float(raw)
        if t <= 0:
            raise ValueError
    except ValueError:
        raise SystemExit(
            f"PUMIUMTALLY_SUBPROC_TIMEOUT={raw!r} is not a positive "
            "number of seconds"
        ) from None
    return t


class LaunchResult:
    def __init__(self, skipped: bool, reason: str, returncodes, outputs):
        self.skipped = skipped
        self.reason = reason
        self.returncodes = returncodes
        self.outputs = outputs


def _spawn(script_args, num_processes: int, port: int, timeout: float):
    """One worker set on one coordinator port. Returns (rcs, outs,
    timed_out_pids)."""
    procs, logs = [], []
    # The coordinator handshake gets its own bound well under the
    # subprocess wait, so a peer that never starts fails FAST with the
    # init marker instead of eating the whole budget.
    coord_timeout = max(15, int(timeout / 4))
    for pid in range(num_processes):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU tunnel claims
        env.pop("RUN_BOTH", None)
        env.setdefault("PUMIUMTALLY_COORD_TIMEOUT", str(coord_timeout))
        # Log files, not pipes: a worker blocked on a full pipe would
        # stall the collective and deadlock the pair.
        log = tempfile.TemporaryFile(mode="w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--num-processes", str(num_processes),
             "--process-id", str(pid),
             "--coord-port", str(port)] + script_args,
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
            text=True,
        ))
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs):
                break
            # A worker that already reported unavailable (or a startup
            # failure) decides the outcome: kill the peer now rather
            # than waiting out its collective/heartbeat timeout.
            if any(p.poll() is not None and p.returncode != 0
                   for p in procs):
                time.sleep(2.0)  # grace: let the peer exit on its own
                break
            time.sleep(0.2)
    finally:
        timed_out = [i for i, p in enumerate(procs) if p.poll() is None]
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    outs = []
    for log in logs:
        log.seek(0)
        outs.append(log.read())
        log.close()
    return [p.returncode for p in procs], outs, timed_out


def launch_distributed(arm: str, out_path=None, *, num_processes: int = 2,
                       devices_per_proc: int = 4, attempts: int = 3,
                       ) -> LaunchResult:
    from pumiumtally_tpu.parallel.distributed import (
        UNAVAILABLE_EXIT_CODE,
        UNAVAILABLE_MARKER,
    )

    timeout = _wait_timeout()
    script_args = ["--arm", arm,
                   "--devices-per-proc", str(devices_per_proc)]
    if out_path:
        script_args += ["--out", str(out_path)]
    for attempt in range(attempts):
        port = _free_port()
        rcs, outs, timed_out = _spawn(
            script_args, num_processes, port, timeout,
        )
        blob = "\n".join(outs)
        if (UNAVAILABLE_MARKER in blob
                or UNAVAILABLE_EXIT_CODE in rcs):
            reason = next(
                (ln for ln in blob.splitlines()
                 if UNAVAILABLE_MARKER in ln),
                f"{UNAVAILABLE_MARKER}: worker exited "
                f"{UNAVAILABLE_EXIT_CODE}",
            )
            return LaunchResult(True, reason, rcs, outs)
        init_failed = _INIT_FAILED_MARKER in blob
        port_race = any(pat in blob.lower()
                        for pat in _PORT_RETRY_PATTERNS)
        if init_failed and port_race and attempt + 1 < attempts:
            continue  # free-port retry: rebind on a fresh port
        if timed_out:
            raise AssertionError(
                f"distributed workers {timed_out} still running after "
                f"{timeout:g}s (PUMIUMTALLY_SUBPROC_TIMEOUT extends "
                f"the bound); outputs:\n{blob[-3000:]}"
            )
        return LaunchResult(False, "", rcs, outs)
    raise AssertionError(
        f"coordinator failed to bind in {attempts} port attempts; "
        f"last outputs:\n{blob[-3000:]}"
    )


_PROBE = None  # session-cached gloo probe verdict (LaunchResult)


def probe_collectives(*, num_processes: int = 2) -> LaunchResult:
    """Session-cached collectives-capability probe: ONE tiny worker
    pair (1 virtual device each) that inits jax.distributed and runs
    ``assert_collectives_available``, nothing else. A gloo-less CPU
    jaxlib fails this in seconds, so every subsequent cross-process
    test skips instantly instead of timing out its own campaign."""
    global _PROBE
    if _PROBE is None:
        _PROBE = launch_distributed(
            "probe", num_processes=num_processes, devices_per_proc=1,
        )
    return _PROBE


def launch_or_skip(arm: str, out_path=None, **kw) -> LaunchResult:
    """Launch the worker set; SKIP the calling test when the backend
    cannot run cross-process collectives, assert success otherwise.

    The skip reason is EXACTLY ``UNAVAILABLE_MARKER`` — details stay
    in the worker logs (``res.reason`` / outputs), the reason string
    stays a single greppable token. Outcomes land in RAN/SKIPPED for
    the session summary line."""
    import pytest

    from pumiumtally_tpu.parallel.distributed import UNAVAILABLE_MARKER

    probe = probe_collectives(
        num_processes=kw.get("num_processes", 2))
    if probe.skipped:
        SKIPPED.append(arm)
        pytest.skip(UNAVAILABLE_MARKER)
    res = launch_distributed(arm, out_path, **kw)
    if res.skipped:
        SKIPPED.append(arm)
        pytest.skip(UNAVAILABLE_MARKER)
    for pid, (rc, out) in enumerate(zip(res.returncodes, res.outputs)):
        assert rc == 0, f"proc {pid} rc={rc}:\n{out[-2000:]}"
    RAN.append(arm)
    return res


if __name__ == "__main__":
    main()
