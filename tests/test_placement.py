"""Topology-aware pod placement (round 19, docs/DESIGN.md
"Topology-aware placement").

``placement="pod_rcb"`` builds element-block ownership by hierarchical
RCB — hosts first (weighted by chips per host), then chips within each
host — so the migrate ring crosses host boundaries only where the mesh
geometry does. The contract pinned here:

- DEGENERACY: equal chips per host aligned with the flat power-of-two
  RCB tree reproduce the linear owner BITWISE (same splits in the same
  order), and default knobs never take the pod path at all — the
  default engine is byte-identical to HEAD.
- The modeled cross-host migration bytes (ring hops weighted by
  ``state_pack_columns`` row bytes) STRICTLY DROP on the pinned 2-host
  layout, for both the 1-block-per-chip and sub-split partitions.
- The cross-arm physics class: positions bitwise equal, every element
  id mismatch is a boundary TIE (bitwise-equal position, adjacent
  elements — crossing pause points land exactly on partition faces,
  the same attribution degeneracy the linear arm shows against the
  monolithic facade on these meshes), and total flux is conserved.
  Per-element flux on tied boundary tracks is attribution, not
  physics, and is deliberately NOT pinned across placements.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pumiumtally_tpu import (  # noqa: E402
    PartitionedPumiTally,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh  # noqa: E402
from pumiumtally_tpu.parallel.distributed import (  # noqa: E402
    derive_host_counts,
    modeled_cross_host_migration_bytes,
)
from pumiumtally_tpu.parallel.partition import (  # noqa: E402
    PLACEMENTS,
    build_partition,
)

FCOLS, ICOLS = 10, 9  # the 13-lane engine state (test_distributed.py)


# -- owner construction -----------------------------------------------------

def test_pod_rcb_equal_hosts_degenerates_to_linear_bitwise():
    """hosts=(4,4) on 8 blocks IS the flat RCB tree cut at depth 1 —
    the hierarchical build must reproduce the linear owner bitwise."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    p_lin = build_partition(mesh, 8)
    p_pod = build_partition(mesh, 8, placement="pod_rcb", hosts=[4, 4])
    np.testing.assert_array_equal(p_lin.owner, p_pod.owner)


def test_pod_rcb_unequal_hosts_changes_owner():
    mesh = build_box(1, 1, 1, 6, 6, 6)
    p_lin = build_partition(mesh, 8)
    p_pod = build_partition(mesh, 8, placement="pod_rcb", hosts=[3, 5])
    assert not np.array_equal(p_lin.owner, p_pod.owner)


def test_linear_placement_is_default_path():
    """placement="linear" + hosts is the DEFAULT owner bitwise (hosts
    describe the machine, not the strategy), and the default build
    records no remote-face census to pay for."""
    mesh = build_box(1, 1, 1, 5, 5, 5)
    p_default = build_partition(mesh, 8)
    p_lin = build_partition(mesh, 8, placement="linear", hosts=None)
    np.testing.assert_array_equal(p_default.owner, p_lin.owner)


def test_build_partition_rejects_unknown_placement():
    mesh = build_box(1, 1, 1, 3, 3, 3)
    with pytest.raises(ValueError, match="placement"):
        build_partition(mesh, 8, placement="hilbert")
    assert PLACEMENTS == ("linear", "pod_rcb")


# -- modeled cross-host bytes -----------------------------------------------

def test_pod_rcb_strictly_reduces_modeled_cross_host_bytes():
    """The pinned 2-host layout: 8 blocks over host chips (3,5) on the
    2x1x1 stretched box — pod RCB puts the host cut on one clean mesh
    layer while the linear order crosses hosts mid-geometry."""
    mesh = build_box(2, 1, 1, 8, 4, 4)
    hosts = (3, 5)
    p_lin = build_partition(mesh, 8)
    p_pod = build_partition(mesh, 8, placement="pod_rcb",
                            hosts=list(hosts))
    b_lin = modeled_cross_host_migration_bytes(
        p_lin.remote_faces, 1, hosts, FCOLS, ICOLS)
    b_pod = modeled_cross_host_migration_bytes(
        p_pod.remote_faces, 1, hosts, FCOLS, ICOLS)
    assert b_pod < b_lin, (b_lin, b_pod)


def test_pod_rcb_reduces_bytes_with_sub_split_blocks():
    """Sub-split partitions (blocks_per_chip=2): host boundaries fall
    between chip groups, and the drop still holds."""
    mesh = build_box(2, 1, 1, 8, 4, 4)
    hosts = (3, 5)
    bpc = 2
    p_lin = build_partition(mesh, 16)
    p_pod = build_partition(mesh, 16, placement="pod_rcb",
                            hosts=[h * bpc for h in hosts])
    b_lin = modeled_cross_host_migration_bytes(
        p_lin.remote_faces, bpc, hosts, FCOLS, ICOLS)
    b_pod = modeled_cross_host_migration_bytes(
        p_pod.remote_faces, bpc, hosts, FCOLS, ICOLS)
    assert b_pod < b_lin, (b_lin, b_pod)


def test_modeled_bytes_zero_on_single_host():
    mesh = build_box(1, 1, 1, 4, 4, 4)
    p = build_partition(mesh, 8)
    assert modeled_cross_host_migration_bytes(
        p.remote_faces, 1, (8,), FCOLS, ICOLS) == 0


# -- host-count derivation --------------------------------------------------

class _FakeDev:
    def __init__(self, pi):
        self.process_index = pi


def _fake_mesh(process_indices):
    import types

    devs = np.empty(len(process_indices), dtype=object)
    for i, pi in enumerate(process_indices):
        devs[i] = _FakeDev(pi)
    return types.SimpleNamespace(devices=devs)


def test_derive_host_counts_single_process():
    dm = make_device_mesh(8)
    assert derive_host_counts(dm) == (8,)


def test_derive_host_counts_contiguous_runs():
    assert derive_host_counts(_fake_mesh([0, 0, 0, 1, 1])) == (3, 2)


def test_derive_host_counts_rejects_interleaved():
    with pytest.raises(ValueError, match="interleaves"):
        derive_host_counts(_fake_mesh([0, 1, 0, 1]))


# -- config / engine validation ---------------------------------------------

def test_config_validates_placement_knobs():
    assert TallyConfig().placement == "linear"
    assert TallyConfig().placement_hosts is None
    with pytest.raises(ValueError, match="placement"):
        TallyConfig(placement="hilbert")
    with pytest.raises(ValueError, match="placement_hosts"):
        TallyConfig(placement_hosts=(3, 0))
    with pytest.raises(ValueError, match="placement_hosts"):
        TallyConfig(placement_hosts=())


def test_engine_rejects_hosts_not_summing_to_devices():
    mesh = build_box(1, 1, 1, 3, 3, 3)
    dm = make_device_mesh(8)
    with pytest.raises(ValueError, match="placement_hosts"):
        PartitionedPumiTally(
            mesh, 64,
            TallyConfig(device_mesh=dm, placement="pod_rcb",
                        placement_hosts=(3, 4)),
        )


# -- engine-level A/B: the pinned equivalence class -------------------------

def _campaign(N=2000, seed=3):
    rng = np.random.default_rng(seed)
    dims = np.array([2.0, 1.0, 1.0])
    src = rng.uniform(0.05, 0.95, (N, 3)) * dims
    d1 = np.clip(src + rng.normal(scale=0.3, size=(N, 3)) * dims,
                 0.01 * dims, 0.99 * dims)
    d2 = np.clip(d1 + rng.normal(scale=0.3, size=(N, 3)) * dims,
                 0.01 * dims, 0.99 * dims)
    fly = (rng.uniform(size=N) > 0.1).astype(np.int8)
    w = rng.uniform(0.5, 2.0, N)
    return src, d1, d2, fly, w


def test_engine_pod_rcb_parity_class_and_byte_drop():
    """Linear vs pod_rcb on the pinned 2-host layout, end to end:

    - modeled cross-host bytes strictly drop (the tentpole win);
    - positions are BITWISE equal;
    - every element-id mismatch is a boundary tie — bitwise-equal
      position, adjacent elements;
    - total flux is conserved across the placement change.
    """
    N = 2000
    mesh = build_box(2, 1, 1, 8, 4, 4)
    dm = make_device_mesh(8)
    src, d1, d2, fly, w = _campaign(N)

    def run(cfg):
        t = PartitionedPumiTally(mesh, N, cfg)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy(), fly.copy(), w)
        t.MoveToNextLocation(None, d2.reshape(-1).copy(),
                             np.ones(N, np.int8), w)
        return t

    lin = run(TallyConfig(device_mesh=dm, placement_hosts=(3, 5)))
    pod = run(TallyConfig(device_mesh=dm, placement="pod_rcb",
                          placement_hosts=(3, 5)))

    b_lin = lin.engine.modeled_cross_host_bytes()
    b_pod = pod.engine.modeled_cross_host_bytes()
    assert 0 < b_pod < b_lin, (b_lin, b_pod)

    pl = np.asarray(lin.positions).reshape(N, 3)
    pp = np.asarray(pod.positions).reshape(N, 3)
    np.testing.assert_array_equal(pl, pp)

    el, ep = np.asarray(lin.elem_ids), np.asarray(pod.elem_ids)
    adj = np.asarray(mesh.face_adj)
    for i in np.nonzero(el != ep)[0]:
        assert el[i] in adj[ep[i]] or ep[i] in adj[el[i]], (
            f"pid {i}: elements {el[i]} vs {ep[i]} differ but are not "
            "face-adjacent — not a boundary tie"
        )
    np.testing.assert_allclose(
        float(np.asarray(lin.flux).sum()),
        float(np.asarray(pod.flux).sum()), rtol=1e-12,
    )


def test_engine_default_knobs_single_host_diagnostic():
    """Default knobs: single-host derivation, zero modeled cross-host
    bytes, and the engine owner bitwise the default build (the
    byte-identical-to-HEAD guarantee)."""
    N = 500
    mesh = build_box(1, 1, 1, 4, 4, 4)
    dm = make_device_mesh(8)
    t = PartitionedPumiTally(mesh, N, TallyConfig(device_mesh=dm))
    assert t.engine.placement == "linear"
    assert tuple(t.engine.host_chips) == (8,)
    assert t.engine.modeled_cross_host_bytes() == 0
    np.testing.assert_array_equal(
        t.engine.part.owner, build_partition(mesh, 8).owner)
