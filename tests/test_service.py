"""The multi-session service layer (round 11).

Contracts pinned here (docs/DESIGN.md "Multi-session service"):

- determinism under concurrency: N >= 3 interleaved sessions of mixed
  facade kinds (scoring on one, sentinel on another), driven by
  concurrent client threads, each produce flux (and score banks, and
  health reports) BITWISE identical to running that campaign alone on
  a bare facade;
- a single-session service is bitwise- AND allocation-identical to
  the bare facade (the service layer allocates nothing on device);
- backpressure: a full session queue refuses with ServiceBusyError at
  submit, without corrupting any session's state — the refused op was
  never queued, and the campaign continues bitwise;
- submit-time validation: malformed moves raise argument-naming
  errors at submit (staging), never occupying a queue slot;
- scheduler: deficit round robin is fair, work-proportional, and
  work-conserving; an emptied queue forfeits banked credit;
- reads ride the session FIFO (a flux read observes exactly the moves
  submitted before it);
- the NDJSON socket front end round-trips a campaign bitwise;
- SIGTERM drains a server with >= 2 open sessions through the
  resilience dispatcher: exit 0, one batch-aligned generation per
  session, bitwise resume per session (subprocess,
  tests/_service_driver.py).
"""

import gc
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from pumiumtally_tpu import (
    EnergyFilter,
    PartitionedPumiTally,
    PumiTally,
    ScoringSpec,
    SentinelPolicy,
    ServiceBusyError,
    SessionClosedError,
    SessionState,
    StreamingTally,
    TallyConfig,
    TallyService,
    build_box,
)
from pumiumtally_tpu.service import (
    DeficitRoundRobinScheduler,
    ServiceDrainingError,
    SocketFrontend,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "_service_driver.py")

N = 192
BATCHES = 2
MOVES = 2


def _mesh():
    return build_box(1.0, 1.0, 1.0, 3, 3, 3)


def _campaign(seed, batches=BATCHES, moves=MOVES, n=N):
    rng = np.random.default_rng(seed)
    return [
        (rng.uniform(0.1, 0.9, (n, 3)),
         [rng.uniform(0.1, 0.9, (n, 3)) for _ in range(moves)],
         [rng.uniform(0.1, 1.9, n) for _ in range(moves)])
        for _ in range(batches)
    ]


def _drive_direct(t, work, with_energy=False):
    for src, dests, energies in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d, e in zip(dests, energies):
            kw = {"energy": e.copy()} if with_energy else {}
            t.MoveToNextLocation(None, d.reshape(-1).copy(), **kw)


def _submit_retry(fn, *args, **kw):
    """Client-side busy-retry: the documented reaction to
    ServiceBusyError (the op was never queued; state is clean)."""
    while True:
        try:
            return fn(*args, **kw)
        except ServiceBusyError:
            time.sleep(0.002)


def _drive_handle(h, work, with_energy=False, timeout=300):
    for src, dests, energies in work:
        futs = [_submit_retry(h.copy_initial_position,
                              src.reshape(-1).copy())]
        for d, e in zip(dests, energies):
            kw = {"energy": e.copy()} if with_energy else {}
            futs.append(_submit_retry(
                h.move, None, d.reshape(-1).copy(), **kw
            ))
        for f in futs:
            f.result(timeout=timeout)


# ---------------------------------------------------------------------------
# Scheduler (pure data structure)
# ---------------------------------------------------------------------------

class _Q:
    def __init__(self, costs):
        self.items = list(costs)

    def head(self):
        return self.items[0] if self.items else None

    def pop(self):
        return self.items.pop(0)


def _run_sched(sched, queues, picks):
    served = []
    for _ in range(picks):
        k = sched.pick(lambda key: queues[key].head())
        if k is None:
            break
        served.append((k, queues[k].pop()))
    return served


def test_drr_strict_alternation_equal_costs():
    """Equal-cost backlogged sessions serve in strict round robin —
    a hot session (100 queued) cannot starve a cold one (3 queued)."""
    sched = DeficitRoundRobinScheduler()
    sched.register("hot")
    sched.register("cold")
    queues = {"hot": _Q([1] * 100), "cold": _Q([1] * 3)}
    served = _run_sched(sched, queues, 8)
    kinds = [k for k, _ in served]
    assert kinds[:6] == ["hot", "cold", "hot", "cold", "hot", "cold"]
    # Work conservation: once cold empties, hot serves every pick.
    assert kinds[6:] == ["hot", "hot"]


def test_drr_work_proportional_unequal_costs():
    """With 10x cost difference, the cheap session serves ~10 ops per
    visit of the expensive one: served COST stays balanced within one
    quantum + one max op cost (the O(1) unfairness bound)."""
    sched = DeficitRoundRobinScheduler()
    sched.register("big")
    sched.register("small")
    queues = {"big": _Q([10] * 50), "small": _Q([1] * 500)}
    served = _run_sched(sched, queues, 110)
    cost = {"big": 0, "small": 0}
    for k, c in served:
        cost[k] += c
    assert abs(cost["big"] - cost["small"]) <= 10 + 10


def test_drr_deficit_resets_when_queue_empties():
    """Idle time banks no credit: a session that drained and refilled
    competes from zero, not from saved-up quantum."""
    sched = DeficitRoundRobinScheduler(quantum=1)
    sched.register("a")
    sched.register("b")
    queues = {"a": _Q([3]), "b": _Q([])}
    # b is visited while empty many times; its deficit must stay 0.
    _run_sched(sched, queues, 1)
    assert sched.deficit("b") == 0
    queues["b"].items = [3]
    queues["a"].items = []
    served = _run_sched(sched, queues, 1)
    # b needed 3 fresh visits of quantum 1 — but pick() loops rounds
    # internally, so one pick serves it; the point is the deficit
    # counter was not pre-loaded.
    assert served == [("b", 3)]
    assert sched.deficit("b") == 0


def test_drr_small_manual_quantum_jumps_not_spins():
    """quantum=1 with 100k-cost ops must serve in O(ring) work per
    pick (the deficit clock jumps arithmetically), not O(cost) spin
    passes under the service lock — and the accounting must match the
    one-pass-at-a-time semantics exactly."""
    sched = DeficitRoundRobinScheduler(quantum=1)
    sched.register("a")
    sched.register("b")
    queues = {"a": _Q([100_000, 100_000]), "b": _Q([50_000])}
    t0 = time.perf_counter()
    served = _run_sched(sched, queues, 3)
    assert time.perf_counter() - t0 < 1.0  # spin would take ~minutes
    # b needs 50k quanta, a needs 100k: b first, then a, then a.
    assert served == [("b", 50_000), ("a", 100_000), ("a", 100_000)]
    assert sched.deficit("b") == 0  # emptied: credit forfeited


def test_drr_register_unregister_and_validation():
    sched = DeficitRoundRobinScheduler()
    sched.register("a")
    with pytest.raises(ValueError):
        sched.register("a")
    sched.register("b")
    sched.unregister("a")
    assert sched.keys == ("b",)
    assert sched.pick(lambda k: None) is None
    with pytest.raises(ValueError):
        DeficitRoundRobinScheduler(quantum=0)


# ---------------------------------------------------------------------------
# Session lifecycle + backpressure
# ---------------------------------------------------------------------------

def test_backpressure_busy_without_corrupting_state():
    """Fill the bounded queue against a stopped worker: the (k+1)-th
    submit refuses with ServiceBusyError, nothing partial enters the
    pipeline, and after the worker starts the campaign completes —
    flux bitwise equal to the solo run of exactly the ACCEPTED ops
    plus the retried one."""
    mesh = _mesh()
    work = _campaign(7, batches=1)
    src, dests, _ = work[0]
    svc = TallyService(autostart=False)
    h = svc.open_session(PumiTally(mesh, N), max_queue=2)
    f1 = h.copy_initial_position(src.reshape(-1).copy())
    f2 = h.move(None, dests[0].reshape(-1).copy())
    flying = np.ones(N, np.int8)
    with pytest.raises(ServiceBusyError):
        h.move(None, dests[1].reshape(-1).copy(), flying=flying)
    # The refusal left the caller's buffers UNTOUCHED — in particular
    # the flying array was not zeroed (the protocol side effect fires
    # only on accept), so the retry below stages identical bytes.
    assert flying.sum() == N
    assert h.tally.iter_count == 0  # nothing executed, nothing corrupted
    svc.start()
    f1.result(timeout=300)
    f2.result(timeout=300)
    # The refused move retries cleanly once a slot frees — and the
    # accepted submit applies the protocol's zeroing side effect.
    _submit_retry(
        h.move, None, dests[1].reshape(-1).copy(), flying=flying
    ).result(timeout=300)
    assert flying.sum() == 0
    flux_s = h.flux().result(timeout=300)
    svc.shutdown(drain=False)

    t = PumiTally(mesh, N)
    _drive_direct(t, work)
    np.testing.assert_array_equal(flux_s, np.asarray(t.flux))


def test_submit_validation_raises_before_queueing():
    """Malformed moves refuse AT SUBMIT with the facades' own
    argument-naming errors — no queue slot consumed, no future
    created, session state untouched, and the campaign continues
    bitwise without them."""
    mesh = _mesh()
    work = _campaign(9, batches=1)
    src, dests, _ = work[0]
    with TallyService() as svc:
        h = svc.open_session(PumiTally(mesh, N), max_queue=4)
        h.copy_initial_position(src.reshape(-1).copy()).result(
            timeout=300
        )
        bad = dests[0].reshape(-1).copy()
        bad[5] = np.nan
        with pytest.raises(ValueError, match="destinations"):
            h.move(None, bad)
        with pytest.raises(ValueError, match="flying"):
            h.move(None, dests[0].reshape(-1).copy(),
                   flying=np.ones(3, np.int8))
        with pytest.raises(ValueError, match="energy"):
            # No scoring armed on this session: energy= must refuse.
            h.move(None, dests[0].reshape(-1).copy(),
                   energy=np.ones(N))
        assert h.pending == 0  # refused ops never occupied a slot
        for d in dests:
            h.move(None, d.reshape(-1).copy())
        flux_s = h.flux().result(timeout=300)
    t = PumiTally(mesh, N)
    _drive_direct(t, work)
    np.testing.assert_array_equal(flux_s, np.asarray(t.flux))


def test_execution_error_propagates_and_session_survives():
    """An op that fails at EXECUTION (not submit) carries its
    exception to exactly that client's future; the worker and every
    other queued op survive."""
    mesh = _mesh()
    work = _campaign(11, batches=1)
    src, dests, _ = work[0]
    with TallyService() as svc:
        h = svc.open_session(PumiTally(mesh, N), max_queue=4)
        h.copy_initial_position(src.reshape(-1).copy())
        bad = h.close_batch()  # no batch_stats on this facade
        good = h.move(None, dests[0].reshape(-1).copy())
        with pytest.raises(RuntimeError, match="batch statistics"):
            bad.result(timeout=300)
        good.result(timeout=300)
        flux_s = h.flux().result(timeout=300)
    t = PumiTally(mesh, N)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dests[0].reshape(-1).copy())
    np.testing.assert_array_equal(flux_s, np.asarray(t.flux))


def test_session_lifecycle_rejections():
    mesh = _mesh()
    with TallyService() as svc:
        h = svc.open_session(PumiTally(mesh, 16), max_queue=4)
        assert h.state is SessionState.OPEN
        first = h.close()
        # A repeated close is idempotent: it returns the SAME future
        # (a second sentinel could never execute once the first
        # unregisters the session — it would hang, not close).
        assert h.close() is first
        first.result(timeout=300)
        assert h.state is SessionState.CLOSED
        assert h.close() is first
        with pytest.raises(SessionClosedError):
            h.flux()
        assert svc.session_ids() == ()
    # A drained service refuses new sessions and new work.
    svc2 = TallyService()
    h2 = svc2.open_session(PumiTally(mesh, 16))
    svc2.request_drain()
    with pytest.raises(ServiceDrainingError):
        svc2.open_session(PumiTally(mesh, 16))
    with pytest.raises(ServiceDrainingError):
        h2.flux()
    svc2.shutdown(drain=False)
    with pytest.raises(ValueError):
        TallyService().open_session(PumiTally(mesh, 16), max_queue=0)


def test_auto_session_ids_skip_caller_claimed():
    """open_session(session_id="s1") then open_session() with no id:
    the generator must skip the caller-claimed id instead of refusing
    the caller who passed nothing."""
    mesh = _mesh()
    with TallyService() as svc:
        h1 = svc.open_session(PumiTally(mesh, 16), session_id="s1")
        h2 = svc.open_session(PumiTally(mesh, 16))
        assert h2.id != h1.id
        assert set(svc.session_ids()) == {h1.id, h2.id}
        h1.close().result(timeout=300)
        h2.close().result(timeout=300)


def test_reads_ride_the_session_fifo():
    """A flux read submitted between moves observes exactly the moves
    before it — FIFO consistency, not eventual consistency."""
    mesh = _mesh()
    work = _campaign(13, batches=1)
    src, dests, _ = work[0]
    with TallyService() as svc:
        h = svc.open_session(PumiTally(mesh, N), max_queue=8)
        h.copy_initial_position(src.reshape(-1).copy())
        h.move(None, dests[0].reshape(-1).copy())
        mid = h.flux()
        h.move(None, dests[1].reshape(-1).copy())
        end = h.flux()
        mid_flux = mid.result(timeout=300)
        end_flux = end.result(timeout=300)
    t = PumiTally(mesh, N)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dests[0].reshape(-1).copy())
    np.testing.assert_array_equal(mid_flux, np.asarray(t.flux))
    t.MoveToNextLocation(None, dests[1].reshape(-1).copy())
    np.testing.assert_array_equal(end_flux, np.asarray(t.flux))


# ---------------------------------------------------------------------------
# Determinism under concurrency (the round-11 acceptance contract)
# ---------------------------------------------------------------------------

def _session_zoo(mesh):
    """Three sessions of mixed facade kinds: sentinel on the
    monolithic one, scoring on the streaming one, a partitioned
    third."""
    spec = ScoringSpec(filters=[EnergyFilter(np.array([0.0, 1.0, 2.0]))],
                       scores=["flux", "events"])
    return {
        "mono_sentinel": PumiTally(
            mesh, N,
            TallyConfig(check_found_all=False, sentinel=SentinelPolicy()),
        ),
        "stream_scoring": StreamingTally(
            mesh, N, chunk_size=128,
            config=TallyConfig(check_found_all=False, scoring=spec),
        ),
        "part": PartitionedPumiTally(
            mesh, N,
            TallyConfig(check_found_all=False, capacity_factor=4.0),
        ),
    }


_SEEDS = {"mono_sentinel": 21, "stream_scoring": 22, "part": 23}


def test_interleaved_sessions_bitwise_vs_solo():
    """THE determinism-under-concurrency pin: three concurrent client
    threads drive three sessions of mixed facade kinds through one
    service; every session's flux — and the scoring session's lane
    bank, and the sentinel session's health record — is BITWISE the
    solo run of the same campaign on a bare facade."""
    mesh = _mesh()
    results = {}
    with TallyService() as svc:
        handles = {
            kind: svc.open_session(t, session_id=kind, max_queue=2)
            for kind, t in _session_zoo(mesh).items()
        }

        errors = []

        def client(kind):
            try:
                h = handles[kind]
                _drive_handle(h, _campaign(_SEEDS[kind]),
                              with_energy=(kind == "stream_scoring"))
                out = {"flux": h.flux().result(timeout=300)}
                if kind == "stream_scoring":
                    out["bank"] = h.score_bank().result(timeout=300)
                if kind == "mono_sentinel":
                    out["health"] = (
                        h.health_report().result(timeout=300).as_dict()
                    )
                results[kind] = out
            except Exception as e:  # noqa: BLE001 — surface in-main
                errors.append((kind, e))

        threads = [
            threading.Thread(target=client, args=(kind,))
            for kind in handles
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not errors, errors

    for kind, solo in _session_zoo(mesh).items():
        _drive_direct(solo, _campaign(_SEEDS[kind]),
                      with_energy=(kind == "stream_scoring"))
        np.testing.assert_array_equal(
            results[kind]["flux"], np.asarray(solo.flux), err_msg=kind,
        )
        if kind == "stream_scoring":
            np.testing.assert_array_equal(
                results[kind]["bank"], np.asarray(solo.score_bank),
            )
        if kind == "mono_sentinel":
            assert results[kind]["health"] == (
                solo.health_report().as_dict()
            )


def test_single_session_bitwise_and_allocation_identical():
    """A 1-session service is indistinguishable from the bare facade:
    same flux/positions/elements BITWISE, and the SAME number of live
    device arrays afterwards — the service layer stages host-side
    numpy only and allocates nothing on device."""
    mesh = _mesh()
    work = _campaign(31)

    # Warm every jit cache + global constant once, so neither measured
    # run pays one-time allocations the other would not.
    warm = PumiTally(mesh, N)
    _drive_direct(warm, work)
    del warm
    gc.collect()
    base = len(jax.live_arrays())

    t_direct = PumiTally(mesh, N)
    _drive_direct(t_direct, work)
    flux_d = np.asarray(t_direct.flux)
    gc.collect()
    direct_delta = len(jax.live_arrays()) - base

    svc = TallyService()
    t_served = PumiTally(mesh, N)
    h = svc.open_session(t_served)
    _drive_handle(h, work)
    flux_s = h.flux().result(timeout=300)
    pos_s = h.tally.positions
    elem_s = h.tally.elem_ids
    svc.shutdown(drain=False)
    del svc, h
    gc.collect()
    service_delta = len(jax.live_arrays()) - base - direct_delta

    np.testing.assert_array_equal(flux_s, flux_d)
    np.testing.assert_array_equal(pos_s, t_direct.positions)
    np.testing.assert_array_equal(elem_s, t_direct.elem_ids)
    assert service_delta == direct_delta


# ---------------------------------------------------------------------------
# Socket front end
# ---------------------------------------------------------------------------

def _rpc(f, obj):
    f.write(json.dumps(obj).encode() + b"\n")
    f.flush()
    return json.loads(f.readline())


def test_socket_frontend_roundtrip():
    """A remote driver over the NDJSON socket gets the same bitwise
    flux as a direct facade — including the pipelined (wait=false +
    sync) path — and malformed requests answer structured errors
    instead of dropping the connection."""
    import base64
    import socket as socketlib

    mesh = _mesh()
    work = _campaign(41, batches=1)
    src, dests, _ = work[0]

    def b64(a):
        return base64.b64encode(
            np.asarray(a, "<f8").tobytes()
        ).decode()

    svc = TallyService()
    fe = SocketFrontend(svc, default_mesh=mesh, default_particles=N)
    fe.start()
    try:
        with socketlib.create_connection((fe.host, fe.port)) as conn:
            f = conn.makefile("rwb")
            r = _rpc(f, {"op": "ping"})
            assert r["ok"] is True and r["draining"] is False
            # Round-20 telemetry rides the ping (schema pinned in
            # tests/test_traffic.py): load aggregate + fusion stats.
            assert r["load"]["sessions"] == 0
            assert r["load"]["queued_cost"] == 0
            assert set(r["fusion"]) == {
                "fused_groups", "fused_moves", "solo_moves",
                "solo_other",
            }
            r = _rpc(f, {"op": "open", "facade": "mono",
                         "num_particles": N, "max_queue": 8})
            assert r["ok"], r
            sid = r["session"]
            assert _rpc(f, {"op": "source", "session": sid,
                            "positions": b64(src.reshape(-1))})["ok"]
            r = _rpc(f, {"op": "move", "session": sid,
                         "dests": b64(dests[0].reshape(-1)),
                         "wait": False})
            assert r == {"ok": True, "queued": True}
            assert _rpc(f, {"op": "move", "session": sid,
                            "dests": b64(dests[1].reshape(-1))})["ok"]
            assert _rpc(f, {"op": "sync", "session": sid})["ok"]
            # Structured errors, connection survives:
            r = _rpc(f, {"op": "nonsense"})
            assert r["ok"] is False and "unknown op" in r["message"]
            r = _rpc(f, {"op": "move", "session": sid, "dests": None})
            assert r["ok"] is False  # bad payload type: still answered
            r = _rpc(f, {"op": "flux", "session": "nope"})
            assert r["ok"] is False and r["error"] == "KeyError"
            r = _rpc(f, {"op": "write", "session": sid,
                         "filename": "x.vtk"})
            assert r["ok"] is False  # allow_write off by default
            r = _rpc(f, {"op": "flux", "session": sid})
            flux_s = np.frombuffer(
                base64.b64decode(r["flux"]), "<f8"
            )
            assert _rpc(f, {"op": "close", "session": sid})["ok"]
    finally:
        fe.stop()
        svc.shutdown(drain=False)

    t = PumiTally(mesh, N)
    _drive_direct(t, work)
    np.testing.assert_array_equal(flux_s,
                                  np.asarray(t.flux, np.float64))


def test_socket_sync_reports_every_pipelined_failure():
    """sync must consume the whole waitlist and surface EVERY failure
    in its one error reply — raising at the first would clear (and so
    silently discard) any later pipelined failure, and a driver that
    fixed only the named op would then get a clean second sync while
    flux is missing a move."""
    from concurrent.futures import Future

    svc = TallyService(autostart=False)
    fe = SocketFrontend(svc)
    try:
        fa, ok, fb = Future(), Future(), Future()
        fa.set_exception(ValueError("bad move A"))
        ok.set_result(None)
        fb.set_exception(RuntimeError("bad move B"))
        waitlist, dropped = [fa, ok, fb], {}
        with pytest.raises(RuntimeError) as ei:
            fe._sync(waitlist, dropped, "s")
        assert "bad move A" in str(ei.value)
        assert "bad move B" in str(ei.value)
        assert waitlist == []  # consumed, not leaked into the next sync
        # A single failure propagates as itself (typed error reply).
        only = Future()
        only.set_exception(ValueError("only failure"))
        waitlist = [only]
        with pytest.raises(ValueError, match="only failure"):
            fe._sync(waitlist, dropped, "s")
        assert waitlist == []
        # Retention cap: a pipeline-forever driver whose ops fail
        # persistently must not grow the waitlist O(ops) — the oldest
        # resolved failures are dropped, counted, and reported.
        waitlist, dropped = [], {}
        for i in range(fe._MAX_RETAINED_FAILURES + 10):
            fut = Future()
            fut.set_exception(ValueError(f"fail {i}"))
            fe._ack(fut, waitlist, dropped, "s", {"wait": False})
        assert len(waitlist) == fe._MAX_RETAINED_FAILURES
        assert dropped["s"] == 10
        with pytest.raises(RuntimeError) as ei:
            fe._sync(waitlist, dropped, "s")
        assert "+10 earlier failures dropped" in str(ei.value)
        assert dropped == {} and waitlist == []
    finally:
        fe.stop()
        svc.shutdown(drain=False)


def test_queued_future_refuses_cancel_and_worker_survives():
    """A client's fut.cancel() on a still-queued op must not land: a
    CANCELLED future would make the worker's set_result raise
    InvalidStateError, killing the one thread that drains every
    session. Cancellation is refused (as the Future contract allows),
    the op still runs (a campaign is exactly its submission sequence),
    and the service keeps serving."""
    svc = TallyService(autostart=False)  # queue while no worker runs
    try:
        h = svc.open_session(PumiTally(_mesh(), N), max_queue=8)
        src, dests, _ = _campaign(17, batches=1)[0]
        f_src = h.copy_initial_position(src.reshape(-1).copy())
        assert f_src.cancel() is False  # refused while queued
        f_move = h.move(None, dests[0].reshape(-1).copy())
        assert f_move.cancel() is False
        svc.start()
        f_move.result(timeout=300)  # resolves normally, not cancelled
        # The worker survived the "cancelled" ops: further work runs.
        flux = h.flux().result(timeout=300)
        assert np.isfinite(flux).all() and flux.sum() > 0
        h.close().result(timeout=300)
    finally:
        svc.shutdown(drain=False)


def test_socket_checkpoint_dir_collision_refused(tmp_path):
    """Two socket sessions sharing one checkpoint_dir would share one
    GenerationStore — keep-pruning then deletes the OTHER session's
    generations and the drain promise (one generation per session)
    silently collapses. The second open must refuse with a structured
    error; closing a session releases its directory for reuse."""
    import socket as socketlib

    svc = TallyService()
    fe = SocketFrontend(svc, default_mesh=_mesh(), default_particles=16)
    fe.start()
    try:
        with socketlib.create_connection((fe.host, fe.port)) as conn:
            f = conn.makefile("rwb")

            def open_ck(d):
                return _rpc(f, {"op": "open", "facade": "mono",
                                "num_particles": 16,
                                "checkpoint_dir": str(d)})

            r1 = open_ck(tmp_path / "ck")
            assert r1["ok"], r1
            r2 = open_ck(tmp_path / "ck")
            assert r2["ok"] is False and "already in use" in r2["message"]
            assert open_ck(tmp_path / "ck2")["ok"]  # distinct dir fine
            assert _rpc(f, {"op": "close", "session": r1["session"]})["ok"]
            assert open_ck(tmp_path / "ck")["ok"]  # released on close
    finally:
        fe.stop()
        svc.shutdown(drain=False)


def test_socket_failed_close_still_cleans_up(tmp_path):
    """A close whose drain checkpoint fails (dir swapped for a file)
    must still drop the wire bookkeeping and the checkpoint-dir
    reservation: the error reply carries the real failure, a retry
    gets an honest unknown-session error instead of the cached
    failure forever, and the directory is reusable."""
    import shutil
    import socket as socketlib

    svc = TallyService()
    fe = SocketFrontend(svc, default_mesh=_mesh(), default_particles=16)
    fe.start()
    try:
        with socketlib.create_connection((fe.host, fe.port)) as conn:
            f = conn.makefile("rwb")
            ck = tmp_path / "ck"
            r = _rpc(f, {"op": "open", "facade": "mono",
                         "num_particles": 16,
                         "checkpoint_dir": str(ck)})
            assert r["ok"], r
            sid = r["session"]
            if ck.exists():
                shutil.rmtree(ck)
            ck.write_text("not a directory")
            r_close = _rpc(f, {"op": "close", "session": sid})
            assert r_close["ok"] is False
            # Retry: the session is genuinely gone, not a cached error.
            r_retry = _rpc(f, {"op": "close", "session": sid})
            assert r_retry["ok"] is False and r_retry["error"] == "KeyError"
            # Reservation released: the (repaired) dir is reusable.
            ck.unlink()
            r2 = _rpc(f, {"op": "open", "facade": "mono",
                          "num_particles": 16,
                          "checkpoint_dir": str(ck)})
            assert r2["ok"], r2
            assert _rpc(f, {"op": "close", "session": r2["session"]})["ok"]
    finally:
        fe.stop()
        svc.shutdown(drain=False)


def test_socket_disconnect_closes_orphaned_sessions():
    """A remote client that vanishes without sending close must not
    leak its sessions (facade device arrays) into the server forever —
    the connection teardown drain-closes them."""
    import socket as socketlib

    svc = TallyService()
    fe = SocketFrontend(svc, default_mesh=_mesh(), default_particles=16)
    fe.start()
    try:
        with socketlib.create_connection((fe.host, fe.port)) as conn:
            f = conn.makefile("rwb")
            r = _rpc(f, {"op": "open", "facade": "mono",
                         "num_particles": 16})
            assert r["ok"] and svc.session_ids() == (r["session"],)
            # makefile() holds its own reference to the fd — close it
            # too, or the "vanished" client never actually sends FIN.
            f.close()
        deadline = time.monotonic() + 60
        while svc.session_ids() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert svc.session_ids() == ()
    finally:
        fe.stop()
        svc.shutdown(drain=False)


# ---------------------------------------------------------------------------
# SIGTERM drain + bitwise resume (subprocess, the satellite gate)
# ---------------------------------------------------------------------------

def _run_service_driver(ckpt_dir, out_dir, *extra, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PUMIUMTALLY_FAULT", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    return subprocess.run(
        [sys.executable, DRIVER, "--ckpt-dir", str(ckpt_dir),
         "--out-dir", str(out_dir), *extra],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
        env=env,
    )


def test_service_drain_sigterm_and_bitwise_resume(tmp_path):
    """SIGTERM against a server with two open sessions (mono +
    streaming, each autosave-armed): the process exits 0, every
    session leaves one BATCH-ALIGNED generation (iter_count a multiple
    of the per-batch move count), and a resumed server finishes each
    session's campaign to flux BITWISE equal to the uninterrupted
    run."""
    from tests._service_driver import MOVES as DRV_MOVES
    from tests._service_driver import SESSIONS

    # Uninterrupted reference.
    r = _run_service_driver(tmp_path / "ck_base", tmp_path / "out_base")
    assert r.returncode == 0, r.stderr
    base = {
        s: np.load(tmp_path / "out_base" / f"{s}.npy") for s in SESSIONS
    }

    # Drain after batch 1: exit 0, no outputs, one extra generation
    # per session (the drain save) beyond the per-batch autosaves.
    r = _run_service_driver(tmp_path / "ck_drain", tmp_path / "out_drain",
                            "--sigterm-after-batch", "1")
    assert r.returncode == 0, r.stderr
    assert not (tmp_path / "out_drain").exists()
    drained = json.loads(
        [ln for ln in r.stdout.splitlines() if ln.startswith("{")][-1]
    )["drained"]
    assert set(drained) == set(SESSIONS)
    assert all(gen is not None for gen in drained.values())

    # Resume: each session reports a batch-aligned restore point and
    # lands bitwise on the reference flux.
    r = _run_service_driver(tmp_path / "ck_drain", tmp_path / "out_drain",
                            "--resume")
    assert r.returncode == 0, r.stderr
    for s in SESSIONS:
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith(f"resumed session {s} ")][0]
        iter_count = int(line.rsplit("iter_count ", 1)[1].rstrip(")"))
        assert iter_count % DRV_MOVES == 0  # batch-aligned
        assert iter_count == 2 * DRV_MOVES  # drained after batch 1
        np.testing.assert_array_equal(
            np.load(tmp_path / "out_drain" / f"{s}.npy"), base[s],
            err_msg=f"{s}: resume arm",
        )


# ---------------------------------------------------------------------------
# CLI serve verb
# ---------------------------------------------------------------------------

def test_cli_serve_roundtrip_and_sigterm_exit(tmp_path):
    """``pumiumtally serve`` binds, serves one socket session (box
    mesh from the open request), and exits 0 on SIGTERM."""
    import base64
    import socket as socketlib

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "pumiumtally_tpu.cli", "serve",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=str(tmp_path), env=env,
    )
    try:
        line = proc.stdout.readline()
        addr = json.loads(line)["serving"]
        n = 32
        rng = np.random.default_rng(3)
        src = rng.uniform(0.1, 0.9, (n, 3))
        dst = rng.uniform(0.1, 0.9, (n, 3))

        def b64(a):
            return base64.b64encode(
                np.asarray(a, "<f8").tobytes()
            ).decode()

        with socketlib.create_connection(
            (addr["host"], addr["port"]), timeout=300
        ) as conn:
            f = conn.makefile("rwb")
            r = _rpc(f, {"op": "open", "facade": "mono",
                         "num_particles": n, "max_queue": 8,
                         "mesh": {"box": [1, 1, 1, 2, 2, 2]}})
            assert r["ok"], r
            sid = r["session"]
            assert _rpc(f, {"op": "source", "session": sid,
                            "positions": b64(src.reshape(-1))})["ok"]
            assert _rpc(f, {"op": "move", "session": sid,
                            "dests": b64(dst.reshape(-1))})["ok"]
            r = _rpc(f, {"op": "flux", "session": sid})
            assert r["ok"]
            flux = np.frombuffer(base64.b64decode(r["flux"]), "<f8")
            assert flux.shape == (6 * 2 * 2 * 2,) and flux.sum() > 0
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        assert rc == 0, proc.stderr.read()[-2000:]
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
