"""Shared test constants (a plain module: importing conftest.py as a
module would re-run its environment side effects under a second name).
"""

import numpy as np

# Per-axis asymmetric clip bounds for cross-engine comparison tests:
# clipping with EQUAL bounds parks many destinations exactly on the box
# meshes' diagonal tet faces (two coords equal), where the containing
# element is genuinely ambiguous and engines may tie-break differently;
# these bounds sit on no grid plane or diagonal of any mesh used in the
# suite.
CLIP_LO = np.array([0.0213, 0.0227, 0.0241])
CLIP_HI = np.array([0.9787, 0.9773, 0.9759])
