"""Seeded collective-safety bugs (JL101-JL104). Parsed by jaxlint in
tests/test_jaxlint.py, never executed. Line pins live in that test —
keep the two in sync when editing."""

import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P


def undeclared_axis(mesh, x):
    # JL101: psum over "data", but the specs only declare "dp".
    def body(x):
        return lax.psum(x, "data")

    return shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")
    )(x)


def broken_ring(mesh, x):
    # JL102: destination 2 appears twice / 3 never — not a permutation.
    def body(x):
        return lax.ppermute(
            x, "dp", perm=[(0, 1), (1, 2), (2, 2), (3, 0)]
        )

    return shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")
    )(x)


def unsummed_scalar(mesh, x):
    # JL103: per-shard reduction returned through a replicated P()
    # out_spec without a psum — each shard reports a DIFFERENT total.
    def body(x):
        shard_total = jnp.sum(x)
        return x, shard_total

    return shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=(P("dp"), P())
    )(x)


def divergent_branch(mesh, x):
    # JL104: the cond predicate is shard-local, and the taken branch
    # contains a collective — shards can disagree about entering the
    # psum and deadlock.
    def body(x):
        local_mean = jnp.mean(x)

        def with_collective(v):
            return lax.psum(v, "dp")

        return lax.cond(
            local_mean > 0.0, with_collective, lambda v: v, x
        )

    return shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")
    )(x)


def clean_reference(mesh, x):
    # Negative control: psum'd before the replicated return — no
    # finding. Keeps the corpus honest about false positives.
    def body(x):
        total = lax.psum(jnp.sum(x), "dp")
        return x, total

    return shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=(P("dp"), P())
    )(x)


__all__ = [
    "undeclared_axis",
    "broken_ring",
    "unsummed_scalar",
    "divergent_branch",
    "clean_reference",
]
