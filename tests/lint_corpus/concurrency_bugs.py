"""Seeded host-concurrency bugs (JL301-JL303). Parsed by jaxlint in
tests/test_jaxlint.py, never executed. Line pins live in that test —
keep the two in sync when editing.

The class below is named ``SocketFrontend`` so that the THREAD_ROOTS
registry in ``pumiumtally_tpu/analysis/concurrency.py`` recognizes its
accept-loop / connection / client entry points; JL301 only analyzes
registered classes.
"""

import threading


class SocketFrontend:
    # JL301 target: `served` is written by the accept thread AND by
    # client calls, and the accept-thread write takes no lock.
    def __init__(self):
        self._lock = threading.Lock()
        self.served = 0

    def _accept_loop(self):
        while True:
            self._serve_conn()
            self.served += 1

    def _serve_conn(self):
        pass

    def reset_stats(self):
        with self._lock:
            self.served = 0


class OrderedLocks:
    # JL302 target: ab() takes _a then _b, ba() takes _b then _a —
    # a classic ordering cycle that deadlocks under contention.
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.state = 0

    def ab(self):
        with self._a:
            with self._b:
                self.state += 1

    def ba(self):
        with self._b:
            with self._a:
                self.state -= 1


class BlockingHolder:
    # JL303 target: an unbounded Future.result() while holding the
    # lock every producer needs to make progress.
    def __init__(self, pool):
        self._lock = threading.Lock()
        self._pool = pool
        self.last = None

    def flush(self, job):
        with self._lock:
            fut = self._pool.submit(job)
            self.last = fut.result()

    def flush_bounded(self, job):
        # Negative control: a timeout bounds the wait — no finding.
        with self._lock:
            fut = self._pool.submit(job)
            self.last = fut.result(timeout=5.0)
