"""Seeded Pallas-kernel bugs (JL201-JL204). Parsed by jaxlint in
tests/test_jaxlint.py, never executed. Line pins live in that test —
keep the two in sync when editing."""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE = 16384  # 4x the vmem_walk feasibility model's element budget


def oversized_block(x):
    # JL201: one f32 input block + one f32 output block of TILE*32
    # elements each blows past VMEM_BLOCK_BUDGET_BYTES.
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((TILE, 32), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((TILE, 32), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((4 * TILE, 32), jnp.float32),
    )(x)


def input_ref_write(x):
    # JL202: writes the INPUT ref (silently dropped on TPU) and reads
    # the output ref before ever writing it (garbage VMEM).
    def kernel(x_ref, o_ref):
        x_ref[0] = 0.0
        acc = o_ref[...]
        o_ref[...] = acc + x_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)


def ragged_grid(x):
    # JL203: out_shape dim 500 is not divisible by the block dim 128.
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((500,), jnp.float32),
    )(x)


def chatty_kernel(x):
    # JL204: host call inside the kernel body — traces once at lower
    # time (misleading) and is unsupported in the compiled kernel.
    def kernel(x_ref, o_ref):
        print("block", x_ref.shape)
        o_ref[...] = x_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)


def clean_reference(x):
    # Negative control: small block, write-before-read on the output
    # ref, divisible dims, no host calls — no finding.
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0
        o_ref[...] = o_ref[...] + 1.0

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)
