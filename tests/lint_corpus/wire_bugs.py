"""Seeded wire-protocol drift for the --wire auditor. Never executed;
tests/test_jaxlint.py installs this file AS tools/loadgen.py inside a
doctored tree (next to the real service/server.py) and pins the exact
(kind, line) findings audit_wire() must report. Unlike the other
corpus files this is encoder drift, not a lint rule: the audit, not
the per-file analyzer, is what must catch it.
"""


def _rpc(f, req):
    return {}


def run(f, sid):
    # UNKNOWN-OP target: "fluxx" is not on the server allowlist.
    r = _rpc(f, {"op": "fluxx", "session": sid})
    # MISSING-FIELD target: source requires "positions".
    _rpc(f, {"op": "source", "session": sid})
    # MISSING-FIELD target: move requires "dests" (augmented keys
    # count — "wait" rides along but does not satisfy it).
    req = {"op": "move", "session": sid}
    req["wait"] = False
    _rpc(f, req)
    r2 = _rpc(f, {"op": "flux", "session": sid})
    # REPLY-DRIFT target: the flux reply carries "flux", not "fluxes".
    return r.get("ok"), r2["fluxes"]
