"""Seeded determinism bugs (JL501-JL503). Parsed by jaxlint in
tests/test_jaxlint.py, never executed. Line pins live in that test —
keep the two in sync when editing.
"""

import jax
import jax.numpy as jnp
import numpy as np


def broadcast_order(active_sessions, replies):
    # JL501 target: set iteration order varies run-to-run, and
    # .append is an order-sensitive sink (wire reply order).
    for sid in set(active_sessions):
        replies.append(sid)
    return replies


def checkpoint_rows(keys):
    # JL501 target: list() materializes the set in hash order.
    return list({k for k in keys})


def commit_quicksort(acc, bins, w):
    # JL502 target: numpy default quicksort reorders equal bins, and
    # this function commits through a segmented .at[].add.
    order = np.argsort(bins)
    return acc.at[bins[order]].add(w[order])


def commit_forced_unstable(acc, seg, w):
    # JL502 target: jnp.argsort is stable by default, but this site
    # explicitly opts OUT on a segment_sum path.
    order = jnp.argsort(seg, stable=False)
    return acc + jax.ops.segment_sum(w[order], seg[order], 8)


def host_total(flux):
    # JL503 target: builtin sum() left-folds the fetched values in
    # host order — a different rounding association than the device
    # reduction the parity gates pin.
    return sum(jax.device_get(flux).tolist())
