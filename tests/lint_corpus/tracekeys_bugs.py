"""Seeded trace-cardinality bugs (JL401, JL404). Parsed by jaxlint in
tests/test_jaxlint.py, never executed. Line pins live in that test —
keep the two in sync when editing.

The registrations reuse REAL budget names ("walk" = 3, "locate" = 2 in
config.RETRACE_BUDGETS) so the cardinality prover folds the seeded
knob domains against the live table; JL402/JL403 are audit-side
(--trace-keys over a doctored tree) and have no corpus lines here.
"""

import jax

from pumiumtally_tpu.utils.profiling import register_entry_point


def _step(state, mode, order):
    return state


def _locate_impl(state, n):
    return state


# JL401 target: the two static knobs below enumerate 3 x 4 = 12
# possible cache keys, but RETRACE_BUDGETS["walk"] allows 3.
_walk = register_entry_point(
    "walk", jax.jit(_step, static_argnames=("mode", "order"))
)

_locate = register_entry_point(
    "locate", jax.jit(_locate_impl, static_argnames=("n",))
)


def drive(state):
    for mode in ("fast", "exact", "paranoid"):
        for order in (1, 2, 3, 4):
            state = _walk(state, mode=mode, order=order)
    return state


def serve(batch, state):
    # JL404 target: a per-call batch size reaching the static key
    # position `n` — one compile per distinct len(batch).
    return _locate(state, n=len(batch))
