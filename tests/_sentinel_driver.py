"""Subprocess arms for the sentinel/overflow-recovery suite
(tests/test_sentinel.py) — run as ``python _sentinel_driver.py <arm>
<workdir>`` so recovery, safety saves, and the poisoned refusal are
pinned across a REAL process boundary (the acceptance contract:
overflow recovery + safety save survive outside the test process's
jax/session state).

Arms:
  recover — a capacity-overflow workload (all particles concentrated
            into one part with ~1/4 of the needed slots) that raised
            RuntimeError at HEAD~ must now complete through the
            recovery ladder; prints one JSON line with the flux sum,
            recovery counters, and a parity flux from a generously
            provisioned engine.
  poison  — the same workload with the capacity escalation disabled
            (monkeypatched to a no-op): the ladder must exhaust,
            write an overflow_safety checkpoint generation through
            the armed CheckpointPolicy, latch the poisoned flag, and
            every subsequent facade call must refuse; prints one JSON
            line describing the refusal.
"""

import json
import sys

import numpy as np


def _workload():
    import jax

    jax.config.update("jax_enable_x64", True)
    from pumiumtally_tpu import build_box

    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4)
    n = 40
    rng = np.random.default_rng(7)
    src = rng.uniform(0.1, 0.9, (n, 3))
    corner = rng.uniform(0.02, 0.10, (n, 3))
    return mesh, n, src, corner


def _tally(mesh, n, capacity_factor, ckpt_dir=None):
    from pumiumtally_tpu import (
        CheckpointPolicy,
        PartitionedPumiTally,
        SentinelPolicy,
        TallyConfig,
    )

    cfg = TallyConfig(
        check_found_all=False,
        capacity_factor=capacity_factor,
        walk_vmem_max_elems=100,
        walk_block_kernel="gather",
        sentinel=SentinelPolicy(),
        checkpoint=(
            None if ckpt_dir is None else CheckpointPolicy(
                dir=ckpt_dir, every_n_batches=None, handle_signals=False,
            )
        ),
    )
    return PartitionedPumiTally(mesh, n, cfg)


def arm_recover(workdir: str) -> None:
    mesh, n, src, corner = _workload()
    big = _tally(mesh, n, 9.0)
    big.CopyInitialPosition(src.reshape(-1).copy())
    big.MoveToNextLocation(None, corner.reshape(-1).copy())

    t = _tally(mesh, n, 1.05, ckpt_dir=workdir)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, corner.reshape(-1).copy())
    rep = t.health_report()
    print(json.dumps({
        "arm": "recover",
        "flux_sum": float(np.asarray(t.flux).sum()),
        "flux_bitwise_vs_big": bool(
            np.array_equal(np.asarray(t.flux), np.asarray(big.flux))
        ),
        "overflow_recoveries": rep.overflow_recoveries,
        "capacity_escalations": rep.capacity_escalations,
        "poisoned": t.engine.poisoned,
    }))


def arm_poison(workdir: str) -> None:
    from pumiumtally_tpu.resilience import GenerationStore
    from pumiumtally_tpu.sentinel import EnginePoisonedError

    mesh, n, src, corner = _workload()
    t = _tally(mesh, n, 1.05, ckpt_dir=workdir)
    # Disable the ladder's escalation rungs: the overflow then has no
    # cure and must exhaust into the poisoned refusal.
    t.engine._escalate_capacity = lambda *a, **k: None
    try:
        # Either the (near-capacity) localization or the concentrating
        # move exhausts the cureless ladder — both end poisoned.
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, corner.reshape(-1).copy())
        raise SystemExit("expected the ladder to exhaust")
    except RuntimeError as e:
        ladder_msg = str(e)
    try:
        t.MoveToNextLocation(None, corner.reshape(-1).copy())
        raise SystemExit("expected the poisoned refusal")
    except EnginePoisonedError as e:
        refusal_msg = str(e)
    store = GenerationStore(workdir)
    gens = store.generations()
    reasons = []
    for _gen, path in gens:
        _payload, _g, meta = store.read_generation(path)
        reasons.append(meta.get("reason"))
    print(json.dumps({
        "arm": "poison",
        "poisoned": t.engine.poisoned,
        "ladder_msg_has_poisoned": "poisoned" in ladder_msg,
        "refusal_msg_has_resume": "resume from checkpoint" in refusal_msg,
        "generations": len(gens),
        "save_reasons": reasons,
    }))


def main() -> None:
    arm, workdir = sys.argv[1], sys.argv[2]
    {"recover": arm_recover, "poison": arm_poison}[arm](workdir)


if __name__ == "__main__":
    main()
