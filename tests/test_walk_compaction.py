"""Compaction cascade ≡ plain lock-step walk.

The cascade (ops/walk.py) is a pure performance transform: sorting
survivors to the front and shrinking the processed window must not
change any per-particle result or the accumulated flux (up to FP
summation order in the scatter-add).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from pumiumtally_tpu import build_box
from pumiumtally_tpu.ops.walk import walk

# Sized for coverage-per-second: the cascade properties are
# size-independent, but each extra halving stage lengthens the unrolled
# jit program (= compile time, the bulk of this file's cost). 2048 ->
# windows 2048/1024/512/256: four stages, multi-stage coverage intact.
N = 2048
DIV = 6  # 1296 tets


def _setup(seed=0):
    mesh = build_box(1.0, 1.0, 1.0, DIV, DIV, DIV)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(np.tile(np.mean(
        np.asarray(mesh.coords)[np.asarray(mesh.tet2vert)[0]], axis=0), (N, 1)))
    elem = jnp.zeros((N,), jnp.int32)
    # localize to random interior points first
    src = jnp.asarray(rng.uniform(0.05, 0.95, (N, 3)))
    r = walk(mesh, x, elem, src, jnp.ones((N,), jnp.int8),
             jnp.zeros((N,)), jnp.zeros((mesh.nelems,)),
             tally=False, tol=1e-12, max_iters=4096, compact=False)
    assert bool(jnp.all(r.done))
    # heterogeneous moves: some long (exit domain), some short, some held
    dest = jnp.asarray(src + rng.normal(scale=0.2, size=(N, 3)))
    fly = jnp.asarray((rng.uniform(size=N) > 0.1).astype(np.int8))
    dest = jnp.where(fly[:, None] == 1, dest, r.x)
    w = jnp.asarray(rng.uniform(0.5, 2.0, N))
    return mesh, r.x, r.elem, dest, fly, w


@pytest.mark.slow
def test_cascade_matches_plain_walk():
    mesh, x, elem, dest, fly, w = _setup()
    flux0 = jnp.zeros((mesh.nelems,))
    a = walk(mesh, x, elem, dest, fly, w, flux0,
             tally=True, tol=1e-12, max_iters=4096, compact=False)
    b = walk(mesh, x, elem, dest, fly, w, flux0,
             tally=True, tol=1e-12, max_iters=4096,
             compact=True, min_window=256)
    assert bool(jnp.all(a.done)) and bool(jnp.all(b.done))
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(b.elem))
    np.testing.assert_array_equal(np.asarray(a.exited), np.asarray(b.exited))
    np.testing.assert_allclose(np.asarray(a.x), np.asarray(b.x), atol=1e-14)
    # flux differs only by FP summation order
    np.testing.assert_allclose(
        np.asarray(a.flux), np.asarray(b.flux), rtol=1e-12, atol=1e-12
    )
    assert float(jnp.sum(b.flux)) > 0


def test_cascade_matches_plain_walk_under_shard_map():
    """The production sharded path runs the cascade inside shard_map for
    shards > min_window; pin that the shard_map-sensitive ops (argsort,
    windowed .at[].set, the iota carry) stay valid there."""
    import jax
    from jax.sharding import PartitionSpec as P
    from functools import partial

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

    from pumiumtally_tpu.parallel import make_device_mesh
    from pumiumtally_tpu.parallel.sharded import shard_map_check_kwargs

    mesh, x, elem, dest, fly, w = _setup()
    dev_mesh = make_device_mesh(8)
    flux0 = jnp.zeros((mesh.nelems,))

    @jax.jit
    @partial(
        shard_map,
        mesh=dev_mesh,
        in_specs=(P(), P("dp"), P("dp"), P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P()),
        **shard_map_check_kwargs(),
    )
    def sharded_cascade(mesh_, x_, elem_, dest_, fly_, w_):
        from jax import lax

        from pumiumtally_tpu.parallel.sharded import _pvary

        zero_flux = _pvary(jnp.zeros((mesh_.volumes.shape[0],), x_.dtype), "dp")
        r = walk(mesh_, x_, elem_, dest_, fly_, w_, zero_flux,
                 tally=True, tol=1e-12, max_iters=4096,
                 compact=True, min_window=64)
        return r.x, r.elem, lax.psum(r.flux, "dp")

    xb, eb, fb = sharded_cascade(mesh, x, elem, dest, fly, w)

    a = walk(mesh, x, elem, dest, fly, w, flux0,
             tally=True, tol=1e-12, max_iters=4096, compact=False)
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(eb))
    np.testing.assert_allclose(np.asarray(a.x), np.asarray(xb), atol=1e-14)
    np.testing.assert_allclose(np.asarray(a.flux), np.asarray(fb),
                               rtol=1e-12, atol=1e-12)


@pytest.mark.slow
def test_cascade_respects_max_iter_budget():
    mesh, x, elem, dest, fly, w = _setup(seed=1)
    flux0 = jnp.zeros((mesh.nelems,))
    r = walk(mesh, x, elem, dest, fly, w, flux0,
             tally=True, tol=1e-12, max_iters=3,
             compact=True, min_window=256, cond_every=1)
    # budget exhausted → some particles unfinished, reported not-done
    assert not bool(jnp.all(r.done))
    assert int(r.iters) <= 3

    # With cond_every=k the budget may overshoot by at most k-1 masked
    # iterations per stage exit (documented in walk()); never more.
    rk = walk(mesh, x, elem, dest, fly, w, flux0,
              tally=True, tol=1e-12, max_iters=3,
              compact=True, min_window=256, cond_every=4)
    assert not bool(jnp.all(rk.done))
    assert int(rk.iters) <= 3 + 3


@pytest.mark.slow
def test_cond_every_k_is_exact():
    """k-unrolled cond evaluation: per-particle results are bitwise
    identical (the s-parametrized step math is window-independent);
    flux matches to summation order (a stage may retire contributions
    in different iteration groups, reordering the f64 adds)."""
    from pumiumtally_tpu.api.tally import _localize_step

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 3000
    rng = np.random.default_rng(12)
    src = jnp.asarray(rng.uniform(0.05, 0.95, (n, 3)))
    dest = jnp.asarray(rng.uniform(-0.1, 1.1, (n, 3)))
    elem = jnp.zeros((n,), jnp.int32)
    # Localize from the tet-0 centroid (walk's 'x inside elem'
    # precondition) and insist it converged.
    c0 = jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0)
    x, elem, done, _ = _localize_step(
        mesh, jnp.broadcast_to(c0, (n, 3)), elem, src, tol=1e-8,
        max_iters=2000,
    )
    assert bool(jnp.all(done))
    fly = jnp.ones((n,), jnp.int8)
    w = jnp.asarray(rng.uniform(0.5, 2.0, n))
    flux0 = jnp.zeros((mesh.nelems,))
    outs = [
        walk(mesh, x, elem, dest, fly, w, flux0, tally=True, tol=1e-8,
             max_iters=2000, min_window=256, cond_every=k)
        for k in (1, 3)
    ]
    np.testing.assert_allclose(np.asarray(outs[0].flux),
                               np.asarray(outs[1].flux),
                               rtol=1e-13, atol=1e-14)
    np.testing.assert_array_equal(np.asarray(outs[0].elem),
                                  np.asarray(outs[1].elem))
    np.testing.assert_array_equal(np.asarray(outs[0].x),
                                  np.asarray(outs[1].x))


@pytest.mark.slow
def test_perm_modes_bitwise_identical():
    """The three stage-boundary permutation strategies ("arrays",
    "packed", "indirect" — ops/walk.py _PERM_MODES) are implementation
    details of the SAME computation: identical values gathered/permuted
    through different layouts, identical scatter order. Results must be
    BITWISE equal, flux included. Slow tier: the three-mode sweep pays
    three full jit compiles; the fast tier still covers each mode's
    correctness through the autotune and walk-kw tests."""
    mesh, x, elem, dest, fly, w = _setup(seed=7)
    flux0 = jnp.zeros((mesh.nelems,))
    outs = {
        mode: walk(mesh, x, elem, dest, fly, w, flux0,
                   tally=True, tol=1e-12, max_iters=4096,
                   compact=True, min_window=256, perm_mode=mode)
        for mode in ("arrays", "packed", "indirect")
    }
    a = outs["arrays"]
    assert bool(jnp.all(a.done))
    for mode in ("packed", "indirect"):
        b = outs[mode]
        np.testing.assert_array_equal(np.asarray(a.x), np.asarray(b.x))
        np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(b.elem))
        np.testing.assert_array_equal(np.asarray(a.done), np.asarray(b.done))
        np.testing.assert_array_equal(
            np.asarray(a.exited), np.asarray(b.exited))
        np.testing.assert_array_equal(np.asarray(a.flux), np.asarray(b.flux))


def test_window_factor_matches_halving():
    """A coarser cascade (window_factor=4) changes stage boundaries but
    not per-particle results; flux agrees up to scatter-order FP."""
    mesh, x, elem, dest, fly, w = _setup(seed=8)
    flux0 = jnp.zeros((mesh.nelems,))
    a = walk(mesh, x, elem, dest, fly, w, flux0, tally=True, tol=1e-12,
             max_iters=4096, min_window=256, window_factor=2)
    b = walk(mesh, x, elem, dest, fly, w, flux0, tally=True, tol=1e-12,
             max_iters=4096, min_window=256, window_factor=4)
    np.testing.assert_array_equal(np.asarray(a.x), np.asarray(b.x))
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(b.elem))
    np.testing.assert_allclose(
        np.asarray(a.flux), np.asarray(b.flux), rtol=1e-12, atol=1e-12
    )
