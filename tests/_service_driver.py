"""Subprocess service campaign driver for the SIGTERM-drain tests.

Runs one deterministic two-session campaign through the multi-session
service (``TallyService`` with the process-wide drain handler), so
tests/test_service.py can drain it mid-campaign and relaunch it with
``--resume``:

    python tests/_service_driver.py --ckpt-dir /tmp/ck --out-dir /tmp/o \
        [--sigterm-after-batch K] [--resume]

Two sessions of different facade kinds (mono + streaming) — or, with
``--mono-pair``, two CO-FUSABLE monolithic sessions sharing one mesh,
so the campaign's moves coalesce into shared launches (round 12)
before any drain lands — each with its OWN autosave store under
``<ckpt-dir>/<session>``. The campaign is
B source batches x M moves per session, all inputs derived from
per-session seeded rngs — every process (fresh, drained, resumed)
computes identical trajectories and indexes into them by each
session's restored ``iter_count``, so a resumed run re-drives exactly
the batches the drained one had not finished (the
tests/_resilience_driver.py recipe, per session).

``--sigterm-after-batch K`` raises SIGTERM against this process right
after batch K completes in both sessions — the deterministic stand-in
for an external preemption notice. The service's drain dispatch sets
the flag; the loop observes it at the next batch boundary, so
``shutdown(drain=True)`` writes one BATCH-ALIGNED generation per
session (iter_count a multiple of M) and the process exits 0 without
writing campaign outputs. Not collected by pytest; runnable
standalone.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCHES = 4
MOVES = 2
N = 64
MESH_ARGS = (1, 1, 1, 3, 3, 3)
SESSIONS = ("mono", "stream")  # session ids double as facade kinds
# --mono-pair: two monolithic sessions SHARING one mesh — the
# co-fusable pair the round-12 drain test runs, so the campaign's
# moves actually coalesce into shared launches before the SIGTERM
# lands (ids still prefix-encode the facade kind).
MONO_PAIR_SESSIONS = ("monoA", "monoB")
# --stream-pair: two co-fusable STREAMING sessions — the round-20
# chunk-wise fusion drain arm; with --priorities/--admission-budget
# the drain lands while priority lanes and the admission gate are
# live (drain under load).
STREAM_PAIR_SESSIONS = ("streamA", "streamB")
SEEDS = {"mono": 101, "stream": 202, "monoA": 303, "monoB": 404,
         "streamA": 505, "streamB": 606}
QUEUE_DEPTH = MOVES + 1  # one batch fits the queue: source + M moves

_MESH = None  # one mesh per process: co-fusion keys on mesh identity


def _mesh():
    global _MESH
    if _MESH is None:
        from pumiumtally_tpu import build_box

        _MESH = build_box(*MESH_ARGS)
    return _MESH


def build_tally(kind, ckpt_dir):
    from pumiumtally_tpu import (
        CheckpointPolicy,
        PumiTally,
        StreamingTally,
        TallyConfig,
    )

    policy = CheckpointPolicy(
        dir=os.path.join(ckpt_dir, kind), every_n_batches=1, keep=5,
        handle_signals=False,  # the SERVICE owns the drain handler
    )
    cfg = TallyConfig(checkpoint=policy, check_found_all=False)
    if kind.startswith("mono"):
        return PumiTally(_mesh(), N, cfg)
    return StreamingTally(_mesh(), N, chunk_size=40, config=cfg)


def trajectory(kind):
    import numpy as np

    rng = np.random.default_rng(SEEDS[kind])
    src = rng.uniform(0.1, 0.9, (BATCHES, N, 3))
    dst = rng.uniform(0.1, 0.9, (BATCHES, MOVES, N, 3))
    return src, dst


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--out-dir", required=True)
    p.add_argument("--sigterm-after-batch", type=int, default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--mono-pair", action="store_true",
                   help="two co-fusable monolithic sessions instead of "
                        "the mono+stream mix (the round-12 fusion drain "
                        "arm)")
    p.add_argument("--stream-pair", action="store_true",
                   help="two co-fusable STREAMING sessions (the "
                        "round-20 chunk-wise fusion drain arm)")
    p.add_argument("--priorities", default=None,
                   help="comma-separated lane per session, e.g. "
                        "'high,low' (default: all normal)")
    p.add_argument("--admission-budget", type=int, default=None,
                   help="service admission budget in cost units; the "
                        "driver retries overload refusals, so the "
                        "drain lands while the gate is live")
    args = p.parse_args()
    if args.mono_pair and args.stream_pair:
        raise SystemExit("--mono-pair and --stream-pair are exclusive")
    sessions = (MONO_PAIR_SESSIONS if args.mono_pair
                else STREAM_PAIR_SESSIONS if args.stream_pair
                else SESSIONS)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_ENABLE_X64", "true")

    import time

    import numpy as np

    from pumiumtally_tpu import TallyService, resume_latest
    from pumiumtally_tpu.service import (
        Priority,
        ServiceDrainingError,
        ServiceOverloadedError,
    )

    lanes = {}
    if args.priorities is not None:
        names = args.priorities.split(",")
        if len(names) != len(sessions):
            raise SystemExit(
                f"--priorities needs {len(sessions)} lanes, got "
                f"{args.priorities!r}"
            )
        lanes = {k: Priority[n.strip().upper()]
                 for k, n in zip(sessions, names)}

    def submit_admitted(fn, *a, **kw):
        """Retry overload refusals: the admission gate refuses without
        touching state, so blind resubmission is correct — exactly
        what a well-behaved client does under a full budget."""
        while True:
            try:
                return fn(*a, **kw)
            except ServiceOverloadedError:
                time.sleep(0.005)

    svc = TallyService(handle_signals=True,
                       admission_budget=args.admission_budget)
    handles = {}
    start_batch = {}
    done_moves = {}
    for kind in sessions:
        t = build_tally(kind, args.ckpt_dir)
        sb = dm = 0
        if args.resume:
            info = resume_latest(t)
            if info is not None:
                sb, dm = divmod(t.iter_count, MOVES)
                print(
                    f"resumed session {kind} generation "
                    f"{info.generation} at batch {sb} "
                    f"(iter_count {t.iter_count})"
                )
        handles[kind] = submit_admitted(
            svc.open_session, t, session_id=kind, max_queue=QUEUE_DEPTH,
            priority=lanes.get(kind, Priority.NORMAL),
        )
        start_batch[kind], done_moves[kind] = sb, dm

    first = min(start_batch.values())
    for b in range(first, BATCHES):
        if svc.drain_requested:
            break
        futs = []
        try:
            for kind in sessions:
                if b < start_batch[kind]:
                    continue  # this session resumed further along
                src, dst = trajectory(kind)
                skip = done_moves[kind] if b == start_batch[kind] else 0
                h = handles[kind]
                if skip == 0:
                    # A mid-batch restore already localized this
                    # batch's sources (same rule as the resilience
                    # driver).
                    futs.append(submit_admitted(
                        h.copy_initial_position,
                        src[b].reshape(-1).copy(),
                    ))
                for m in range(skip, MOVES):
                    futs.append(submit_admitted(
                        h.move, None, dst[b, m].reshape(-1).copy()
                    ))
        except ServiceDrainingError:
            pass  # an external SIGTERM landed mid-batch: drain below
        for f in futs:
            f.result(timeout=300)
        print(f"batch {b} done", flush=True)
        if args.sigterm_after_batch is not None and (
            b == args.sigterm_after_batch
        ):
            os.kill(os.getpid(), signal.SIGTERM)

    if svc.drain_requested:
        saved = svc.shutdown(drain=True)
        print(json.dumps({
            "drained": {
                sid: (None if gen is None else gen[0])
                for sid, gen in saved.items()
            },
            "fusion": svc.fusion_stats,
        }), flush=True)
        raise SystemExit(0)

    os.makedirs(args.out_dir, exist_ok=True)
    for kind in sessions:
        flux = handles[kind].flux().result(timeout=300)
        np.save(os.path.join(args.out_dir, f"{kind}.npy"),
                np.asarray(flux, np.float64))
    print(json.dumps({"fusion": svc.fusion_stats}), flush=True)
    svc.shutdown(drain=False)


if __name__ == "__main__":
    main()
