"""BASELINE config-2 scale proof (slow tier): the ~1M-tet assembly
lattice builds, walks, and conserves track length — monolithic and
partitioned over the 8-virtual-device mesh.

Particle counts are small (CPU backend); the on-chip rate rows for
this geometry come from tools/exp_r4_scale.py via the measurement
suite. Element ids at this scale still fit f32 exactly (< 2^24), so
the packed walk table is in play — the unpacked-fallback semantics
are pinned separately by test_box_mesh.py's forced-fallback parity
test.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.pincell import build_lattice

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def lattice_1m():
    mesh, mat, cell = build_lattice(
        10, 10, n_theta=24, n_rings_fuel=4, n_rings_pad=4, nz=10
    )
    assert mesh.nelems > 1_000_000
    return mesh


def test_lattice_1m_geometry(lattice_1m):
    mesh = lattice_1m
    pitch, height = 1.26, 1.0
    np.testing.assert_allclose(
        float(np.asarray(mesh.volumes, np.float64).sum()),
        10 * 10 * pitch * pitch * height, rtol=1e-9,
    )


def test_lattice_1m_walk_conservation(lattice_1m):
    mesh = lattice_1m
    n = 20_000
    rng = np.random.default_rng(42)
    box = np.array([10 * 1.26, 10 * 1.26, 1.0])
    src = rng.uniform(0.02, 0.98, (n, 3)) * box
    # Short steps: segment length bounds the crossing count per move.
    dest = np.clip(src + rng.normal(scale=0.05, size=(n, 3)),
                   0.01 * box, 0.99 * box)
    t = PumiTally(mesh, n, TallyConfig(check_found_all=False))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dest.reshape(-1).copy())
    got = float(np.float64(jnp.sum(t.flux)))
    want = float(np.linalg.norm(dest - src, axis=1).sum())
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_lattice_1m_partitioned(lattice_1m):
    from pumiumtally_tpu import PartitionedPumiTally
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = lattice_1m
    n = 1_000
    rng = np.random.default_rng(43)
    box = np.array([10 * 1.26, 10 * 1.26, 1.0])
    src = rng.uniform(0.05, 0.95, (n, 3)) * box
    dest = np.clip(src + rng.normal(scale=0.05, size=(n, 3)),
                   0.01 * box, 0.99 * box)
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(device_mesh=make_device_mesh(8), capacity_factor=8.0,
                    check_found_all=False),
    )
    assert t.engine.part.L >= mesh.nelems // 8
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dest.reshape(-1).copy())
    got = float(np.asarray(t.flux, np.float64).sum())
    want = float(np.linalg.norm(dest - src, axis=1).sum())
    np.testing.assert_allclose(got, want, rtol=1e-9)
