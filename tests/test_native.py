"""C ABI tests: build libpumiumtally_c.so, drive it via ctypes, and run
the pure-C++ demo host end-to-end.

The ctypes path loads the shared library into this (Python) process —
exercising the attach-to-existing-interpreter branch — while the demo
binary embeds its own interpreter the way a physics host app (the
OpenMC --ohMesh fork, reference README.md:84-104) would.
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(ROOT, "native")
SO = os.path.join(NATIVE, "libpumiumtally_c.so")


def _write_box_msh(path):
    """Unit-cube 6-tet mesh as Gmsh v2.2 ASCII."""
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    with open(path, "w") as f:
        f.write("$MeshFormat\n2.2 0 8\n$EndMeshFormat\n$Nodes\n")
        f.write(f"{len(coords)}\n")
        for i, (x, y, z) in enumerate(coords, 1):
            f.write(f"{i} {x} {y} {z}\n")
        f.write("$EndNodes\n$Elements\n")
        f.write(f"{len(tets)}\n")
        for i, t in enumerate(tets, 1):
            f.write(f"{i} 4 2 0 1 {t[0]+1} {t[1]+1} {t[2]+1} {t[3]+1}\n")
        f.write("$EndElements\n")


@pytest.fixture(scope="module")
def native_lib():
    r = subprocess.run(
        ["make", "-C", NATIVE, "-s", f"PY={sys.executable}"],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        pytest.skip(f"native build unavailable: {r.stderr[-500:]}")
    lib = ctypes.CDLL(SO)
    lib.pumiumtally_create.restype = ctypes.c_void_p
    lib.pumiumtally_create.argtypes = [ctypes.c_char_p, ctypes.c_int32]
    lib.pumiumtally_copy_initial_position.restype = ctypes.c_int
    lib.pumiumtally_copy_initial_position.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int32]
    lib.pumiumtally_move_to_next_location.restype = ctypes.c_int
    lib.pumiumtally_move_to_next_location.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int8),
        ctypes.POINTER(ctypes.c_double), ctypes.c_int32]
    lib.pumiumtally_write_tally_results.restype = ctypes.c_int
    lib.pumiumtally_write_tally_results.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p]
    lib.pumiumtally_get_flux.restype = ctypes.c_int64
    lib.pumiumtally_get_flux.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
    lib.pumiumtally_move_continue.restype = ctypes.c_int
    lib.pumiumtally_move_continue.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_int8), ctypes.POINTER(ctypes.c_double),
        ctypes.c_int32]
    lib.pumiumtally_get_positions.restype = ctypes.c_int64
    lib.pumiumtally_get_positions.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
    lib.pumiumtally_get_elem_ids.restype = ctypes.c_int64
    lib.pumiumtally_get_elem_ids.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.pumiumtally_destroy.restype = None
    lib.pumiumtally_destroy.argtypes = [ctypes.c_void_p]
    return lib


def _dp(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def test_c_abi_oracle_sequence(native_lib, tmp_path):
    lib = native_lib
    msh = str(tmp_path / "box.msh")
    _write_box_msh(msh)
    n = 5
    h = lib.pumiumtally_create(msh.encode(), n)
    assert h, "create failed"
    try:
        init = np.tile([0.1, 0.4, 0.5], (n, 1)).reshape(-1)
        rc = lib.pumiumtally_copy_initial_position(h, _dp(init), 3 * n)
        assert rc == 0

        dests = np.tile([1.2, 0.4, 0.5], (n, 1)).reshape(-1)
        flying = np.ones(n, dtype=np.int8)
        weights = np.ones(n, dtype=np.float64)
        rc = lib.pumiumtally_move_to_next_location(
            h, _dp(init), _dp(dests),
            flying.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
            _dp(weights), 3 * n,
        )
        assert rc == 0
        # in-place zeroing crossed the C boundary
        np.testing.assert_array_equal(flying, np.zeros(n, dtype=np.int8))

        ne = lib.pumiumtally_get_flux(h, None, 0)
        assert ne == 6
        flux = np.zeros(ne, dtype=np.float64)
        lib.pumiumtally_get_flux(h, _dp(flux), ne)
        np.testing.assert_allclose(
            flux, [0.0, 0.0, 0.3 * n, 0.1 * n, 0.5 * n, 0.0], atol=1e-8
        )

        out = str(tmp_path / "flux.vtk")
        rc = lib.pumiumtally_write_tally_results(h, out.encode())
        assert rc == 0
        assert os.path.getsize(out) > 0
    finally:
        lib.pumiumtally_destroy(h)


def test_c_host_oracle_binary(native_lib, tmp_path):
    """Oracle-grade pure-C end-to-end (VERDICT r5 item 4): a C host
    binary (native/test_host.c) drives the 6-tet cube through the .so
    with the reference's exact 5-particle trajectories and asserts
    flux[2,3,4] = 1.5/0.5/2.5 plus the move-2 increments to 1e-8,
    exiting nonzero on any mismatch. The --corrupt run perturbs one
    expectation and must FAIL — proof the harness's assertions are
    live, not a vacuous rc==0."""
    r = subprocess.run(
        ["make", "-C", NATIVE, "-s", "test_host", f"PY={sys.executable}"],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        pytest.skip(f"test_host build failed: {r.stderr[-500:]}")
    msh = str(tmp_path / "box.msh")
    _write_box_msh(msh)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # don't claim the TPU tunnel
    binary = os.path.join(NATIVE, "test_host")
    r = subprocess.run([binary, msh], capture_output=True, text=True,
                       env=env, timeout=300)
    assert r.returncode == 0, f"oracle host failed:\n{r.stdout}\n{r.stderr}"
    assert "test_host OK" in r.stdout
    # Negative control: a corrupted expectation must exit nonzero.
    r = subprocess.run([binary, msh, "--corrupt"], capture_output=True,
                       text=True, env=env, timeout=300)
    assert r.returncode != 0
    assert "MISMATCH" in r.stderr


def test_c_abi_continue_and_accessors(native_lib, tmp_path):
    """Continue-mode move (NULL flying/weights) + state accessors."""
    lib = native_lib
    msh = str(tmp_path / "box.msh")
    _write_box_msh(msh)
    n = 4
    h = lib.pumiumtally_create(msh.encode(), n)
    assert h
    try:
        init = np.tile([0.2, 0.4, 0.5], (n, 1)).reshape(-1)
        assert lib.pumiumtally_copy_initial_position(h, _dp(init), 3 * n) == 0

        dests = np.tile([0.4, 0.4, 0.5], (n, 1)).reshape(-1)
        nullp8 = ctypes.POINTER(ctypes.c_int8)()
        nullpd = ctypes.POINTER(ctypes.c_double)()
        rc = lib.pumiumtally_move_continue(h, _dp(dests), nullp8, nullpd, 3 * n)
        assert rc == 0

        pos = np.zeros(3 * n)
        assert lib.pumiumtally_get_positions(h, _dp(pos), 3 * n) == 3 * n
        np.testing.assert_allclose(pos, dests, atol=1e-8)
        eids = np.zeros(n, dtype=np.int32)
        got = lib.pumiumtally_get_elem_ids(
            h, eids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n
        )
        assert got == n
        np.testing.assert_array_equal(eids, np.full(n, 2))
        flux = np.zeros(6)
        lib.pumiumtally_get_flux(h, _dp(flux), 6)
        np.testing.assert_allclose(flux.sum(), 0.2 * n, atol=1e-8)
    finally:
        lib.pumiumtally_destroy(h)


@pytest.mark.slow
def test_cpp_demo_host(native_lib, tmp_path):
    # Slow tier: boots a whole embedded interpreter (~17 s); the C ABI
    # itself stays covered fast via the ctypes tests above. CI's
    # native job runs both tiers of this file explicitly (test.yml),
    # so the embedded path keeps a job that pre-builds native/.
    """Full embedding path: a pure-C++ binary hosts the engine."""
    r = subprocess.run(
        ["make", "-C", NATIVE, "-s", "demo", f"PY={sys.executable}"],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        pytest.skip(f"demo build failed: {r.stderr[-500:]}")
    msh = str(tmp_path / "box.msh")
    _write_box_msh(msh)
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # don't contend for the TPU tunnel
    r = subprocess.run(
        [os.path.join(NATIVE, "demo"), msh, "200"],
        capture_output=True, text=True, cwd=str(tmp_path), env=env,
        timeout=300,
    )
    assert r.returncode == 0, f"demo failed:\n{r.stdout}\n{r.stderr}"
    assert "demo OK" in r.stdout
    assert os.path.exists(str(tmp_path / "demo_fluxresult.vtk"))


def test_c_abi_echo_protocol_dedup(native_lib, tmp_path):
    """Reference-style host loop over the C ABI: origins echo the
    previous destinations every move (in the SAME recycled buffers a
    C host would reuse); the engine's auto_continue dedup must keep
    conservation exact across the boundary."""
    lib = native_lib
    msh = str(tmp_path / "box.msh")
    _write_box_msh(msh)
    n = 64
    h = lib.pumiumtally_create(msh.encode(), n)
    assert h
    try:
        rng = np.random.default_rng(17)
        origins = rng.uniform(0.1, 0.9, (n, 3)).reshape(-1)
        rc = lib.pumiumtally_copy_initial_position(h, _dp(origins), 3 * n)
        assert rc == 0
        expect = 0.0
        obuf = origins.copy()
        dbuf = np.empty(3 * n)
        for _ in range(4):
            dests = rng.uniform(0.1, 0.9, (n, 3)).reshape(-1)
            dbuf[:] = dests
            flying = np.ones(n, np.int8)
            weights = np.ones(n)
            rc = lib.pumiumtally_move_to_next_location(
                h, _dp(obuf), _dp(dbuf),
                flying.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
                _dp(weights), 3 * n,
            )
            assert rc == 0
            expect += float(np.linalg.norm(
                (dests - obuf).reshape(n, 3), axis=1).sum())
            obuf[:] = dests  # echo: recycled origin buffer
        flux = np.zeros(6)
        lib.pumiumtally_get_flux(h, _dp(flux), 6)
        assert abs(flux.sum() - expect) / expect < 1e-9
    finally:
        lib.pumiumtally_destroy(h)


def test_native_env_selects_block_kernel(tmp_path, monkeypatch):
    """PUMIUMTALLY_BLOCK_KERNEL routes through to
    TallyConfig.walk_block_kernel for the partitioned engines, and is
    rejected for non-partitioned engines like the other
    partitioned-only knobs."""
    from pumiumtally_tpu.api.native import native_create

    msh = str(tmp_path / "box.msh")
    _write_box_msh(msh)
    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "partitioned")
    monkeypatch.setenv("PUMIUMTALLY_DEVICES", "2")
    monkeypatch.setenv("PUMIUMTALLY_VMEM_MAX_ELEMS", "2")
    monkeypatch.setenv("PUMIUMTALLY_BLOCK_KERNEL", "gather")
    monkeypatch.setenv("PUMIUMTALLY_CAPACITY_FACTOR", "8.0")
    t = native_create(msh, 16)
    assert t.engine.blocks_per_chip > 1 and not t.engine.use_vmem_walk
    assert t.config.walk_block_kernel == "gather"
    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "mono")
    monkeypatch.delenv("PUMIUMTALLY_VMEM_MAX_ELEMS")
    with pytest.raises(ValueError, match="BLOCK_KERNEL"):
        native_create(msh, 16)


def _embedded_boot_env_and_code(tmp_path):
    msh = str(tmp_path / "box.msh")
    _write_box_msh(msh)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}  # hook no-ops without it
    env["JAX_PLATFORMS"] = "axon"  # ...but the env still names it
    env["PUMIUMTALLY_ENGINE"] = "mono"
    code = (
        "import numpy as np\n"
        "from pumiumtally_tpu.api.native import native_create\n"
        f"t = native_create({msh!r}, 8)\n"
        "src = np.full((8, 3), 0.3) + np.arange(8)[:, None] * 0.05\n"
        "t.CopyInitialPosition(src.reshape(-1).copy())\n"
        "dest = src + 0.1\n"
        "t.MoveToNextLocation(src.reshape(-1).copy(),"
        " dest.reshape(-1).copy(), np.ones(8, np.int8), np.ones(8))\n"
        "import jax.numpy as jnp\n"
        "print('SUM', float(jnp.sum(t.flux)))\n"
    )
    return env, code


def test_embedded_boot_unregistered_accelerator_refuses(tmp_path):
    """An embedding host's interpreter may inherit JAX_PLATFORMS naming
    a PJRT *plugin* backend whose registration hook (sitecustomize)
    never ran — the exact failure the round-4 on-chip native bench hit.
    The old behavior silently ran the tally on CPU (a physics host
    would get CPU numbers believing the accelerator ran — VERDICT r4
    weak #6); native_create must now REFUSE without explicit opt-in."""
    env, code = _embedded_boot_env_and_code(tmp_path)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode != 0
    assert "Refusing to run the tally silently on CPU" in r.stderr


def test_embedded_boot_cpu_fallback_opt_in(tmp_path):
    """With PUMIUMTALLY_ALLOW_CPU_FALLBACK=1 the embedded host gets a
    working CPU engine plus a loud ACCELERATOR FALLBACK warning."""
    env, code = _embedded_boot_env_and_code(tmp_path)
    env["PUMIUMTALLY_ALLOW_CPU_FALLBACK"] = "1"
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    out = r.stderr + r.stdout
    assert "falling back to automatic backend selection" in out
    assert "ACCELERATOR FALLBACK" in out
    got = float(r.stdout.strip().split("SUM", 1)[1])
    want = float(np.linalg.norm(np.full((8, 3), 0.1), axis=1).sum())
    assert abs(got - want) < 1e-6
