"""Box-mesh fixture geometry checks.

Pins the 6-tet unit-cube decomposition the oracle suite depends on
(reference builds it with Omega_h::build_box, test:34-35):
element 0 centroid (0.5, 0.75, 0.25) (test:83) and the element
containment the rays assume.
"""

import numpy as np
import pytest

from pumiumtally_tpu.mesh.box import build_box
from pumiumtally_tpu.ops import geometry


@pytest.fixture(scope="module")
def cube():
    return build_box(1, 1, 1, 1, 1, 1)


def test_counts(cube):
    assert cube.nelems == 6
    assert cube.nverts == 8


def test_positive_volumes_sum_to_one(cube):
    v = np.asarray(cube.volumes)
    assert np.all(v > 0)
    np.testing.assert_allclose(v, 1.0 / 6.0, atol=1e-12)
    np.testing.assert_allclose(v.sum(), 1.0, atol=1e-12)


def test_elem0_centroid(cube):
    # Reference oracle: centroid of element 0 is (0.5, 0.75, 0.25)
    # (test_pumi_tally_impl_methods.cpp:83).
    c = np.asarray(cube.centroids())
    np.testing.assert_allclose(c[0], [0.5, 0.75, 0.25], atol=1e-12)


def test_point_containment_matches_oracle(cube):
    # (0.1,0.4,0.5) in elem 2 (test:157-159); phase-2 destinations in
    # elems 3 and 4 (test:286-289).
    pts = np.array(
        [[0.1, 0.4, 0.5], [0.15, 0.05, 0.2], [0.85, 0.05, 0.1]]
    )
    elems = geometry.locate_bruteforce(
        cube.coords, cube.tet2vert, pts
    )
    np.testing.assert_array_equal(np.asarray(elems), [2, 3, 4])


def test_face_adjacency_symmetric(cube):
    adj = np.asarray(cube.face_adj)
    # Interior faces: neighbor's adjacency must point back.
    for e in range(6):
        for f in range(4):
            nb = adj[e, f]
            if nb >= 0:
                assert e in adj[nb], (e, f, nb)
    # A unit cube of 6 Kuhn tets has 12 boundary half-faces (2 per cube face).
    assert (adj == -1).sum() == 12


def test_outward_normals(cube):
    # n·(centroid - face_point) < 0 for the tet's own centroid.
    import numpy as np

    n = np.asarray(cube.face_normals)
    off = np.asarray(cube.face_offsets)
    cent = np.asarray(cube.centroids())
    s = np.einsum("efc,ec->ef", n, cent) - off
    assert np.all(s < 0)


def test_larger_box_adjacency_counts():
    m = build_box(2.0, 1.0, 3.0, 3, 2, 4)
    ncells = 3 * 2 * 4
    assert m.nelems == 6 * ncells
    v = np.asarray(m.volumes)
    np.testing.assert_allclose(v.sum(), 2.0 * 1.0 * 3.0, rtol=1e-12)
    adj = np.asarray(m.face_adj)
    # boundary faces = 2 triangles per exposed quad
    nbnd = 2 * 2 * (3 * 2 + 2 * 4 + 3 * 4)
    assert (adj == -1).sum() == nbnd


def test_unpacked_walk_table_fallback_matches_packed():
    """Meshes past the exact float-id limit store separate walk arrays
    (walk_table=None); forced at small size, the full engine must
    produce bit-identical results to the packed layout."""
    from pumiumtally_tpu import PumiTally, TetMesh
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(1, 1, 1, 3, 3, 3)
    packed = TetMesh.from_arrays(coords, tets)
    unpacked = TetMesh.from_arrays(coords, tets, force_unpacked=True)
    assert packed.walk_table is not None and unpacked.walk_table is None
    np.testing.assert_array_equal(
        np.asarray(packed.face_adj), np.asarray(unpacked.face_adj)
    )
    np.testing.assert_array_equal(
        np.asarray(packed.face_normals), np.asarray(unpacked.face_normals)
    )
    # astype must preserve the unpacked layout (a dtype-differing
    # TallyConfig would otherwise silently repack the test mesh)
    assert unpacked.astype(np.float32).walk_table is None

    n = 800
    rng = np.random.default_rng(41)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(-0.1, 1.1, (n, 3))  # includes boundary exits
    out = []
    for mesh in (packed, unpacked):
        t = PumiTally(mesh, n)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        out.append((np.asarray(t.flux), t.positions, t.elem_ids))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_array_equal(out[0][2], out[1][2])
