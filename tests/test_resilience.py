"""Fault-tolerance suite (round 8): atomic generational checkpoints,
autosave/drain policy, fault injection, kill-and-resume.

Two layers:

- in-process: store mechanics (atomicity, digest fallback, pruning),
  policy cadence, drain semantics, the bitwise resume contract on
  every engine facade, and the lost-particle accounting satellite;
- subprocess: the acceptance gate — a campaign killed mid-flight
  (graceful SIGTERM drain AND hard SIGKILL mid-save, both injected
  deterministically via PUMIUMTALLY_FAULT) resumes from the surviving
  generation and reproduces the uninterrupted run's final flux
  BITWISE; a deliberately corrupted latest generation is skipped with
  a warning, never a crash.
"""

import io
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from pumiumtally_tpu import (
    CheckpointPolicy,
    PartitionedPumiTally,
    PumiTally,
    StreamingPartitionedTally,
    StreamingTally,
    TallyConfig,
    build_box,
    resume_latest,
)
from pumiumtally_tpu.resilience import (
    CorruptCheckpointError,
    GenerationStore,
    parse_fault,
)
from pumiumtally_tpu.utils import load_tally_state, save_tally_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "_resilience_driver.py")

N = 24
MESH_ARGS = (1, 1, 1, 3, 3, 3)


def _mesh():
    return build_box(*MESH_ARGS)


def _policy(tmp_path, **kw):
    kw.setdefault("handle_signals", False)
    return CheckpointPolicy(dir=str(tmp_path / "ck"), **kw)


def _drive(t, rng, moves=1):
    src = rng.uniform(0.1, 0.9, (t.num_particles, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    for _ in range(moves):
        dst = rng.uniform(0.1, 0.9, (t.num_particles, 3))
        t.MoveToNextLocation(None, dst.reshape(-1).copy())


# ---------------------------------------------------------------------------
# Atomic save + corrupt-checkpoint errors (satellite: load_tally_state
# on garbage must raise a clear error, not a raw zipfile traceback)
# ---------------------------------------------------------------------------

def test_load_garbage_npz_clear_error(tmp_path):
    t = PumiTally(_mesh(), N)
    bad = tmp_path / "garbage.npz"
    bad.write_bytes(b"this is not a zip archive at all")
    with pytest.raises(CorruptCheckpointError, match="corrupt checkpoint"):
        load_tally_state(t, str(bad))


def test_load_truncated_npz_clear_error(tmp_path):
    t = PumiTally(_mesh(), N)
    _drive(t, np.random.default_rng(0))
    ckpt = tmp_path / "state.npz"
    save_tally_state(t, str(ckpt))
    data = ckpt.read_bytes()
    ckpt.write_bytes(data[: int(len(data) * 0.6)])  # cut the tail
    t2 = PumiTally(_mesh(), N)
    with pytest.raises(CorruptCheckpointError, match="corrupt checkpoint"):
        load_tally_state(t2, str(ckpt))
    # Missing files stay FileNotFoundError: absence is not corruption.
    with pytest.raises(FileNotFoundError):
        load_tally_state(t2, str(tmp_path / "never_written.npz"))


def test_save_is_atomic_on_failure(tmp_path, monkeypatch):
    """A failing save must leave the previous checkpoint intact and no
    temp litter — the temp-write + os.replace contract."""
    t = PumiTally(_mesh(), N)
    _drive(t, np.random.default_rng(1))
    ckpt = tmp_path / "state.npz"
    save_tally_state(t, str(ckpt))
    good = ckpt.read_bytes()

    def boom(src, dst):
        raise OSError("disk full")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError, match="disk full"):
        save_tally_state(t, str(ckpt))
    monkeypatch.undo()
    assert ckpt.read_bytes() == good  # old checkpoint untouched
    assert [p for p in os.listdir(tmp_path) if "tmp" in p] == []


# ---------------------------------------------------------------------------
# Generation store: digest, fallback, pruning, payload validation
# ---------------------------------------------------------------------------

def _store_with_gens(tmp_path, n_gens=3, keep=5):
    t = PumiTally(_mesh(), N)
    rng = np.random.default_rng(7)
    store = GenerationStore(str(tmp_path / "gens"), keep=keep)
    fluxes = []
    for g in range(n_gens):
        _drive(t, rng)
        store.save(t, meta={"g": g})
        fluxes.append(np.asarray(t.flux, np.float64))
    return t, store, fluxes


def test_generation_store_latest_and_prune(tmp_path):
    _, store, fluxes = _store_with_gens(tmp_path, n_gens=5, keep=2)
    gens = store.generations()
    assert [g for g, _ in gens] == [4, 5]  # oldest pruned, newest kept
    t2 = PumiTally(_mesh(), N)
    info = store.load_latest(t2)
    assert info.generation == 5 and info.meta["g"] == 4
    np.testing.assert_array_equal(np.asarray(t2.flux, np.float64), fluxes[-1])


@pytest.mark.parametrize("damage", ["truncate", "bitflip", "header"])
def test_generation_fallback_past_damage(tmp_path, damage):
    """Storage damage on the newest generation: warn, fall back one
    generation, never crash."""
    _, store, fluxes = _store_with_gens(tmp_path, n_gens=3)
    gen, path = store.generations()[-1]
    data = bytearray(open(path, "rb").read())
    if damage == "truncate":
        open(path, "wb").write(data[:-80])
    elif damage == "bitflip":
        data[len(data) // 2] ^= 0xFF
        open(path, "wb").write(bytes(data))
    else:  # garbage header
        open(path, "wb").write(b"EHLO" + bytes(data))
    t2 = PumiTally(_mesh(), N)
    with pytest.warns(UserWarning, match="corrupt.*falling back"):
        info = store.load_latest(t2)
    assert info.generation == gen - 1
    np.testing.assert_array_equal(np.asarray(t2.flux, np.float64), fluxes[-2])


def test_generation_fallback_past_nan_payload(tmp_path, monkeypatch):
    """A digest-clean generation carrying NaN flux (the nan@gen fault:
    poisoned BEFORE sealing) must be rejected by payload validation and
    fall back, same as storage damage."""
    t, store, fluxes = _store_with_gens(tmp_path, n_gens=2)
    monkeypatch.setenv("PUMIUMTALLY_FAULT", "nan@gen:3")
    rng = np.random.default_rng(11)
    _drive(t, rng)
    store.save(t)  # generation 3, NaN-poisoned but digest-valid
    monkeypatch.delenv("PUMIUMTALLY_FAULT")
    payload, _, _ = store.read_generation(store.generations()[-1][1])
    assert np.isnan(np.load(io.BytesIO(payload))["flux"]).all()  # sealed NaN
    t2 = PumiTally(_mesh(), N)
    with pytest.warns(UserWarning, match="non-finite"):
        info = store.load_latest(t2)
    assert info.generation == 2
    np.testing.assert_array_equal(np.asarray(t2.flux, np.float64), fluxes[-1])


def test_all_generations_corrupt_raises(tmp_path):
    _, store, _ = _store_with_gens(tmp_path, n_gens=2)
    for _, path in store.generations():
        open(path, "wb").write(b"\x00" * 100)
    t2 = PumiTally(_mesh(), N)
    with pytest.warns(UserWarning):
        with pytest.raises(CorruptCheckpointError, match="every checkpoint"):
            store.load_latest(t2)


def test_empty_store_returns_none(tmp_path):
    t = PumiTally(_mesh(), N)
    assert GenerationStore(str(tmp_path / "empty")).load_latest(t) is None


def test_header_mismatch_is_config_error_not_corruption(tmp_path):
    """A VALID generation that does not fit the target raises the
    header ValueError immediately — falling back would be wrong (older
    generations would not fit either)."""
    _, store, _ = _store_with_gens(tmp_path, n_gens=2)
    wrong_n = PumiTally(_mesh(), N + 1)
    with pytest.raises(ValueError, match="particles"):
        store.load_latest(wrong_n)


def test_fault_spec_grammar():
    f = parse_fault("kill@save:3")
    assert (f.action, f.site, f.ordinal, f.arg) == ("kill", "save", 3, None)
    assert parse_fault("truncate@gen:2:128").arg == 128
    for bad in ("kill@gen:1", "kill@save", "kill@save:0", "frob@save:1",
                "kill@save:1:2:3", "killsave:1"):
        with pytest.raises(ValueError, match="PUMIUMTALLY_FAULT"):
            parse_fault(bad)


# ---------------------------------------------------------------------------
# Autosave policy: cadence + drain
# ---------------------------------------------------------------------------

def test_policy_validation():
    with pytest.raises(ValueError, match="every_n_batches"):
        CheckpointPolicy(dir="/tmp/x", every_n_batches=0)
    with pytest.raises(ValueError, match="every_seconds"):
        CheckpointPolicy(dir="/tmp/x", every_seconds=0.0)
    with pytest.raises(ValueError, match="keep"):
        CheckpointPolicy(dir="/tmp/x", keep=0)
    with pytest.raises(ValueError, match="CheckpointPolicy"):
        TallyConfig(checkpoint="not-a-policy")


def test_autosave_every_n_batches(tmp_path):
    pol = _policy(tmp_path, every_n_batches=2, keep=10)
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    rng = np.random.default_rng(3)
    for _ in range(5):  # closes 4 batches (the 5th stays open)
        _drive(t, rng)
    store = t._resilience.store
    # Batches close at sourcings 2..5; cadence 2 -> saves at closes 2, 4.
    assert [g for g, _ in store.generations()] == [1, 2]
    _, _, meta = store.read_generation(store.generations()[-1][1])
    assert meta["batches_closed"] == 4 and meta["reason"] == "batch_close"
    assert meta["iter_count"] == 4


def test_autosave_every_seconds(tmp_path, monkeypatch):
    from pumiumtally_tpu.resilience import policy as policy_mod

    clock = {"t": 1000.0}
    monkeypatch.setattr(policy_mod.time, "monotonic", lambda: clock["t"])
    pol = _policy(tmp_path, every_n_batches=None, every_seconds=30.0)
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    rng = np.random.default_rng(4)
    _drive(t, rng, moves=2)   # timer not yet due: no saves
    store = t._resilience.store
    assert store.generations() == []
    clock["t"] += 31.0
    dst = rng.uniform(0.1, 0.9, (N, 3))
    t.MoveToNextLocation(None, dst.reshape(-1).copy())  # move-end save
    assert [g for g, _ in store.generations()] == [1]
    _, _, meta = store.read_generation(store.generations()[0][1])
    assert meta["reason"] == "every_seconds"


def test_empty_batch_is_not_a_cadence_tick(tmp_path):
    pol = _policy(tmp_path, every_n_batches=1)
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    rng = np.random.default_rng(5)
    src = rng.uniform(0.1, 0.9, (N, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.CopyInitialPosition(src.reshape(-1).copy())  # empty batch: no save
    assert t._resilience.store.generations() == []
    assert t._resilience.batches_closed == 0


def test_drain_sigterm_saves_and_exits(tmp_path):
    """First SIGTERM sets the drain flag; each further move end writes
    a SAFETY generation (bounded loss if the grace window expires) but
    keeps running; the in-flight source batch finishes and its close
    saves + exits 0; handlers restored."""
    pol = _policy(tmp_path, every_n_batches=None, handle_signals=True)
    prev_term = signal.getsignal(signal.SIGTERM)
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    runner = t._resilience
    try:
        assert signal.getsignal(signal.SIGTERM) is not prev_term
        rng = np.random.default_rng(6)
        src = rng.uniform(0.1, 0.9, (N, 3))
        dst = rng.uniform(0.1, 0.9, (N, 3))
        t.CopyInitialPosition(src.reshape(-1).copy())
        os.kill(os.getpid(), signal.SIGTERM)  # handler runs synchronously
        assert runner.drain_requested
        # Mid-batch move: completes, writes a safety gen, NO exit —
        # the in-flight source batch is allowed to finish.
        t.MoveToNextLocation(None, dst.reshape(-1).copy())
        gens = runner.store.generations()
        assert len(gens) == 1
        _, _, meta = runner.store.read_generation(gens[0][1])
        assert meta["reason"] == "drain_safety" and meta["iter_count"] == 1
        # The batch close is the clean-exit point.
        with pytest.raises(SystemExit) as exc:
            t.CopyInitialPosition(src.reshape(-1).copy())
        assert exc.value.code == 0
        gens = runner.store.generations()
        # Same state as the safety gen (nothing moved since): the
        # drain exit deduplicates instead of writing a twin.
        assert len(gens) == 1
        # The move COMPLETED before the safety save (drain never
        # aborts device work): the saved flux is the post-move flux.
        t2 = PumiTally(_mesh(), N)
        GenerationStore(pol.dir).load_latest(t2)
        np.testing.assert_array_equal(
            np.asarray(t2.flux), np.asarray(t.flux)
        )
        assert signal.getsignal(signal.SIGTERM) == prev_term  # restored
    finally:
        runner.close()
        signal.signal(signal.SIGTERM, prev_term)


def test_drain_safety_generation_resumes_midbatch_bitwise(tmp_path):
    """A drain safety generation survived by a hard kill lands
    MID-batch; the move-granular resume recipe (skip re-sourcing, skip
    the done moves) must continue bitwise — the real preemption timing
    where the grace window expires before the batch closes."""
    pol = _policy(tmp_path, every_n_batches=None, handle_signals=True)
    prev_term = signal.getsignal(signal.SIGTERM)
    rng = np.random.default_rng(16)
    src = rng.uniform(0.1, 0.9, (N, 3))
    d1 = rng.uniform(0.1, 0.9, (N, 3))
    d2 = rng.uniform(0.1, 0.9, (N, 3))
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    try:
        t.CopyInitialPosition(src.reshape(-1).copy())
        os.kill(os.getpid(), signal.SIGTERM)
        t.MoveToNextLocation(None, d1.reshape(-1).copy())  # safety gen
        # (hard kill here in real life: the batch close never runs)
    finally:
        t._resilience.close()
        signal.signal(signal.SIGTERM, prev_term)

    t_ref = PumiTally(_mesh(), N)  # uninterrupted arm, no checkpoints
    t_ref.CopyInitialPosition(src.reshape(-1).copy())
    t_ref.MoveToNextLocation(None, d1.reshape(-1).copy())
    t_ref.MoveToNextLocation(None, d2.reshape(-1).copy())

    t2 = PumiTally(_mesh(), N, TallyConfig(checkpoint=_policy(tmp_path)))
    info = resume_latest(t2)
    assert info.meta["reason"] == "drain_safety"
    start, done = divmod(t2.iter_count, 2)
    assert (start, done) == (0, 1)  # mid-batch: sources already in
    t2.MoveToNextLocation(None, d2.reshape(-1).copy())  # remainder only
    np.testing.assert_array_equal(
        np.asarray(t2.flux), np.asarray(t_ref.flux)
    )
    np.testing.assert_array_equal(t2.positions, t_ref.positions)


def test_checkpoint_now_consumes_pending_drain(tmp_path):
    """A SIGTERM during the FINAL batch (whose close no re-sourcing
    will ever run) must not be absorbed: the campaign's sealing
    checkpoint_now saves, restores the signal handlers, and exits 0."""
    pol = _policy(tmp_path, every_n_batches=None, handle_signals=True)
    prev_term = signal.getsignal(signal.SIGTERM)
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    try:
        _drive(t, np.random.default_rng(17))
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(SystemExit) as exc:
            t.checkpoint_now(final=True)
        assert exc.value.code == 0
        assert signal.getsignal(signal.SIGTERM) == prev_term
        gens = t._resilience.store.generations()
        _, _, meta = t._resilience.store.read_generation(gens[-1][1])
        # The seal itself is the saved generation (reason manual with
        # the caller's extras; a separate drain twin is not written).
        assert meta["reason"] == "manual" and meta["final"] is True
    finally:
        t._resilience.close()
        signal.signal(signal.SIGTERM, prev_term)


def test_save_meta_reserved_keys_win(tmp_path):
    """checkpoint_now extras must not shadow the runner's bookkeeping
    keys — sync_from_resume reads them back into the cadence state."""
    pol = _policy(tmp_path)
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    _drive(t, np.random.default_rng(18))
    t.checkpoint_now(iter_count=999, reason="lies", tag="ok")
    store = t._resilience.store
    _, _, meta = store.read_generation(store.generations()[-1][1])
    assert meta["iter_count"] == 1 and meta["reason"] == "manual"
    assert meta["tag"] == "ok"


def test_second_runner_takes_over_and_escalation_still_kills(tmp_path):
    """With several checkpoint-armed tallies the NEWEST runner owns the
    drain handler, and the second-signal escalation restores the
    original (pre-any-runner) disposition — stale runners can never
    absorb the operator's 'kill now' signal."""
    prev_int = signal.getsignal(signal.SIGINT)
    prev_term = signal.getsignal(signal.SIGTERM)
    pol_a = CheckpointPolicy(dir=str(tmp_path / "a"), handle_signals=True)
    pol_b = CheckpointPolicy(dir=str(tmp_path / "b"), handle_signals=True)
    t_a = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol_a))
    t_b = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol_b))
    try:
        os.kill(os.getpid(), signal.SIGINT)
        assert t_b._resilience.drain_requested  # newest runner owns it
        assert not t_a._resilience.drain_requested
        with pytest.raises(KeyboardInterrupt):  # SECOND signal kills —
            os.kill(os.getpid(), signal.SIGINT)  # never a third
        assert signal.getsignal(signal.SIGINT) == prev_int  # originals
        assert signal.getsignal(signal.SIGTERM) == prev_term  # restored
    finally:
        t_b._resilience.close()
        t_a._resilience.close()
        signal.signal(signal.SIGINT, prev_int)
        signal.signal(signal.SIGTERM, prev_term)


def test_second_sigint_escalates(tmp_path):
    """A second signal while draining restores the previous disposition
    and re-delivers — the operator's double ctrl-C still interrupts."""
    pol = _policy(tmp_path, handle_signals=True)
    prev_int = signal.getsignal(signal.SIGINT)
    prev_term = signal.getsignal(signal.SIGTERM)
    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    runner = t._resilience
    try:
        os.kill(os.getpid(), signal.SIGINT)
        assert runner.drain_requested
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGINT)
    finally:
        runner.close()
        signal.signal(signal.SIGINT, prev_int)
        signal.signal(signal.SIGTERM, prev_term)


# ---------------------------------------------------------------------------
# Bitwise resume contract, every facade, in-process
# ---------------------------------------------------------------------------

def _build_facade(facade, n=N):
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = _mesh()
    if facade == "mono":
        return PumiTally(mesh, n)
    if facade == "sharded":
        return PumiTally(
            mesh, n, TallyConfig(device_mesh=make_device_mesh(4))
        )
    if facade == "stream":
        return StreamingTally(mesh, n, chunk_size=10)
    if facade == "part":
        return PartitionedPumiTally(
            mesh, n, TallyConfig(capacity_factor=4.0)
        )
    if facade == "stream_part":
        return StreamingPartitionedTally(
            mesh, n, chunk_size=10,
            config=TallyConfig(device_mesh=make_device_mesh(4),
                               capacity_factor=6.0),
        )
    raise AssertionError(facade)


@pytest.mark.parametrize(
    "facade", ["mono", "sharded", "stream", "part", "stream_part"]
)
def test_resume_is_bitwise_on_every_facade(facade, tmp_path):
    """The layout-exact restore contract at the HARDEST point: save
    MID-source-batch (sources localized, one of two moves done — the
    state a drain safety save or an every_seconds save captures),
    restore into an identically configured engine, continue
    move-granularly — flux, positions, and element ids all stay
    BITWISE equal to the uninterrupted run through further batches."""
    def trajectory():
        rng = np.random.default_rng(42)
        return [
            (rng.uniform(0.1, 0.9, (N, 3)),
             [rng.uniform(0.1, 0.9, (N, 3)) for _ in range(2)])
            for _ in range(3)
        ]

    work = trajectory()

    def run_batch(t, batch, skip_moves=0):
        src, dests = batch
        if skip_moves == 0:
            t.CopyInitialPosition(src.reshape(-1).copy())
        for d in dests[skip_moves:]:
            t.MoveToNextLocation(None, d.reshape(-1).copy())

    t_full = _build_facade(facade)
    run_batch(t_full, work[0])
    src1, dests1 = work[1]
    t_full.CopyInitialPosition(src1.reshape(-1).copy())
    t_full.MoveToNextLocation(None, dests1[0].reshape(-1).copy())
    ckpt = str(tmp_path / "mid.npz")
    save_tally_state(t_full, ckpt)  # MID batch 1: move 1 of 2 done
    t_res = _build_facade(facade)
    load_tally_state(t_res, ckpt)
    assert divmod(t_res.iter_count, 2) == (1, 1)
    run_batch(t_full, work[1], skip_moves=1)
    run_batch(t_res, work[1], skip_moves=1)  # remainder only, no re-source
    for t in (t_full, t_res):
        run_batch(t, work[2])
    np.testing.assert_array_equal(
        np.asarray(t_res.flux), np.asarray(t_full.flux), err_msg=facade
    )
    np.testing.assert_array_equal(t_res.positions, t_full.positions)
    np.testing.assert_array_equal(t_res.elem_ids, t_full.elem_ids)


def test_layout_mismatch_falls_back_to_canonical(tmp_path):
    """A partitioned checkpoint restored into a DIFFERENTLY laid-out
    partitioned engine (different capacity) must still restore
    correctly through the canonical path (exact state; flux scatter
    order may differ on later moves, which is the documented class)."""
    t = PartitionedPumiTally(_mesh(), N, TallyConfig(capacity_factor=4.0))
    _drive(t, np.random.default_rng(8))
    ckpt = str(tmp_path / "p.npz")
    save_tally_state(t, ckpt)
    t2 = PartitionedPumiTally(_mesh(), N, TallyConfig(capacity_factor=2.0))
    load_tally_state(t2, ckpt)
    np.testing.assert_array_equal(
        np.asarray(t2.flux, np.float64), np.asarray(t.flux, np.float64)
    )
    np.testing.assert_array_equal(t2.elem_ids, t.elem_ids)


# ---------------------------------------------------------------------------
# Lost-particle accounting (satellite): cumulative counter + VTK field
# ---------------------------------------------------------------------------

def _sources_with_lost(rng, n, n_lost):
    src = rng.uniform(0.1, 0.9, (n, 3))
    src[:n_lost] = [2.5, 2.5, 2.5]  # outside the unit box: no element
    return src


def test_lost_particles_counter_partitioned():
    t = PartitionedPumiTally(_mesh(), N, TallyConfig(capacity_factor=4.0))
    rng = np.random.default_rng(9)
    t.CopyInitialPosition(_sources_with_lost(rng, N, 2).reshape(-1).copy())
    dst = rng.uniform(0.1, 0.9, (N, 3))
    t.MoveToNextLocation(None, dst.reshape(-1).copy())
    assert t.lost_particles == 2
    # Second sourcing, 1 more lost: the counter is CUMULATIVE.
    t.CopyInitialPosition(_sources_with_lost(rng, N, 1).reshape(-1).copy())
    t.MoveToNextLocation(None, dst.reshape(-1).copy())
    assert t.lost_particles == 3
    # ... and rides checkpoints.
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        ckpt = os.path.join(d, "l.npz")
        save_tally_state(t, ckpt)
        t2 = PartitionedPumiTally(_mesh(), N, TallyConfig(capacity_factor=4.0))
        load_tally_state(t2, ckpt)
        assert t2.lost_particles == 3


def test_lost_particles_zero_on_clamping_facades():
    """Monolithic/streaming engines clamp out-of-domain sources to the
    hull instead of dropping them — their counter stays 0."""
    rng = np.random.default_rng(10)
    src = _sources_with_lost(rng, N, 2)
    for t in (PumiTally(_mesh(), N), StreamingTally(_mesh(), N, chunk_size=10)):
        t.CopyInitialPosition(src.reshape(-1).copy())
        assert t.lost_particles == 0


def test_lost_particles_in_vtk_field_data(tmp_path, capsys):
    from pumiumtally_tpu.io.vtk import read_vtk_field_scalars

    t = PartitionedPumiTally(_mesh(), N, TallyConfig(capacity_factor=4.0))
    rng = np.random.default_rng(11)
    t.CopyInitialPosition(_sources_with_lost(rng, N, 3).reshape(-1).copy())
    dst = rng.uniform(0.1, 0.9, (N, 3))
    t.MoveToNextLocation(None, dst.reshape(-1).copy())
    for name in ("out.vtk", "out.vtu"):
        path = str(tmp_path / name)
        t.WriteTallyResults(path)
        np.testing.assert_array_equal(
            read_vtk_field_scalars(path, "lost_particles"), [3.0]
        )
    # The pvtu path replicates the field into every piece.
    t.WriteTallyResults(str(tmp_path / "out.pvtu"))
    np.testing.assert_array_equal(
        read_vtk_field_scalars(str(tmp_path / "out_p0.vtu"),
                               "lost_particles"),
        [3.0],
    )
    capsys.readouterr()  # swallow the timing prints


def test_streaming_partitioned_lost_counter():
    from pumiumtally_tpu.parallel import make_device_mesh

    t = StreamingPartitionedTally(
        _mesh(), N, chunk_size=10,
        config=TallyConfig(device_mesh=make_device_mesh(4),
                           capacity_factor=6.0, check_found_all=False),
    )
    rng = np.random.default_rng(12)
    t.CopyInitialPosition(_sources_with_lost(rng, N, 2).reshape(-1).copy())
    assert t.lost_particles == 2


# ---------------------------------------------------------------------------
# Kill-and-resume, subprocess (the acceptance gate)
# ---------------------------------------------------------------------------

def _driver_env(facade, fault=None):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PUMIUMTALLY_FAULT", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    if facade in ("sharded", "stream_part"):
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    if fault:
        env["PUMIUMTALLY_FAULT"] = fault
    return env


def _run_driver(facade, ckpt_dir, out, fault=None, resume=False, timeout=240):
    cmd = [sys.executable, DRIVER, "--facade", facade,
           "--ckpt-dir", str(ckpt_dir), "--out", str(out)]
    if resume:
        cmd.append("--resume")
    return subprocess.run(
        cmd, capture_output=True, text=True, cwd=REPO, timeout=timeout,
        env=_driver_env(facade, fault),
    )


def _kill_and_resume_case(facade, tmp_path):
    # Uninterrupted reference run.
    base_out = tmp_path / "base.npy"
    r = _run_driver(facade, tmp_path / "ck_base", base_out)
    assert r.returncode == 0, r.stderr
    flux_base = np.load(base_out)

    # Arm 1: graceful drain — SIGTERM injected at the 2nd batch close.
    # The run must exit CLEANLY (rc 0) after saving, without finishing.
    out1 = tmp_path / "drain.npy"
    r = _run_driver(facade, tmp_path / "ck_drain", out1,
                    fault="sigterm@batch:2")
    assert r.returncode == 0, r.stderr
    assert not out1.exists()  # drained before the campaign finished
    r = _run_driver(facade, tmp_path / "ck_drain", out1, resume=True)
    assert r.returncode == 0, r.stderr
    assert "resumed generation" in r.stdout
    np.testing.assert_array_equal(np.load(out1), flux_base,
                                  err_msg=f"{facade}: drain arm")

    # Arm 2: hard kill mid-save — SIGKILL between the temp-file fsync
    # and the atomic rename of generation 3. The store must be left
    # with generations 1-2 intact; resume falls back to generation 2.
    out2 = tmp_path / "kill.npy"
    r = _run_driver(facade, tmp_path / "ck_kill", out2,
                    fault="kill@save:3")
    assert r.returncode == -signal.SIGKILL
    names = sorted(os.listdir(tmp_path / "ck_kill"))
    assert [n for n in names if n.endswith(".ckpt")] == [
        "gen-00000001.ckpt", "gen-00000002.ckpt",
    ]
    r = _run_driver(facade, tmp_path / "ck_kill", out2, resume=True)
    assert r.returncode == 0, r.stderr
    assert "resumed generation 2 at batch 2" in r.stdout
    np.testing.assert_array_equal(np.load(out2), flux_base,
                                  err_msg=f"{facade}: kill arm")
    # The resumed store swept the dead writer's orphaned temp file.
    assert not [n for n in os.listdir(tmp_path / "ck_kill")
                if n.startswith(".tmp-gen-")]

    # Arm 3: the reference run's LATEST generation is deliberately
    # corrupted; resume must warn, fall back one generation, re-run
    # the final batch, and still land bitwise on the same flux.
    gens = sorted((tmp_path / "ck_base").glob("gen-*.ckpt"))
    data = gens[-1].read_bytes()
    gens[-1].write_bytes(data[: len(data) - 120])
    out3 = tmp_path / "corrupt.npy"
    r = _run_driver(facade, tmp_path / "ck_base", out3, resume=True)
    assert r.returncode == 0, r.stderr
    assert "corrupt" in (r.stderr + r.stdout)
    np.testing.assert_array_equal(np.load(out3), flux_base,
                                  err_msg=f"{facade}: corrupt arm")


@pytest.mark.parametrize("facade", ["mono", "stream", "part"])
def test_kill_and_resume_bitwise(facade, tmp_path):
    _kill_and_resume_case(facade, tmp_path)


@pytest.mark.slow
@pytest.mark.parametrize("facade", ["sharded", "stream_part"])
def test_kill_and_resume_bitwise_multichip(facade, tmp_path):
    _kill_and_resume_case(facade, tmp_path)


def test_resume_counters_continue(tmp_path):
    """A resumed runner continues generation numbering and batch
    counters where the dead process stopped (resume_latest re-syncs
    from the restored metadata)."""
    pol = _policy(tmp_path, every_n_batches=1, keep=10)
    rng_args = dict(seed=13)

    def batches(t, start, stop):
        rng = np.random.default_rng(rng_args["seed"])
        work = [
            (rng.uniform(0.1, 0.9, (N, 3)), rng.uniform(0.1, 0.9, (N, 3)))
            for _ in range(stop)
        ]
        for src, dst in work[start:stop]:
            t.CopyInitialPosition(src.reshape(-1).copy())
            t.MoveToNextLocation(None, dst.reshape(-1).copy())

    t = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    batches(t, 0, 3)  # closes batches at sourcings 2, 3 -> gens 1, 2
    t2 = PumiTally(_mesh(), N, TallyConfig(checkpoint=pol))
    info = resume_latest(t2)
    assert info.generation == 2 and t2._resilience.batches_closed == 2
    assert t2.iter_count == 2
    batches(t2, 2, 4)
    # Batch 2's sourcing closes nothing (the restored state is already
    # at that boundary); batch 3's sourcing closes batch 2 -> gen 3;
    # batch 3 itself stays open (no further sourcing).
    assert [g for g, _ in t2._resilience.store.generations()] == [1, 2, 3]
    assert t2._resilience.batches_closed == 3
