"""Pod-scale distributed campaigns (round 13, docs/DESIGN.md
"Pod-scale campaigns").

Tier-1 pins the whole distributed machinery on ONE process with 8
virtual CPU devices — the collective programs are identical under
multi-process partitioning, only device placement changes:

- the collective migration (``make_collective_migrate``: all_gather'd
  counting-rank keys + ppermute ring) is BITWISE equal to the global
  scatter ``partition._migrate_impl`` for both partition methods,
  overflow and non-overflow arms;
- the collective FRONTIER migration (round 19,
  ``make_collective_frontier_migrate``: the same ring at
  ``cap_frontier`` rows) is bitwise equal to
  ``partition._frontier_migrate_impl`` — state dict, overflow latch,
  departure/arrival counts — on sparse and all-to-one-overflow arms,
  and the engine composition ``migrate_collective x cap_frontier``
  (construction used to refuse the pair) matches the on-chip frontier
  engine bit for bit across all 4 walk perm modes, including the
  slab-overflow fallback to the full-capacity collective and the
  ``cap_frontier=0`` forced-full arm;
- the partitioned engine with ``migrate_collective=True`` lands flux,
  positions, element ids, and score banks bitwise equal to the
  default global-scatter engine (the determinism contract that makes
  pod campaigns trustworthy);
- ``SessionRouter`` pins sessions to home workers and forwards NDJSON
  ops with per-session results bitwise equal to a direct facade;
- the ``init_distributed`` front door validates its arguments instead
  of dying in the coordinator handshake.

The slow tier then runs the REAL 2-process version through
tests/_distributed_driver.py and compares process 0's fetched global
results bitwise against the in-process single-process reference at the
same global shapes. On jaxlib builds without cross-process CPU
collectives (no gloo) the workers exit with the
``DISTRIBUTED-UNAVAILABLE`` marker and the test SKIPS — never fails.
"""

import json
import os
import socket

import numpy as np
import pytest

from tests._distributed_driver import (
    ARMS,
    build_tally,
    collect,
    launch_or_skip,
    run_campaign,
)

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pumiumtally_tpu import (  # noqa: E402
    EnergyFilter,
    PartitionedPumiTally,
    ScoringSpec,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh  # noqa: E402
from pumiumtally_tpu.parallel.distributed import (  # noqa: E402
    UNAVAILABLE_MARKER,
    fetch_global,
    global_device_mesh,
    init_distributed,
    make_collective_frontier_migrate,
    make_collective_migrate,
    modeled_migration_collective_bytes,
    state_pack_columns,
)
from pumiumtally_tpu.parallel.partition import (  # noqa: E402
    _frontier_migrate_impl,
    _migrate_impl,
)


# -- collective migration vs global scatter ---------------------------------

def _mkstate(rng, cap, part_L, pending):
    return {
        "x": jnp.asarray(rng.standard_normal((cap, 3))),
        "lelem": jnp.asarray(rng.integers(0, part_L, cap).astype(np.int32)),
        "pending": jnp.asarray(pending.astype(np.int32)),
        "pid": jnp.asarray(np.arange(cap, dtype=np.int32)),
        "alive": jnp.asarray(rng.random(cap) < 0.3),
        "done": jnp.asarray(rng.random(cap) < 0.5),
        "exited": jnp.asarray(rng.random(cap) < 0.1),
        "lost": jnp.asarray(np.zeros(cap, bool)),
        "dest": jnp.asarray(rng.standard_normal((cap, 3))),
        "fly": jnp.asarray(rng.integers(0, 2, cap).astype(np.int8)),
        "w": jnp.asarray(rng.random(cap)),
        "sbin": jnp.asarray(rng.integers(0, 4, cap).astype(np.int32)),
        "sfac": jnp.asarray(rng.random((cap, 3))),
    }


@pytest.mark.parametrize("method", ["rank", "argsort"])
def test_collective_migrate_bitwise_vs_global_scatter(method):
    """all_gather + ppermute-ring migrate == full-capacity scatter,
    bit for bit, in both the committing and the overflow-refusing arm."""
    mesh = global_device_mesh()
    ndev = int(mesh.devices.size)
    bpc, cap_b, part_L = 2, 5, 7
    nparts = ndev * bpc
    cap = nparts * cap_b
    rng = np.random.default_rng(0)
    coll = make_collective_migrate(
        mesh, part_L=part_L, nparts=nparts, cap_per_block=cap_b,
        partition_method=method,
    )
    ref_fn = jax.jit(
        lambda s: _migrate_impl(part_L, nparts, cap_b, s, method)
    )

    # Sparse pendings: the migrate commits (no overflow).
    pend = np.full(cap, -1)
    pend[rng.choice(cap, 8, replace=False)] = rng.integers(
        0, nparts * part_L, 8
    )
    st = _mkstate(rng, cap, part_L, pend)
    ref, ovf_ref = ref_fn(st)
    got, ovf = jax.jit(coll)(st)
    assert bool(ovf) == bool(ovf_ref) is False
    for k in sorted(ref):
        a, b = np.asarray(ref[k]), np.asarray(got[k])
        assert a.dtype == b.dtype, (k, a.dtype, b.dtype)
        np.testing.assert_array_equal(a, b, err_msg=k)

    # Everyone pending to partition 0: overflow, pre-state survives.
    st = _mkstate(rng, cap, part_L, np.zeros(cap))
    ref, ovf_ref = ref_fn(st)
    got, ovf = jax.jit(coll)(st)
    assert bool(ovf_ref) and bool(ovf)
    for k in sorted(ref):
        np.testing.assert_array_equal(
            np.asarray(ref[k]), np.asarray(got[k]), err_msg=k
        )


@pytest.mark.parametrize("method", ["rank", "argsort"])
def test_collective_frontier_migrate_bitwise(method):
    """The cap_frontier-row ppermute ring == the on-chip frontier slab
    scatter, bit for bit: state dict (every lane, dtype included), the
    psum'd overflow latch, and the departure/arrival census."""
    mesh = global_device_mesh()
    ndev = int(mesh.devices.size)
    bpc, cap_b, part_L, cf = 2, 5, 7, 16
    nparts = ndev * bpc
    cap = nparts * cap_b
    coll = make_collective_frontier_migrate(
        mesh, part_L=part_L, nparts=nparts, cap_per_block=cap_b,
        cap_frontier=cf, partition_method=method,
    )
    ref_fn = jax.jit(
        lambda s: _frontier_migrate_impl(part_L, nparts, cap_b, cf, s,
                                         method)
    )
    rng = np.random.default_rng(0)

    def check(st, want_overflow):
        ref, ovf_r, dep_r, arr_r = ref_fn(st)
        got, ovf_g, dep_g, arr_g = jax.jit(coll)(st)
        assert bool(ovf_r) == bool(ovf_g) is want_overflow
        assert np.asarray(dep_g).dtype == np.asarray(dep_r).dtype
        np.testing.assert_array_equal(np.asarray(dep_r),
                                      np.asarray(dep_g))
        np.testing.assert_array_equal(np.asarray(arr_r),
                                      np.asarray(arr_g))
        for k in sorted(ref):
            a, b = np.asarray(ref[k]), np.asarray(got[k])
            assert a.dtype == b.dtype, (k, a.dtype, b.dtype)
            np.testing.assert_array_equal(a, b, err_msg=k)

    # Sparse front: fits the slab, commits.
    pend = np.full(cap, -1)
    pend[rng.choice(cap, 8, replace=False)] = rng.integers(
        0, nparts * part_L, 8
    )
    check(_mkstate(rng, cap, part_L, pend), want_overflow=False)

    # All-to-one overflow: the front fits the slab but the target
    # partition has no free rows — the latch trips on every shard and
    # the pre-state survives unchanged.
    pend = np.full(cap, -1)
    pend[rng.choice(cap, cf, replace=False)] = 3
    st = _mkstate(rng, cap, part_L, pend)
    st["alive"] = jnp.asarray(np.ones(cap, bool))
    check(st, want_overflow=True)


# -- engine-level on/off parity ---------------------------------------------

def _campaign_arrays(N=3000, seed=3):
    rng = np.random.default_rng(seed)
    src = rng.uniform(0.05, 0.95, (N, 3))
    dest1 = np.clip(src + rng.normal(scale=0.3, size=(N, 3)), 0.01, 0.99)
    dest2 = np.clip(dest1 + rng.normal(scale=0.3, size=(N, 3)), 0.01, 0.99)
    fly = (rng.uniform(size=N) > 0.1).astype(np.int8)
    w = rng.uniform(0.5, 2.0, N)
    return src, dest1, dest2, fly, w


def test_partitioned_engine_collective_parity():
    """migrate_collective=True is bitwise the global-scatter engine:
    same flux, same positions, same element ids after crossing-heavy
    moves on the 8-virtual-device mesh."""
    N = 3000
    mesh = build_box(1, 1, 1, 5, 5, 5)
    dm = make_device_mesh(8)
    src, dest1, dest2, fly, w = _campaign_arrays(N)
    off = PartitionedPumiTally(mesh, N, TallyConfig(device_mesh=dm))
    on = PartitionedPumiTally(
        mesh, N, TallyConfig(device_mesh=dm, migrate_collective=True)
    )
    for t in (off, on):
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dest1.reshape(-1).copy(), fly.copy(), w)
        t.MoveToNextLocation(None, dest2.reshape(-1).copy(),
                             np.ones(N, np.int8), w)
    np.testing.assert_array_equal(off.elem_ids, on.elem_ids)
    assert (np.asarray(off.positions) == np.asarray(on.positions)).all()
    assert (np.asarray(off.flux) == np.asarray(on.flux)).all()


def test_partitioned_engine_collective_parity_scoring():
    """The scoring-armed engine keeps the bitwise contract too — the
    collective ships the scoring lanes (sbin / factors) in the same
    packed slab, so score banks match bit for bit."""
    N = 3000
    mesh = build_box(1, 1, 1, 5, 5, 5)
    dm = make_device_mesh(8)
    src, dest1, _dest2, fly, w = _campaign_arrays(N)
    spec = ScoringSpec(filters=[EnergyFilter([0.0, 1.0, 2.0])],
                       scores=["flux", "events"])
    en = np.where(np.arange(N) % 2 == 0, 0.5, 1.5)
    off = PartitionedPumiTally(
        mesh, N, TallyConfig(device_mesh=dm, scoring=spec)
    )
    on = PartitionedPumiTally(
        mesh, N,
        TallyConfig(device_mesh=dm, scoring=spec, migrate_collective=True),
    )
    for t in (off, on):
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dest1.reshape(-1).copy(), fly.copy(), w,
                             energy=en)
    assert (np.asarray(off.flux) == np.asarray(on.flux)).all()
    assert (np.asarray(off.score_bank) == np.asarray(on.score_bank)).all()


# -- cap_frontier x migrate_collective composition (round 19) ---------------

def _frontier_campaign_arrays(N=1500, seed=3):
    """x-heavy seeded moves on the 2x1x1 box: the linear block order
    splits along x, so these crossings ride the migrate ring every
    round and the frontier slab actually fills."""
    rng = np.random.default_rng(seed)
    src = rng.uniform(0.05, 0.95, (N, 3)) * np.array([2.0, 1.0, 1.0])
    d1 = np.clip(src + rng.normal(scale=0.3, size=(N, 3)), 0.01, 0.99)
    d1[:, 0] = np.clip(src[:, 0] + rng.normal(scale=0.6, size=N),
                       0.02, 1.98)
    d2 = d1.copy()
    d2[:, 0] = np.clip(d1[:, 0] + rng.normal(scale=0.6, size=N),
                       0.02, 1.98)
    fly = (rng.uniform(size=N) > 0.1).astype(np.int8)
    w = rng.uniform(0.5, 2.0, N)
    return src, d1, d2, fly, w


def _run_frontier_campaign(mesh, N, cfg, arrays, energy=None):
    src, d1, d2, fly, w = arrays
    kw = {} if energy is None else {"energy": energy}
    t = PartitionedPumiTally(mesh, N, cfg)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, d1.reshape(-1).copy(), fly.copy(), w, **kw)
    t.MoveToNextLocation(None, d2.reshape(-1).copy(),
                         np.ones(N, np.int8), w, **kw)
    return t


def test_migrate_collective_composes_with_cap_frontier():
    """Round 19 lifted the construction refusal: the pair is accepted,
    and the phase cache key carries (cap_frontier, migrate_collective)
    AND placement — engines differing in any of the three never share
    a compiled program."""
    cfg = TallyConfig(migrate_collective=True, cap_frontier=64)
    assert cfg.migrate_collective and cfg.cap_frontier == 64

    mesh = build_box(1, 1, 1, 3, 3, 3)
    dm = make_device_mesh(8)

    def key_of(**kw):
        t = PartitionedPumiTally(mesh, 128,
                                 TallyConfig(device_mesh=dm, **kw))
        return t.engine._phase_key("phase", True)

    keys = [
        key_of(migrate_collective=True, cap_frontier=64),
        key_of(migrate_collective=False, cap_frontier=64),
        key_of(migrate_collective=True, cap_frontier=0),
        key_of(placement="pod_rcb", placement_hosts=(3, 5)),
        key_of(),
    ]
    assert len(set(keys)) == len(keys), keys
    composed = keys[0]
    assert 64 in composed and True in composed and "linear" in composed


@pytest.mark.parametrize("perm_mode",
                         ["arrays", "packed", "indirect", "sorted"])
def test_partitioned_engine_frontier_collective_parity(perm_mode):
    """cap_frontier + migrate_collective == cap_frontier on-chip,
    bitwise (element ids, positions, flux), across all 4 perm modes."""
    N = 1500
    mesh = build_box(2, 1, 1, 8, 4, 4)
    dm = make_device_mesh(8)
    arrays = _frontier_campaign_arrays(N)
    base = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=1024, walk_perm_mode=perm_mode,
    ), arrays)
    comp = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=1024, walk_perm_mode=perm_mode,
        migrate_collective=True,
    ), arrays)
    np.testing.assert_array_equal(base.elem_ids, comp.elem_ids)
    assert (np.asarray(base.positions) == np.asarray(comp.positions)).all()
    assert (np.asarray(base.flux) == np.asarray(comp.flux)).all()


def test_frontier_collective_slab_overflow_fallback():
    """A slab far smaller than the crossing front overflows every
    round; both engines take the lax.cond fallback to their
    full-capacity path (collective ring vs global scatter) and stay
    bitwise equal — the fallback is pinned end to end."""
    N = 1500
    mesh = build_box(2, 1, 1, 8, 4, 4)
    dm = make_device_mesh(8)
    arrays = _frontier_campaign_arrays(N)
    base = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=8), arrays)
    comp = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=8, migrate_collective=True), arrays)
    np.testing.assert_array_equal(base.elem_ids, comp.elem_ids)
    assert (np.asarray(base.positions) == np.asarray(comp.positions)).all()
    assert (np.asarray(base.flux) == np.asarray(comp.flux)).all()


def test_cap_frontier_zero_forces_full_capacity_collective():
    """cap_frontier=0 + migrate_collective rides the FULL-capacity
    collective every round: bit for bit the cap_frontier=0 scatter
    engine AND the plain migrate_collective engine."""
    N = 1500
    mesh = build_box(2, 1, 1, 8, 4, 4)
    dm = make_device_mesh(8)
    arrays = _frontier_campaign_arrays(N)
    z_on = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=0, migrate_collective=True), arrays)
    z_off = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=0), arrays)
    full = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, migrate_collective=True), arrays)
    for other in (z_off, full):
        np.testing.assert_array_equal(z_on.elem_ids, other.elem_ids)
        assert (np.asarray(z_on.positions)
                == np.asarray(other.positions)).all()
        assert (np.asarray(z_on.flux) == np.asarray(other.flux)).all()


def test_frontier_collective_parity_scoring():
    """The scoring lanes (sbin / factors) ride the cap_frontier ring in
    the same packed slab: score banks bitwise between the
    frontier-collective and on-chip frontier engines."""
    N = 1500
    mesh = build_box(2, 1, 1, 8, 4, 4)
    dm = make_device_mesh(8)
    arrays = _frontier_campaign_arrays(N)
    spec = ScoringSpec(filters=[EnergyFilter([0.0, 1.0, 2.0])],
                       scores=["flux", "events"])
    en = np.where(np.arange(N) % 2 == 0, 0.5, 1.5)
    base = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=1024, scoring=spec,
    ), arrays, energy=en)
    comp = _run_frontier_campaign(mesh, N, TallyConfig(
        device_mesh=dm, cap_frontier=1024, scoring=spec,
        migrate_collective=True,
    ), arrays, energy=en)
    assert (np.asarray(base.flux) == np.asarray(comp.flux)).all()
    assert (np.asarray(base.score_bank)
            == np.asarray(comp.score_bank)).all()


def test_launch_or_skip_reason_is_exact_marker(monkeypatch):
    """A gloo-less backend skips with a reason that is EXACTLY the
    DISTRIBUTED-UNAVAILABLE marker (one greppable token, details stay
    in the worker logs) and lands in the session skip census."""
    from tests import _distributed_driver as drv

    unavailable = drv.LaunchResult(
        True, f"{UNAVAILABLE_MARKER}: no gloo in this jaxlib", [77, 0],
        ["", ""],
    )
    monkeypatch.setattr(drv, "_PROBE", unavailable)
    before = len(drv.SKIPPED)
    with pytest.raises(pytest.skip.Exception) as exc:
        drv.launch_or_skip("partitioned")
    assert str(exc.value) == UNAVAILABLE_MARKER
    assert drv.SKIPPED[before:] == ["partitioned"]
    del drv.SKIPPED[before:]  # this was not a real cross-process skip


# -- front-door helpers -----------------------------------------------------

def test_init_distributed_validates_partial_identifiers():
    with pytest.raises(ValueError, match="num_processes"):
        init_distributed(coordinator_address="127.0.0.1:1234")
    with pytest.raises(ValueError, match="coordinator_address"):
        init_distributed(num_processes=2, process_id=0)
    with pytest.raises(ValueError, match="process_id must be in"):
        init_distributed("127.0.0.1:1234", 2, 2)
    with pytest.raises(ValueError, match="num_processes must be"):
        init_distributed("127.0.0.1:1234", 0, 0)


def test_fetch_global_passthrough():
    a = np.arange(6.0)
    assert fetch_global(a) is a
    j = jnp.arange(6.0)
    np.testing.assert_array_equal(fetch_global(j), a)


def test_modeled_migration_collective_bytes():
    rng = np.random.default_rng(1)
    st = _mkstate(rng, 80, 7, np.full(80, -1))
    fcols, icols = state_pack_columns(st)
    # x(3) + dest(3) + w(1) + sfac(3) floats; lelem/pending/pid/sbin
    # int32 + alive/done/exited/lost bool + fly int8 — 9 int lanes.
    assert (fcols, icols) == (10, 9)
    got = modeled_migration_collective_bytes(80, 8, fcols, icols)
    n_loc = 80 // 8
    expect = 7 * n_loc * 4 + 7 * (n_loc * (10 * 8 + 9 * 4 + 4))
    assert got == expect


# -- per-host service workers: the router -----------------------------------

def test_session_router_bitwise_and_homing():
    """Two in-process workers behind a SessionRouter: sessions spread
    least-loaded, honor explicit home hints, and every forwarded
    campaign's flux is bitwise the direct facade."""
    from pumiumtally_tpu import PumiTally, TallyService
    from pumiumtally_tpu.service import SessionRouter, SocketFrontend
    from pumiumtally_tpu.service.server import _decode_array, _encode_array

    mesh = build_box(1, 1, 1, 4, 4, 4)
    N = 500
    svcs = [TallyService(), TallyService()]
    fes = [SocketFrontend(s, default_mesh=mesh, default_particles=N)
           for s in svcs]
    for fe in fes:
        fe.start()
    router = SessionRouter([(fe.host, fe.port) for fe in fes])
    router.start()
    conn = f = None
    try:
        conn = socket.create_connection((router.host, router.port))
        f = conn.makefile("rwb")

        def rpc(**req):
            f.write(json.dumps(req).encode() + b"\n")
            f.flush()
            return json.loads(f.readline().decode())

        r = rpc(op="ping")
        assert r["ok"] and r["backends"] == 2, r

        r1 = rpc(op="open", facade="mono", num_particles=N)
        r2 = rpc(op="open", facade="mono", num_particles=N)
        assert r1["ok"] and r2["ok"], (r1, r2)
        assert r1["home"] != r2["home"], (r1, r2)  # least-loaded spread
        r3 = rpc(op="open", facade="mono", num_particles=N, home=0)
        assert r3["ok"] and r3["home"] == 0, r3  # explicit home hint

        rng = np.random.default_rng(5)
        src = rng.uniform(0.1, 0.9, (N, 3))
        dst = rng.uniform(0.1, 0.9, (N, 3))
        ref = PumiTally(mesh, N, TallyConfig(check_found_all=False))
        ref.CopyInitialPosition(src.reshape(-1).copy())
        ref.MoveToNextLocation(None, dst.reshape(-1).copy())
        for sid in (r1["session"], r2["session"]):
            assert rpc(op="source", session=sid,
                       positions=_encode_array(src.reshape(-1)))["ok"]
            assert rpc(op="move", session=sid,
                       dests=_encode_array(dst.reshape(-1)))["ok"]
            r = rpc(op="flux", session=sid)
            assert r["ok"], r
            flux = _decode_array(r["flux"], np.dtype("<f8"))
            np.testing.assert_array_equal(flux, np.asarray(ref.flux))

        r = rpc(op="flux", session="notasession")
        assert not r["ok"] and "unknown session" in r["message"], r
        assert rpc(op="close", session=r1["session"])["ok"]
    finally:
        if f is not None:
            f.close()
        if conn is not None:
            conn.close()
        router.stop()
        for fe in fes:
            fe.stop()
        for s in svcs:
            s.shutdown()


def test_router_backlogged_worker_stops_winning_open():
    """Least-loaded placement reads LIVE load over the ping channel
    (round 20): a worker pinned behind queued particle cost loses the
    next open to a worker with MORE sessions but an empty queue — the
    session-count tiebreak only applies at equal cost. The router's
    ping aggregates the same telemetry fleet-wide."""
    from pumiumtally_tpu import PumiTally, TallyService
    from pumiumtally_tpu.service import SessionRouter, SocketFrontend

    import threading

    mesh = build_box(1, 1, 1, 3, 3, 3)
    N = 200
    # Worker 0: ONE session whose worker thread is parked on a
    # blocking call op, with transport cost queued behind it. Worker
    # 1: TWO idle sessions. Count-based placement would pick worker 0;
    # cost-based must not.
    svc0 = TallyService()
    svc1 = TallyService()
    rng = np.random.default_rng(9)
    unstall = threading.Event()
    h = svc0.open_session(PumiTally(mesh, N,
                                    TallyConfig(check_found_all=False)),
                          session_id="busy", max_queue=8)
    h._call("stall", lambda t: unstall.wait(timeout=300))
    h.copy_initial_position(rng.uniform(0.1, 0.9, N * 3))
    for _ in range(2):
        h.move(None, rng.uniform(0.1, 0.9, N * 3))
    for sid in ("idle_a", "idle_b"):
        svc1.open_session(PumiTally(mesh, N,
                                    TallyConfig(check_found_all=False)),
                          session_id=sid, max_queue=8)
    fes = [SocketFrontend(s, default_mesh=mesh, default_particles=N)
           for s in (svc0, svc1)]
    for fe in fes:
        fe.start()
    router = SessionRouter([(fe.host, fe.port) for fe in fes])
    router.start()
    conn = f = None
    try:
        conn = socket.create_connection((router.host, router.port))
        f = conn.makefile("rwb")

        def rpc(**req):
            f.write(json.dumps(req).encode() + b"\n")
            f.flush()
            return json.loads(f.readline().decode())

        r = rpc(op="ping")
        assert r["ok"] and r["backends"] == 2, r
        assert r["load"]["sessions"] == 3, r
        assert r["load"]["queued_cost"] == 3 * N, r
        assert r["per_backend"][0]["queued_cost"] == 3 * N, r
        assert r["per_backend"][1]["queued_cost"] == 0, r

        r = rpc(op="open", facade="mono", num_particles=N)
        assert r["ok"], r
        assert r["home"] == 1, r  # the backlogged worker lost the open
    finally:
        unstall.set()
        if f is not None:
            f.close()
        if conn is not None:
            conn.close()
        router.stop()
        for fe in fes:
            fe.stop()
        svc1.shutdown()
        svc0.shutdown(drain=False)


def test_cli_route_forwards_and_sigterm_exit(tmp_path):
    """``pumiumtally route`` fronts a ``serve`` worker: a session opened
    through the router serves flux, and BOTH processes exit 0 on
    SIGTERM (the preemption-safe contract ``serve`` already pins)."""
    import signal
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def start(*argv):
        return subprocess.Popen(
            [sys.executable, "-m", "pumiumtally_tpu.cli", *argv],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(tmp_path), env=env,
        )

    worker = start("serve", "--port", "0")
    router = None
    try:
        waddr = json.loads(worker.stdout.readline())["serving"]
        router = start("route", "--backend",
                       f"{waddr['host']}:{waddr['port']}", "--port", "0")
        raddr = json.loads(router.stdout.readline())["routing"]
        assert raddr["backends"] == 1, raddr
        with socket.create_connection(
            (raddr["host"], raddr["port"]), timeout=300
        ) as conn:
            f = conn.makefile("rwb")

            def rpc(**req):
                f.write(json.dumps(req).encode() + b"\n")
                f.flush()
                return json.loads(f.readline().decode())

            r = rpc(op="open", facade="mono", num_particles=16,
                    mesh={"box": [1, 1, 1, 2, 2, 2]})
            assert r["ok"] and r["home"] == 0, r
            r2 = rpc(op="flux", session=r["session"])
            assert r2["ok"], r2
        router.send_signal(signal.SIGTERM)
        assert router.wait(timeout=120) == 0, router.stderr.read()[-2000:]
        worker.send_signal(signal.SIGTERM)
        assert worker.wait(timeout=120) == 0, worker.stderr.read()[-2000:]
    finally:
        for proc in (router, worker):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)


# -- the real 2-process job (slow tier; SKIPs without gloo) -----------------

@pytest.mark.slow
@pytest.mark.parametrize("arm", ARMS)
def test_two_process_bitwise_parity(arm, tmp_path):
    """Two OS processes x 4 virtual devices vs ONE process x 8 virtual
    devices at the same global shapes: fetched global flux, positions,
    element ids (and score bank when armed) must match BITWISE."""
    out = tmp_path / f"{arm}.npz"
    launch_or_skip(arm, out)
    assert out.exists(), "worker 0 did not write its results"
    got = np.load(out)
    ref_t = build_tally(arm, make_device_mesh(8))
    run_campaign(ref_t, arm)
    ref = collect(ref_t, arm)
    assert sorted(got.files) == sorted(ref)
    for k in sorted(ref):
        np.testing.assert_array_equal(got[k], np.asarray(ref[k]),
                                      err_msg=k)
