"""Pin the driver entrypoints (__graft_entry__.py).

The multichip dryrun is a shipped signal: the round driver executes it
against a virtual CPU mesh to validate the framework's multi-chip
sharding without real chips. Two rounds were lost to environmental
hangs around it, so the self-provisioning path (re-exec into a CPU
subprocess with the device-tunnel env stripped) is itself under test.
"""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_self_provisions_cpu_mesh():
    """dryrun_multichip must succeed from an environment that neither
    selects the CPU platform nor provides enough devices — the driver's
    situation — by re-executing itself onto a virtual CPU mesh. The
    tunnel env var is set to a value that would hang if any child
    dialed it; the 240 s cap (vs the entry script's own 300 s child
    budget) doubles as the wedge-proofing check."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # not "cpu": forces the subprocess path
    env.pop("XLA_FLAGS", None)
    # A blackhole tunnel address: any child that fails to strip this
    # and dials it hangs, tripping the timeout below — the regression
    # class that lost two rounds.
    env["PALLAS_AXON_POOL_IPS"] = "10.255.255.1"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(2)"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout


def test_entry_returns_jittable_step():
    """entry() must yield (fn, args) that jit-compiles and runs on the
    test backend (the driver compile-checks the same contract on a real
    chip). Repo root is already importable (tests/conftest.py)."""
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    x2, elem2, flux2, ok = out
    assert x2.shape == args[1].shape  # positions keep their shape
    assert float(flux2.sum()) > 0.0
