"""Pin the driver entrypoints (__graft_entry__.py).

The multichip dryrun is a shipped signal: the round driver executes it
against a virtual CPU mesh to validate the framework's multi-chip
sharding without real chips. Two rounds were lost to environmental
hangs around it, so the self-provisioning path (re-exec into a CPU
subprocess with the device-tunnel env stripped) is itself under test.
"""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dryrun_multichip_survives_sitecustomize_clobber(tmp_path):
    """The driver's real environment: it sets JAX_PLATFORMS=cpu and an
    8-device XLA_FLAGS — so the env LOOKS local-safe — but an
    interpreter-startup hook (axon sitecustomize on PYTHONPATH) has
    already imported jax and called
    ``jax.config.update("jax_platforms", "axon,cpu")``, overriding the
    env (the register/pjrt.py pattern). Rounds 2-4 died here: the
    in-process branch trusted the env and dialed the device tunnel.
    dryrun_multichip must detect the repointed config, fall to the
    scrubbed subprocess, and succeed."""
    hook_dir = tmp_path / "fake_axon_site"
    hook_dir.mkdir()
    (hook_dir / "sitecustomize.py").write_text(
        # Faithful to the real hook: it does NOT touch the env var (the
        # driver's JAX_PLATFORMS=cpu stays in place) — it imports jax
        # and repoints jax.config, which is what wins at backend init.
        "import os\n"
        "if os.environ.get('PALLAS_AXON_POOL_IPS'):\n"
        "    import jax\n"
        "    try:\n"
        "        jax.config.update('jax_platforms', 'axon,cpu')\n"
        "    except Exception:\n"
        "        pass\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(hook_dir)
    env["JAX_PLATFORMS"] = "cpu"  # the driver's (clobbered) intent
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # Blackhole tunnel: with no registered 'axon' plugin the clobbered
    # config fails fast instead of hanging, which is still the red
    # signal — the old code took the in-process branch and died there.
    env["PALLAS_AXON_POOL_IPS"] = "10.255.255.1"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(2)"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout
    # The detection must have routed around the poisoned in-process jax.
    assert "spawning CPU-mesh child" in proc.stdout


@pytest.mark.slow
def test_dryrun_multichip_self_provisions_cpu_mesh():
    """dryrun_multichip must succeed from an environment that neither
    selects the CPU platform nor provides enough devices — the driver's
    situation — by re-executing itself onto a virtual CPU mesh. The
    tunnel env var is set to a value that would hang if any child
    dialed it; the 240 s cap (comfortably above the entry script's own
    120 s child fuse, so the script's diagnostic fires first) doubles
    as the wedge-proofing check."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # not "cpu": forces the subprocess path
    env.pop("XLA_FLAGS", None)
    # A blackhole tunnel address: any child that fails to strip this
    # and dials it hangs, tripping the timeout below — the regression
    # class that lost two rounds.
    env["PALLAS_AXON_POOL_IPS"] = "10.255.255.1"
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(2)"],
        cwd=_ROOT, env=env, capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout


def test_entry_returns_jittable_step():
    """entry() must yield (fn, args) that jit-compiles and runs on the
    test backend (the driver compile-checks the same contract on a real
    chip). Repo root is already importable (tests/conftest.py)."""
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    # round 9: the step returns the per-particle done mask + the final
    # ray coordinate instead of a pre-reduced scalar (sentinel ladder
    # inputs) — same physics outputs in front.
    x2, elem2, flux2, done, _s = out
    assert x2.shape == args[1].shape  # positions keep their shape
    assert float(flux2.sum()) > 0.0
    assert done.shape == (args[1].shape[0],)
