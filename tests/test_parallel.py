"""Multi-chip (8 virtual device) tests for the sharded walk.

The reference cannot test its MPI mode without a cluster (SURVEY.md §4:
"Multi-node is not tested"); here the same oracle suite runs sharded
over an 8-device CPU mesh, and the sharded flux must match the
single-device flux bitwise (deterministic psum replaces
Kokkos::atomic_add).
"""

import jax
import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.parallel import make_device_mesh

NUM = 5  # deliberately not divisible by 8: exercises capacity padding
TOL = 1e-8


@pytest.fixture()
def dev_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_device_mesh(8)


def _flat(points):
    return np.ascontiguousarray(np.asarray(points, dtype=np.float64).reshape(-1))


def _run_oracle(tally):
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    tally.CopyInitialPosition(_flat(init), 3 * NUM)
    dests = np.tile([1.2, 0.4, 0.5], (NUM, 1))
    tally.MoveToNextLocation(
        _flat(init), _flat(dests), np.ones(NUM, np.int8), np.ones(NUM), 3 * NUM
    )
    return tally


def test_sharded_oracle_sequence(dev_mesh):
    tally = _run_oracle(
        PumiTally(build_box(1, 1, 1, 1, 1, 1), NUM,
                  TallyConfig(device_mesh=dev_mesh))
    )
    np.testing.assert_array_equal(tally.elem_ids, np.full(NUM, 4))
    np.testing.assert_allclose(
        tally.positions, np.tile([1.0, 0.4, 0.5], (NUM, 1)), atol=TOL
    )
    np.testing.assert_allclose(
        np.asarray(tally.flux),
        [0.0, 0.0, 0.3 * NUM, 0.1 * NUM, 0.5 * NUM, 0.0],
        atol=TOL,
    )


def test_sharded_matches_single_device(dev_mesh):
    """Sharded flux agrees with single-device to fp tolerance (the
    summation order differs across topologies, so exact identity is only
    required run-to-run — see test_sharded_runs_are_deterministic)."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 64
    rng = np.random.default_rng(42)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = rng.uniform(-0.1, 1.1, (n, 3))
    fly = (rng.uniform(size=n) < 0.8).astype(np.int8)
    w = rng.uniform(0.5, 2.0, n)

    results = []
    for cfg in (TallyConfig(), TallyConfig(device_mesh=dev_mesh)):
        t = PumiTally(mesh, n, cfg)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(
            src.reshape(-1).copy(), dst.reshape(-1).copy(), fly.copy(), w.copy()
        )
        results.append(
            (np.asarray(t.flux), t.elem_ids.copy(), t.positions.copy())
        )
    (f0, e0, x0), (f1, e1, x1) = results
    np.testing.assert_allclose(f0, f1, rtol=1e-13, atol=1e-15)
    np.testing.assert_array_equal(e0, e1)  # walk itself is per-particle exact
    np.testing.assert_array_equal(x0, x1)


def test_sharded_runs_are_deterministic(dev_mesh):
    """Two identical sharded runs are BITWISE identical — the property
    the reference cannot offer (Kokkos::atomic_add ordering races,
    reference PumiTallyImpl.cpp:376; SURVEY.md §5 'race detection')."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 64
    rng = np.random.default_rng(3)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = rng.uniform(-0.1, 1.1, (n, 3))

    fluxes = []
    for _ in range(2):
        t = PumiTally(mesh, n, TallyConfig(device_mesh=dev_mesh))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(
            src.reshape(-1).copy(), dst.reshape(-1).copy(),
            np.ones(n, np.int8), np.ones(n),
        )
        fluxes.append(np.asarray(t.flux))
    np.testing.assert_array_equal(fluxes[0], fluxes[1])


def test_sharded_conservation(dev_mesh):
    """sum(flux) == total in-box track length, sharded over 8 devices."""
    mesh = build_box(1, 1, 1, 5, 5, 5)
    n = 1000
    rng = np.random.default_rng(7)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = rng.uniform(0.0, 1.0, (n, 3))
    t = PumiTally(mesh, n, TallyConfig(device_mesh=dev_mesh))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(
        src.reshape(-1).copy(), dst.reshape(-1).copy(),
        np.ones(n, np.int8), np.ones(n),
    )
    expected = np.sum(np.linalg.norm(dst - src, axis=1))
    np.testing.assert_allclose(float(np.sum(np.asarray(t.flux))), expected,
                               rtol=1e-12)


def test_initialize_distributed_wires_the_pod_mesh(monkeypatch):
    """`initialize_distributed` (the jax.distributed analogue of the
    reference's pumipic::Library MPI_Init, PumiTallyImpl.cpp:238-241)
    must join the distributed job THEN build the mesh over every device
    in the pod. jax.distributed needs a real multi-host job, so the
    join call is intercepted; everything else runs for real."""
    import pumiumtally_tpu.parallel.device as device

    calls = {}

    def fake_initialize(coordinator_address=None, num_processes=None,
                        process_id=None):
        calls["args"] = (coordinator_address, num_processes, process_id)

    monkeypatch.setattr(
        device.jax.distributed, "initialize", fake_initialize
    )
    mesh = device.initialize_distributed(
        coordinator_address="10.0.0.1:8476", num_processes=1, process_id=0,
    )
    assert calls["args"] == ("10.0.0.1:8476", 1, 0)
    assert mesh.axis_names == ("dp",)
    assert mesh.devices.size == len(device.jax.devices())
