"""Filtered multi-score tallies (pumiumtally_tpu/scoring): spec/filter
validation, the scoring-off and scoring-on bitwise parity contracts on
every engine, bin-partition telescoping, score semantics (heating /
events), out-of-range policy, checkpoint round-trips, the VTK payload,
and the scoring statistics lanes.
"""

import numpy as np
import pytest

from pumiumtally_tpu import (
    EnergyFilter,
    PartitionedPumiTally,
    PumiTally,
    ScoringSpec,
    StreamingPartitionedTally,
    StreamingTally,
    TallyConfig,
    TimeFilter,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh

N = 240
MESH_ARGS = (1, 1, 1, 4, 4, 4)
E = 6 * 4**3

ENGINE_NAMES = (
    "monolithic", "sharded", "streaming", "partitioned",
    "streaming_partitioned",
)


def _spec2():
    """The canonical 2-energy-bin, 3-score spec of this suite."""
    return ScoringSpec(
        filters=[EnergyFilter([0.0, 1.0, 2.0])],
        scores=["flux", "heating", "events"],
    )


def _make_engine(name: str, spec, **cfg_kw):
    cfg = lambda **kw: TallyConfig(scoring=spec, **cfg_kw, **kw)
    mesh = build_box(*MESH_ARGS)
    if name == "monolithic":
        return PumiTally(mesh, N, cfg())
    if name == "sharded":
        return PumiTally(mesh, N, cfg(device_mesh=make_device_mesh(2)))
    if name == "streaming":
        return StreamingTally(mesh, N, chunk_size=120, config=cfg())
    if name == "partitioned":
        return PartitionedPumiTally(
            mesh, N,
            cfg(device_mesh=make_device_mesh(4), capacity_factor=4.0),
        )
    return StreamingPartitionedTally(
        mesh, N, chunk_size=120,
        config=cfg(device_mesh=make_device_mesh(4), capacity_factor=4.0),
    )


def _corridor_workload(rng, moves: int = 2):
    """Disjoint-corridor batches: group A (energies in bin 0) transports
    strictly inside x < 0.5, group B (bin 1) strictly inside x > 0.5 —
    a cell-boundary plane of the 4^3 box, so every ELEMENT only ever
    sees one bin's particles. That single-bin-per-element structure is
    what makes the bin-partition telescoping claim BITWISE (mixed-bin
    elements would reassociate the scatter sums)."""
    half = N // 2
    def pts():
        p = np.empty((N, 3))
        p[:half] = rng.uniform(
            [0.05, 0.05, 0.05], [0.45, 0.95, 0.95], (half, 3)
        )
        p[half:] = rng.uniform(
            [0.55, 0.05, 0.05], [0.95, 0.95, 0.95], (N - half, 3)
        )
        return p
    energy = np.where(np.arange(N) < half, 0.5, 1.5)
    return pts(), [pts() for _ in range(moves)], energy


def _drive(t, src, dests, **move_kw):
    t.CopyInitialPosition(src.reshape(-1).copy())
    for d in dests:
        t.MoveToNextLocation(None, d.reshape(-1).copy(), **move_kw)
    return t


# ---------------------------------------------------------------------------
# Spec / filter validation
# ---------------------------------------------------------------------------

def test_filter_validation():
    with pytest.raises(ValueError, match="at least 2 edges"):
        EnergyFilter([1.0])
    with pytest.raises(ValueError, match="strictly increasing"):
        EnergyFilter([0.0, 1.0, 1.0])
    with pytest.raises(ValueError, match="finite"):
        TimeFilter([0.0, np.inf])


def test_spec_validation():
    with pytest.raises(ValueError, match="unknown score"):
        ScoringSpec(scores=["flux", "dose"])
    with pytest.raises(ValueError, match="duplicate"):
        ScoringSpec(scores=["flux", "flux"])
    with pytest.raises(ValueError, match="at least one score"):
        ScoringSpec(scores=[])
    with pytest.raises(ValueError, match="overflow"):
        ScoringSpec(overflow="wrap")
    with pytest.raises(ValueError, match="one EnergyFilter"):
        ScoringSpec(filters=[EnergyFilter([0, 1]), EnergyFilter([0, 1])])
    with pytest.raises(ValueError, match="EnergyFilter/TimeFilter"):
        ScoringSpec(filters=[object()])
    with pytest.raises(ValueError, match="ScoringSpec"):
        TallyConfig(scoring=0.5)
    spec = ScoringSpec(
        filters=[EnergyFilter([0, 1, 2, 3]), TimeFilter([0, 1, 2])],
        scores=["flux", "events"],
    )
    assert spec.n_bins == 6 and spec.n_scores == 2
    assert spec.needs_energy and spec.needs_time
    # Edge VALUES never appear in the static identity.
    assert spec.static_key() == (("flux", "events"), "drop", 3, 2)


def test_scoring_disabled_surface():
    t = PumiTally(build_box(*MESH_ARGS), N)
    with pytest.raises(RuntimeError, match="scoring.ScoringSpec"):
        t.score_bank
    with pytest.raises(RuntimeError, match="scoring.ScoringSpec"):
        t.score_array()
    rng = np.random.default_rng(0)
    src, dests, en = _corridor_workload(rng, 1)
    t.CopyInitialPosition(src.reshape(-1).copy())
    with pytest.raises(ValueError, match="energy=/time= require"):
        t.MoveToNextLocation(None, dests[0].reshape(-1).copy(), energy=en)


# ---------------------------------------------------------------------------
# The parity contracts + bin-partition telescoping, on every engine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ENGINE_NAMES)
def test_scoring_parity_and_telescoping(name):
    """The acceptance contract on every engine: scoring-ON leaves
    flux, positions and element ids BITWISE identical to the
    scoring-off run (the flux scatter is untouched), and on the
    single-bin-per-element corridor workload the 2-bin flux lanes sum
    to the unfiltered flux lane BITWISE (bin-partition telescoping)."""
    rng = np.random.default_rng(7)
    src, dests, en = _corridor_workload(rng, 2)
    t_off = _drive(_make_engine(name, None), src, dests)
    t_on = _drive(_make_engine(name, _spec2()), src, dests, energy=en)
    f_off = np.asarray(t_off.flux)
    np.testing.assert_array_equal(np.asarray(t_on.flux), f_off)
    np.testing.assert_array_equal(t_on.positions, t_off.positions)
    np.testing.assert_array_equal(t_on.elem_ids, t_off.elem_ids)
    arr = np.asarray(t_on.score_bank).reshape(E, 2, 3)
    # Telescoping: flux lanes over bins == the flux lane, bitwise.
    np.testing.assert_array_equal(arr[:, :, 0].sum(axis=1), f_off)
    # Both bins genuinely populated (the telescoping is not vacuous).
    assert arr[:, 0, 0].sum() > 0 and arr[:, 1, 0].sum() > 0


def test_scoring_off_constructs_nothing():
    """Scoring-off allocates no runtime, no bank, no extra state keys
    (partitioned), and the checkpoint payload carries no scoring keys
    — today's format, byte-compatible."""
    from pumiumtally_tpu.utils.checkpoint import collect_tally_state

    t = _make_engine("partitioned", None)
    assert t._scoring is None and t._score_bank is None
    assert "sbin" not in t.engine.state and t.engine.score_padded is None
    rng = np.random.default_rng(1)
    src, dests, _ = _corridor_workload(rng, 1)
    _drive(t, src, dests)
    z = collect_tally_state(t)
    assert not [k for k in z if "score" in k or "sbin" in k or "sfac" in k]


# ---------------------------------------------------------------------------
# Score semantics
# ---------------------------------------------------------------------------

def test_heating_is_energy_scaled_flux_bitwise():
    """heating = track x energy: with every particle at energy 2.0
    (a power of two — exact float scaling), the heating lane is
    BITWISE 2x the flux lane."""
    spec = ScoringSpec(filters=[EnergyFilter([0.0, 4.0])],
                       scores=["flux", "heating"])
    rng = np.random.default_rng(9)
    src, dests, _ = _corridor_workload(rng, 2)
    t = _drive(_make_engine("monolithic", spec), src, dests,
               energy=np.full(N, 2.0))
    arr = np.asarray(t.score_array())  # [E,1,2]
    np.testing.assert_array_equal(arr[:, 0, 1], 2.0 * arr[:, 0, 0])
    np.testing.assert_array_equal(arr[:, 0, 0], np.asarray(t.flux))


@pytest.mark.parametrize("name", [n for n in ENGINE_NAMES
                                  if n != "monolithic"])
def test_events_exact_across_engines(name):
    """Face-crossing counts are exact small integers, so every engine
    must agree EXACTLY with the monolithic reference — a partition-face
    pause commits its crossing exactly once across the migration."""
    rng = np.random.default_rng(11)
    src, dests, en = _corridor_workload(rng, 2)
    base = _drive(_make_engine("monolithic", _spec2()), src, dests,
                  energy=en)
    t = _drive(_make_engine(name, _spec2()), src, dests, energy=en)
    ev_base = np.asarray(base.score_array())[:, :, 2]
    ev = np.asarray(t.score_array())[:, :, 2]
    assert np.array_equal(ev, np.round(ev)) and ev.sum() > 0
    np.testing.assert_array_equal(ev, ev_base)


def test_time_filter_and_product_binning():
    """Energy x time filters bin into the product layout (time-minor):
    a particle at (e-bin i, t-bin j) scores lane i*n_tbins + j."""
    spec = ScoringSpec(
        filters=[EnergyFilter([0.0, 1.0, 2.0]), TimeFilter([0.0, 1.0, 2.0])],
        scores=["flux"],
    )
    rng = np.random.default_rng(13)
    src, dests, en = _corridor_workload(rng, 1)
    tm = np.where(np.arange(N) % 2 == 0, 0.5, 1.5)
    t = _drive(_make_engine("monolithic", spec), src, dests,
               energy=en, time=tm)
    arr = np.asarray(t.score_array())  # [E, 4, 1]
    half = N // 2
    # Group A (bin-0 energy) has both time bins -> lanes 0 and 1;
    # group B (bin-1 energy) -> lanes 2 and 3. All four populated,
    # and the total telescopes to the flux (allclose: time bins mix
    # within elements).
    for b in range(4):
        assert arr[:, b, 0].sum() > 0, b
    np.testing.assert_allclose(
        arr[:, :, 0].sum(axis=1), np.asarray(t.flux), rtol=1e-12
    )
    # time-minor: the x<0.5 corridor's elements hold lanes 0/1 only.
    a_elems = arr[:, 0, 0] + arr[:, 1, 0] > 0
    assert np.all(arr[a_elems][:, 2:, 0] == 0)


# ---------------------------------------------------------------------------
# Out-of-range policy (drop vs clamp), on every engine
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ENGINE_NAMES)
def test_overflow_policy_drop_vs_clamp(name):
    """Energies below edges[0] / at-or-above edges[-1]: ``drop``
    scores them into NO bin (deterministically discarded by the
    scatter's drop mode — the flux lane is untouched either way);
    ``clamp`` lands them in the nearest edge bin. One knob, pinned on
    every facade."""
    rng = np.random.default_rng(17)
    src, dests, _ = _corridor_workload(rng, 1)
    en = np.where(np.arange(N) < N // 2, -3.0, 9.0)  # all out of range

    def spec(policy):
        return ScoringSpec(filters=[EnergyFilter([0.0, 1.0, 2.0])],
                           scores=["flux"], overflow=policy)

    t_drop = _drive(_make_engine(name, spec("drop")), src, dests,
                    energy=en)
    flux = np.asarray(t_drop.flux)
    assert flux.sum() > 0  # transport happened
    assert np.asarray(t_drop.score_bank).sum() == 0.0  # nothing scored
    t_clamp = _drive(_make_engine(name, spec("clamp")), src, dests,
                     energy=en)
    arr = np.asarray(t_clamp.score_bank).reshape(E, 2, 1)
    # Below-range -> bin 0, above-range -> bin 1; single-bin elements
    # (the corridors) make the telescoping bitwise again.
    assert arr[:, 0, 0].sum() > 0 and arr[:, 1, 0].sum() > 0
    np.testing.assert_array_equal(arr.sum(axis=(1, 2)), flux)


# ---------------------------------------------------------------------------
# Attribute validation (narrow prevalidator arm)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ("monolithic", "streaming"))
def test_energy_time_validation_names_argument(name):
    t = _make_engine(name, _spec2())
    rng = np.random.default_rng(19)
    src, dests, en = _corridor_workload(rng, 1)
    t.CopyInitialPosition(src.reshape(-1).copy())
    d = dests[0].reshape(-1)
    with pytest.raises(ValueError, match="pass energy="):
        t.MoveToNextLocation(None, d.copy())
    with pytest.raises(ValueError, match="energy buffer has 3 values"):
        t.MoveToNextLocation(None, d.copy(), energy=np.ones(3))
    bad = en.copy()
    bad[7] = np.nan
    with pytest.raises(ValueError, match="energy contains 1 non-finite"):
        t.MoveToNextLocation(None, d.copy(), energy=bad)
    with pytest.raises(ValueError, match="no TimeFilter"):
        t.MoveToNextLocation(None, d.copy(), energy=en, time=np.ones(N))
    # The refused moves left the engine clean: a good move still runs.
    t.MoveToNextLocation(None, d.copy(), energy=en)
    assert np.asarray(t.score_bank).sum() > 0


# ---------------------------------------------------------------------------
# Checkpoint round-trips
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ENGINE_NAMES)
def test_checkpoint_roundtrip_scoring_bitwise(name, tmp_path):
    """Mid-campaign save -> restore into a fresh same-configured
    engine -> continue: final flux AND scoring lanes bitwise-equal to
    the uninterrupted run, on every facade."""
    from pumiumtally_tpu.utils.checkpoint import (
        load_tally_state,
        save_tally_state,
    )

    rng = np.random.default_rng(23)
    src, dests, en = _corridor_workload(rng, 4)
    ref = _drive(_make_engine(name, _spec2()), src, dests, energy=en)

    t1 = _make_engine(name, _spec2())
    t1.CopyInitialPosition(src.reshape(-1).copy())
    for d in dests[:2]:
        t1.MoveToNextLocation(None, d.reshape(-1).copy(), energy=en)
    path = str(tmp_path / f"score_{name}.npz")
    save_tally_state(t1, path)

    t2 = _make_engine(name, _spec2())
    load_tally_state(t2, path)
    for d in dests[2:]:
        t2.MoveToNextLocation(None, d.reshape(-1).copy(), energy=en)
    np.testing.assert_array_equal(
        np.asarray(t2.flux), np.asarray(ref.flux)
    )
    np.testing.assert_array_equal(
        np.asarray(t2.score_bank), np.asarray(ref.score_bank)
    )


def test_checkpoint_scoring_version_skew(tmp_path):
    """Both skew directions: a scoring save restored into a
    scoring-less target drops the lanes with a warning (flux intact);
    a scoring-less save restored into a scoring-armed target zeroes
    the bank (scoring starts at the restore point)."""
    from pumiumtally_tpu.utils.checkpoint import (
        load_tally_state,
        save_tally_state,
    )

    rng = np.random.default_rng(29)
    src, dests, en = _corridor_workload(rng, 1)
    t_on = _drive(_make_engine("monolithic", _spec2()), src, dests,
                  energy=en)
    p_on = str(tmp_path / "on.npz")
    save_tally_state(t_on, p_on)
    t_off = _make_engine("monolithic", None)
    with pytest.warns(UserWarning, match="scoring lanes"):
        load_tally_state(t_off, p_on)
    np.testing.assert_array_equal(
        np.asarray(t_off.flux), np.asarray(t_on.flux)
    )

    t_plain = _drive(_make_engine("monolithic", None), src, dests)
    p_off = str(tmp_path / "off.npz")
    save_tally_state(t_plain, p_off)
    t_armed = _make_engine("monolithic", _spec2())
    load_tally_state(t_armed, p_off)
    np.testing.assert_array_equal(
        np.asarray(t_armed.flux), np.asarray(t_plain.flux)
    )
    assert np.asarray(t_armed.score_bank).sum() == 0.0


# ---------------------------------------------------------------------------
# VTK payload
# ---------------------------------------------------------------------------

def test_write_tally_results_score_arrays(tmp_path):
    """<score>_bin<k> cell arrays beside flux+volume, every lane
    volume-normalized like flux — so the flux lanes' sum reproduces
    the written flux array bitwise on the corridor workload."""
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars

    rng = np.random.default_rng(31)
    src, dests, en = _corridor_workload(rng, 2)
    t = _drive(_make_engine("monolithic", _spec2()), src, dests,
               energy=en)
    out = str(tmp_path / "scored.vtk")
    t.WriteTallyResults(out)
    flux = read_vtk_cell_scalars(out, "flux")
    arr = np.asarray(t.score_array())
    vol = np.asarray(t.mesh.volumes)
    total = np.zeros(E)
    for b in range(2):
        for j, s in enumerate(("flux", "heating", "events")):
            got = read_vtk_cell_scalars(out, f"{s}_bin{b}")
            np.testing.assert_array_equal(got, arr[:, b, j] / vol)
        total += read_vtk_cell_scalars(out, f"flux_bin{b}")
    np.testing.assert_array_equal(total, flux)


def test_write_pvtu_score_arrays(tmp_path):
    """The partitioned .pvtu path splits the scoring arrays per piece
    like every other cell array."""
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars

    rng = np.random.default_rng(37)
    src, dests, en = _corridor_workload(rng, 1)
    t = _drive(_make_engine("partitioned", _spec2()), src, dests,
               energy=en)
    out = str(tmp_path / "scored.pvtu")
    t.WriteTallyResults(out)
    owner = t.engine.part.owner // t.engine.blocks_per_chip
    arr = np.asarray(t.score_array())
    vol = np.asarray(t.mesh.volumes)
    for r in range(4):
        sel = np.flatnonzero(owner == r)
        piece = str(tmp_path / f"scored_p{r}.vtu")
        np.testing.assert_array_equal(
            read_vtk_cell_scalars(piece, "flux_bin1"),
            (arr[:, 1, 0] / vol)[sel],
        )


def test_scoring_off_payload_unchanged(tmp_path):
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars

    rng = np.random.default_rng(41)
    src, dests, _ = _corridor_workload(rng, 1)
    t = _drive(_make_engine("monolithic", None), src, dests)
    out = str(tmp_path / "plain.vtk")
    t.WriteTallyResults(out)
    with pytest.raises(KeyError):
        read_vtk_cell_scalars(out, "flux_bin0")


# ---------------------------------------------------------------------------
# Scoring statistics lanes (stats accumulators gain scoring lanes)
# ---------------------------------------------------------------------------

def test_score_statistics_lanes():
    """With batch_stats=True the scoring bank gets its own per-batch
    (sum, sq-sum) lanes: the per-lane mean over closed batches matches
    the numpy statistics of the actual bank deltas."""
    rng = np.random.default_rng(43)
    t = _make_engine("monolithic", _spec2(), batch_stats=True)
    deltas = []
    prev = np.zeros(E * 6)
    for _ in range(3):
        src, dests, en = _corridor_workload(rng, 1)
        _drive(t, src, dests, energy=en)
        now = np.asarray(t.score_bank, np.float64)
        deltas.append(now - prev)
        prev = now
        t.close_batch()
    st = t.score_statistics()
    assert st.num_batches == 3
    x = np.stack(deltas)
    np.testing.assert_allclose(
        np.asarray(st.mean), x.mean(0), rtol=1e-12, atol=1e-300
    )
    # The flux statistics ride unchanged beside the scoring ones.
    assert t.batch_statistics().num_batches == 3


# ---------------------------------------------------------------------------
# Sentinel interplay: the straggler ladder continues the lanes
# ---------------------------------------------------------------------------

def test_straggler_recovery_keeps_scoring_bitwise():
    """A forced-tiny iteration budget truncates particles mid-flight;
    the sentinel ladder re-walks the residue CONTINUING the original
    parametrization — recovered flux AND scoring lanes must be bitwise
    what an unconstrained run produces."""
    from pumiumtally_tpu import SentinelPolicy

    rng = np.random.default_rng(47)
    src, dests, en = _corridor_workload(rng, 2)
    mesh = build_box(*MESH_ARGS)
    free = PumiTally(mesh, N, TallyConfig(scoring=_spec2()))
    _drive(free, src, dests, energy=en)
    t = PumiTally(
        mesh, N,
        TallyConfig(scoring=_spec2(), max_iters=2,
                    sentinel=SentinelPolicy(on_anomaly="record")),
    )
    _drive(t, src, dests, energy=en)
    rep = t.health_report()
    assert rep.stragglers_recovered > 0 and rep.stragglers_lost == 0
    np.testing.assert_array_equal(
        np.asarray(t.flux), np.asarray(free.flux)
    )
    np.testing.assert_array_equal(
        np.asarray(t.score_bank), np.asarray(free.score_bank)
    )


def test_refused_move_leaves_flying_buffer_intact():
    """A move refused for a missing/invalid scoring attribute must not
    have executed the flying-zeroing side effect: the caller's
    corrected retry would otherwise silently transport nothing
    (review finding, round 10)."""
    rng = np.random.default_rng(53)
    src, dests, en = _corridor_workload(rng, 1)
    t = _make_engine("monolithic", _spec2())
    t.CopyInitialPosition(src.reshape(-1).copy())
    fly = np.ones(N, np.int8)
    d = dests[0].reshape(-1)
    with pytest.raises(ValueError, match="pass energy="):
        t.MoveToNextLocation(None, d.copy(), fly)
    np.testing.assert_array_equal(fly, np.ones(N, np.int8))
    bad = en.copy()
    bad[0] = np.inf
    with pytest.raises(ValueError, match="energy"):
        t.MoveToNextLocation(None, d.copy(), fly, energy=bad)
    np.testing.assert_array_equal(fly, np.ones(N, np.int8))
    # The good retry actually transports.
    t.MoveToNextLocation(None, d.copy(), fly, energy=en)
    assert np.asarray(t.flux).sum() > 0
    assert np.all(fly == 0)  # NOW the side effect fired


@pytest.mark.parametrize("name", ("monolithic", "partitioned"))
def test_checkpoint_scoring_spec_mismatch_zeroes_banks(name, tmp_path):
    """A bank saved under a DIFFERENT ScoringSpec must never restore
    under the wrong (bin, score) interpretation: the target warns,
    zeroes its banks (scoring restarts at the restore point), and the
    flux restores unchanged (review finding, round 10)."""
    from pumiumtally_tpu.utils.checkpoint import (
        load_tally_state,
        save_tally_state,
    )

    rng = np.random.default_rng(59)
    src, dests, en = _corridor_workload(rng, 1)
    saver = _drive(_make_engine(name, _spec2()), src, dests, energy=en)
    path = str(tmp_path / f"mismatch_{name}.npz")
    save_tally_state(saver, path)
    # Same lane COUNT (6 per element: 3 bins x 2 scores vs 2 bins x 3
    # scores) — the nastiest case, where a size check alone passes.
    other = ScoringSpec(
        filters=[EnergyFilter([0.0, 1.0, 2.0, 3.0])],
        scores=["flux", "heating"],
    )
    target = _make_engine(name, other)
    with pytest.warns(UserWarning, match="different"):
        load_tally_state(target, path)
    np.testing.assert_array_equal(
        np.asarray(target.flux), np.asarray(saver.flux)
    )
    assert np.asarray(target.score_bank).sum() == 0.0
    # The restored engine still scores cleanly under ITS spec — a
    # FRESH destination set (the saved one is already committed; a
    # re-move there would be a zero-length no-op).
    target.MoveToNextLocation(
        None, src.reshape(-1).copy(), energy=en
    )
    assert np.asarray(target.score_bank).sum() > 0
