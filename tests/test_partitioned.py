"""Partitioned-mesh mode ≡ single-chip engine, including migrations.

The ownership-restricted walk + migration (parallel/partition.py) is a
pure parallelization strategy: fluxes, final positions, and element ids
must match the replicated single-chip engine up to FP summation order.
Runs on the 8-virtual-CPU-device mesh (tests/conftest.py).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh
from pumiumtally_tpu.parallel.partition import build_partition, rcb_partition


from tests.bounds import CLIP_HI as _HI, CLIP_LO as _LO

N = 3000


def test_rcb_partition_balanced():
    mesh = build_box(1, 1, 1, 6, 6, 6)
    cent = np.asarray(mesh.coords)[np.asarray(mesh.tet2vert)].mean(axis=1)
    for nparts in (2, 3, 8):
        owner = rcb_partition(cent, nparts)
        counts = np.bincount(owner, minlength=nparts)
        assert counts.sum() == mesh.nelems
        assert counts.max() - counts.min() <= max(2, mesh.nelems // nparts // 10)


def test_partition_adjacency_roundtrip():
    """Every local adjacency entry maps back to the correct original
    neighbor (local id, remote glid, or boundary)."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    part = build_partition(mesh, 8)
    table = np.asarray(part.table)
    adj_local = table[:, 16:20].astype(np.int64)
    orig_of_glid = np.asarray(part.orig_of_glid)
    glid_of_orig = np.asarray(part.glid_of_orig)
    face_adj = np.asarray(mesh.face_adj)
    owner = part.owner
    L = part.L
    for e in range(mesh.nelems):
        g = glid_of_orig[e]
        chip = g // L
        for f in range(4):
            enc = adj_local[g, f]
            nb = face_adj[e, f]
            if nb == -1:
                assert enc == -1
            elif owner[nb] == owner[e]:
                assert 0 <= enc < L
                assert orig_of_glid[chip * L + enc] == nb
            else:
                assert enc <= -2
                assert orig_of_glid[-enc - 2] == nb


# The explicit-origins variant costs a second full compile of the
# phase program; the continue variant exercises the same parity and
# stays fast. Both tiers run in CI.
@pytest.mark.parametrize("continue_mode", [
    pytest.param(False, marks=pytest.mark.slow), True,
])
def test_partitioned_matches_single_chip(continue_mode):
    mesh = build_box(1, 1, 1, 5, 5, 5)  # 750 tets over 8 chips
    dm = make_device_mesh(8)
    rng = np.random.default_rng(3)
    src = rng.uniform(0.05, 0.95, (N, 3))
    # long steps → many particles cross partition boundaries
    dest1 = np.clip(src + rng.normal(scale=0.3, size=(N, 3)), _LO, _HI)
    dest2 = np.clip(dest1 + rng.normal(scale=0.3, size=(N, 3)), _LO, _HI)
    fly = (rng.uniform(size=N) > 0.1).astype(np.int8)
    w = rng.uniform(0.5, 2.0, N)

    ref = PumiTally(mesh, N, TallyConfig())
    par = PartitionedPumiTally(mesh, N, TallyConfig(device_mesh=dm))

    for t in (ref, par):
        t.CopyInitialPosition(src.reshape(-1).copy())
    np.testing.assert_array_equal(ref.elem_ids, par.elem_ids)
    np.testing.assert_allclose(ref.positions, par.positions, atol=1e-13)

    for t in (ref, par):
        if continue_mode:
            t.MoveToNextLocation(None, dest1.reshape(-1).copy(),
                                 fly.copy(), w)
        else:
            pos = t.positions.astype(np.float64)
            t.MoveToNextLocation(pos.reshape(-1).copy(),
                                 dest1.reshape(-1).copy(), fly.copy(), w)
    np.testing.assert_array_equal(ref.elem_ids, par.elem_ids)
    np.testing.assert_allclose(ref.positions, par.positions, atol=1e-12)
    np.testing.assert_allclose(
        np.asarray(ref.flux), np.asarray(par.flux), rtol=1e-11, atol=1e-12
    )

    # second move accumulates
    for t in (ref, par):
        t.MoveToNextLocation(None, dest2.reshape(-1).copy(),
                             np.ones(N, np.int8), w)
    np.testing.assert_allclose(
        np.asarray(ref.flux), np.asarray(par.flux), rtol=1e-11, atol=1e-12
    )


@pytest.mark.slow
def test_partitioned_phase_a_migration_keeps_weights_aligned():
    """Resampled origins far from committed positions force phase-A
    migrations that permute slots; phase B must still tally each
    particle with ITS OWN weight (regression: stale slot-order restore)."""
    mesh = build_box(1, 1, 1, 5, 5, 5)
    dm = make_device_mesh(8)
    rng = np.random.default_rng(11)
    n = 800
    src = rng.uniform(0.05, 0.95, (n, 3))
    # resample EVERY particle to a far corner region → all migrate in
    # phase A; then short tallied hops with per-particle weights
    origins = rng.uniform(0.05, 0.95, (n, 3))[::-1].copy()
    dests = np.clip(origins + rng.normal(scale=0.1, size=(n, 3)), _LO, _HI)
    w = rng.uniform(0.1, 4.0, n)

    ref = PumiTally(mesh, n, TallyConfig())
    par = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=4.0)
    )
    for t in (ref, par):
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(origins.reshape(-1).copy(),
                             dests.reshape(-1).copy(),
                             np.ones(n, np.int8), w)
    np.testing.assert_array_equal(ref.elem_ids, par.elem_ids)
    np.testing.assert_allclose(
        np.asarray(ref.flux), np.asarray(par.flux), rtol=1e-11, atol=1e-12
    )


def test_partitioned_oracle_6tet_cube():
    """The reference's exact flux oracle (BASELINE.md) through the
    partitioned engine: 6 tets spread over 8 chips, rays crossing
    elements 2→3→4 — every crossing is a migration."""
    mesh = build_box(1, 1, 1, 1, 1, 1)
    dm = make_device_mesh(8)
    # all 5 particles pile into single-element chips → needs capacity
    # for full concentration (the documented capacity_factor trade-off)
    t = PartitionedPumiTally(
        mesh, 5, TallyConfig(device_mesh=dm, capacity_factor=8.0)
    )
    init = np.tile([0.1, 0.4, 0.5], (5, 1))
    t.CopyInitialPosition(init.reshape(-1).copy())
    np.testing.assert_array_equal(t.elem_ids, np.full(5, 2))

    dests = np.tile([1.2, 0.4, 0.5], (5, 1))
    t.MoveToNextLocation(init.reshape(-1).copy(), dests.reshape(-1).copy(),
                         np.ones(5, np.int8), np.ones(5))
    np.testing.assert_array_equal(t.elem_ids, np.full(5, 4))
    np.testing.assert_allclose(
        t.positions, np.tile([1.0, 0.4, 0.5], (5, 1)), atol=1e-8
    )
    np.testing.assert_allclose(
        np.asarray(t.flux),
        np.array([0.0, 0.0, 1.5, 0.5, 2.5, 0.0]),
        atol=1e-8,
    )


@pytest.mark.slow
def test_partitioned_split_adjacency_matches_packed():
    """The int32 out-of-row adjacency fallback (f32 meshes past the
    exact-id limit) must walk identically to the packed table."""
    from pumiumtally_tpu.parallel.partition import PartitionedEngine

    mesh = build_box(1, 1, 1, 4, 4, 4)
    dm = make_device_mesh(8)
    rng = np.random.default_rng(5)
    n = 500
    src = rng.uniform(0.05, 0.95, (n, 3))
    dest = np.clip(src + rng.normal(scale=0.3, size=(n, 3)), _LO, _HI)

    results = []
    for split in (False, True):
        eng = PartitionedEngine(
            mesh, dm, n, capacity_factor=4.0, tol=1e-8, max_iters=500,
        )
        if split:
            from pumiumtally_tpu.parallel.partition import build_partition

            eng.part = build_partition(mesh, 8, force_split_adj=True)
            assert eng.part.adj_int is not None
        eng.localize(jnp.asarray(src))
        eng.move(None, jnp.asarray(dest), jnp.ones(n, jnp.int8),
                 jnp.ones(n))
        results.append(
            (eng.elem_ids(), np.asarray(eng.flux_original()))
        )
    np.testing.assert_array_equal(results[0][0], results[1][0])
    np.testing.assert_allclose(results[0][1], results[1][1],
                               rtol=1e-12, atol=1e-13)


@pytest.mark.slow
def test_partitioned_stress_forced_migrations():
    """Load test: 8 chips, 100k particles, 6k tets, long steps forcing
    heavy cross-partition traffic; conservation must hold exactly (no
    particle exits) and flux must match the single-chip engine."""
    mesh = build_box(1, 1, 1, 10, 10, 10)  # 6000 tets
    dm = make_device_mesh(8)
    n = 100_000
    rng = np.random.default_rng(42)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dest = np.clip(src + rng.normal(scale=0.35, size=(n, 3)), _LO, _HI)

    par = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=2.0)
    )
    par.CopyInitialPosition(src.reshape(-1).copy())
    par.MoveToNextLocation(None, dest.reshape(-1).copy())
    total = float(np.asarray(par.flux).sum())
    expect = float(np.linalg.norm(dest - src, axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-10)

    ref = PumiTally(mesh, n, TallyConfig())
    ref.CopyInitialPosition(src.reshape(-1).copy())
    ref.MoveToNextLocation(None, dest.reshape(-1).copy())
    np.testing.assert_array_equal(ref.elem_ids, par.elem_ids)
    np.testing.assert_allclose(
        np.asarray(ref.flux), np.asarray(par.flux), rtol=1e-11, atol=1e-12
    )


@pytest.mark.slow
def test_partitioned_lost_source_points_never_tally(capsys):
    """Source points outside every element (possible only on
    non-convex/foreign geometry, or points outside the hull) must be
    flagged, excluded from transport, and contribute zero flux."""
    mesh = build_box(1, 1, 1, 3, 3, 3)
    dm = make_device_mesh(4)
    n = 64
    t = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=4.0)
    )
    rng = np.random.default_rng(9)
    src = rng.uniform(0.1, 0.9, (n, 3))
    src[::4] += 5.0  # every 4th point far outside the unit box
    t.CopyInitialPosition(src.reshape(-1).copy())
    out = capsys.readouterr().out
    assert "lie in no mesh element" in out
    assert "Not all particles are found" in out
    # Lost particles report the -1 sentinel, never a phantom element.
    ids = t.elem_ids
    assert np.all(ids[::4] == -1)
    assert np.all(ids[np.arange(n) % 4 != 0] >= 0)
    dest = rng.uniform(0.1, 0.9, (n, 3))
    t.MoveToNextLocation(None, dest.reshape(-1).copy())
    total = float(np.asarray(t.flux).sum())
    # Only the 48 located particles tally; lost ones contribute nothing.
    inside = np.ones(n, bool)
    inside[::4] = False
    expect = float(
        np.linalg.norm((dest - src)[inside], axis=1).sum()
    )
    np.testing.assert_allclose(total, expect, rtol=1e-10)

    # Revival: a two-phase move with valid in-mesh origins re-locates
    # the lost particles and they tally again (single-chip parity for
    # reincarnated particles, reference PumiTallyImpl.cpp:88-109).
    orig2 = rng.uniform(0.1, 0.9, (n, 3))
    dest2 = np.clip(orig2 + 0.05, _LO, _HI)
    t.MoveToNextLocation(orig2.reshape(-1).copy(), dest2.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    assert np.all(t.elem_ids >= 0)
    total2 = float(np.asarray(t.flux).sum()) - total
    expect2 = float(np.linalg.norm(dest2 - orig2, axis=1).sum())
    np.testing.assert_allclose(total2, expect2, rtol=1e-10)


def test_partitioned_overflow_near_capacity_recovers():
    """Concentrating every particle into one chip's region with slot
    capacity for barely 1/8th of the batch used to raise the overflow
    error AFTER a half-migrated round; since round 9 the commit is
    overflow-safe and the recovery ladder (full-capacity retry →
    host-side capacity escalation) completes the move — with the same
    final flux as a run provisioned generously up front (scatter-order
    class: the escalated engine has a different slot layout)."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    dm = make_device_mesh(8)
    n = 2000
    rng = np.random.default_rng(1)
    src = rng.uniform(0.05, 0.95, (n, 3))
    corner = np.tile([0.03, 0.03, 0.03], (n, 1))  # all to one chip

    big = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=9.0)
    )
    big.CopyInitialPosition(src.reshape(-1).copy())
    big.MoveToNextLocation(None, corner.reshape(-1).copy())

    # capacity_factor 1.3 → cap_per_chip ≈ 1.3·n/8: enough slack for
    # the (balanced) localization, nowhere near enough for an
    # all-on-one-chip concentration.
    t = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=1.3)
    )
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, corner.reshape(-1).copy())
    assert t.engine.overflow_recoveries >= 1
    assert t.engine.capacity_escalations >= 1
    assert not t.engine.poisoned
    np.testing.assert_allclose(
        np.asarray(t.flux), np.asarray(big.flux), rtol=1e-12
    )
    np.testing.assert_array_equal(t.positions, big.positions)


def test_partitioned_exit_and_hold_semantics():
    mesh = build_box(1, 1, 1, 3, 3, 3)
    dm = make_device_mesh(4)
    # the exit move sweeps every particle onto the +x face, owned by a
    # subset of chips → allow full concentration
    t = PartitionedPumiTally(
        mesh, 100, TallyConfig(device_mesh=dm, capacity_factor=4.0)
    )
    rng = np.random.default_rng(0)
    src = rng.uniform(0.2, 0.8, (100, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    # holds: nobody flies
    t.MoveToNextLocation(None, rng.uniform(0, 1, (100, 3)).reshape(-1),
                         np.zeros(100, np.int8), np.ones(100))
    np.testing.assert_allclose(t.positions, src, atol=1e-13)
    np.testing.assert_allclose(np.asarray(t.flux), 0.0, atol=1e-14)
    # exits: everyone leaves through +x; clamp to the face
    far = src.copy()
    far[:, 0] = 2.0
    t.MoveToNextLocation(None, far.reshape(-1).copy())
    assert np.allclose(t.positions[:, 0], 1.0, atol=1e-7)
    total = float(np.asarray(t.flux).sum())
    expect = float(np.linalg.norm(
        np.column_stack([1.0 - src[:, 0], np.zeros(100), np.zeros(100)]),
        axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-9)


@pytest.mark.slow
def test_partitioned_scale_48k_tets_100k_particles():
    """VERDICT-scale stress: 48k-tet mesh (bench geometry) partitioned
    over 8 chips with 100k particles — localization and a long-step
    tallied move with cross-partition migrations; conservation holds to
    f64 accumulation noise (compile time dominates the wall clock)."""
    mesh = build_box(1, 1, 1, 20, 20, 20)  # 48000 tets
    dm = make_device_mesh(8)
    n = 100_000
    rng = np.random.default_rng(42)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dest = np.clip(src + rng.normal(scale=0.3, size=(n, 3)), 0.02, 0.98)

    par = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=2.0)
    )
    par.CopyInitialPosition(src.reshape(-1).copy())
    par.MoveToNextLocation(None, dest.reshape(-1).copy())
    total = float(np.asarray(par.flux).sum())
    expect = float(np.linalg.norm(dest - src, axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-10)


@pytest.mark.slow
def test_walk_local_cascade_matches_plain():
    """The in-round compaction cascade in walk_local is a pure
    performance transform: per-slot results are bitwise identical to
    the plain lock-step form and the owned flux agrees to FP scatter
    order. Exercised directly (min_window=64 so the cascade engages at
    test scale) on a single chip's full table with remote faces
    present, so early pausers are among the compacted-out slots."""
    from pumiumtally_tpu.parallel.partition import build_partition, walk_local

    mesh = build_box(1, 1, 1, 4, 4, 4)
    part = build_partition(mesh, 4)
    L = part.L
    table = np.asarray(part.table)[:L]  # chip 0's rows
    rng = np.random.default_rng(61)
    n = 1000
    # start at owned element centroids of chip 0
    own = np.flatnonzero(np.asarray(part.orig_of_glid)[:L] >= 0)
    lelem = jnp.asarray(rng.choice(own, n).astype(np.int32))
    orig = np.asarray(part.orig_of_glid)[np.asarray(lelem)]
    verts = np.asarray(mesh.coords)[np.asarray(mesh.tet2vert)[orig]]
    x = jnp.asarray(verts.mean(axis=1))
    dest = jnp.asarray(
        np.clip(np.asarray(x) + rng.normal(scale=0.3, size=(n, 3)), -0.2, 1.2)
    )
    fly = jnp.asarray((rng.uniform(size=n) > 0.1).astype(np.int8))
    dest = jnp.where(fly[:, None] == 1, dest, x)
    w = jnp.asarray(rng.uniform(0.5, 2.0, n))
    done0 = jnp.zeros((n,), bool) | (fly == 0)
    ex0 = jnp.zeros((n,), bool)
    flux0 = jnp.zeros((L,), x.dtype)

    outs = {}
    for name, kw in (
        ("plain", dict(compact=False)),
        ("cascade", dict(compact=True, min_window=64)),
    ):
        outs[name] = walk_local(
            jnp.asarray(table), x, lelem, dest, fly, w, done0, ex0, flux0,
            tally=True, tol=1e-12, max_iters=4096, **kw,
        )
    a, b = outs["plain"], outs["cascade"]
    assert int(jnp.sum(b[4] >= 0)) > 0  # some slots actually paused
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))  # x
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))  # lelem
    np.testing.assert_array_equal(np.asarray(a[2]), np.asarray(b[2]))  # done
    np.testing.assert_array_equal(np.asarray(a[3]), np.asarray(b[3]))  # exited
    np.testing.assert_array_equal(np.asarray(a[4]), np.asarray(b[4]))  # pending
    np.testing.assert_allclose(
        np.asarray(a[5]), np.asarray(b[5]), rtol=1e-12, atol=1e-13)  # flux


def test_migrate_state_pack_round_trip():
    """_pack_state/_unpack_state move the particle state as two packed
    matrices; every dtype (float, int32, int8, bool) and trailing shape
    (1D, [*,3], and a 3D [*,2,3] future-field case) must round-trip
    exactly through pack -> permute -> unpack."""
    from pumiumtally_tpu.parallel.partition import (
        _pack_state,
        _unpack_state,
    )

    rng = np.random.default_rng(71)
    cap = 64
    state = {
        "x": jnp.asarray(rng.random((cap, 3))),
        "w": jnp.asarray(rng.random(cap)),
        "hist": jnp.asarray(rng.random((cap, 2, 3))),  # 3D trailing shape
        "lelem": jnp.asarray(rng.integers(0, 100, cap, dtype=np.int32)),
        "fly": jnp.asarray(rng.integers(0, 2, cap).astype(np.int8)),
        "alive": jnp.asarray(rng.integers(0, 2, cap).astype(bool)),
    }
    defaults = {k: jnp.zeros_like(v) for k, v in state.items()}
    fpack, ipack, fdef, idef, layout = _pack_state(state, defaults)
    perm = jnp.asarray(rng.permutation(cap))
    out = _unpack_state(fpack[perm], ipack[perm], layout)
    for k, v in state.items():
        got = out[k]
        assert got.dtype == v.dtype and got.shape == v.shape, k
        np.testing.assert_array_equal(np.asarray(got), np.asarray(v[perm]), k)


@pytest.mark.slow
def test_last_walk_rounds_diagnostic():
    """last_walk_rounds reports the phase's walk rounds: 1 when no
    particle crosses a partition (no migration), >1 when crossings
    force migrations."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    dm = make_device_mesh(8)
    n = 400
    t = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=8.0)
    )
    rng = np.random.default_rng(81)
    src = rng.uniform(0.05, 0.95, (n, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())

    # Tiny steps that stay within an element: one round, no migration.
    t.MoveToNextLocation(None, (src + 1e-4).reshape(-1).copy())
    assert t.engine.last_walk_rounds == 1

    # Long diagonal steps: crossings force migrations -> several rounds.
    far = np.clip(src + 0.6, 0.05, 0.95)
    t.MoveToNextLocation(None, far.reshape(-1).copy())
    assert t.engine.last_walk_rounds > 1
