"""Tests for aux subsystems: checkpoint round-trip, logging, timers,
chip-lock busy paths, CLI subprocess timeouts."""


import os

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.utils import (
    get_logger,
    load_tally_state,
    phase_timer,
    save_tally_state,
    set_verbosity,
)


from tests.bounds import CLIP_HI as _HI, CLIP_LO as _LO

N = 16


def _driven_tally():
    mesh = build_box(1, 1, 1, 3, 3, 3)
    t = PumiTally(mesh, N)
    rng = np.random.default_rng(5)
    src = rng.uniform(0.1, 0.9, (N, 3))
    dst = rng.uniform(0.1, 0.9, (N, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(N, np.int8), np.ones(N))
    return t


def test_checkpoint_roundtrip(tmp_path):
    t = _driven_tally()
    ckpt = str(tmp_path / "state.npz")
    save_tally_state(t, ckpt)

    t2 = PumiTally(build_box(1, 1, 1, 3, 3, 3), N)
    load_tally_state(t2, ckpt)
    np.testing.assert_array_equal(np.asarray(t2.flux), np.asarray(t.flux))
    np.testing.assert_array_equal(t2.positions, t.positions)
    np.testing.assert_array_equal(t2.elem_ids, t.elem_ids)
    assert t2.iter_count == t.iter_count
    assert t2.is_initialized

    # Resumed engine keeps tallying identically to the original.
    dst = np.tile([0.5, 0.5, 0.5], (N, 1))
    for eng in (t, t2):
        eng.MoveToNextLocation(
            eng.positions.reshape(-1).copy(), dst.reshape(-1).copy(),
            np.ones(N, np.int8), np.ones(N),
        )
    np.testing.assert_array_equal(np.asarray(t2.flux), np.asarray(t.flux))


@pytest.mark.slow
def test_checkpoint_cross_engine_roundtrip(tmp_path):
    """A checkpoint is canonical: save from one engine kind, resume in
    another, and the continued tally matches exactly."""
    from pumiumtally_tpu import (
        PartitionedPumiTally,
        StreamingPartitionedTally,
        StreamingTally,
    )
    from pumiumtally_tpu.parallel import make_device_mesh

    n = 600
    mesh_args = (1, 1, 1, 4, 4, 4)
    rng = np.random.default_rng(9)
    src = rng.uniform(0.1, 0.9, (n, 3))
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), _LO, _HI)

    t = PumiTally(build_box(*mesh_args), n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dst.reshape(-1).copy())
    ckpt = str(tmp_path / "c.npz")
    save_tally_state(t, ckpt)

    targets = {
        "stream": StreamingTally(build_box(*mesh_args), n, chunk_size=250),
        "part": PartitionedPumiTally(
            build_box(*mesh_args), n,
            TallyConfig(device_mesh=make_device_mesh(4), capacity_factor=4.0),
        ),
        "stream_part": StreamingPartitionedTally(
            build_box(*mesh_args), n, chunk_size=250,
            config=TallyConfig(
                device_mesh=make_device_mesh(4), capacity_factor=4.0
            ),
        ),
    }
    dst2 = np.clip(dst - 0.15, _LO, _HI)
    t.MoveToNextLocation(None, dst2.reshape(-1).copy())
    for name, t2 in targets.items():
        load_tally_state(t2, ckpt)
        np.testing.assert_allclose(
            np.asarray(t2.flux), np.load(ckpt)["flux"], atol=1e-14,
            err_msg=name,
        )
        np.testing.assert_array_equal(t2.elem_ids, np.load(ckpt)["elem"][:n])
        # resumed engine continues identically to the original
        t2.MoveToNextLocation(None, dst2.reshape(-1).copy())
        np.testing.assert_allclose(
            np.asarray(t2.flux), np.asarray(t.flux), rtol=1e-11,
            atol=1e-12, err_msg=name,
        )
        np.testing.assert_array_equal(t2.elem_ids, t.elem_ids, err_msg=name)

    # and the reverse: save from partitioned, resume monolithic
    ckpt2 = str(tmp_path / "c2.npz")
    save_tally_state(targets["part"], ckpt2)
    t3 = PumiTally(build_box(*mesh_args), n)
    load_tally_state(t3, ckpt2)
    np.testing.assert_allclose(
        np.asarray(t3.flux), np.asarray(targets["part"].flux), atol=1e-14
    )
    np.testing.assert_array_equal(t3.elem_ids, targets["part"].elem_ids)


@pytest.mark.slow
def test_checkpoint_restores_into_subsplit_engine(tmp_path):
    """Restore must route slots and size flux at BLOCK granularity
    (nparts groups of cap_per_block) — a chip-granular restore once
    silently dropped particles / crashed on the flux size."""
    from pumiumtally_tpu import PartitionedPumiTally
    from pumiumtally_tpu.parallel import make_device_mesh

    n = 600
    mesh_args = (1, 1, 1, 4, 4, 4)
    rng = np.random.default_rng(10)
    src = rng.uniform(0.1, 0.9, (n, 3))
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), _LO, _HI)
    t = PumiTally(build_box(*mesh_args), n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dst.reshape(-1).copy())
    ckpt = str(tmp_path / "b.npz")
    save_tally_state(t, ckpt)

    t2 = PartitionedPumiTally(
        build_box(*mesh_args), n,
        TallyConfig(device_mesh=make_device_mesh(4),
                    capacity_factor=4.0, walk_vmem_max_elems=40),
    )
    assert t2.engine.blocks_per_chip > 1
    load_tally_state(t2, ckpt)
    np.testing.assert_allclose(
        np.asarray(t2.flux), np.load(ckpt)["flux"], atol=1e-14
    )
    np.testing.assert_array_equal(t2.elem_ids, np.load(ckpt)["elem"][:n])
    dst2 = np.clip(dst - 0.15, _LO, _HI)
    t.MoveToNextLocation(None, dst2.reshape(-1).copy())
    t2.MoveToNextLocation(None, dst2.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(t2.flux), np.asarray(t.flux), rtol=1e-11, atol=1e-12
    )
    np.testing.assert_array_equal(t2.elem_ids, t.elem_ids)


def test_checkpoint_restores_into_gather_blocked_engine(tmp_path):
    """Same block-granular restore contract for the GATHER sub-split
    (walk_block_kernel='gather', single-device default mesh): restore
    from a monolithic checkpoint, then both engines must stay in
    lockstep through a further move."""
    from pumiumtally_tpu import PartitionedPumiTally

    n = 600
    mesh_args = (1, 1, 1, 4, 4, 4)
    rng = np.random.default_rng(10)
    src = rng.uniform(0.1, 0.9, (n, 3))
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), _LO, _HI)
    t = PumiTally(build_box(*mesh_args), n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dst.reshape(-1).copy())
    ckpt = str(tmp_path / "g.npz")
    save_tally_state(t, ckpt)

    t2 = PartitionedPumiTally(
        build_box(*mesh_args), n,
        TallyConfig(capacity_factor=4.0, walk_vmem_max_elems=40,
                    walk_block_kernel="gather"),
    )
    assert t2.engine.blocks_per_chip > 1 and not t2.engine.use_vmem_walk
    load_tally_state(t2, ckpt)
    np.testing.assert_allclose(
        np.asarray(t2.flux), np.load(ckpt)["flux"], atol=1e-14
    )
    dst2 = np.clip(dst - 0.15, _LO, _HI)
    t.MoveToNextLocation(None, dst2.reshape(-1).copy())
    t2.MoveToNextLocation(None, dst2.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(t2.flux), np.asarray(t.flux), rtol=1e-11, atol=1e-12
    )
    np.testing.assert_array_equal(t2.elem_ids, t.elem_ids)


def test_checkpoint_mismatch_raises(tmp_path):
    t = _driven_tally()
    ckpt = str(tmp_path / "state.npz")
    save_tally_state(t, ckpt)
    other = PumiTally(build_box(1, 1, 1, 2, 2, 2), N)  # different mesh
    with pytest.raises(ValueError, match="elements"):
        load_tally_state(other, ckpt)
    wrong_n = PumiTally(build_box(1, 1, 1, 3, 3, 3), N + 1)
    with pytest.raises(ValueError, match="particles"):
        load_tally_state(wrong_n, ckpt)


def _driven_stats_tally(batches: int = 3, seed: int = 6):
    """A stats-enabled monolithic tally with `batches` closed batches
    (and a 4th batch OPEN with one move in it, so the open-snapshot
    round-trip is exercised too)."""
    mesh = build_box(1, 1, 1, 3, 3, 3)
    t = PumiTally(mesh, N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(seed)
    for _ in range(batches + 1):
        src = rng.uniform(0.1, 0.9, (N, 3))
        dst = rng.uniform(0.1, 0.9, (N, 3))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dst.reshape(-1).copy())
    # batches closed by re-sourcing; the last one is still open with
    # one move in it.
    assert t._stats.num_batches == batches and t._stats.batch_open
    return t


def test_checkpoint_stats_roundtrip_exact(tmp_path):
    """A stats-carrying (v3) checkpoint resumes the statistics EXACTLY:
    lanes, batch counter, and the open-batch snapshot — so the resumed
    run's closes produce bit-identical statistics to the unrestarted
    one."""
    t = _driven_stats_tally()
    ckpt = str(tmp_path / "stats.npz")
    save_tally_state(t, ckpt)
    assert int(np.load(ckpt)["format_version"]) == 3

    t2 = PumiTally(build_box(1, 1, 1, 3, 3, 3), N,
                   TallyConfig(batch_stats=True))
    load_tally_state(t2, ckpt)
    assert t2._stats.num_batches == t._stats.num_batches
    assert t2._stats.moves_in_batch == t._stats.moves_in_batch
    np.testing.assert_array_equal(
        np.asarray(t2._stats.flux_sum), np.asarray(t._stats.flux_sum)
    )
    np.testing.assert_array_equal(
        np.asarray(t2._stats.flux_sq_sum),
        np.asarray(t._stats.flux_sq_sum),
    )
    # Both close their (identically snapshotted) open batch after one
    # more identical move: statistics stay bit-identical.
    dst = np.tile([0.4, 0.6, 0.5], (N, 1))
    for eng in (t, t2):
        eng.MoveToNextLocation(None, dst.reshape(-1).copy())
        eng.close_batch()
    np.testing.assert_array_equal(
        np.asarray(t2._stats.flux_sum), np.asarray(t._stats.flux_sum)
    )
    np.testing.assert_array_equal(
        np.asarray(t2._stats.flux_sq_sum),
        np.asarray(t._stats.flux_sq_sum),
    )
    assert t2._stats.num_batches == t._stats.num_batches


def test_checkpoint_prestats_forward_compat(tmp_path):
    """Forward compatibility: a pre-stats (v2) checkpoint loads cleanly
    into a stats-enabled engine — lanes zero-initialized, batch
    counter 0, and a fresh batch opened at the restored flux so the
    next close measures only post-restore work."""
    t = _driven_tally()  # stats-less: writes format_version 2
    ckpt = str(tmp_path / "v2.npz")
    save_tally_state(t, ckpt)
    assert int(np.load(ckpt)["format_version"]) == 2

    t2 = PumiTally(build_box(1, 1, 1, 3, 3, 3), N,
                   TallyConfig(batch_stats=True))
    load_tally_state(t2, ckpt)
    assert t2._stats.num_batches == 0
    assert np.all(np.asarray(t2._stats.flux_sum) == 0.0)
    assert np.all(np.asarray(t2._stats.flux_sq_sum) == 0.0)
    assert t2._stats.batch_open
    # The opened batch delta excludes everything before the restore.
    dst = np.tile([0.5, 0.5, 0.5], (N, 1))
    t2.MoveToNextLocation(None, dst.reshape(-1).copy())
    flux_before = np.asarray(np.load(ckpt)["flux"], np.float64)
    t2.close_batch()
    np.testing.assert_allclose(
        np.asarray(t2._stats.flux_sum),
        np.asarray(t2.flux, np.float64) - flux_before,
        rtol=1e-12, atol=1e-14,
    )


def test_checkpoint_stats_refused_by_old_reader(tmp_path, monkeypatch):
    """A stats-carrying checkpoint handed to a pre-v3 reader must fail
    at the header check with the clear format message — never a shape
    error from half-understood arrays. (Simulated by pinning the
    reader's format version back to 2.)"""
    from pumiumtally_tpu.utils import checkpoint as ckpt_mod

    t = _driven_stats_tally()
    path = str(tmp_path / "stats.npz")
    save_tally_state(t, path)
    monkeypatch.setattr(ckpt_mod, "_FORMAT_VERSION", 2)
    t2 = PumiTally(build_box(1, 1, 1, 3, 3, 3), N)
    with pytest.raises(ValueError, match="format 3 newer than 2"):
        load_tally_state(t2, path)


def test_checkpoint_stats_into_disabled_engine_warns(tmp_path):
    """Stats-carrying checkpoint into a stats-disabled engine: the
    tally itself restores unchanged; the lanes are dropped with a
    warning, not an error."""
    t = _driven_stats_tally()
    path = str(tmp_path / "stats.npz")
    save_tally_state(t, path)
    t2 = PumiTally(build_box(1, 1, 1, 3, 3, 3), N)
    with pytest.warns(UserWarning, match="batch_stats disabled"):
        load_tally_state(t2, path)
    np.testing.assert_array_equal(np.asarray(t2.flux), np.asarray(t.flux))
    np.testing.assert_array_equal(t2.positions, t.positions)


def test_logger_prefix_style(capsys):
    logger = get_logger()
    set_verbosity("INFO")
    logger.info("mesh loaded")
    logger.error("Not all particles are found")
    err = capsys.readouterr().err
    assert "[INFO] mesh loaded" in err
    assert "[ERROR] Not all particles are found" in err
    set_verbosity("ERROR")
    logger.info("hidden")
    assert "hidden" not in capsys.readouterr().err
    set_verbosity("INFO")


def test_phase_timer_accumulates():
    class Sink:
        t = 0.0

    s = Sink()
    with phase_timer(s, "t"):
        pass
    first = s.t
    assert first >= 0.0
    with phase_timer(s, "t"):
        pass
    assert s.t >= first


@pytest.mark.slow
def test_checkpoint_restore_into_device_groups_hybrid(tmp_path):
    """A monolithic checkpoint restores into the dp x part hybrid
    (device_groups=2) and transport continues identically."""
    from pumiumtally_tpu import StreamingPartitionedTally
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 3, 3, 3)
    n, chunk = 2000, 512
    rng = np.random.default_rng(77)
    src = rng.uniform(0.1, 0.9, (n, 3))
    d1 = rng.uniform(0.1, 0.9, (n, 3))
    d2 = rng.uniform(0.1, 0.9, (n, 3))
    a = PumiTally(mesh, n)
    a.CopyInitialPosition(src.reshape(-1).copy())
    a.MoveToNextLocation(None, d1.reshape(-1).copy())
    p = str(tmp_path / "ck.npz")
    save_tally_state(a, p)

    b = StreamingPartitionedTally(
        mesh, n, chunk_size=chunk,
        config=TallyConfig(device_mesh=make_device_mesh(8),
                           device_groups=2, capacity_factor=6.0),
    )
    load_tally_state(b, p)
    b.MoveToNextLocation(None, d2.reshape(-1).copy())
    a.MoveToNextLocation(None, d2.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(b.flux, np.float64), np.asarray(a.flux, np.float64),
        rtol=1e-11, atol=1e-13,
    )


def test_autotune_walk_returns_valid_tuned_config():
    """The autotuner sweeps its grid on the current backend, returns a
    usable TallyConfig whose tuned engine reproduces the untuned flux,
    and preserves non-walk fields of the base config."""
    from pumiumtally_tpu import PumiTally, TallyConfig, build_box
    from pumiumtally_tpu.utils import autotune_walk

    mesh = build_box(1, 1, 1, 3, 3, 3)
    base = TallyConfig(check_found_all=False)
    # Candidates whose knobs are ALL non-default: whichever wins the
    # timing race, the normalized config must keep a visible knob
    # (a default-equal winner would legitimately normalize to ()).
    cfg, report = autotune_walk(
        mesh, n_particles=2000, moves=2,
        candidates=[
            {"walk_cond_every": 8},
            {"walk_perm_mode": "indirect", "walk_window_factor": 4},
        ],
        base=base,
    )
    assert len(report) == 2
    assert report[0]["moves_per_sec"] >= report[1]["moves_per_sec"] > 0
    assert cfg.walk_kwargs() != () and cfg.check_found_all is False

    n = 800
    rng = np.random.default_rng(41)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    out = []
    for c in (TallyConfig(), cfg):
        t = PumiTally(mesh, n, c)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        out.append(np.asarray(t.flux, np.float64))
    np.testing.assert_allclose(out[0], out[1], rtol=1e-12, atol=1e-12)


def test_block_length_candidates_law():
    """The L-vs-mean-free-path law: candidates scale with the cube of
    the mean step length at fixed mesh density, bracket the matched
    block size one octave each way, keep the incumbent, and clip to
    [256, nelems/2]."""
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.utils.autotune import block_length_candidates

    mesh = build_box(1, 1, 1, 10, 10, 10)  # 6000 tets, density 6000
    small = block_length_candidates(mesh, 0.1, base_bound=None)
    large = block_length_candidates(mesh, 0.5, base_bound=None)
    # density * step^3: 6 at step 0.1 (clips to 256), 750 at 0.5.
    assert small == [256]
    assert large == [375, 750, 1500]
    assert max(large) <= mesh.nelems // 2 and min(large) >= 256
    with_base = block_length_candidates(mesh, 0.5, base_bound=3072)
    assert 3000 in with_base  # incumbent rides along (clipped to E/2)


def test_autotune_blocked_adopts_only_when_faster():
    """The adoption contract: the incumbent configuration is swept
    alongside the law's candidates and only a STRICTLY faster
    candidate displaces it — a wash keeps the base config. Rates are
    injected so the contract is tested, not the CPU's mood."""
    import dataclasses

    from pumiumtally_tpu import TallyConfig, build_box
    from pumiumtally_tpu.utils.autotune import autotune_blocked

    mesh = build_box(1, 1, 1, 6, 6, 6)
    base = TallyConfig(walk_vmem_max_elems=300,
                       walk_block_kernel="gather",
                       check_found_all=False)

    def rates(table):
        return lambda cfg: table[cfg.walk_vmem_max_elems]

    # Candidate 128 measures faster -> adopted.
    cfg, report = autotune_blocked(
        mesh, candidates=[128, 256], base=base,
        _measure=rates({128: 3e5, 256: 1e5, 300: 2e5}),
    )
    assert cfg.walk_vmem_max_elems == 128
    assert cfg.walk_block_kernel == "gather"
    assert report[0]["adopted"] and report[0]["walk_vmem_max_elems"] == 128
    assert any(r.get("incumbent") for r in report)
    # Incumbent fastest -> wash, base returned unchanged.
    cfg2, report2 = autotune_blocked(
        mesh, candidates=[128, 256], base=base,
        _measure=rates({128: 1e5, 256: 1e5, 300: 3e5}),
    )
    assert cfg2 == dataclasses.replace(base)
    assert not any(r.get("adopted") for r in report2)


@pytest.mark.slow
def test_autotune_blocked_real_sweep_smoke():
    """One real (tiny) measured sweep: returns a usable config and a
    rate for every bound including the unblocked incumbent."""
    from pumiumtally_tpu import TallyConfig, build_box
    from pumiumtally_tpu.utils.autotune import autotune_blocked

    mesh = build_box(1, 1, 1, 6, 6, 6)
    cfg, report = autotune_blocked(
        mesh, n_particles=1500, moves=2, candidates=[300],
        base=TallyConfig(check_found_all=False),
    )
    assert len(report) == 2  # candidate + unblocked incumbent
    assert all(r["moves_per_sec"] > 0 for r in report)
    assert (cfg.walk_vmem_max_elems in (None, 300))


# ---------------------------------------------------------------------------
# Chip-lock busy paths (utils/chiplock.py)
# ---------------------------------------------------------------------------

def _busy_lock(tmp_path, monkeypatch):
    """Point the module at a fresh lock file, clear the in-process /
    inherited short-circuits, and hold the lock on an independent file
    descriptor (flock treats separate descriptors as separate owners,
    so this models 'another process holds the window')."""
    import fcntl

    from pumiumtally_tpu.utils import chiplock

    lockfile = str(tmp_path / "chip.lock")
    monkeypatch.setattr(chiplock, "LOCK_PATH", lockfile)
    monkeypatch.setattr(chiplock, "_held_in_process", False)
    monkeypatch.delenv(chiplock._HELD_ENV, raising=False)
    fd = os.open(lockfile, os.O_CREAT | os.O_RDWR, 0o666)
    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    return chiplock, fd


def test_chip_lock_nonblocking_busy(tmp_path, monkeypatch):
    """blocking=False against a held lock yields False immediately and
    leaves no holder state behind (the caller decides skip-vs-proceed)."""
    import fcntl
    import time as _time

    chiplock, fd = _busy_lock(tmp_path, monkeypatch)
    try:
        t0 = _time.monotonic()
        with chiplock.chip_lock(blocking=False) as held:
            assert held is False
            # A busy miss must NOT masquerade as a held window.
            assert chiplock._held_in_process is False
            assert chiplock._HELD_ENV not in os.environ
        assert _time.monotonic() - t0 < 0.5  # no 1 s retry sleep
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def test_chip_lock_timeout_expires_busy(tmp_path, monkeypatch):
    """A timeout that expires while the lock stays busy yields False
    after at least one retry sleep, without acquiring."""
    import fcntl
    import time as _time

    chiplock, fd = _busy_lock(tmp_path, monkeypatch)
    try:
        t0 = _time.monotonic()
        with chiplock.chip_lock(timeout_s=0.01) as held:
            assert held is False
        # One failed attempt, one 1 s sleep, one deadline check.
        assert _time.monotonic() - t0 >= 0.9
        assert chiplock._held_in_process is False
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def test_chip_lock_acquires_after_release(tmp_path, monkeypatch):
    """After the contender releases: acquisition succeeds, exports the
    child-inheritance env var, nests reentrantly, and cleans up."""
    import fcntl

    chiplock, fd = _busy_lock(tmp_path, monkeypatch)
    fcntl.flock(fd, fcntl.LOCK_UN)
    os.close(fd)
    with chiplock.chip_lock(blocking=False) as held:
        assert held is True
        assert os.environ[chiplock._HELD_ENV] == "1"
        assert chiplock._held_in_process is True
        # Nested acquire in the same process: inherited, no deadlock.
        with chiplock.chip_lock(blocking=False) as inner:
            assert inner is True
    assert chiplock._HELD_ENV not in os.environ
    assert chiplock._held_in_process is False


def test_chip_lock_parent_env_inherited(tmp_path, monkeypatch):
    """A child of a lock holder sees the env var and skips acquisition
    entirely — proven by pointing LOCK_PATH somewhere unopenable."""
    from pumiumtally_tpu.utils import chiplock

    monkeypatch.setattr(chiplock, "_held_in_process", False)
    monkeypatch.setattr(
        chiplock, "LOCK_PATH", str(tmp_path / "no_dir" / "x.lock")
    )
    monkeypatch.setenv(chiplock._HELD_ENV, "1")
    with chiplock.chip_lock(blocking=False) as held:
        assert held is True  # os.open would have raised if attempted


# ---------------------------------------------------------------------------
# CLI subprocess timeout (cli.py, PUMIUMTALLY_SUBPROC_TIMEOUT)
# ---------------------------------------------------------------------------

def test_subproc_timeout_env(monkeypatch):
    from pumiumtally_tpu import cli

    monkeypatch.delenv("PUMIUMTALLY_SUBPROC_TIMEOUT", raising=False)
    assert cli._subproc_timeout() == 1800.0
    monkeypatch.setenv("PUMIUMTALLY_SUBPROC_TIMEOUT", "42.5")
    assert cli._subproc_timeout() == 42.5
    monkeypatch.setenv("PUMIUMTALLY_SUBPROC_TIMEOUT", "zero")
    with pytest.raises(SystemExit, match="PUMIUMTALLY_SUBPROC_TIMEOUT"):
        cli._subproc_timeout()
    monkeypatch.setenv("PUMIUMTALLY_SUBPROC_TIMEOUT", "-3")
    with pytest.raises(SystemExit, match="PUMIUMTALLY_SUBPROC_TIMEOUT"):
        cli._subproc_timeout()


def test_aot_check_timeout_names_env_var(monkeypatch, capsys):
    """An expired helper subprocess must surface the env var that
    extends the budget, and honor the configured timeout value."""
    import subprocess as sp

    from pumiumtally_tpu import cli

    seen = {}

    def fake_run(cmd, **kw):
        seen["timeout"] = kw.get("timeout")
        raise sp.TimeoutExpired(cmd, kw.get("timeout"), output="partial")

    monkeypatch.setenv("PUMIUMTALLY_SUBPROC_TIMEOUT", "7")
    monkeypatch.setattr(sp, "run", fake_run)
    args = type("A", (), {"multichip": False})()
    with pytest.raises(SystemExit):
        cli.cmd_aot_check(args)
    out = capsys.readouterr().out
    assert seen["timeout"] == 7.0
    assert "timed out after 7s" in out
    assert "PUMIUMTALLY_SUBPROC_TIMEOUT" in out


# ---------------------------------------------------------------------------
# Retrace tripwire (utils/profiling.py; docs/STATIC_ANALYSIS.md)
# ---------------------------------------------------------------------------

def test_retrace_guard_counts_entry_point_compiles():
    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu.utils.profiling import (
        register_entry_point,
        retrace_guard,
    )

    step = register_entry_point(
        "_test_rg_counts", jax.jit(lambda x: x * 2)
    )
    with retrace_guard(raise_on_exceed=False) as report:
        step(jnp.ones(7))      # compile 1 (new shape)
        step(jnp.ones(7))      # cache hit
        step(jnp.ones(13))     # compile 2 (new shape)
    assert report.compiles["_test_rg_counts"] == 2
    assert report.total_compiles >= 2
    assert report.exceeded == {}


def test_retrace_guard_budget_breach_raises():
    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu.utils.profiling import (
        RetraceBudgetExceeded,
        register_entry_point,
        retrace_guard,
    )

    step = register_entry_point(
        "_test_rg_budget", jax.jit(lambda x: x + 1)
    )
    with pytest.raises(RetraceBudgetExceeded, match="_test_rg_budget"):
        with retrace_guard({"_test_rg_budget": 1}):
            step(jnp.ones(3))
            step(jnp.ones(5))  # second key > budget 1
    # raise_on_exceed=False records instead (the conftest fixture path)
    with retrace_guard({"_test_rg_budget": 0},
                       raise_on_exceed=False) as report:
        step(jnp.ones(9))
    assert report.exceeded["_test_rg_budget"] == (1, 0)


def test_retrace_guard_counts_survive_engine_gc():
    """Per-engine entry points die with their engine BEFORE a
    surrounding guard exits (test locals are freed at function return,
    fixture teardown runs after) — call-time counting must still see
    their compiles."""
    import gc

    import jax
    import jax.numpy as jnp

    from pumiumtally_tpu.utils.profiling import (
        register_entry_point,
        retrace_guard,
    )

    def build_and_drive():
        step = register_entry_point(
            "_test_rg_gc", jax.jit(lambda x: x - 1)
        )
        step(jnp.ones(11))
        # `step` (and the jit cache behind it) dies on return

    with retrace_guard(raise_on_exceed=False) as report:
        build_and_drive()
        gc.collect()
    assert report.compiles["_test_rg_gc"] == 1


def test_register_entry_point_rejects_unjitted():
    from pumiumtally_tpu.utils.profiling import register_entry_point

    with pytest.raises(TypeError, match="_cache_size"):
        register_entry_point("_test_rg_plain", lambda x: x)


def test_tally_entry_points_registered():
    """The engine's hot paths are registered for retrace accounting:
    importing the facades registers the module-level entry points, and
    driving a FRESH shape through a monolithic move is counted as
    exactly one walk compile, within the declared budgets."""
    from pumiumtally_tpu.config import RETRACE_BUDGETS
    from pumiumtally_tpu.utils.profiling import (
        entry_point_names,
        retrace_guard,
    )

    assert {"walk", "walk_continue", "locate", "localize",
            "sharded_walk", "sharded_walk_continue"} <= set(
        entry_point_names()
    )
    mesh = build_box(1, 1, 1, 3, 3, 3)
    n = 23  # a particle count no other test uses: walk MUST compile
    t = PumiTally(mesh, n)
    rng = np.random.default_rng(5)
    src = rng.uniform(0.1, 0.9, (n, 3))
    dst = rng.uniform(0.1, 0.9, (n, 3))
    with retrace_guard(RETRACE_BUDGETS) as report:  # raises on breach
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(),
                             dst.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
    assert report.compiles.get("walk") == 1


# ---------------------------------------------------------------------------
# Narrow prevalidator: per-particle move-attribute arrays (round 10)
# ---------------------------------------------------------------------------

def test_host_scalar_field_names_argument():
    """Wrong-shape energy/time buffers must raise with the ARGUMENT
    NAME in the message — without this narrow prevalidation the shape
    error surfaces later as an opaque jit broadcast failure."""
    from pumiumtally_tpu.api.tally import host_scalar_field

    with pytest.raises(ValueError, match="energy buffer has 3 values, "
                                         "need 10"):
        host_scalar_field(np.ones(3), 10, "energy")
    with pytest.raises(ValueError, match="time buffer has"):
        host_scalar_field([1.0], 2, "time")
    # Longer buffers truncate like every other staged input.
    assert host_scalar_field(np.arange(12.0), 10, "energy").shape == (10,)
    # 2-D inputs flatten (the host protocol is flat buffers).
    assert host_scalar_field(np.ones((5, 2)), 10, "time").shape == (10,)


def test_stage_move_attr_nonfinite_names_argument():
    """NaN/inf in energy/time refuse BEFORE anything dispatches, with
    the argument name and the flat index — including the narrow-dtype
    corner where a finite f64 value overflows the f32 working dtype
    to inf (checked AFTER the cast, like positions/weights)."""
    import jax.numpy as jnp

    from pumiumtally_tpu import EnergyFilter, ScoringSpec

    spec = ScoringSpec(filters=[EnergyFilter([0.0, 1.0])])
    mesh = build_box(1, 1, 1, 2, 2, 2)
    t = PumiTally(mesh, N, TallyConfig(scoring=spec, dtype=jnp.float32))
    bad = np.ones(N)
    bad[3] = np.nan
    with pytest.raises(ValueError, match=r"energy contains 1 non-finite"
                                         r".*index 3"):
        t._stage_move_attr(bad, "energy")
    overflow = np.ones(N)
    overflow[5] = 1e300  # finite f64, inf in the f32 working dtype
    with pytest.raises(ValueError, match="energy contains 1 non-finite"):
        t._stage_move_attr(overflow, "energy")
    # validate_inputs=False opts out of the finite check (shape checks
    # still apply — they are free).
    t2 = PumiTally(mesh, N, TallyConfig(scoring=spec, dtype=jnp.float32,
                                        validate_inputs=False))
    assert t2._stage_move_attr(overflow, "energy").shape == (N,)
