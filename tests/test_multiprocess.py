"""REAL multi-process distributed backend test.

The reference reaches multi-node through MPI inside pumipic::Library
(reference PumiTallyImpl.cpp:238-241) but never tests it (SURVEY.md §4:
"Multi-node is not tested — there is no mpirun in CI"). Here the
TPU-native equivalent actually runs: two OS processes join one
jax.distributed job over a localhost coordinator, each contributing 4
virtual CPU devices to an 8-device global mesh, and the sharded tally
step's flux psum crosses the process boundary (gloo on CPU — the same
program rides ICI/DCN on a TPU pod unchanged).

Round 13 de-flaked this pair (ISSUE satellite): the coordinator port
is retried on a lost bind race, the wait is bounded by
``PUMIUMTALLY_SUBPROC_TIMEOUT``, and a CPU jaxlib that cannot execute
cross-process collectives (no gloo) yields a clear SKIP — the workers
exit ``UNAVAILABLE_EXIT_CODE`` with the ``DISTRIBUTED-UNAVAILABLE``
marker instead of failing.
"""

import os
import subprocess
import sys
import tempfile
import time

import pytest

from tests._distributed_driver import (
    _INIT_FAILED_MARKER,
    _PORT_RETRY_PATTERNS,
    _free_port,
    _wait_timeout,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tools", "exp_multiproc.py")


def _run_pair(port: int, timeout: float):
    """One worker pair on one coordinator port -> (rcs, outputs)."""
    procs, logs = [], []
    try:
        for pid in (0, 1):
            env = dict(os.environ)
            env["PROC_ID"] = str(pid)
            env["COORD_PORT"] = str(port)
            env.pop("RUN_BOTH", None)
            # The workers pick their own platform/device-count flags;
            # they must not inherit the parent's TPU tunnel claim.
            env.pop("PALLAS_AXON_POOL_IPS", None)
            # Log files, not pipes: a worker blocked on a full pipe
            # would stall the collective and deadlock the pair.
            log = tempfile.TemporaryFile(mode="w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, SCRIPT], env=env, cwd=REPO,
                stdout=log, stderr=subprocess.STDOUT, text=True,
            ))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in procs):
                break
            if any(p.poll() is not None and p.returncode != 0
                   for p in procs):
                # One worker already gave up (unavailable backend or a
                # startup failure): kill the peer now instead of
                # waiting out its collective/heartbeat timeout.
                time.sleep(2.0)
                break
            time.sleep(0.2)
        timed_out = [i for i, p in enumerate(procs) if p.poll() is None]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    outs = []
    for log in logs:
        log.seek(0)
        outs.append(log.read())
        log.close()
    if timed_out and all(p.returncode == 0 or i in timed_out
                         for i, p in enumerate(procs)):
        raise AssertionError(
            f"distributed workers {timed_out} still running after "
            f"{timeout:g}s (PUMIUMTALLY_SUBPROC_TIMEOUT extends the "
            f"bound); outputs:\n" + "\n".join(outs)[-3000:]
        )
    return [p.returncode for p in procs], outs


@pytest.mark.slow
def test_two_process_distributed_tally():
    from pumiumtally_tpu.parallel.distributed import (
        UNAVAILABLE_EXIT_CODE,
        UNAVAILABLE_MARKER,
    )

    timeout = _wait_timeout()
    attempts = 3
    for attempt in range(attempts):
        rcs, outs = _run_pair(_free_port(), timeout)
        blob = "\n".join(outs)
        if UNAVAILABLE_MARKER in blob or UNAVAILABLE_EXIT_CODE in rcs:
            reason = next(
                (ln for ln in blob.splitlines()
                 if UNAVAILABLE_MARKER in ln),
                f"worker exited {UNAVAILABLE_EXIT_CODE}",
            )
            pytest.skip(reason)
        if (_INIT_FAILED_MARKER in blob
                and any(p in blob.lower() for p in _PORT_RETRY_PATTERNS)
                and attempt + 1 < attempts):
            continue  # lost the free-port race: retry on a fresh port
        break
    for pid, (rc, out) in enumerate(zip(rcs, outs)):
        assert rc == 0, f"proc {pid} rc={rc}:\n{out[-2000:]}"
        assert f"proc {pid}: devices=8" in out
        assert f"proc {pid}: partitioned flux=" in out
