"""REAL multi-process distributed backend test.

The reference reaches multi-node through MPI inside pumipic::Library
(reference PumiTallyImpl.cpp:238-241) but never tests it (SURVEY.md §4:
"Multi-node is not tested — there is no mpirun in CI"). Here the
TPU-native equivalent actually runs: two OS processes join one
jax.distributed job over a localhost coordinator, each contributing 4
virtual CPU devices to an 8-device global mesh, and the sharded tally
step's flux psum crosses the process boundary (gloo on CPU — the same
program rides ICI/DCN on a TPU pod unchanged).
"""

import os
import socket
import subprocess
import sys

import pytest



def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_tally():
    # Bounded by the workers' communicate(timeout=280) below.
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "tools", "exp_multiproc.py")
    port = _free_port()
    procs = []
    logs = []
    try:
        for pid in (0, 1):
            env = dict(os.environ)
            env["PROC_ID"] = str(pid)
            env["COORD_PORT"] = str(port)
            env.pop("RUN_BOTH", None)
            # The workers pick their own platform/device-count flags;
            # they must not inherit the parent's TPU tunnel claim.
            env.pop("PALLAS_AXON_POOL_IPS", None)
            # Log files, not pipes: a worker blocked on a full pipe
            # would stall the collective and deadlock the pair.
            log = tempfile.TemporaryFile(mode="w+")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, script], env=env, cwd=repo,
                stdout=log, stderr=subprocess.STDOUT, text=True,
            ))
        for p in procs:
            p.wait(timeout=280)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, log) in enumerate(zip(procs, logs)):
        log.seek(0)
        out = log.read()
        assert p.returncode == 0, f"proc {pid} rc={p.returncode}:\n{out[-2000:]}"
        assert f"proc {pid}: devices=8" in out
        assert f"proc {pid}: partitioned flux=" in out
