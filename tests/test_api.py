"""API surface tests: VTK output, normalization, timing, config."""

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars

NUM = 5


def _run_move1(tally):
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1)).reshape(-1)
    tally.CopyInitialPosition(init.copy(), 3 * NUM)
    dests = np.tile([1.2, 0.4, 0.5], (NUM, 1)).reshape(-1)
    tally.MoveToNextLocation(
        init.copy(), dests, np.ones(NUM, np.int8), np.ones(NUM), 3 * NUM
    )


def test_write_tally_results(tmp_path, capsys):
    tally = PumiTally(build_box(1, 1, 1, 1, 1, 1), NUM)
    _run_move1(tally)
    out = str(tmp_path / "fluxresult.vtk")
    tally.WriteTallyResults(out)

    # Normalization: flux / volume (volume = 1/6 per tet). Reference
    # NormalizeFlux (PumiTallyImpl.cpp:382-409).
    flux = read_vtk_cell_scalars(out, "flux")
    vol = read_vtk_cell_scalars(out, "volume")
    np.testing.assert_allclose(vol, 1.0 / 6.0, atol=1e-12)
    raw = np.array([0.0, 0.0, 1.5, 0.5, 2.5, 0.0])
    np.testing.assert_allclose(flux, raw / (1.0 / 6.0), atol=1e-6)

    # Timing report printed (reference PrintTimes at WriteTallyResults,
    # PumiTally.cpp:59).
    captured = capsys.readouterr()
    assert "[TIME] Initialization time" in captured.out
    assert "[TIME] Total PUMI-Tally time" in captured.out
    times = tally.tally_times
    assert times.initialization_time > 0
    assert times.total_time_to_tally > 0
    assert times.vtk_file_write_time > 0


def test_default_output_filename(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    tally = PumiTally(build_box(1, 1, 1, 1, 1, 1), NUM)
    _run_move1(tally)
    tally.WriteTallyResults()  # reference hard-codes fluxresult.vtk (cpp:153)
    assert (tmp_path / "fluxresult.vtk").exists()


def test_size_assertion():
    import pytest

    tally = PumiTally(build_box(1, 1, 1, 1, 1, 1), NUM)
    with pytest.raises(ValueError):
        tally.CopyInitialPosition(np.zeros(3 * NUM), size=7)
    with pytest.raises(ValueError):
        tally.CopyInitialPosition(np.zeros(4))  # too short, no size given


def test_move_before_init_raises():
    import pytest

    tally = PumiTally(build_box(1, 1, 1, 1, 1, 1), NUM)
    z = np.zeros(3 * NUM)
    with pytest.raises(RuntimeError):
        # reference invariant: cpp:437-438
        tally.MoveToNextLocation(z, z, np.zeros(NUM, np.int8), np.zeros(NUM))


def test_flying_side_effect_on_list_and_noncontiguous():
    m = build_box(1, 1, 1, 1, 1, 1)
    t = PumiTally(m, NUM)
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1)).reshape(-1)
    t.CopyInitialPosition(init.copy())
    # list input
    fly_list = [1] * NUM
    t.MoveToNextLocation(init.copy(), init.copy(), fly_list, np.ones(NUM))
    assert fly_list == [0] * NUM
    # non-contiguous ndarray input (stride 2 view)
    backing = np.ones(2 * NUM, np.int8)
    fly_view = backing[::2]
    t.MoveToNextLocation(init.copy(), init.copy(), fly_view, np.ones(NUM))
    assert fly_view.sum() == 0


def test_flat_and_2d_inputs_equivalent():
    m = build_box(1, 1, 1, 1, 1, 1)
    t1 = PumiTally(m, NUM)
    t2 = PumiTally(m, NUM)
    init2d = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    t1.CopyInitialPosition(init2d.reshape(-1))
    t2.CopyInitialPosition(init2d)
    np.testing.assert_array_equal(t1.elem_ids, t2.elem_ids)


def test_native_create_engine_selection(monkeypatch, tmp_path):
    """The C ABI's environment-driven engine factory builds each engine
    flavor (native/pumiumtally_c.cpp routes pumiumtally_create here)."""
    from pumiumtally_tpu import (
        PartitionedPumiTally,
        PumiTally,
        StreamingPartitionedTally,
        StreamingTally,
    )
    from pumiumtally_tpu.api.native import native_create
    from pumiumtally_tpu.io.osh import write_osh
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    mesh_path = str(tmp_path / "m.osh")
    write_osh(mesh_path, coords, tets)

    monkeypatch.delenv("PUMIUMTALLY_ENGINE", raising=False)
    assert type(native_create(mesh_path, 50)) is PumiTally

    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "streaming")
    monkeypatch.setenv("PUMIUMTALLY_CHUNK_SIZE", "16")
    t = native_create(mesh_path, 50)
    assert type(t) is StreamingTally and t.nchunks == 4

    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "partitioned")
    monkeypatch.setenv("PUMIUMTALLY_DEVICES", "4")
    monkeypatch.setenv("PUMIUMTALLY_CAPACITY_FACTOR", "4.0")
    t = native_create(mesh_path, 50)
    assert type(t) is PartitionedPumiTally
    assert t.engine.ndev == 4

    monkeypatch.setenv("PUMIUMTALLY_VMEM_MAX_ELEMS", "100000")
    t = native_create(mesh_path, 50)
    assert t.engine.use_vmem_walk  # env knob reaches the engine
    # Engine-scoped knob: a non-partitioned engine must error loudly
    # (same contract as PUMIUMTALLY_DEVICE_GROUPS), not ignore it.
    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "mono")
    with pytest.raises(ValueError, match="VMEM_MAX_ELEMS"):
        native_create(mesh_path, 50)
    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "partitioned")
    monkeypatch.delenv("PUMIUMTALLY_VMEM_MAX_ELEMS")

    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "streaming_partitioned")
    t = native_create(mesh_path, 50)
    assert type(t) is StreamingPartitionedTally

    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "bogus")
    with pytest.raises(ValueError, match="PUMIUMTALLY_ENGINE"):
        native_create(mesh_path, 50)


def test_locate_localization_matches_walk():
    """TallyConfig.localization="locate": MXU point location agrees
    with the reference-style walk localization, including the
    out-of-hull clamp fallback, and the subsequent tallied move is
    bit-identical."""
    from pumiumtally_tpu import PumiTally, TallyConfig, build_box

    mesh = build_box(1, 1, 1, 5, 5, 5)
    n = 3000
    rng = np.random.default_rng(71)
    src = rng.uniform(0.05, 0.95, (n, 3))
    src[::7] += 3.0  # every 7th source outside the hull → clamp path
    dest = rng.uniform(0.05, 0.95, (n, 3))

    out = []
    for how in ("walk", "locate"):
        t = PumiTally(mesh, n, TallyConfig(localization=how))
        t.CopyInitialPosition(src.reshape(-1).copy())
        pos_after_localize = t.positions.copy()
        elems = t.elem_ids.copy()
        t.MoveToNextLocation(None, dest.reshape(-1).copy())
        out.append((pos_after_localize, elems, np.asarray(t.flux),
                    t.positions))
    np.testing.assert_allclose(out[0][0], out[1][0], atol=1e-12)
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_allclose(out[0][2], out[1][2], rtol=1e-12, atol=1e-14)
    np.testing.assert_allclose(out[0][3], out[1][3], atol=1e-12)


def test_locate_localization_interior_fast_path():
    """All-interior sources take the no-walk path: committed positions
    equal the staged sources bit-exactly and elements match the
    brute-force oracle."""
    import jax.numpy as jnp

    from pumiumtally_tpu import PumiTally, TallyConfig, build_box
    from pumiumtally_tpu.ops import geometry

    mesh = build_box(1, 1, 1, 3, 3, 3)
    n = 500
    rng = np.random.default_rng(72)
    src = rng.uniform(0.02, 0.98, (n, 3))
    t = PumiTally(mesh, n, TallyConfig(localization="locate"))
    t.CopyInitialPosition(src.reshape(-1).copy())
    want = geometry.locate_bruteforce(
        mesh.coords, mesh.tet2vert,
        jnp.asarray(src, mesh.coords.dtype), tol=t._tol,
    )
    np.testing.assert_array_equal(t.elem_ids, np.asarray(want))
    np.testing.assert_array_equal(
        t.positions, np.asarray(src, t.positions.dtype)
    )


def test_locate_localization_relocalize_and_validation():
    """Re-localizing mid-run walks unlocated points from the COMMITTED
    state (as walk mode does), and bad localization values are
    rejected at config construction."""
    from pumiumtally_tpu import PumiTally, TallyConfig, build_box

    with pytest.raises(ValueError, match="localization"):
        TallyConfig(localization="Locate")

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 400
    rng = np.random.default_rng(73)
    src = rng.uniform(0.1, 0.9, (n, 3))
    d1 = rng.uniform(0.1, 0.9, (n, 3))
    # second-batch sources: some outside the hull (clamp path from the
    # committed positions, which differ per particle by now)
    src2 = rng.uniform(0.1, 0.9, (n, 3))
    src2[::5] += 2.5

    out = []
    for how in ("walk", "locate"):
        t = PumiTally(mesh, n, TallyConfig(localization=how))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        t.CopyInitialPosition(src2.reshape(-1).copy())
        out.append((t.positions, t.elem_ids))
    np.testing.assert_allclose(out[0][0], out[1][0], atol=1e-12)
    np.testing.assert_array_equal(out[0][1], out[1][1])


def test_locate_localization_degenerate_sources_contained():
    """Sources exactly on faces/edges/vertices: the located element may
    legitimately differ from the walk's (tolerance tie), but it must
    CONTAIN the point and transport from it must conserve."""
    import jax.numpy as jnp

    from pumiumtally_tpu import PumiTally, TallyConfig, build_box
    from pumiumtally_tpu.ops import geometry

    mesh = build_box(1, 1, 1, 4, 4, 4)
    # Grid nodes, edge midpoints in ALL three directions, and cube-face
    # centers (which lie on face diagonals of the 6-tet decomposition —
    # a distinct degeneracy class) of the 4x4x4 lattice.
    g = np.linspace(0, 1, 5)
    h = g[:-1] + 0.125  # cell midlines
    grids = [(g, g, g), (h, g, g), (g, h, g), (g, g, h),
             (h, h, g), (h, g, h), (g, h, h)]
    src = np.vstack([
        np.array(np.meshgrid(*axes)).reshape(3, -1).T for axes in grids
    ])
    n = src.shape[0]

    t = PumiTally(mesh, n, TallyConfig(localization="locate"))
    t.CopyInitialPosition(src.reshape(-1).copy())
    ids = t.elem_ids
    assert np.all(ids >= 0)
    inside = geometry.contains(
        mesh.coords, mesh.tet2vert, jnp.asarray(ids),
        jnp.asarray(src, mesh.coords.dtype), tol=1e-9,
    )
    assert bool(jnp.all(inside))

    dest = np.clip(src + 0.2, 0.01, 0.99)
    t.MoveToNextLocation(None, dest.reshape(-1).copy())
    got = float(np.sum(np.asarray(t.flux)))
    want = float(np.linalg.norm(dest - src, axis=1).sum())
    assert abs(got - want) / want < 1e-12


def test_native_create_new_config_envs(monkeypatch, tmp_path):
    from pumiumtally_tpu.api.native import native_create
    from pumiumtally_tpu.io.osh import write_osh
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    mesh_path = str(tmp_path / "m.osh")
    write_osh(mesh_path, coords, tets)
    monkeypatch.delenv("PUMIUMTALLY_ENGINE", raising=False)
    monkeypatch.setenv("PUMIUMTALLY_LOCALIZATION", "locate")
    monkeypatch.setenv("PUMIUMTALLY_AUTO_CONTINUE", "0")
    monkeypatch.setenv("PUMIUMTALLY_FENCED_TIMING", "0")
    t = native_create(mesh_path, 20)
    assert t.config.localization == "locate"
    assert t.config.auto_continue is False
    assert t.config.fenced_timing is False
    monkeypatch.setenv("PUMIUMTALLY_LOCALIZATION", "bogus")
    with pytest.raises(ValueError, match="localization"):
        native_create(mesh_path, 20)


def test_native_env_flag_spellings(monkeypatch, tmp_path):
    from pumiumtally_tpu.api.native import native_create
    from pumiumtally_tpu.io.osh import write_osh
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    mesh_path = str(tmp_path / "m.osh")
    write_osh(mesh_path, coords, tets)
    monkeypatch.delenv("PUMIUMTALLY_ENGINE", raising=False)
    # capitalized/padded spellings count as false too
    monkeypatch.setenv("PUMIUMTALLY_AUTO_CONTINUE", "False")
    monkeypatch.setenv("PUMIUMTALLY_FENCED_TIMING", " OFF ")
    t = native_create(mesh_path, 10)
    assert t.config.auto_continue is False
    assert t.config.fenced_timing is False
    # unfenced implies check_found_all off...
    assert t.config.check_found_all is False
    # ...unless explicitly requested
    monkeypatch.setenv("PUMIUMTALLY_CHECK_FOUND_ALL", "1")
    t = native_create(mesh_path, 10)
    assert t.config.fenced_timing is False
    assert t.config.check_found_all is True


def test_native_device_groups_env(monkeypatch, tmp_path):
    from pumiumtally_tpu.api.native import native_create
    from pumiumtally_tpu.io.osh import write_osh
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    mesh_path = str(tmp_path / "m.osh")
    write_osh(mesh_path, coords, tets)
    monkeypatch.setenv("PUMIUMTALLY_ENGINE", "streaming_partitioned")
    monkeypatch.setenv("PUMIUMTALLY_DEVICES", "8")
    monkeypatch.setenv("PUMIUMTALLY_CHUNK_SIZE", "32")
    monkeypatch.setenv("PUMIUMTALLY_CAPACITY_FACTOR", "6.0")
    monkeypatch.setenv("PUMIUMTALLY_DEVICE_GROUPS", "2")
    t = native_create(mesh_path, 64)
    assert t.config.device_groups == 2
    assert len({id(e.device_mesh) for e in t.engines}) == 2

    with pytest.raises(ValueError, match="device_groups"):
        TallyConfig(device_groups=0)


@pytest.mark.slow
def test_walk_tuning_knobs_reach_all_facades():
    """TallyConfig.walk_* knobs flow through every facade's jitted
    dispatch as static args; a tuned config reproduces the untuned
    flux/positions exactly (perm modes are bitwise-identical; cascade
    shape changes only reorder the scatter within FP tolerance). On
    the partitioned facade only cond_every reaches the engine (its
    walk has no cascade — see TallyConfig); the equality here checks
    that the remaining knobs are at least harmless there."""
    from pumiumtally_tpu import (
        PartitionedPumiTally,
        StreamingTally,
        TallyConfig,
        build_box,
    )
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 1500
    rng = np.random.default_rng(31)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    tuned = dict(walk_cond_every=2, walk_perm_mode="indirect",
                 walk_window_factor=4, walk_min_window=256)
    dm = make_device_mesh(8)

    for cls, base_kw in (
        (PumiTally, {}),
        (PumiTally, {"device_mesh": dm}),
        (StreamingTally, {}),
        (PartitionedPumiTally,
         {"device_mesh": dm, "capacity_factor": 8.0}),
    ):
        out = []
        for knobs in ({}, tuned):
            cfg = TallyConfig(**base_kw, **knobs)
            if cls is StreamingTally:
                t = cls(mesh, n, chunk_size=512, config=cfg)
            else:
                t = cls(mesh, n, cfg)
            assert t._walk_kw == cfg.walk_kwargs()
            t.CopyInitialPosition(src.reshape(-1).copy())
            t.MoveToNextLocation(None, d1.reshape(-1).copy())
            out.append((np.asarray(t.flux, np.float64), t.positions))
        np.testing.assert_allclose(out[0][0], out[1][0],
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_array_equal(out[0][1], out[1][1])

    with pytest.raises(ValueError):
        TallyConfig(walk_perm_mode="bogus")
    with pytest.raises(ValueError):
        TallyConfig(walk_window_factor=1)


def test_perm_mode_env_resolves_in_walk_kwargs(monkeypatch):
    """PUMIUMTALLY_WALK_PERM must resolve at CONFIG resolution (into
    the static jit key), not at trace time inside the kernel — an env
    flip in a running process then recompiles instead of silently
    reusing the stale cached mode (ADVICE r3)."""
    from pumiumtally_tpu import TallyConfig

    monkeypatch.delenv("PUMIUMTALLY_WALK_PERM", raising=False)
    assert TallyConfig().walk_kwargs() == ()
    # An explicit default-equal mode normalizes away (cache-key parity).
    assert TallyConfig(walk_perm_mode="packed").walk_kwargs() == ()
    monkeypatch.setenv("PUMIUMTALLY_WALK_PERM", "arrays")
    assert ("perm_mode", "arrays") in TallyConfig().walk_kwargs()
    assert ("perm_mode", "arrays") in TallyConfig(
        walk_perm_mode="auto"
    ).walk_kwargs()
    # An explicit non-auto mode wins over the env var...
    assert ("perm_mode", "indirect") in TallyConfig(
        walk_perm_mode="indirect"
    ).walk_kwargs()
    # ...including an explicit DEFAULT mode under a contrary env var
    # (dropping it would let the kernel's trace-time fallback override
    # the explicit choice).
    assert ("perm_mode", "packed") in TallyConfig(
        walk_perm_mode="packed"
    ).walk_kwargs()
    # A bogus env value fails loudly at config resolution.
    monkeypatch.setenv("PUMIUMTALLY_WALK_PERM", "bogus")
    with pytest.raises(ValueError):
        TallyConfig().walk_kwargs()


def test_partitioned_engine_consumes_cond_every():
    """The one walk knob the partitioned engines support must actually
    reach the engine (and an invalid value must be rejected)."""
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 2, 2, 2)
    t = PartitionedPumiTally(
        mesh, 64,
        TallyConfig(device_mesh=make_device_mesh(8), capacity_factor=8.0,
                    walk_cond_every=2),
    )
    assert t.engine.cond_every == 2
    with pytest.raises(ValueError):
        TallyConfig(walk_cond_every=0)


def test_walk_kw_actually_reaches_kernel(monkeypatch):
    """Regression guard for the ~10 dispatch call sites: record the
    kwargs the walk kernel RECEIVES (the knobs are performance-only, so
    output parity alone cannot detect a dropped walk_kw argument)."""
    import pumiumtally_tpu.api.tally as tally_mod
    import pumiumtally_tpu.parallel.sharded as sharded_mod
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.ops.walk import walk as real_walk
    from pumiumtally_tpu.parallel import make_device_mesh

    seen = []

    def recorder(*a, **kw):
        seen.append({k: kw.get(k) for k in
                     ("cond_every", "perm_mode", "min_window")})
        return real_walk(*a, **kw)

    monkeypatch.setattr(tally_mod, "walk", recorder)
    monkeypatch.setattr(sharded_mod, "walk", recorder)

    # Unique static values so the jitted steps cannot hit a cached
    # trace from another test (tracing is when the recorder fires).
    knobs = dict(walk_cond_every=3, walk_perm_mode="indirect",
                 walk_min_window=333)
    mesh = build_box(1, 1, 1, 2, 2, 2)
    n = 200
    rng = np.random.default_rng(51)
    src = rng.uniform(0.1, 0.9, (n, 3))
    d1 = rng.uniform(0.1, 0.9, (n, 3))

    for dm in (None, make_device_mesh(8)):
        seen.clear()
        t = PumiTally(mesh, n, TallyConfig(device_mesh=dm, **knobs))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        t.MoveToNextLocation(None, src.reshape(-1).copy())
        assert len(seen) >= 3  # localize + phase A/B + continue
        for s in seen:
            assert s == {"cond_every": 3, "perm_mode": "indirect",
                         "min_window": 333}, (dm, s)
