"""StreamingTally (chunked batches) ≡ monolithic PumiTally."""

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, StreamingTally, TallyConfig, build_box

N = 2500  # deliberately NOT a multiple of the chunk size


@pytest.mark.parametrize("continue_mode", [False, True])
def test_streaming_matches_monolithic(continue_mode):
    mesh = build_box(1, 1, 1, 4, 4, 4)
    rng = np.random.default_rng(2)
    src = rng.uniform(0.05, 0.95, (N, 3))
    dest = np.clip(src + rng.normal(scale=0.2, size=(N, 3)), 0.02, 0.98)
    fly = (rng.uniform(size=N) > 0.15).astype(np.int8)
    w = rng.uniform(0.5, 2.0, N)

    mono = PumiTally(mesh, N, TallyConfig())
    stream = StreamingTally(mesh, N, chunk_size=600, config=TallyConfig())
    assert stream.nchunks == 5

    for t in (mono, stream):
        t.CopyInitialPosition(src.reshape(-1).copy())
    np.testing.assert_array_equal(mono.elem_ids, stream.elem_ids)

    fly_mono, fly_stream = fly.copy(), fly.copy()
    for t, fl in ((mono, fly_mono), (stream, fly_stream)):
        if continue_mode:
            t.MoveToNextLocation(None, dest.reshape(-1).copy(), fl, w)
        else:
            pos = t.positions.astype(np.float64)
            t.MoveToNextLocation(pos.reshape(-1).copy(),
                                 dest.reshape(-1).copy(), fl, w)
    # flying zeroed in place for both
    np.testing.assert_array_equal(fly_mono, np.zeros(N, np.int8))
    np.testing.assert_array_equal(fly_stream, np.zeros(N, np.int8))
    np.testing.assert_array_equal(mono.elem_ids, stream.elem_ids)
    np.testing.assert_allclose(mono.positions, stream.positions, atol=1e-13)
    np.testing.assert_allclose(
        np.asarray(mono.flux), np.asarray(stream.flux), rtol=1e-12, atol=1e-13
    )


@pytest.mark.parametrize("continue_mode", [False, True])
def test_streaming_sharded_matches_single_device(continue_mode):
    """BASELINE configs 3+5 composed: chunked batches where every chunk
    walks as the 8-virtual-device sharded step; flux must match the
    single-device streaming engine to the oracle tolerance."""
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 4, 4, 4)
    rng = np.random.default_rng(7)
    src = rng.uniform(0.05, 0.95, (N, 3))
    dest = np.clip(src + rng.normal(scale=0.2, size=(N, 3)), 0.02, 0.98)
    fly = (rng.uniform(size=N) > 0.15).astype(np.int8)
    w = rng.uniform(0.5, 2.0, N)

    single = StreamingTally(mesh, N, chunk_size=600, config=TallyConfig())
    dev_mesh = make_device_mesh(8)
    sharded = StreamingTally(
        mesh, N, chunk_size=600, config=TallyConfig(device_mesh=dev_mesh)
    )
    assert sharded.chunk_size % 8 == 0  # rounded up to shard evenly

    for t in (single, sharded):
        t.CopyInitialPosition(src.reshape(-1).copy())
    np.testing.assert_array_equal(
        single.elem_ids[:N], sharded.elem_ids[:N]
    )

    for t in (single, sharded):
        if continue_mode:
            t.MoveToNextLocation(None, dest.reshape(-1).copy(), fly.copy(), w)
        else:
            pos = t.positions[:N].astype(np.float64)
            t.MoveToNextLocation(
                pos.reshape(-1).copy(), dest.reshape(-1).copy(), fly.copy(), w
            )
    np.testing.assert_array_equal(single.elem_ids[:N], sharded.elem_ids[:N])
    np.testing.assert_allclose(
        single.positions[:N], sharded.positions[:N], atol=1e-13
    )
    np.testing.assert_allclose(
        np.asarray(single.flux), np.asarray(sharded.flux),
        rtol=1e-12, atol=1e-13,
    )


def test_streaming_accumulates_and_writes(tmp_path):
    mesh = build_box(1, 1, 1, 3, 3, 3)
    rng = np.random.default_rng(4)
    src = rng.uniform(0.1, 0.9, (N, 3))
    t = StreamingTally(mesh, N, chunk_size=1000)
    t.CopyInitialPosition(src.reshape(-1).copy())
    d1 = np.clip(src + 0.1, 0.02, 0.98)
    d2 = np.clip(d1 - 0.2, 0.02, 0.98)
    t.MoveToNextLocation(None, d1.reshape(-1).copy())
    t.MoveToNextLocation(None, d2.reshape(-1).copy())
    got = float(np.asarray(t.flux).sum())
    expect = float(
        np.linalg.norm(d1 - src, axis=1).sum()
        + np.linalg.norm(d2 - d1, axis=1).sum()
    )
    np.testing.assert_allclose(got, expect, rtol=1e-10)
    out = str(tmp_path / "f.vtk")
    t.WriteTallyResults(out)
    assert open(out, "rb").readline().startswith(b"# vtk")


@pytest.mark.slow
def test_streaming_partitioned_composition():
    """Chunked batches through the PARTITIONED engine (mesh sharded,
    particles migrate) must reproduce the monolithic flux — BASELINE
    configs 2+5 composed."""
    from pumiumtally_tpu import StreamingPartitionedTally
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 4, 4, 4)
    rng = np.random.default_rng(21)
    n = 2500
    src = rng.uniform(0.05, 0.95, (n, 3))
    dest = np.clip(src + rng.normal(scale=0.25, size=(n, 3)),
                   [0.0213, 0.0227, 0.0241], [0.9787, 0.9773, 0.9759])
    w = rng.uniform(0.5, 2.0, n)

    mono = PumiTally(mesh, n, TallyConfig())
    dm = make_device_mesh(8)
    sp = StreamingPartitionedTally(
        mesh, n, chunk_size=600,
        config=TallyConfig(device_mesh=dm, capacity_factor=4.0),
    )
    assert sp.nchunks == 5
    for t in (mono, sp):
        t.CopyInitialPosition(src.reshape(-1).copy())
    np.testing.assert_array_equal(mono.elem_ids, sp.elem_ids)

    for t in (mono, sp):
        t.MoveToNextLocation(None, dest.reshape(-1).copy(),
                             np.ones(n, np.int8), w)
    np.testing.assert_array_equal(mono.elem_ids, sp.elem_ids)
    np.testing.assert_allclose(
        np.asarray(mono.flux), np.asarray(sp.flux), rtol=1e-11, atol=1e-12
    )
    # second move accumulates across the shared-partition chunk engines
    dest2 = np.clip(dest - 0.15, [0.0213, 0.0227, 0.0241],
                    [0.9787, 0.9773, 0.9759])
    for t in (mono, sp):
        t.MoveToNextLocation(None, dest2.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(mono.flux), np.asarray(sp.flux), rtol=1e-11, atol=1e-12
    )


def test_streaming_partitioned_deferred_overflow_recovers():
    """Deferred per-chunk syncs used to surface capacity overflow as a
    RuntimeError over corrupt state at the end of the move; since
    round 9 the commit is overflow-safe and the batch sync point runs
    the recovery ladder instead — the continue-mode move completes
    with the same flux as a generously provisioned run (scatter-order
    class) and no stale not-found error."""
    from pumiumtally_tpu import StreamingPartitionedTally
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 4, 4, 4)
    dm = make_device_mesh(8)
    n = 1600
    rng = np.random.default_rng(3)
    src = rng.uniform(0.05, 0.95, (n, 3))
    corner = np.tile([0.03, 0.03, 0.03], (n, 1))

    big = StreamingPartitionedTally(
        mesh, n, chunk_size=800,
        config=TallyConfig(device_mesh=dm, capacity_factor=9.0),
    )
    big.CopyInitialPosition(src.reshape(-1).copy())
    big.MoveToNextLocation(None, corner.reshape(-1).copy())

    sp = StreamingPartitionedTally(
        mesh, n, chunk_size=800,
        config=TallyConfig(device_mesh=dm, capacity_factor=1.3),
    )
    sp.CopyInitialPosition(src.reshape(-1).copy())
    sp.MoveToNextLocation(None, corner.reshape(-1).copy())
    assert sum(e.overflow_recoveries for e in sp.engines) >= 1
    assert not any(e.poisoned for e in sp.engines)
    np.testing.assert_allclose(
        np.asarray(sp.flux), np.asarray(big.flux), rtol=1e-12
    )
    np.testing.assert_array_equal(sp.positions, big.positions)


def test_streaming_partitioned_lost_warning(capsys):
    """The deferred chunk pipeline still surfaces the specific
    out-of-mesh-source diagnostic (at the batch sync point)."""
    from pumiumtally_tpu import StreamingPartitionedTally
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 3, 3, 3)
    dm = make_device_mesh(4)
    n = 64
    sp = StreamingPartitionedTally(
        mesh, n, chunk_size=32,
        config=TallyConfig(device_mesh=dm, capacity_factor=4.0),
    )
    rng = np.random.default_rng(2)
    src = rng.uniform(0.1, 0.9, (n, 3))
    src[::8] += 7.0  # out of the unit box
    sp.CopyInitialPosition(src.reshape(-1).copy())
    out = capsys.readouterr().out
    assert "8 source points lie in no mesh element" in out
    ids = sp.elem_ids
    assert np.all(ids[::8] == -1)


def test_streaming_origin_echo_dedup_matches_disabled():
    """Echoed origins reuse the retained per-chunk device dests; flux
    and positions must be bit-identical to auto_continue=False, and a
    recycled caller buffer must not fool the compare."""
    from pumiumtally_tpu import StreamingTally, TallyConfig, build_box

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n, chunk = 3000, 1024  # 3 chunks, last one partial
    rng = np.random.default_rng(21)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    d2 = rng.uniform(0.05, 0.95, (n, 3))

    out = []
    for auto in (True, False):
        t = StreamingTally(mesh, n, chunk_size=chunk,
                           config=TallyConfig(auto_continue=auto))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        t.MoveToNextLocation(d1.reshape(-1).copy(), d2.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        out.append((np.asarray(t.flux), t.positions, t.auto_continue_hits))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])
    assert out[0][2] == 1 and out[1][2] == 0

    # recycled buffer: resampled origins in the same memory must miss
    buf = np.empty(3 * n)
    t = StreamingTally(mesh, n, chunk_size=chunk)
    t.CopyInitialPosition(src.reshape(-1).copy())
    buf[:] = d1.reshape(-1)
    t.MoveToNextLocation(src.reshape(-1).copy(), buf,
                         np.ones(n, np.int8), np.ones(n))
    resampled = rng.uniform(0.05, 0.95, (n, 3))
    buf[:] = resampled.reshape(-1)
    d3 = np.clip(resampled + 0.1, 0.02, 0.98)
    t.MoveToNextLocation(buf, d3.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    assert t.auto_continue_hits == 0
    want = float(np.linalg.norm(d1 - src, axis=1).sum()
                 + np.linalg.norm(d3 - resampled, axis=1).sum())
    got = float(np.sum(np.asarray(t.flux)))
    assert abs(got - want) / want < 1e-12


def test_streaming_unfenced_matches_fenced():
    from pumiumtally_tpu import StreamingTally, TallyConfig, build_box

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n, chunk = 3000, 1024
    rng = np.random.default_rng(22)
    traj = [rng.uniform(0.05, 0.95, (n, 3)) for _ in range(4)]
    out = []
    for fenced in (True, False):
        t = StreamingTally(
            mesh, n, chunk_size=chunk,
            config=TallyConfig(fenced_timing=fenced, check_found_all=False),
        )
        t.CopyInitialPosition(traj[0].reshape(-1).copy())
        for m in range(1, 4):
            t.MoveToNextLocation(traj[m - 1].reshape(-1).copy(),
                                 traj[m].reshape(-1).copy(),
                                 np.ones(n, np.int8), np.ones(n))
        out.append((np.asarray(t.flux), t.positions))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])


def test_streaming_unfenced_recycled_buffers_safe():
    """An unfenced call returns with walks in flight; a host that
    immediately overwrites its (f64, view-aliasable) buffers must not
    corrupt the queued chunks — staging owns its memory when unfenced."""
    from pumiumtally_tpu import StreamingTally, TallyConfig, build_box

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n, chunk = 3000, 1024
    rng = np.random.default_rng(23)
    traj = [rng.uniform(0.05, 0.95, (n, 3)) for _ in range(4)]
    t = StreamingTally(
        mesh, n, chunk_size=chunk,
        config=TallyConfig(fenced_timing=False, check_found_all=False,
                           auto_continue=False),
    )
    obuf, dbuf = np.empty(3 * n), np.empty(3 * n)
    obuf[:] = traj[0].reshape(-1)
    t.CopyInitialPosition(obuf)
    obuf[:] = -1e30  # clobber immediately, walks may still be queued
    for m in range(1, 4):
        obuf[:] = traj[m - 1].reshape(-1)
        dbuf[:] = traj[m].reshape(-1)
        t.MoveToNextLocation(obuf, dbuf, np.ones(n, np.int8), np.ones(n))
        obuf[:] = -1e30  # recycle: clobber both before the next use
        dbuf[:] = -1e30
    got = float(np.sum(np.asarray(t.flux)))
    want = sum(float(np.linalg.norm(traj[m] - traj[m - 1], axis=1).sum())
               for m in range(1, 4))
    assert abs(got - want) / want < 1e-12


def test_streaming_locate_localization_matches_walk():
    from pumiumtally_tpu import StreamingTally, TallyConfig, build_box

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n, chunk = 3000, 1024
    rng = np.random.default_rng(24)
    src = rng.uniform(0.05, 0.95, (n, 3))
    src[::11] += 2.0  # some out-of-hull -> clamp path
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    out = []
    for how in ("walk", "locate"):
        t = StreamingTally(mesh, n, chunk_size=chunk,
                           config=TallyConfig(localization=how))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        out.append((t.positions, t.elem_ids, np.asarray(t.flux)))
    np.testing.assert_allclose(out[0][0], out[1][0], atol=1e-12)
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_allclose(out[0][2], out[1][2], rtol=1e-12, atol=1e-14)


@pytest.mark.slow
def test_streaming_partitioned_device_groups_matches_single_group():
    """dp x part hybrid: chunks round-robin over 2 disjoint 4-device
    groups (each partitioning the mesh over its own chips); flux and
    state match the single-group engine and the monolithic engine."""
    from pumiumtally_tpu import (
        PumiTally,
        StreamingPartitionedTally,
        TallyConfig,
        build_box,
    )
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 3, 3, 3)
    n, chunk = 4000, 1024  # 4 chunks over 2 groups
    dm = make_device_mesh(8)
    rng = np.random.default_rng(25)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))

    out = []
    for groups in (1, 2):
        t = StreamingPartitionedTally(
            mesh, n, chunk_size=chunk,
            config=TallyConfig(device_mesh=dm, device_groups=groups,
                               capacity_factor=4.0),
        )
        assert len({id(e.device_mesh) for e in t.engines}) == groups
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        out.append((np.asarray(t.flux, np.float64), t.positions, t.elem_ids))
    np.testing.assert_allclose(out[0][0], out[1][0], rtol=1e-11, atol=1e-13)
    np.testing.assert_allclose(out[0][1], out[1][1], atol=1e-12)
    np.testing.assert_array_equal(out[0][2], out[1][2])

    ref = PumiTally(mesh, n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    ref.MoveToNextLocation(None, d1.reshape(-1).copy())
    got = float(out[1][0].sum())
    want = float(np.asarray(ref.flux).sum())
    np.testing.assert_allclose(got, want, rtol=1e-11)

    # indivisible group count is rejected
    with pytest.raises(ValueError, match="device_groups"):
        StreamingPartitionedTally(
            mesh, n, chunk_size=chunk,
            config=TallyConfig(device_mesh=dm, device_groups=3),
        )


def test_streaming_partitioned_group_misconfig_rejected():
    from pumiumtally_tpu import StreamingPartitionedTally, TallyConfig, build_box
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 2, 2, 2)
    dm = make_device_mesh(8)
    # more groups than chunks -> trailing groups would idle silently
    with pytest.raises(ValueError, match="chunk"):
        StreamingPartitionedTally(
            mesh, 100, chunk_size=100,
            config=TallyConfig(device_mesh=dm, device_groups=2,
                               capacity_factor=8.0),
        )


def test_streaming_sharded_locate_matches_walk():
    from pumiumtally_tpu import StreamingTally, TallyConfig, build_box
    from pumiumtally_tpu.parallel import make_device_mesh

    dm = make_device_mesh(8)
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n, chunk = 3000, 1024
    rng = np.random.default_rng(27)
    src = rng.uniform(0.05, 0.95, (n, 3))
    src[::10] += 2.0
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    out = []
    for how in ("walk", "locate"):
        t = StreamingTally(
            mesh, n, chunk_size=chunk,
            config=TallyConfig(device_mesh=dm, localization=how),
        )
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        out.append((t.positions, t.elem_ids, np.asarray(t.flux)))
    np.testing.assert_allclose(out[0][0], out[1][0], atol=1e-12)
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_allclose(out[0][2], out[1][2], rtol=1e-12, atol=1e-14)
