"""Frontier-local migration: parity, conservation, occupancy, profiling.

The frontier slab (``TallyConfig.cap_frontier``,
parallel/partition.py ``_frontier_migrate_impl``) makes each in-loop
migration round move only the particles that actually paused at a
partition/block face. The parity contract (docs/DESIGN.md):

- frontier vs the FULL-CAPACITY frontier arm (a slab of ``cap`` rows):
  bitwise identical in everything, flux included — same scatter
  destinations for every row, whatever the slab size;
- the overflow fallback runs today's full-capacity ``_migrate_impl``
  bitwise: an engine whose every round falls back (cap_frontier=0, the
  testing hook) is bitwise identical to the cap_frontier=None default;
- frontier vs the compaction default: per-particle observables
  (positions, elements) bitwise, conservation exact, per-element flux
  equal to scatter-add ordering — the same documented class as
  ``walk_perm_mode="sorted"`` (a different, equally valid slot layout).

The conftest retrace tripwire wraps every test here, so the frontier
phase programs keep the existing compile budgets by construction.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh
from pumiumtally_tpu.parallel.partition import (
    PhaseProfile,
    _frontier_migrate_impl,
    _migrate_impl,
)


def _clustered_workload(n=800, seed=21, moves=2):
    """Corner-clustered sources/destinations on a finely blocked mesh:
    multiple migration rounds with a small crossing front — the
    frontier's home turf."""
    rng = np.random.default_rng(seed)
    src = rng.uniform(0.05, 0.30, (n, 3))
    dsts = [rng.uniform(0.05, 0.30, (n, 3)) for _ in range(moves)]
    return src, dsts


def _run_blocked(cap_frontier, src, dsts, n, profile=None, bound=100,
                 **cfg):
    mesh = build_box(1, 1, 1, 6, 6, 6)
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(walk_vmem_max_elems=bound, walk_block_kernel="gather",
                    capacity_factor=20.0, cap_frontier=cap_frontier,
                    **cfg),
    )
    t.CopyInitialPosition(src.reshape(-1).copy())
    for d in dsts:
        if profile is not None:
            dt = t.engine.state["x"].dtype
            t.engine.move(None, jnp.asarray(d, dt),
                          jnp.asarray(np.ones(n, np.int8)),
                          jnp.asarray(np.ones(n), dt), profile=profile)
        else:
            t.MoveToNextLocation(None, d.reshape(-1).copy())
    return t


# -- bitwise parity: frontier slab vs full-capacity slab ----------------

def test_frontier_vs_full_capacity_slab_bitwise():
    """The same-destinations contract: a working slab and a slab of
    cap rows (the full-capacity frontier migrate) produce bitwise
    identical flux, positions, and elements over a multi-round
    clustered phase — and neither round falls back."""
    n = 800
    src, dsts = _clustered_workload(n)
    t_slab = _run_blocked(4096, src, dsts, n)
    t_full = _run_blocked(10**9, src, dsts, n)  # clamps to cap
    assert t_slab.engine.cap_frontier == 4096
    assert t_full.engine.cap_frontier == t_full.engine.cap
    # Sanity: the slab actually held every round's front (else this
    # test would silently compare fallback rounds).
    assert t_slab.engine.last_frontier_max <= 4096
    assert t_slab.engine.last_fallback_rounds == 0
    assert t_slab.engine.last_walk_rounds >= 2  # migrations happened
    np.testing.assert_array_equal(
        np.asarray(t_slab.flux), np.asarray(t_full.flux)
    )
    np.testing.assert_array_equal(t_slab.positions, t_full.positions)
    np.testing.assert_array_equal(t_slab.elem_ids, t_full.elem_ids)


def test_forced_fallback_bitwise_vs_default():
    """cap_frontier=0 (every round overflows the slab) must reproduce
    the cap_frontier=None default engine bitwise — the fallback IS
    today's ``_migrate_impl``, semantics included."""
    n = 800
    src, dsts = _clustered_workload(n, seed=23)
    t_zero = _run_blocked(0, src, dsts, n)
    t_def = _run_blocked(None, src, dsts, n)
    migrations = t_zero.engine.last_walk_rounds - 1
    assert migrations >= 1
    assert t_zero.engine.last_fallback_rounds == migrations
    assert t_def.engine.last_fallback_rounds == 0  # knob off: not counted
    np.testing.assert_array_equal(
        np.asarray(t_zero.flux), np.asarray(t_def.flux)
    )
    np.testing.assert_array_equal(t_zero.positions, t_def.positions)
    np.testing.assert_array_equal(t_zero.elem_ids, t_def.elem_ids)


def test_frontier_vs_default_engine_and_monolithic():
    """Frontier engine vs the compaction default: per-particle
    observables bitwise, flux equal to scatter-order rounding (the
    documented divergence class) — and both conserve exactly against
    the monolithic reference."""
    n = 800
    src, dsts = _clustered_workload(n, seed=29)
    t_fr = _run_blocked(4096, src, dsts, n)
    t_def = _run_blocked(None, src, dsts, n)
    np.testing.assert_array_equal(t_fr.positions, t_def.positions)
    np.testing.assert_array_equal(t_fr.elem_ids, t_def.elem_ids)
    np.testing.assert_allclose(
        np.asarray(t_fr.flux), np.asarray(t_def.flux),
        rtol=1e-12, atol=1e-13,
    )
    # Conservation + parity with the monolithic engine.
    ref = PumiTally(build_box(1, 1, 1, 6, 6, 6), n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    for d in dsts:
        ref.MoveToNextLocation(None, d.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(t_fr.flux, np.float64),
        np.asarray(ref.flux, np.float64), rtol=1e-10, atol=1e-13,
    )
    want = float(np.linalg.norm(dsts[0] - src, axis=1).sum()) + sum(
        float(np.linalg.norm(dsts[m] - dsts[m - 1], axis=1).sum())
        for m in range(1, len(dsts))
    )
    got = float(np.asarray(t_fr.flux, np.float64).sum())
    np.testing.assert_allclose(got, want, rtol=1e-9)


def test_frontier_multichip_two_phase_bitwise_vs_fullslab():
    """8-chip mesh, two-phase moves (both tally phases migrate), the
    cascade engaged inside walk_local: frontier-vs-full-slab parity
    holds across the whole composition."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 2000
    rng = np.random.default_rng(5)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), -0.1, 1.1)
    out = {}
    for label, cf in (("slab", 2048), ("full", 10**9)):
        t = PartitionedPumiTally(
            mesh, n,
            TallyConfig(device_mesh=make_device_mesh(8),
                        capacity_factor=6.0, walk_min_window=64,
                        cap_frontier=cf),
        )
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        out[label] = t
    assert out["slab"].engine.last_fallback_rounds == 0
    assert out["slab"].engine.last_frontier_max > 0
    np.testing.assert_array_equal(
        np.asarray(out["slab"].flux), np.asarray(out["full"].flux)
    )
    np.testing.assert_array_equal(
        out["slab"].positions, out["full"].positions
    )
    np.testing.assert_array_equal(
        out["slab"].elem_ids, out["full"].elem_ids
    )


def test_frontier_two_tier_bf16_tables():
    """The bf16 two-tier walk tables compose with the frontier slab:
    bitwise parity against the full-capacity slab arm on the same
    tiered engine (migration never touches the tables, but the phase
    program threads them — pin the composition)."""
    n = 600
    src, dsts = _clustered_workload(n, seed=31, moves=1)
    out = {}
    for label, cf in (("slab", 4096), ("full", 10**9)):
        # bound=50: the bf16 tier doubles the block-element bound at
        # constant resident bytes (block_elems_bound), so halve it to
        # keep the mesh finely blocked enough for migrations.
        t = _run_blocked(cf, src, dsts, n, bound=50,
                         walk_table_dtype="bfloat16")
        assert t.engine.two_tier
        out[label] = t
    assert out["slab"].engine.last_walk_rounds >= 2
    np.testing.assert_array_equal(
        np.asarray(out["slab"].flux), np.asarray(out["full"].flux)
    )
    np.testing.assert_array_equal(
        out["slab"].positions, out["full"].positions
    )
    np.testing.assert_array_equal(
        out["slab"].elem_ids, out["full"].elem_ids
    )


# -- migrate-impl level: stayer-fixed placement ------------------------

def test_frontier_migrate_impl_moves_only_the_frontier():
    """Direct _frontier_migrate_impl: stayers keep their slots (zero
    row movement off the frontier), departures reset to defaults,
    arrivals land in the target part's free slots in stable order, and
    the overflow flag matches _migrate_impl's condition exactly."""
    nparts, cap_b, part_L = 5, 16, 50
    cap = nparts * cap_b
    rng = np.random.default_rng(11)
    # Engine-like slack (~1.5x over-provisioning): without free slots,
    # random targets overflow some part almost surely.
    alive = rng.uniform(size=cap) < 0.6
    pend = np.full(cap, -1, np.int32)
    movers = alive & (rng.uniform(size=cap) < 0.2)
    pend[movers] = rng.integers(0, nparts * part_L, movers.sum())
    state = {
        "x": jnp.asarray(rng.random((cap, 3))),
        "w": jnp.asarray(rng.random(cap)),
        "lelem": jnp.asarray(rng.integers(0, part_L, cap), jnp.int32),
        "pending": jnp.asarray(pend),
        "pid": jnp.asarray(np.where(alive, np.arange(cap), -1), jnp.int32),
        "alive": jnp.asarray(alive),
        "done": jnp.asarray(rng.uniform(size=cap) < 0.5),
    }
    st, ovf, dep, arr = _frontier_migrate_impl(
        part_L, nparts, cap_b, cap, dict(state)
    )
    assert not bool(ovf)
    moving = pend >= 0
    stay = alive & ~moving
    # Stayers bitwise in place.
    for k in ("x", "w", "lelem", "pid"):
        np.testing.assert_array_equal(
            np.asarray(st[k])[stay], np.asarray(state[k])[stay], err_msg=k
        )
    # Departed sources are reset to defaults unless an arrival took
    # the slot.
    arrived = np.asarray(st["pending"] == -1) & np.asarray(st["alive"])
    vacated = moving & ~np.asarray(st["alive"])
    assert np.all(np.asarray(st["pid"])[vacated] == -1)
    # Every mover arrived somewhere in its target part's slot range.
    tgt_counts = np.bincount(pend[moving] // part_L, minlength=nparts)
    new_chip = np.arange(cap) // cap_b
    moved_in = arrived & ~stay
    got_counts = np.bincount(new_chip[moved_in], minlength=nparts)
    np.testing.assert_array_equal(got_counts, tgt_counts)
    # Occupancy deltas: arrivals bucketed by target, departures by
    # source part, both totalling the frontier.
    np.testing.assert_array_equal(np.asarray(arr), tgt_counts)
    np.testing.assert_array_equal(
        np.asarray(dep),
        np.bincount(np.arange(cap)[moving] // cap_b, minlength=nparts),
    )
    assert int(np.asarray(dep).sum()) == int(moving.sum())
    # Same overflow condition as the full migrate.
    _, ovf_full = _migrate_impl(part_L, nparts, cap_b, dict(state))
    assert bool(ovf) == bool(ovf_full)


def test_frontier_capacity_overflow_recovers_like_default():
    """A real capacity overflow (every particle into one corner block
    with capacity_factor ~1) engages the round-9 recovery ladder
    through the frontier path exactly as through the default: the
    move COMPLETES (it raised OVERFLOW_MESSAGE before round 9), the
    engine records the recovery + escalation, and both paths' final
    flux matches a generously provisioned engine (scatter-order
    class). The frontier-vs-default overflow-condition equivalence is
    pinned by test_frontier_overflow_condition_matches_default
    above."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 600
    rng = np.random.default_rng(3)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = rng.uniform(0.02, 0.12, (n, 3))  # converge into one corner
    big = PartitionedPumiTally(
        mesh, n,
        TallyConfig(walk_vmem_max_elems=100,
                    walk_block_kernel="gather", capacity_factor=12.0),
    )
    big.CopyInitialPosition(src.reshape(-1).copy())
    big.MoveToNextLocation(None, dst.reshape(-1).copy())
    for cf in (4096, None):
        # 1.3x headroom: enough for the spread localization (Poisson
        # block occupancy at n/blocks ~ 46), nowhere near enough for
        # the corner convergence.
        t = PartitionedPumiTally(
            mesh, n,
            TallyConfig(walk_vmem_max_elems=100,
                        walk_block_kernel="gather",
                        capacity_factor=1.3, cap_frontier=cf),
        )
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dst.reshape(-1).copy())
        assert t.engine.overflow_recoveries >= 1
        assert t.engine.capacity_escalations >= 1
        assert not t.engine.poisoned
        np.testing.assert_allclose(
            np.asarray(t.flux), np.asarray(big.flux),
            rtol=1e-12, atol=1e-15,
        )


# -- incremental occupancy ---------------------------------------------

def test_incremental_occupancy_equivalence():
    """The incremental occupied-block list (departure/arrival deltas)
    must dispatch exactly the blocks the default engine's full done
    scan dispatches — block membership is physics, not layout — while
    still skipping empty blocks on a clustered workload."""
    n = 800
    src, dsts = _clustered_workload(n, seed=21)
    t_fr = _run_blocked(4096, src, dsts, n)
    t_def = _run_blocked(None, src, dsts, n)
    blocks = t_fr.engine.nparts
    assert blocks >= 8
    assert t_fr.engine.last_walk_rounds == t_def.engine.last_walk_rounds
    assert (t_fr.engine.last_block_dispatches
            == t_def.engine.last_block_dispatches)
    rounds = t_fr.engine.last_walk_rounds
    disp = t_fr.engine.last_block_dispatches
    assert disp < rounds * blocks, (disp, rounds, blocks)
    assert disp >= rounds


# -- diagnostics + profiled driver -------------------------------------

def test_frontier_diagnostics_populated():
    n = 800
    src, dsts = _clustered_workload(n, seed=37, moves=1)
    t = _run_blocked(4096, src, dsts, n)
    eng = t.engine
    migrations = eng.last_walk_rounds - 1
    assert migrations >= 1
    assert eng.last_frontier_max >= 1
    assert 0.0 < eng.last_frontier_mean <= eng.last_frontier_max
    assert eng.last_fallback_rounds == 0
    # Mean * migrations == the summed fronts (int bookkeeping).
    assert eng.last_frontier_mean * migrations == pytest.approx(
        eng._last_frontier_sum_cache
    )


def test_profiled_move_bitwise_and_budget():
    """The profiled driver (one fenced dispatch per component per
    round) runs the same round/migrate/occupancy programs as the fused
    phase: flux/positions bitwise vs an unprofiled engine of the same
    config, with every budget section populated."""
    n = 800
    src, dsts = _clustered_workload(n, seed=41)
    prof = PhaseProfile()
    t_prof = _run_blocked(4096, src, dsts, n, profile=prof)
    t_fused = _run_blocked(4096, src, dsts, n)
    np.testing.assert_array_equal(
        np.asarray(t_prof.flux), np.asarray(t_fused.flux)
    )
    np.testing.assert_array_equal(t_prof.positions, t_fused.positions)
    assert prof.rounds >= 2
    assert prof.dispatches >= prof.rounds
    assert prof.walk_s > 0 and prof.migrate_s > 0
    assert prof.occupancy_s > 0 and prof.bookkeeping_s > 0
    assert prof.fallback_rounds == 0
    assert len(prof.frontier_sizes) == prof.rounds - len(dsts)
    assert prof.frontier_max == max(prof.frontier_sizes)
    # The last_* diagnostics keep their most-recent-phase contract
    # under profiling (same workload -> same last phase as the fused
    # engine's).
    assert (t_prof.engine.last_walk_rounds
            == t_fused.engine.last_walk_rounds >= 1)
    assert (t_prof.engine.last_block_dispatches
            == t_fused.engine.last_block_dispatches)
    assert (t_prof.engine.last_frontier_max
            == t_fused.engine.last_frontier_max)
    assert t_prof.engine.last_fallback_rounds == 0
    d = prof.as_dict()
    for key in ("walk_ms", "migrate_ms", "occupancy_ms", "rounds",
                "dispatches", "frontier_max", "frontier_mean",
                "cap_frontier", "fallback_rounds"):
        assert key in d
    assert d["cap_frontier"] == 4096


def test_profile_defer_sync_mutually_exclusive():
    n = 64
    src, dsts = _clustered_workload(n, seed=2, moves=1)
    t = _run_blocked(None, src, dsts, n)
    with pytest.raises(ValueError, match="defer_sync"):
        t.engine._run_phase(tally=True, defer_sync=True,
                            profile=PhaseProfile())


def test_cap_frontier_config_validation():
    with pytest.raises(ValueError, match="cap_frontier"):
        TallyConfig(cap_frontier=-1)
    assert TallyConfig(cap_frontier=0).cap_frontier == 0
    assert TallyConfig().cap_frontier is None
