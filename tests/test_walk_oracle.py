"""THE parity suite: the reference's exact-arithmetic flux oracles.

Reproduces the white-box integration test of the reference
(test/test_pumi_tally_impl_methods.cpp) against our three-call API, with
the hand-computed expected values from BASELINE.md:

- localization: all particles → element 2 from (0.1,0.4,0.5), flux all
  zero after the initial search (test:152-170)
- move 1: ray to (1.2,0.4,0.5) crosses elems 2,3,4 with lengths
  0.3/0.1/0.5; exits the box → position clamps to x=1.0, element 4
  (test:221-282)
- move 2: mixed weights/flying; flux[3] += 0.08790490988459178*2,
  flux[4] += 0.879049070406094*2 + 0.552268050859363*0.5 (test:361-389)

Note: move 2 passes the particles' CURRENT committed positions
(1.0,0.4,0.5) as origins — the production contract (see
api/tally.py docstring); the reference test passes stale origins there
but was never built by its CI (SURVEY.md §2.1).
"""

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box

NUM = 5
TOL = 1e-8  # reference comparison tolerance (test:21-27)


@pytest.fixture()
def tally():
    mesh = build_box(1, 1, 1, 1, 1, 1)
    return PumiTally(mesh, NUM, TallyConfig())


def _flat(points):
    return np.ascontiguousarray(np.asarray(points, dtype=np.float64).reshape(-1))


def test_initial_seed_at_elem0_centroid(tally):
    # All particles start at elem 0's centroid (test:81-109).
    np.testing.assert_allclose(
        tally.positions, np.tile([0.5, 0.75, 0.25], (NUM, 1)), atol=TOL
    )
    np.testing.assert_array_equal(tally.elem_ids, np.zeros(NUM))


def test_full_oracle_sequence(tally):
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    tally.CopyInitialPosition(_flat(init), 3 * NUM)

    # -- localization oracle (test:152-170) --
    np.testing.assert_array_equal(tally.elem_ids, np.full(NUM, 2))
    np.testing.assert_allclose(np.asarray(tally.flux), 0.0, atol=TOL)
    np.testing.assert_allclose(tally.positions, init, atol=TOL)

    # -- move 1 (test:176-282) --
    dests = np.tile([1.2, 0.4, 0.5], (NUM, 1))
    flying = np.ones(NUM, dtype=np.int8)
    weights = np.ones(NUM)
    tally.MoveToNextLocation(_flat(init), _flat(dests), flying, weights, 3 * NUM)

    # flying zeroed in place (test:186-212, reference cpp:169-172)
    np.testing.assert_array_equal(flying, np.zeros(NUM, dtype=np.int8))
    # all particles reach element 4 (test:221-228)
    np.testing.assert_array_equal(tally.elem_ids, np.full(NUM, 4))
    # boundary clamp to x=1.0 (test:242-245)
    np.testing.assert_allclose(
        tally.positions, np.tile([1.0, 0.4, 0.5], (NUM, 1)), atol=TOL
    )
    flux = np.asarray(tally.flux)
    expected1 = np.array([0.0, 0.0, 0.3 * NUM, 0.1 * NUM, 0.5 * NUM, 0.0])
    np.testing.assert_allclose(flux, expected1, atol=TOL)

    # -- move 2 (test:284-390) --
    # Origins are the committed positions (production contract).
    origins = np.tile([1.0, 0.4, 0.5], (NUM, 1))
    next_pos = np.tile([1.0, 0.4, 0.5], (NUM, 1))
    flying2 = np.zeros(NUM, dtype=np.int8)
    weights2 = np.ones(NUM)
    next_pos[0] = [0.15, 0.05, 0.20]
    flying2[0], weights2[0] = 1, 2.0
    next_pos[2] = [0.85, 0.05, 0.10]
    flying2[2], weights2[2] = 1, 0.5

    tally.MoveToNextLocation(
        _flat(origins), _flat(next_pos), flying2, weights2, 3 * NUM
    )

    # new committed positions == destinations (test:323-346)
    np.testing.assert_allclose(tally.positions, next_pos, atol=TOL)
    # final elements (test:348-359)
    np.testing.assert_array_equal(tally.elem_ids, [3, 4, 4, 4, 4])

    flux2 = np.asarray(tally.flux)
    expected2 = expected1.copy()
    expected2[3] += 0.08790490988459178 * 2.0
    expected2[4] += 0.879049070406094 * 2.0 + 0.552268050859363 * 0.5
    np.testing.assert_allclose(flux2, expected2, atol=TOL)


def test_resampled_particle_relocates_without_tally(tally):
    """Phase A's purpose (reference PumiTally.h:80-86): a reincarnated
    particle shows up at a new origin; it must relocate there WITHOUT
    contributing flux, then tally only the origin→destination leg."""
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    tally.CopyInitialPosition(_flat(init), 3 * NUM)

    # Particle 0 is "resampled" far from its current position.
    origins = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    origins[0] = [0.9, 0.1, 0.05]  # x≥y≥z region → elem 5
    dests = origins.copy()
    dests[0] = [0.9, 0.2, 0.05]  # short +y hop staying in elem 5
    flying = np.zeros(NUM, dtype=np.int8)
    flying[0] = 1
    weights = np.ones(NUM)
    tally.MoveToNextLocation(_flat(origins), _flat(dests), flying, weights, 3 * NUM)

    flux = np.asarray(tally.flux)
    # Only the tallied leg (length 0.1, weight 1) in elem 5.
    expected = np.zeros(6)
    expected[5] = 0.1
    np.testing.assert_allclose(flux, expected, atol=TOL)
    np.testing.assert_allclose(tally.positions[0], dests[0], atol=TOL)
    assert tally.elem_ids[0] == 5


def test_nonflying_particles_hold_and_do_not_tally(tally):
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    tally.CopyInitialPosition(_flat(init), 3 * NUM)
    origins = init.copy()
    dests = np.tile([0.9, 0.4, 0.5], (NUM, 1))
    flying = np.zeros(NUM, dtype=np.int8)  # nobody flies
    weights = np.ones(NUM)
    tally.MoveToNextLocation(_flat(origins), _flat(dests), flying, weights, 3 * NUM)
    np.testing.assert_allclose(np.asarray(tally.flux), 0.0, atol=TOL)
    np.testing.assert_allclose(tally.positions, init, atol=TOL)
    np.testing.assert_array_equal(tally.elem_ids, np.full(NUM, 2))


def test_flux_accumulates_across_moves(tally):
    init = np.tile([0.2, 0.4, 0.5], (NUM, 1))
    tally.CopyInitialPosition(_flat(init), 3 * NUM)
    origins = init.copy()
    dests = np.tile([0.3, 0.4, 0.5], (NUM, 1))  # stays inside elem 2
    flying = np.ones(NUM, dtype=np.int8)
    weights = np.full(NUM, 0.25)
    tally.MoveToNextLocation(_flat(origins), _flat(dests), flying.copy(), weights, 3 * NUM)
    tally.MoveToNextLocation(_flat(dests), _flat(init), flying.copy(), weights, 3 * NUM)
    flux = np.asarray(tally.flux)
    expected = np.zeros(6)
    expected[2] = 2 * NUM * 0.1 * 0.25
    np.testing.assert_allclose(flux, expected, atol=TOL)


def test_conservation_invariant_under_rigid_transform():
    """Physics pin: rotating+translating the mesh AND the trajectory
    together must leave the total track length invariant (the walk has
    no axis-aligned assumptions) and preserve per-element flux up to
    the element reordering identity (same mesh topology)."""
    import numpy as np

    from pumiumtally_tpu import PumiTally
    from pumiumtally_tpu.mesh.tetmesh import TetMesh
    from pumiumtally_tpu.mesh.box import box_arrays

    coords, tets = box_arrays(1, 1, 1, 3, 3, 3)
    # a random (proper) rotation + translation
    rng = np.random.default_rng(17)
    a, b, c = rng.uniform(0, 2 * np.pi, 3)

    def rot(axis, t):
        cs, sn = np.cos(t), np.sin(t)
        m = np.eye(3)
        i, j = [(1, 2), (0, 2), (0, 1)][axis]
        m[i, i] = cs
        m[i, j] = -sn if axis != 1 else sn
        m[j, i] = sn if axis != 1 else -sn
        m[j, j] = cs
        return m

    R = rot(0, a) @ rot(1, b) @ rot(2, c)
    t0 = np.array([3.0, -2.0, 5.0])
    n = 2000
    src = rng.uniform(0.1, 0.9, (n, 3))
    dst = rng.uniform(0.1, 0.9, (n, 3))

    fluxes = []
    for xform in (lambda p: p, lambda p: p @ R.T + t0):
        mesh = TetMesh.from_arrays(xform(coords), tets)
        t = PumiTally(mesh, n)
        t.CopyInitialPosition(xform(src).reshape(-1).copy())
        t.MoveToNextLocation(xform(src).reshape(-1).copy(),
                             xform(dst).reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        fluxes.append(np.asarray(t.flux, np.float64))
    expect = float(np.linalg.norm(dst - src, axis=1).sum())
    for fl in fluxes:
        np.testing.assert_allclose(fl.sum(), expect, rtol=1e-9)
    # per-element flux identical up to FP rounding of the rotation
    np.testing.assert_allclose(fluxes[0], fluxes[1], rtol=2e-7, atol=1e-10)


def test_intersection_points_debug_surface():
    """Reference getIntersectionPoints() parity (PumiTallyImpl.h:177-178,
    test:464-467): the last face-intersection point per particle, using
    the same 6-tet geometry as the flux oracle. On the oracle ray
    (0.1,0.4,0.5)->(1.2,...) the walk crosses faces at x=0.4 and x=0.5
    and exits the boundary at x=1.0 — the LAST intersection is the
    boundary point. A shorter ray to x=0.45 (inside elem 3) last
    crosses at x=0.4; a no-crossing move keeps the start point; a
    non-flying particle keeps its position."""
    mesh = build_box(1, 1, 1, 1, 1, 1)
    t = PumiTally(mesh, NUM, TallyConfig(record_xpoints=True))
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    t.CopyInitialPosition(_flat(init), 3 * NUM)
    # Before any move: xpoints == starting positions (the reference's
    # UpdatePreviousXPoints(ptcls) initialization).
    np.testing.assert_allclose(t.intersection_points(), init, atol=TOL)

    # Oracle move 1: exits the box at x=1.0 -> boundary intersection.
    dests = np.tile([1.2, 0.4, 0.5], (NUM, 1))
    t.MoveToNextLocation(_flat(init), _flat(dests),
                         np.ones(NUM, np.int8), np.ones(NUM))
    np.testing.assert_allclose(
        t.intersection_points(), np.tile([1.0, 0.4, 0.5], (NUM, 1)),
        atol=TOL,
    )

    # Fresh engine: ray stopping inside elem 3 -> last crossing x=0.4.
    t2 = PumiTally(mesh, NUM, TallyConfig(record_xpoints=True))
    t2.CopyInitialPosition(_flat(init), 3 * NUM)
    half = np.tile([0.45, 0.4, 0.5], (NUM, 1))
    t2.MoveToNextLocation(_flat(init), _flat(half),
                          np.ones(NUM, np.int8), np.ones(NUM))
    np.testing.assert_allclose(
        t2.intersection_points(), np.tile([0.4, 0.4, 0.5], (NUM, 1)),
        atol=TOL,
    )
    # Continue-mode micro-move inside the current tet: no face crossed,
    # xpoints fall back to the move's start points.
    tiny = half + np.tile([0.001, 0.0, 0.0], (NUM, 1))
    t2.MoveToNextLocation(None, _flat(tiny))
    np.testing.assert_allclose(t2.intersection_points(), half, atol=TOL)
    # Non-flying particles hold position and record no crossing.
    fly = np.ones(NUM, np.int8)
    fly[0] = 0
    far = np.tile([0.9, 0.4, 0.5], (NUM, 1))
    t2.MoveToNextLocation(_flat(tiny), _flat(far), fly, np.ones(NUM))
    xp = t2.intersection_points()
    np.testing.assert_allclose(xp[0], tiny[0], atol=TOL)
    np.testing.assert_allclose(xp[1:], np.tile([0.5, 0.4, 0.5], (NUM - 1, 1)),
                               atol=TOL)

    # Off by default: the facade must refuse rather than silently
    # return stale data.
    t3 = PumiTally(mesh, NUM)
    t3.CopyInitialPosition(_flat(init), 3 * NUM)
    with pytest.raises(RuntimeError, match="record_xpoints"):
        t3.intersection_points()
    # Subclasses route moves through their own engines and never
    # populate the stash — they must refuse too, not return stale data.
    from pumiumtally_tpu import PartitionedPumiTally, StreamingTally

    t4 = PartitionedPumiTally(mesh, NUM, TallyConfig(record_xpoints=True))
    t4.CopyInitialPosition(_flat(init), 3 * NUM)
    with pytest.raises(NotImplementedError, match="PartitionedPumiTally"):
        t4.intersection_points()
    t5 = StreamingTally(mesh, NUM, 4, TallyConfig(record_xpoints=True))
    t5.CopyInitialPosition(_flat(init), 3 * NUM)
    with pytest.raises(NotImplementedError, match="StreamingTally"):
        t5.intersection_points()
