"""jaxlint analyzer tests: a fixture corpus of known-bad snippets.

Each corpus entry is one minimal trace-safety violation; the assertions
pin EXACT rule ids and line numbers so a rule that drifts (fires on the
wrong line, or stops firing) fails loudly rather than rotting. The
self-check at the bottom asserts the shipped engine is jaxlint-clean —
the same gate CI runs (.github/workflows/static-analysis.yml).

Pure host-side tests: the analyzer never imports jax or executes the
snippets, so this module needs no devices and runs first-class in
tier 1.
"""

import os
import subprocess
import sys

from pumiumtally_tpu.analysis import RULES, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ids(diags):
    return [(d.rule, d.line) for d in diags]


# ---------------------------------------------------------------------------
# JL001 — host sync inside a traced body
# ---------------------------------------------------------------------------

def test_jl001_item_in_jit():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()
"""
    assert ids(lint_source(src)) == [("JL001", 5)]


def test_jl001_device_get_and_asarray():
    src = """\
import jax
import numpy as np

@jax.jit
def f(x):
    y = np.asarray(x)
    return jax.device_get(y)
"""
    assert ids(lint_source(src)) == [("JL001", 6), ("JL001", 7)]


def test_jl001_float_on_traced():
    src = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return x * float(jnp.max(x))
"""
    assert ids(lint_source(src)) == [("JL001", 6)]


def test_jl001_inside_while_loop_body():
    src = """\
from jax import lax

def run(state):
    def body(s):
        return s + s.item()
    return lax.while_loop(lambda s: s.sum() > 0, body, state)
"""
    assert ids(lint_source(src)) == [("JL001", 5)]


def test_jl001_not_flagged_outside_trace():
    # The same calls at the host boundary are the API working as
    # intended — zero diagnostics.
    src = """\
import numpy as np

def fetch(dev):
    return np.asarray(dev), dev.item()
"""
    assert lint_source(src) == []


def test_jl001_asarray_of_static_is_fine():
    src = """\
import jax
import numpy as np

@jax.jit
def f(x, shape_tuple=(3, 4)):
    n = np.asarray([1, 2, 3])
    return x
"""
    # np.asarray of a concrete literal at trace time is legal constant
    # folding, not a sync.
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL002 — Python control flow on traced values
# ---------------------------------------------------------------------------

def test_jl002_if_and_while():
    src = """\
import jax

@jax.jit
def f(x):
    if x > 0:
        x = x + 1
    while x < 10:
        x = x * 2
    return x
"""
    assert ids(lint_source(src)) == [("JL002", 5), ("JL002", 7)]


def test_jl002_assert_and_ifexp():
    src = """\
import jax

@jax.jit
def f(x):
    assert x.sum() > 0
    return x if x.max() > 1 else -x
"""
    assert ids(lint_source(src)) == [("JL002", 5), ("JL002", 6)]


def test_jl002_static_branches_allowed():
    # Branching on shapes, None-ness, static args, len() — the
    # bookkeeping every JAX kernel is full of — must NOT flag.
    src = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode",))
def f(x, w, mode="fast"):
    if mode == "fast":
        x = x + 1
    if w is None:
        w = x
    if x.shape[0] > 4:
        x = x[:4]
    if len(x.shape) == 2:
        x = x.sum(0)
    return x + w
"""
    assert lint_source(src) == []


def test_jl002_retaint_inside_loop_uses_fresh_taint():
    """Expression checks must see taint AS OF the statement's position:
    a variable reassigned to a concrete value inside a loop must not be
    judged by its stale pre-loop taint (and the stale verdict must not
    pin `seen`)."""
    src = """\
import jax

@jax.jit
def f(x, xs):
    v = x * 2
    for i in range(3):
        v = x.shape[0]
        h = float(v)
    return x
"""
    assert lint_source(src) == []


def test_jl001_augassign_keeps_taint():
    """`x += 1` reads the traced x — it must stay traced (a plain
    overwrite-with-RHS-taint analysis silently drops it)."""
    src = """\
import jax

@jax.jit
def f(x):
    x += 1
    if x > 0:
        x = -x
    return x
"""
    assert ids(lint_source(src)) == [("JL002", 6)]


# ---------------------------------------------------------------------------
# JL003 — use after donation
# ---------------------------------------------------------------------------

def test_jl003_use_after_donate():
    src = """\
import jax

def update(s, u):
    return s + u

step = jax.jit(update, donate_argnums=(0,))

def run(state, u):
    out = step(state, u)
    return out + state.sum()
"""
    assert ids(lint_source(src)) == [("JL003", 10)]


def test_jl003_multiline_call_args_do_not_self_flag():
    """A donating call written across several lines must not flag its
    own argument list; a later use still flags."""
    src = """\
import jax

def update(s, u):
    return s + u

step = jax.jit(update, donate_argnums=(0,))

def run(state, u):
    out = step(
        state,
        u,
    )
    return out + state.sum()
"""
    assert ids(lint_source(src)) == [("JL003", 13)]


def test_jl003_rebind_is_clean():
    src = """\
import jax

def update(s, u):
    return s + u

step = jax.jit(update, donate_argnums=(0,))

def run(state, u):
    state = step(state, u)
    return state.sum()
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL004 — retrace-bait static defaults
# ---------------------------------------------------------------------------

def test_jl004_list_default():
    src = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("knobs",))
def walk(x, knobs=[8, 4]):
    return x
"""
    assert ids(lint_source(src)) == [("JL004", 5)]


def test_jl004_array_default_via_static_argnums():
    src = """\
import jax
import numpy as np

@jax.jit(static_argnums=(1,))
def f(x, table=np.zeros(4)):
    return x
"""
    assert ids(lint_source(src)) == [("JL004", 5)]


def test_jl004_tuple_default_is_clean():
    src = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("knobs",))
def walk(x, knobs=(8, 4)):
    return x
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL005 — module-state mutation under trace
# ---------------------------------------------------------------------------

def test_jl005_global_and_container():
    src = """\
import jax

CACHE = {}
COUNT = 0

@jax.jit
def f(x):
    global COUNT
    COUNT = COUNT + 1
    CACHE[0] = x
    return x
"""
    assert ids(lint_source(src)) == [("JL005", 9), ("JL005", 10)]


def test_jl005_mutator_method():
    src = """\
import jax

LOG = []

@jax.jit
def f(x):
    LOG.append(1)
    return x
"""
    assert ids(lint_source(src)) == [("JL005", 7)]


# ---------------------------------------------------------------------------
# One-level helper resolution
# ---------------------------------------------------------------------------

def test_indirect_sync_one_level():
    src = """\
import jax

def fetch(v):
    return v.item()

@jax.jit
def f(x):
    return fetch(x)
"""
    # The diagnostic lands on the sync INSIDE the helper (line 4),
    # reached through the traced call on line 8.
    assert ids(lint_source(src)) == [("JL001", 4)]


def test_indirect_taint_through_helper_args():
    src = """\
import jax

def branchy(flag, v):
    if flag:
        return v
    return -v

@jax.jit
def f(x):
    return branchy(x > 0, x)
"""
    assert ids(lint_source(src)) == [("JL002", 4)]


def test_two_levels_not_followed():
    # Depth limit is ONE: a sync two hops away is out of scope (the
    # documented precision/recall trade — see docs/STATIC_ANALYSIS.md).
    src = """\
import jax

def inner(v):
    return v.item()

def outer(v):
    return inner(v)

@jax.jit
def f(x):
    return outer(x)
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

def test_pragma_with_justification_suppresses():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()  # jaxlint: disable=JL001 -- boundary fetch by design
"""
    assert lint_source(src) == []


def test_pragma_without_justification_is_jl000():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()  # jaxlint: disable=JL001
"""
    # The bare pragma reports JL000 AND the original finding survives.
    assert sorted(ids(lint_source(src))) == [("JL000", 5), ("JL001", 5)]


def test_pragma_unknown_rule_reported():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()  # jaxlint: disable=JL999 -- no such rule
"""
    got = ids(lint_source(src))
    assert ("JL000", 5) in got and ("JL001", 5) in got


def test_pragma_only_disables_named_rule():
    src = """\
import jax

@jax.jit
def f(x):
    if x > 0:
        x = x.item()  # jaxlint: disable=JL002 -- wrong rule named
    return x
"""
    got = ids(lint_source(src))
    assert got == [("JL002", 5), ("JL001", 6)]


# ---------------------------------------------------------------------------
# Rule registry / CLI contract
# ---------------------------------------------------------------------------

def test_rule_registry_complete():
    assert sorted(RULES) == [
        "JL000", "JL001", "JL002", "JL003", "JL004", "JL005",
        "JL101", "JL102", "JL103", "JL104",
        "JL201", "JL202", "JL203", "JL204",
        "JL301", "JL302", "JL303",
        "JL401", "JL402", "JL403", "JL404",
        "JL501", "JL502", "JL503",
    ]
    # Registration order == id order (the --list-rules contract).
    assert list(RULES) == sorted(RULES)
    for rule in RULES.values():
        assert rule.summary and rule.doc
        assert "bad" in rule.doc and "good" in rule.doc


def test_jit_wrapped_in_registration_call_still_analyzed():
    """register_entry_point (the retrace counting wrapper) must not
    hide the jit from trace-root discovery — the engine's own
    `_move_step = register_entry_point("walk", jit(move_step))` form."""
    src = """\
import jax
from functools import partial
from pumiumtally_tpu.utils.profiling import register_entry_point

def move_step(x, tol):
    return x.item()

_move_step = register_entry_point(
    "walk",
    partial(jax.jit, static_argnames=("tol",))(move_step),
)
"""
    assert ids(lint_source(src)) == [("JL001", 6)]


def test_cli_missing_path_is_usage_error():
    """A typo'd target must not read as clean (exit 2, like ruff)."""
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "no_such_dir_xyz/"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


def test_cli_nonzero_on_bad_corpus(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    assert "JL001" in proc.stdout and "bad.py:5" in proc.stdout


def test_cli_explain():
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "--explain", "JL004"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    assert "retrace" in proc.stdout


# ---------------------------------------------------------------------------
# Self-check: the shipped engine is jaxlint-clean
# ---------------------------------------------------------------------------

def test_engine_is_jaxlint_clean():
    """The acceptance gate CI enforces, as a test: every diagnostic in
    the engine tree is either fixed or carries a justified pragma."""
    from pumiumtally_tpu.analysis import lint_paths

    diags = lint_paths([os.path.join(REPO, "pumiumtally_tpu")])
    assert diags == [], "\n".join(d.render() for d in diags)


def test_stats_subsystem_registered_and_pragma_free():
    """The batch-statistics modules (r7) must be IN the self-check's
    file set (a packaging slip that moved them out of the package tree
    would silently drop their coverage) and hold the strongest form of
    the clean contract: zero violations with zero pragmas — the stats
    layer only ever reads engine arrays, so it has no excuse for even
    a justified suppression."""
    import glob

    stats_dir = os.path.join(REPO, "pumiumtally_tpu", "stats")
    files = sorted(glob.glob(os.path.join(stats_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "accumulators.py", "estimators.py",
            "triggers.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    assert lint_paths(files) == []
    for f in files:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the stats modules ship pragma-free"
            )


def test_resilience_subsystem_registered_and_pragma_free():
    """The fault-tolerance modules (r8) must be IN the self-check's
    file set and hold the strongest form of the clean contract: zero
    violations with zero pragmas — the resilience layer is host-side
    Python over numpy buffers (no jitted code at all), so it has no
    excuse for even a justified suppression."""
    import glob

    res_dir = os.path.join(REPO, "pumiumtally_tpu", "resilience")
    files = sorted(glob.glob(os.path.join(res_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "generations.py", "policy.py",
            "faults.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    assert lint_paths(files) == []
    for f in files:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the resilience modules ship pragma-free"
            )


def test_sentinel_subsystem_registered_and_pragma_free():
    """The runtime-sentinel modules (r9) must be IN the self-check's
    file set and hold the strongest form of the clean contract: zero
    violations with zero pragmas — the audit/retry programs are plain
    jitted reductions and walks with no host syncs reachable from a
    trace, so there is no excuse for even a justified suppression.
    The bench-consumed A/B tool is covered the same way (it is in
    tools/lint_all.py's jaxlint targets)."""
    import glob

    sen_dir = os.path.join(REPO, "pumiumtally_tpu", "sentinel")
    files = sorted(glob.glob(os.path.join(sen_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "policy.py", "audit.py", "straggler.py",
            "quarantine.py", "runner.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    ab = os.path.join(REPO, "tools", "exp_sentinel_ab.py")
    assert lint_paths(files + [ab]) == []
    for f in files + [ab]:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the sentinel modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_sentinel_ab.py" in fh.read()


def test_scoring_subsystem_registered_and_pragma_free():
    """The filtered-scoring modules (r10) must be IN the self-check's
    file set and hold the strongest form of the clean contract: zero
    violations with zero pragmas — the bin resolution and the walk
    hook are plain jitted array programs with no host syncs reachable
    from a trace, so there is no excuse for even a justified
    suppression. The bench-consumed A/B tool is covered the same way
    (it is in tools/lint_all.py's jaxlint targets)."""
    import glob

    sc_dir = os.path.join(REPO, "pumiumtally_tpu", "scoring")
    files = sorted(glob.glob(os.path.join(sc_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "filters.py", "scores.py",
            "binding.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    ab = os.path.join(REPO, "tools", "exp_scoring_ab.py")
    assert lint_paths(files + [ab]) == []
    for f in files + [ab]:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the scoring modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_scoring_ab.py" in fh.read()


def test_service_subsystem_registered_and_pragma_free():
    """The multi-session-service modules (r11, plus the r12 fusion
    module and the r20 traffic-engineering additions) must be IN the
    self-check's file set and hold the strongest form of the clean
    contract: zero violations with zero pragmas — the service layer
    (priority lanes, admission ledger, latency telemetry included) is
    host-side threading and prepacked numpy buffers, and its ONE
    trace root (fusion.py's walk_fused) is a plain jitted
    pack/walk/split program with no host syncs reachable from the
    trace, so there is no excuse for even a justified suppression.
    The bench-consumed A/B tools and the r20 load generator (pure
    stdlib+numpy — it must stay importable without jax) are covered
    the same way (they are in tools/lint_all.py's jaxlint
    targets)."""
    import glob

    svc_dir = os.path.join(REPO, "pumiumtally_tpu", "service")
    files = sorted(glob.glob(os.path.join(svc_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "session.py", "scheduler.py", "staging.py",
            "server.py", "fusion.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    abs_ = [os.path.join(REPO, "tools", "exp_service_ab.py"),
            os.path.join(REPO, "tools", "exp_fusion_ab.py"),
            os.path.join(REPO, "tools", "exp_service_load.py"),
            os.path.join(REPO, "tools", "loadgen.py")]
    assert lint_paths(files + abs_) == []
    for f in files + abs_:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the service modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tools (a slip here
    # would silently drop their CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        targets = fh.read()
    assert "tools/exp_service_ab.py" in targets
    assert "tools/exp_fusion_ab.py" in targets
    assert "tools/exp_service_load.py" in targets
    assert "tools/loadgen.py" in targets
    # loadgen must not import jax — scripted clients run anywhere.
    with open(os.path.join(REPO, "tools", "loadgen.py")) as fh:
        src = fh.read()
    assert "import jax" not in src


def test_distributed_subsystem_registered_and_pragma_free():
    """The pod-scale distributed module (r13) must be IN the
    self-check's file set (parallel/ is inside the package tree the
    self-check lints) and hold the strongest form of the clean
    contract: zero violations with zero pragmas — the collective
    migration is one shard_map'd all_gather + ppermute-ring program
    with no host syncs reachable from the trace, and the front-door
    helpers (init/probe/fetch) do their host work OUTSIDE any trace.
    The bench-consumed A/B tool is covered the same way (it is in
    tools/lint_all.py's jaxlint targets)."""
    import glob

    par_dir = os.path.join(REPO, "pumiumtally_tpu", "parallel")
    files = sorted(glob.glob(os.path.join(par_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert "distributed.py" in names
    from pumiumtally_tpu.analysis import lint_paths

    ab = os.path.join(REPO, "tools", "exp_distributed_ab.py")
    assert lint_paths(files + [ab]) == []
    for f in files + [ab]:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the distributed modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_distributed_ab.py" in fh.read()


def test_pallas_walk_kernel_registered_and_pragma_free():
    """The one-kernel Pallas walk (r17) must be IN the self-check's
    file set (ops/ is inside the package tree the self-check lints)
    and hold the strongest form of the clean contract: zero violations
    with zero pragmas — the kernel body is a grid-pipelined pallas_call
    whose while-loop state lives in output refs, with no host syncs
    reachable from the trace. The bench-consumed A/B tool is covered
    the same way (it is in tools/lint_all.py's jaxlint targets)."""
    from pumiumtally_tpu.analysis import lint_paths

    kern = os.path.join(REPO, "pumiumtally_tpu", "ops", "pallas_walk.py")
    ab = os.path.join(REPO, "tools", "exp_pallas_walk_ab.py")
    assert lint_paths([kern, ab]) == []
    for f in (kern, ab):
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the pallas walk ships pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_pallas_walk_ab.py" in fh.read()


def test_placement_modules_lint_clean_and_pragma_free():
    """The round-19 placement surface — the hierarchical-RCB /
    collective-frontier host+trace code in parallel/ (already in the
    distributed sweep above) plus its bench-consumed A/B tool — holds
    the strongest clean contract: zero violations, zero pragmas. The
    tool is also pinned into tools/lint_all.py's jaxlint targets so a
    slip cannot silently drop its CI coverage."""
    from pumiumtally_tpu.analysis import lint_paths

    files = [
        os.path.join(REPO, "pumiumtally_tpu", "parallel", "partition.py"),
        os.path.join(REPO, "pumiumtally_tpu", "parallel",
                     "distributed.py"),
        os.path.join(REPO, "tools", "exp_placement_ab.py"),
    ]
    assert lint_paths(files) == []
    for f in files:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the placement modules ship pragma-free"
            )
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_placement_ab.py" in fh.read()


# ---------------------------------------------------------------------------
# JL101-JL104 — collective safety
# ---------------------------------------------------------------------------

def test_jl101_undeclared_axis():
    src = """\
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, x):
    def body(x):
        return lax.psum(x, "data")
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=P("dp"))(x)
"""
    assert ids(lint_source(src)) == [("JL101", 7)]


def test_jl101_mesh_ctor_declares_axes():
    src = """\
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

def f(devs, x):
    def body(x):
        return lax.psum(x, "data")
    return shard_map(body, mesh=Mesh(devs, ("dp", "data")),
                     in_specs=(P("dp"),), out_specs=P("dp"))(x)
"""
    # "data" IS a mesh axis even though no spec names it — clean.
    assert lint_source(src) == []


def test_jl101_nonliteral_spec_disables_the_check():
    # `pp` is a runtime value: the declared-axes set is unknowable, so
    # the literal "data" axis must NOT be flagged (no guessing).
    src = """\
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, pp, x):
    def body(x):
        return lax.psum(x, "data")
    return shard_map(body, mesh=mesh, in_specs=(P(), pp),
                     out_specs=pp)(x)
"""
    assert lint_source(src) == []


def test_jl101_decorator_form():
    src = """\
from functools import partial
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def make(mesh):
    @partial(shard_map, mesh=mesh, in_specs=(P("dp"),),
             out_specs=P("dp"))
    def step(x):
        return lax.psum(x, "devices")
    return step
"""
    assert ids(lint_source(src)) == [("JL101", 10)]


def test_jl102_broken_permutation():
    src = """\
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, x):
    def body(x):
        return lax.ppermute(x, "dp",
                            perm=[(0, 1), (1, 2), (2, 2), (3, 0)])
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=P("dp"))(x)
"""
    assert ids(lint_source(src)) == [("JL102", 7)]


def test_jl102_comprehension_ring_not_guessed():
    src = """\
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, n, x):
    def body(x):
        return lax.ppermute(x, "dp",
                            perm=[(i, (i + 1) % n) for i in range(n)])
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=P("dp"))(x)
"""
    assert lint_source(src) == []


def test_jl103_unsummed_scalar_through_replicated_spec():
    src = """\
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, x):
    def body(x):
        total = jnp.sum(x)
        return x, total
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=(P("dp"), P()))(x)
"""
    assert ids(lint_source(src)) == [("JL103", 9)]


def test_jl103_psum_clears_the_taint():
    src = """\
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, x):
    def body(x):
        total = lax.psum(jnp.sum(x), "dp")
        return x, total
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=(P("dp"), P()))(x)
"""
    assert lint_source(src) == []


def test_jl104_divergent_cond_around_collective():
    src = """\
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, x):
    def body(x):
        m = jnp.mean(x)
        def yes(v):
            return lax.psum(v, "dp")
        return lax.cond(m > 0.0, yes, lambda v: v, x)
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=P("dp"))(x)
"""
    assert ids(lint_source(src)) == [("JL104", 11)]


def test_jl104_collective_free_branches_are_fine():
    # partition.py's blk_cond pattern: shard-local predicate, but the
    # branches contain no collective — nothing can deadlock.
    src = """\
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, x):
    def body(x):
        n = jnp.sum(x)
        def loop_body(c):
            return (c[0] + 1, c[1] * 2)
        def loop_cond(c):
            return c[0] < n
        return lax.while_loop(loop_cond, loop_body, (0, x))[1]
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=P("dp"))(x)
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL201-JL204 — Pallas kernel discipline
# ---------------------------------------------------------------------------

def test_jl201_oversized_block():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def f(x):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((16384, 32), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((16384, 32), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((65536, 32), jnp.float32),
    )(x)
"""
    assert ids(lint_source(src)) == [("JL201", 8)]


def test_jl201_budget_constant_mirrors_vmem_walk():
    """The analyzer cannot import ops/vmem_walk.py (it imports jax), so
    it mirrors the feasibility constants; this pin breaks when the
    model moves without the mirror."""
    import re

    from pumiumtally_tpu.analysis.pallas import VMEM_BLOCK_BUDGET_BYTES

    src = open(os.path.join(
        REPO, "pumiumtally_tpu", "ops", "vmem_walk.py")).read()
    elems = int(re.search(
        r"^VMEM_FEASIBLE_MAX_ELEMS\s*=\s*(\d+)", src, re.M).group(1))
    pad = int(re.search(
        r"^TABLE_PAD_COLS\s*=\s*(\d+)", src, re.M).group(1))
    assert VMEM_BLOCK_BUDGET_BYTES == elems * pad * 4


def test_jl202_input_write_and_output_read_before_write():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def f(x):
    def kernel(x_ref, o_ref):
        x_ref[0] = 0.0
        acc = o_ref[...]
        o_ref[...] = acc + x_ref[...]
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)
"""
    assert ids(lint_source(src)) == [("JL202", 7), ("JL202", 8)]


def test_jl202_write_before_read_is_clean():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def f(x):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]
        o_ref[...] = o_ref[...] + 1.0
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)
"""
    assert lint_source(src) == []


def test_jl203_indivisible_grid():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def f(x):
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((500,), jnp.float32),
    )(x)
"""
    # Reported at the out_specs line — the BlockSpec at fault.
    assert ids(lint_source(src)) == [("JL203", 12)]


def test_jl204_host_call_in_kernel():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def f(x):
    def kernel(x_ref, o_ref):
        print("tile", x_ref.shape)
        o_ref[...] = x_ref[...]
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)
"""
    assert ids(lint_source(src)) == [("JL204", 7)]


def test_jl204_debug_print_is_fine():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def f(x):
    def kernel(x_ref, o_ref):
        pl.debug_print("tile {}", x_ref[0])
        o_ref[...] = x_ref[...]
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL301-JL303 — host concurrency
# ---------------------------------------------------------------------------

def test_jl301_unlocked_cross_root_write():
    src = """\
import threading

class TallyService:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0

    def _worker_loop(self):
        self.pending -= 1

    def submit(self, job):
        with self._lock:
            self.pending += 1
"""
    assert ids(lint_source(src)) == [("JL301", 9)]


def test_jl301_both_writes_locked_is_clean():
    src = """\
import threading

class TallyService:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0

    def _worker_loop(self):
        with self._lock:
            self.pending -= 1

    def submit(self, job):
        with self._lock:
            self.pending += 1
"""
    assert lint_source(src) == []


def test_jl301_unregistered_class_exempt():
    # TallySession is documented guarded-by the owning service lock;
    # unregistered classes are exempt by design.
    src = """\
class TallySession:
    def __init__(self):
        self.pending = 0

    def _worker_loop(self):
        self.pending -= 1

    def submit(self, job):
        self.pending += 1
"""
    assert lint_source(src) == []


def test_jl302_lock_ordering_cycle():
    src = """\
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ba(self):
        with self._b:
            with self._a:
                pass
"""
    got = ids(lint_source(src))
    # Reported at the cycle's earliest inner acquisition.
    assert got == [("JL302", 10)]


def test_jl302_consistent_order_is_clean():
    src = """\
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def ab2(self):
        with self._a:
            with self._b:
                pass
"""
    assert lint_source(src) == []


def test_jl303_blocking_result_under_lock():
    src = """\
import threading

class Flush:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self._pool = pool

    def flush(self, job):
        with self._lock:
            fut = self._pool.submit(job)
            return fut.result()
"""
    assert ids(lint_source(src)) == [("JL303", 11)]


def test_jl303_timeout_and_condition_wait_exempt():
    src = """\
import threading

class Flush:
    def __init__(self, pool):
        self._cv = threading.Condition()
        self._pool = pool

    def flush(self, job):
        with self._cv:
            self._cv.wait()
            fut = self._pool.submit(job)
            return fut.result(timeout=5.0)
"""
    # Condition.wait on the HELD condition releases it; a timeout
    # bounds the result() wait — both exempt.
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# Pragma grammar covers the new families
# ---------------------------------------------------------------------------

def test_pragma_suppresses_new_family_rules():
    src = """\
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def f(mesh, x):
    def body(x):
        return lax.psum(x, "data")  # jaxlint: disable=JL101 -- axis injected by caller contract
    return shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                     out_specs=P("dp"))(x)
"""
    assert lint_source(src) == []


def test_bare_pragma_on_new_family_is_jl000():
    src = """\
import threading

class TallyService:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0

    def _worker_loop(self):
        self.pending -= 1  # jaxlint: disable=JL301

    def submit(self, job):
        with self._lock:
            self.pending += 1
"""
    assert sorted(ids(lint_source(src))) == [("JL000", 9), ("JL301", 9)]


def test_pragma_wrong_family_does_not_suppress():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def f(x):
    def kernel(x_ref, o_ref):
        print("tile")  # jaxlint: disable=JL001 -- wrong rule named
        o_ref[...] = x_ref[...]
    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128,), lambda i: (i,))],
        out_specs=pl.BlockSpec((128,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((512,), jnp.float32),
    )(x)
"""
    assert ids(lint_source(src)) == [("JL204", 7)]


# ---------------------------------------------------------------------------
# --format json: stable machine-readable schema
# ---------------------------------------------------------------------------

def test_cli_json_schema(tmp_path):
    import json

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "--format", "json", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    got = json.loads(proc.stdout)
    assert isinstance(got, list) and len(got) == 1
    # THE schema: exactly these four keys, these types. Pinned so
    # downstream consumers (CI annotations, editors) can rely on it.
    assert set(got[0]) == {"path", "line", "rule", "message"}
    assert got[0]["line"] == 5
    assert got[0]["rule"] == "JL001"
    assert got[0]["path"].endswith("bad.py")
    assert isinstance(got[0]["message"], str) and got[0]["message"]


def test_cli_json_clean_is_empty_array(tmp_path):
    import json

    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "--format", "json", str(ok)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    assert json.loads(proc.stdout) == []


# ---------------------------------------------------------------------------
# --contracts: the five-facade hook-surface audit
# ---------------------------------------------------------------------------

FACADE_NAMES = [
    "monolithic", "sharded", "streaming", "partitioned",
    "streaming_partitioned",
]
HOOK_POINTS = [
    "batch-close", "move-end", "checkpoint-rows", "lane-bank",
    "fusion-key",
]


def test_cli_contracts_lists_all_five_facades():
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "--contracts"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for facade in FACADE_NAMES:
        assert facade in proc.stdout
    assert "MISSING" not in proc.stdout


def test_cli_contracts_json():
    import json

    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "--contracts", "--format", "json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    report = json.loads(proc.stdout)
    assert report["hook_points"] == HOOK_POINTS
    assert [r["facade"] for r in report["facades"]] == FACADE_NAMES
    for row in report["facades"]:
        assert row["engine_kind_dispatched"] is True
        for point in HOOK_POINTS:
            h = row["hooks"][point]
            assert h["status"] != "MISSING"
            assert "DRIFT" not in h["status"], (
                f"{row['facade']}/{point}: {h}"
            )


def test_contracts_audit_api():
    """The library surface: every facade covers every hook, and the
    checkpoint dispatcher covers every engine kind."""
    from pumiumtally_tpu.analysis import audit_contracts

    report, code = audit_contracts()
    assert code == 0
    kinds = set(report["engine_kinds_dispatched"])
    assert {"monolithic", "streaming", "partitioned",
            "streaming_partitioned"} <= kinds


def test_contracts_detect_missing_hook(tmp_path):
    """A facade that drops a hook must audit as MISSING with exit 1 —
    proved against a doctored copy of the api tree."""
    import shutil as _sh

    from pumiumtally_tpu.analysis.contracts import audit_contracts

    root = tmp_path / "pkg"
    for rel in ("api", "utils"):
        (root / rel).mkdir(parents=True)
    for rel in ("api/tally.py", "api/streaming.py",
                "api/partitioned.py", "utils/checkpoint.py"):
        _sh.copy(os.path.join(REPO, "pumiumtally_tpu", rel), root / rel)
    doctored = (root / "api/tally.py").read_text().replace(
        "def close_batch(", "def close_batch_renamed(")
    (root / "api/tally.py").write_text(doctored)
    report, code = audit_contracts(str(root))
    assert code == 1
    mono = report["facades"][0]
    assert mono["hooks"]["batch-close"]["status"] == "MISSING"


# ---------------------------------------------------------------------------
# Seeded-bug corpus: each pass proven non-vacuous on realistic files
# ---------------------------------------------------------------------------

CORPUS = os.path.join(REPO, "tests", "lint_corpus")


def lint_corpus_file(name):
    from pumiumtally_tpu.analysis import lint_paths

    return ids(lint_paths([os.path.join(CORPUS, name)]))


def test_seeded_collective_corpus():
    assert lint_corpus_file("collective_bugs.py") == [
        ("JL101", 14), ("JL102", 24), ("JL103", 38), ("JL104", 55),
    ]


def test_seeded_pallas_corpus():
    assert lint_corpus_file("pallas_bugs.py") == [
        ("JL201", 18), ("JL202", 31), ("JL202", 32), ("JL203", 53),
        ("JL204", 62),
    ]


def test_seeded_concurrency_corpus():
    assert lint_corpus_file("concurrency_bugs.py") == [
        ("JL301", 24), ("JL302", 44), ("JL303", 64),
    ]


def test_corpus_outside_acceptance_lint_set():
    """The seeded bugs must not trip the repo-clean gate: CI lints
    pumiumtally_tpu/ tools/ examples/ bench.py, never tests/."""
    with open(os.path.join(
            REPO, ".github", "workflows", "static-analysis.yml")) as fh:
        wf = fh.read()
    jaxlint_lines = [ln for ln in wf.splitlines()
                     if "tools/jaxlint.py" in ln]
    assert jaxlint_lines, "CI must run jaxlint"
    assert not any("tests" in ln for ln in jaxlint_lines)


# ---------------------------------------------------------------------------
# Self-check: the analyzer package itself
# ---------------------------------------------------------------------------

def test_analysis_package_registered_and_pragma_free():
    """The six-pass suite must actually be wired: the pass modules
    exist, Analyzer.run() dispatches them, and the analyzer's own code
    holds the strongest form of the clean contract (zero violations,
    zero pragmas) — a linter that needs to suppress itself has lost
    the argument."""
    import glob

    from pumiumtally_tpu.analysis import lint_paths

    ana_dir = os.path.join(REPO, "pumiumtally_tpu", "analysis")
    files = sorted(glob.glob(os.path.join(ana_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "__main__.py", "core.py", "rules.py",
            "collective.py", "pallas.py", "concurrency.py",
            "contracts.py", "tracekeys.py", "determinism.py",
            "wire.py"} <= names
    with open(os.path.join(ana_dir, "core.py")) as fh:
        core_src = fh.read()
    for mod in ("collective", "pallas", "concurrency", "tracekeys",
                "determinism"):
        assert f"{mod}.check" in core_src, (
            f"Analyzer.run() must dispatch the {mod} pass"
        )
    assert lint_paths(files) == []
    # Zero ACTIVE pragmas. The analyzer's own docstrings and the
    # pragma regex legitimately contain the pragma TEXT, so this scans
    # real comment tokens, not raw substrings.
    import io
    import tokenize

    from pumiumtally_tpu.analysis.core import _PRAGMA_RE

    for f in files:
        with open(f) as fh:
            src = fh.read()
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                assert not _PRAGMA_RE.search(tok.string), (
                    f"{f}:{tok.start[0]}: the analyzer ships pragma-free"
                )


def test_lint_all_runs_contracts_stage():
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        src = fh.read()
    assert "--contracts" in src
    # Pin drift is a FAILURE with remediation, not a warning.
    assert "pip install ruff==" in src
    # The two round-20 audits are failing stages beside contracts.
    assert "--trace-keys" in src
    assert "--wire" in src


# ---------------------------------------------------------------------------
# JL401/JL404 — trace-key cardinality. The snippets register REAL
# budget names ("walk" = 3, "locate" = 2) so the prover folds the
# seeded domains against the live config.RETRACE_BUDGETS table.
# ---------------------------------------------------------------------------

def test_jl401_enumerable_domain_over_budget():
    src = """\
import jax

from pumiumtally_tpu.utils.profiling import register_entry_point


def _step(state, mode):
    return state


_walk = register_entry_point(
    "walk", jax.jit(_step, static_argnames=("mode",))
)


def drive(state):
    for mode in ("fast", "exact", "paranoid", "audit"):
        state = _walk(state, mode=mode)
    return state
"""
    assert ids(lint_source(src)) == [("JL401", 10)]


def test_jl401_within_budget_is_clean():
    # Three enumerable keys against a budget of three: tight but legal.
    src = """\
import jax

from pumiumtally_tpu.utils.profiling import register_entry_point


def _step(state, mode):
    return state


_walk = register_entry_point(
    "walk", jax.jit(_step, static_argnames=("mode",))
)


def drive(state):
    for mode in ("fast", "exact", "paranoid"):
        state = _walk(state, mode=mode)
    return state
"""
    assert ids(lint_source(src)) == []


def test_jl401_runtime_knob_never_guessed():
    # A knob whose values the prover cannot enumerate is counted as
    # dynamic and skipped — no-false-positive bias, not a guess.
    src = """\
import jax

from pumiumtally_tpu.utils.profiling import register_entry_point


def _step(state, mode):
    return state


_walk = register_entry_point(
    "walk", jax.jit(_step, static_argnames=("mode",))
)


def drive(state, mode):
    return _walk(state, mode=mode)
"""
    assert ids(lint_source(src)) == []


def test_jl404_len_reaches_static_key():
    src = """\
import jax

from pumiumtally_tpu.utils.profiling import register_entry_point


def _locate_impl(state, n):
    return state


_locate = register_entry_point(
    "locate", jax.jit(_locate_impl, static_argnames=("n",))
)


def serve(batch, state):
    return _locate(state, n=len(batch))
"""
    assert ids(lint_source(src)) == [("JL404", 16)]


def test_jl404_shape_reaches_static_key():
    src = """\
import jax

from pumiumtally_tpu.utils.profiling import register_entry_point


def _locate_impl(state, n):
    return state


_locate = register_entry_point(
    "locate", jax.jit(_locate_impl, static_argnames=("n",))
)


def serve(state):
    return _locate(state, n=state.shape[0])
"""
    assert ids(lint_source(src)) == [("JL404", 16)]


def test_jl404_module_constant_is_clean():
    # A module-level constant reaching the static slot is ONE key.
    src = """\
import jax

from pumiumtally_tpu.utils.profiling import register_entry_point

CHUNK = 4096


def _locate_impl(state, n):
    return state


_locate = register_entry_point(
    "locate", jax.jit(_locate_impl, static_argnames=("n",))
)


def serve(state):
    return _locate(state, n=CHUNK)
"""
    assert ids(lint_source(src)) == []


# ---------------------------------------------------------------------------
# JL501–JL503 — determinism
# ---------------------------------------------------------------------------

def test_jl501_set_iteration_into_sink():
    src = """\
def broadcast(sessions, out):
    for sid in set(sessions):
        out.append(sid)
    return out
"""
    assert ids(lint_source(src)) == [("JL501", 2)]


def test_jl501_list_of_set_materialization():
    src = """\
def rows(keys):
    return list({k for k in keys})
"""
    assert ids(lint_source(src)) == [("JL501", 2)]


def test_jl501_sorted_set_is_clean():
    src = """\
def broadcast(sessions, out):
    for sid in sorted(set(sessions)):
        out.append(sid)
    return out
"""
    assert ids(lint_source(src)) == []


def test_jl501_membership_only_set_is_clean():
    src = """\
def dedupe(items):
    seen = set()
    out = []
    for x in items:
        if x in seen:
            continue
        seen.add(x)
        out.append(x)
    return out
"""
    assert ids(lint_source(src)) == []


def test_jl502_numpy_default_sort_in_commit():
    src = """\
import numpy as np


def commit(acc, bins, w):
    order = np.argsort(bins)
    return acc.at[bins[order]].add(w[order])
"""
    assert ids(lint_source(src)) == [("JL502", 5)]


def test_jl502_stable_kind_is_clean():
    src = """\
import numpy as np


def commit(acc, bins, w):
    order = np.argsort(bins, kind="stable")
    return acc.at[bins[order]].add(w[order])
"""
    assert ids(lint_source(src)) == []


def test_jl502_no_commit_path_is_clean():
    src = """\
import numpy as np


def rank(bins):
    return np.argsort(bins)
"""
    assert ids(lint_source(src)) == []


def test_jl502_jnp_default_is_stable_and_clean():
    src = """\
import jax.numpy as jnp


def commit(acc, seg, w):
    order = jnp.argsort(seg)
    return acc.at[seg[order]].add(w[order])
"""
    assert ids(lint_source(src)) == []


def test_jl503_host_sum_over_fetch():
    src = """\
import jax


def total(flux):
    return sum(jax.device_get(flux).tolist())
"""
    assert ids(lint_source(src)) == [("JL503", 5)]


def test_jl503_plain_python_sum_is_clean():
    src = """\
def total(weights):
    return sum(weights)
"""
    assert ids(lint_source(src)) == []


def test_jl503_device_reduction_is_clean():
    src = """\
import jax.numpy as jnp


def total(flux):
    return float(jnp.sum(flux))
"""
    assert ids(lint_source(src)) == []


def test_seeded_tracekeys_corpus():
    assert lint_corpus_file("tracekeys_bugs.py") == [
        ("JL401", 26), ("JL404", 45),
    ]


def test_seeded_determinism_corpus():
    assert lint_corpus_file("determinism_bugs.py") == [
        ("JL501", 14), ("JL501", 21), ("JL502", 27), ("JL502", 34),
        ("JL503", 42),
    ]


# ---------------------------------------------------------------------------
# --trace-keys: the budget/entry-point audit (JL402/JL403)
# ---------------------------------------------------------------------------

JAXLINT = os.path.join(REPO, "tools", "jaxlint.py")


def test_cli_trace_keys_table_clean_at_head():
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--trace-keys"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "walk_fused" in proc.stdout
    assert "every budget live, every entry point budgeted" in (
        proc.stdout
    )


def test_cli_trace_keys_json_bijective():
    import json

    proc = subprocess.run(
        [sys.executable, JAXLINT, "--trace-keys", "--format", "json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    # The invariant the audit exists to hold: registered entry points
    # and (non-exempt) budgets are the SAME set, and every jit wrapper
    # resolved statically.
    names = {r["name"] for r in report["entry_points"]}
    budget_names = {
        k for k in report["budgets"] if k != "total"
    }
    assert names == budget_names
    assert all(r["jit_resolved"] for r in report["entry_points"])
    walk = [r for r in report["entry_points"] if r["name"] == "walk"]
    assert walk and walk[0]["budget"] == report["budgets"]["walk"]


def test_trace_keys_detect_dead_and_unbudgeted(tmp_path):
    """A pruned registration (JL402) and an unbudgeted one (JL403)
    must fail the audit — proved against a doctored tree."""
    from pumiumtally_tpu.analysis.tracekeys import audit_trace_keys

    root = tmp_path / "pkg"
    root.mkdir()
    (root / "config.py").write_text(
        'RETRACE_BUDGETS: dict = {"alive": 2, "dead": 3}\n'
    )
    (root / "mod.py").write_text(
        "import jax\n"
        "\n"
        "from pumiumtally_tpu.utils.profiling import (\n"
        "    register_entry_point,\n"
        ")\n"
        "\n"
        "\n"
        "def _f(state, k):\n"
        "    return state\n"
        "\n"
        "\n"
        'alive = register_entry_point("alive", jax.jit(_f))\n'
        'orphan = register_entry_point("orphan", jax.jit(_f))\n'
    )
    report, code = audit_trace_keys(str(root))
    assert code == 1
    found = {(f["rule"], f["name"]) for f in report["findings"]}
    assert found == {("JL402", "dead"), ("JL403", "orphan")}


def test_trace_keys_clean_tree_and_total_exempt(tmp_path):
    from pumiumtally_tpu.analysis.tracekeys import audit_trace_keys

    root = tmp_path / "pkg"
    root.mkdir()
    # "total" bounds whole-test compiles, not an entry point: never
    # flagged as a dead budget.
    (root / "config.py").write_text(
        'RETRACE_BUDGETS: dict = {"alive": 2, "total": 40}\n'
    )
    (root / "mod.py").write_text(
        "import jax\n"
        "\n"
        "from pumiumtally_tpu.utils.profiling import (\n"
        "    register_entry_point,\n"
        ")\n"
        "\n"
        "\n"
        "def _f(state):\n"
        "    return state\n"
        "\n"
        "\n"
        'alive = register_entry_point("alive", jax.jit(_f))\n'
    )
    report, code = audit_trace_keys(str(root))
    assert code == 0, report["findings"]
    assert report["findings"] == []


# ---------------------------------------------------------------------------
# --wire: the wire-protocol auditor
# ---------------------------------------------------------------------------

def test_cli_wire_clean_at_head():
    proc = subprocess.run(
        [sys.executable, JAXLINT, "--wire"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "tools/loadgen.py" in proc.stdout
    assert "every encoder speaks the server's protocol" in proc.stdout


def test_cli_wire_json_schema():
    import json

    from pumiumtally_tpu.analysis.wire import ENCODER_FILES

    proc = subprocess.run(
        [sys.executable, JAXLINT, "--wire", "--format", "json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    report = json.loads(proc.stdout)
    assert report["findings"] == []
    srv = report["server"]
    assert {"open", "source", "move", "flux", "sync", "close"} <= (
        set(srv["ops"])
    )
    assert srv["required"]["move"] == ["dests", "session"]
    assert srv["required"]["source"] == ["positions", "session"]
    assert "flux" in srv["replies"]["flux"]
    assert {"session", "home"} <= set(srv["replies"]["open"])
    assert "error" in srv["error_keys"]
    assert [e["path"] for e in report["encoders"]] == (
        list(ENCODER_FILES)
    )
    loadgen = report["encoders"][1]
    assert loadgen["requests"] > 0 or loadgen["reply_reads"] > 0


def test_wire_detects_doctored_encoder(tmp_path):
    """wire_bugs.py installed AS the load generator must produce the
    exact pinned drift findings against the real server schema."""
    import shutil as _sh

    from pumiumtally_tpu.analysis.wire import ENCODER_FILES, audit_wire

    root = tmp_path / "tree"
    for rel in ENCODER_FILES:
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        _sh.copy(os.path.join(REPO, rel), dst)
    _sh.copy(
        os.path.join(CORPUS, "wire_bugs.py"),
        root / "tools" / "loadgen.py",
    )
    report, code = audit_wire(str(root))
    assert code == 1
    assert [(f["kind"], f["line"]) for f in report["findings"]] == [
        ("UNKNOWN-OP", 16),
        ("MISSING-FIELD", 18),
        ("MISSING-FIELD", 21),
        ("REPLY-DRIFT", 26),
    ]
    assert all(
        f["path"] == "tools/loadgen.py" for f in report["findings"]
    )


def test_wire_missing_encoder_fails(tmp_path):
    """Deleting a pinned encoder must FAIL, not shrink the audit."""
    import shutil as _sh

    from pumiumtally_tpu.analysis.wire import ENCODER_FILES, audit_wire

    root = tmp_path / "tree"
    dropped = "examples/multi_client_service.py"
    for rel in ENCODER_FILES:
        if rel == dropped:
            continue
        dst = root / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        _sh.copy(os.path.join(REPO, rel), dst)
    report, code = audit_wire(str(root))
    assert code == 1
    assert [(f["kind"], f["path"]) for f in report["findings"]] == [
        ("MISSING-ENCODER", dropped),
    ]


# ---------------------------------------------------------------------------
# Deterministic walk: __pycache__/.tmp-* pruned, output byte-stable
# ---------------------------------------------------------------------------

def test_lint_walk_pruned_and_sorted(tmp_path):
    from pumiumtally_tpu.analysis.core import iter_python_files

    pkg = tmp_path / "pkg"
    (pkg / "__pycache__").mkdir(parents=True)
    (pkg / ".tmp-scratch").mkdir()
    (pkg / "b.py").write_text("x = 1\n")
    (pkg / "a.py").write_text("y = 2\n")
    (pkg / "__pycache__" / "c.py").write_text("z = 3\n")
    (pkg / ".tmp-scratch" / "d.py").write_text("z = 4\n")
    (pkg / ".tmp-e.py").write_text("z = 5\n")
    (pkg / "notes.txt").write_text("not python\n")
    files = iter_python_files([str(tmp_path)])
    assert files == [str(pkg / "a.py"), str(pkg / "b.py")]
    # Deterministic: a second walk is identical.
    assert files == iter_python_files([str(tmp_path)])


def test_cli_json_byte_stable_and_cache_blind(tmp_path):
    """--format json over the same tree twice is byte-identical, and
    a violation hidden in __pycache__ neither fires nor perturbs the
    output."""
    pkg = tmp_path / "pkg"
    (pkg / "__pycache__").mkdir(parents=True)
    (pkg / "clean.py").write_text("x = 1\n")
    (pkg / "z_bug.py").write_text(
        "import jax\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item()\n"
    )
    (pkg / "__pycache__" / "stale.py").write_text(
        "import jax\n"
        "\n"
        "\n"
        "@jax.jit\n"
        "def g(x):\n"
        "    return x.item()\n"
    )
    runs = [
        subprocess.run(
            [sys.executable, JAXLINT, "--format", "json",
             str(tmp_path)],
            capture_output=True, text=True, cwd=REPO,
        )
        for _ in range(2)
    ]
    assert [p.returncode for p in runs] == [1, 1]
    assert runs[0].stdout == runs[1].stdout
    assert "z_bug.py" in runs[0].stdout
    assert "__pycache__" not in runs[0].stdout


def test_ci_runs_trace_keys_and_wire_audits():
    with open(os.path.join(
            REPO, ".github", "workflows", "static-analysis.yml")) as fh:
        wf = fh.read()
    jaxlint_lines = [ln for ln in wf.splitlines()
                     if "tools/jaxlint.py" in ln]
    assert any("--trace-keys" in ln for ln in jaxlint_lines)
    assert any("--wire" in ln for ln in jaxlint_lines)


# ---------------------------------------------------------------------------
# tools/retrace_calibrate.py — record-vs-budget diff
# ---------------------------------------------------------------------------

CALIBRATE = os.path.join(REPO, "tools", "retrace_calibrate.py")


def _run_calibrate(*argv):
    return subprocess.run(
        [sys.executable, CALIBRATE, *argv],
        capture_output=True, text=True, cwd=REPO,
    )


def test_retrace_calibrate_clean_record(tmp_path):
    rec = tmp_path / "rt.ndjson"
    rec.write_text(
        '{"test": "t::a", "total": 3,'
        ' "compiles": {"walk": 1, "locate": 2}}\n'
    )
    proc = _run_calibrate(str(rec))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "every observed entry point within budget" in proc.stdout
    assert "OVER" not in proc.stdout


def test_retrace_calibrate_flags_over_and_unbudgeted(tmp_path):
    rec = tmp_path / "rt.ndjson"
    rec.write_text(
        '{"test": "t::a", "total": 3,'
        ' "compiles": {"walk": 99, "ghost": 1}}\n'
    )
    proc = _run_calibrate(str(rec))
    assert proc.returncode == 1
    assert "OVER" in proc.stdout
    assert "UNBUDGETED" in proc.stdout


def test_retrace_calibrate_missing_record(tmp_path):
    proc = _run_calibrate(str(tmp_path / "nope.ndjson"))
    assert proc.returncode == 2
