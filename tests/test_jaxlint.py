"""jaxlint analyzer tests: a fixture corpus of known-bad snippets.

Each corpus entry is one minimal trace-safety violation; the assertions
pin EXACT rule ids and line numbers so a rule that drifts (fires on the
wrong line, or stops firing) fails loudly rather than rotting. The
self-check at the bottom asserts the shipped engine is jaxlint-clean —
the same gate CI runs (.github/workflows/static-analysis.yml).

Pure host-side tests: the analyzer never imports jax or executes the
snippets, so this module needs no devices and runs first-class in
tier 1.
"""

import os
import subprocess
import sys

from pumiumtally_tpu.analysis import RULES, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ids(diags):
    return [(d.rule, d.line) for d in diags]


# ---------------------------------------------------------------------------
# JL001 — host sync inside a traced body
# ---------------------------------------------------------------------------

def test_jl001_item_in_jit():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()
"""
    assert ids(lint_source(src)) == [("JL001", 5)]


def test_jl001_device_get_and_asarray():
    src = """\
import jax
import numpy as np

@jax.jit
def f(x):
    y = np.asarray(x)
    return jax.device_get(y)
"""
    assert ids(lint_source(src)) == [("JL001", 6), ("JL001", 7)]


def test_jl001_float_on_traced():
    src = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return x * float(jnp.max(x))
"""
    assert ids(lint_source(src)) == [("JL001", 6)]


def test_jl001_inside_while_loop_body():
    src = """\
from jax import lax

def run(state):
    def body(s):
        return s + s.item()
    return lax.while_loop(lambda s: s.sum() > 0, body, state)
"""
    assert ids(lint_source(src)) == [("JL001", 5)]


def test_jl001_not_flagged_outside_trace():
    # The same calls at the host boundary are the API working as
    # intended — zero diagnostics.
    src = """\
import numpy as np

def fetch(dev):
    return np.asarray(dev), dev.item()
"""
    assert lint_source(src) == []


def test_jl001_asarray_of_static_is_fine():
    src = """\
import jax
import numpy as np

@jax.jit
def f(x, shape_tuple=(3, 4)):
    n = np.asarray([1, 2, 3])
    return x
"""
    # np.asarray of a concrete literal at trace time is legal constant
    # folding, not a sync.
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL002 — Python control flow on traced values
# ---------------------------------------------------------------------------

def test_jl002_if_and_while():
    src = """\
import jax

@jax.jit
def f(x):
    if x > 0:
        x = x + 1
    while x < 10:
        x = x * 2
    return x
"""
    assert ids(lint_source(src)) == [("JL002", 5), ("JL002", 7)]


def test_jl002_assert_and_ifexp():
    src = """\
import jax

@jax.jit
def f(x):
    assert x.sum() > 0
    return x if x.max() > 1 else -x
"""
    assert ids(lint_source(src)) == [("JL002", 5), ("JL002", 6)]


def test_jl002_static_branches_allowed():
    # Branching on shapes, None-ness, static args, len() — the
    # bookkeeping every JAX kernel is full of — must NOT flag.
    src = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode",))
def f(x, w, mode="fast"):
    if mode == "fast":
        x = x + 1
    if w is None:
        w = x
    if x.shape[0] > 4:
        x = x[:4]
    if len(x.shape) == 2:
        x = x.sum(0)
    return x + w
"""
    assert lint_source(src) == []


def test_jl002_retaint_inside_loop_uses_fresh_taint():
    """Expression checks must see taint AS OF the statement's position:
    a variable reassigned to a concrete value inside a loop must not be
    judged by its stale pre-loop taint (and the stale verdict must not
    pin `seen`)."""
    src = """\
import jax

@jax.jit
def f(x, xs):
    v = x * 2
    for i in range(3):
        v = x.shape[0]
        h = float(v)
    return x
"""
    assert lint_source(src) == []


def test_jl001_augassign_keeps_taint():
    """`x += 1` reads the traced x — it must stay traced (a plain
    overwrite-with-RHS-taint analysis silently drops it)."""
    src = """\
import jax

@jax.jit
def f(x):
    x += 1
    if x > 0:
        x = -x
    return x
"""
    assert ids(lint_source(src)) == [("JL002", 6)]


# ---------------------------------------------------------------------------
# JL003 — use after donation
# ---------------------------------------------------------------------------

def test_jl003_use_after_donate():
    src = """\
import jax

def update(s, u):
    return s + u

step = jax.jit(update, donate_argnums=(0,))

def run(state, u):
    out = step(state, u)
    return out + state.sum()
"""
    assert ids(lint_source(src)) == [("JL003", 10)]


def test_jl003_multiline_call_args_do_not_self_flag():
    """A donating call written across several lines must not flag its
    own argument list; a later use still flags."""
    src = """\
import jax

def update(s, u):
    return s + u

step = jax.jit(update, donate_argnums=(0,))

def run(state, u):
    out = step(
        state,
        u,
    )
    return out + state.sum()
"""
    assert ids(lint_source(src)) == [("JL003", 13)]


def test_jl003_rebind_is_clean():
    src = """\
import jax

def update(s, u):
    return s + u

step = jax.jit(update, donate_argnums=(0,))

def run(state, u):
    state = step(state, u)
    return state.sum()
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL004 — retrace-bait static defaults
# ---------------------------------------------------------------------------

def test_jl004_list_default():
    src = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("knobs",))
def walk(x, knobs=[8, 4]):
    return x
"""
    assert ids(lint_source(src)) == [("JL004", 5)]


def test_jl004_array_default_via_static_argnums():
    src = """\
import jax
import numpy as np

@jax.jit(static_argnums=(1,))
def f(x, table=np.zeros(4)):
    return x
"""
    assert ids(lint_source(src)) == [("JL004", 5)]


def test_jl004_tuple_default_is_clean():
    src = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("knobs",))
def walk(x, knobs=(8, 4)):
    return x
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# JL005 — module-state mutation under trace
# ---------------------------------------------------------------------------

def test_jl005_global_and_container():
    src = """\
import jax

CACHE = {}
COUNT = 0

@jax.jit
def f(x):
    global COUNT
    COUNT = COUNT + 1
    CACHE[0] = x
    return x
"""
    assert ids(lint_source(src)) == [("JL005", 9), ("JL005", 10)]


def test_jl005_mutator_method():
    src = """\
import jax

LOG = []

@jax.jit
def f(x):
    LOG.append(1)
    return x
"""
    assert ids(lint_source(src)) == [("JL005", 7)]


# ---------------------------------------------------------------------------
# One-level helper resolution
# ---------------------------------------------------------------------------

def test_indirect_sync_one_level():
    src = """\
import jax

def fetch(v):
    return v.item()

@jax.jit
def f(x):
    return fetch(x)
"""
    # The diagnostic lands on the sync INSIDE the helper (line 4),
    # reached through the traced call on line 8.
    assert ids(lint_source(src)) == [("JL001", 4)]


def test_indirect_taint_through_helper_args():
    src = """\
import jax

def branchy(flag, v):
    if flag:
        return v
    return -v

@jax.jit
def f(x):
    return branchy(x > 0, x)
"""
    assert ids(lint_source(src)) == [("JL002", 4)]


def test_two_levels_not_followed():
    # Depth limit is ONE: a sync two hops away is out of scope (the
    # documented precision/recall trade — see docs/STATIC_ANALYSIS.md).
    src = """\
import jax

def inner(v):
    return v.item()

def outer(v):
    return inner(v)

@jax.jit
def f(x):
    return outer(x)
"""
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# Pragmas
# ---------------------------------------------------------------------------

def test_pragma_with_justification_suppresses():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()  # jaxlint: disable=JL001 -- boundary fetch by design
"""
    assert lint_source(src) == []


def test_pragma_without_justification_is_jl000():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()  # jaxlint: disable=JL001
"""
    # The bare pragma reports JL000 AND the original finding survives.
    assert sorted(ids(lint_source(src))) == [("JL000", 5), ("JL001", 5)]


def test_pragma_unknown_rule_reported():
    src = """\
import jax

@jax.jit
def f(x):
    return x.item()  # jaxlint: disable=JL999 -- no such rule
"""
    got = ids(lint_source(src))
    assert ("JL000", 5) in got and ("JL001", 5) in got


def test_pragma_only_disables_named_rule():
    src = """\
import jax

@jax.jit
def f(x):
    if x > 0:
        x = x.item()  # jaxlint: disable=JL002 -- wrong rule named
    return x
"""
    got = ids(lint_source(src))
    assert got == [("JL002", 5), ("JL001", 6)]


# ---------------------------------------------------------------------------
# Rule registry / CLI contract
# ---------------------------------------------------------------------------

def test_rule_registry_complete():
    assert sorted(RULES) == ["JL000", "JL001", "JL002", "JL003", "JL004",
                             "JL005"]
    for rule in RULES.values():
        assert rule.summary and rule.doc
        assert "bad" in rule.doc and "good" in rule.doc


def test_jit_wrapped_in_registration_call_still_analyzed():
    """register_entry_point (the retrace counting wrapper) must not
    hide the jit from trace-root discovery — the engine's own
    `_move_step = register_entry_point("walk", jit(move_step))` form."""
    src = """\
import jax
from functools import partial
from pumiumtally_tpu.utils.profiling import register_entry_point

def move_step(x, tol):
    return x.item()

_move_step = register_entry_point(
    "walk",
    partial(jax.jit, static_argnames=("tol",))(move_step),
)
"""
    assert ids(lint_source(src)) == [("JL001", 6)]


def test_cli_missing_path_is_usage_error():
    """A typo'd target must not read as clean (exit 2, like ruff)."""
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "no_such_dir_xyz/"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 2
    assert "no such path" in proc.stderr


def test_cli_nonzero_on_bad_corpus(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n@jax.jit\ndef f(x):\n    return x.item()\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 1
    assert "JL001" in proc.stdout and "bad.py:5" in proc.stdout


def test_cli_explain():
    proc = subprocess.run(
        [sys.executable, "-m", "pumiumtally_tpu.analysis",
         "--explain", "JL004"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0
    assert "retrace" in proc.stdout


# ---------------------------------------------------------------------------
# Self-check: the shipped engine is jaxlint-clean
# ---------------------------------------------------------------------------

def test_engine_is_jaxlint_clean():
    """The acceptance gate CI enforces, as a test: every diagnostic in
    the engine tree is either fixed or carries a justified pragma."""
    from pumiumtally_tpu.analysis import lint_paths

    diags = lint_paths([os.path.join(REPO, "pumiumtally_tpu")])
    assert diags == [], "\n".join(d.render() for d in diags)


def test_stats_subsystem_registered_and_pragma_free():
    """The batch-statistics modules (r7) must be IN the self-check's
    file set (a packaging slip that moved them out of the package tree
    would silently drop their coverage) and hold the strongest form of
    the clean contract: zero violations with zero pragmas — the stats
    layer only ever reads engine arrays, so it has no excuse for even
    a justified suppression."""
    import glob

    stats_dir = os.path.join(REPO, "pumiumtally_tpu", "stats")
    files = sorted(glob.glob(os.path.join(stats_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "accumulators.py", "estimators.py",
            "triggers.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    assert lint_paths(files) == []
    for f in files:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the stats modules ship pragma-free"
            )


def test_resilience_subsystem_registered_and_pragma_free():
    """The fault-tolerance modules (r8) must be IN the self-check's
    file set and hold the strongest form of the clean contract: zero
    violations with zero pragmas — the resilience layer is host-side
    Python over numpy buffers (no jitted code at all), so it has no
    excuse for even a justified suppression."""
    import glob

    res_dir = os.path.join(REPO, "pumiumtally_tpu", "resilience")
    files = sorted(glob.glob(os.path.join(res_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "generations.py", "policy.py",
            "faults.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    assert lint_paths(files) == []
    for f in files:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the resilience modules ship pragma-free"
            )


def test_sentinel_subsystem_registered_and_pragma_free():
    """The runtime-sentinel modules (r9) must be IN the self-check's
    file set and hold the strongest form of the clean contract: zero
    violations with zero pragmas — the audit/retry programs are plain
    jitted reductions and walks with no host syncs reachable from a
    trace, so there is no excuse for even a justified suppression.
    The bench-consumed A/B tool is covered the same way (it is in
    tools/lint_all.py's jaxlint targets)."""
    import glob

    sen_dir = os.path.join(REPO, "pumiumtally_tpu", "sentinel")
    files = sorted(glob.glob(os.path.join(sen_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "policy.py", "audit.py", "straggler.py",
            "quarantine.py", "runner.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    ab = os.path.join(REPO, "tools", "exp_sentinel_ab.py")
    assert lint_paths(files + [ab]) == []
    for f in files + [ab]:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the sentinel modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_sentinel_ab.py" in fh.read()


def test_scoring_subsystem_registered_and_pragma_free():
    """The filtered-scoring modules (r10) must be IN the self-check's
    file set and hold the strongest form of the clean contract: zero
    violations with zero pragmas — the bin resolution and the walk
    hook are plain jitted array programs with no host syncs reachable
    from a trace, so there is no excuse for even a justified
    suppression. The bench-consumed A/B tool is covered the same way
    (it is in tools/lint_all.py's jaxlint targets)."""
    import glob

    sc_dir = os.path.join(REPO, "pumiumtally_tpu", "scoring")
    files = sorted(glob.glob(os.path.join(sc_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "filters.py", "scores.py",
            "binding.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    ab = os.path.join(REPO, "tools", "exp_scoring_ab.py")
    assert lint_paths(files + [ab]) == []
    for f in files + [ab]:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the scoring modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_scoring_ab.py" in fh.read()


def test_service_subsystem_registered_and_pragma_free():
    """The multi-session-service modules (r11, plus the r12 fusion
    module) must be IN the self-check's file set and hold the
    strongest form of the clean contract: zero violations with zero
    pragmas — the service layer is host-side threading and prepacked
    numpy buffers, and its ONE trace root (fusion.py's walk_fused) is
    a plain jitted pack/walk/split program with no host syncs
    reachable from the trace, so there is no excuse for even a
    justified suppression. The bench-consumed A/B tools are covered
    the same way (they are in tools/lint_all.py's jaxlint targets)."""
    import glob

    svc_dir = os.path.join(REPO, "pumiumtally_tpu", "service")
    files = sorted(glob.glob(os.path.join(svc_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert {"__init__.py", "session.py", "scheduler.py", "staging.py",
            "server.py", "fusion.py"} <= names
    from pumiumtally_tpu.analysis import lint_paths

    abs_ = [os.path.join(REPO, "tools", "exp_service_ab.py"),
            os.path.join(REPO, "tools", "exp_fusion_ab.py")]
    assert lint_paths(files + abs_) == []
    for f in files + abs_:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the service modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tools (a slip here
    # would silently drop their CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        targets = fh.read()
    assert "tools/exp_service_ab.py" in targets
    assert "tools/exp_fusion_ab.py" in targets


def test_distributed_subsystem_registered_and_pragma_free():
    """The pod-scale distributed module (r13) must be IN the
    self-check's file set (parallel/ is inside the package tree the
    self-check lints) and hold the strongest form of the clean
    contract: zero violations with zero pragmas — the collective
    migration is one shard_map'd all_gather + ppermute-ring program
    with no host syncs reachable from the trace, and the front-door
    helpers (init/probe/fetch) do their host work OUTSIDE any trace.
    The bench-consumed A/B tool is covered the same way (it is in
    tools/lint_all.py's jaxlint targets)."""
    import glob

    par_dir = os.path.join(REPO, "pumiumtally_tpu", "parallel")
    files = sorted(glob.glob(os.path.join(par_dir, "*.py")))
    names = {os.path.basename(f) for f in files}
    assert "distributed.py" in names
    from pumiumtally_tpu.analysis import lint_paths

    ab = os.path.join(REPO, "tools", "exp_distributed_ab.py")
    assert lint_paths(files + [ab]) == []
    for f in files + [ab]:
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the distributed modules ship pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_distributed_ab.py" in fh.read()


def test_pallas_walk_kernel_registered_and_pragma_free():
    """The one-kernel Pallas walk (r17) must be IN the self-check's
    file set (ops/ is inside the package tree the self-check lints)
    and hold the strongest form of the clean contract: zero violations
    with zero pragmas — the kernel body is a grid-pipelined pallas_call
    whose while-loop state lives in output refs, with no host syncs
    reachable from the trace. The bench-consumed A/B tool is covered
    the same way (it is in tools/lint_all.py's jaxlint targets)."""
    from pumiumtally_tpu.analysis import lint_paths

    kern = os.path.join(REPO, "pumiumtally_tpu", "ops", "pallas_walk.py")
    ab = os.path.join(REPO, "tools", "exp_pallas_walk_ab.py")
    assert lint_paths([kern, ab]) == []
    for f in (kern, ab):
        with open(f) as fh:
            assert "jaxlint: disable" not in fh.read(), (
                f"{f}: the pallas walk ships pragma-free"
            )
    # tools/lint_all.py actually targets the A/B tool (a slip here
    # would silently drop its CI coverage).
    with open(os.path.join(REPO, "tools", "lint_all.py")) as fh:
        assert "tools/exp_pallas_walk_ab.py" in fh.read()
