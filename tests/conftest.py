"""Test harness config: 8 virtual CPU devices + x64.

Must run before jax initializes. The parity oracles are 1e-8-tight
(reference test/test_pumi_tally_impl_methods.cpp:21-27) so the suite
runs in f64 on the CPU backend; multi-chip tests use the 8-device
virtual mesh (SURVEY.md §4: "add what the reference lacks: multi-chip
tests via 8-device CPU simulation").
"""

import os
import sys

# Force (not setdefault): the surrounding environment may point JAX at
# a remote TPU (JAX_PLATFORMS=axon); the parity suite must run on the
# local CPU backend with 8 virtual devices regardless. jax may already
# be *imported* (a sitecustomize can import it at interpreter start) —
# that is fine as long as no backend has been initialized yet, since
# XLA_FLAGS and platform selection are read at first backend use.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "true"

# One persistent XLA compilation cache for the whole run, shared (via
# the inherited environment) with every subprocess the suite spawns —
# the resilience/sentinel/service kill-and-resume drivers, the CLI
# serve round-trips, and the example runs each boot a fresh
# interpreter that would otherwise recompile programs the parent (or a
# sibling arm) already compiled; re-used engines inside the parent hit
# it too (a fresh facade's closures are new pjit entries even for
# byte-identical HLO). Executables are keyed by HLO + compile options,
# so a hit returns the exact artifact a compile would have produced —
# results are unchanged, only redundant XLA:CPU compile time goes
# away (~35% of suite wall time). The dir is fresh per run (no
# cross-run staleness) and removed at exit; an externally-set
# JAX_COMPILATION_CACHE_DIR wins and is left alone. The retrace
# tripwire is cache-aware: utils/profiling.py counts a disk retrieval
# exactly like the backend compile it replaced.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    import atexit
    import shutil
    import tempfile

    _cache_dir = tempfile.mkdtemp(prefix="pumiumtally-xla-cache-")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache_dir
    atexit.register(shutil.rmtree, _cache_dir, ignore_errors=True)
# Cache every program, however small/fast — the suite's cost is many
# medium compiles, not a few giant ones.
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
from jax._src import xla_bridge  # noqa: E402

if xla_bridge._backends:
    raise RuntimeError(
        "tests/conftest.py must run before any jax backend is initialized"
    )
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# Robust against a pre-imported jax (whose config defaults were read
# before the environment block above ran).
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update(
    "jax_persistent_cache_min_entry_size_bytes",
    int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]),
)
jax.config.update(
    "jax_persistent_cache_min_compile_time_secs",
    float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
)

import json  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _retrace_tripwire(request):
    """Retrace tripwire: fail any test whose engine entry points compile
    beyond their declared budget (config.RETRACE_BUDGETS).

    The static half of this invariant is jaxlint rule JL004
    (docs/STATIC_ANALYSIS.md); this is the runtime half — cache-key
    instability only shows up as jit-cache growth at run time. Budgets
    bound DISTINCT (shape, static-args) keys per test, so a healthy
    entry point stays within budget even on the first test to compile
    it; a breach means either a cache-key leak (fix the entry point) or
    a test legitimately sweeping more keys (raise the budget in
    config.py with a justifying comment).

    Set PUMIUMTALLY_RETRACE_RECORD=<path> to append one JSON line of
    per-test compile counts (budget calibration) instead of relying on
    memory of which test compiles what.

    Tests marked ``slow`` get 2x the tier-1 budgets: the stress tier's
    sweep tests legitimately drive more distinct keys per test (knob
    combinations across every facade, device-group configurations,
    forced-migration engine rebuilds) — measured maxima there stay
    under 2x while a genuine per-call cache-key leak blows through any
    constant factor.
    """
    from pumiumtally_tpu.config import RETRACE_BUDGETS
    from pumiumtally_tpu.utils.profiling import retrace_guard

    budgets = RETRACE_BUDGETS
    if request.node.get_closest_marker("slow") is not None:
        budgets = {k: 2 * v for k, v in budgets.items()}
    with retrace_guard(budgets, raise_on_exceed=False) as report:
        yield
    record = os.environ.get("PUMIUMTALLY_RETRACE_RECORD")
    if record and (report.compiles or report.total_compiles):
        with open(record, "a") as f:
            f.write(json.dumps({
                "test": request.node.nodeid,
                "total": report.total_compiles,
                "compiles": report.compiles,
            }) + "\n")
    if report.exceeded:
        detail = ", ".join(
            f"{name}: {got} compiles > budget {budget}"
            for name, (got, budget) in sorted(report.exceeded.items())
        )
        pytest.fail(
            f"retrace budget exceeded ({detail}); full report: "
            f"{report.render()}. One compile per distinct (shape, "
            "static-args) key is the contract — see "
            "config.RETRACE_BUDGETS and docs/STATIC_ANALYSIS.md.",
            pytrace=False,
        )


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """One skipped-vs-run line for the cross-process (2-OS-process)
    tests, fed by tests/_distributed_driver.py's RAN/SKIPPED counters.
    Silent when no cross-process test was collected this session —
    tier-1 (`-m 'not slow'`) never launches worker pairs."""
    drv = (sys.modules.get("tests._distributed_driver")
           or sys.modules.get("_distributed_driver"))
    if drv is None or not (drv.RAN or drv.SKIPPED):
        return
    terminalreporter.write_line(
        f"cross-process distributed tests: {len(drv.RAN)} ran, "
        f"{len(drv.SKIPPED)} skipped (DISTRIBUTED-UNAVAILABLE)"
    )
