"""Test harness config: 8 virtual CPU devices + x64.

Must run before jax initializes. The parity oracles are 1e-8-tight
(reference test/test_pumi_tally_impl_methods.cpp:21-27) so the suite
runs in f64 on the CPU backend; multi-chip tests use the 8-device
virtual mesh (SURVEY.md §4: "add what the reference lacks: multi-chip
tests via 8-device CPU simulation").
"""

import os
import sys

# Force (not setdefault): the surrounding environment may point JAX at
# a remote TPU (JAX_PLATFORMS=axon); the parity suite must run on the
# local CPU backend with 8 virtual devices regardless. jax may already
# be *imported* (a sitecustomize can import it at interpreter start) —
# that is fine as long as no backend has been initialized yet, since
# XLA_FLAGS and platform selection are read at first backend use.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["JAX_ENABLE_X64"] = "true"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
from jax._src import xla_bridge  # noqa: E402

if xla_bridge._backends:
    raise RuntimeError(
        "tests/conftest.py must run before any jax backend is initialized"
    )
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
