"""Gather-kernel sub-split (walk_block_kernel="gather").

The blocked engine's second kernel: walk_local run block-by-block with
lax.map, capturing the measured small-table gather regime
(docs/PERF_NOTES.md round 4: 2.2-2.4M moves/s at L<=3k on chip vs ~1.1M
on the monolithic 48k table) without Pallas/Mosaic constraints. Same
layout contract as the vmem sub-split (slots grouped per block, lelem
block-local, migration at block granularity), so parity against the
unblocked engines is the whole correctness story.

Reference semantics anchored the same way as the vmem tests: the walk
is the reference's adjacency search (PumiTallyImpl.cpp:352-380), the
sub-split is this port's TPU-native decomposition of it.
"""

import numpy as np
import pytest

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh
from pumiumtally_tpu.parallel.partition import PartitionedEngine, build_partition


def _workload(n, seed=5):
    rng = np.random.default_rng(seed)
    src = rng.uniform(0.05, 0.95, (n, 3))
    # Some destinations exit the unit box: boundary clamp + exited
    # bookkeeping must agree across engines too.
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), -0.1, 1.1)
    return src, dst


def test_single_device_gather_blocked_matches_plain_engine():
    """PartitionedPumiTally with NO device_mesh runs on a default
    1-device mesh; gather sub-split flux matches the monolithic engine
    to f64 round-off."""
    mesh = build_box(1, 1, 1, 6, 6, 6)  # 1296 tets
    n = 4000
    src, dst = _workload(n)
    ref = PumiTally(mesh, n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    ref.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                           np.ones(n, np.int8), np.ones(n))
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(walk_vmem_max_elems=200, walk_block_kernel="gather",
                    capacity_factor=3.0),
    )
    assert int(t.engine.device_mesh.devices.size) == 1
    assert t.engine.blocks_per_chip > 1 and not t.engine.use_vmem_walk
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    np.testing.assert_allclose(
        np.asarray(t.flux, np.float64), np.asarray(ref.flux, np.float64),
        rtol=1e-10, atol=1e-13,
    )


def test_multichip_gather_blocked_matches_unblocked():
    """8-chip mesh, sub-split with the gather kernel (vma checking stays
    ON for this variant): flux, positions and conservation match the
    unblocked partitioned engine."""
    mesh = build_box(1, 1, 1, 6, 6, 6)  # 1296 tets
    n = 600
    rng = np.random.default_rng(11)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    d2 = rng.uniform(0.05, 0.95, (n, 3))
    out = []
    for knob in (None, 40):
        t = PartitionedPumiTally(
            mesh, n,
            TallyConfig(device_mesh=make_device_mesh(8),
                        capacity_factor=8.0,
                        walk_vmem_max_elems=knob,
                        walk_block_kernel="gather"),
        )
        if knob is None:
            assert t.engine.blocks_per_chip == 1
        else:
            assert t.engine.blocks_per_chip == 5
            assert not t.engine.use_vmem_walk
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        t.MoveToNextLocation(None, d2.reshape(-1).copy())
        out.append((np.asarray(t.flux, np.float64), t.positions))
    np.testing.assert_allclose(out[0][0], out[1][0], rtol=1e-10, atol=1e-13)
    np.testing.assert_allclose(out[0][1], out[1][1], rtol=1e-12, atol=1e-12)
    expect = (np.linalg.norm(d1 - src, axis=1)
              + np.linalg.norm(d2 - d1, axis=1)).sum()
    np.testing.assert_allclose(out[1][0].sum(), expect, rtol=1e-9)


@pytest.mark.slow
def test_gather_blocked_supports_adj_sidecar():
    """Unlike the vmem kernel, the gather block kernel accepts
    partitions carrying the int-adjacency sidecar (ids too large for
    the float table) — the configuration the vmem gate rejects."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 600
    src, dst = _workload(n, seed=13)
    dm = make_device_mesh(8)
    part = build_partition(mesh, 40, force_split_adj=True)
    assert part.adj_int is not None
    eng = PartitionedEngine(
        mesh, dm, n, capacity_factor=8.0, tol=1e-8, max_iters=4096,
        part=part, block_kernel="gather",
    )
    assert eng.blocks_per_chip == 5 and not eng.use_vmem_walk
    ref = PumiTally(mesh, n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    ref.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                           np.ones(n, np.int8), np.ones(n))
    import jax.numpy as jnp

    eng.localize(jnp.asarray(src))
    eng.move(jnp.asarray(src), jnp.asarray(dst),
             jnp.ones(n, jnp.int8), jnp.ones(n))
    np.testing.assert_allclose(
        np.asarray(eng.flux_original(), np.float64),
        np.asarray(ref.flux, np.float64), rtol=1e-10, atol=1e-13,
    )


def test_vmem_kernel_gate_unchanged_and_config_validates():
    """Default block kernel stays 'vmem' with its existing hard gate;
    bad kernel names are rejected at config construction."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    with pytest.raises(ValueError, match="walk_block_kernel"):
        TallyConfig(walk_block_kernel="mxu")
    # vmem kernel + adj sidecar + sub-split still raises (the gather
    # fallback must be explicit, not silent).
    part = build_partition(mesh, 40, force_split_adj=True)
    with pytest.raises(ValueError, match="sub-split"):
        PartitionedEngine(
            mesh, make_device_mesh(8), 100, capacity_factor=8.0,
            tol=1e-8, max_iters=64, part=part,
            vmem_walk_max_elems=40,
        )


@pytest.mark.slow
def test_gather_blocked_streaming_partitioned():
    """dp x part hybrid with the gather block kernel conserves."""
    from pumiumtally_tpu import StreamingPartitionedTally

    mesh = build_box(1, 1, 1, 4, 4, 4)  # 384 tets
    n = 400
    rng = np.random.default_rng(12)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    t = StreamingPartitionedTally(
        mesh, n, chunk_size=200,
        config=TallyConfig(device_mesh=make_device_mesh(8),
                           capacity_factor=8.0,
                           walk_vmem_max_elems=20,
                           walk_block_kernel="gather"),
    )
    for e in t.engines:
        assert e.blocks_per_chip == 3 and not e.use_vmem_walk
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, d1.reshape(-1).copy())
    got = float(np.asarray(t.flux, np.float64).sum())
    want = float(np.linalg.norm(d1 - src, axis=1).sum())
    np.testing.assert_allclose(got, want, rtol=1e-9)
