"""Robustness on irregular Delaunay meshes.

The box and pincell generators produce well-shaped tets with regular
adjacency. A Delaunay tetrahedralization of random points is the
opposite — slivers, near-degenerate dihedral angles, high-valence
vertices — and is exactly the mesh class a user converts from Gmsh in
practice (the reference's pipeline is Gmsh → msh2osh → .osh,
README.md:115-125). These tests pin that the walk kernel's geometry
(s-parametrized crossings, boundary clamp, tie handling on shared
faces) survives bad element quality: conservation must hold to f64
oracle tightness and every engine must agree.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.tetmesh import TetMesh

scipy_spatial = pytest.importorskip("scipy.spatial")


def _delaunay_mesh(npts=300, seed=0):
    rng = np.random.default_rng(seed)
    # Include the cube corners so the convex hull is exactly [0,1]^3
    # and interior trajectories never exit.
    pts = np.vstack([
        rng.uniform(0, 1, (npts, 3)),
        np.array(np.meshgrid([0, 1], [0, 1], [0, 1])).reshape(3, -1).T,
    ])
    tri = scipy_spatial.Delaunay(pts)
    # Drop numerically degenerate slivers (zero volume breaks the
    # inside-test everywhere, not just here).
    t = tri.simplices.astype(np.int64)
    v = pts[t]
    vol = np.einsum(
        "ij,ij->i",
        np.cross(v[:, 1] - v[:, 0], v[:, 2] - v[:, 0]),
        v[:, 3] - v[:, 0],
    ) / 6.0
    t = t[np.abs(vol) > 1e-12]
    return TetMesh.from_arrays(pts, t)


def test_delaunay_mesh_builds_and_fills_the_cube():
    mesh = _delaunay_mesh()
    total = float(np.asarray(mesh.volumes, np.float64).sum())
    np.testing.assert_allclose(total, 1.0, rtol=1e-9)
    assert int(jnp.sum(mesh.face_adj == -1)) > 0  # hull faces exist


def test_delaunay_conservation_and_engine_agreement():
    """Interior random trajectory on a sliver-ridden mesh: sum(flux)
    must equal the analytic track length, walk and locate localization
    must agree, and the streaming engine must reproduce the monolithic
    flux."""
    from pumiumtally_tpu import StreamingTally

    mesh = _delaunay_mesh(400, seed=3)
    n = 4000
    rng = np.random.default_rng(4)
    src = rng.uniform(0.05, 0.95, (n, 3))
    moves = [rng.uniform(0.05, 0.95, (n, 3)) for _ in range(3)]

    results = []
    for make in (
        lambda: PumiTally(mesh, n, TallyConfig()),
        lambda: PumiTally(mesh, n, TallyConfig(localization="locate")),
        lambda: StreamingTally(mesh, n, chunk_size=1024,
                               config=TallyConfig()),
    ):
        t = make()
        t.CopyInitialPosition(src.reshape(-1).copy())
        assert (t.elem_ids >= 0).all()
        prev = src
        for d in moves:
            t.MoveToNextLocation(prev.reshape(-1).copy(),
                                 d.reshape(-1).copy(),
                                 np.ones(n, np.int8), np.ones(n))
            prev = d
        results.append(np.asarray(t.flux, np.float64))

    expect = sum(
        float(np.linalg.norm(b - a, axis=1).sum())
        for a, b in zip([src] + moves[:-1], moves)
    )
    for flux in results:
        np.testing.assert_allclose(flux.sum(), expect, rtol=1e-8)
    np.testing.assert_allclose(results[0], results[1], rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(results[0], results[2], rtol=1e-12, atol=1e-12)


def test_delaunay_boundary_clamp():
    """Rays leaving through irregular hull facets clamp exactly to the
    hull (x=1 face here) and tally the clamped length."""
    mesh = _delaunay_mesh(250, seed=5)
    n = 500
    rng = np.random.default_rng(6)
    src = rng.uniform(0.3, 0.7, (n, 3))
    dest = src + np.array([5.0, 0.0, 0.0])
    t = PumiTally(mesh, n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dest.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    pos = t.positions.reshape(n, 3)
    np.testing.assert_allclose(pos[:, 0], 1.0, atol=1e-9)
    expect = float((1.0 - src[:, 0]).sum())
    got = float(np.asarray(t.flux, np.float64).sum())
    np.testing.assert_allclose(got, expect, rtol=1e-8)
