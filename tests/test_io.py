"""Mesh-file loading tests (.msh v2/v4 → TetMesh → full tally run)."""

import os

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally
from pumiumtally_tpu.io.load import load_mesh
from pumiumtally_tpu.mesh.box import box_arrays


def _write_msh_v2(path, coords, tets):
    with open(path, "w") as f:
        f.write("$MeshFormat\n2.2 0 8\n$EndMeshFormat\n$Nodes\n")
        f.write(f"{len(coords)}\n")
        for i, (x, y, z) in enumerate(coords, start=1):
            f.write(f"{i} {x:.17g} {y:.17g} {z:.17g}\n")
        f.write("$EndNodes\n$Elements\n")
        f.write(f"{len(tets)}\n")
        for i, t in enumerate(tets, start=1):
            f.write(f"{i} 4 2 0 1 {t[0]+1} {t[1]+1} {t[2]+1} {t[3]+1}\n")
        f.write("$EndElements\n")


def _write_msh_v4(path, coords, tets):
    with open(path, "w") as f:
        f.write("$MeshFormat\n4.1 0 8\n$EndMeshFormat\n$Nodes\n")
        nv = len(coords)
        f.write(f"1 {nv} 1 {nv}\n")
        f.write(f"3 1 0 {nv}\n")
        for i in range(1, nv + 1):
            f.write(f"{i}\n")
        for x, y, z in coords:
            f.write(f"{x:.17g} {y:.17g} {z:.17g}\n")
        f.write("$EndNodes\n$Elements\n")
        ne = len(tets)
        f.write(f"1 {ne} 1 {ne}\n")
        f.write(f"3 1 4 {ne}\n")
        for i, t in enumerate(tets, start=1):
            f.write(f"{i} {t[0]+1} {t[1]+1} {t[2]+1} {t[3]+1}\n")
        f.write("$EndElements\n")


def _write_msh_v2_binary(path, coords, tets):
    import struct

    with open(path, "wb") as f:
        f.write(b"$MeshFormat\n2.2 1 8\n")
        f.write(struct.pack("<i", 1))
        f.write(b"\n$EndMeshFormat\n$Nodes\n")
        f.write(f"{len(coords)}\n".encode())
        for i, (x, y, z) in enumerate(coords, start=1):
            f.write(struct.pack("<iddd", i, x, y, z))
        f.write(b"\n$EndNodes\n$Elements\n")
        f.write(f"{len(tets)}\n".encode())
        # one block of tets: etype=4, nfollow, ntags=2
        f.write(struct.pack("<iii", 4, len(tets), 2))
        for i, t in enumerate(tets, start=1):
            f.write(struct.pack("<7i", i, 0, 1,
                                t[0] + 1, t[1] + 1, t[2] + 1, t[3] + 1))
        f.write(b"\n$EndElements\n")


def _write_msh_v4_binary(path, coords, tets):
    import struct

    nv, ne = len(coords), len(tets)
    with open(path, "wb") as f:
        f.write(b"$MeshFormat\n4.1 1 8\n")
        f.write(struct.pack("<i", 1))
        f.write(b"\n$EndMeshFormat\n$Nodes\n")
        f.write(struct.pack("<4q", 1, nv, 1, nv))
        f.write(struct.pack("<iiiq", 3, 1, 0, nv))
        for i in range(1, nv + 1):
            f.write(struct.pack("<q", i))
        for x, y, z in coords:
            f.write(struct.pack("<3d", x, y, z))
        f.write(b"\n$EndNodes\n$Elements\n")
        f.write(struct.pack("<4q", 1, ne, 1, ne))
        f.write(struct.pack("<iiiq", 3, 1, 4, ne))
        for i, t in enumerate(tets, start=1):
            f.write(struct.pack("<5q", i,
                                t[0] + 1, t[1] + 1, t[2] + 1, t[3] + 1))
        f.write(b"\n$EndElements\n")


@pytest.mark.parametrize(
    "writer",
    [_write_msh_v2, _write_msh_v4, _write_msh_v2_binary,
     _write_msh_v4_binary],
)
def test_gmsh_round_trip(tmp_path, writer):
    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    path = str(tmp_path / "m.msh")
    writer(path, coords, tets)
    mesh = load_mesh(path)
    assert mesh.nelems == 48
    np.testing.assert_allclose(np.asarray(mesh.volumes).sum(), 1.0, atol=1e-12)


def test_pumitally_from_msh_path(tmp_path):
    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    path = str(tmp_path / "cube.msh")
    _write_msh_v2(path, coords, tets)
    t = PumiTally(path, 5)
    init = np.tile([0.1, 0.4, 0.5], (5, 1)).reshape(-1)
    t.CopyInitialPosition(init.copy())
    np.testing.assert_array_equal(t.elem_ids, np.full(5, 2))


def test_osh_round_trip(tmp_path):
    from pumiumtally_tpu.io.osh import read_osh, write_osh

    coords, tets = box_arrays(2, 1, 1, 3, 2, 2)
    path = str(tmp_path / "m.osh")
    write_osh(path, coords, tets)
    c2, t2 = read_osh(path)
    np.testing.assert_array_equal(c2, coords)
    # The Omega_h layout stores tet->tri->edge->vert adjacency chains;
    # the reader recovers each tet's vertex SET (order is irrelevant —
    # TetMesh re-orients by signed volume).
    np.testing.assert_array_equal(np.sort(t2, axis=1), np.sort(tets, axis=1))
    assert t2.shape == tets.shape
    # and through the full dispatch + engine
    mesh = load_mesh(path)
    np.testing.assert_allclose(np.asarray(mesh.volumes).sum(), 2.0, atol=1e-12)


def test_osh_multipart_merge(tmp_path):
    """A multi-part directory (per-rank streams + global id tags, the
    structure Omega_h writes for distributed meshes) merges back to the
    full mesh."""
    from pumiumtally_tpu.io.osh import read_osh, write_osh

    coords, tets = box_arrays(1, 1, 1, 3, 3, 3)
    path = str(tmp_path / "multi.osh")
    write_osh(path, coords, tets, nparts=4)
    import os

    assert sorted(os.listdir(path)) == [
        "0.osh", "1.osh", "2.osh", "3.osh", "nparts", "version"
    ]
    c2, t2 = read_osh(path)
    np.testing.assert_array_equal(c2, coords)
    np.testing.assert_array_equal(np.sort(t2, axis=1), np.sort(tets, axis=1))
    mesh = load_mesh(path)
    np.testing.assert_allclose(np.asarray(mesh.volumes).sum(), 1.0, atol=1e-12)


def test_osh_multipart_edge_cases(tmp_path):
    """Orphan vertices survive the multi-part round trip, and more
    parts than tets (empty rank streams) still read back."""
    from pumiumtally_tpu.io.osh import read_osh, write_osh

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)  # 6 tets, 8 verts
    coords = np.vstack([coords, [[5.0, 5.0, 5.0]]])  # orphan node
    path = str(tmp_path / "edge.osh")
    write_osh(path, coords, tets, nparts=4)
    c2, t2 = read_osh(path)
    np.testing.assert_array_equal(c2, coords)
    np.testing.assert_array_equal(np.sort(t2, axis=1), np.sort(tets, axis=1))

    tiny = str(tmp_path / "tiny.osh")
    write_osh(tiny, coords, tets[:2], nparts=4)  # 2 tets over 4 parts
    c3, t3 = read_osh(tiny)
    np.testing.assert_array_equal(c3, coords)
    np.testing.assert_array_equal(
        np.sort(t3, axis=1), np.sort(tets[:2], axis=1)
    )


def test_osh_legacy_container_still_loads(tmp_path):
    """Directories converted by the round-1 own-format writer keep
    loading (back-compat)."""
    import os
    import struct
    import zlib

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    d = tmp_path / "legacy.osh"
    os.makedirs(d)
    (d / "nparts").write_text("1\n")
    (d / "format").write_text("pumiumtally-osh 1\n")

    def arr(a, code):
        raw = np.ascontiguousarray(a).tobytes()
        z = zlib.compress(raw, 6)
        use = len(z) < len(raw)
        body = z if use else raw
        return struct.pack("<bqbq", code, a.size, int(use), len(body)) + body

    with open(d / "0.osh", "wb") as f:
        f.write(b"\xa1\x1a")
        f.write(struct.pack("<biiqq", 1, 1, 3, len(coords), len(tets)))
        f.write(arr(np.asarray(coords, np.float64).reshape(-1), 0))
        f.write(arr(np.asarray(tets, np.int32).reshape(-1), 1))
    from pumiumtally_tpu.io.osh import read_osh

    c2, t2 = read_osh(str(d))
    np.testing.assert_array_equal(c2, coords)
    np.testing.assert_array_equal(t2, tets)


def test_pumitally_from_osh_path(tmp_path):
    from pumiumtally_tpu.io.osh import write_osh

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    path = str(tmp_path / "cube.osh")
    write_osh(path, coords, tets)
    t = PumiTally(path, 5)
    init = np.tile([0.1, 0.4, 0.5], (5, 1)).reshape(-1)
    t.CopyInitialPosition(init.copy())
    np.testing.assert_array_equal(t.elem_ids, np.full(5, 2))


def test_cli_msh2osh_describe_scale(tmp_path, capsys):
    from pumiumtally_tpu.cli import main
    from pumiumtally_tpu.io.osh import read_osh

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    msh = str(tmp_path / "m.msh")
    _write_msh_v2(msh, coords, tets)
    osh = str(tmp_path / "m.osh")
    main(["msh2osh", msh, osh])
    main(["describe", osh])
    out = capsys.readouterr().out
    assert "48 tets" in out and "x range  : [0, 1]" in out

    scaled = str(tmp_path / "s.osh")
    main(["scale", osh, scaled, "10"])
    c2, _ = read_osh(scaled)
    np.testing.assert_allclose(c2, coords * 10, atol=1e-12)


def test_osh_clear_error(tmp_path):
    with pytest.raises((ValueError, NotImplementedError, FileNotFoundError)):
        load_mesh(str(tmp_path / "missing.osh"))


def test_osh_foreign_file_detected(tmp_path):
    """A directory that looks like a real Omega_h output (magic but no
    `format` metadata) gets a clear re-convert message, not garbage."""
    import os

    d = tmp_path / "omega.osh"
    os.makedirs(d)
    (d / "nparts").write_text("1\n")
    (d / "0.osh").write_bytes(b"\xa1\x1a" + b"\x00" * 64)
    with pytest.raises(ValueError, match="msh2osh"):
        load_mesh(str(d))


def test_unknown_format():
    with pytest.raises(ValueError):
        load_mesh("mesh.stl")


@pytest.mark.parametrize("mode", ["binary", "ascii", "vtu"])
def test_vtk_cell_data_round_trip(tmp_path, mode):
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars, write_vtk

    coords, tets = box_arrays(1, 1, 1, 3, 3, 3)
    ne = len(tets)
    rng = np.random.default_rng(0)
    flux = rng.uniform(size=ne)
    vol = rng.uniform(1, 2, size=ne)
    out = str(tmp_path / ("f.vtu" if mode == "vtu" else "f.vtk"))
    write_vtk(out, coords, tets, cell_data={"flux": flux, "volume": vol},
              ascii=(mode == "ascii"))
    np.testing.assert_allclose(read_vtk_cell_scalars(out, "flux"), flux,
                               rtol=1e-15)
    np.testing.assert_allclose(read_vtk_cell_scalars(out, "volume"), vol,
                               rtol=1e-15)
    if mode != "vtu":
        with open(out, "rb") as f:
            head = f.read(64).decode("ascii", "replace")
        assert head.startswith("# vtk DataFile")
        assert ("ASCII" in head) == (mode == "ascii")


def test_write_tally_results_normalization_contract(tmp_path):
    """Pin the ``WriteTallyResults`` normalization: by element volume
    ONLY — NOT per source particle (the reference README claims a
    total-weight division its code never performs; the code is the
    contract, api/tally.py docstring). Asserted against the
    reference's 5-particle oracle (native/test_host.c move 1): raw
    flux[2,3,4] = 1.5/0.5/2.5 on the 6-tet unit cube, every tet volume
    1/6, so the WRITTEN field is 9/3/15 — and would be 9/5, 3/5, 15/5
    under the per-source-particle normalization this test exists to
    refuse."""
    from pumiumtally_tpu import build_box
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars

    num = 5
    t = PumiTally(build_box(1, 1, 1, 1, 1, 1), num)
    t.CopyInitialPosition(
        np.tile([0.1, 0.4, 0.5], num).astype(np.float64))
    t.MoveToNextLocation(
        np.tile([0.1, 0.4, 0.5], num).astype(np.float64),
        np.tile([1.2, 0.4, 0.5], num).astype(np.float64),
        np.ones(num, np.int8), np.ones(num),
    )
    out = str(tmp_path / "oracle.vtk")
    t.WriteTallyResults(out)
    got = read_vtk_cell_scalars(out, "flux")
    raw = np.array([0.0, 0.0, 0.3 * num, 0.1 * num, 0.5 * num, 0.0])
    vol = read_vtk_cell_scalars(out, "volume")
    np.testing.assert_allclose(vol, np.full(6, 1.0 / 6.0), rtol=1e-12)
    np.testing.assert_allclose(got, raw / vol, atol=1e-8)  # volume-only
    # The per-source-particle variant differs by 5x on the scored
    # elements — a normalization regression cannot pass both.
    assert np.all(np.abs(got[2:5] - raw[2:5] / vol[2:5] / num) > 1.0)


def test_vtk_binary_scales(tmp_path):
    """Binary output must be byte-bounded (~raw array size) regardless
    of the data values — the point of replacing savetxt for 1M-tet
    meshes, where full-precision ASCII floats are ~3x the bytes and
    orders of magnitude slower to format."""
    import os

    from pumiumtally_tpu.io.vtk import write_vtk

    coords, tets = box_arrays(1, 1, 1, 8, 8, 8)  # 3072 tets
    ne = len(tets)
    rng = np.random.default_rng(0)
    flux = rng.uniform(size=ne)  # full-precision values
    b = str(tmp_path / "b.vtk")
    write_vtk(b, coords, tets, cell_data={"flux": flux})
    raw = coords.size * 8 + ne * 5 * 4 + ne * 4 + ne * 8
    assert os.path.getsize(b) < raw + 4096  # headers only on top of raw


def test_cli_box_and_pincell_generation(tmp_path, capsys):
    from pumiumtally_tpu.cli import main
    from pumiumtally_tpu.io.load import load_mesh

    box = str(tmp_path / "box.osh")
    main(["box", box, "--nx", "3", "--ny", "3", "--nz", "3"])
    mesh = load_mesh(box)
    assert mesh.nelems == 6 * 27
    np.testing.assert_allclose(np.asarray(mesh.volumes).sum(), 1.0,
                               atol=1e-12)

    pin = str(tmp_path / "pin.osh")
    main(["pincell", pin, "--n-theta", "8", "--nz", "2"])
    out = capsys.readouterr().out
    assert "fuel" in out and "moderator" in out
    mesh = load_mesh(pin)
    np.testing.assert_allclose(
        np.asarray(mesh.volumes).sum(), 1.26**2, rtol=1e-12
    )
    # The material classification rides in the written stream as the
    # class_id element tag.
    from pumiumtally_tpu.io.osh import _WRITE_VERSION, _read_stream_any

    with open(pin + "/0.osh", "rb") as f:
        parsed = _read_stream_any(f, _WRITE_VERSION)
    region = np.asarray(parsed["tags"][3]["class_id"])
    assert set(np.unique(region)) == {0, 1}
    assert region.shape[0] == mesh.nelems


def test_osh_elem_tag_validation(tmp_path):
    from pumiumtally_tpu.io.osh import (
        _WRITE_VERSION,
        _read_stream_any,
        write_osh,
    )

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    ne = len(tets)
    with pytest.raises(ValueError, match="reserved"):
        write_osh(str(tmp_path / "r.osh"), coords, tets,
                  elem_tags={"global": np.arange(ne)})
    # float32/int16 widen exactly instead of silently casting to int32
    p = str(tmp_path / "t.osh")
    write_osh(p, coords, tets, elem_tags={
        "density": np.linspace(0.1, 0.7, ne).astype(np.float32),
        "mat": np.arange(ne, dtype=np.int16),
    })
    with open(p + "/0.osh", "rb") as f:
        tags = _read_stream_any(f, _WRITE_VERSION)["tags"][3]
    np.testing.assert_allclose(
        tags["density"], np.linspace(0.1, 0.7, ne).astype(np.float32),
        rtol=1e-7,
    )
    np.testing.assert_array_equal(tags["mat"], np.arange(ne))


def test_osh_elem_tags_read_back(tmp_path):
    """read_osh(with_tags=True): per-element tags survive the round
    trip in the returned ELEMENT order — single part, multi-part
    (merged through globals), and the C++-written fixture's msh2osh
    tag set."""
    from pumiumtally_tpu.io.osh import read_osh, write_osh

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    ne = len(tets)
    mat = (np.arange(ne, dtype=np.int32) % 3) + 1
    dens = np.linspace(0.5, 2.0, ne)
    for nparts in (1, 3):
        p = str(tmp_path / f"t{nparts}.osh")
        write_osh(p, coords, tets, nparts=nparts,
                  elem_tags={"mat": mat, "density": dens})
        c2, t2, tags = read_osh(p, with_tags=True)
        # Identify each returned element by its vertex set and check
        # its tag rode along (multi-part merge may reorder elements).
        key = {tuple(sorted(t)): i for i, t in enumerate(tets.tolist())}
        back = np.array([key[tuple(sorted(t))] for t in t2.tolist()])
        np.testing.assert_array_equal(tags["mat"], mat[back])
        np.testing.assert_allclose(tags["density"], dens[back],
                                   rtol=1e-15)
    # Plain read is unchanged.
    assert len(read_osh(str(tmp_path / "t1.osh"))) == 2
    # The C++ transcription fixture carries class_id/class_dim.
    _, _, ftags = read_osh(
        os.path.join(_FIX, "cube_omega_cpp.osh"), with_tags=True
    )
    np.testing.assert_array_equal(ftags["class_id"], np.ones(6, np.int32))
    np.testing.assert_array_equal(ftags["class_dim"],
                                  np.full(6, 3, np.int8))


def test_pvtu_pieces_round_trip(tmp_path):
    """write_pvtu: per-owner pieces cover every element exactly once;
    piece cell data concatenated in owner order equals the original."""
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars, write_pvtu

    coords, tets = box_arrays(1, 1, 1, 3, 3, 3)
    ne = tets.shape[0]
    rng = np.random.default_rng(8)
    owner = rng.integers(0, 4, ne)
    flux = rng.uniform(size=ne)
    path = str(tmp_path / "out.pvtu")
    write_pvtu(path, coords, tets, owner, cell_data={"flux": flux})

    import os
    pieces = sorted(p for p in os.listdir(tmp_path) if p.endswith(".vtu"))
    assert pieces == [f"out_p{r}.vtu" for r in range(4)]
    text = open(path).read()
    for p in pieces:
        assert f'Source="{p}"' in text
    got = np.concatenate([
        read_vtk_cell_scalars(str(tmp_path / f"out_p{r}.vtu"), "flux")
        for r in range(4)
    ])
    want = np.concatenate([flux[owner == r] for r in range(4)])
    np.testing.assert_array_equal(got, want)
    counts = [read_vtk_cell_scalars(str(tmp_path / f"out_p{r}.vtu"),
                                    "flux").shape[0] for r in range(4)]
    assert sum(counts) == ne


@pytest.mark.slow
def test_partitioned_write_pvtu(tmp_path):
    """PartitionedPumiTally writes rank-aware .pvtu pieces whose
    assembled flux matches the engine's normalized flux."""
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig, build_box
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 3, 3, 3)
    dm = make_device_mesh(4)
    n = 500
    t = PartitionedPumiTally(mesh, n, TallyConfig(device_mesh=dm,
                                                  capacity_factor=4.0))
    rng = np.random.default_rng(2)
    src = rng.uniform(0.1, 0.9, (n, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, np.clip(src + 0.2, 0.05, 0.95).reshape(-1).copy())
    path = str(tmp_path / "res.pvtu")
    t.WriteTallyResults(path)

    owner = t.engine.part.owner
    want = np.asarray(t.normalized_flux())
    got = np.empty_like(want)
    for r in range(4):
        got[owner == r] = read_vtk_cell_scalars(
            str(tmp_path / f"res_p{r}.vtu"), "flux")
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)
    # monolithic writer refuses .pvtu with guidance
    from pumiumtally_tpu.io.vtk import write_vtk
    import pytest as _pytest
    with _pytest.raises(ValueError, match="pvtu"):
        write_vtk(str(tmp_path / "x.pvtu"), np.asarray(mesh.coords),
                  np.asarray(mesh.tet2vert), cell_data={})


def test_pvtu_explicit_nparts_writes_empty_trailing_pieces(tmp_path):
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars, write_pvtu

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)  # 6 tets
    owner = np.zeros(6, np.int32)  # everything on rank 0 of 4
    path = str(tmp_path / "skew.pvtu")
    write_pvtu(path, coords, tets, owner, cell_data={"flux": np.ones(6)},
               nparts=4)
    import os
    pieces = sorted(p for p in os.listdir(tmp_path) if p.endswith(".vtu"))
    assert pieces == [f"skew_p{r}.vtu" for r in range(4)]
    assert read_vtk_cell_scalars(str(tmp_path / "skew_p0.vtu"),
                                 "flux").shape[0] == 6
    import pytest as _pytest
    with _pytest.raises(ValueError, match="nparts"):
        write_pvtu(str(tmp_path / "bad.pvtu"), coords, tets,
                   np.full(6, 5), nparts=2)


def test_cli_lattice_generation(tmp_path, capsys):
    from pumiumtally_tpu.cli import main as cli_main
    from pumiumtally_tpu.io.osh import _WRITE_VERSION, _read_stream_any

    out = str(tmp_path / "asm.osh")
    cli_main(["lattice", out, "--nx", "2", "--ny", "2", "--n-theta", "8",
              "--rings-fuel", "2", "--rings-pad", "2", "--nz", "2"])
    msg = capsys.readouterr().out
    assert "2x2 cells" in msg
    mesh = load_mesh(out)
    np.testing.assert_allclose(
        np.asarray(mesh.volumes).sum(), 4 * 1.26**2, rtol=1e-12
    )
    with open(out + "/0.osh", "rb") as f:
        parsed = _read_stream_any(f, _WRITE_VERSION)
    cid = np.asarray(parsed["tags"][3]["cell_id"])
    assert sorted(np.unique(cid).tolist()) == [0, 1, 2, 3]
    assert cid.shape[0] == mesh.nelems


# -- independently generated Omega_h-layout fixtures (tests/data/) ----------
# Written by tools/make_osh_fixture.py: fresh struct.pack code sharing
# nothing with io/osh.py, first-appearance entity numbering, stored
# child vertex orders from the defining parent (so tet->tri / tri->edge
# alignment codes carry genuine rotations/flips), msh2osh-style
# class_id/class_dim tags, RIB hints, and (2-part) shared interface
# vertices with real owner arrays. See that script's docstring for what
# this does and does not prove (reference PumiTallyImpl.cpp:562).

_FIX = os.path.join(os.path.dirname(__file__), "data")
_CUBE_VERTS = np.array([
    [0, 0, 0], [1, 0, 0], [1, 1, 0], [0, 1, 0],
    [0, 0, 1], [1, 0, 1], [1, 1, 1], [0, 1, 1],
], dtype=np.float64)
_CUBE_TETS = {
    (0, 1, 2, 6), (0, 2, 3, 6), (0, 3, 6, 7),
    (0, 4, 6, 7), (0, 4, 5, 6), (0, 1, 5, 6),
}


@pytest.mark.parametrize("name", [
    # tools/make_osh_fixture.py output: big-endian with an in-stream
    # version (this package's earlier reading of the layout).
    "cube_omega1.osh", "cube_omega2.osh",
    # native/osh_writer.cpp output: a C++ transcription of the
    # upstream writer's serialization logic — little-endian, version
    # only in the directory file, compress2-at-Z_BEST_SPEED zlib
    # framing (and a raw variant). NOT produced by any Python module
    # in this repo.
    "cube_omega_cpp.osh", "cube_omega_cpp_raw.osh",
])
def test_osh_reads_independent_fixture(name):
    from pumiumtally_tpu.io.osh import read_osh

    coords, tets = read_osh(os.path.join(_FIX, name))
    np.testing.assert_allclose(coords, _CUBE_VERTS)
    assert {tuple(sorted(t)) for t in tets.tolist()} == _CUBE_TETS


def test_osh_cpp_fixture_is_little_endian_without_stream_version():
    """Pin the layout axes the C++ transcription settles differently
    from the earlier Python fixtures, so regeneration cannot silently
    collapse the variant coverage: after the 2-byte magic the stream
    begins with the compression flag (no int32 version), and the first
    array count is little-endian."""
    import struct

    with open(os.path.join(_FIX, "cube_omega_cpp_raw.osh", "0.osh"),
              "rb") as f:
        data = f.read()
    assert data[:2] == b"\xa1\x1a"
    # compressed?=0, family=0 (simplex), dim=3 — not a version int32.
    assert data[2] == 0 and data[3] == 0 and data[4] == 3
    # meta: cs(i32) cr(i32) parting(i8) ng(i32) hints(i8) then nverts.
    cs, cr = struct.unpack_from("<ii", data, 5)
    assert (cs, cr) == (1, 0)
    (nverts,) = struct.unpack_from("<i", data, 5 + 4 + 4 + 1 + 4 + 1)
    assert nverts == 8  # little-endian read of the true count
    # edge->vert count follows: 2 per edge, 19 edges for the 6-tet cube.
    (ev_count,) = struct.unpack_from("<i", data, 5 + 4 + 4 + 1 + 4 + 1 + 4)
    assert ev_count == 38


def test_osh_fixture_builds_mesh_with_unit_volume():
    """End-to-end: fixture -> TetMesh -> volumes sum to the cube's."""
    from pumiumtally_tpu.io.load import load_mesh

    mesh = load_mesh(os.path.join(_FIX, "cube_omega1.osh"))
    assert mesh.nelems == 6
    total = float(np.asarray(mesh.volumes, np.float64).sum())
    np.testing.assert_allclose(total, 1.0, rtol=1e-12)


def test_osh_fixture_codes_are_nontrivial():
    """Guard the fixture's point: if regeneration ever made every
    alignment code zero (ascending stored orders), it would stop
    exercising the code-insensitivity claim."""
    import struct
    import zlib

    with open(os.path.join(_FIX, "cube_omega1.osh", "0.osh"), "rb") as f:
        data = f.read()
    # Walk to the two code arrays with a minimal ad-hoc scan: skip
    # header (magic2+ver4+c1+fam1+dim1+cs4+cr4+part1+ng4+hints: 1+4+48)
    off = 2 + 4 + 1 + 1 + 1 + 4 + 4 + 1 + 4 + (1 + 4 + 48) + 4

    def arr(off, itemsize):
        count = struct.unpack_from(">i", data, off)[0]
        zlen = struct.unpack_from(">q", data, off + 4)[0]
        raw = zlib.decompress(data[off + 12: off + 12 + zlen])
        assert len(raw) == count * itemsize
        return raw, off + 12 + zlen

    _, off = arr(off, 4)            # edge2vert
    _, off = arr(off, 4)            # tri2edge
    tri_codes, off = arr(off, 1)
    _, off = arr(off, 4)            # tet2tri
    tet_codes, off = arr(off, 1)
    assert any(b != 0 for b in tri_codes)
    assert any(b != 0 for b in tet_codes)


def test_cli_generators_dispatch_msh_output(tmp_path, capsys):
    """`box ... out.msh` must write a real Gmsh 2.2 file (previously it
    silently wrote an .osh DIRECTORY at the .msh path), and the writer
    must round-trip through the v2 reader, physical ids included."""
    from pumiumtally_tpu.cli import main as cli
    from pumiumtally_tpu.io.gmsh import read_gmsh, write_gmsh

    out = str(tmp_path / "b.msh")
    cli(["box", "--nx", "3", "--ny", "3", "--nz", "3", out])
    assert os.path.isfile(out)  # a FILE, not an .osh directory
    coords, tets = read_gmsh(out)
    assert tets.shape == (6 * 27, 4)
    mesh = load_mesh(out)
    np.testing.assert_allclose(
        float(np.asarray(mesh.volumes, np.float64).sum()), 1.0, rtol=1e-12)

    # explicit writer round-trip with physical ids
    phys = np.arange(tets.shape[0]) % 3
    p2 = str(tmp_path / "p.msh")
    write_gmsh(p2, coords, tets, physical=phys)
    c2, t2 = read_gmsh(p2)
    np.testing.assert_allclose(c2, coords)
    np.testing.assert_array_equal(t2, tets)


@pytest.mark.slow
def test_cli_autotune_verb(tmp_path, capsys):
    """`pumiumtally autotune mesh.osh` sweeps the knob grid on the test
    backend and prints a usable best-config line."""
    from pumiumtally_tpu.cli import main as cli
    from pumiumtally_tpu.utils.autotune import DEFAULT_CANDIDATES

    out = str(tmp_path / "m.osh")
    cli(["box", "--nx", "3", "--ny", "3", "--nz", "3", out])
    capsys.readouterr()
    cli(["autotune", out, "--particles", "1500", "--moves", "2"])
    text = capsys.readouterr().out
    assert "best:" in text and "TallyConfig(" in text
    # every default candidate measured (one "->" line each)
    assert text.count("->") >= len(DEFAULT_CANDIDATES)


@pytest.mark.parametrize("fixture,with_version_file", [
    # Big-endian stream carrying its own version, no version file.
    ("cube_omega1.osh", False),
    # The C++ transcription's upstream-protocol framing: little-endian,
    # version only in the directory file (compressed + raw variants) —
    # the variant auto-detection must stay fuzz-clean on ALL framings.
    ("cube_omega_cpp.osh", True),
    ("cube_omega_cpp_raw.osh", True),
])
def test_osh_truncation_fuzz(fixture, with_version_file):
    """Every truncation of a valid stream must fail with a clean
    ValueError/OshFormatError — never a crash, hang, or silent
    success (the reader is fed real user files)."""
    from pumiumtally_tpu.io.osh import read_osh

    src = os.path.join(_FIX, fixture, "0.osh")
    with open(src, "rb") as f:
        data = f.read()
    import tempfile

    rng = np.random.default_rng(91)
    cuts = sorted({int(c) for c in rng.integers(0, len(data), 40)} | {0, 1, 7})
    with tempfile.TemporaryDirectory() as td:
        d = os.path.join(td, "t.osh")
        os.makedirs(d)
        with open(os.path.join(d, "nparts"), "w") as f:
            f.write("1\n")
        if with_version_file:
            with open(os.path.join(d, "version"), "w") as f:
                f.write("9\n")
        for cut in cuts:
            with open(os.path.join(d, "0.osh"), "wb") as f:
                f.write(data[:cut])
            with pytest.raises(ValueError):
                read_osh(d)
        # and byte corruption in the payloads
        for _ in range(10):
            b = bytearray(data)
            pos = int(rng.integers(60, len(data)))
            b[pos] ^= 0xFF
            with open(os.path.join(d, "0.osh"), "wb") as f:
                f.write(bytes(b))
            try:
                coords, tets = read_osh(d)
                # a flipped byte the checks cannot see must still yield
                # structurally sane output, not garbage shapes
                assert coords.shape[1] == 3 and tets.shape[1] == 4
            except ValueError:
                pass  # the expected outcome
        if fixture != "cube_omega1.osh":
            return  # the crafted-bomb tail below is framing-specific
        # crafted inflate bomb: small declared count, huge payload —
        # a minimal self-contained stream (no fixture-layout coupling)
        import struct
        import zlib

        bomb = zlib.compress(b"\x00" * 100_000)
        hdr = (b"\xa1\x1a" + struct.pack(">i", 9)      # magic, version
               + struct.pack(">b", 1)                   # compressed
               + struct.pack(">b", 0)                   # family simplex
               + struct.pack(">b", 3)                   # dim
               + struct.pack(">ii", 1, 0)               # comm size/rank
               + struct.pack(">b", 0)                   # parting
               + struct.pack(">i", 0)                   # nghost
               + struct.pack(">b", 0)                   # no hints
               + struct.pack(">i", 4))                  # nverts
        with open(os.path.join(d, "0.osh"), "wb") as f:
            f.write(hdr + struct.pack(">i", 10)
                    + struct.pack(">q", len(bomb)) + bomb)
        with pytest.raises(ValueError, match="inflates past"):
            read_osh(d)


def test_gmsh_truncation_fuzz(tmp_path):
    """Truncations and byte flips of every .msh flavor must fail with a
    clean ValueError (or parse to sane shapes) — never leak raw parser
    exceptions (fuzz-found: a cut ASCII $Nodes line raised IndexError)."""
    from pumiumtally_tpu.io.gmsh import read_gmsh, write_gmsh

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    writers = {
        "ascii_v2": lambda p: write_gmsh(p, coords, tets),
        "bin_v2": lambda p: _write_msh_v2_binary(p, coords, tets),
        "bin_v4": lambda p: _write_msh_v4_binary(p, coords, tets),
    }
    rng = np.random.default_rng(93)
    for name, writer in writers.items():
        src = str(tmp_path / f"{name}.msh")
        writer(src)
        with open(src, "rb") as f:
            data = f.read()
        q = str(tmp_path / "t.msh")
        for cut in {int(c) for c in rng.integers(0, len(data), 25)}:
            with open(q, "wb") as f:
                f.write(data[:cut])
            try:
                c2, t2 = read_gmsh(q)
                assert c2.shape[1] == 3 and t2.shape[1] == 4, (name, cut)
            except ValueError:
                pass
        for _ in range(10):
            b = bytearray(data)
            b[int(rng.integers(20, len(data)))] ^= 0xFF
            with open(q, "wb") as f:
                f.write(bytes(b))
            try:
                c2, t2 = read_gmsh(q)
                assert c2.shape[1] == 3 and t2.shape[1] == 4, name
            except ValueError:
                pass


def test_gmsh_hostile_headers_rejected(tmp_path):
    """Crafted count fields must fail cleanly: a negative binary-v2
    block count previously spun the parser forever, and a 2^31 node
    count attempted a 16 GiB allocation."""
    import struct

    from pumiumtally_tpu.io.gmsh import read_gmsh

    neg = str(tmp_path / "neg.msh")
    with open(neg, "wb") as f:
        f.write(b"$MeshFormat\n2.2 1 8\n" + struct.pack("<i", 1)
                + b"\n$EndMeshFormat\n")
        f.write(b"$Nodes\n1\n" + struct.pack("<iddd", 1, 0, 0, 0)
                + b"\n$EndNodes\n")
        f.write(b"$Elements\n1\n" + struct.pack("<iii", 1, -1, 0)
                + b"\x00" * 12 + b"\n$EndElements\n")
    with pytest.raises(ValueError, match="implausible"):
        read_gmsh(neg)

    big = str(tmp_path / "big.msh")
    with open(big, "wb") as f:
        f.write(b"$MeshFormat\n4.1 1 8\n" + struct.pack("<i", 1)
                + b"\n$EndMeshFormat\n")
        f.write(b"$Nodes\n" + struct.pack("<4q", 1, 2**31, 1, 2**31)
                + b"\n$EndNodes\n")
        f.write(b"$Elements\n" + struct.pack("<4q", 0, 0, 0, 0)
                + b"\n$EndElements\n")
    with pytest.raises(ValueError, match="implausible"):
        read_gmsh(big)


@pytest.mark.parametrize("kind", ["vtk_bin", "vtk_ascii", "vtu"])
def test_vtk_truncation_fuzz(tmp_path, kind):
    """Truncations/byte flips of every VTK flavor must fail with a
    clean ValueError/KeyError or parse to the full-length array —
    never raw parser exceptions or silently SHORT data (fuzz-found:
    a cut binary .vtk returned 42 of 48 declared values)."""
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars, write_vtk

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    flux = np.arange(48.0)
    ext = ".vtu" if kind == "vtu" else ".vtk"
    src = str(tmp_path / f"m{ext}")
    write_vtk(src, coords, tets, cell_data={"flux": flux},
              ascii=(kind == "vtk_ascii"))
    with open(src, "rb") as f:
        data = f.read()
    q = str(tmp_path / f"t{ext}")
    rng = np.random.default_rng(95)
    # Dense sweep: EVERY truncation point (the silent-garbage windows
    # found by review were only ~40 bytes wide). A successful parse of
    # a TRUNCATED file must return the exact original values.
    for cut in range(len(data)):
        with open(q, "wb") as f:
            f.write(data[:cut])
        try:
            out = read_vtk_cell_scalars(q, "flux")
            np.testing.assert_array_equal(out, flux, err_msg=f"{kind}@{cut}")
        except (ValueError, KeyError):
            pass
    for _ in range(10):
        b = bytearray(data)
        b[int(rng.integers(0, len(data)))] ^= 0xFF
        with open(q, "wb") as f:
            f.write(bytes(b))
        try:
            out = read_vtk_cell_scalars(q, "flux")
            assert out.shape[0] == 48, kind
        except (ValueError, KeyError):
            pass


@pytest.mark.slow
def test_cli_aot_check_verb(capsys):
    """`pumiumtally aot-check` compiles the walk kernel chipless via
    the local libtpu and reports OK (cluster pre-flight; skips where
    libtpu itself is absent)."""
    from pumiumtally_tpu.cli import main as cli

    try:
        cli(["aot-check"])
    except SystemExit as e:
        out = capsys.readouterr()
        if ("topology not implemented" in out.out + out.err
                or "libtpu.so" in out.out + out.err):
            pytest.skip("libtpu unavailable for AOT")
        raise AssertionError(out.out + out.err) from e
    out = capsys.readouterr()
    assert "[OK] walk kernel" in out.out


# ---------------------------------------------------------------------------
# Multi-array cell data: ordering + the name-collision guard (round 10)
# ---------------------------------------------------------------------------

def test_multi_array_ordering_round_trip_all_formats(tmp_path):
    """MANY cell arrays written together must each read back by NAME
    with their own values — in the legacy .vtk (binary AND ascii), in
    .vtu, and in every .pvtu piece. Guards the writer/reader pairing
    against array-order mixups when the payload grows (the scoring
    lanes add a dozen arrays beside flux+volume)."""
    from pumiumtally_tpu.io.vtk import (
        read_vtk_cell_scalars,
        write_pvtu,
        write_vtk,
    )

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    ne = tets.shape[0]
    rng = np.random.default_rng(10)
    arrays = {
        name: rng.uniform(size=ne)
        for name in ("flux", "volume", "flux_bin0", "flux_bin1",
                     "heating_bin0", "events_bin1", "rel_err")
    }
    for fname, kw in (("a.vtk", {}), ("a_ascii.vtk", {"ascii": True}),
                      ("a.vtu", {})):
        path = str(tmp_path / fname)
        write_vtk(path, coords, tets, cell_data=arrays, **kw)
        for name, want in arrays.items():
            got = read_vtk_cell_scalars(path, name)
            np.testing.assert_allclose(got, want, rtol=0, atol=0)
    owner = rng.integers(0, 3, ne)
    ppath = str(tmp_path / "a.pvtu")
    write_pvtu(ppath, coords, tets, owner, cell_data=arrays)
    for r in range(3):
        piece = str(tmp_path / f"a_p{r}.vtu")
        sel = owner == r
        for name, want in arrays.items():
            np.testing.assert_array_equal(
                read_vtk_cell_scalars(piece, name), want[sel]
            )


def test_merge_cell_data_refuses_collisions():
    """A user-facing array name colliding with an existing payload
    array (e.g. a scoring lane named ``flux_mean`` beside the stats
    arrays) must raise a clear ValueError, never silently shadow."""
    from pumiumtally_tpu.io.vtk import merge_cell_data

    a = {"flux": np.ones(3), "volume": np.ones(3)}
    b = {"flux_mean": np.ones(3)}
    merged = merge_cell_data(a, b, None, {})
    assert set(merged) == {"flux", "volume", "flux_mean"}
    with pytest.raises(ValueError, match="flux_mean"):
        merge_cell_data(a, b, {"flux_mean": np.zeros(3)})
    with pytest.raises(ValueError, match="collision"):
        merge_cell_data(a, {"flux": np.zeros(3)})
