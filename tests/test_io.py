"""Mesh-file loading tests (.msh v2/v4 → TetMesh → full tally run)."""

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally
from pumiumtally_tpu.io.load import load_mesh
from pumiumtally_tpu.mesh.box import box_arrays


def _write_msh_v2(path, coords, tets):
    with open(path, "w") as f:
        f.write("$MeshFormat\n2.2 0 8\n$EndMeshFormat\n$Nodes\n")
        f.write(f"{len(coords)}\n")
        for i, (x, y, z) in enumerate(coords, start=1):
            f.write(f"{i} {x:.17g} {y:.17g} {z:.17g}\n")
        f.write("$EndNodes\n$Elements\n")
        f.write(f"{len(tets)}\n")
        for i, t in enumerate(tets, start=1):
            f.write(f"{i} 4 2 0 1 {t[0]+1} {t[1]+1} {t[2]+1} {t[3]+1}\n")
        f.write("$EndElements\n")


def _write_msh_v4(path, coords, tets):
    with open(path, "w") as f:
        f.write("$MeshFormat\n4.1 0 8\n$EndMeshFormat\n$Nodes\n")
        nv = len(coords)
        f.write(f"1 {nv} 1 {nv}\n")
        f.write(f"3 1 0 {nv}\n")
        for i in range(1, nv + 1):
            f.write(f"{i}\n")
        for x, y, z in coords:
            f.write(f"{x:.17g} {y:.17g} {z:.17g}\n")
        f.write("$EndNodes\n$Elements\n")
        ne = len(tets)
        f.write(f"1 {ne} 1 {ne}\n")
        f.write(f"3 1 4 {ne}\n")
        for i, t in enumerate(tets, start=1):
            f.write(f"{i} {t[0]+1} {t[1]+1} {t[2]+1} {t[3]+1}\n")
        f.write("$EndElements\n")


@pytest.mark.parametrize("writer", [_write_msh_v2, _write_msh_v4])
def test_gmsh_round_trip(tmp_path, writer):
    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    path = str(tmp_path / "m.msh")
    writer(path, coords, tets)
    mesh = load_mesh(path)
    assert mesh.nelems == 48
    np.testing.assert_allclose(np.asarray(mesh.volumes).sum(), 1.0, atol=1e-12)


def test_pumitally_from_msh_path(tmp_path):
    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    path = str(tmp_path / "cube.msh")
    _write_msh_v2(path, coords, tets)
    t = PumiTally(path, 5)
    init = np.tile([0.1, 0.4, 0.5], (5, 1)).reshape(-1)
    t.CopyInitialPosition(init.copy())
    np.testing.assert_array_equal(t.elem_ids, np.full(5, 2))


def test_osh_round_trip(tmp_path):
    from pumiumtally_tpu.io.osh import read_osh, write_osh

    coords, tets = box_arrays(2, 1, 1, 3, 2, 2)
    path = str(tmp_path / "m.osh")
    write_osh(path, coords, tets)
    c2, t2 = read_osh(path)
    np.testing.assert_array_equal(c2, coords)
    np.testing.assert_array_equal(t2, tets)
    # and through the full dispatch + engine
    mesh = load_mesh(path)
    np.testing.assert_allclose(np.asarray(mesh.volumes).sum(), 2.0, atol=1e-12)


def test_pumitally_from_osh_path(tmp_path):
    from pumiumtally_tpu.io.osh import write_osh

    coords, tets = box_arrays(1, 1, 1, 1, 1, 1)
    path = str(tmp_path / "cube.osh")
    write_osh(path, coords, tets)
    t = PumiTally(path, 5)
    init = np.tile([0.1, 0.4, 0.5], (5, 1)).reshape(-1)
    t.CopyInitialPosition(init.copy())
    np.testing.assert_array_equal(t.elem_ids, np.full(5, 2))


def test_cli_msh2osh_describe_scale(tmp_path, capsys):
    from pumiumtally_tpu.cli import main
    from pumiumtally_tpu.io.osh import read_osh

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    msh = str(tmp_path / "m.msh")
    _write_msh_v2(msh, coords, tets)
    osh = str(tmp_path / "m.osh")
    main(["msh2osh", msh, osh])
    main(["describe", osh])
    out = capsys.readouterr().out
    assert "48 tets" in out and "x range  : [0, 1]" in out

    scaled = str(tmp_path / "s.osh")
    main(["scale", osh, scaled, "10"])
    c2, _ = read_osh(scaled)
    np.testing.assert_allclose(c2, coords * 10, atol=1e-12)


def test_osh_clear_error(tmp_path):
    with pytest.raises((ValueError, NotImplementedError, FileNotFoundError)):
        load_mesh(str(tmp_path / "missing.osh"))


def test_osh_foreign_file_detected(tmp_path):
    """A directory that looks like a real Omega_h output (magic but no
    `format` metadata) gets a clear re-convert message, not garbage."""
    import os

    d = tmp_path / "omega.osh"
    os.makedirs(d)
    (d / "nparts").write_text("1\n")
    (d / "0.osh").write_bytes(b"\xa1\x1a" + b"\x00" * 64)
    with pytest.raises(ValueError, match="msh2osh"):
        load_mesh(str(d))


def test_unknown_format():
    with pytest.raises(ValueError):
        load_mesh("mesh.stl")
