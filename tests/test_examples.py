"""The examples are documentation that must keep executing.

Each example runs as a subprocess on the test backend (a fresh
interpreter forced onto 8 virtual CPU devices) at the example's own
default sizes, so API drift breaks CI, not a user's first contact with
the framework.
"""

import os
import re
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(name: str, tmp_path, args=(), timeout=420):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial a device tunnel
    env["JAX_PLATFORMS"] = "cpu"
    # The subprocess is a fresh interpreter, so (unlike conftest.py,
    # which must respect an already-imported jax) the device count can
    # be FORCED to 8 — the piece-count assertion below depends on it.
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["PYTHONPATH"] = _ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", name), *args],
        cwd=str(tmp_path),  # examples write output files
        env=env, capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.slow
@pytest.mark.parametrize("mode,protocol,extra", [
    ("mono", "fast", []),
    ("mono", "reference", []),  # origins every move (echo-dedup path)
    ("stream", "fast", []),
    ("part", "fast", []),
    ("part", "fast", ["--vmem-bound", "200"]),  # blocked vmem local walk
])
def test_openmc_style_driver_runs(tmp_path, mode, protocol, extra):
    proc = _run_example(
        "openmc_style_driver.py", tmp_path,
        args=["--mode", mode, "--protocol", protocol, *extra],
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out_files = os.listdir(tmp_path)
    if mode == "part":  # partitioned mode writes rank-aware pieces
        assert any(f.endswith(".pvtu") for f in out_files)
    else:
        assert any(f.endswith(".vtk") for f in out_files)


@pytest.mark.slow
def test_multi_client_service(tmp_path):
    """Two concurrent drivers on one service: the example asserts each
    session's flux bitwise against its serial single-client run (the
    service determinism contract) and must keep executing."""
    proc = _run_example("multi_client_service.py", tmp_path)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.count("bitwise vs serial run: True") == 2
    assert "zero cross-talk" in proc.stdout


@pytest.mark.slow
def test_multichip_checkpointed_run(tmp_path):
    proc = _run_example("multichip_checkpointed_run.py", tmp_path)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "flux_result.pvtu" in proc.stdout
    out = os.listdir(tmp_path)
    assert "flux_result.pvtu" in out and "campaign.npz" in out
    assert sum(f.endswith(".vtu") for f in out) >= 8  # one piece per chip
