"""VMEM one-hot MXU walk (ops/vmem_walk.py) vs the gather-based
``walk_local`` — semantics parity in pallas interpret mode (the CPU
environment; Mosaic-compiled timing happens in the on-chip suite).

The kernel is documented NOT bitwise-identical (column-wise projections
round differently from the einsum), so parity here is: identical
done/exited/pending/lelem transitions on generic (non-face-tie)
workloads, positions and flux to rounding, and the engines' own
conservation gate when wired in via TallyConfig.walk_vmem_max_elems.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import build_box
from pumiumtally_tpu.ops.vmem_walk import vmem_walk_local
from pumiumtally_tpu.parallel.partition import build_partition, walk_local


def _chip_workload(seed, n, ndev=4, divs=4):
    """A single chip's slice of a partitioned walk: its [L,20] table
    plus particles localized to its elements, some destined to cross
    partition faces (pauses), some non-flying (hold), some dead."""
    mesh = build_box(1, 1, 1, divs, divs, divs)
    part = build_partition(mesh, ndev)
    assert part.adj_int is None
    rng = np.random.default_rng(seed)
    chip = 1
    table = part.table[chip * part.L: (chip + 1) * part.L]
    # Localize sources inside chip 1's owned elements via centroids.
    owned = np.flatnonzero(np.asarray(part.orig_of_glid).reshape(
        ndev, part.L)[chip] >= 0)
    lelem = rng.choice(owned, size=n).astype(np.int32)
    coords = np.asarray(mesh.coords)
    tets = np.asarray(mesh.tet2vert)
    orig = np.asarray(part.orig_of_glid).reshape(ndev, part.L)[chip]
    cent = coords[tets[orig[lelem]]].mean(axis=1)
    # Random walk destinations: mix of short hops (stay local), long
    # hops (cross partitions -> pause), and exits (outside the box).
    step = rng.normal(scale=0.25, size=(n, 3))
    dest = cent + step
    fly = (rng.random(n) > 0.15).astype(np.int8)
    dead = rng.random(n) < 0.1
    w = rng.uniform(0.5, 2.0, n)
    x = jnp.asarray(cent)
    dest = jnp.asarray(np.where(fly[:, None] == 1, dest, cent))
    done0 = jnp.asarray(dead)
    exited0 = jnp.zeros(n, bool)
    flux0 = jnp.zeros((part.L,), x.dtype)
    return (table, x, jnp.asarray(lelem), dest, jnp.asarray(fly),
            jnp.asarray(w), done0, exited0, flux0)


@pytest.mark.parametrize("tally", [True, False])
def test_vmem_walk_local_matches_gather_walk(tally):
    args = _chip_workload(seed=5, n=700)
    ref = walk_local(*args, tally=tally, tol=1e-8, max_iters=4096)
    out = vmem_walk_local(*args, tally=tally, tol=1e-8, max_iters=4096,
                          w_tile=128, interpret=True)
    rx, rl, rd, rex, rp, rf, _ = ref
    vx, vl, vd, vex, vp, vf, _ = out
    np.testing.assert_array_equal(np.asarray(vd), np.asarray(rd))
    np.testing.assert_array_equal(np.asarray(vex), np.asarray(rex))
    np.testing.assert_array_equal(np.asarray(vp), np.asarray(rp))
    np.testing.assert_array_equal(np.asarray(vl), np.asarray(rl))
    np.testing.assert_allclose(np.asarray(vx), np.asarray(rx),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(vf), np.asarray(rf),
                               rtol=1e-10, atol=1e-13)
    if not tally:
        np.testing.assert_array_equal(np.asarray(vf), np.asarray(rf))
    # The workload must actually exercise pauses and mixed outcomes,
    # or this parity test proves nothing.
    assert int(np.sum(np.asarray(rp) >= 0)) > 0
    assert int(np.sum(np.asarray(rex))) > 0
    assert int(np.sum(np.asarray(rd))) > 0


def test_vmem_walk_local_tile_padding_invariance():
    """Results must not depend on the tile size / padding split.

    w_tile rounds up to the TILE_1D=1024 layout granule, so the
    distinct splits at n=2500 are 1024 (3 tiles), 2048 (2 tiles) and
    4096 (1 tile, maximal padding); n is deliberately not a multiple
    of any of them."""
    args = _chip_workload(seed=6, n=2500)
    outs = []
    for w_tile in (1024, 2048, 4096):
        outs.append(vmem_walk_local(
            *args, tally=True, tol=1e-8, max_iters=4096,
            w_tile=w_tile, interpret=True,
        ))
    for o in outs[1:]:
        # Per-particle outputs (x, lelem, done, exited, pending) are
        # exactly tile-invariant: each trajectory's math is unchanged
        # by how particles are grouped into kernel tiles.
        for a, b in zip(outs[0][:5], o[:5]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # Flux is reduced per tile then summed, so only the ADDITION
        # ORDER depends on the split — values agree to rounding.
        np.testing.assert_allclose(np.asarray(outs[0][5]),
                                   np.asarray(o[5]),
                                   rtol=1e-12, atol=1e-15)


def test_partitioned_engine_with_vmem_walk_conserves():
    """TallyConfig.walk_vmem_max_elems wires the kernel into the
    partitioned engine and the engine conserves track length exactly.
    (Full flux/position parity against the gather engine — including
    the sub-split — lives in the slow tier; the kernel-level parity
    tests above stay fast.)"""
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 600
    rng = np.random.default_rng(9)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(device_mesh=make_device_mesh(8), capacity_factor=8.0,
                    walk_vmem_max_elems=10_000),
    )
    assert t.engine.use_vmem_walk
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, d1.reshape(-1).copy())
    expect = np.linalg.norm(d1 - src, axis=1).sum()
    np.testing.assert_allclose(
        np.asarray(t.flux, np.float64).sum(), expect, rtol=1e-9
    )


@pytest.mark.slow
def test_vmem_subsplit_blocks_match_default_engine(tmp_path):
    """A chip whose partition exceeds walk_vmem_max_elems is sub-split
    into VMEM-sized blocks (migration at block granularity, in-chip
    cross-block moves pause and re-bucket); results match the
    unblocked gather engine and conserve track length."""
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 6, 6, 6)  # 1296 tets
    n = 600
    rng = np.random.default_rng(11)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    d2 = rng.uniform(0.05, 0.95, (n, 3))
    out = []
    for knob in (None, 40):
        t = PartitionedPumiTally(
            mesh, n,
            TallyConfig(device_mesh=make_device_mesh(8),
                        capacity_factor=8.0,
                        walk_vmem_max_elems=knob),
        )
        if knob is None:
            assert t.engine.blocks_per_chip == 1
        else:
            # ceil(1296 / (8*40)) = 5 blocks per chip, block size <= 40.
            assert t.engine.blocks_per_chip == 5
            assert t.engine.use_vmem_walk
            assert t.engine.part.L <= 40
            assert t.engine.nparts == 40
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        t.MoveToNextLocation(None, d2.reshape(-1).copy())
        out.append((np.asarray(t.flux, np.float64), t.positions,
                    t.elem_ids))
        # Rank-aware output stays one piece per CHIP under the
        # sub-split (part.owner is at BLOCK granularity — a raw
        # pass-through once crashed the pvtu writer here).
        pv = str(tmp_path / f"b{knob}.pvtu")
        t.WriteTallyResults(pv)
        import glob

        assert len(glob.glob(str(tmp_path / f"b{knob}_p*.vtu"))) == 8
    np.testing.assert_allclose(out[0][0], out[1][0],
                               rtol=1e-10, atol=1e-13)
    np.testing.assert_allclose(out[0][1], out[1][1],
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_array_equal(out[0][2], out[1][2])
    expect = (np.linalg.norm(d1 - src, axis=1)
              + np.linalg.norm(d2 - d1, axis=1)).sum()
    np.testing.assert_allclose(out[1][0].sum(), expect, rtol=1e-9)


@pytest.mark.slow
def test_vmem_subsplit_streaming_partitioned():
    """The dp x part hybrid derives the same sub-split for its shared
    partition; chunked + blocked still conserves."""
    from pumiumtally_tpu import StreamingPartitionedTally, TallyConfig
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 4, 4, 4)  # 384 tets
    n = 400
    rng = np.random.default_rng(12)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    t = StreamingPartitionedTally(
        mesh, n, chunk_size=200,
        config=TallyConfig(device_mesh=make_device_mesh(8),
                           capacity_factor=8.0,
                           walk_vmem_max_elems=20),  # 384/(8*20) -> k=3
    )
    for e in t.engines:
        assert e.blocks_per_chip == 3 and e.use_vmem_walk
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, d1.reshape(-1).copy())
    got = float(np.asarray(t.flux, np.float64).sum())
    want = float(np.linalg.norm(d1 - src, axis=1).sum())
    np.testing.assert_allclose(got, want, rtol=1e-9)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [105, 206, 307])
def test_vmem_walk_local_parity_seed_sweep(seed):
    """Same contract as the fast parity test, across more random
    workloads (different pause/exit/hold mixtures and mesh sizes)."""
    args = _chip_workload(seed=seed, n=500, ndev=3 + seed % 3,
                          divs=3 + seed % 3)
    ref = walk_local(*args, tally=True, tol=1e-8, max_iters=4096)
    out = vmem_walk_local(*args, tally=True, tol=1e-8, max_iters=4096,
                          w_tile=128, interpret=True)
    for i in (1, 2, 3, 4):  # lelem, done, exited, pending
        np.testing.assert_array_equal(np.asarray(out[i]),
                                      np.asarray(ref[i]), err_msg=str(i))
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref[0]),
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(np.asarray(out[5]), np.asarray(ref[5]),
                               rtol=1e-10, atol=1e-13)


@pytest.mark.slow
def test_vmem_subsplit_overflow_raises_not_corrupts():
    """Flooding one block past its slot capacity must raise the
    documented overflow error (block-granular capacity check), never
    scatter-collide silently."""
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig
    from pumiumtally_tpu.parallel import make_device_mesh
    from pumiumtally_tpu.parallel.partition import OVERFLOW_MESSAGE

    mesh = build_box(1, 1, 1, 6, 6, 6)  # 1296 tets, 40 blocks at bound 40
    n = 4000  # cap_per_block rounds to 256 < n: one block cannot hold all
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(device_mesh=make_device_mesh(8), capacity_factor=1.01,
                    walk_vmem_max_elems=40),
    )
    assert t.engine.blocks_per_chip > 1
    assert t.engine.cap_per_block < n
    rng = np.random.default_rng(13)
    src = rng.uniform(0.05, 0.95, (n, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    # Every particle heads into one corner element's neighborhood: the
    # owning block must overflow during migration.
    corner = np.tile([0.02, 0.02, 0.02], (n, 1))
    with pytest.raises(RuntimeError, match=OVERFLOW_MESSAGE[:30]):
        t.MoveToNextLocation(None, corner.reshape(-1).copy())


def test_vmem_gate_oversized_subsplits_and_adj_sidecar_falls_back():
    """An oversized partition SUB-SPLITS to fit the bound (the knob is
    satisfied by blocking, not ignored); only the int-adjacency
    sidecar keeps the gather walk — silently at blocks=1, loudly when
    a sub-split would be required."""
    from pumiumtally_tpu.parallel import make_device_mesh
    from pumiumtally_tpu.parallel.partition import (
        PartitionedEngine,
        build_partition,
        derive_blocks_per_chip,
    )

    mesh = build_box(1, 1, 1, 4, 4, 4)  # 384 tets over 8 chips: L=48
    dm = make_device_mesh(8)

    # Construction-only checks (the sub-split engine is DRIVEN by the
    # slow-tier parity test; here just the gating/derivation).
    assert derive_blocks_per_chip(384, 8, 10) == 5
    e0 = PartitionedEngine(
        mesh, dm, 100, capacity_factor=8.0, tol=1e-8, max_iters=4096,
        vmem_walk_max_elems=10,
    )
    assert e0.use_vmem_walk and e0.blocks_per_chip == 5
    assert e0.part.L <= 10 and e0.nparts == 40
    # blocks=1 + int-adjacency sidecar: silent gather fallback.
    e = PartitionedEngine(
        mesh, dm, 100, capacity_factor=8.0, tol=1e-8, max_iters=4096,
        part=build_partition(mesh, 8, force_split_adj=True),
        vmem_walk_max_elems=10_000,
    )
    assert e.use_vmem_walk is False and e.blocks_per_chip == 1

    # A sub-split that would need the sidecar cannot run at all: loud.
    with pytest.raises(ValueError, match="sub-split"):
        PartitionedEngine(
            mesh, dm, 100, capacity_factor=8.0, tol=1e-8, max_iters=4096,
            part=build_partition(mesh, 16, force_split_adj=True),
            vmem_walk_max_elems=10_000,
        )


@pytest.mark.slow
def test_vmem_kernel_mosaic_compiles_chipless():
    """The kernel must STAY Mosaic-compilable — round 4 found three
    lowering laws the interpret path never checks (block-shape
    multiples, scf carry legalization, XLA T(1024) rank-1 layout).
    Chipless AOT against the local libtpu needs no TPU device and no
    tunnel (tools/aot_vmem_compile.py); skip only when libtpu itself
    is unavailable."""
    import os
    import subprocess
    import sys

    ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "aot_vmem_compile.py"),
         "2048", "1024", "1024", "4", "1"],
        capture_output=True, text=True, timeout=600,
        env={k: v for k, v in os.environ.items()
             if k not in ("JAX_PLATFORMS", "XLA_FLAGS")},
    )
    out = r.stdout + r.stderr
    if r.returncode != 0 and (
        "topology not implemented" in out  # jax: no TPU support built
        or "libtpu.so" in out  # plugin present but .so unloadable
    ):
        pytest.skip(f"libtpu unavailable for AOT: {out[-300:]}")
    assert r.returncode == 0 and "COMPILE OK" in out, out[-2000:]


def test_vmem_bound_clamped_on_compiled_backends(monkeypatch, caplog):
    """On a compiled-TPU backend a bound past the measured scoped-VMEM
    ceiling is clamped (finer sub-split, same intent) instead of dying
    in Mosaic's allocator at first walk. CPU interpret mode keeps the
    exact bound (asserted by the surrounding suite's block counts)."""
    import pumiumtally_tpu.ops.vmem_walk as vw
    from pumiumtally_tpu import PartitionedPumiTally, TallyConfig
    from pumiumtally_tpu.parallel import make_device_mesh

    monkeypatch.setattr(vw, "backend_needs_interpret", lambda: False)
    # Pin the ceiling via the env override (the r5 re-measured default,
    # 8192, no longer splits this 3072-tet mesh).
    monkeypatch.setenv("PUMIUMTALLY_VMEM_CEILING_ELEMS", "2048")
    mesh = build_box(1, 1, 1, 8, 8, 8)  # 3072 tets
    t = PartitionedPumiTally(
        mesh, 64,
        TallyConfig(device_mesh=make_device_mesh(1), capacity_factor=4.0,
                    walk_vmem_max_elems=100_000),
    )
    # Unclamped, 3072 <= 100k would give one 3072-elem block; the clamp
    # forces ceil(3072/2048) = 2 blocks of <= 2048.
    assert t.engine.blocks_per_chip == 2
    assert t.engine.part.L <= 2048
    assert t.engine.use_vmem_walk


def test_vmem_ceiling_default_and_override(monkeypatch):
    """The feasibility ceiling is the r5 re-measured compiler-constant
    default (the scoped-VMEM stack limit binds identically on v5e and
    v5p per the cross-topology AOT sweep — physical-VMEM scaling was
    the wrong model) and PUMIUMTALLY_VMEM_CEILING_ELEMS overrides
    outright for operators who raise the compiler's scoped limit."""
    import pumiumtally_tpu.ops.vmem_walk as vw

    monkeypatch.setattr(vw, "backend_needs_interpret", lambda: False)
    assert vw.effective_vmem_bound(100_000) == 8192
    monkeypatch.setenv("PUMIUMTALLY_VMEM_CEILING_ELEMS", "512")
    assert vw.effective_vmem_bound(100_000) == 512
    assert vw.effective_vmem_bound(300) == 300  # under-ceiling untouched


@pytest.mark.slow
def test_multichip_tpu_programs_compile_chipless():
    """The FULL partitioned phase programs — shard_map over a 4-chip
    v5e topology, psum collectives, migration sort/scatter, and the
    Pallas VMEM kernel inside shard_map (whole-block and sub-split) —
    must compile through the real Mosaic+XLA TPU pipeline. The
    driver's dryrun only ever compiles them for virtual CPU devices;
    this is the multi-chip TPU certification (tools/
    aot_multichip_compile.py)."""
    import os
    import subprocess
    import sys

    ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "aot_multichip_compile.py"), "2048"],
        capture_output=True, text=True, timeout=900,
        env={k: v for k, v in os.environ.items()
             if k not in ("JAX_PLATFORMS", "XLA_FLAGS")},
    )
    out = r.stdout + r.stderr
    if r.returncode != 0 and (
        "topology not implemented" in out or "libtpu.so" in out
    ):
        pytest.skip(f"libtpu unavailable for AOT: {out[-300:]}")
    # 7 rows since r5: the four v5e:2x2x1 phase programs, the 16-chip
    # v5e:4x4 gather sub-split, and the two expected-rejection rows of
    # the scoped-VMEM envelope cross-check (v5e + v5p single-chip) —
    # tools/aot_multichip_compile.py.
    assert r.returncode == 0 and out.count("OK ") == 7, out[-2000:]
