"""Geometric edge cases for the walk kernel.

The verify playbook's probes: source points exactly on vertices/edges/
faces, rays along face planes, zero-length flights, destinations
exactly on the domain boundary. None of these may hang, lose a
particle (elem = -1), or tally a wrong total length.
"""

import numpy as np

from pumiumtally_tpu import PumiTally, TallyConfig, build_box

TOL = 1e-9


def _drive(points, dests, div=3):
    n = points.shape[0]
    mesh = build_box(1, 1, 1, div, div, div)
    t = PumiTally(mesh, n, TallyConfig())
    t.CopyInitialPosition(points.reshape(-1).copy())
    assert (t.elem_ids >= 0).all()
    assert (t.elem_ids < mesh.nelems).all()
    t.MoveToNextLocation(None, dests.reshape(-1).copy())
    return t


def test_sources_on_vertices_edges_faces():
    pts = np.array([
        [0.0, 0.0, 0.0],          # domain corner vertex
        [1 / 3, 1 / 3, 1 / 3],    # interior grid vertex
        [0.5, 1 / 3, 1 / 3],      # interior grid edge
        [0.5, 0.5, 1 / 3],        # interior cell-face point
        [0.5, 0.5, 0.0],          # boundary face point
        [1.0, 1.0, 1.0],          # far corner
    ])
    dests = np.full_like(pts, 0.51)
    t = _drive(pts, dests)
    np.testing.assert_allclose(t.positions, dests, atol=TOL)
    total = float(np.asarray(t.flux).sum())
    expect = float(np.linalg.norm(dests - pts, axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-9)


def test_ray_along_grid_planes():
    """Flight exactly inside a mesh face plane (degenerate but legal)."""
    n = 3
    pts = np.array([
        [0.1, 1 / 3, 0.2],   # travels inside the y=1/3 plane
        [1 / 3, 0.1, 0.9],   # inside x=1/3 plane
        [0.2, 0.2, 0.5],
    ])
    dests = pts.copy()
    dests[0, 0] = 0.9
    dests[1, 1] = 0.9
    dests[2] = [0.8, 0.8, 0.5]
    t = _drive(pts, dests)
    np.testing.assert_allclose(t.positions, dests, atol=1e-7)
    total = float(np.asarray(t.flux).sum())
    expect = float(np.linalg.norm(dests - pts, axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-7)


def test_zero_length_flights_tally_nothing():
    pts = np.random.default_rng(0).uniform(0.05, 0.95, (50, 3))
    t = _drive(pts, pts.copy())
    np.testing.assert_allclose(np.asarray(t.flux), 0.0, atol=1e-15)
    np.testing.assert_allclose(t.positions, pts, atol=TOL)


def test_destination_exactly_on_boundary():
    pts = np.tile([0.4, 0.5, 0.5], (4, 1))
    dests = np.array([
        [1.0, 0.5, 0.5],   # +x face
        [0.0, 0.5, 0.5],   # -x face
        [0.4, 1.0, 0.5],   # +y face
        [0.4, 0.5, 0.0],   # -z face
    ])
    t = _drive(pts, dests)
    np.testing.assert_allclose(t.positions, dests, atol=1e-7)
    total = float(np.asarray(t.flux).sum())
    expect = float(np.linalg.norm(dests - pts, axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-9)


def test_corner_to_corner_diagonal():
    """The worst ray: full body diagonal grazing many edges/vertices."""
    pts = np.array([[0.0, 0.0, 0.0], [1.0, 0.0, 0.0]])
    dests = np.array([[1.0, 1.0, 1.0], [0.0, 1.0, 1.0]])
    t = _drive(pts, dests, div=5)
    np.testing.assert_allclose(t.positions, dests, atol=1e-6)
    total = float(np.asarray(t.flux).sum())
    np.testing.assert_allclose(total, 2 * np.sqrt(3.0), rtol=1e-7)


def test_overlay_tally_mesh_smaller_than_domain():
    """Overlay-tally usage (the BASELINE DAGMC-config shape, minus the
    CAD host): the tally mesh covers only part of the transport
    domain, and the host hands surface-to-surface track legs the way
    event-based transport does. Legs ENTERING on a face and leaving
    beyond the far side must tally exactly the in-mesh chord (the
    vacuum clamp commits the exit point); successive legs chain
    through the hull without losing particles."""
    rng = np.random.default_rng(17)
    n = 200
    # Entry points on the -x face (surface-crossing leg origins), flight
    # directions with a positive x component, dests beyond the +x face.
    entry = np.column_stack([
        np.zeros(n), rng.uniform(0.05, 0.95, n), rng.uniform(0.05, 0.95, n)
    ])
    dirs = np.column_stack([
        rng.uniform(0.5, 1.0, n), rng.uniform(-0.3, 0.3, n),
        rng.uniform(-0.3, 0.3, n),
    ])
    dirs /= np.linalg.norm(dirs, axis=1)[:, None]
    dests = entry + 3.0 * dirs  # far outside the unit tally box

    t = _drive(entry, dests, div=4)
    # Each particle's contribution = its chord through the unit box.
    lo = np.zeros(3)
    hi = np.ones(3)
    with np.errstate(divide="ignore"):
        t_lo = (lo - entry) / dirs
        t_hi = (hi - entry) / dirs
    t_exit = np.maximum(t_lo, t_hi).min(axis=1)
    chord = np.minimum(t_exit, 3.0)
    total = float(np.asarray(t.flux).sum())
    np.testing.assert_allclose(total, chord.sum(), rtol=1e-9)
    # Exit commits ON the hull (the clamp), never outside.
    assert (t.positions <= 1.0 + 1e-9).all()
    assert (t.positions >= -1e-9).all()
    # The NEXT leg re-enters from a resampled surface point (a fresh
    # batch in the host's loop): localization + transport keep working
    # from the clamped state without CopyInitialPosition.
    entry2 = np.column_stack([
        rng.uniform(0.05, 0.95, n), np.zeros(n), rng.uniform(0.05, 0.95, n)
    ])
    dest2 = entry2 + np.array([0.0, 0.4, 0.0])
    t.MoveToNextLocation(entry2.reshape(-1).copy(), dest2.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    total2 = float(np.asarray(t.flux).sum())
    np.testing.assert_allclose(total2, chord.sum() + n * 0.4, rtol=1e-9)


def test_non_finite_inputs_rejected_before_staging():
    """One NaN/Inf destination or weight silently poisons the WHOLE
    accumulated flux (nan scatter-add — the reference's atomic_add has
    the same hole); TallyConfig.validate_inputs (default on) refuses
    such a batch BEFORE upload, keeping the committed state clean, and
    the opt-out restores raw staging for trusted max-rate drivers."""
    import pytest

    from pumiumtally_tpu import PumiTally, StreamingTally, TallyConfig

    mesh = build_box(1, 1, 1, 3, 3, 3)
    n = 12
    src = np.full((n, 3), 0.4) + np.arange(n)[:, None] * 0.01

    t = PumiTally(mesh, n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    flux_before = np.asarray(t.flux).copy()
    dest = src + 0.05
    dest[3, 0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        t.MoveToNextLocation(src.reshape(-1).copy(),
                             dest.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
    # The refusal happened before staging: committed flux unchanged.
    np.testing.assert_array_equal(np.asarray(t.flux), flux_before)

    good = src + 0.05
    w = np.ones(n)
    w[5] = np.inf
    with pytest.raises(ValueError, match="weights"):
        t.MoveToNextLocation(src.reshape(-1).copy(),
                             good.reshape(-1).copy(),
                             np.ones(n, np.int8), w)

    # NaN source positions refused at initialization too.
    bad_src = src.copy()
    bad_src[0, 1] = np.inf
    t2 = PumiTally(mesh, n)
    with pytest.raises(ValueError, match="non-finite"):
        t2.CopyInitialPosition(bad_src.reshape(-1).copy())

    # Streaming facade shares the guard.
    ts = StreamingTally(mesh, n, chunk_size=5)
    ts.CopyInitialPosition(src.reshape(-1).copy())
    with pytest.raises(ValueError, match="destinations"):
        ts.MoveToNextLocation(None, dest.reshape(-1).copy())

    # Opt-out: the unchecked path stages (flux may go nan — caller's
    # choice), and must not hang.
    t3 = PumiTally(mesh, n, TallyConfig(validate_inputs=False,
                                        max_iters=200,
                                        check_found_all=False))
    t3.CopyInitialPosition(src.reshape(-1).copy())
    t3.MoveToNextLocation(src.reshape(-1).copy(), dest.reshape(-1).copy(),
                          np.ones(n, np.int8), np.ones(n))
    assert not np.isfinite(np.asarray(t3.flux)).all()


def test_f32_overflow_inputs_rejected_after_cast():
    """A value finite in the caller's f64 buffer but inf after the
    working-dtype (f32) cast must also be refused — the check runs
    post-cast on both facades."""
    import jax.numpy as jnp
    import pytest

    from pumiumtally_tpu import PumiTally, StreamingTally, build_box

    mesh32 = build_box(1, 1, 1, 3, 3, 3, dtype=jnp.float32)
    n = 8
    src = np.full((n, 3), 0.4) + np.arange(n)[:, None] * 0.01
    t = PumiTally(mesh32, n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    dest = src + 0.05
    dest[2, 0] = 1e300  # finite f64, inf f32
    with pytest.raises(ValueError, match="destinations"):
        t.MoveToNextLocation(src.reshape(-1).copy(),
                             dest.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))

    ts = StreamingTally(mesh32, n, chunk_size=4)
    ts.CopyInitialPosition(src.reshape(-1).copy())
    flux_before = np.asarray(ts.flux, np.float64).copy()
    with pytest.raises(ValueError, match="destinations"):
        ts.MoveToNextLocation(None, dest.reshape(-1).copy())
    # Atomic refusal (ADVICE r4): the bad value sits in chunk 0 of 2,
    # but even a bad value in a LATER chunk must not leave earlier
    # chunks' flux committed — the pre-dispatch validation pass checks
    # every chunk before any dispatch.
    np.testing.assert_array_equal(
        np.asarray(ts.flux, np.float64), flux_before
    )
    dest2 = src + 0.05
    dest2[n - 1, 0] = 1e300  # bad value in the LAST chunk
    with pytest.raises(ValueError, match="destinations"):
        ts.MoveToNextLocation(None, dest2.reshape(-1).copy())
    np.testing.assert_array_equal(
        np.asarray(ts.flux, np.float64), flux_before
    )
    # The engine is not poisoned: a clean follow-up move still works.
    ts.MoveToNextLocation(None, (src + 0.05).reshape(-1).copy())
    assert float(np.asarray(ts.flux, np.float64).sum()) > 0.0
