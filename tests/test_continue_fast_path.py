"""origins=None / flying=None / weights=None fast paths ≡ explicit args.

The continue-mode move (a TPU-native extension; see api/tally.py) must
produce exactly the state the full two-phase move produces when the
caller's origins equal the committed positions.
"""

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.parallel import make_device_mesh

N = 2000


def _mk(device_mesh=None):
    mesh = build_box(1, 1, 1, 4, 4, 4)
    cfg = TallyConfig(device_mesh=device_mesh)
    t = PumiTally(mesh, N, cfg)
    rng = np.random.default_rng(7)
    src = rng.uniform(0.05, 0.95, (N, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    return t, rng


@pytest.mark.parametrize("sharded", [False, True])
def test_continue_matches_explicit_origins(sharded):
    dm = make_device_mesh(8) if sharded else None
    ta, rng_a = _mk(dm)
    tb, rng_b = _mk(dm)
    dest = rng_a.uniform(0.05, 0.95, (N, 3))
    rng_b.uniform(0.05, 0.95, (N, 3))  # keep rngs aligned
    fly = np.ones(N, np.int8)
    w = rng_a.uniform(0.5, 2.0, N)
    rng_b.uniform(0.5, 2.0, N)

    # explicit: origins == committed positions
    pos = ta.positions.astype(np.float64)
    ta.MoveToNextLocation(pos.reshape(-1).copy(), dest.reshape(-1).copy(),
                          fly.copy(), w)
    # fast path
    tb.MoveToNextLocation(None, dest.reshape(-1).copy(), fly.copy(), w)

    np.testing.assert_allclose(ta.positions, tb.positions, atol=1e-13)
    np.testing.assert_array_equal(ta.elem_ids, tb.elem_ids)
    np.testing.assert_allclose(
        np.asarray(ta.flux), np.asarray(tb.flux), rtol=1e-12, atol=1e-13
    )


def test_none_flying_and_weights_mean_all_fly_unit_weight():
    ta, rng_a = _mk()
    tb, rng_b = _mk()
    dest = rng_a.uniform(0.05, 0.95, (N, 3))
    rng_b.uniform(0.05, 0.95, (N, 3))
    pos = ta.positions.astype(np.float64)
    ta.MoveToNextLocation(pos.reshape(-1).copy(), dest.reshape(-1).copy(),
                          np.ones(N, np.int8), np.ones(N))
    tb.MoveToNextLocation(None, dest.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(ta.flux), np.asarray(tb.flux), rtol=1e-12, atol=1e-13
    )
    np.testing.assert_array_equal(ta.elem_ids, tb.elem_ids)


def test_continue_holds_nonflying_particles():
    t, rng = _mk()
    pos0 = t.positions.copy()
    dest = rng.uniform(0.05, 0.95, (N, 3))
    fly = np.zeros(N, np.int8)
    t.MoveToNextLocation(None, dest.reshape(-1).copy(), fly, np.ones(N))
    np.testing.assert_allclose(t.positions, pos0, atol=1e-14)
    np.testing.assert_allclose(np.asarray(t.flux), 0.0, atol=1e-14)


def test_two_phase_with_echoed_origins_matches_continue_bitwise():
    """When the host echoes committed positions back as origins (no
    resampling), the full two-phase protocol must produce bit-identical
    results to the continue-mode fast path: the device-side trivial
    check skips phase A entirely."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 1500
    rng = np.random.default_rng(6)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dest = rng.uniform(0.0, 1.0, (n, 3))

    results = []
    for mode in ("two_phase", "continue"):
        t = PumiTally(mesh, n, TallyConfig())
        t.CopyInitialPosition(src.reshape(-1).copy())
        if mode == "two_phase":
            pos = t.positions.astype(np.float64)
            t.MoveToNextLocation(pos.reshape(-1).copy(),
                                 dest.reshape(-1).copy(),
                                 np.ones(n, np.int8), np.ones(n))
        else:
            t.MoveToNextLocation(None, dest.reshape(-1).copy(),
                                 np.ones(n, np.int8), np.ones(n))
        results.append((np.asarray(t.flux), t.positions, t.elem_ids))
    np.testing.assert_array_equal(results[0][0], results[1][0])
    np.testing.assert_array_equal(results[0][1], results[1][1])
    np.testing.assert_array_equal(results[0][2], results[1][2])
