"""origins=None / flying=None / weights=None fast paths ≡ explicit args.

The continue-mode move (a TPU-native extension; see api/tally.py) must
produce exactly the state the full two-phase move produces when the
caller's origins equal the committed positions.
"""

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box
from pumiumtally_tpu.parallel import make_device_mesh

N = 2000


def _mk(device_mesh=None):
    mesh = build_box(1, 1, 1, 4, 4, 4)
    cfg = TallyConfig(device_mesh=device_mesh)
    t = PumiTally(mesh, N, cfg)
    rng = np.random.default_rng(7)
    src = rng.uniform(0.05, 0.95, (N, 3))
    t.CopyInitialPosition(src.reshape(-1).copy())
    return t, rng


@pytest.mark.parametrize("sharded", [False, True])
def test_continue_matches_explicit_origins(sharded):
    dm = make_device_mesh(8) if sharded else None
    ta, rng_a = _mk(dm)
    tb, rng_b = _mk(dm)
    dest = rng_a.uniform(0.05, 0.95, (N, 3))
    rng_b.uniform(0.05, 0.95, (N, 3))  # keep rngs aligned
    fly = np.ones(N, np.int8)
    w = rng_a.uniform(0.5, 2.0, N)
    rng_b.uniform(0.5, 2.0, N)

    # explicit: origins == committed positions
    pos = ta.positions.astype(np.float64)
    ta.MoveToNextLocation(pos.reshape(-1).copy(), dest.reshape(-1).copy(),
                          fly.copy(), w)
    # fast path
    tb.MoveToNextLocation(None, dest.reshape(-1).copy(), fly.copy(), w)

    np.testing.assert_allclose(ta.positions, tb.positions, atol=1e-13)
    np.testing.assert_array_equal(ta.elem_ids, tb.elem_ids)
    np.testing.assert_allclose(
        np.asarray(ta.flux), np.asarray(tb.flux), rtol=1e-12, atol=1e-13
    )


def test_none_flying_and_weights_mean_all_fly_unit_weight():
    ta, rng_a = _mk()
    tb, rng_b = _mk()
    dest = rng_a.uniform(0.05, 0.95, (N, 3))
    rng_b.uniform(0.05, 0.95, (N, 3))
    pos = ta.positions.astype(np.float64)
    ta.MoveToNextLocation(pos.reshape(-1).copy(), dest.reshape(-1).copy(),
                          np.ones(N, np.int8), np.ones(N))
    tb.MoveToNextLocation(None, dest.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(ta.flux), np.asarray(tb.flux), rtol=1e-12, atol=1e-13
    )
    np.testing.assert_array_equal(ta.elem_ids, tb.elem_ids)


def test_continue_holds_nonflying_particles():
    t, rng = _mk()
    pos0 = t.positions.copy()
    dest = rng.uniform(0.05, 0.95, (N, 3))
    fly = np.zeros(N, np.int8)
    t.MoveToNextLocation(None, dest.reshape(-1).copy(), fly, np.ones(N))
    np.testing.assert_allclose(t.positions, pos0, atol=1e-14)
    np.testing.assert_allclose(np.asarray(t.flux), 0.0, atol=1e-14)


def test_two_phase_with_echoed_origins_matches_continue_bitwise():
    """When the host echoes committed positions back as origins (no
    resampling), the full two-phase protocol must produce bit-identical
    results to the continue-mode fast path: the device-side trivial
    check skips phase A entirely."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 1500
    rng = np.random.default_rng(6)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dest = rng.uniform(0.0, 1.0, (n, 3))

    results = []
    for mode in ("two_phase", "continue"):
        t = PumiTally(mesh, n, TallyConfig())
        t.CopyInitialPosition(src.reshape(-1).copy())
        if mode == "two_phase":
            pos = t.positions.astype(np.float64)
            t.MoveToNextLocation(pos.reshape(-1).copy(),
                                 dest.reshape(-1).copy(),
                                 np.ones(n, np.int8), np.ones(n))
        else:
            t.MoveToNextLocation(None, dest.reshape(-1).copy(),
                                 np.ones(n, np.int8), np.ones(n))
        results.append((np.asarray(t.flux), t.positions, t.elem_ids))
    np.testing.assert_array_equal(results[0][0], results[1][0])
    np.testing.assert_array_equal(results[0][1], results[1][1])
    np.testing.assert_array_equal(results[0][2], results[1][2])


@pytest.mark.parametrize("facade", [
    "mono", "sharded",
    pytest.param("partitioned", marks=pytest.mark.slow),
])
def test_auto_continue_fires_on_echo_and_matches_disabled(facade):
    """Host-side auto-continue (TallyConfig.auto_continue): echoing the
    previous destinations as origins skips the origin upload, with
    results bit-identical to the optimization turned off — on every
    facade (the partitioned engine treats the substituted device array
    exactly like fresh origins)."""
    from pumiumtally_tpu import PartitionedPumiTally

    dm = make_device_mesh(8) if facade != "mono" else None
    mesh = build_box(1, 1, 1, 4, 4, 4)
    rng = np.random.default_rng(11)
    src = rng.uniform(0.05, 0.95, (N, 3))
    d1 = rng.uniform(0.05, 0.95, (N, 3))
    d2 = rng.uniform(0.05, 0.95, (N, 3))

    out = []
    for auto in (True, False):
        cfg = TallyConfig(device_mesh=dm, auto_continue=auto,
                          capacity_factor=4.0)
        cls = PartitionedPumiTally if facade == "partitioned" else PumiTally
        t = cls(mesh, N, cfg)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                             np.ones(N, np.int8), np.ones(N))
        # echo: origins == previous destinations (the physics-host case)
        t.MoveToNextLocation(d1.reshape(-1).copy(), d2.reshape(-1).copy(),
                             np.ones(N, np.int8), np.ones(N))
        out.append((np.asarray(t.flux), t.positions, t.elem_ids,
                    t.auto_continue_hits))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_array_equal(out[0][2], out[1][2])
    assert out[0][3] == 1  # move 2 skipped the upload
    assert out[1][3] == 0


def test_auto_continue_correct_after_boundary_exit():
    """A particle clamped at the hull has committed != dests, so phase A
    is NOT trivial on the next echoing move: the substituted device
    origins must still drive the relocation walk (clamp point → echoed
    outside origin → re-clamp), with results bit-identical to
    auto_continue=False."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 500
    rng = np.random.default_rng(12)
    src = rng.uniform(0.3, 0.7, (n, 3))
    d1 = src + np.array([2.0, 0.0, 0.0])  # everyone exits +x
    d2 = rng.uniform(0.05, 0.95, (n, 3))

    out = []
    for auto in (True, False):
        t = PumiTally(mesh, n, TallyConfig(auto_continue=auto))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        t.MoveToNextLocation(d1.reshape(-1).copy(), d2.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        out.append((np.asarray(t.flux), t.positions, t.auto_continue_hits))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])
    assert out[0][2] == 1  # upload skipped; phase A still ran on device
    assert out[1][2] == 0


def test_auto_continue_declines_on_resample_and_correct_for_nonflying():
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 400
    rng = np.random.default_rng(13)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))

    # resampled origins differ from the previous dests -> host veto
    t = PumiTally(mesh, n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    resampled = rng.uniform(0.05, 0.95, (n, 3))
    t.MoveToNextLocation(resampled.reshape(-1).copy(),
                         np.clip(resampled + 0.1, 0, 1).reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    assert t.auto_continue_hits == 0

    # a particle held (non-flying) on move 1 sits at src, not d1; the
    # echoing move 2 must relocate it through phase A even though the
    # origin upload was skipped.
    out = []
    for auto in (True, False):
        t2 = PumiTally(mesh, n, TallyConfig(auto_continue=auto))
        t2.CopyInitialPosition(src.reshape(-1).copy())
        fly = np.ones(n, np.int8)
        fly[0] = 0
        t2.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                              fly.copy(), np.ones(n))
        t2.MoveToNextLocation(d1.reshape(-1).copy(),
                              np.clip(d1 + 0.1, 0, 1).reshape(-1).copy(),
                              np.ones(n, np.int8), np.ones(n))
        out.append((np.asarray(t2.flux), t2.positions, t2.auto_continue_hits))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])
    assert out[0][2] == 1 and out[1][2] == 0


def test_auto_continue_not_fooled_by_recycled_caller_buffer():
    """In f64 mode staging returns VIEWS of the caller's buffer; a host
    app that reuses its destination buffer to hold the next move's
    resampled origins must not trick the echo check into comparing the
    caller's memory against itself."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 300
    rng = np.random.default_rng(14)
    src = rng.uniform(0.05, 0.95, (n, 3))
    buf = np.empty(3 * n)  # the recycled host buffer

    t = PumiTally(mesh, n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    buf[:] = rng.uniform(0.05, 0.95, 3 * n)  # move-1 destinations
    d1 = buf.reshape(n, 3).copy()
    t.MoveToNextLocation(src.reshape(-1).copy(), buf, np.ones(n, np.int8),
                         np.ones(n))
    # Recycle: same buffer now holds RESAMPLED origins.
    resampled = rng.uniform(0.05, 0.95, (n, 3))
    buf[:] = resampled.reshape(-1)
    d2 = np.clip(resampled + 0.1, 0.02, 0.98)
    t.MoveToNextLocation(buf, d2.reshape(-1).copy(), np.ones(n, np.int8),
                         np.ones(n))
    assert t.auto_continue_hits == 0  # resample must NOT be skipped
    # flux must include the phase-A-relocated leg, i.e. move 2 tallies
    # |d2 - resampled|, not |d2 - d1|.
    want = float(np.linalg.norm(d1 - src, axis=1).sum()
                 + np.linalg.norm(d2 - resampled, axis=1).sum())
    got = float(np.sum(np.asarray(t.flux)))
    assert abs(got - want) / want < 1e-12


def test_unfenced_timing_pipeline_matches_fenced():
    """fenced_timing=False lets calls return after dispatch; results
    after the final sync must be identical to the fenced engine."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 1000
    rng = np.random.default_rng(15)
    src = rng.uniform(0.05, 0.95, (n, 3))
    traj = [src]
    for _ in range(4):
        traj.append(np.clip(traj[-1] + rng.normal(scale=0.2, size=(n, 3)),
                            0.02, 0.98))
    out = []
    for fenced in (True, False):
        t = PumiTally(mesh, n, TallyConfig(fenced_timing=fenced,
                                           check_found_all=False))
        t.CopyInitialPosition(traj[0].reshape(-1).copy())
        for m in range(1, 5):
            t.MoveToNextLocation(traj[m - 1].reshape(-1).copy(),
                                 traj[m].reshape(-1).copy(),
                                 np.ones(n, np.int8), np.ones(n))
        out.append((np.asarray(t.flux), t.positions, t.elem_ids))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_array_equal(out[0][2], out[1][2])


def test_staging_caches_for_flying_and_weights_match_disabled():
    """All-ones flying reuses the cached device ones; unchanged
    non-unit weights reuse the previous device array. Results must be
    bit-identical to auto_continue=False (which stages everything)."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 800
    rng = np.random.default_rng(16)
    src = rng.uniform(0.05, 0.95, (n, 3))
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    d2 = rng.uniform(0.05, 0.95, (n, 3))
    w = rng.uniform(0.5, 2.0, n)

    out = []
    for auto in (True, False):
        t = PumiTally(mesh, n, TallyConfig(auto_continue=auto))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                             np.ones(n, np.int8), w.copy())
        t.MoveToNextLocation(d1.reshape(-1).copy(), d2.reshape(-1).copy(),
                             np.ones(n, np.int8), w.copy())
        out.append((np.asarray(t.flux), t.positions, t.elem_ids))
    np.testing.assert_array_equal(out[0][0], out[1][0])
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_array_equal(out[0][2], out[1][2])

    # changed weights on move 3 must be staged fresh (miss path)
    t = PumiTally(mesh, n)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), d1.reshape(-1).copy(),
                         np.ones(n, np.int8), w.copy())
    w2 = w * 2.0
    t.MoveToNextLocation(d1.reshape(-1).copy(), d2.reshape(-1).copy(),
                         np.ones(n, np.int8), w2.copy())
    got = float(np.sum(np.asarray(t.flux)))
    want = float((np.linalg.norm(d1 - src, axis=1) * w).sum()
                 + (np.linalg.norm(d2 - d1, axis=1) * w2).sum())
    assert abs(got - want) / want < 1e-12


def test_sharded_locate_localization_matches_walk():
    """Sharded (dp) facade with localization="locate": the shard_map'd
    point location + masked walk match walk-mode localization exactly,
    out-of-hull clamps included."""
    dm = make_device_mesh(8)
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 2000
    rng = np.random.default_rng(26)
    src = rng.uniform(0.05, 0.95, (n, 3))
    src[::9] += 2.0  # clamp path
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    out = []
    for how in ("walk", "locate"):
        t = PumiTally(mesh, n, TallyConfig(device_mesh=dm, localization=how))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, d1.reshape(-1).copy())
        out.append((t.positions, t.elem_ids, np.asarray(t.flux)))
    np.testing.assert_allclose(out[0][0], out[1][0], atol=1e-12)
    np.testing.assert_array_equal(out[0][1], out[1][1])
    np.testing.assert_allclose(out[0][2], out[1][2], rtol=1e-12, atol=1e-14)


def test_echo_disarm_state_machine():
    """The never-echoing-driver disarm (api/tally.py _ECHO_MISS_LIMIT):
    after 8 consecutive misses the facade drops its snapshots and stops
    retaining; a hit resets the streak; CopyInitialPosition re-arms.
    Results stay correct throughout (a miss only costs an upload)."""
    from pumiumtally_tpu.api.tally import _ECHO_MISS_LIMIT

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 300
    rng = np.random.default_rng(21)
    t = PumiTally(mesh, n, TallyConfig())
    pts = rng.uniform(0.05, 0.95, (n, 3))
    t.CopyInitialPosition(pts.reshape(-1).copy())

    def move(origins, dests):
        t.MoveToNextLocation(origins.reshape(-1).copy(),
                             dests.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))

    # Resampling driver: every move passes freshly sampled origins, so
    # they never equal the previous move's destinations.
    for i in range(_ECHO_MISS_LIMIT + 2):
        origins = rng.uniform(0.05, 0.95, (n, 3))
        dests = rng.uniform(0.05, 0.95, (n, 3))
        move(origins, dests)
        if i == 0:
            # First move can't compare (no snapshot yet) but still
            # ticks the re-arm clock — and must retain.
            assert t._echo_misses == 1 and t._last_dests_host is not None
    assert t.auto_continue_hits == 0
    # Disarmed: snapshots dropped, retention off.
    assert t._echo_misses >= _ECHO_MISS_LIMIT
    assert t._last_dests_host is None and t._last_dests_dev is None
    move(rng.uniform(0.05, 0.95, (n, 3)), rng.uniform(0.05, 0.95, (n, 3)))
    assert t._last_dests_host is None  # stays off between retry windows

    # Periodic re-arm (_ECHO_REARM_PERIOD): while disarmed the facade
    # retains ONE retry snapshot per period, and an intermittently
    # echoing driver regains the upload skip on the following move.
    from pumiumtally_tpu.api.tally import _ECHO_REARM_PERIOD

    while t._echo_misses % _ECHO_REARM_PERIOD != _ECHO_REARM_PERIOD - 2:
        move(rng.uniform(0.05, 0.95, (n, 3)),
             rng.uniform(0.05, 0.95, (n, 3)))
        assert t._last_dests_host is None  # still within the window
    retry_dests = rng.uniform(0.05, 0.95, (n, 3))
    move(rng.uniform(0.05, 0.95, (n, 3)), retry_dests)  # hits the boundary
    assert t._last_dests_host is not None  # the periodic retry snapshot
    hits_before = t.auto_continue_hits
    move(retry_dests, rng.uniform(0.05, 0.95, (n, 3)))  # echo on retry
    assert t.auto_continue_hits == hits_before + 1
    assert t._echo_misses == 0  # fully re-armed by the hit

    # CopyInitialPosition re-arms the detector.
    t.CopyInitialPosition(pts.reshape(-1).copy())
    assert t._echo_misses == 0
    d1 = rng.uniform(0.05, 0.95, (n, 3))
    move(pts, d1)
    assert t._last_dests_host is not None  # retaining again
    d2 = rng.uniform(0.05, 0.95, (n, 3))
    hits_before = t.auto_continue_hits
    move(d1, d2)  # echo!
    assert t.auto_continue_hits == hits_before + 1
    assert t._echo_misses == 0  # hit reset the streak

    # A NONZERO miss streak is reset by a hit, so interleaved
    # resample/echo drivers never disarm.
    for _ in range(_ECHO_MISS_LIMIT - 2):
        move(rng.uniform(0.05, 0.95, (n, 3)),
             rng.uniform(0.05, 0.95, (n, 3)))  # real misses
    assert 0 < t._echo_misses < _ECHO_MISS_LIMIT
    d2 = t.positions.reshape(n, 3).copy()  # committed == last dests here
    d3 = rng.uniform(0.05, 0.95, (n, 3))
    hits_before = t.auto_continue_hits
    move(d2, d3)  # echo hit with a live miss streak
    assert t.auto_continue_hits == hits_before + 1
    assert t._echo_misses == 0  # the hit reset the nonzero streak
    for _ in range(_ECHO_MISS_LIMIT - 1):
        move(rng.uniform(0.05, 0.95, (n, 3)),
             rng.uniform(0.05, 0.95, (n, 3)))
    assert t._last_dests_host is not None  # still armed: streak < limit
