"""Runtime sentinels & graceful degradation (round 9).

Covers the failure-taxonomy contracts (docs/DESIGN.md):

- the PINNED before/after semantics of ``max_iters`` exhaustion
  (silent truncation without a sentinel — the flux deficit is exactly
  the untallied remainder — vs ladder recovery with one);
- straggler recovery bitwise-equal to an unconstrained run on all
  five facades (disjoint particle corridors: each element is scored
  by one history per move, so re-grouped scatter-adds stay exact);
- quarantine + ``lost_particles`` for unrecoverable residue;
- the on-device audit lanes (conservation residual, non-finite flux,
  anomaly dispositions);
- overflow recovery + the poisoned-engine guard (subprocess-pinned);
- quarantine-file hygiene (atomic append, torn-tail read-back).
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    SentinelPolicy,
    StreamingPartitionedTally,
    StreamingTally,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh

HERE = os.path.dirname(os.path.abspath(__file__))


def _corridor_workload(n=6, div=6):
    """Disjoint-lane workload: particle i flies along x inside its own
    (y, z) cell lane, so no element is ever scored by two histories —
    the regime where split-walk tallies re-associate EXACTLY (each
    element's flux is a sum over one particle's crossings, in
    iteration order both with and without truncation)."""
    lanes = (np.arange(n) + 0.5) / n
    src = np.stack([np.full(n, 0.07), lanes, lanes], axis=1)
    d1 = np.stack([np.full(n, 0.93), lanes, lanes], axis=1)
    d2 = np.stack([np.full(n, 0.15), lanes, lanes], axis=1)
    return build_box(1.0, 1.0, 1.0, div, div, div), src, [d1, d2]


def _drive(t, src, moves):
    t.CopyInitialPosition(src.reshape(-1).copy())
    for d in moves:
        t.MoveToNextLocation(None, d.reshape(-1).copy())


# ---------------------------------------------------------------------------
# Satellite: pin the PRE-sentinel exhaustion semantics
# ---------------------------------------------------------------------------

def test_max_iters_exhaustion_is_silent_truncation_without_sentinel(
    capsys,
):
    """The before/after contract the sentinel changes: WITHOUT one, a
    forced-tiny ``max_iters`` truncates particles mid-flight with zero
    signal under the recommended perf config (check_found_all=False) —
    no error, no warning — and the flux deficit equals exactly the
    untallied remainder (total flux == sum of w·|committed − start|,
    the s-telescoping invariant; strictly less than the full-path
    expectation)."""
    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    t = PumiTally(
        mesh, n, TallyConfig(check_found_all=False, max_iters=2)
    )
    t.CopyInitialPosition(src.reshape(-1).copy())
    x0 = t.positions.copy()
    t.MoveToNextLocation(None, moves[0].reshape(-1).copy())
    x1 = t.positions
    total = float(np.asarray(t.flux).sum())
    tallied = float(np.linalg.norm(x1 - x0, axis=1).sum())
    full = float(np.linalg.norm(moves[0] - x0, axis=1).sum())
    np.testing.assert_allclose(total, tallied, rtol=1e-12)
    assert total < full * 0.9  # really truncated, not a near-miss
    out = capsys.readouterr()
    assert "ERROR" not in out.out and "WARNING" not in out.out
    assert out.err == ""


# ---------------------------------------------------------------------------
# Straggler escalation: recovery bitwise on all five facades
# ---------------------------------------------------------------------------

def _make_facade(kind, mesh, n, cfg_kw):
    cfg = TallyConfig(check_found_all=False, **cfg_kw)
    if kind == "monolithic":
        return PumiTally(mesh, n, cfg)
    if kind == "sharded":
        cfg = TallyConfig(
            check_found_all=False, device_mesh=make_device_mesh(2),
            **cfg_kw,
        )
        return PumiTally(mesh, n, cfg)
    if kind == "streaming":
        return StreamingTally(mesh, n, chunk_size=3, config=cfg)
    if kind == "partitioned":
        cfg = TallyConfig(
            check_found_all=False, walk_vmem_max_elems=300,
            walk_block_kernel="gather", **cfg_kw,
        )
        return PartitionedPumiTally(mesh, n, cfg)
    cfg = TallyConfig(
        check_found_all=False, device_mesh=make_device_mesh(2),
        **cfg_kw,
    )
    return StreamingPartitionedTally(mesh, n, chunk_size=3, config=cfg)


@pytest.mark.parametrize(
    "kind",
    ["monolithic", "sharded", "streaming", "partitioned",
     "streaming_partitioned"],
)
def test_straggler_recovery_bitwise_vs_unconstrained(kind):
    """A forced-tiny-``max_iters`` run with the sentinel armed must
    recover the truncated particles bitwise-equal (positions, element
    ids, flux) to an unconstrained run — on every facade.

    Flux class per facade (docs/DESIGN.md "Failure taxonomy"): the
    replicated-mesh ladders continue the EXACT interrupted ray
    parametrization (WalkResult.s), so their recovered flux is
    bitwise; the partitioned resume-phase restarts rays from the
    committed pause points — the same re-parametrization every normal
    migration round performs — so its flux lands in that engine's
    existing scatter-order class (pinned at 1e-12 relative with a
    1e-15 absolute floor for the epsilon slivers a pause-face
    re-parametrization can move between adjacent elements;
    positions/elements still bitwise)."""
    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    ref = _make_facade(kind, mesh, n, {})
    _drive(ref, src, moves)

    t = _make_facade(
        kind, mesh, n, {"max_iters": 2, "sentinel": SentinelPolicy()}
    )
    _drive(t, src, moves)
    rep = t.health_report()
    assert rep.unfinished_total > 0  # the budget really truncated
    assert rep.stragglers_lost == 0
    assert rep.stragglers_recovered == rep.unfinished_total
    if kind in ("monolithic", "sharded", "streaming"):
        np.testing.assert_array_equal(
            np.asarray(t.flux), np.asarray(ref.flux)
        )
    else:
        np.testing.assert_allclose(
            np.asarray(t.flux), np.asarray(ref.flux),
            rtol=1e-12, atol=1e-15,
        )
    np.testing.assert_array_equal(t.positions, ref.positions)
    np.testing.assert_array_equal(t.elem_ids, ref.elem_ids)


def test_straggler_recovery_bf16_f32_rung():
    """Two-tier (bf16 select) engines carry an extra rung: the exact
    f32/hi-tier retry (its purpose is to cure the select tier's
    documented tie-class dead ends by walking the exact planes).
    Force rung 1 to be useless (a 1-iteration stub) so recovery must
    come from the forced-f32 rung: everyone recovers, committed
    positions match the unconstrained two-tier run bitwise (recovered
    particles commit dest exactly under either tier), and the audit
    stays conservation-clean — the recovered path's ELEMENT footprint
    may differ from the bf16 walk's on select-tier ties (the same
    benign class docs/DESIGN.md pins for the tier itself), so flux is
    checked by conservation, not bitwise equality."""
    import pumiumtally_tpu.sentinel.straggler as straggler

    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    cfg_kw = {"walk_table_dtype": "bfloat16"}
    ref = _make_facade("monolithic", mesh, n, cfg_kw)
    _drive(ref, src, moves)

    real = straggler._retry_step
    calls = []

    def capped_first_rung(mesh_, x, e, d, f, w, fx, k, s=None,
                          score_ops=None, *, tol, max_iters, walk_kw=(),
                          score_kinds=()):
        calls.append(dict(walk_kw).get("table_dtype"))
        if len(calls) == 1:
            max_iters = 1  # starve rung 1: rung 2 must do the work
        return real(mesh_, x, e, d, f, w, fx, k, s, score_ops, tol=tol,
                    max_iters=max_iters, walk_kw=walk_kw,
                    score_kinds=score_kinds)

    straggler._retry_step = capped_first_rung
    try:
        t = _make_facade(
            "monolithic", mesh, n,
            {**cfg_kw, "max_iters": 2, "sentinel": SentinelPolicy()},
        )
        _drive(t, src, moves)
    finally:
        straggler._retry_step = real
    assert "float32" in calls  # the exact-tier rung actually ran
    rep = t.health_report()
    assert rep.stragglers_lost == 0
    assert rep.stragglers_recovered > 0
    # Conservation (the audit's own gate) bounds the recovered flux;
    # the per-element footprint is tie-class vs the bf16 reference.
    assert rep.anomaly_moves == 0
    np.testing.assert_allclose(
        float(np.asarray(t.flux).sum()),
        float(np.asarray(ref.flux).sum()), rtol=1e-12,
    )
    np.testing.assert_array_equal(t.positions, ref.positions)


def test_unrecoverable_straggler_quarantined_and_counted(tmp_path):
    """When the whole ladder fails (stubbed to 1-iteration retries),
    the residue is declared lost: folded into ``lost_particles`` AND
    written to the quarantine JSONL with its origin/dest/element/
    weight for postmortem re-injection."""
    import pumiumtally_tpu.sentinel.straggler as straggler

    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    real = straggler._retry_step

    def useless(mesh_, x, e, d, f, w, fx, k, s=None, score_ops=None, *,
                tol, max_iters, walk_kw=(), score_kinds=()):
        return real(mesh_, x, e, d, f, w, fx, k, s, score_ops, tol=tol,
                    max_iters=1, walk_kw=walk_kw,
                    score_kinds=score_kinds)

    straggler._retry_step = useless
    try:
        t = PumiTally(
            mesh, n,
            TallyConfig(
                check_found_all=False, max_iters=2,
                sentinel=SentinelPolicy(
                    quarantine_dir=str(tmp_path), on_anomaly="record",
                ),
            ),
        )
        # Exact localization first (the stubbed ladder would lose the
        # sources too): sources sit in known cells after a full-budget
        # localize.
        t2 = PumiTally(mesh, n, TallyConfig(check_found_all=False))
        t2.CopyInitialPosition(src.reshape(-1).copy())
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.x = jnp.asarray(t2.positions.copy())
        t.elem = jnp.asarray(t2.elem_ids.copy())
        t.MoveToNextLocation(None, moves[0].reshape(-1).copy())
    finally:
        straggler._retry_step = real
    rep = t.health_report()
    assert rep.stragglers_lost > 0
    # lost_particles counts the MOVE's quarantined residue (the
    # localization ladder's losses stay clamped particles in this
    # facade, counted in the report only).
    assert t.lost_particles > 0
    from pumiumtally_tpu.sentinel import quarantine_path, read_quarantine

    records = read_quarantine(quarantine_path(str(tmp_path)))
    assert len(records) == t.lost_particles
    for r in records:
        assert set(r) == {"pid", "move", "origin", "dest", "elem",
                          "weight", "reason"}
        assert r["reason"] == "iteration_budget"
        np.testing.assert_allclose(r["dest"], moves[0][r["pid"]])
        assert r["weight"] == 1.0


# ---------------------------------------------------------------------------
# Audit lanes
# ---------------------------------------------------------------------------

def test_audit_pack_split_roundtrip():
    from pumiumtally_tpu.sentinel.audit import split_packed
    from pumiumtally_tpu.sentinel.policy import (
        ANOMALY_CONSERVATION,
        ANOMALY_UNFINISHED,
    )

    n_unf, mask = split_packed(
        37 * 8 + (ANOMALY_UNFINISHED | ANOMALY_CONSERVATION)
    )
    assert n_unf == 37 and mask == 3


def test_clean_run_audits_clean_and_bitwise():
    """Sentinel-on over a healthy workload: zero anomalies, a
    conservation residual at rounding level, and flux BITWISE equal to
    the sentinel-off engine (the audit only reads state)."""
    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    off = PumiTally(mesh, n, TallyConfig(check_found_all=False))
    _drive(off, src, moves)
    on = PumiTally(
        mesh, n,
        TallyConfig(check_found_all=False, sentinel=SentinelPolicy()),
    )
    _drive(on, src, moves)
    rep = on.health_report()
    assert rep.moves_audited == 2 and rep.anomaly_moves == 0
    assert rep.max_conservation_residual < 1e-12
    np.testing.assert_array_equal(
        np.asarray(on.flux), np.asarray(off.flux)
    )
    assert off._sentinel is None  # off constructs nothing


def test_conservation_anomaly_detected_and_raises():
    """Corrupting the flux accumulator between moves breaks the
    tallied-vs-straight-line identity: the next audited move must trip
    the conservation bit — warn by default, raise under
    on_anomaly='raise'."""
    from pumiumtally_tpu.sentinel import SentinelAnomalyError
    from pumiumtally_tpu.sentinel.policy import ANOMALY_CONSERVATION

    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    t = PumiTally(
        mesh, n,
        TallyConfig(
            check_found_all=False,
            sentinel=SentinelPolicy(on_anomaly="raise"),
        ),
    )
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, moves[0].reshape(-1).copy())
    t.flux = t.flux.at[0].add(1.0)  # in-flight corruption
    with pytest.raises(SentinelAnomalyError, match="conservation"):
        t.MoveToNextLocation(None, moves[1].reshape(-1).copy())
    rep = t.health_report()
    assert rep.anomaly_mask_union & ANOMALY_CONSERVATION
    assert rep.max_conservation_residual > 1e-6


def test_nonfinite_flux_anomaly_recorded(capsys):
    """A poisoned accumulator (NaN flux) trips the non-finite bit; the
    'record' disposition counts it without printing or raising."""
    from pumiumtally_tpu.sentinel.policy import ANOMALY_NONFINITE

    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    t = PumiTally(
        mesh, n,
        TallyConfig(
            check_found_all=False,
            sentinel=SentinelPolicy(on_anomaly="record"),
        ),
    )
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.flux = t.flux.at[0].set(jnp.nan)
    t.MoveToNextLocation(None, moves[0].reshape(-1).copy())
    rep = t.health_report()
    assert rep.anomaly_mask_union & ANOMALY_NONFINITE
    assert rep.anomaly_moves == 1
    assert "[SENTINEL]" not in capsys.readouterr().out


def test_health_report_in_vtk_field_data(tmp_path):
    """WriteTallyResults with a sentinel armed carries the health
    report as FIELD data beside lost_particles."""
    from pumiumtally_tpu.io.vtk import read_vtk_field_scalars

    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    t = PumiTally(
        mesh, n,
        TallyConfig(check_found_all=False, max_iters=2,
                    sentinel=SentinelPolicy()),
    )
    _drive(t, src, moves)
    out = str(tmp_path / "health.vtk")
    t.WriteTallyResults(out)
    assert read_vtk_field_scalars(out, "sentinel_moves_audited")[0] == 2.0
    assert read_vtk_field_scalars(
        out, "sentinel_stragglers_recovered"
    )[0] > 0.0
    assert read_vtk_field_scalars(
        out, "sentinel_stragglers_lost"
    )[0] == 0.0
    assert read_vtk_field_scalars(out, "lost_particles")[0] == 0.0


def test_retrace_budgets_cover_sentinel_entry_points():
    from pumiumtally_tpu.config import RETRACE_BUDGETS

    assert "audit_pack" in RETRACE_BUDGETS
    assert "straggler_retry" in RETRACE_BUDGETS


# ---------------------------------------------------------------------------
# Overflow recovery + poisoned guard (subprocess-pinned)
# ---------------------------------------------------------------------------

def _run_driver(arm, workdir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(HERE)
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "_sentinel_driver.py"),
         arm, str(workdir)],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(
        [ln for ln in proc.stdout.splitlines() if ln.startswith("{")][-1]
    )


@pytest.mark.slow
def test_overflow_recovery_subprocess(tmp_path):
    """The acceptance workload across a real process boundary: a
    capacity overflow that raised RuntimeError at HEAD~ completes
    through the ladder with flux bitwise-equal to a generously
    provisioned engine."""
    rec = _run_driver("recover", tmp_path)
    assert rec["flux_bitwise_vs_big"] is True
    assert rec["overflow_recoveries"] >= 1
    assert rec["capacity_escalations"] >= 1
    assert rec["poisoned"] is False


@pytest.mark.slow
def test_overflow_poison_and_safety_save_subprocess(tmp_path):
    """Ladder exhaustion (escalation disabled): one overflow_safety
    generation is written through the armed CheckpointPolicy, the
    engine latches poisoned, and the next facade call refuses with
    the resume-from-checkpoint error."""
    rec = _run_driver("poison", tmp_path)
    assert rec["poisoned"] is True
    assert rec["ladder_msg_has_poisoned"] is True
    assert rec["refusal_msg_has_resume"] is True
    assert rec["generations"] >= 1
    assert "overflow_safety" in rec["save_reasons"]


def test_poisoned_guard_refuses_every_protocol_call(tmp_path):
    """In-process version of the poisoned guard: every protocol call
    (move, re-source, write) refuses once the latch is set."""
    from pumiumtally_tpu.sentinel import EnginePoisonedError

    mesh, src, moves = _corridor_workload()
    n = src.shape[0]
    t = PartitionedPumiTally(
        mesh, n, TallyConfig(check_found_all=False)
    )
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.engine.poisoned = True
    with pytest.raises(EnginePoisonedError, match="corrupt"):
        t.MoveToNextLocation(None, moves[0].reshape(-1).copy())
    with pytest.raises(EnginePoisonedError, match="resume from checkpoint"):
        t.CopyInitialPosition(src.reshape(-1).copy())
    with pytest.raises(EnginePoisonedError):
        t.WriteTallyResults(str(tmp_path / "refused.vtk"))
    assert not os.path.exists(tmp_path / "refused.vtk")
    # The .pvtu writer branch bypasses super(): it must refuse too.
    with pytest.raises(EnginePoisonedError):
        t.WriteTallyResults(str(tmp_path / "refused.pvtu"))
    assert not os.path.exists(tmp_path / "refused.pvtu")

    # The streaming facade overrides the protocol methods wholesale —
    # its own entry points must consult the engines' latches.
    sp = _make_facade("streaming_partitioned", mesh, n, {})
    sp.CopyInitialPosition(src.reshape(-1).copy())
    sp.engines[0].poisoned = True
    with pytest.raises(EnginePoisonedError, match="resume from checkpoint"):
        sp.MoveToNextLocation(None, moves[0].reshape(-1).copy())
    with pytest.raises(EnginePoisonedError):
        sp.CopyInitialPosition(src.reshape(-1).copy())


def test_overflow_recovery_inprocess_localization():
    """Localization overflow (every source in one part, slots for a
    quarter of them): recovered by ONE demand-sized escalation, final
    state identical to a generously provisioned engine."""
    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4)
    n = 40
    rng = np.random.default_rng(5)
    src = rng.uniform(0.02, 0.10, (n, 3))
    dest = rng.uniform(0.1, 0.9, (n, 3))

    def cfg(capf):
        return TallyConfig(
            check_found_all=False, capacity_factor=capf,
            walk_vmem_max_elems=100, walk_block_kernel="gather",
        )

    big = PartitionedPumiTally(mesh, n, cfg(8.0))
    _drive(big, src, [dest])
    t = PartitionedPumiTally(mesh, n, cfg(1.05))
    _drive(t, src, [dest])
    assert t.engine.overflow_recoveries == 1
    assert t.engine.capacity_escalations == 1
    np.testing.assert_array_equal(
        np.asarray(t.flux), np.asarray(big.flux)
    )
    np.testing.assert_array_equal(t.positions, big.positions)


def test_checkpoint_restore_after_capacity_escalation(tmp_path):
    """A checkpoint saved AFTER the overflow ladder escalated capacity
    holds a particle distribution a freshly built (small-capacity)
    engine cannot place — the restore must escalate-and-retry exactly
    like the live ladder (found by the r9 end-to-end drive: it raised
    OVERFLOW_MESSAGE before this fix)."""
    from pumiumtally_tpu.utils.checkpoint import (
        load_tally_state,
        save_tally_state,
    )

    mesh = build_box(1.0, 1.0, 1.0, 4, 4, 4)
    n = 40
    rng = np.random.default_rng(9)
    src = rng.uniform(0.1, 0.9, (n, 3))
    corner = rng.uniform(0.02, 0.10, (n, 3))
    cfg = TallyConfig(
        check_found_all=False, capacity_factor=1.05,
        walk_vmem_max_elems=100, walk_block_kernel="gather",
        sentinel=SentinelPolicy(),
    )
    t = PartitionedPumiTally(mesh, n, cfg)
    _drive(t, src, [corner])
    assert t.engine.capacity_escalations >= 1  # the premise
    path = str(tmp_path / "escalated.npz")
    save_tally_state(t, path)

    t2 = PartitionedPumiTally(mesh, n, cfg)
    load_tally_state(t2, path)
    assert t2.engine.capacity_escalations >= 1
    np.testing.assert_array_equal(t2.positions, t.positions)
    np.testing.assert_allclose(
        np.asarray(t2.flux), np.asarray(t.flux), rtol=1e-12, atol=1e-15
    )
    # The restored engine keeps transporting (no poisoned latch, no
    # stale overflow).
    t2.MoveToNextLocation(None, src.reshape(-1).copy())


# ---------------------------------------------------------------------------
# Quarantine-file hygiene (atomic append)
# ---------------------------------------------------------------------------

def test_quarantine_append_and_torn_tail_readback(tmp_path):
    from pumiumtally_tpu.sentinel.quarantine import (
        append_quarantine,
        quarantine_path,
        read_quarantine,
    )

    d = str(tmp_path)
    append_quarantine(d, [{"pid": 1, "reason": "a"}])
    append_quarantine(d, [{"pid": 2, "reason": "b"},
                          {"pid": 3, "reason": "c"}])
    path = quarantine_path(d)
    recs = read_quarantine(path)
    assert [r["pid"] for r in recs] == [1, 2, 3]

    # Torn tail (no newline): skipped, the intact prefix survives.
    with open(path, "ab") as f:
        f.write(b'{"pid": 4, "reas')
    recs = read_quarantine(path)
    assert [r["pid"] for r in recs] == [1, 2, 3]

    # Torn line in the MIDDLE is real corruption and raises.
    with open(path, "wb") as f:
        f.write(b'{"pid": 1}\n{"bro\n{"pid": 3}\n')
    with pytest.raises(ValueError, match="unparseable"):
        read_quarantine(path)


def test_atomic_append_creates_and_extends(tmp_path):
    from pumiumtally_tpu.utils.checkpoint import atomic_append

    p = str(tmp_path / "log.jsonl")
    atomic_append(p, b"one\n")
    atomic_append(p, b"two\n")
    with open(p, "rb") as f:
        assert f.read() == b"one\ntwo\n"
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


def test_sentinel_policy_validation():
    with pytest.raises(ValueError, match="on_anomaly"):
        SentinelPolicy(on_anomaly="explode")
    with pytest.raises(ValueError, match="retry_iters_factor"):
        SentinelPolicy(retry_iters_factor=0)
    with pytest.raises(ValueError, match="sentinel"):
        TallyConfig(sentinel=object())
    with pytest.raises(RuntimeError, match="sentinel"):
        PumiTally(
            build_box(1, 1, 1, 2, 2, 2), 4,
            TallyConfig(check_found_all=False),
        ).health_report()
