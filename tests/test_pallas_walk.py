"""One-kernel Pallas walk (ops/pallas_walk.py) vs the two-tier
``walk_local`` — round 17's fused select/refine/scatter kernel, pinned
in pallas interpret mode (the CPU environment; Mosaic-compiled timing
happens in the on-chip suite, tools/r13_onchip_suite.sh).

Unlike the vmem prototype (whose column-wise projections round
differently from the einsum — tests/test_vmem_walk.py), this kernel
calls the SAME row-level helpers as the gather walk after an exact
one-hot fetch, so the parity pin here is strict: positions, elements,
done/exited/pending BITWISE vs ``walk_local``'s two-tier path; flux and
scoring lanes differ only in accumulation order (per-tile matmul
partials vs cascaded scatter-adds — the documented benign class).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    SentinelPolicy,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.ops.pallas_walk import (
    modeled_walk_bytes,
    pallas_walk_local,
)
from pumiumtally_tpu.parallel.partition import (
    build_partition,
    resolve_block_kernel,
    walk_local,
)

HERE = os.path.dirname(os.path.abspath(__file__))


def _chip_workload(seed, n, ndev=4, divs=4):
    """A single chip's slice of a partitioned two-tier walk: its bf16
    select tier + f32 refinement tier plus particles localized to its
    elements, some destined to cross partition faces (pauses), some
    non-flying (hold), some dead — the mixed-outcome regime the parity
    pin needs (mirrors tests/test_vmem_walk.py)."""
    mesh = build_box(1, 1, 1, divs, divs, divs)
    part = build_partition(mesh, ndev, table_dtype="bfloat16")
    assert part.adj_int is None and part.table_hi is not None
    rng = np.random.default_rng(seed)
    chip = 1
    table = part.table[chip * part.L: (chip + 1) * part.L]
    hi = part.table_hi[chip * part.L * 4: (chip + 1) * part.L * 4]
    owned = np.flatnonzero(np.asarray(part.orig_of_glid).reshape(
        ndev, part.L)[chip] >= 0)
    lelem = rng.choice(owned, size=n).astype(np.int32)
    coords = np.asarray(mesh.coords)
    tets = np.asarray(mesh.tet2vert)
    orig = np.asarray(part.orig_of_glid).reshape(ndev, part.L)[chip]
    cent = coords[tets[orig[lelem]]].mean(axis=1)
    step = rng.normal(scale=0.25, size=(n, 3))
    dest = cent + step
    fly = (rng.random(n) > 0.15).astype(np.int8)
    dead = rng.random(n) < 0.1
    w = rng.uniform(0.5, 2.0, n)
    x = jnp.asarray(cent)
    dest = jnp.asarray(np.where(fly[:, None] == 1, dest, cent))
    done0 = jnp.asarray(dead)
    exited0 = jnp.zeros(n, bool)
    flux0 = jnp.zeros((part.L,), x.dtype)
    return (table, hi, x, jnp.asarray(lelem), dest, jnp.asarray(fly),
            jnp.asarray(w), done0, exited0, flux0)


def _split(args):
    """(table, hi, rest...) -> walk_local's (table, rest..., hi) call."""
    table, hi = args[0], args[1]
    return table, hi, args[2:]


@pytest.mark.parametrize("tally", [True, False])
def test_pallas_walk_local_bitwise_vs_walk_local(tally):
    """The tentpole pin: positions/elements/done/exited/pending are
    BITWISE ``walk_local``'s two-tier path; flux to rounding (and
    EXACTLY untouched on non-tallying walks)."""
    table, hi, rest = _split(_chip_workload(seed=5, n=700))
    ref = walk_local(table, *rest, tally=tally, tol=1e-8, max_iters=4096,
                     table_hi=hi)
    out = pallas_walk_local(table, hi, *rest, tally=tally, tol=1e-8,
                            max_iters=4096, interpret=True)
    rx, rl, rd, rex, rp, rf, _ = ref
    px, plm, pd_, pex, pp_, pf, _ = out
    np.testing.assert_array_equal(np.asarray(px), np.asarray(rx))
    np.testing.assert_array_equal(np.asarray(plm), np.asarray(rl))
    np.testing.assert_array_equal(np.asarray(pd_), np.asarray(rd))
    np.testing.assert_array_equal(np.asarray(pex), np.asarray(rex))
    np.testing.assert_array_equal(np.asarray(pp_), np.asarray(rp))
    if tally:
        np.testing.assert_allclose(np.asarray(pf), np.asarray(rf),
                                   rtol=1e-10, atol=1e-13)
    else:
        np.testing.assert_array_equal(np.asarray(pf), np.asarray(rf))
    # The workload must actually exercise pauses and mixed outcomes,
    # or this parity test proves nothing.
    assert int(np.sum(np.asarray(rp) >= 0)) > 0
    assert int(np.sum(np.asarray(rex))) > 0
    assert int(np.sum(np.asarray(rd))) > 0


def test_pallas_walk_tile_padding_invariance():
    """Per-particle outputs are exactly tile-invariant (each
    trajectory's math is unchanged by how particles are grouped into
    kernel tiles); flux is reduced per tile then summed, so only its
    ADDITION ORDER depends on the split."""
    table, hi, rest = _split(_chip_workload(seed=6, n=2500))
    outs = []
    for w_tile in (1024, 2048, 4096):
        outs.append(pallas_walk_local(
            table, hi, *rest, tally=True, tol=1e-8, max_iters=4096,
            w_tile=w_tile, interpret=True,
        ))
    for o in outs[1:]:
        for a, b in zip(outs[0][:5], o[:5]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(outs[0][5]),
                                   np.asarray(o[5]),
                                   rtol=1e-12, atol=1e-15)


def test_pallas_walk_blocked_streaming_matches_per_block_walks():
    """blocks>1 (the double-buffered streaming case): two stacked block
    tables walked in ONE kernel launch match running ``walk_local`` on
    each block separately — bitwise per-particle state, per-block flux
    to rounding. Layout per the sub-split contract: slots grouped by
    block (cap_b each), lelem block-local, flux [blocks*L]."""
    cap_b = 1024  # one w_tile per block
    wl = []
    stacked = {"lo": [], "hi": []}
    per_block = []
    for b, seed in enumerate((7, 8)):
        table, hi, rest = _split(_chip_workload(seed=seed, n=cap_b))
        stacked["lo"].append(table)
        stacked["hi"].append(hi)
        per_block.append(rest)
        wl.append(walk_local(table, *rest, tally=True, tol=1e-8,
                             max_iters=4096, table_hi=hi))
    lo2 = jnp.concatenate(stacked["lo"])
    hi2 = jnp.concatenate(stacked["hi"])
    cat = [jnp.concatenate([a[i] for a in per_block])
           for i in range(len(per_block[0]))]
    out = pallas_walk_local(lo2, hi2, *cat, tally=True, tol=1e-8,
                            max_iters=4096, blocks=2, w_tile=cap_b,
                            interpret=True)
    for i in range(5):  # x, lelem, done, exited, pending
        np.testing.assert_array_equal(
            np.asarray(out[i]),
            np.concatenate([np.asarray(wl[b][i]) for b in (0, 1)]),
        )
    np.testing.assert_allclose(
        np.asarray(out[5]),
        np.concatenate([np.asarray(wl[b][5]) for b in (0, 1)]),
        rtol=1e-10, atol=1e-13,
    )
    with pytest.raises(ValueError, match="blocks"):
        pallas_walk_local(lo2, hi2, *[a[:-1] for a in cat[:1]] + cat[1:],
                          tally=True, tol=1e-8, max_iters=16, blocks=2,
                          interpret=True)


def test_pallas_walk_scoring_lanes_bitwise_vs_walk_local():
    """Scoring-armed kernel walk: per-particle state stays bitwise
    ``walk_local``'s, and the accumulated lane bank lands in the same
    reassociation class as flux. Two lanes x two bins with a DROP
    sentinel row exercised (dropped lanes die like mode='drop')."""
    from pumiumtally_tpu.scoring.binding import ScoreOps

    table, hi, rest = _split(_chip_workload(seed=9, n=700))
    x, lelem, dest, fly, w, done, exited, flux = rest
    L = flux.shape[0]
    kinds = ("track", "one")
    stride = 2 * len(kinds)  # 2 bins x 2 scores
    n = x.shape[0]
    rng = np.random.default_rng(3)
    bank_size = L * stride
    sbin = (rng.integers(0, 2, n).astype(np.int32) * len(kinds))
    sbin[::17] = bank_size  # DROP sentinel rows
    sbin = jnp.asarray(sbin)
    sfac = jnp.asarray(rng.uniform(0.5, 2.0, (n, len(kinds))), x.dtype)
    mk = lambda: ScoreOps(kinds, jnp.zeros(bank_size, x.dtype), sbin, sfac)
    ref = walk_local(table, *rest, tally=True, tol=1e-8, max_iters=4096,
                     table_hi=hi, scoring=mk())
    out = pallas_walk_local(table, hi, *rest, tally=True, tol=1e-8,
                            max_iters=4096, interpret=True, scoring=mk())
    for i in range(5):
        np.testing.assert_array_equal(np.asarray(out[i]),
                                      np.asarray(ref[i]))
    np.testing.assert_allclose(np.asarray(out[5]), np.asarray(ref[5]),
                               rtol=1e-10, atol=1e-13)
    np.testing.assert_allclose(np.asarray(out[7]), np.asarray(ref[7]),
                               rtol=1e-10, atol=1e-13)
    assert float(jnp.sum(out[7])) > 0  # lanes genuinely populated
    with pytest.raises(ValueError, match="tallying"):
        pallas_walk_local(table, hi, *rest, tally=False, tol=1e-8,
                          max_iters=16, interpret=True, scoring=mk())


@pytest.mark.parametrize(
    "perm_mode", ["arrays", "packed", "indirect", "sorted"]
)
def test_pallas_engine_parity_across_perm_modes(perm_mode):
    """Engine-level parity in each of the replicated walk's four
    cascade perm modes: the pallas engine stays BITWISE the bf16 gather
    partitioned engine (the kernel seam's own pin), and both land on
    the monolithic reference within the partitioned engines'
    pre-existing exit-materialization class (a boundary hit's
    ``x0 + s·d0`` rounds differently from the replicated ray — ulps,
    gather and pallas identically)."""
    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 500
    rng = np.random.default_rng(21)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), -0.1, 1.1)
    ref = PumiTally(mesh, n, TallyConfig(
        walk_table_dtype="bfloat16", walk_perm_mode=perm_mode))
    t = PartitionedPumiTally(mesh, n, TallyConfig(
        walk_table_dtype="bfloat16", walk_kernel="pallas",
        capacity_factor=3.0))
    tg = PartitionedPumiTally(mesh, n, TallyConfig(
        walk_table_dtype="bfloat16", capacity_factor=3.0))
    assert t.engine.use_pallas_walk and not tg.engine.use_pallas_walk
    for e in (ref, t, tg):
        e.CopyInitialPosition(src.reshape(-1).copy())
        e.MoveToNextLocation(src.reshape(-1).copy(),
                             dst.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
    np.testing.assert_array_equal(t.positions, tg.positions)
    np.testing.assert_array_equal(t.elem_ids, tg.elem_ids)
    np.testing.assert_allclose(t.positions, ref.positions,
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(t.flux, np.float64), np.asarray(ref.flux, np.float64),
        rtol=1e-10, atol=1e-13,
    )


def test_pallas_engine_blocked_matches_gather_and_conserves():
    """walk_vmem_max_elems forces the sub-split: the STREAMED pallas
    engine (blocks>1) matches the bf16 gather sub-split bitwise on
    positions and conserves track length exactly."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 2000
    rng = np.random.default_rng(5)
    src = rng.uniform(0.05, 0.95, (n, 3))
    # In-box destinations: the whole track length is tallied, so the
    # conservation gate is exact (boundary-exit truncation is covered
    # by the kernel-level parity tests above).
    dst = rng.uniform(0.05, 0.95, (n, 3))

    def run(kernel):
        t = PartitionedPumiTally(mesh, n, TallyConfig(
            walk_table_dtype="bfloat16", walk_kernel=kernel,
            walk_vmem_max_elems=200, capacity_factor=3.0))
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        return t

    tp, tg = run("pallas"), run("gather")
    assert tp.engine.use_pallas_walk and tp.engine.blocks_per_chip > 1
    assert not tg.engine.use_pallas_walk
    np.testing.assert_array_equal(tp.positions, tg.positions)
    np.testing.assert_array_equal(tp.elem_ids, tg.elem_ids)
    np.testing.assert_allclose(
        np.asarray(tp.flux, np.float64), np.asarray(tg.flux, np.float64),
        rtol=1e-10, atol=1e-13,
    )
    expect = np.linalg.norm(dst - src, axis=1).sum()
    np.testing.assert_allclose(
        np.asarray(tp.flux, np.float64).sum(), expect, rtol=1e-9
    )


def test_pallas_straggler_ladder_recovery():
    """A forced-tiny-``max_iters`` pallas run with the sentinel armed
    recovers the truncated particles to the unconstrained pallas run —
    the partitioned resume-phase contract (positions/elements bitwise,
    flux in the pause-re-parametrization class of
    tests/test_sentinel.py)."""
    div = 6
    n = 6
    lanes = (np.arange(n) + 0.5) / n
    src = np.stack([np.full(n, 0.07), lanes, lanes], axis=1)
    moves = [np.stack([np.full(n, 0.93), lanes, lanes], axis=1),
             np.stack([np.full(n, 0.15), lanes, lanes], axis=1)]
    mesh = build_box(1.0, 1.0, 1.0, div, div, div)

    def make(**kw):
        return PartitionedPumiTally(mesh, n, TallyConfig(
            check_found_all=False, walk_table_dtype="bfloat16",
            walk_kernel="pallas", **kw))

    def drive(t):
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d in moves:
            t.MoveToNextLocation(None, d.reshape(-1).copy())
        return t

    ref = drive(make())
    t = drive(make(max_iters=2, sentinel=SentinelPolicy()))
    rep = t.health_report()
    assert rep.unfinished_total > 0  # the budget really truncated
    assert rep.stragglers_lost == 0
    assert rep.stragglers_recovered == rep.unfinished_total
    np.testing.assert_allclose(np.asarray(t.flux), np.asarray(ref.flux),
                               rtol=1e-12, atol=1e-15)
    np.testing.assert_array_equal(t.positions, ref.positions)
    np.testing.assert_array_equal(t.elem_ids, ref.elem_ids)


def test_walk_kernel_knob_roundtrip_and_validation():
    """TallyConfig.walk_kernel: the default 'gather' setting is the
    STATUS-QUO resolution (defers to the legacy walk_block_kernel knob,
    so untuned configs build byte-identical engines); 'pallas' demands
    the bf16 tier; junk is rejected."""
    cfg = TallyConfig()
    assert cfg.walk_kernel == "gather"
    assert cfg.resolved_walk_kernel() == cfg.walk_block_kernel
    assert TallyConfig(walk_kernel="vmem").resolved_walk_kernel() == "vmem"
    assert TallyConfig(
        walk_table_dtype="bfloat16", walk_kernel="pallas"
    ).resolved_walk_kernel() == "pallas"
    with pytest.raises(ValueError, match="walk_kernel"):
        TallyConfig(walk_kernel="mxu")
    with pytest.raises(ValueError, match="bfloat16"):
        TallyConfig(walk_kernel="pallas")
    with pytest.raises(ValueError, match="bfloat16"):
        resolve_block_kernel("pallas", "float32")
    assert resolve_block_kernel("pallas", "bfloat16") == "pallas"


def test_default_walk_kernel_path_byte_and_allocation_identical():
    """The default-config partitioned engine must be indistinguishable
    from one built through the legacy knob alone: same resolved block
    kernel, bitwise flux/positions, and not one device array more
    (the pallas module is never even imported on this path)."""
    import gc

    import jax

    mesh = build_box(1, 1, 1, 4, 4, 4)
    n = 400
    rng = np.random.default_rng(2)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = rng.uniform(0.05, 0.95, (n, 3))

    def run(cfg):
        t = PartitionedPumiTally(mesh, n, cfg)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dst.reshape(-1).copy())
        return t

    warm = run(TallyConfig(capacity_factor=3.0))
    legacy_kernel = warm.engine.block_kernel
    del warm
    gc.collect()
    base = len(jax.live_arrays())
    t_default = run(TallyConfig(capacity_factor=3.0))
    flux_default = np.asarray(t_default.flux).copy()
    pos_default = np.array(t_default.positions)
    assert t_default.engine.block_kernel == legacy_kernel
    assert not t_default.engine.use_pallas_walk
    gc.collect()
    default_delta = len(jax.live_arrays()) - base
    del t_default
    gc.collect()
    prev = len(jax.live_arrays())
    t_explicit = run(TallyConfig(capacity_factor=3.0,
                                 walk_kernel="gather"))
    np.testing.assert_array_equal(np.asarray(t_explicit.flux),
                                  flux_default)
    np.testing.assert_array_equal(np.array(t_explicit.positions),
                                  pos_default)
    gc.collect()
    explicit_delta = len(jax.live_arrays()) - prev
    assert explicit_delta == default_delta


def test_bf16_vmem_reroute_is_logged(caplog):
    """Satellite: the bf16 + block_kernel='vmem' reroute to gather is
    no longer silent — an INFO diagnostic names the reroute and the
    pallas alternative."""
    import logging

    from pumiumtally_tpu.utils.logging import get_logger

    logger = get_logger()
    caplog.handler.setLevel(logging.INFO)
    logger.addHandler(caplog.handler)  # the logger does not propagate
    try:
        assert resolve_block_kernel("vmem", "bfloat16") == "gather"
    finally:
        logger.removeHandler(caplog.handler)
    assert any("rerouting" in r.message and "pallas" in r.message
               for r in caplog.records)


def test_modeled_walk_bytes():
    """The 80 B f32 gather and 52 B two-tier streaming models, derived
    from the packed-layout constants (a layout change reprices the
    bench row automatically)."""
    from pumiumtally_tpu.mesh.tetmesh import (
        WALK_PLANE_WIDTH,
        WALK_TABLE_LO_WIDTH,
        WALK_TABLE_WIDTH,
    )

    assert modeled_walk_bytes("gather") == 80 == WALK_TABLE_WIDTH * 4
    assert modeled_walk_bytes("gather", "bfloat16") == 52
    assert modeled_walk_bytes("pallas", "bfloat16") == 52
    assert (WALK_TABLE_LO_WIDTH * 2 + WALK_PLANE_WIDTH * 4) == 52
    assert modeled_walk_bytes("vmem") == 0
    with pytest.raises(ValueError, match="two-tier"):
        modeled_walk_bytes("pallas", "float32")
    with pytest.raises(ValueError, match="vmem"):
        modeled_walk_bytes("vmem", "bfloat16")
    with pytest.raises(ValueError, match="kernel"):
        modeled_walk_bytes("mxu")
    with pytest.raises(ValueError, match="table_dtype"):
        modeled_walk_bytes("gather", "float16")


@pytest.mark.slow
def test_aot_pallas_walk_compile_chipless():
    """The chipless AOT/Mosaic lowering stage: compiles the streaming
    kernel against a TPU topology without hardware, or records a clean
    structured skip (no hang — the tool carries its own alarm)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    proc = subprocess.run(
        [sys.executable,
         os.path.join(HERE, os.pardir, "tools",
                      "aot_pallas_walk_compile.py"),
         "--quick"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    blob = proc.stdout + proc.stderr
    if proc.returncode != 0 or "SKIP" in blob:
        for pat in ("topology", "libtpu", "SKIP"):
            if pat in blob:
                pytest.skip(f"chipless AOT unavailable here: {pat}")
        raise AssertionError(f"AOT tool failed:\n{blob}")
    assert "COMPILE OK" in blob
