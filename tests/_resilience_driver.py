"""Subprocess campaign driver for the kill-and-resume tests.

Runs one deterministic multi-batch campaign on a chosen engine facade
with autosave armed, so tests/test_resilience.py can kill it
(injected SIGTERM drain, or hard SIGKILL mid-save via
PUMIUMTALLY_FAULT) and relaunch it with ``--resume``:

    python tests/_resilience_driver.py --facade part \
        --ckpt-dir /tmp/ck --out /tmp/flux.npy [--resume]

The campaign is B source batches x M continue-mode moves, all inputs
derived from one seeded rng — every process (fresh, killed, resumed)
computes the identical trajectory and indexes into it by the restored
``iter_count``, so a resumed run re-drives exactly the batches the
dead one had not finished. Not collected by pytest (no ``test_``
prefix); runnable standalone.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BATCHES = 4
MOVES = 2
N = 96
MESH_ARGS = (1, 1, 1, 3, 3, 3)
SEED = 1234


def build(facade, ckpt_dir):
    from pumiumtally_tpu import (
        CheckpointPolicy,
        PartitionedPumiTally,
        PumiTally,
        StreamingPartitionedTally,
        StreamingTally,
        TallyConfig,
        build_box,
    )
    from pumiumtally_tpu.parallel import make_device_mesh

    policy = CheckpointPolicy(dir=ckpt_dir, every_n_batches=1, keep=3)
    mesh = build_box(*MESH_ARGS)
    if facade == "mono":
        return PumiTally(
            mesh, N, TallyConfig(checkpoint=policy, check_found_all=False)
        )
    if facade == "sharded":
        return PumiTally(
            mesh, N,
            TallyConfig(checkpoint=policy, check_found_all=False,
                        device_mesh=make_device_mesh(4)),
        )
    if facade == "stream":
        return StreamingTally(
            mesh, N, chunk_size=40,
            config=TallyConfig(checkpoint=policy, check_found_all=False),
        )
    if facade == "part":
        return PartitionedPumiTally(
            mesh, N,
            TallyConfig(checkpoint=policy, check_found_all=False,
                        capacity_factor=4.0),
        )
    if facade == "stream_part":
        return StreamingPartitionedTally(
            mesh, N, chunk_size=40,
            config=TallyConfig(checkpoint=policy, check_found_all=False,
                               device_mesh=make_device_mesh(4),
                               capacity_factor=6.0),
        )
    raise SystemExit(f"unknown facade {facade!r}")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--facade", required=True,
                   choices=["mono", "sharded", "stream", "part",
                            "stream_part"])
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--resume", action="store_true")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("JAX_ENABLE_X64", "true")
    if args.facade in ("sharded", "stream_part"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()

    import numpy as np

    rng = np.random.default_rng(SEED)
    src = rng.uniform(0.1, 0.9, (BATCHES, N, 3))
    dst = rng.uniform(0.1, 0.9, (BATCHES, MOVES, N, 3))

    t = build(args.facade, args.ckpt_dir)
    start_batch, done_moves = 0, 0
    if args.resume:
        from pumiumtally_tpu import resume_latest

        info = resume_latest(t)
        if info is not None:
            # Move-granular resume: a graceful drain leaves a
            # batch-aligned newest generation (done_moves == 0), but a
            # drain SAFETY save survived by a hard kill — or an
            # every_seconds save — can land mid-batch; then the
            # restored state already contains that batch's sources and
            # first done_moves moves, so re-drive only the remainder.
            start_batch, done_moves = divmod(t.iter_count, MOVES)
            print(f"resumed generation {info.generation} at batch "
                  f"{start_batch} (iter_count {t.iter_count})")
    for b in range(start_batch, BATCHES):
        skip = done_moves if b == start_batch else 0
        if skip == 0:
            # A mid-batch restore already localized this batch's
            # sources; re-sourcing would rewind committed positions.
            t.CopyInitialPosition(src[b].reshape(-1).copy())
        for m in range(skip, MOVES):
            t.MoveToNextLocation(None, dst[b, m].reshape(-1).copy())
    # The final batch never closes via re-sourcing; seal the campaign
    # with an explicit generation so a corrupted-latest test can fall
    # back past it.
    t.checkpoint_now(final=True)
    np.save(args.out, np.asarray(t.flux, np.float64))


if __name__ == "__main__":
    main()
