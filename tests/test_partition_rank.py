"""Sort-free redistribution: bitwise parity at all three rewired sites
plus the gather sub-split's empty-block skip.

The counting-rank partition (ops/bucketize.py) replaces the stable
argsort at (a) the compaction cascade's stage boundaries (ops/walk.py),
(b) walk_local's in-round compaction and slot restore, and (c) particle
migration's destination computation (parallel/partition.py). Both
methods compute the IDENTICAL permutation, so every observable —
flux included — must be BITWISE equal between
``partition_method="rank"`` and ``"argsort"`` (the same parity pattern
as the perm-mode tests). The "sorted" perm mode (element-locality
argsort, the pre-rank default) is a different-but-valid permutation:
FP-equal only.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    TallyConfig,
    build_box,
)
from pumiumtally_tpu.ops.walk import walk
from pumiumtally_tpu.parallel import make_device_mesh
from pumiumtally_tpu.parallel.partition import migrate, walk_local


def _walk_setup(seed=0, n=2048, div=6):
    mesh = build_box(1.0, 1.0, 1.0, div, div, div)
    rng = np.random.default_rng(seed)
    x = jnp.asarray(np.tile(np.mean(
        np.asarray(mesh.coords)[np.asarray(mesh.tet2vert)[0]], axis=0),
        (n, 1)))
    elem = jnp.zeros((n,), jnp.int32)
    src = jnp.asarray(rng.uniform(0.05, 0.95, (n, 3)))
    r = walk(mesh, x, elem, src, jnp.ones((n,), jnp.int8),
             jnp.zeros((n,)), jnp.zeros((mesh.nelems,)),
             tally=False, tol=1e-12, max_iters=4096, compact=False)
    assert bool(jnp.all(r.done))
    dest = jnp.asarray(np.asarray(src) + rng.normal(scale=0.2, size=(n, 3)))
    fly = jnp.asarray((rng.uniform(size=n) > 0.1).astype(np.int8))
    dest = jnp.where(fly[:, None] == 1, dest, r.x)
    w = jnp.asarray(rng.uniform(0.5, 2.0, n))
    return mesh, r.x, r.elem, dest, fly, w


# -- site (a): the compaction cascade -----------------------------------

@pytest.mark.parametrize("mode", ["packed", "indirect", "arrays"])
def test_cascade_rank_vs_argsort_bitwise(mode):
    mesh, x, elem, dest, fly, w = _walk_setup()
    flux0 = jnp.zeros((mesh.nelems,))
    out = {
        meth: walk(mesh, x, elem, dest, fly, w, flux0,
                   tally=True, tol=1e-12, max_iters=4096,
                   compact=True, min_window=256, perm_mode=mode,
                   partition_method=meth)
        for meth in ("rank", "argsort")
    }
    a, b = out["rank"], out["argsort"]
    assert bool(jnp.all(a.done))
    for f in ("x", "elem", "done", "exited", "flux"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        )


def test_sorted_mode_is_fp_equal_only():
    """perm_mode="sorted" (the pre-rank element-locality argsort) is a
    different, equally valid permutation: identical per-particle state,
    flux equal to scatter-order round-off."""
    mesh, x, elem, dest, fly, w = _walk_setup(seed=3)
    flux0 = jnp.zeros((mesh.nelems,))
    a = walk(mesh, x, elem, dest, fly, w, flux0, tally=True, tol=1e-12,
             max_iters=4096, compact=True, min_window=256,
             perm_mode="packed")
    s = walk(mesh, x, elem, dest, fly, w, flux0, tally=True, tol=1e-12,
             max_iters=4096, compact=True, min_window=256,
             perm_mode="sorted")
    np.testing.assert_array_equal(np.asarray(a.x), np.asarray(s.x))
    np.testing.assert_array_equal(np.asarray(a.elem), np.asarray(s.elem))
    np.testing.assert_allclose(
        np.asarray(a.flux), np.asarray(s.flux), rtol=1e-12, atol=1e-12
    )
    with pytest.raises(ValueError, match="partition_method"):
        walk(mesh, x, elem, dest, fly, w, flux0, tally=True, tol=1e-12,
             max_iters=4096, partition_method="radix")


# -- site (b): walk_local's cascade + restore ---------------------------

def test_walk_local_rank_vs_argsort_bitwise():
    """Direct walk_local with the cascade engaged (min_window below the
    slot count) and remote pauses in play: every output including the
    owned flux must be bitwise identical across methods."""
    from pumiumtally_tpu.parallel.partition import build_partition

    mesh = build_box(1, 1, 1, 6, 6, 6)
    part = build_partition(mesh, 2)
    rng = np.random.default_rng(7)
    n = 1024
    # Localize on the FULL mesh, then keep chip 0's particles.
    src = rng.uniform(0.05, 0.95, (n, 3))
    ref = PumiTally(mesh, n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    glid = np.asarray(part.glid_of_orig)[ref.elem_ids]
    on0 = glid < part.L
    x = jnp.asarray(src[on0])
    lelem = jnp.asarray(glid[on0], jnp.int32)
    m = int(on0.sum())
    assert m > 300  # the RCB split leaves a real population on chip 0
    dest = jnp.asarray(  # some cross the partition face -> pauses
        np.clip(src[on0] + rng.normal(scale=0.3, size=(m, 3)), -0.1, 1.1)
    )
    fly = jnp.ones((m,), jnp.int8)
    w = jnp.asarray(rng.uniform(0.5, 2.0, m))
    done0 = jnp.zeros((m,), bool)
    ex0 = jnp.zeros((m,), bool)
    out = {
        meth: walk_local(
            part.table[: part.L], x, lelem, dest, fly, w, done0, ex0,
            jnp.zeros((part.L,)), tally=True, tol=1e-12, max_iters=4096,
            cond_every=2, min_window=64, partition_method=meth,
        )
        for meth in ("rank", "argsort")
    }
    paused = np.asarray(out["rank"][4]) >= 0
    assert paused.any()  # remote pauses actually exercised
    for a, b in zip(out["rank"], out["argsort"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- site (c): migration ------------------------------------------------

def test_migrate_rank_vs_argsort_bitwise():
    """Synthetic migration shuffle with live, paused, and dead slots:
    the direct-scatter rank path must reproduce the sorted path's state
    bit-for-bit, overflow flag included."""
    nparts, cap_b, part_L = 7, 13, 50
    cap = nparts * cap_b
    rng = np.random.default_rng(11)
    pend = np.full(cap, -1, np.int32)
    movers = rng.uniform(size=cap) < 0.3
    pend[movers] = rng.integers(0, nparts * part_L, movers.sum())
    alive = rng.uniform(size=cap) < 0.9
    state = {
        "x": jnp.asarray(rng.random((cap, 3))),
        "dest": jnp.asarray(rng.random((cap, 3))),
        "w": jnp.asarray(rng.random(cap)),
        "lelem": jnp.asarray(rng.integers(0, part_L, cap), jnp.int32),
        "pending": jnp.asarray(pend),
        "pid": jnp.asarray(
            np.where(alive, np.arange(cap), -1), jnp.int32),
        "alive": jnp.asarray(alive),
        "done": jnp.asarray(rng.uniform(size=cap) < 0.5),
        "exited": jnp.asarray(rng.uniform(size=cap) < 0.1),
        "fly": jnp.asarray(rng.integers(0, 2, cap), jnp.int8),
    }
    outs = {}
    for meth in ("rank", "argsort"):
        st, ovf = migrate(part_L=part_L, ndev=nparts, cap_per_chip=cap_b,
                          state=dict(state), partition_method=meth)
        outs[meth] = (st, bool(ovf))
    assert outs["rank"][1] == outs["argsort"][1]
    for k in state:
        np.testing.assert_array_equal(
            np.asarray(outs["rank"][0][k]),
            np.asarray(outs["argsort"][0][k]),
            err_msg=k,
        )


# -- engine-level: all three sites composed -----------------------------

def test_partitioned_engine_rank_vs_argsort_bitwise():
    """8-chip partitioned engine, cascade engaged inside walk_local
    (walk_min_window below the per-chip slot count), migrations across
    chips: flux and positions bitwise identical across methods."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 2000
    rng = np.random.default_rng(5)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), -0.1, 1.1)
    out = {}
    for meth in ("rank", "argsort"):
        t = PartitionedPumiTally(
            mesh, n,
            TallyConfig(device_mesh=make_device_mesh(8),
                        capacity_factor=6.0,
                        walk_partition_method=meth,
                        walk_min_window=64),
        )
        assert t.engine.partition_method == meth
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(),
                             dst.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        out[meth] = (np.asarray(t.flux), t.positions)
    np.testing.assert_array_equal(out["rank"][0], out["argsort"][0])
    np.testing.assert_array_equal(out["rank"][1], out["argsort"][1])


# -- empty-block skip (gather sub-split) --------------------------------

def test_gather_blocked_skips_empty_blocks_and_conserves():
    """Particles clustered in one corner of a finely blocked mesh: the
    per-round block loop must dispatch only occupied blocks (strictly
    fewer than rounds x blocks), with flux conserved and identical to
    the monolithic engine."""
    mesh = build_box(1, 1, 1, 6, 6, 6)  # 1296 tets
    n = 800
    rng = np.random.default_rng(21)
    # Cluster: sources and destinations inside one corner octant.
    src = rng.uniform(0.05, 0.30, (n, 3))
    dst = rng.uniform(0.05, 0.30, (n, 3))
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(walk_vmem_max_elems=100, walk_block_kernel="gather",
                    capacity_factor=20.0),
    )
    blocks = t.engine.nparts
    assert blocks >= 8  # finely blocked, or the skip can't show
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dst.reshape(-1).copy())
    rounds = t.engine.last_walk_rounds
    disp = t.engine.last_block_dispatches
    assert rounds >= 1
    # The skip property: no per-block work for unoccupied blocks. A
    # corner-clustered batch occupies only a few blocks, so dispatches
    # must be well under the full-sweep count...
    assert disp < rounds * blocks, (disp, rounds, blocks)
    # ...but every round walks at least one occupied block.
    assert disp >= rounds
    # Conservation: the clustered move still tallies every segment.
    got = float(np.asarray(t.flux, np.float64).sum())
    want = float(np.linalg.norm(dst - src, axis=1).sum())
    np.testing.assert_allclose(got, want, rtol=1e-9)
    # And parity with the monolithic engine (the existing pattern).
    ref = PumiTally(mesh, n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    ref.MoveToNextLocation(None, dst.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(t.flux, np.float64), np.asarray(ref.flux, np.float64),
        rtol=1e-10, atol=1e-13,
    )


def test_gather_blocked_spread_workload_still_matches():
    """Counter-case to the clustered test: a domain-spanning workload
    (most blocks occupied) through the while_loop block dispatcher
    still matches the monolithic engine — the skip rewrite changed
    scheduling, not physics."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 2000
    rng = np.random.default_rng(23)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = np.clip(src + rng.normal(scale=0.2, size=(n, 3)), -0.1, 1.1)
    ref = PumiTally(mesh, n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    ref.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                           np.ones(n, np.int8), np.ones(n))
    t = PartitionedPumiTally(
        mesh, n,
        TallyConfig(walk_vmem_max_elems=200, walk_block_kernel="gather",
                    capacity_factor=4.0),
    )
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    assert t.engine.last_block_dispatches >= 1
    np.testing.assert_allclose(
        np.asarray(t.flux, np.float64), np.asarray(ref.flux, np.float64),
        rtol=1e-10, atol=1e-13,
    )
