"""Two-tier walk tables: oracle parity, conservation, knob plumbing.

The bf16 select tier + full-precision refinement tier
(docs/PERF_NOTES.md "Table precision tiers", docs/DESIGN.md
select-in-bf16/commit-in-f32 invariant) is NOT bitwise vs the f32
tier: wrong-face selection on sub-bf16-epsilon crossing ties commits
the adjacent neighbor — the documented benign divergence class. What
IS pinned here:

- the BASELINE.md flux oracles reproduce at the reference tolerance
  (the oracle rays cross well-separated faces, so selection is
  unambiguous and the refined commit is full-precision-exact);
- conservation holds at the engines' gate on random workloads, for
  the monolithic, partitioned, and gather-blocked engines;
- the per-element flux divergence vs the f32 arm stays in the
  tie-class band (small L1, not a systematic bias);
- the walk_table_dtype knob resolves at CONFIG time into the static
  jit key (env flip => recompile), mirroring the walk_perm_mode
  plumbing tests;
- the tier build itself: layout, derived properties, astype, and the
  partition's 2x block-element bound.
"""

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig, build_box

NUM = 5
TOL = 1e-8  # reference comparison tolerance (oracle suite)


def _flat(points):
    return np.ascontiguousarray(
        np.asarray(points, dtype=np.float64).reshape(-1)
    )


def _bf16_cfg(**kw):
    return TallyConfig(walk_table_dtype="bfloat16", **kw)


def _random_workload(mesh, n, seed=0):
    lo, hi = mesh.bounding_box()
    rng = np.random.default_rng(seed)
    span = hi - lo
    src = lo + rng.uniform(0.05, 0.95, (n, 3)) * span
    dst = lo + rng.uniform(0.05, 0.95, (n, 3)) * span
    return src, dst


def _run_one_move(cls_or_factory, mesh, n, cfg, src, dst):
    t = cls_or_factory(mesh, n, cfg)
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    return np.asarray(t.flux, np.float64)


# ---------------------------------------------------------------------------
# Tier build (mesh layer)
# ---------------------------------------------------------------------------

def test_lowp_table_build_and_views():
    """with_lowp_tables: layout constants hold, the derived
    face_normals/face_offsets keep FULL precision (they come from the
    refinement tier), the select tier is the bf16 rounding of them,
    and the packed row table is dropped (the tiers replace it)."""
    import jax.numpy as jnp

    from pumiumtally_tpu.mesh.tetmesh import (
        WALK_PLANE_WIDTH,
        WALK_TABLE_LO_WIDTH,
    )

    mesh = build_box(1, 1, 1, 2, 2, 2)
    two = mesh.with_lowp_tables()
    ne = mesh.nelems
    assert two.walk_table is None
    assert two.walk_table_lo.shape == (ne, WALK_TABLE_LO_WIDTH)
    assert two.walk_table_lo.dtype == jnp.bfloat16
    assert two.walk_table_hi.shape == (ne * 4, WALK_PLANE_WIDTH)
    assert two.walk_table_hi.dtype == mesh.coords.dtype
    # Full-precision planes survive the conversion bit-for-bit.
    np.testing.assert_array_equal(
        np.asarray(two.face_normals), np.asarray(mesh.face_normals)
    )
    np.testing.assert_array_equal(
        np.asarray(two.face_offsets), np.asarray(mesh.face_offsets)
    )
    # Select tier == bf16 rounding of the same planes.
    np.testing.assert_array_equal(
        np.asarray(two.walk_table_lo[:, 0:12], np.float64),
        np.asarray(
            mesh.face_normals.reshape(ne, 12).astype(jnp.bfloat16),
            np.float64,
        ),
    )
    # Idempotent; astype round-trips stay two-tier.
    assert two.with_lowp_tables() is two
    f32 = two.astype(np.float32)
    assert f32.walk_table_lo is not None and f32.walk_table is None
    assert f32.walk_table_hi.dtype == jnp.float32
    # from_arrays builds the tiers directly too.
    from pumiumtally_tpu.mesh.box import box_arrays
    from pumiumtally_tpu.mesh.tetmesh import TetMesh

    coords, tets = box_arrays(1, 1, 1, 2, 2, 2)
    direct = TetMesh.from_arrays(coords, tets, table_dtype="bfloat16")
    assert direct.walk_table is None and direct.walk_table_lo is not None
    np.testing.assert_array_equal(
        np.asarray(direct.walk_table_lo, np.float64),
        np.asarray(two.walk_table_lo, np.float64),
    )


# ---------------------------------------------------------------------------
# Oracle parity (BASELINE.md values) + conservation gates
# ---------------------------------------------------------------------------

def test_two_tier_oracle_sequence():
    """The reference's exact-arithmetic flux oracles under the bf16
    tier, at the ORACLE tolerance: the oracle rays cross well-
    separated faces (no bf16-epsilon ties), so the refined commit
    reproduces the full-precision values exactly — this is the
    documented numerical contract, not luck."""
    mesh = build_box(1, 1, 1, 1, 1, 1)
    t = PumiTally(mesh, NUM, _bf16_cfg())
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    t.CopyInitialPosition(_flat(init), 3 * NUM)
    np.testing.assert_array_equal(t.elem_ids, np.full(NUM, 2))
    np.testing.assert_allclose(np.asarray(t.flux), 0.0, atol=TOL)

    dests = np.tile([1.2, 0.4, 0.5], (NUM, 1))
    t.MoveToNextLocation(_flat(init), _flat(dests),
                         np.ones(NUM, np.int8), np.ones(NUM), 3 * NUM)
    np.testing.assert_array_equal(t.elem_ids, np.full(NUM, 4))
    np.testing.assert_allclose(
        t.positions, np.tile([1.0, 0.4, 0.5], (NUM, 1)), atol=TOL
    )
    expected1 = np.array([0.0, 0.0, 0.3 * NUM, 0.1 * NUM, 0.5 * NUM, 0.0])
    np.testing.assert_allclose(np.asarray(t.flux), expected1, atol=TOL)

    origins = np.tile([1.0, 0.4, 0.5], (NUM, 1))
    next_pos = origins.copy()
    flying2 = np.zeros(NUM, dtype=np.int8)
    weights2 = np.ones(NUM)
    next_pos[0] = [0.15, 0.05, 0.20]
    flying2[0], weights2[0] = 1, 2.0
    next_pos[2] = [0.85, 0.05, 0.10]
    flying2[2], weights2[2] = 1, 0.5
    t.MoveToNextLocation(_flat(origins), _flat(next_pos), flying2, weights2,
                         3 * NUM)
    np.testing.assert_allclose(t.positions, next_pos, atol=TOL)
    np.testing.assert_array_equal(t.elem_ids, [3, 4, 4, 4, 4])
    expected2 = expected1.copy()
    expected2[3] += 0.08790490988459178 * 2.0
    expected2[4] += 0.879049070406094 * 2.0 + 0.552268050859363 * 0.5
    np.testing.assert_allclose(np.asarray(t.flux), expected2, atol=TOL)


def test_two_tier_random_parity_and_conservation():
    """Random bench-shaped workload: both arms conserve at the gate;
    the per-element divergence stays in the tie-class band (small L1
    reattribution between face-adjacent elements, no systematic
    bias)."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 4000
    src, dst = _random_workload(mesh, n)
    expect = float(np.linalg.norm(dst - src, axis=1).sum())
    f32 = _run_one_move(PumiTally, mesh, n, TallyConfig(), src, dst)
    bf = _run_one_move(PumiTally, mesh, n, _bf16_cfg(), src, dst)
    assert abs(f32.sum() - expect) / expect < 1e-9
    assert abs(bf.sum() - expect) / expect < 1e-9
    # Tie-class reattribution: ~1e-3 relative L1 observed; 1e-2 is the
    # refuse-a-systematic-bias line, not a precision promise.
    assert np.abs(f32 - bf).sum() / expect < 1e-2


def test_two_tier_partitioned_multichip():
    """The partitioned engine under the bf16 tier on 8 virtual chips:
    conserves at the gate and stays in the tie-class band vs the f32
    partitioned arm."""
    from pumiumtally_tpu import PartitionedPumiTally
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 3000
    src, dst = _random_workload(mesh, n, seed=1)
    expect = float(np.linalg.norm(dst - src, axis=1).sum())

    multi = _run_one_move(
        PartitionedPumiTally, mesh, n,
        _bf16_cfg(device_mesh=make_device_mesh(8), capacity_factor=4.0),
        src, dst,
    )
    assert abs(multi.sum() - expect) / expect < 1e-9
    f32 = _run_one_move(
        PartitionedPumiTally, mesh, n,
        TallyConfig(device_mesh=make_device_mesh(8), capacity_factor=4.0),
        src, dst,
    )
    assert np.abs(f32 - multi).sum() / expect < 1e-2


def test_two_tier_gather_blocked():
    """The single-device gather sub-split under the bf16 tier:
    conserves, derives blocks from 2x the f32 element bound (same
    resident bytes at half the row width), and routes around the vmem
    kernel (no two-tier lowering)."""
    from pumiumtally_tpu import PartitionedPumiTally

    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 3000
    src, dst = _random_workload(mesh, n, seed=1)
    expect = float(np.linalg.norm(dst - src, axis=1).sum())

    t = PartitionedPumiTally(
        mesh, n,
        _bf16_cfg(capacity_factor=4.0, walk_vmem_max_elems=100),
    )
    # vmem has no two-tier lowering: rerouted to the gather kernel,
    # with the block bound doubled (100 -> 200 elements per block).
    assert t.engine.block_kernel == "gather"
    assert t.engine.two_tier
    from pumiumtally_tpu.parallel.partition import derive_blocks_per_chip

    f32_blocks = derive_blocks_per_chip(mesh.nelems, 1, 100)
    assert t.engine.blocks_per_chip == derive_blocks_per_chip(
        mesh.nelems, 1, 200
    )
    assert t.engine.blocks_per_chip < f32_blocks
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    blocked = np.asarray(t.flux, np.float64)
    assert abs(blocked.sum() - expect) / expect < 1e-9
    # Tie-class band vs the monolithic f32 walk on the same workload.
    f32 = _run_one_move(PumiTally, mesh, n, TallyConfig(), src, dst)
    assert np.abs(f32 - blocked).sum() / expect < 1e-2


def test_two_tier_hull_exit_divergence_bounded():
    """The documented hull-exit caveat (PERF_NOTES tie anatomy): under
    the bf16 tier a small fraction of boundary-EXITING particles may
    terminate slightly inside the hull (wrong-corridor dead end). Pin
    the BOUNDS: rate a few percent of exits, magnitude a few percent
    of a segment, total flux within 1e-3 of the f32 arm — a regression
    past these means the selection/refinement contract broke, not just
    a tie."""
    mesh = build_box(1, 1, 1, 6, 6, 6)
    n = 4000
    rng = np.random.default_rng(3)
    src = rng.uniform(0.05, 0.95, (n, 3))
    dst = rng.uniform(0.0, 1.4, (n, 3))  # many exit the hull
    out = {}
    for label, cfg in (("f32", TallyConfig()), ("bf16", _bf16_cfg())):
        t = PumiTally(mesh, n, cfg)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                             np.ones(n, np.int8), np.ones(n))
        out[label] = (t.positions, np.asarray(t.flux, np.float64))
    exited = (dst > 1.0).any(axis=1)
    assert exited.sum() > 500  # the probe must actually probe exits
    x32, xbf = out["f32"][0], out["bf16"][0]
    # f32: every exiting particle commits ON the hull.
    assert np.isclose(x32[exited].max(axis=1), 1.0, atol=1e-5).all()
    # bf16: bounded dead-end tail, not a systematic drift.
    inside = 1.0 - xbf[exited].max(axis=1)
    assert np.mean(inside > 1e-5) < 0.05  # rate: a few % of exits
    assert inside.max() < 0.2  # magnitude: a fraction of one segment
    f32_sum, bf_sum = out["f32"][1].sum(), out["bf16"][1].sum()
    assert abs(f32_sum - bf_sum) / f32_sum < 1e-3


def test_two_tier_requires_lo_tables():
    """A direct walk() call asking for the bf16 tier on a mesh without
    the tiers must refuse loudly (a silent f32 fallback would
    invalidate every A/B built on the knob)."""
    import jax.numpy as jnp

    from pumiumtally_tpu.ops.walk import walk

    mesh = build_box(1, 1, 1, 1, 1, 1)
    n = 4
    with pytest.raises(ValueError, match="two-tier"):
        walk(
            mesh,
            jnp.zeros((n, 3), mesh.coords.dtype),
            jnp.zeros((n,), jnp.int32),
            jnp.zeros((n, 3), mesh.coords.dtype),
            jnp.ones((n,), jnp.int8),
            jnp.ones((n,), mesh.coords.dtype),
            jnp.zeros((mesh.nelems,), mesh.coords.dtype),
            tally=True, tol=1e-8, max_iters=8,
            table_dtype="bfloat16",
        )
    # The tier build refuses when neighbor ids cannot be exact in the
    # refinement rows' float adj lane (same ceiling as the packed
    # layout — enforced, not silently corrupted). f32's limit is 2^24;
    # fake a tiny limit to exercise the guard at test size.
    import pumiumtally_tpu.mesh.tetmesh as tm

    orig = tm._exact_id_limit
    tm._exact_id_limit = lambda dtype: 4
    try:
        with pytest.raises(ValueError, match="exact-id"):
            mesh.with_lowp_tables()
    finally:
        tm._exact_id_limit = orig


# ---------------------------------------------------------------------------
# Knob plumbing (mirrors the walk_perm_mode env-resolution tests)
# ---------------------------------------------------------------------------

def test_table_dtype_env_resolves_in_walk_kwargs(monkeypatch):
    """PUMIUMTALLY_WALK_TABLE_DTYPE must resolve at CONFIG resolution
    (into the static jit key), not at trace time — an env flip in a
    running process then recompiles instead of silently reusing the
    stale tier (same contract as PUMIUMTALLY_WALK_PERM)."""
    monkeypatch.delenv("PUMIUMTALLY_WALK_TABLE_DTYPE", raising=False)
    assert TallyConfig().walk_kwargs() == ()
    # An explicit default-equal tier normalizes away (cache-key parity).
    assert TallyConfig(walk_table_dtype="float32").walk_kwargs() == ()
    monkeypatch.setenv("PUMIUMTALLY_WALK_TABLE_DTYPE", "bfloat16")
    assert ("table_dtype", "bfloat16") in TallyConfig().walk_kwargs()
    assert ("table_dtype", "bfloat16") in TallyConfig(
        walk_table_dtype="auto"
    ).walk_kwargs()
    # An explicit DEFAULT tier under a contrary env var must still be
    # emitted (the kernel's trace-time fallback would otherwise
    # override the explicit choice).
    assert ("table_dtype", "float32") in TallyConfig(
        walk_table_dtype="float32"
    ).walk_kwargs()
    # The facades' mesh conversion follows the same resolution.
    assert TallyConfig().resolved_table_dtype() == "bfloat16"
    assert TallyConfig(
        walk_table_dtype="float32"
    ).resolved_table_dtype() == "float32"
    # A bogus env value fails loudly at config resolution.
    monkeypatch.setenv("PUMIUMTALLY_WALK_TABLE_DTYPE", "f16")
    with pytest.raises(ValueError):
        TallyConfig().walk_kwargs()
    with pytest.raises(ValueError):
        TallyConfig(walk_table_dtype="bogus")


def test_table_dtype_env_flip_recompiles(monkeypatch):
    """End to end: flipping the env var between two engines over the
    same mesh shape changes the static jit key, so the second engine
    COMPILES rather than silently reusing the f32 program (the
    retrace-tripwire budgets in config.py already admit the two keys).
    """
    from pumiumtally_tpu.utils.profiling import retrace_guard

    mesh = build_box(1, 1, 1, 2, 2, 2)
    n = 64
    src, dst = _random_workload(mesh, n, seed=2)

    def drive(cfg):
        t = PumiTally(mesh, n, cfg)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dst.reshape(-1).copy())
        return float(np.asarray(t.flux, np.float64).sum())

    monkeypatch.delenv("PUMIUMTALLY_WALK_TABLE_DTYPE", raising=False)
    drive(TallyConfig())  # prime the f32 jit cache for this shape
    with retrace_guard(raise_on_exceed=False) as g:
        monkeypatch.setenv("PUMIUMTALLY_WALK_TABLE_DTYPE", "bfloat16")
        drive(TallyConfig())
    assert g.compiles.get("walk_continue", 0) >= 1
    # Flipping back reuses the pre-flip cache: zero new compiles.
    with retrace_guard(raise_on_exceed=False) as g2:
        monkeypatch.delenv("PUMIUMTALLY_WALK_TABLE_DTYPE", raising=False)
        drive(TallyConfig())
    assert g2.compiles.get("walk_continue", 0) == 0


def test_autotune_sweeps_but_does_not_adopt_bf16():
    """The autotuner measures the bf16-tier candidate (the chip window
    needs its rate) but must not ADOPT it without allow_approximate —
    tuning's default contract is that it never changes physics."""
    from pumiumtally_tpu.utils.autotune import autotune_walk

    mesh = build_box(1, 1, 1, 2, 2, 2)
    cands = [
        {"walk_cond_every": 2},
        {"walk_table_dtype": "bfloat16"},
    ]
    cfg, report = autotune_walk(
        mesh, n_particles=256, moves=1, candidates=cands
    )
    assert {"walk_table_dtype": "bfloat16"} in [r["knobs"] for r in report]
    assert cfg.walk_table_dtype is None
    cfg2, _ = autotune_walk(
        mesh, n_particles=256, moves=1,
        candidates=[{"walk_table_dtype": "bfloat16"}],
        allow_approximate=True,
    )
    assert cfg2.walk_table_dtype == "bfloat16"


def test_xpoints_replay_matches_two_tier_transport():
    """The intersection-points replay must run the SAME tier as the
    transport (the shared-advance contract): under the bf16 tier the
    oracle ray's last crossing is still the boundary point."""
    mesh = build_box(1, 1, 1, 1, 1, 1)
    t = PumiTally(mesh, NUM, _bf16_cfg(record_xpoints=True))
    init = np.tile([0.1, 0.4, 0.5], (NUM, 1))
    t.CopyInitialPosition(_flat(init), 3 * NUM)
    dests = np.tile([1.2, 0.4, 0.5], (NUM, 1))
    t.MoveToNextLocation(_flat(init), _flat(dests),
                         np.ones(NUM, np.int8), np.ones(NUM))
    np.testing.assert_allclose(
        t.intersection_points(), np.tile([1.0, 0.4, 0.5], (NUM, 1)),
        atol=TOL,
    )
