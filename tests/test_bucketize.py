"""ops/bucketize.py: counting-rank partitions == stable argsort, exactly.

The whole sort-free redistribution story rests on one integer-level
identity: for keys over a small alphabet, the counting-rank destination
``starts[key] + rank`` reproduces the stable-argsort permutation
bit-for-bit. These tests pin that identity across alphabet sizes
(including the slabbed path for large alphabets), jit, and the
degenerate corners; the site-level bitwise tests live in
test_partition_rank.py.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from pumiumtally_tpu.ops.bucketize import (
    _RANK_SLAB,
    bucket_destinations,
    counting_ranks,
    partition_perm,
    unpermute,
)


@pytest.mark.parametrize(
    "k", [2, 3, 17, _RANK_SLAB, _RANK_SLAB + 1, 3 * _RANK_SLAB + 5]
)
def test_rank_matches_argsort_machinery(k):
    rng = np.random.default_rng(k)
    key = jnp.asarray(rng.integers(0, k, 4001), jnp.int32)
    r_rank = counting_ranks(key, k, method="rank")
    r_sort = counting_ranks(key, k, method="argsort")
    np.testing.assert_array_equal(np.asarray(r_rank), np.asarray(r_sort))
    perm, counts, starts = partition_perm(key, k, method="rank")
    np.testing.assert_array_equal(
        np.asarray(perm), np.asarray(jnp.argsort(key, stable=True))
    )
    np.testing.assert_array_equal(
        np.asarray(counts), np.bincount(np.asarray(key), minlength=k)
    )
    dest, _, _ = bucket_destinations(key, k, method="rank")
    # dest is a permutation of iota — every slot gets a unique position.
    np.testing.assert_array_equal(
        np.sort(np.asarray(dest)), np.arange(key.shape[0])
    )
    # Scatter-to-dest == gather-through-perm == stable sort.
    vals = jnp.asarray(rng.random(key.shape[0]))
    np.testing.assert_array_equal(
        np.asarray(jnp.zeros_like(vals).at[dest].set(vals)),
        np.asarray(vals[perm]),
    )


def test_stability_within_bucket():
    """Equal keys keep their original slot order (the property the
    cascade and migration correctness proofs rely on)."""
    key = jnp.asarray([1, 0, 1, 1, 0, 2, 0, 1], jnp.int32)
    dest, _, starts = bucket_destinations(key, 3)
    d = np.asarray(dest)
    for b in range(3):
        slots = np.flatnonzero(np.asarray(key) == b)
        np.testing.assert_array_equal(
            d[slots], int(starts[b]) + np.arange(slots.size)
        )


def test_single_bucket_and_empty_buckets():
    key = jnp.zeros((17,), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(counting_ranks(key, 5)), np.arange(17)
    )
    # Bucket 1..4 empty: starts collapse, dest still the identity.
    dest, counts, _ = bucket_destinations(key, 5)
    np.testing.assert_array_equal(np.asarray(dest), np.arange(17))
    assert int(counts[0]) == 17 and int(jnp.sum(counts[1:])) == 0


def test_unpermute_inverts_accumulated_permutation():
    rng = np.random.default_rng(9)
    idx = jnp.asarray(rng.permutation(513), jnp.int32)
    vals = jnp.asarray(rng.random((513, 3)))
    out = unpermute(vals, idx)
    # Row i held original slot idx[i]; the scatter must equal the
    # argsort-inverse gather the seed used.
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(vals)[np.argsort(np.asarray(idx))]
    )


def test_under_jit_and_method_validation():
    key = jnp.asarray([0, 2, 1, 2, 0], jnp.int32)
    f = jax.jit(lambda k: partition_perm(k, 3)[0])
    np.testing.assert_array_equal(
        np.asarray(f(key)), np.asarray(jnp.argsort(key, stable=True))
    )
    with pytest.raises(ValueError, match="method"):
        counting_ranks(key, 3, method="radix")
