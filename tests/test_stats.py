"""Batch-statistics subsystem (pumiumtally_tpu/stats): accumulator
math vs a numpy reference, the stats-off bitwise-parity contract on
every engine, cross-engine statistics equivalence, trigger-based early
stop on the box workload, and the VTK statistics payload.
"""

import numpy as np
import pytest

from pumiumtally_tpu import (
    PartitionedPumiTally,
    PumiTally,
    StreamingPartitionedTally,
    StreamingTally,
    TallyConfig,
    TriggerSpec,
    build_box,
)
from pumiumtally_tpu.parallel import make_device_mesh

N = 240
MESH_ARGS = (1, 1, 1, 4, 4, 4)


def _random_batches(rng, batches: int, moves: int):
    """(src, [(dests, weights), ...]) per batch — fresh random samples
    each batch, the statistics workload."""
    out = []
    for _ in range(batches):
        src = rng.uniform(0.1, 0.9, (N, 3))
        segs = [
            (rng.uniform(0.1, 0.9, (N, 3)), rng.uniform(0.5, 1.5, N))
            for _ in range(moves)
        ]
        out.append((src, segs))
    return out


def _drive(t, work, close_each=False, trigger=None):
    results = []
    for src, segs in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d, w in segs:
            t.MoveToNextLocation(None, d.reshape(-1).copy(), None, w.copy())
        if close_each:
            results.append(t.close_batch(trigger))
    return results


ENGINE_NAMES = (
    "monolithic", "sharded", "streaming", "partitioned",
    "streaming_partitioned",
)


def _make_engine(name: str, stats: bool):
    cfg = lambda **kw: TallyConfig(batch_stats=stats, **kw)
    mesh = build_box(*MESH_ARGS)
    if name == "monolithic":
        return PumiTally(mesh, N, cfg())
    if name == "sharded":
        return PumiTally(mesh, N, cfg(device_mesh=make_device_mesh(2)))
    if name == "streaming":
        return StreamingTally(mesh, N, chunk_size=120, config=cfg())
    if name == "partitioned":
        return PartitionedPumiTally(
            mesh, N,
            cfg(device_mesh=make_device_mesh(4), capacity_factor=4.0),
        )
    return StreamingPartitionedTally(
        mesh, N, chunk_size=120,
        config=cfg(device_mesh=make_device_mesh(4), capacity_factor=4.0),
    )


# ---------------------------------------------------------------------------
# Accumulator math
# ---------------------------------------------------------------------------

def test_estimators_match_numpy_reference():
    """mean / std dev / rel err from the on-device lanes must equal the
    numpy statistics of the actual per-batch flux deltas."""
    t = PumiTally(build_box(*MESH_ARGS), N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(3)
    work = _random_batches(rng, 5, 2)
    deltas = []
    prev = np.zeros(6 * 4**3)
    for src, segs in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        for d, w in segs:
            t.MoveToNextLocation(None, d.reshape(-1).copy(), None, w.copy())
        now = np.asarray(t.flux, np.float64)
        deltas.append(now - prev)
        prev = now
        t.close_batch()
    st = t.finalize()
    assert st.num_batches == 5
    x = np.stack(deltas)  # [B, E]
    np.testing.assert_allclose(np.asarray(st.mean), x.mean(0), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(st.std_dev), x.std(0, ddof=1), rtol=1e-9, atol=1e-13
    )
    re = np.asarray(st.rel_err)
    scored = x.mean(0) > 0
    expect = x.std(0, ddof=1)[scored] / np.sqrt(5) / x.mean(0)[scored]
    np.testing.assert_allclose(re[scored], expect, rtol=1e-9, atol=1e-13)
    assert np.all(np.isinf(re[~scored]))
    # FOM: finite and positive exactly where RE is finite and nonzero.
    fom = np.asarray(st.figure_of_merit)
    assert np.all(fom[scored][expect > 0] > 0)
    assert np.all(fom[~scored] == 0.0)


def test_empty_batch_is_not_a_sample():
    """A close with zero moves since open must leave the lanes and the
    batch counter untouched (a structural zero would bias RE low)."""
    t = PumiTally(build_box(*MESH_ARGS), N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(4)
    _drive(t, _random_batches(rng, 2, 1), close_each=True)
    assert t._stats.num_batches == 2
    before = np.asarray(t._stats.flux_sum).copy()
    t.close_batch()  # nothing moved since the last close
    t.close_batch()
    assert t._stats.num_batches == 2
    np.testing.assert_array_equal(np.asarray(t._stats.flux_sum), before)
    # CopyInitialPosition with no subsequent move also closes as no-op.
    t.CopyInitialPosition(
        rng.uniform(0.1, 0.9, (N, 3)).reshape(-1).copy()
    )
    t.CopyInitialPosition(
        rng.uniform(0.1, 0.9, (N, 3)).reshape(-1).copy()
    )
    assert t._stats.num_batches == 2


def test_copy_initial_position_rolls_batches():
    """Batch boundaries WITHOUT explicit close_batch calls: each
    CopyInitialPosition closes the previous source batch; finalize
    closes the last. 3 sourcings + finalize == 3 batches."""
    t = PumiTally(build_box(*MESH_ARGS), N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(5)
    _drive(t, _random_batches(rng, 3, 2), close_each=False)
    assert t._stats.num_batches == 2  # first two closed by re-sourcing
    st = t.finalize()
    assert st.num_batches == 3
    # finalize left no batch open: further moves are unattributed.
    assert not t._stats.batch_open


def test_stats_disabled_surface_raises():
    t = PumiTally(build_box(*MESH_ARGS), N)
    with pytest.raises(RuntimeError, match="batch_stats=True"):
        t.close_batch()
    with pytest.raises(RuntimeError, match="batch_stats=True"):
        t.batch_statistics()
    with pytest.raises(RuntimeError, match="batch_stats=True"):
        t.finalize()


def test_trigger_spec_validation():
    with pytest.raises(ValueError, match="metric"):
        TriggerSpec(threshold=0.1, metric="variance")
    with pytest.raises(ValueError, match="threshold"):
        TriggerSpec(threshold=0.0)
    with pytest.raises(ValueError, match="quantile"):
        TriggerSpec(threshold=0.1, quantile=0.0)
    with pytest.raises(ValueError, match="TriggerSpec"):
        TallyConfig(batch_stats=True, batch_stats_trigger=0.1)
    with pytest.raises(ValueError, match="batch_stats=True"):
        TallyConfig(batch_stats_trigger=TriggerSpec(threshold=0.1))


# ---------------------------------------------------------------------------
# The parity contract: stats-off == stats-on engine state, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", ENGINE_NAMES)
def test_stats_never_perturb_engine_state(name):
    """The acceptance contract on every engine: enabling batch_stats
    (accumulating + closing batches throughout) leaves flux, positions
    and element ids BITWISE identical to the stats-less run — the
    subsystem only ever reads the engine."""
    rng = np.random.default_rng(11)
    work = _random_batches(rng, 2, 2)
    t_off = _make_engine(name, False)
    t_on = _make_engine(name, True)
    _drive(t_off, work, close_each=False)
    _drive(t_on, work, close_each=True,
           trigger=TriggerSpec(threshold=0.5))
    np.testing.assert_array_equal(
        np.asarray(t_on.flux), np.asarray(t_off.flux)
    )
    np.testing.assert_array_equal(t_on.positions, t_off.positions)
    np.testing.assert_array_equal(t_on.elem_ids, t_off.elem_ids)


@pytest.mark.parametrize("name", [n for n in ENGINE_NAMES
                                  if n != "monolithic"])
def test_cross_engine_statistics_agree(name):
    """The same batches through different engines yield the same
    statistics (engines agree on flux to rounding; the lanes are
    derived from flux alone). One engine per test, each against the
    monolithic reference — building every engine in one test would
    blow the per-test retrace budgets for the ENGINE entry points
    (five partitioned phase program sets), which budget the statistics
    tests like any other."""
    rng = np.random.default_rng(12)
    work = _random_batches(rng, 3, 2)
    base_t = _make_engine("monolithic", True)
    _drive(base_t, work, close_each=True)
    base = base_t.finalize()
    t = _make_engine(name, True)
    _drive(t, work, close_each=True)
    st = t.finalize()
    base_re = np.asarray(base.rel_err)
    finite = np.isfinite(base_re)
    assert st.num_batches == base.num_batches
    np.testing.assert_allclose(
        np.asarray(st.mean), np.asarray(base.mean),
        rtol=1e-11, atol=1e-13,
    )
    re = np.asarray(st.rel_err)
    np.testing.assert_array_equal(np.isfinite(re), finite)
    np.testing.assert_allclose(
        re[finite], base_re[finite], rtol=1e-6, atol=1e-10
    )


# ---------------------------------------------------------------------------
# Trigger-based early stop (acceptance: the box workload)
# ---------------------------------------------------------------------------

def test_trigger_early_stop_on_box_workload():
    """Monotone relative-error decay, stop at the threshold, and the
    1/sqrt(N)-law batches-remaining projection within 2x of what
    actually happened. Deterministic alternating-weight batches
    (identical geometry, weights 1.0/1.2) make the decay exactly
    monotone: RE ~ (0.1/1.1)/sqrt(N-1)."""
    t = PumiTally(
        build_box(*MESH_ARGS), N,
        TallyConfig(batch_stats=True,
                    batch_stats_trigger=TriggerSpec(threshold=0.035)),
    )
    rng = np.random.default_rng(13)
    src = rng.uniform(0.1, 0.9, (N, 3))
    dst = rng.uniform(0.1, 0.9, (N, 3))
    values, projection, actual = [], None, None
    for b in range(40):
        w = np.full(N, 1.0 if b % 2 == 0 else 1.2)
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dst.reshape(-1).copy(), None, w)
        res = t.close_batch()  # config trigger applies
        assert res.num_batches == b + 1
        if np.isfinite(res.value):
            values.append(res.value)
        if projection is None and res.batches_remaining not in (None, 0):
            projection = res.num_batches + res.batches_remaining
        if res.converged:
            assert res.batches_remaining == 0
            actual = res.num_batches
            break
    assert actual is not None, "trigger never fired in 40 batches"
    assert values[-1] <= 0.035
    # Monotone decay of the relative error across closes.
    assert all(b < a for a, b in zip(values, values[1:])), values
    # The first projection's implied total within 2x of the actual.
    assert projection is not None
    assert actual / 2 <= projection <= actual * 2, (projection, actual)


def test_trigger_quantile_and_std_err_metrics():
    """quantile < 1 can only LOOSEN the criterion (a lower quantile of
    the per-element metric), and the std_err metric evaluates the
    STANDARD ERROR of the mean in flux units (sample std dev /
    sqrt(N) — deliberately not named after the estimator surface's
    std_dev); both share the evaluation machinery."""
    from pumiumtally_tpu.stats.triggers import evaluate_trigger

    t = PumiTally(build_box(*MESH_ARGS), N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(14)
    _drive(t, _random_batches(rng, 4, 2), close_each=True)
    stats = t._stats
    v_max = evaluate_trigger(stats, TriggerSpec(threshold=1e-9)).value
    v_med = evaluate_trigger(
        stats, TriggerSpec(threshold=1e-9, quantile=0.5)
    ).value
    assert np.isfinite(v_max) and np.isfinite(v_med)
    assert v_med <= v_max
    # Quantiles of the fetched per-element estimator agree with numpy.
    re = np.asarray(t.batch_statistics().rel_err)
    scored = np.sort(re[np.isfinite(re)])
    np.testing.assert_allclose(v_max, scored[-1], rtol=1e-12)
    np.testing.assert_allclose(
        v_med, scored[int(np.ceil(0.5 * scored.size)) - 1], rtol=1e-12
    )
    sd = evaluate_trigger(
        stats, TriggerSpec(threshold=1e-9, metric="std_err")
    ).value
    sem = np.asarray(t.batch_statistics().std_dev) / np.sqrt(4)
    np.testing.assert_allclose(
        sd, np.max(sem[np.isfinite(re)]), rtol=1e-12
    )


def test_negative_flux_elements_stay_scored():
    """Negative-weight (variance reduction) workloads can leave
    net-negative elements; those are SCORED — rel_err = sem/|mean| is
    finite and the trigger's quantile includes them. Only an
    exactly-zero mean is unscored."""
    from pumiumtally_tpu.stats.triggers import evaluate_trigger

    t = PumiTally(build_box(*MESH_ARGS), N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(18)
    for b in range(3):
        src = rng.uniform(0.1, 0.9, (N, 3))
        dst = rng.uniform(0.1, 0.9, (N, 3))
        w = np.full(N, -1.0 - 0.1 * b)  # all-negative weights
        t.CopyInitialPosition(src.reshape(-1).copy())
        t.MoveToNextLocation(None, dst.reshape(-1).copy(), None, w)
        t.close_batch()
    st = t.batch_statistics()
    mean = np.asarray(st.mean)
    re = np.asarray(st.rel_err)
    neg = mean < 0
    assert neg.any()  # the workload actually produced negative flux
    assert np.all(np.isfinite(re[neg]))  # scored, not inf
    np.testing.assert_array_equal(np.isinf(re), mean == 0.0)
    # And the trigger's max-quantile reflects them too.
    res = evaluate_trigger(t._stats, TriggerSpec(threshold=1e-9))
    np.testing.assert_allclose(
        res.value, np.max(re[np.isfinite(re)]), rtol=1e-12
    )


def test_trigger_needs_two_batches():
    """Fewer than 2 closed batches: unconverged, value inf, no
    projection — and no device work at all."""
    t = PumiTally(build_box(*MESH_ARGS), N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(15)
    res = t.close_batch(TriggerSpec(threshold=0.1))
    assert not res.converged and np.isinf(res.value)
    assert res.batches_remaining is None and res.num_batches == 0
    _drive(t, _random_batches(rng, 1, 1), close_each=False)
    res = t.close_batch(TriggerSpec(threshold=0.1))
    assert not res.converged and res.num_batches == 1
    assert res.batches_remaining is None


# ---------------------------------------------------------------------------
# VTK payload
# ---------------------------------------------------------------------------

def test_write_tally_results_stats_arrays(tmp_path):
    """With >= 2 closed batches the written file carries flux_mean and
    rel_err cell arrays beside flux+volume; flux_mean is
    volume-normalized like flux, and unscored elements write rel_err
    0.0 (not inf)."""
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars

    t = PumiTally(build_box(*MESH_ARGS), N, TallyConfig(batch_stats=True))
    rng = np.random.default_rng(16)
    _drive(t, _random_batches(rng, 3, 2), close_each=True)
    out = str(tmp_path / "stats.vtk")
    t.WriteTallyResults(out)
    st = t.batch_statistics()
    vol = np.asarray(t.mesh.volumes)
    np.testing.assert_allclose(
        read_vtk_cell_scalars(out, "flux_mean"),
        np.asarray(st.mean) / vol, rtol=1e-12,
    )
    re = np.asarray(st.rel_err)
    expect = np.where(np.isfinite(re), re, 0.0)
    np.testing.assert_allclose(
        read_vtk_cell_scalars(out, "rel_err"), expect, rtol=1e-12
    )
    # The reference payload is still there, unchanged.
    np.testing.assert_allclose(
        read_vtk_cell_scalars(out, "flux"),
        np.asarray(t.flux) / vol, rtol=1e-12,
    )


def test_write_tally_results_default_payload_unchanged(tmp_path):
    """Stats disabled (and stats enabled with zero closed batches):
    the file carries exactly the reference's flux+volume arrays."""
    from pumiumtally_tpu.io.vtk import read_vtk_cell_scalars

    for cfg in (TallyConfig(), TallyConfig(batch_stats=True)):
        t = PumiTally(build_box(*MESH_ARGS), N, cfg)
        rng = np.random.default_rng(17)
        _drive(t, _random_batches(rng, 1, 1), close_each=False)
        out = str(tmp_path / f"plain_{cfg.batch_stats}.vtk")
        t.WriteTallyResults(out)
        assert read_vtk_cell_scalars(out, "flux").size
        with pytest.raises(KeyError):
            read_vtk_cell_scalars(out, "flux_mean")
