"""Traffic engineering for the multi-session service (round 20).

Contracts pinned here (docs/DESIGN.md "Chunk-wise fusion & traffic
engineering"):

- strict priority between lanes: the highest lane with queued work
  serves; lower lanes wait at op granularity (never mid-op);
- DRR within a lane is the flat round-11 algorithm unchanged — and a
  skipped idle lane forfeits banked CREDIT exactly like an emptied
  ring visit, while co-fusion DEBT follows the session;
- the low-lane starvation bound: a LOW session whose head is
  fusion-compatible with a HIGH lead rides the shared launch
  (pre-paying its own cost); incompatible low work waits for the high
  lane to drain — both at the scheduler and through a live service,
  where the mixed-priority fused campaign stays bitwise per session;
- admission control: with a budget armed, a transport op that would
  exceed it refuses with ``ServiceOverloadedError`` (budget/admitted/
  cost attributes) BEFORE any state changes — the caller's flying
  buffer is untouched, reads and the close sentinel are never
  refused, and the budget frees as the worker completes ops;
- telemetry: ``stats()`` exposes per-session priority/queued_cost and
  p50/p99 submit->resolve latency; the NDJSON ``ping`` reply carries
  the aggregate load the router's least-loaded placement reads, and
  overload refusals answer ``"overloaded": true`` on the wire;
- SIGTERM drain UNDER LOAD: a stream-pair campaign running with
  priority lanes and a near-full admission budget drains to one
  batch-aligned generation per session and resumes bitwise
  (subprocess, tests/_service_driver.py --stream-pair).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pumiumtally_tpu import (
    PumiTally,
    StreamingTally,
    TallyConfig,
    TallyService,
    build_box,
)
from pumiumtally_tpu.service import (
    DeficitRoundRobinScheduler,
    Priority,
    ServiceOverloadedError,
    SocketFrontend,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "_service_driver.py")

N = 64


def _mesh():
    return build_box(1.0, 1.0, 1.0, 3, 3, 3)


def _cfg(**kw):
    return TallyConfig(check_found_all=False, **kw)


def _campaign(seed, batches=1, moves=2, n=N):
    rng = np.random.default_rng(seed)
    return [
        (rng.uniform(0.1, 0.9, n * 3),
         [rng.uniform(0.1, 0.9, n * 3) for _ in range(moves)])
        for _ in range(batches)
    ]


# ---------------------------------------------------------------------------
# Scheduler lanes (pure data structure)
# ---------------------------------------------------------------------------

class _Q:
    """A scripted head-cost oracle: pop-on-pick queues per key."""

    def __init__(self, costs):
        self.q = {k: list(v) for k, v in costs.items()}

    def head(self, k):
        return self.q[k][0] if self.q[k] else None

    def pop(self, k):
        return self.q[k].pop(0)


def test_strict_priority_between_lanes():
    """The highest lane with queued work serves; lower lanes advance
    only once every lane above them is empty."""
    s = DeficitRoundRobinScheduler()
    s.register("lo", priority=Priority.LOW)
    s.register("n1", priority=Priority.NORMAL)
    s.register("hi", priority=Priority.HIGH)
    assert s.priority("hi") is Priority.HIGH
    assert s.priority("n1") is Priority.NORMAL
    q = _Q({"hi": [2, 2], "n1": [3, 3], "lo": [1, 1, 1]})
    order = []
    while True:
        k = s.pick(q.head)
        if k is None:
            break
        q.pop(k)
        order.append(k)
    assert order == ["hi", "hi", "n1", "n1", "lo", "lo", "lo"]


def test_lane_preempts_at_op_granularity():
    """Work landing in a higher lane mid-campaign preempts the lower
    lane at the next pick — the in-flight op always finishes."""
    s = DeficitRoundRobinScheduler()
    s.register("hi", priority=Priority.HIGH)
    s.register("lo", priority=Priority.LOW)
    q = _Q({"hi": [], "lo": [4, 4, 4]})
    assert s.pick(q.head) == "lo"
    q.pop("lo")
    q.q["hi"] = [4, 4]  # urgent work arrives
    assert s.pick(q.head) == "hi"
    q.pop("hi")
    assert s.pick(q.head) == "hi"
    q.pop("hi")
    assert s.pick(q.head) == "lo"


def test_skipped_idle_lane_forfeits_credit_keeps_debt():
    """An idle higher lane forfeits banked CREDIT when a lower lane
    serves (idle banks no credit), but co-fusion DEBT is kept."""
    s = DeficitRoundRobinScheduler(quantum=3)
    s.register("hi", priority=Priority.HIGH)
    s.register("lo", priority=Priority.LOW)
    # hi serves a cost-5 op with quantum 3: two credits, one debit,
    # leaving 1 unit of banked credit.
    q = _Q({"hi": [5], "lo": [2]})
    assert s.pick(q.head) == "hi"
    q.pop("hi")
    assert s.deficit("hi") == 1
    # hi is now idle; the LOW lane serves — hi's credit is forfeited.
    assert s.pick(q.head) == "lo"
    q.pop("lo")
    assert s.deficit("hi") == 0

    # Debt survives the same transition: h2 pre-pays a ride on h1's
    # fused launch, empties, and still owes when the low lane serves.
    s2 = DeficitRoundRobinScheduler()
    s2.register("h1", priority=Priority.HIGH)
    s2.register("h2", priority=Priority.HIGH)
    s2.register("lo", priority=Priority.LOW)
    q2 = _Q({"h1": [4], "h2": [4], "lo": [2]})

    def gk(k):
        # lo's head is a different composition — it never co-fuses.
        return ("K" if k != "lo" and q2.head(k) is not None else None)

    g = s2.pick_group(q2.head, gk, 8)
    assert sorted(g) == ["h1", "h2"]
    for k in g:
        q2.pop(k)
    lead = g[0]
    rider = g[1]
    assert s2.deficit(rider) == -4  # pre-paid, not yet credited
    assert s2.pick(q2.head) == "lo"
    q2.pop("lo")
    assert s2.deficit(rider) == -4  # debt kept across the lane switch
    assert s2.deficit(lead) == 0


def test_low_lane_ride_along_bound_under_saturated_high():
    """The starvation bound, end to end at the scheduler: compatible
    LOW heads ride every HIGH-led fused launch; incompatible LOW work
    waits for the high lane to drain, then serves first (its sibling
    carries ride-along debt)."""
    s = DeficitRoundRobinScheduler()
    s.register("h1", priority=Priority.HIGH)
    s.register("h2", priority=Priority.HIGH)
    s.register("lo_compat", priority=Priority.LOW)
    s.register("lo_other", priority=Priority.LOW)
    rounds = 6
    q = _Q({
        "h1": [4] * rounds, "h2": [4] * rounds,
        "lo_compat": [4] * rounds, "lo_other": [4] * rounds,
    })
    keys = {"h1": "K", "h2": "K", "lo_compat": "K", "lo_other": "X"}

    def gk(k):
        return keys[k] if q.head(k) is not None else None

    served = {k: 0 for k in keys}
    for _ in range(rounds):
        g = s.pick_group(q.head, gk, 8)
        assert sorted(g) == ["h1", "h2", "lo_compat"]
        for k in g:
            q.pop(k)
            served[k] += 1
    # The compatible LOW session advanced at the fused cadence; the
    # incompatible one did not move while the high lane was saturated.
    assert served["lo_compat"] == rounds
    assert served["lo_other"] == 0
    assert s.deficit("lo_compat") == -4 * rounds
    # High lane drained: lo_other serves FIRST (lo_compat owes its
    # ride-along debt), and alone (keys differ).
    g = s.pick_group(q.head, gk, 8)
    assert g == ["lo_other"]
    q.pop("lo_other")


def test_all_normal_is_the_flat_scheduler():
    """Default-priority registration reproduces the flat round-11
    pick sequence bit for bit (same costs as the exact-deficit pin in
    tests/test_service.py)."""
    flat_costs = {"a": [5, 5], "b": [3, 3, 3], "c": [1] * 8}
    picks = {}
    for arm in ("default", "explicit"):
        s = DeficitRoundRobinScheduler(quantum=4)
        for k in ("a", "b", "c"):
            if arm == "default":
                s.register(k)
            else:
                s.register(k, priority=Priority.NORMAL)
        q = _Q(flat_costs)
        seq = []
        while True:
            k = s.pick(q.head)
            if k is None:
                break
            q.pop(k)
            seq.append(k)
        picks[arm] = (seq, {k: s.deficit(k) for k in ("a", "b", "c")})
    assert picks["default"] == picks["explicit"]


def test_unregister_adjusts_lane_ring():
    s = DeficitRoundRobinScheduler()
    for k in ("a", "b", "c"):
        s.register(k, priority=Priority.HIGH)
    q = _Q({"a": [1, 1], "b": [1, 1], "c": [1, 1]})
    assert s.pick(q.head) == "a"
    q.pop("a")
    s.unregister("a")
    with pytest.raises(ValueError, match="not registered"):
        s.unregister("a")
    order = []
    while True:
        k = s.pick(q.head)
        if k is None:
            break
        q.pop(k)
        order.append(k)
    assert sorted(order) == ["b", "b", "c", "c"]


# ---------------------------------------------------------------------------
# Admission control (live service)
# ---------------------------------------------------------------------------

def test_admission_refusal_is_stateless_and_recovers():
    """A transport op over budget refuses with the structured error,
    BEFORE the caller's flying buffer is zeroed; reads still admit;
    the budget frees as the worker drains and the campaign lands
    bitwise on the solo facade."""
    mesh = _mesh()
    svc = TallyService(autostart=False, admission_budget=N + 10)
    try:
        h = svc.open_session(PumiTally(mesh, N, _cfg()),
                             session_id="s0", max_queue=8)
        (src, dests), = _campaign(11, moves=2)
        h.copy_initial_position(src.copy())  # cost N: admitted
        flying = np.ones(N, np.int8)
        with pytest.raises(ServiceOverloadedError) as ei:
            h.move(None, dests[0].copy(), flying=flying)
        assert ei.value.budget == N + 10
        assert ei.value.admitted == N
        assert ei.value.cost == N
        # Refused => no side effects: the flying buffer still holds
        # the caller's bytes, nothing joined the queue.
        np.testing.assert_array_equal(flying, np.ones(N, np.int8))
        st = svc.stats()
        assert st["admission"]["refused_ops"] == 1
        assert st["admission"]["admitted_cost"] == N
        assert st["admission"]["queued_cost"] == N
        assert st["admission"]["inflight_cost"] == 0
        assert st["sessions"]["s0"]["pending"] == 1
        # Reads are cost-1 "call" ops — never counted, never refused.
        f_flux = h.flux()
        # Worker drains the source: budget frees, the retry admits and
        # zeroes the flying buffer (accept-then-zero).
        svc.start()
        f_flux.result(timeout=300)
        fut = h.move(None, dests[0].copy(), flying=flying)
        np.testing.assert_array_equal(flying, np.zeros(N, np.int8))
        fut.result(timeout=300)
        h.move(None, dests[1].copy()).result(timeout=300)
        got = np.asarray(h.flux().result(timeout=300))
        st = svc.stats()
        assert st["admission"]["admitted_cost"] == 0
    finally:
        svc.shutdown(drain=False)
    solo = PumiTally(mesh, N, _cfg())
    solo.CopyInitialPosition(src.copy())
    for d in dests:
        solo.MoveToNextLocation(None, d.copy())
    np.testing.assert_array_equal(got, np.asarray(solo.flux))


def test_open_refused_while_budget_full_and_close_bypasses():
    """``open_session`` refuses while the budget is already full (the
    session would be unservable anyway); the close sentinel is never
    refused, so teardown stays live under overload."""
    mesh = _mesh()
    svc = TallyService(autostart=False, admission_budget=N)
    try:
        h = svc.open_session(PumiTally(mesh, N, _cfg()),
                             session_id="s0", max_queue=8)
        (src, _), = _campaign(12, moves=1)
        h.copy_initial_position(src.copy())  # fills the budget exactly
        with pytest.raises(ServiceOverloadedError):
            svc.open_session(PumiTally(mesh, N, _cfg()),
                             session_id="s1", max_queue=8)
        assert svc.stats()["admission"]["refused_sessions"] == 1
        assert svc.session_ids() == ("s0",)
        # Teardown under a full budget: the close sentinel bypasses
        # the gate (kind == "call").
        f_close = h.close()
        svc.start()
        f_close.result(timeout=300)
        # Budget freed: the refused open now succeeds.
        h1 = svc.open_session(PumiTally(mesh, N, _cfg()),
                              session_id="s1", max_queue=8)
        assert h1.id == "s1"
    finally:
        svc.shutdown(drain=False)


def test_stats_schema_priorities_and_latency():
    """The ``stats()`` snapshot: per-session priority names, queue
    cost, and populated p50/p99 submit->resolve latency after a
    served campaign; admission ledger consistent."""
    mesh = _mesh()
    svc = TallyService(admission_budget=10_000)
    try:
        hi = svc.open_session(PumiTally(mesh, N, _cfg()),
                              session_id="hi", max_queue=8,
                              priority=Priority.HIGH)
        lo = svc.open_session(PumiTally(mesh, N, _cfg()),
                              session_id="lo", max_queue=8,
                              priority=Priority.LOW)
        for h, seed in ((hi, 21), (lo, 22)):
            (src, dests), = _campaign(seed, moves=2)
            h.copy_initial_position(src.copy())
            futs = [h.move(None, d.copy()) for d in dests]
            for f in futs:
                f.result(timeout=300)
        st = svc.stats()
        assert set(st) >= {"sessions", "fusion", "admission"}
        assert set(st["admission"]) == {
            "budget", "admitted_cost", "queued_cost", "inflight_cost",
            "refused_ops", "refused_sessions",
        }
        assert st["admission"]["budget"] == 10_000
        for sid, pr in (("hi", "high"), ("lo", "low")):
            row = st["sessions"][sid]
            assert set(row) == {
                "state", "priority", "pending", "queued_cost",
                "ops_completed", "moves_completed", "latency_p50_ms",
                "latency_p99_ms",
            }
            assert row["priority"] == pr
            assert row["moves_completed"] == 2
            assert row["latency_p50_ms"] > 0.0
            assert row["latency_p99_ms"] >= row["latency_p50_ms"]
    finally:
        svc.shutdown(drain=False)


def test_mixed_priority_fused_streaming_bitwise():
    """A LOW streaming session whose staged moves are chunk-compatible
    with a HIGH lead rides its fused launches — and both land bitwise
    on their solo campaigns (the service-level half of the starvation
    bound)."""
    mesh = _mesh()
    chunk = 24
    works = {"hi": _campaign(31, moves=2), "lo": _campaign(32, moves=2)}
    svc = TallyService(autostart=False, admission_budget=10_000)
    got = {}
    try:
        handles = {}
        for sid, pr in (("hi", Priority.HIGH), ("lo", Priority.LOW)):
            t = StreamingTally(mesh, N, chunk_size=chunk, config=_cfg())
            if sid == "lo":
                # Localize LOW's source directly so its queued head is
                # a MOVE when the HIGH lead serves — the ride-along
                # window. (Pre-open direct calls are the caller's to
                # make; the service owns the facade only after open.)
                t.CopyInitialPosition(works[sid][0][0].copy())
            handles[sid] = svc.open_session(t, session_id=sid,
                                            max_queue=8, priority=pr)
        futs = []
        (src, dests) = works["hi"][0]
        futs.append(handles["hi"].copy_initial_position(src.copy()))
        for m in range(2):
            for sid in ("hi", "lo"):
                futs.append(handles[sid].move(
                    None, works[sid][0][1][m].copy()
                ))
        svc.start()
        for f in futs:
            f.result(timeout=300)
        for sid in ("hi", "lo"):
            got[sid] = np.asarray(handles[sid].flux().result(timeout=300))
        assert svc.fusion_stats["fused_moves"] >= 2  # lo rode hi's lead
    finally:
        svc.shutdown(drain=False)
    for sid in ("hi", "lo"):
        solo = StreamingTally(mesh, N, chunk_size=chunk, config=_cfg())
        (src, dests) = works[sid][0]
        solo.CopyInitialPosition(src.copy())
        for d in dests:
            solo.MoveToNextLocation(None, d.copy())
        np.testing.assert_array_equal(got[sid], np.asarray(solo.flux),
                                      err_msg=sid)


# ---------------------------------------------------------------------------
# Wire schema (NDJSON front end)
# ---------------------------------------------------------------------------

def _rpc(f, req):
    f.write((json.dumps(req) + "\n").encode("utf-8"))
    f.flush()
    return json.loads(f.readline())


def test_socket_priority_stats_and_overloaded_reply():
    """Socket half of the round-20 schema: ``open`` takes a priority
    name (unknown names answer a structured error), ``stats`` returns
    the full snapshot, ``ping`` the aggregate load, and an
    admission-budget refusal answers ``"overloaded": true`` (distinct
    from per-session ``"busy"``)."""
    import base64
    import socket as sk

    svc = TallyService(admission_budget=N)
    fe = SocketFrontend(svc)
    fe.start()
    try:
        with sk.create_connection((fe.host, fe.port)) as conn:
            f = conn.makefile("rwb")
            r = _rpc(f, {"op": "open", "facade": "mono",
                         "num_particles": N, "priority": "urgent",
                         "mesh": {"box": [1, 1, 1, 3, 3, 3]}})
            assert r["ok"] is False and r["error"] == "ValueError"
            assert "unknown priority" in r["message"]
            assert r["busy"] is False and r["overloaded"] is False

            r = _rpc(f, {"op": "open", "facade": "mono",
                         "num_particles": N, "priority": "high",
                         "max_queue": 8,
                         "mesh": {"box": [1, 1, 1, 3, 3, 3]}})
            assert r["ok"] is True
            sid = r["session"]

            st = _rpc(f, {"op": "stats"})
            assert st["ok"] is True
            assert st["stats"]["sessions"][sid]["priority"] == "high"

            ping = _rpc(f, {"op": "ping"})
            assert ping["ok"] is True and ping["draining"] is False
            assert set(ping["load"]) == {
                "sessions", "queued_cost", "inflight_cost",
                "admitted_cost", "budget",
            }
            assert ping["load"]["sessions"] == 1
            assert ping["load"]["budget"] == N
            assert set(ping["fusion"]) == {
                "fused_groups", "fused_moves", "solo_moves",
                "solo_other",
            }

            # Fill the budget with an unserved source (wait=False so
            # the reply returns while the op may still be queued),
            # then a second transport refuses with "overloaded".
            (src, dests), = _campaign(41, moves=1)
            b64 = base64.b64encode(
                np.ascontiguousarray(src, "<f8").tobytes()
            ).decode("ascii")
            d64 = base64.b64encode(
                np.ascontiguousarray(dests[0], "<f8").tobytes()
            ).decode("ascii")
            # Stall the worker behind nothing — instead, drive the
            # refusal deterministically by shrinking to a service
            # whose budget a single source fills (cost N == budget).
            r = _rpc(f, {"op": "source", "session": sid,
                         "positions": b64, "wait": False})
            assert r["ok"] is True
            r = _rpc(f, {"op": "move", "session": sid, "dests": d64,
                         "wait": False})
            if not r["ok"]:  # the source may already have completed
                assert r["error"] == "ServiceOverloadedError"
                assert r["overloaded"] is True and r["busy"] is False
    finally:
        fe.stop()
        svc.shutdown(drain=False)


def test_socket_overloaded_reply_deterministic():
    """The overload refusal on the wire, deterministically: with the
    worker never started, a queued source holds the whole budget."""
    import base64
    import socket as sk

    svc = TallyService(autostart=False, admission_budget=N)
    fe = SocketFrontend(svc)
    fe.start()
    try:
        with sk.create_connection((fe.host, fe.port)) as conn:
            f = conn.makefile("rwb")
            r = _rpc(f, {"op": "open", "facade": "mono",
                         "num_particles": N, "max_queue": 8,
                         "mesh": {"box": [1, 1, 1, 3, 3, 3]}})
            sid = r["session"]
            (src, dests), = _campaign(42, moves=1)

            def enc(a):
                return base64.b64encode(
                    np.ascontiguousarray(a, "<f8").tobytes()
                ).decode("ascii")

            r = _rpc(f, {"op": "source", "session": sid,
                         "positions": enc(src), "wait": False})
            assert r["ok"] is True
            r = _rpc(f, {"op": "move", "session": sid,
                         "dests": enc(dests[0]), "wait": False})
            assert r["ok"] is False
            assert r["error"] == "ServiceOverloadedError"
            assert r["overloaded"] is True and r["busy"] is False
    finally:
        fe.stop()
        svc.shutdown(drain=False)


# ---------------------------------------------------------------------------
# SIGTERM drain under load (subprocess)
# ---------------------------------------------------------------------------

def _run_driver(ckpt_dir, out_dir, *extra, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PUMIUMTALLY_FAULT", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    return subprocess.run(
        [sys.executable, DRIVER, "--ckpt-dir", str(ckpt_dir),
         "--out-dir", str(out_dir), "--stream-pair", *extra],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
        env=env,
    )


def _last_json(stdout):
    return json.loads(
        [ln for ln in stdout.splitlines() if ln.startswith("{")][-1]
    )


LOAD_FLAGS = ("--priorities", "high,low", "--admission-budget", "383")


def test_stream_pair_drain_under_load_batch_aligned_bitwise(tmp_path):
    """SIGTERM against a stream-pair campaign running with priority
    lanes and a near-full admission budget (383 of the 384 cost units
    a batch round stages, so the gate refuses and the driver's retry
    loop is live): exit 0, one BATCH-ALIGNED generation per session,
    and the resumed campaigns land bitwise on the uninterrupted
    reference — which, run without lanes, actually chunk-fuses."""
    from tests._service_driver import MOVES as DRV_MOVES
    from tests._service_driver import STREAM_PAIR_SESSIONS

    # Uninterrupted reference (no lanes: DRR interleaves the pair, so
    # the campaign coalesces chunk-wise — the round-20 fusion path).
    r = _run_driver(tmp_path / "ck_base", tmp_path / "out_base")
    assert r.returncode == 0, r.stderr
    assert _last_json(r.stdout)["fusion"]["fused_moves"] > 0
    base = {
        s: np.load(tmp_path / "out_base" / f"{s}.npy")
        for s in STREAM_PAIR_SESSIONS
    }

    r = _run_driver(tmp_path / "ck", tmp_path / "out", *LOAD_FLAGS,
                    "--sigterm-after-batch", "1")
    assert r.returncode == 0, r.stderr
    assert not (tmp_path / "out").exists()
    drained = _last_json(r.stdout)
    assert set(drained["drained"]) == set(STREAM_PAIR_SESSIONS)
    assert all(g is not None for g in drained["drained"].values())

    r = _run_driver(tmp_path / "ck", tmp_path / "out", *LOAD_FLAGS,
                    "--resume")
    assert r.returncode == 0, r.stderr
    for s in STREAM_PAIR_SESSIONS:
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith(f"resumed session {s} ")][0]
        iter_count = int(line.rsplit("iter_count ", 1)[1].rstrip(")"))
        assert iter_count % DRV_MOVES == 0  # batch-aligned
        assert iter_count == 2 * DRV_MOVES  # drained after batch 1
        np.testing.assert_array_equal(
            np.load(tmp_path / "out" / f"{s}.npy"), base[s],
            err_msg=f"{s}: resume arm (lanes + admission gate live)",
        )
