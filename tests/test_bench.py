"""The benchmark itself is a deliverable — pin its machinery.

Runs bench.py's workload functions at a tiny scale on the CPU backend
(the preflight device probe is NOT exercised — it exists precisely for
environments where the accelerator may hang). Covers: the three
protocol modes, the conservation gate, the trajectory generator's
bounds, and the autotune integration (including its opt-out).
"""

import importlib
import os

import numpy as np
import pytest


@pytest.fixture()
def bench(monkeypatch):
    monkeypatch.setenv("PUMIUMTALLY_BENCH_N", "4000")
    monkeypatch.setenv("PUMIUMTALLY_BENCH_DIV", "6")
    monkeypatch.setenv("PUMIUMTALLY_BENCH_MOVES", "2")
    monkeypatch.syspath_prepend(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench as mod

    # Reload per test: picks up the env-sized workload and resets the
    # module's autotune memo (every user of bench goes through this
    # fixture, so no restore pass is needed).
    yield importlib.reload(mod)


def test_trajectory_stays_inside_box(bench):
    rng = np.random.default_rng(0)
    pts = bench.make_trajectory(rng, 1000, 5, box=[2.0, 1.0, 1.0])
    for p in pts:
        assert p.min() >= 0.0 and (p <= [2.0, 1.0, 1.0]).all()


def test_workload_protocols_and_conservation(bench, monkeypatch):
    monkeypatch.setenv("PUMIUMTALLY_BENCH_AUTOTUNE", "0")
    for mode in ("two_phase", "two_phase_forced", "continue"):
        res = bench.run_workload(bench.N, bench.MOVES, mode)
        assert res["moves_per_sec"] > 0
        assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL
    assert bench.tuned_knobs() == {}  # opt-out honored


def test_autotune_integration_and_conservation(bench):
    """The sweep must actually RUN (not silently fall back): force a
    sweep whose only candidate is non-default, so the memoized knobs
    prove the autotuner executed and its winner reached the config."""
    import pumiumtally_tpu.utils.autotune as at

    bench._TUNED_KNOBS = None
    orig = at.autotune_walk

    def pinned(mesh, **kw):
        return orig(mesh, candidates=[{"walk_cond_every": 8}], **kw)

    at.autotune_walk = pinned
    try:
        assert bench.tuned_knobs() == {"walk_cond_every": 8}
    finally:
        at.autotune_walk = orig
    # The pinned memo stays in place: run_workload exercises the
    # conservation gate UNDER the tuned config without re-sweeping
    # (the fixture's reload isolates other tests).
    res = bench.run_workload(bench.N, bench.MOVES, "two_phase")
    assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL


def test_preflight_max_wait_env_caps_budget(bench, monkeypatch):
    """PUMIUMTALLY_BENCH_MAX_WAIT must bound BOTH the retry deadline
    and the per-probe timeout, so a round driver controls exactly what
    a wedged tunnel costs. Probes are simulated (a real one could hang
    this suite — the very failure mode the knob exists for)."""
    import subprocess as sp

    monkeypatch.setenv("PUMIUMTALLY_BENCH_MAX_WAIT", "45")
    # Point the stale-result fallback at nothing: this test asserts the
    # no-cached-result refusal path (the fallback has its own test).
    monkeypatch.setattr(bench, "LAST_SUCCESS_PATH", "/nonexistent/x.json")
    seen_timeouts = []

    def fake_run(cmd, **kw):
        seen_timeouts.append(kw["timeout"])
        raise sp.TimeoutExpired(cmd, kw["timeout"])

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    # Controlled clock: each probe costs its timeout, each sleep its
    # duration — so the deadline logic runs without real waiting.
    clock = {"t": 0.0}
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock["t"])

    def fake_sleep(s):
        clock["t"] += s

    monkeypatch.setattr(bench.time, "sleep", fake_sleep)

    real_run = fake_run

    def run_and_advance(cmd, **kw):
        clock["t"] += kw["timeout"]
        return real_run(cmd, **kw)

    monkeypatch.setattr(bench.subprocess, "run", run_and_advance)
    with pytest.raises(SystemExit) as exc:
        bench.preflight_device()
    # A refusal is a reported outcome, not a crash: rc 0 with a
    # machine-parseable single-line JSON (the r5 record showed the
    # rc=1-no-JSON shape left the driver with ``parsed: null``).
    assert exc.value.code == 0
    # Probe timeouts never exceed the env budget (floor of 30 s aside),
    # and the loop gave up at the env deadline, not the 25-min default.
    assert seen_timeouts[0] == 45.0
    assert all(t <= 45.0 for t in seen_timeouts)
    assert clock["t"] <= 45.0 + 30.0 + 30.0  # one probe + floor slack


def test_pincell_workload(bench):
    res = bench.run_pincell(2000, 2)
    assert res["moves_per_sec"] > 0
    assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL


def test_component_ab_rows_exist(bench, monkeypatch):
    """Both component A/B rows must be CALLABLE top-level functions —
    regression guard for the best-effort try/except in
    _measure_and_report, which would silently swallow a NameError and
    record null for a row forever (nearly shipped when the
    table_precision row displaced run_redistribution_ab's def line).

    N is raised above the fixture's tiny size: the migrate-round
    parity assert inside the tool presumes no bucket overflows its
    1.5x capacity (true at bench scale by design; at n=4000 random
    16-way buckets overflow almost surely and the two arms' scatter
    collision order legitimately differs)."""
    monkeypatch.setattr(bench, "N", 64_000)
    red = bench.run_redistribution_ab()
    assert set(red) == {"cascade_boundary", "migrate_round"}


def test_table_precision_ab_row(bench):
    """The f32-vs-bf16 component row: both arms conserve (the tool
    exits hard otherwise), the select-tier bytes report at the halved
    ratio, and the divergence stays in the tie-class band."""
    res = bench.run_table_precision_ab()
    # 16 bf16 lanes vs 20 working-dtype lanes: 0.4 at f32, 0.2 under
    # the suite's f64 harness — "halved" is the worst case.
    assert res["select_bytes_ratio"] <= 0.5
    assert res["bytes"]["bf16"]["modeled_bytes_per_crossing"] < (
        res["bytes"]["f32"]["modeled_bytes_per_crossing"]
    )
    assert res["flux_l1_rel_divergence"] < 1e-2
    assert res["f32_moves_per_sec"] > 0 and res["bf16_moves_per_sec"] > 0


def test_blocked_profile_row(bench, monkeypatch):
    """The blocked_profile component-budget row: every declared field
    present, rounds/dispatches consistent, conservation gated, and the
    frontier stats reflect an actual crossing front (the 6^3 fixture
    mesh with a 100-element bound forces multiple blocks and at least
    one migration round)."""
    monkeypatch.setenv("PUMIUMTALLY_BENCH_BLOCK_ELEMS", "100")
    res = bench.run_blocked_profile(bench.N, 2)
    for key in ("walk_ms", "migrate_ms", "occupancy_ms",
                "bookkeeping_ms", "walk_ms_per_round",
                "migrate_ms_per_round", "occupancy_ms_per_round",
                "rounds", "dispatches", "fallback_rounds",
                "cap_frontier", "frontier_max", "frontier_mean",
                "blocks_per_chip", "block_elems",
                "conservation_rel_err"):
        assert key in res, key
    assert res["rounds"] >= 2  # 2 moves, >= 1 round each
    assert res["dispatches"] >= res["rounds"]
    assert res["walk_ms"] > 0 and res["migrate_ms"] > 0
    assert res["blocks_per_chip"] > 1 and res["block_elems"] <= 100
    assert res["cap_frontier"] == bench.N // 8
    assert res["frontier_max"] >= res["frontier_mean"]
    assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL


def test_batch_stats_row(bench):
    """The batch-statistics component row: schema keys present, flux
    parity between the arms asserted (the tool exits hard otherwise),
    the trigger trace well-formed (monotone decay on its deterministic
    alternating-weight workload), and the compiles-healthy contract —
    ``compiles.timed == 0``: the close_batch/trigger_eval entry points
    compile once each in the warmup batches, never inside the timed
    window."""
    res = bench.run_batch_stats()
    for key in ("on_moves_per_sec", "off_moves_per_sec",
                "close_overhead_pct", "close_lane_update_ms",
                "close_trigger_eval_ms", "flux_parity_bitwise",
                "trigger", "compiles", "workload"):
        assert key in res, key
    assert res["flux_parity_bitwise"] is True
    assert res["on_moves_per_sec"] > 0 and res["off_moves_per_sec"] > 0
    assert res["close_lane_update_ms"] > 0
    assert res["close_trigger_eval_ms"] > 0
    trig = res["trigger"]
    assert trig["monotone_decay"] is True
    assert trig["converged_at_batches"] is not None
    assert len(trig["values"]) >= 2
    # The healthy contract: zero compiles in the measured window, and
    # exactly one compile for each stats entry point over the run.
    assert res["compiles"]["timed"] == 0
    assert res["compiles"]["close_batch"] == 1
    assert res["compiles"]["trigger_eval"] == 1


def test_scoring_row(bench):
    """The filtered-scoring component row: schema keys present, the
    BITWISE flux-parity and bin-telescoping gates asserted (the tool
    raises otherwise), positive rates in both arms, and the
    compiles-healthy contract — ``compiles.timed == 0``: the
    scoring-armed walk and the score_bins resolution compile once
    each in the warmup moves."""
    res = bench.run_scoring()
    for key in ("on_moves_per_sec", "off_moves_per_sec",
                "scoring_overhead_pct", "scoring_ms_per_move",
                "flux_parity_bitwise", "telescoping_bitwise",
                "events_total", "lanes", "compiles", "workload"):
        assert key in res, key
    assert res["flux_parity_bitwise"] is True
    assert res["telescoping_bitwise"] is True
    assert res["on_moves_per_sec"] > 0 and res["off_moves_per_sec"] > 0
    assert res["events_total"] > 0
    assert res["lanes"] == {"n_bins": 2, "n_scores": 3,
                            "bank_elems": 6 * bench.MESH_DIV**3 * 6}
    assert res["compiles"]["timed"] == 0
    assert res["compiles"].get("score_bins", 0) == 1


def test_resilience_row(bench):
    """The fault-tolerance component row: schema keys present, bitwise
    flux parity between the autosave-on/off arms asserted (the tool
    raises otherwise), a positive fenced per-save cost and on-disk
    generation size, the live keep-K prune, and the host-side-only
    contract — zero compiles attributable to the resilience layer
    (``timed == 0`` and the totals are the engine's own warmup)."""
    res = bench.run_resilience_ab()
    for key in ("on_moves_per_sec", "off_moves_per_sec",
                "autosave_overhead_pct", "save_ms", "ckpt_bytes",
                "generations_written", "generations_retained",
                "flux_parity_bitwise", "compiles", "workload"):
        assert key in res, key
    assert res["flux_parity_bitwise"] is True
    assert res["on_moves_per_sec"] > 0 and res["off_moves_per_sec"] > 0
    assert res["save_ms"] > 0 and res["ckpt_bytes"] > 0
    # 6 batch-close autosaves + 5 manual microcost saves; keep=2.
    assert res["generations_written"] >= 8
    assert res["generations_retained"] == res["keep"] == 2
    assert res["compiles"]["timed"] == 0


def test_sentinel_row(bench):
    """The runtime-sentinel component row: schema keys present,
    bitwise flux parity between the sentinel-on/off arms asserted
    (the tool raises otherwise), an anomaly-free health report on the
    healthy workload, a positive fenced per-move audit cost, and the
    compiles-healthy contract — ``compiles.timed == 0``: audit_pack
    compiles once in the warmup batches and straggler_retry never
    compiles on a healthy run."""
    res = bench.run_sentinel_ab()
    for key in ("on_moves_per_sec", "off_moves_per_sec",
                "sentinel_overhead_pct", "audit_ms",
                "flux_parity_bitwise", "health", "compiles",
                "workload"):
        assert key in res, key
    assert res["flux_parity_bitwise"] is True
    assert res["on_moves_per_sec"] > 0 and res["off_moves_per_sec"] > 0
    assert res["audit_ms"] > 0
    health = res["health"]
    assert health["anomaly_moves"] == 0
    assert health["stragglers_lost"] == 0
    assert health["moves_audited"] > 0
    assert res["compiles"]["timed"] == 0
    assert res["compiles"].get("audit_pack", 0) == 1
    assert res["compiles"].get("straggler_retry", 0) == 0


def test_service_row(bench):
    """The multi-session-service component row: schema keys present,
    bitwise flux parity between the 1-session service and the direct
    facade asserted (the tool raises otherwise), positive rates in
    all three arms, and the host-side-only contract —
    ``compiles.timed == 0``: the service adds no jitted entry points,
    so a served session compiles exactly what a bare facade does (in
    warmup)."""
    res = bench.run_service_ab()
    for key in ("direct_moves_per_sec", "service_moves_per_sec",
                "service_fenced_moves_per_sec", "service_overhead_pct",
                "pipeline_speedup", "flux_parity_bitwise",
                "queue_depth", "compiles", "workload"):
        assert key in res, key
    assert res["flux_parity_bitwise"] is True
    assert res["direct_moves_per_sec"] > 0
    assert res["service_moves_per_sec"] > 0
    assert res["service_fenced_moves_per_sec"] > 0
    assert res["queue_depth"] >= 2  # double-buffered at minimum
    assert res["compiles"]["timed"] == 0


def test_service_fusion_row(bench):
    """The cross-session-fusion component row (r12 + r20): schema keys
    present per session count, bitwise per-session flux parity in
    BOTH arms asserted (the tool raises otherwise), the dispatch
    amortization visible in the telemetry (1 dispatch per move
    unfused, ~1/K fused up to the max_fuse=8 group cap at the 32
    point), the r20 "streaming" sub-row (chunk-wise fused
    StreamingTally facades at 4/8 sessions) under the same gates, and
    the compiles-healthy contract — ``compiles.timed == 0``:
    walk_fused compiles once per group composition in the warmup
    pass, and every measured pass runs against a hot cache. Tiny
    shape: the schema test pins machinery, not throughput (the
    >= 1.15x serving gate is the full-shape A/B's job)."""
    res = bench.run_service_fusion_ab()

    def check_arm(arm):
        assert arm["flux_parity_bitwise"] is True
        assert arm["compiles"]["timed"] == 0
        for s_count, row in arm["per_sessions"].items():
            for key in ("unfused_moves_per_sec", "fused_moves_per_sec",
                        "fused_speedup", "unfused_dispatches_per_move",
                        "fused_dispatches_per_move",
                        "fused_move_fraction"):
                assert key in row, (s_count, key)
            assert row["unfused_moves_per_sec"] > 0
            assert row["fused_moves_per_sec"] > 0
            assert row["unfused_dispatches_per_move"] == 1.0
            if int(s_count) > 8:
                # Above the max_fuse=8 cap waves split into several
                # groups (and DRR desync strands a few solo moves):
                # the amortization bound is the CAP, not K.
                assert row["fused_dispatches_per_move"] < 0.25
                assert row["fused_move_fraction"] >= 0.9
            elif int(s_count) > 1:
                # Every move wave coalesced: K moves -> 1 dispatch.
                assert row["fused_dispatches_per_move"] == pytest.approx(
                    1.0 / int(s_count)
                )
                assert row["fused_move_fraction"] == 1.0
            else:
                assert row["fused_dispatches_per_move"] == 1.0
                assert row["fused_move_fraction"] == 0.0
        assert "walk_fused" in arm["compiles"]

    check_arm(res)
    assert set(res["per_sessions"]) == {"1", "4", "8", "32"}
    assert res["facade"] == "mono"
    # The r20 streaming sub-row: chunk-wise fusion, same gates.
    stream = res["streaming"]
    assert stream["facade"] == "stream"
    assert stream["workload"]["chunk_size"] >= 1
    assert set(stream["per_sessions"]) == {"4", "8"}
    check_arm(stream)


def test_service_load_row(bench):
    """The served-throughput-under-load row (r20): >= 100 scripted
    clients with a deterministic seeded schedule through a 2-worker
    router, all served (the tool raises on any failed/timed-out
    client), schema keys present, per-lane fairness and refusal
    telemetry populated, the bitwise spot-check parity gate asserted
    inside the tool, and ``compiles.timed == 0`` (the warmup ladder
    pre-compiles every fused composition the run can dispatch)."""
    res = bench.run_service_load()
    for key in ("clients", "moves_per_s", "particle_moves_per_s",
                "latency_ms", "lanes", "refusals", "parity_bitwise",
                "compiles", "workload"):
        assert key in res, key
    assert res["clients"] >= 100
    assert res["parity_bitwise"] is True
    assert res["parity_clients"] >= 1
    assert res["moves_per_s"] > 0
    assert res["latency_ms"]["p99"] >= res["latency_ms"]["p50"] > 0
    assert set(res["lanes"]) == {"high", "normal", "low"}
    for lane in res["lanes"].values():
        assert lane["clients"] > 0  # the 0.2/0.6/0.2 mix fills every lane
        assert 0.0 < lane["jain"] <= 1.0
    assert set(res["refusals"]) == {"busy_retries", "overload_refusals"}
    assert res["compiles"]["timed"] == 0
    assert res["workload"]["workers"] == 2


def test_distributed_row(bench):
    """The pod-scale distributed component row (r13): schema keys
    present, the BITWISE collective-vs-scatter flux-parity gate
    asserted (the tool raises otherwise), positive rates and fenced
    per-move costs in both arms, a migration byte model consistent
    with the engine's packed layout, and the compiles-healthy
    contract — ``compiles.timed == 0``: the collective path is one
    phase-program variant, compiled in warmup. The cross-process
    subarm either proves 2-process bitwise parity or reports
    ``available: false`` with the backend's reason (jaxlib without
    cross-process CPU collectives) — never a failure."""
    res = bench.run_distributed_ab()
    for key in ("scatter_moves_per_sec", "collective_moves_per_sec",
                "collective_overhead_pct", "fenced_scatter_ms_per_move",
                "fenced_collective_ms_per_move", "flux_parity_bitwise",
                "migration", "two_process", "compiles", "workload"):
        assert key in res, key
    assert res["flux_parity_bitwise"] is True
    assert res["scatter_moves_per_sec"] > 0
    assert res["collective_moves_per_sec"] > 0
    assert res["fenced_scatter_ms_per_move"] > 0
    assert res["fenced_collective_ms_per_move"] > 0
    mig = res["migration"]
    assert mig["modeled_collective_bytes_per_round"] > 0
    assert mig["float_cols"] >= 7 and mig["int_cols"] >= 8
    assert mig["capacity"] % mig["devices"] == 0
    two = res["two_process"]
    if two["available"]:
        assert two["parity_bitwise"] is True
        assert two["processes"] == 2 and two["global_devices"] == 8
    else:
        assert two["reason"]
    assert res["compiles"]["timed"] == 0


def test_placement_row(bench):
    """The topology-aware placement component row (r19): schema keys
    present, the tool's gates ran (equal-host degeneracy bitwise, the
    cross-arm class — positions bitwise, elem-id diffs boundary-ties
    only, total flux conserved — it raises otherwise), the modeled
    cross-host byte drop STRICT in both sub-rows, positive fenced
    per-move costs both arms, and the compiles-healthy contract —
    ``compiles.timed == 0``: both placements drive the same phase
    programs, compiled in warmup."""
    res = bench.run_placement_ab()
    assert set(res) == {"placement_owner", "engine_placement"}
    owner = res["placement_owner"]
    assert owner["equal_host_degeneracy_bitwise"] is True
    assert 0 < owner["bytes_pod_rcb"] < owner["bytes_linear"]
    assert owner["hosts"] == [3, 5]
    eng = res["engine_placement"]
    for key in ("bytes_linear", "bytes_pod_rcb", "drop_frac",
                "positions_bitwise", "boundary_ties",
                "total_flux_rel_err", "linear_move_ms",
                "pod_rcb_move_ms", "speedup", "linear_walk_rounds",
                "pod_rcb_walk_rounds", "compiles"):
        assert key in eng, key
    assert eng["positions_bitwise"] is True
    assert 0 < eng["bytes_pod_rcb"] < eng["bytes_linear"]
    assert eng["linear_move_ms"] > 0 and eng["pod_rcb_move_ms"] > 0
    assert eng["compiles"]["timed"] == 0


def test_pallas_walk_row(bench):
    """The one-kernel Pallas walk component row (r17): schema keys
    present, the tool's gates ran (interpret-mode bitwise pin vs
    walk_local, bitwise positions/elem_ids between the timed arms —
    it exits hard otherwise), the pallas arm really streamed
    (blocks > 1), the 80 B vs 52 B modeled bytes provenance, and the
    compiles-healthy contract — ``compiles.timed == 0``: the pallas
    round program is one phase-program variant, compiled in warmup."""
    res = bench.run_pallas_walk_ab()
    for key in ("gather_moves_per_sec", "pallas_moves_per_sec",
                "speedup", "fenced_gather_ms_per_move",
                "fenced_pallas_ms_per_move", "interpret_parity",
                "blocks_per_chip", "modeled_bytes_per_crossing",
                "compiles", "workload"):
        assert key in res, key
    assert res["interpret_parity"]["bitwise"] is True
    assert res["interpret_parity"]["pauses"] > 0
    assert res["interpret_parity"]["exits"] > 0
    assert res["gather_moves_per_sec"] > 0
    assert res["pallas_moves_per_sec"] > 0
    assert res["fenced_gather_ms_per_move"] > 0
    assert res["fenced_pallas_ms_per_move"] > 0
    assert res["blocks_per_chip"] > 1  # the streaming regime
    mb = res["modeled_bytes_per_crossing"]
    assert mb["gather_f32"] == 80
    assert mb["gather_bf16"] == mb["pallas_bf16"] == 52
    assert res["compiles"]["timed"] == 0
    # On this suite's CPU backend the pallas arm is interpret-mode.
    assert res["pallas_interpret_mode"] is (res["backend"] != "tpu")


def test_frontier_ab_row(bench):
    """The frontier-migrate component row: both front sizes present,
    positive timings for both arms, and the tool's slab-invariance
    bitwise check ran (it asserts internally before timing)."""
    res = bench.run_frontier_ab()
    assert set(res) == {"frac_2pct", "frac_20pct"}
    for row in res.values():
        assert row["full_ms"] > 0 and row["frontier_ms"] > 0
        assert row["speedup"] > 0
        assert row["slab_invariance_bitwise"] is True
        assert row["frontier"] <= row["cap_frontier"]


def test_blocked_profile_cap_frontier_env(bench, monkeypatch):
    """PUMIUMTALLY_BENCH_CAP_FRONTIER sizes the slab; 0 forces the
    full-capacity fallback every migration round and the row records
    those rounds honestly."""
    monkeypatch.setenv("PUMIUMTALLY_BENCH_BLOCK_ELEMS", "100")
    monkeypatch.setenv("PUMIUMTALLY_BENCH_CAP_FRONTIER", "0")
    res = bench.run_blocked_profile(bench.N, 2)
    assert res["cap_frontier"] == 0
    migrations = res["rounds"] - 2  # 2 moves: one walk round each
    assert res["fallback_rounds"] == migrations
    assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL


@pytest.mark.slow
def test_vmem_blocked_workload(bench, monkeypatch):
    """The blocked-vmem extra metric: conserves, reports its sub-split
    shape (on this 6^3 mesh a bound of 100 forces >1 block)."""
    monkeypatch.setenv("PUMIUMTALLY_BENCH_VMEM_BOUND", "100")
    res = bench.run_vmem_blocked(bench.N, bench.MOVES)
    assert res["moves_per_sec"] > 0
    assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL
    assert res["blocks_per_chip"] > 1
    assert res["block_elems"] <= 100


def test_vmem_blocked_child_hang_contained(bench, monkeypatch):
    """A child that exceeds its budget is killed and yields None —
    never an exception, never a stall: a hung Mosaic compile (the
    round-4 tunnel wedge) must not eat the bench headline."""
    monkeypatch.setenv("PUMIUMTALLY_BENCH_VMEM_TIMEOUT", "0.01")
    assert bench.run_vmem_blocked_subprocess() is None


@pytest.mark.slow
def test_vmem_blocked_subprocess_wrapper(bench, monkeypatch):
    """The real child round-trip (interpreter boot + engine compile,
    ~25 s): the wrapper must relay the parent's backend to the child
    (a fresh interpreter's startup hook would otherwise re-point it at
    the device tunnel) and parse its JSON line."""
    monkeypatch.setenv("PUMIUMTALLY_BENCH_VMEM_BOUND", "100")
    res = bench.run_vmem_blocked_subprocess()
    assert res is not None and res["blocks_per_chip"] >= 2
    assert res["conservation_rel_err"] < 1e-5


def _last_json(out: str) -> dict:
    import json

    return json.loads(
        [ln for ln in out.splitlines() if ln.startswith("{")][-1]
    )


def test_stale_result_fallback(bench, monkeypatch, tmp_path, capsys):
    """Device unreachable at report time: bench must fall back to this
    round's last successful measurement, conspicuously flagged stale —
    and refuse a cache old enough to be another round's number. Every
    REFUSAL exits 0 with a single-line ``{"stale_refused": true,
    "reason"}`` JSON record (the r5 rc=1-no-JSON shape left the round
    driver with ``parsed: null`` and the reason lost in stderr)."""
    import json
    import time as _time

    path = tmp_path / "last.json"
    monkeypatch.setattr(bench, "LAST_SUCCESS_PATH", str(path))

    # No cache -> machine-parseable refusal, rc 0.
    with pytest.raises(SystemExit) as e:
        bench._report_stale_result_or_die()
    assert e.value.code == 0
    rec = _last_json(capsys.readouterr().out)
    assert rec["stale_refused"] is True and "no cached" in rec["reason"]
    # No rate-like keys ride along a refusal.
    assert "value" not in rec and "metric" not in rec

    bench.record_success({"metric": "particle_moves_per_sec",
                          "value": 123.0, "vs_baseline": 2.0})
    with pytest.raises(SystemExit) as e:
        bench._report_stale_result_or_die()
    assert e.value.code == 0
    out = capsys.readouterr()
    line = [l for l in out.out.splitlines() if l.startswith("{")][-1]
    rec = json.loads(line)
    assert rec["stale"] is True and rec["value"] == 123.0
    # Distinct metric name (ADVICE r4): a consumer keying on
    # metric/value alone must opt in to a cached number.
    assert rec["metric"] == "particle_moves_per_sec_stale"
    assert "measured_at_utc" in rec and "stale_reason" in rec
    assert "STALE" in out.err

    # Too old -> refuse (rc 0, stale_refused record).
    old = json.load(open(path))
    old["measured_at_epoch"] = _time.time() - bench.STALE_MAX_AGE_S - 60
    json.dump(old, open(path, "w"))
    with pytest.raises(SystemExit) as e:
        bench._report_stale_result_or_die()
    assert e.value.code == 0
    rec = _last_json(capsys.readouterr().out)
    assert rec["stale_refused"] is True and "old" in rec["reason"]


def test_stale_result_round_mismatch_refused(bench, monkeypatch, tmp_path,
                                             capsys):
    """A cached result stamped with a different round id must be
    refused even when it is young enough for the age backstop — as a
    rc-0 ``stale_refused`` JSON record naming both rounds."""
    import json

    path = tmp_path / "last.json"
    monkeypatch.setattr(bench, "LAST_SUCCESS_PATH", str(path))
    monkeypatch.setattr(bench, "_current_round", lambda: 5)
    bench.record_success({"value": 1.0})
    rec = json.load(open(path))
    assert rec["measured_in_round"] == 5
    rec["measured_in_round"] = 4
    json.dump(rec, open(path, "w"))
    with pytest.raises(SystemExit) as e:
        bench._report_stale_result_or_die()
    assert e.value.code == 0
    out = _last_json(capsys.readouterr().out)
    assert out["stale_refused"] is True
    assert "round 4" in out["reason"] and "round 5" in out["reason"]

    # Opt-out kills the fallback outright (still a parseable refusal).
    rec["measured_in_round"] = 5
    json.dump(rec, open(path, "w"))
    monkeypatch.setenv("PUMIUMTALLY_BENCH_NO_STALE", "1")
    with pytest.raises(SystemExit) as e:
        bench._report_stale_result_or_die()
    assert e.value.code == 0
    out = _last_json(capsys.readouterr().out)
    assert out["stale_refused"] is True
    assert "NO_STALE" in out["reason"]


def test_record_success_gating(bench, monkeypatch, tmp_path):
    """Env-resized runs must never become the cached 'official' round
    measurement (the bench fixture itself sets the resize envs, so
    this process is exactly the case the gate exists for)."""
    assert bench._is_standard_workload() is False
    for k in ("PUMIUMTALLY_BENCH_N", "PUMIUMTALLY_BENCH_DIV",
              "PUMIUMTALLY_BENCH_MOVES"):
        monkeypatch.delenv(k, raising=False)
    assert bench._is_standard_workload() is True
