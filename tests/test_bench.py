"""The benchmark itself is a deliverable — pin its machinery.

Runs bench.py's workload functions at a tiny scale on the CPU backend
(the preflight device probe is NOT exercised — it exists precisely for
environments where the accelerator may hang). Covers: the three
protocol modes, the conservation gate, the trajectory generator's
bounds, and the autotune integration (including its opt-out).
"""

import importlib
import os

import numpy as np
import pytest


@pytest.fixture()
def bench(monkeypatch):
    monkeypatch.setenv("PUMIUMTALLY_BENCH_N", "4000")
    monkeypatch.setenv("PUMIUMTALLY_BENCH_DIV", "6")
    monkeypatch.setenv("PUMIUMTALLY_BENCH_MOVES", "2")
    monkeypatch.syspath_prepend(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import bench as mod

    # Reload per test: picks up the env-sized workload and resets the
    # module's autotune memo (every user of bench goes through this
    # fixture, so no restore pass is needed).
    yield importlib.reload(mod)


def test_trajectory_stays_inside_box(bench):
    rng = np.random.default_rng(0)
    pts = bench.make_trajectory(rng, 1000, 5, box=[2.0, 1.0, 1.0])
    for p in pts:
        assert p.min() >= 0.0 and (p <= [2.0, 1.0, 1.0]).all()


def test_workload_protocols_and_conservation(bench, monkeypatch):
    monkeypatch.setenv("PUMIUMTALLY_BENCH_AUTOTUNE", "0")
    for mode in ("two_phase", "two_phase_forced", "continue"):
        res = bench.run_workload(bench.N, bench.MOVES, mode)
        assert res["moves_per_sec"] > 0
        assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL
    assert bench.tuned_knobs() == {}  # opt-out honored


def test_autotune_integration_and_conservation(bench):
    """The sweep must actually RUN (not silently fall back): force a
    sweep whose only candidate is non-default, so the memoized knobs
    prove the autotuner executed and its winner reached the config."""
    import pumiumtally_tpu.utils.autotune as at

    bench._TUNED_KNOBS = None
    orig = at.autotune_walk

    def pinned(mesh, **kw):
        return orig(mesh, candidates=[{"walk_cond_every": 8}], **kw)

    at.autotune_walk = pinned
    try:
        assert bench.tuned_knobs() == {"walk_cond_every": 8}
    finally:
        at.autotune_walk = orig
    # The pinned memo stays in place: run_workload exercises the
    # conservation gate UNDER the tuned config without re-sweeping
    # (the fixture's reload isolates other tests).
    res = bench.run_workload(bench.N, bench.MOVES, "two_phase")
    assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL


def test_pincell_workload(bench):
    res = bench.run_pincell(2000, 2)
    assert res["moves_per_sec"] > 0
    assert res["conservation_rel_err"] < bench.CONSERVATION_RTOL
