"""Cross-session batch fusion (round 12, service/fusion.py).

Contracts pinned here (docs/DESIGN.md "Cross-session fusion"):

- a fused group's per-session flux / positions / elements / scoring
  bank / sentinel health are BITWISE the solo run of each campaign —
  the round-11 determinism contract survives sharing ONE device
  launch (mono, scoring-armed, and origin-passing variants; the
  fusion_stats telemetry proves the launches actually coalesced);
- ``fuse_sessions=False`` reproduces the one-op-at-a-time path bit
  for bit, and a 1-session service stays bitwise- AND
  allocation-identical to the bare facade whether fusion is on or
  off (a group of one always runs the unfused path);
- streaming sessions fuse CHUNK-WISE (round 20): one shared launch
  per chunk index, bitwise vs solo streaming runs — including ragged
  last chunks, scoring banks, sentinel health, origin-passing phase
  A, and every cascade permutation mode;
- sessions with DIFFERENT fusion keys (other facade kinds, other
  meshes, other scoring statics, other chunk sizes) never co-fuse —
  and still land bitwise;
- a mid-group failure (move before source) lands on exactly the
  failing session's future while the other sessions' results commit;
- ``pick_group`` charges co-fused heads by their own cost (fairness
  bounds unchanged) and groups deterministically in ring order;
- SIGTERM drain under fusion writes one BATCH-ALIGNED generation per
  session with bitwise per-session resume (subprocess,
  tests/_service_driver.py --mono-pair).
"""

import gc
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from pumiumtally_tpu import (
    EnergyFilter,
    PumiTally,
    ScoringSpec,
    SentinelPolicy,
    StreamingTally,
    TallyConfig,
    TallyService,
    build_box,
)
from pumiumtally_tpu.service import DeficitRoundRobinScheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRIVER = os.path.join(REPO, "tests", "_service_driver.py")

N = 192
BATCHES = 2
MOVES = 2


def _mesh():
    return build_box(1.0, 1.0, 1.0, 3, 3, 3)


def _campaign(seed, batches=BATCHES, moves=MOVES, n=N):
    rng = np.random.default_rng(seed)
    return [
        (rng.uniform(0.1, 0.9, (n, 3)),
         [rng.uniform(0.1, 0.9, (n, 3)) for _ in range(moves)],
         [rng.uniform(0.1, 1.9, n) for _ in range(moves)])
        for _ in range(batches)
    ]


def _drive_direct(t, work, with_energy=False, with_origins=False):
    for src, dests, energies in work:
        t.CopyInitialPosition(src.reshape(-1).copy())
        prev = src
        for d, e in zip(dests, energies):
            kw = {"energy": e.copy()} if with_energy else {}
            org = prev.reshape(-1).copy() if with_origins else None
            t.MoveToNextLocation(org, d.reshape(-1).copy(), **kw)
            prev = d


def _submit_campaigns(svc, handles, works, with_energy=False,
                      with_origins=False):
    """Queue every session's whole campaign against a STOPPED worker
    (autostart=False + generous queues), so when the worker starts,
    all compatible heads are backlogged together — fusion grouping is
    then deterministic, not a race against client threads."""
    futs = []
    for b in range(BATCHES):
        for sid, h in handles.items():
            src, dests, energies = works[sid][b]
            futs.append(h.copy_initial_position(src.reshape(-1).copy()))
            prev = src
            for d, e in zip(dests, energies):
                kw = {"energy": e.copy()} if with_energy else {}
                org = prev.reshape(-1).copy() if with_origins else None
                futs.append(h.move(org, d.reshape(-1).copy(), **kw))
                prev = d
    svc.start()
    for f in futs:
        f.result(timeout=300)


# ---------------------------------------------------------------------------
# pick_group (pure scheduler)
# ---------------------------------------------------------------------------

def test_pick_group_charges_cofused_heads_by_own_cost():
    """The fusion window serves compatible heads early but charges
    each by ITS OWN cost: the co-fused session's deficit goes negative
    (pre-paid service), so over a backlogged window the DRR fairness
    bound is unchanged."""
    sched = DeficitRoundRobinScheduler()
    for k in ("a", "b", "c"):
        sched.register(k)
    costs = {"a": 5, "b": 3, "c": 7}
    keys = {"a": "K", "b": "K", "c": "K"}
    group = sched.pick_group(lambda k: costs.get(k),
                             lambda k: keys.get(k), max_group=8)
    assert group == ["a", "b", "c"]
    # The lead paid through pick() (quantum 7 credited, 5 debited);
    # the co-fused members were debited their own costs with no
    # credit.
    assert sched.deficit("a") == 2
    assert sched.deficit("b") == -3
    assert sched.deficit("c") == -7


def test_cofusion_debt_survives_queue_empty():
    """The empty-queue forfeit drops banked CREDIT only: a session
    that rides fused launches in one-at-a-time bursts (queue empties
    between submissions) keeps its negative deficit across the empty
    — otherwise its entire consumption would be forgiven and the
    fairness bound would not hold for intermittent co-fused
    sessions."""
    sched = DeficitRoundRobinScheduler()  # auto quantum
    for k in ("a", "b"):
        sched.register(k)
    costs = {"a": 4, "b": 4}
    group = sched.pick_group(lambda k: costs.get(k), lambda k: "K", 8)
    assert group == ["a", "b"]
    assert sched.deficit("b") == -4  # pre-paid co-fused service
    # b's queue empties; a stays backlogged. The visit/ring forfeits
    # must NOT zero b's debt.
    assert sched.pick(lambda k: 4 if k == "a" else None) == "a"
    assert sched.deficit("b") == -4
    # Positive CREDIT still forfeits on empty (the classic DRR reset):
    # with quantum=3, x (cost 5) needs two passes, so y banks +3...
    sched2 = DeficitRoundRobinScheduler(quantum=3)
    sched2.register("x")
    sched2.register("y")
    assert sched2.pick(lambda k: 5) == "x"
    assert sched2.deficit("y") == 3
    # ...then y empties: its banked credit drops to zero, not below.
    assert sched2.pick(lambda k: 5 if k == "x" else None) == "x"
    assert sched2.deficit("y") == 0


def test_pick_group_respects_keys_window_and_nonfusable_heads():
    sched = DeficitRoundRobinScheduler()
    for k in ("a", "b", "c", "d"):
        sched.register(k)
    costs = {"a": 1, "b": 1, "c": 1, "d": 1}
    keys = {"a": "K", "b": "OTHER", "c": None, "d": "K"}
    # b (different key) and c (non-fusable head) stay out; d joins.
    group = sched.pick_group(lambda k: costs.get(k),
                             lambda k: keys.get(k), max_group=8)
    assert group == ["a", "d"]
    # A window of one degenerates to plain pick (no key calls needed).
    sched2 = DeficitRoundRobinScheduler()
    sched2.register("x")
    sched2.register("y")
    group = sched2.pick_group(lambda k: 1, lambda k: "K", max_group=1)
    assert group == ["x"]
    # Nothing queued -> None, like pick().
    assert sched2.pick_group(lambda k: None, lambda k: None, 8) is None


# ---------------------------------------------------------------------------
# The walk's segmented-commit hook (ops/walk.py walk(tally_seg=))
# ---------------------------------------------------------------------------

def test_walk_tally_seg_bitwise_across_perm_modes():
    """The segmented flux commit at the kernel level: a slab packing
    two independent populations, walked ONCE with per-particle segment
    offsets into a [2E] bank, reproduces each population's solo walk
    BITWISE — flux segments AND per-particle outputs — in every
    cascade permutation mode (the stable stage partitions preserve
    each segment's relative row order; "sorted" holds too because a
    stable sort induces the stable sort of every subsequence). Small
    min_window so the cascade actually runs at test size."""
    import jax.numpy as jnp

    from pumiumtally_tpu.ops.walk import walk

    mesh = _mesh()
    E = int(mesh.nelems)
    fdtype = mesh.coords.dtype
    c0 = np.asarray(jnp.mean(mesh.coords[mesh.tet2vert[0]], axis=0))

    def pop(n, seed):
        r = np.random.default_rng(seed)
        return (np.broadcast_to(c0, (n, 3)).astype(fdtype),
                r.uniform(0.1, 0.9, (n, 3)).astype(fdtype),
                r.uniform(0.5, 1.5, n).astype(fdtype))

    pops = [pop(512, 1), pop(384, 2)]
    for mode in ("packed", "arrays", "indirect", "sorted"):
        kw = dict(tally=True, tol=1e-8, max_iters=600, min_window=256,
                  perm_mode=mode)
        solos = []
        for x, dest, w in pops:
            n = x.shape[0]
            solos.append(walk(
                mesh, jnp.asarray(x), jnp.zeros((n,), jnp.int32),
                jnp.asarray(dest), jnp.ones((n,), jnp.int8),
                jnp.asarray(w), jnp.zeros((E,), fdtype), **kw,
            ))
        seg = np.concatenate([
            np.full(pops[0][0].shape[0], 0, np.int32),
            np.full(pops[1][0].shape[0], E, np.int32),
        ])
        fused = walk(
            mesh,
            jnp.asarray(np.concatenate([p[0] for p in pops])),
            jnp.zeros((seg.shape[0],), jnp.int32),
            jnp.asarray(np.concatenate([p[1] for p in pops])),
            jnp.ones((seg.shape[0],), jnp.int8),
            jnp.asarray(np.concatenate([p[2] for p in pops])),
            jnp.zeros((2 * E,), fdtype),
            tally_seg=jnp.asarray(seg), **kw,
        )
        a = 0
        for k, solo in enumerate(solos):
            n = pops[k][0].shape[0]
            np.testing.assert_array_equal(
                np.asarray(fused.flux)[k * E:(k + 1) * E],
                np.asarray(solo.flux), err_msg=f"{mode} seg {k}",
            )
            for field in ("x", "elem", "done", "s"):
                np.testing.assert_array_equal(
                    np.asarray(getattr(fused, field))[a:a + n],
                    np.asarray(getattr(solo, field)),
                    err_msg=f"{mode} {field} seg {k}",
                )
            a += n
    with pytest.raises(ValueError, match="tally_seg"):
        x, dest, _w = pops[0]
        walk(mesh, jnp.asarray(x), jnp.zeros((512,), jnp.int32),
             jnp.asarray(dest), jnp.ones((512,), jnp.int8),
             jnp.zeros((512,), fdtype), jnp.zeros((0,), fdtype),
             tally=False, tol=1e-8, max_iters=10,
             tally_seg=jnp.asarray(seg[:512]))


# ---------------------------------------------------------------------------
# Fused bitwise parity (the tentpole contract)
# ---------------------------------------------------------------------------

def _fused_vs_solo(mesh, build, *, with_energy=False, with_origins=False,
                   expect_fused, fuse=True, seeds=(71, 72, 73)):
    """Run len(seeds) sessions through one service and compare each,
    bitwise, against the solo run of the same campaign."""
    svc = TallyService(autostart=False, fuse_sessions=fuse)
    handles = {}
    works = {}
    for i, seed in enumerate(seeds):
        sid = f"s{i}"
        # Generous queues: the whole campaign stages against the
        # stopped worker (see _submit_campaigns).
        handles[sid] = svc.open_session(build(i), session_id=sid,
                                        max_queue=BATCHES * (MOVES + 2))
        works[sid] = _campaign(seed)
    _submit_campaigns(svc, handles, works, with_energy, with_origins)
    out = {
        sid: {
            "flux": h.flux().result(timeout=300),
            "pos": h.tally.positions,
            "elem": h.tally.elem_ids,
        }
        for sid, h in handles.items()
    }
    for sid, h in handles.items():
        if h.tally._scoring is not None:
            out[sid]["bank"] = h.score_bank().result(timeout=300)
        if h.tally._sentinel is not None:
            out[sid]["health"] = (
                h.health_report().result(timeout=300).as_dict()
            )
    stats = dict(svc.fusion_stats)
    svc.shutdown(drain=False)
    total_moves = len(seeds) * BATCHES * MOVES
    if expect_fused:
        assert stats["fused_moves"] == total_moves, stats
        assert stats["solo_moves"] == 0, stats
    else:
        assert stats["fused_groups"] == 0, stats
        assert stats["solo_moves"] == total_moves, stats
    for i, seed in enumerate(seeds):
        sid = f"s{i}"
        solo = build(i)
        _drive_direct(solo, _campaign(seed), with_energy, with_origins)
        np.testing.assert_array_equal(
            out[sid]["flux"], np.asarray(solo.flux), err_msg=sid,
        )
        np.testing.assert_array_equal(out[sid]["pos"], solo.positions,
                                      err_msg=sid)
        np.testing.assert_array_equal(out[sid]["elem"], solo.elem_ids,
                                      err_msg=sid)
        if "bank" in out[sid]:
            np.testing.assert_array_equal(
                out[sid]["bank"], np.asarray(solo.score_bank),
                err_msg=sid,
            )
        if "health" in out[sid]:
            assert out[sid]["health"] == solo.health_report().as_dict()
    return stats


def test_fused_three_mono_sessions_bitwise_vs_solo():
    """THE fusion pin: three co-fusable monolithic sessions run their
    whole campaigns through shared launches (every move fused —
    telemetry-checked) and each lands flux/positions/elements BITWISE
    on its solo run. Continue-mode and origin-passing (phase A through
    the fused program) both covered."""
    mesh = _mesh()

    def build(_i):
        return PumiTally(mesh, N, TallyConfig(check_found_all=False))

    _fused_vs_solo(mesh, build, expect_fused=True)
    _fused_vs_solo(mesh, build, with_origins=True, expect_fused=True)


def test_fused_scoring_and_sentinel_sessions_bitwise_vs_solo():
    """Scoring lanes ride the fused launch (per-session bank segments
    through the pre-shifted bin offsets) and a sentinel-armed session
    co-fuses with unarmed ones (the audit runs per-session after the
    shared launch): banks and health records bitwise vs solo."""
    mesh = _mesh()

    def build(i):
        spec = ScoringSpec(
            filters=[EnergyFilter(np.array([0.0, 1.0, 2.0]))],
            scores=["flux", "events"],
        )
        kw = {"check_found_all": False, "scoring": spec}
        if i == 1:
            kw["sentinel"] = SentinelPolicy()
        return PumiTally(mesh, N, TallyConfig(**kw))

    _fused_vs_solo(mesh, build, with_energy=True, expect_fused=True)


def test_fused_streaming_sessions_chunkwise_bitwise_vs_solo():
    """Round 20: streaming sessions fuse CHUNK-WISE — one shared
    launch per chunk index, all of a group's k-th chunks in one slab —
    and each session's flux/positions/elements land BITWISE on its
    solo streaming run. Covered with a ragged last chunk (192 over
    chunk 80 → 80/80/32: pad rows are grounded and dropped at the
    segmented scatter exactly like solo staging pads) and with
    origin-passing phase A through the fused program."""
    mesh = _mesh()

    def build_ragged(_i):
        return StreamingTally(mesh, N, chunk_size=80,
                              config=TallyConfig(check_found_all=False))

    def build_even(_i):
        return StreamingTally(mesh, N, chunk_size=64,
                              config=TallyConfig(check_found_all=False))

    _fused_vs_solo(mesh, build_ragged, expect_fused=True,
                   seeds=(81, 82, 83))
    _fused_vs_solo(mesh, build_even, with_origins=True,
                   expect_fused=True, seeds=(84, 85))


def test_fused_streaming_scoring_and_sentinel_bitwise_vs_solo():
    """Streaming chunk fusion with scoring lanes (per-chunk resolved
    bins ride the fused launch through the same pre-shifted offsets)
    and one sentinel-armed session in the group (its phase-B audit
    runs per chunk after each shared launch): banks and health
    records bitwise vs solo streaming."""
    mesh = _mesh()

    def build(i):
        spec = ScoringSpec(
            filters=[EnergyFilter(np.array([0.0, 1.0, 2.0]))],
            scores=["flux", "events"],
        )
        kw = {"check_found_all": False, "scoring": spec}
        if i == 1:
            kw["sentinel"] = SentinelPolicy()
        return StreamingTally(mesh, N, chunk_size=80,
                              config=TallyConfig(**kw))

    _fused_vs_solo(mesh, build, with_energy=True, expect_fused=True)


@pytest.mark.parametrize("mode", ["packed", "arrays", "indirect",
                                  "sorted"])
def test_fused_streaming_bitwise_across_perm_modes(mode):
    """The chunk-wise determinism proof holds in every cascade
    permutation mode (the stable-stage subsequence argument is
    mode-independent; "sorted" holds because a stable sort induces
    the stable sort of every subsequence): service-level bitwise pin
    per mode. One mode per test so each walk_fused composition stays
    inside the per-test retrace budget."""
    mesh = _mesh()

    def build(_i):
        return StreamingTally(
            mesh, N, chunk_size=64,
            config=TallyConfig(check_found_all=False,
                               walk_perm_mode=mode),
        )

    _fused_vs_solo(mesh, build, expect_fused=True, seeds=(86, 87))


def test_streaming_mixed_keys_never_cofuse():
    """Chunk-wise fusion keys lead with the facade KIND and pin
    (num_particles, chunk_size): a monolithic head never groups with
    a streaming head, and two streaming sessions with different chunk
    sizes never group either — the zoo runs entirely unfused and
    still bitwise."""
    mesh = _mesh()

    def build(i):
        if i == 0:
            return PumiTally(mesh, N, TallyConfig(check_found_all=False))
        if i == 1:
            return StreamingTally(
                mesh, N, chunk_size=64,
                config=TallyConfig(check_found_all=False),
            )
        return StreamingTally(
            mesh, N, chunk_size=96,
            config=TallyConfig(check_found_all=False),
        )

    stats = _fused_vs_solo(mesh, build, expect_fused=False)
    assert stats["fused_groups"] == 0


def test_mixed_key_sessions_never_cofuse():
    """Different meshes, different facade kinds, and different scoring
    statics are different fusion keys: a mixed zoo runs entirely
    unfused (zero groups) and bitwise."""
    mesh_a = _mesh()
    mesh_b = _mesh()  # equal values, DIFFERENT identity: no co-fusion
    spec = ScoringSpec(scores=["flux"])

    def build(i):
        if i == 0:
            return PumiTally(mesh_a, N, TallyConfig(check_found_all=False))
        if i == 1:
            return PumiTally(mesh_b, N, TallyConfig(check_found_all=False))
        return PumiTally(mesh_a, N, TallyConfig(check_found_all=False,
                                                scoring=spec))

    stats = _fused_vs_solo(mesh_a, build, expect_fused=False)
    assert stats["fused_groups"] == 0


def test_fuse_off_is_bitwise_and_allocation_identical():
    """fuse_sessions=False: the round-11 one-op-at-a-time path, bit
    for bit — multi-session campaigns land bitwise, and the 1-session
    service allocates not one device array more than the bare facade
    (fusion code never runs, so the live-array census matches exactly
    as it did in round 11)."""
    mesh = _mesh()

    def build(_i):
        return PumiTally(mesh, N, TallyConfig(check_found_all=False))

    _fused_vs_solo(mesh, build, expect_fused=False, fuse=False,
                   seeds=(91, 92))

    # Allocation census (the round-11 single-session pin, re-run with
    # the knob in both positions: a group of one never fuses).
    work = _campaign(93)
    warm = PumiTally(mesh, N)
    _drive_direct(warm, work)
    del warm
    gc.collect()
    base = len(jax.live_arrays())

    t_direct = PumiTally(mesh, N)
    _drive_direct(t_direct, work)
    flux_d = np.asarray(t_direct.flux)
    gc.collect()
    direct_delta = len(jax.live_arrays()) - base

    for fuse in (False, True):
        gc.collect()
        prev = len(jax.live_arrays())
        t_served = PumiTally(mesh, N)
        svc = TallyService(fuse_sessions=fuse)
        h = svc.open_session(t_served, max_queue=BATCHES * (MOVES + 2))
        futs = []
        for src, dests, _ in work:
            futs.append(h.copy_initial_position(src.reshape(-1).copy()))
            for d in dests:
                futs.append(h.move(None, d.reshape(-1).copy()))
        for f in futs:
            f.result(timeout=300)
        # Owned copy: the raw read is a view whose .base pins the
        # facade's device array across the next loop's census.
        flux_s = np.array(h.flux().result(timeout=300))
        assert svc.fusion_stats["fused_groups"] == 0
        svc.shutdown(drain=False)
        del svc, h, futs
        gc.collect()
        # The (still-live) served facade accounts for every device
        # array the run left behind — the service itself added none.
        served_delta = len(jax.live_arrays()) - prev
        np.testing.assert_array_equal(flux_s, flux_d)
        assert served_delta == direct_delta, f"fuse_sessions={fuse}"
        del t_served


def test_mid_group_error_lands_on_failing_session_only():
    """A session whose staged move refuses at the fused stage step
    (move before source) gets the error on ITS future; the other
    sessions in the group still fuse, commit, and land bitwise — and
    the failed session recovers with a late source."""
    mesh = _mesh()
    svc = TallyService(autostart=False)
    hs = [
        svc.open_session(
            PumiTally(mesh, N, TallyConfig(check_found_all=False)),
            session_id=f"s{i}", max_queue=8,
        )
        for i in range(3)
    ]
    works = [_campaign(61 + i, batches=1) for i in range(3)]
    futs = []
    for i, h in enumerate(hs):
        src, dests, _ = works[i][0]
        if i != 2:  # s2 never sources: its move must fail at stage
            futs.append(h.copy_initial_position(src.reshape(-1).copy()))
        futs.append(h.move(None, dests[0].reshape(-1).copy()))
    svc.start()
    with pytest.raises(RuntimeError, match="CopyInitialPosition"):
        futs[-1].result(timeout=300)
    for f in futs[:-1]:
        f.result(timeout=300)
    # The refusal SHRANK the launch to the healthy pair instead of
    # breaking it — and the telemetry counts what actually dispatched:
    # two moves through one shared launch, the refused op nowhere (it
    # dispatched nothing).
    assert svc.fusion_stats["fused_groups"] == 1, svc.fusion_stats
    assert svc.fusion_stats["fused_moves"] == 2, svc.fusion_stats
    # The failed session is not poisoned: a late source + move works.
    src2, dests2, _ = works[2][0]
    hs[2].copy_initial_position(src2.reshape(-1).copy())
    hs[2].move(None, dests2[0].reshape(-1).copy())
    fluxes = [h.flux().result(timeout=300) for h in hs]
    svc.shutdown(drain=False)
    for i in range(3):
        solo = PumiTally(mesh, N, TallyConfig(check_found_all=False))
        src, dests, _ = works[i][0]
        solo.CopyInitialPosition(src.reshape(-1).copy())
        solo.MoveToNextLocation(None, dests[0].reshape(-1).copy())
        np.testing.assert_array_equal(fluxes[i], np.asarray(solo.flux),
                                      err_msg=f"s{i}")


# ---------------------------------------------------------------------------
# SIGTERM drain under fusion (subprocess)
# ---------------------------------------------------------------------------

def _run_driver(ckpt_dir, out_dir, *extra, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if k not in ("PUMIUMTALLY_FAULT", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_ENABLE_X64"] = "true"
    return subprocess.run(
        [sys.executable, DRIVER, "--ckpt-dir", str(ckpt_dir),
         "--out-dir", str(out_dir), "--mono-pair", *extra],
        capture_output=True, text=True, cwd=REPO, timeout=timeout,
        env=env,
    )


def _last_json(stdout: str) -> dict:
    return json.loads(
        [ln for ln in stdout.splitlines() if ln.startswith("{")][-1]
    )


def test_drain_under_fusion_batch_aligned_and_bitwise_resume(tmp_path):
    """SIGTERM against a server whose two sessions were actually
    SHARING launches: exit 0, one BATCH-ALIGNED generation per session
    (iter_count a multiple of the per-batch move count), and the
    resumed campaigns land bitwise on the uninterrupted (also fused)
    reference — fusion changes dispatch, never state."""
    from tests._service_driver import MONO_PAIR_SESSIONS
    from tests._service_driver import MOVES as DRV_MOVES

    r = _run_driver(tmp_path / "ck_base", tmp_path / "out_base")
    assert r.returncode == 0, r.stderr
    assert _last_json(r.stdout)["fusion"]["fused_moves"] > 0
    base = {
        s: np.load(tmp_path / "out_base" / f"{s}.npy")
        for s in MONO_PAIR_SESSIONS
    }

    r = _run_driver(tmp_path / "ck", tmp_path / "out",
                    "--sigterm-after-batch", "1")
    assert r.returncode == 0, r.stderr
    assert not (tmp_path / "out").exists()
    drained = _last_json(r.stdout)
    assert set(drained["drained"]) == set(MONO_PAIR_SESSIONS)
    assert all(g is not None for g in drained["drained"].values())
    assert drained["fusion"]["fused_moves"] > 0  # drained WHILE fusing

    r = _run_driver(tmp_path / "ck", tmp_path / "out", "--resume")
    assert r.returncode == 0, r.stderr
    for s in MONO_PAIR_SESSIONS:
        line = [ln for ln in r.stdout.splitlines()
                if ln.startswith(f"resumed session {s} ")][0]
        iter_count = int(line.rsplit("iter_count ", 1)[1].rstrip(")"))
        assert iter_count % DRV_MOVES == 0  # batch-aligned
        assert iter_count == 2 * DRV_MOVES  # drained after batch 1
        np.testing.assert_array_equal(
            np.load(tmp_path / "out" / f"{s}.npy"), base[s],
            err_msg=f"{s}: resume arm",
        )
