"""Pincell O-grid mesh builder (BASELINE configs[0-1] geometry)."""

import math

import numpy as np

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.pincell import build_pincell, pincell_arrays

PITCH = 1.26
R = 0.4095


def test_pincell_fills_the_cell_exactly():
    height = 1.5
    mesh, region = build_pincell(pitch=PITCH, fuel_radius=R, height=height)
    vols = np.asarray(mesh.volumes)
    # Conforming cover of the square cell: signed volumes are all
    # positive (from_arrays validates) and sum EXACTLY to pitch^2 * h —
    # any overlap or gap would break the identity.
    np.testing.assert_allclose(vols.sum(), PITCH**2 * height, rtol=1e-12)
    # Fuel region approximates the cylinder (inscribed polygon, 16
    # sectors -> ~2.6% low, never high).
    fuel = vols[region == 0].sum()
    assert fuel < math.pi * R**2 * height
    assert fuel > 0.95 * math.pi * R**2 * height
    # Exact boundary topology: lateral surface = 2 tris per side quad
    # (nz layers x n_theta sectors), caps = the 2-D triangulation's
    # n_theta*(2*nrings-1) triangles each.
    n_theta, nrings, nz = 16, 6, 4  # build_pincell defaults
    fa = np.asarray(mesh.face_adj)
    expect_boundary = 2 * nz * n_theta + 2 * n_theta * (2 * nrings - 1)
    assert int((fa == -1).sum()) == expect_boundary


def test_pincell_counts_scale():
    n_theta, nrf, nrp, nz = 32, 5, 5, 12
    coords, tets, region = pincell_arrays(
        n_theta=n_theta, n_rings_fuel=nrf, n_rings_pad=nrp, nz=nz
    )
    assert tets.shape[0] == 3 * nz * n_theta * (2 * (nrf + nrp) - 1)
    assert region.shape[0] == tets.shape[0]


def test_pincell_walk_conserves_track_length():
    """Random interior transport on the pincell conserves total track
    length — fails if the prism split left holes or non-conforming
    faces (particles would exit through an interior 'boundary')."""
    mesh, _ = build_pincell(pitch=PITCH, fuel_radius=R, height=1.0)
    n = 2000
    rng = np.random.default_rng(5)
    lo, hi = 0.05, PITCH - 0.05
    src = np.column_stack([
        rng.uniform(lo, hi, n), rng.uniform(lo, hi, n),
        rng.uniform(0.05, 0.95, n),
    ])
    dst = np.column_stack([
        rng.uniform(lo, hi, n), rng.uniform(lo, hi, n),
        rng.uniform(0.05, 0.95, n),
    ])
    t = PumiTally(mesh, n, TallyConfig())
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    total = float(np.asarray(t.flux).sum())
    expect = float(np.linalg.norm(dst - src, axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-10)
    # Nobody exited: all destinations are interior.
    np.testing.assert_allclose(t.positions, dst, atol=1e-9)
