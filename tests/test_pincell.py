"""Pincell O-grid mesh builder (BASELINE configs[0-1] geometry)."""

import math

import numpy as np
import pytest

from pumiumtally_tpu import PumiTally, TallyConfig
from pumiumtally_tpu.mesh.pincell import build_pincell, pincell_arrays

PITCH = 1.26
R = 0.4095


def test_pincell_fills_the_cell_exactly():
    height = 1.5
    mesh, region = build_pincell(pitch=PITCH, fuel_radius=R, height=height)
    vols = np.asarray(mesh.volumes)
    # Conforming cover of the square cell: signed volumes are all
    # positive (from_arrays validates) and sum EXACTLY to pitch^2 * h —
    # any overlap or gap would break the identity.
    np.testing.assert_allclose(vols.sum(), PITCH**2 * height, rtol=1e-12)
    # Fuel region approximates the cylinder (inscribed polygon, 16
    # sectors -> ~2.6% low, never high).
    fuel = vols[region == 0].sum()
    assert fuel < math.pi * R**2 * height
    assert fuel > 0.95 * math.pi * R**2 * height
    # Exact boundary topology: lateral surface = 2 tris per side quad
    # (nz layers x n_theta sectors), caps = the 2-D triangulation's
    # n_theta*(2*nrings-1) triangles each.
    n_theta, nrings, nz = 16, 6, 4  # build_pincell defaults
    fa = np.asarray(mesh.face_adj)
    expect_boundary = 2 * nz * n_theta + 2 * n_theta * (2 * nrings - 1)
    assert int((fa == -1).sum()) == expect_boundary


def test_pincell_counts_scale():
    n_theta, nrf, nrp, nz = 32, 5, 5, 12
    coords, tets, region = pincell_arrays(
        n_theta=n_theta, n_rings_fuel=nrf, n_rings_pad=nrp, nz=nz
    )
    assert tets.shape[0] == 3 * nz * n_theta * (2 * (nrf + nrp) - 1)
    assert region.shape[0] == tets.shape[0]


def test_pincell_walk_conserves_track_length():
    """Random interior transport on the pincell conserves total track
    length — fails if the prism split left holes or non-conforming
    faces (particles would exit through an interior 'boundary')."""
    mesh, _ = build_pincell(pitch=PITCH, fuel_radius=R, height=1.0)
    n = 2000
    rng = np.random.default_rng(5)
    lo, hi = 0.05, PITCH - 0.05
    src = np.column_stack([
        rng.uniform(lo, hi, n), rng.uniform(lo, hi, n),
        rng.uniform(0.05, 0.95, n),
    ])
    dst = np.column_stack([
        rng.uniform(lo, hi, n), rng.uniform(lo, hi, n),
        rng.uniform(0.05, 0.95, n),
    ])
    t = PumiTally(mesh, n, TallyConfig())
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(src.reshape(-1).copy(), dst.reshape(-1).copy(),
                         np.ones(n, np.int8), np.ones(n))
    total = float(np.asarray(t.flux).sum())
    expect = float(np.linalg.norm(dst - src, axis=1).sum())
    np.testing.assert_allclose(total, expect, rtol=1e-10)
    # Nobody exited: all destinations are interior.
    np.testing.assert_allclose(t.positions, dst, atol=1e-9)


def test_lattice_conforming_and_transport():
    """nx x ny assembly (BASELINE configs[1-2] geometry class): welded
    cell interfaces are conforming — the boundary-face count equals the
    analytic hull count, and particles crossing cell boundaries
    conserve track length exactly (a gap would clamp them early)."""
    from pumiumtally_tpu import PumiTally, TallyConfig
    from pumiumtally_tpu.mesh.pincell import build_lattice

    nx, ny, nz, n_theta = 3, 2, 3, 16
    pitch, height = 1.26, 1.0
    mesh, region, cell_id = build_lattice(
        nx, ny, pitch=pitch, height=height, n_theta=n_theta,
        n_rings_fuel=2, n_rings_pad=2, nz=nz,
    )
    vol = float(np.asarray(mesh.volumes).sum())
    np.testing.assert_allclose(vol, nx * ny * pitch * pitch * height,
                               rtol=1e-12)
    nb = int((np.asarray(mesh.face_adj) == -1).sum())
    t2d = mesh.nelems // (3 * nz)
    assert nb == 2 * t2d + 2 * (nx + ny) * (n_theta // 4) * nz * 2

    n = 4000
    rng = np.random.default_rng(31)
    box = np.array([nx * pitch, ny * pitch, height])
    src = rng.uniform(0.03, 0.97, (n, 3)) * box
    # long diagonal flights spanning several cells
    dest = rng.uniform(0.03, 0.97, (n, 3)) * box
    t = PumiTally(mesh, n, TallyConfig(localization="locate"))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dest.reshape(-1).copy())
    got = float(np.sum(np.asarray(t.flux)))
    want = float(np.linalg.norm(dest - src, axis=1).sum())
    assert abs(got - want) / want < 1e-12

    # per-cell flux decomposition: flux·volume restricted to one cell's
    # elements is bounded by that cell's share and all cells sum to the
    # total exactly
    flux = np.asarray(t.flux)
    per_cell = np.array([
        flux[cell_id == c].sum() for c in range(nx * ny)
    ])
    np.testing.assert_allclose(per_cell.sum(), got, rtol=1e-12)
    assert np.all(per_cell > 0)

    # region labels: fuel volume fraction matches pi r^2 / pitch^2 to
    # the O-grid's polygonal approximation (coarse -> few %)
    vols = np.asarray(mesh.volumes)
    frac = vols[region == 0].sum() / vols.sum()
    want_frac = np.pi * 0.4095**2 / pitch**2
    assert abs(frac - want_frac) / want_frac < 0.05


def test_lattice_1x1_equals_pincell():
    from pumiumtally_tpu.mesh.pincell import build_lattice, build_pincell

    m1, r1, c1 = build_lattice(1, 1, n_theta=8, n_rings_fuel=2,
                               n_rings_pad=2, nz=2)
    p1, pr1 = build_pincell(n_theta=8, n_rings_fuel=2, n_rings_pad=2, nz=2)
    assert m1.nelems == p1.nelems
    np.testing.assert_array_equal(r1, pr1)
    assert np.all(c1 == 0)
    np.testing.assert_allclose(
        np.asarray(m1.volumes).sum(), np.asarray(p1.volumes).sum(),
        rtol=1e-12,
    )


@pytest.mark.slow
def test_lattice_partitioned_matches_monolithic():
    """Partitioned engine over the assembly geometry: RCB ownership of
    the O-grid cells, migration across curved-ring interfaces; flux
    matches the monolithic engine exactly."""
    from pumiumtally_tpu import PartitionedPumiTally, PumiTally, TallyConfig
    from pumiumtally_tpu.mesh.pincell import build_lattice
    from pumiumtally_tpu.parallel import make_device_mesh

    mesh, region, cid = build_lattice(2, 2, n_theta=8, n_rings_fuel=2,
                                      n_rings_pad=2, nz=2)
    dm = make_device_mesh(4)
    n = 2000
    pitch = 1.26
    rng = np.random.default_rng(32)
    box = np.array([2 * pitch, 2 * pitch, 1.0])
    src = rng.uniform(0.03, 0.97, (n, 3)) * box
    dest = rng.uniform(0.03, 0.97, (n, 3)) * box

    par = PartitionedPumiTally(
        mesh, n, TallyConfig(device_mesh=dm, capacity_factor=3.0)
    )
    par.CopyInitialPosition(src.reshape(-1).copy())
    par.MoveToNextLocation(None, dest.reshape(-1).copy())

    ref = PumiTally(mesh, n)
    ref.CopyInitialPosition(src.reshape(-1).copy())
    ref.MoveToNextLocation(None, dest.reshape(-1).copy())
    np.testing.assert_allclose(
        np.asarray(par.flux), np.asarray(ref.flux), rtol=1e-11, atol=1e-13
    )


def test_label_reductions_on_lattice():
    """Per-cell and per-material reductions recover analytic totals."""
    from pumiumtally_tpu import PumiTally, TallyConfig
    from pumiumtally_tpu.mesh.pincell import build_lattice
    from pumiumtally_tpu.utils.postprocess import label_averages, label_totals

    mesh, region, cid = build_lattice(3, 2, n_theta=8, n_rings_fuel=2,
                                      n_rings_pad=2, nz=2)
    n = 3000
    pitch = 1.26
    rng = np.random.default_rng(33)
    box = np.array([3 * pitch, 2 * pitch, 1.0])
    src = rng.uniform(0.03, 0.97, (n, 3)) * box
    dest = rng.uniform(0.03, 0.97, (n, 3)) * box
    t = PumiTally(mesh, n, TallyConfig(localization="locate"))
    t.CopyInitialPosition(src.reshape(-1).copy())
    t.MoveToNextLocation(None, dest.reshape(-1).copy())

    vols = np.asarray(mesh.volumes)
    nflux = np.asarray(t.normalized_flux())
    want_total = float(np.linalg.norm(dest - src, axis=1).sum())

    per_cell = label_totals(nflux, vols, cid)
    assert per_cell.shape[0] == 6
    np.testing.assert_allclose(per_cell.sum(), want_total, rtol=1e-12)
    per_mat = label_totals(nflux, vols, region)
    np.testing.assert_allclose(per_mat.sum(), want_total, rtol=1e-12)

    mean, lab_vols = label_averages(nflux, vols, cid)
    np.testing.assert_allclose(lab_vols.sum(), vols.sum(), rtol=1e-12)
    np.testing.assert_allclose(mean * lab_vols, per_cell, rtol=1e-12)

    with pytest.raises(ValueError, match="entries"):
        label_totals(nflux, vols, cid[:-1])
    with pytest.raises(ValueError, match="non-negative"):
        label_totals(nflux, vols, cid - 1)


def test_label_reductions_validation_and_minlength():
    from pumiumtally_tpu.utils.postprocess import label_totals

    flux = np.array([1.0, 2.0])
    vol = np.array([0.5, 0.5])
    # float labels with exactly integral values are accepted
    np.testing.assert_allclose(
        label_totals(flux, vol, np.array([0.0, 1.0])), [0.5, 1.0]
    )
    # non-integral float labels are rejected, not truncated
    with pytest.raises(ValueError, match="integral"):
        label_totals(flux, vol, np.array([0.0, 1.5]))
    # trailing empty labels keep their slots via num_labels
    out = label_totals(flux, vol, np.array([0, 1]), num_labels=6)
    assert out.shape[0] == 6 and out[2:].sum() == 0
