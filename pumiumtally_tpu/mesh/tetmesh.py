"""Tetrahedral mesh with precomputed walk geometry.

TPU-native replacement for the Omega_h mesh layer (SURVEY.md §1 L1) and
the PUMIPic picparts wrapper (SURVEY.md §2.2). Where the reference asks
Omega_h for downward adjacency and simplex geometry on demand
(``ask_down(REGION, VERT)``, ``simplex_basis`` — reference
PumiTallyImpl.cpp:384-407), we precompute everything the walk kernel
needs ONCE on the host and ship it to HBM as flat arrays:

- ``coords[V,3]``        vertex coordinates
- ``tet2vert[E,4]``      tet connectivity (positively oriented)
- ``face_normals[E,4,3]`` unit OUTWARD normal of the face opposite each
                          local vertex
- ``face_offsets[E,4]``  plane offset: ``n · p`` for any point p on the face
- ``face_adj[E,4]``      neighbor tet across each face, −1 at the boundary
                          (replaces PUMIPic's adjacency search structures)
- ``volumes[E]``         tet volumes (reference NormalizeFlux,
                          PumiTallyImpl.cpp:382-409)

This turns the per-step ray/tet-face intersection into four dot products
and a gather — dense, static-shaped work that XLA vectorizes over the
whole particle batch (no per-particle pointer chasing as in the Kokkos
implementation).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Local face f is the face opposite local vertex f.
_FACE_OF_VERT = np.array(
    [[1, 2, 3], [0, 2, 3], [0, 1, 3], [0, 1, 2]], dtype=np.int32
)


def _signed_volumes(coords: np.ndarray, tet2vert: np.ndarray) -> np.ndarray:
    v = coords[tet2vert]  # [E,4,3]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    return np.einsum("ei,ei->e", np.cross(a, b), c) / 6.0


def _build_face_adjacency(tet2vert: np.ndarray) -> np.ndarray:
    """face_adj[E,4]: tet across the face opposite local vertex f, or -1.

    Vectorized half-face matching: each tet contributes 4 faces keyed by
    their sorted global vertex triple; identical keys appearing twice are
    interior faces shared by two tets.
    """
    ne = tet2vert.shape[0]
    faces = tet2vert[:, _FACE_OF_VERT]  # [E,4,3]
    keys = np.sort(faces.reshape(-1, 3), axis=1)  # [4E,3]
    order = np.lexsort((keys[:, 2], keys[:, 1], keys[:, 0]))
    sk = keys[order]
    same = np.all(sk[1:] == sk[:-1], axis=1)
    # owning tet of each half-face, in sorted order
    owner = order // 4
    face_adj = np.full(ne * 4, -1, dtype=np.int32)
    lo = np.nonzero(same)[0]  # sk[lo] == sk[lo+1] → paired half-faces
    face_adj[order[lo]] = owner[lo + 1]
    face_adj[order[lo + 1]] = owner[lo]
    return face_adj.reshape(ne, 4)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class TetMesh:
    """Immutable tet mesh as a pytree of device arrays."""

    coords: Any  # [V,3] float
    tet2vert: Any  # [E,4] int32
    face_normals: Any  # [E,4,3] float, unit outward
    face_offsets: Any  # [E,4] float
    face_adj: Any  # [E,4] int32, -1 = boundary
    volumes: Any  # [E] float

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        children = (
            self.coords,
            self.tet2vert,
            self.face_normals,
            self.face_offsets,
            self.face_adj,
            self.volumes,
        )
        return children, None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- construction ---------------------------------------------------
    @classmethod
    def from_arrays(
        cls, coords: np.ndarray, tet2vert: np.ndarray, dtype: Any = None
    ) -> "TetMesh":
        """Build a mesh (host-side precompute) from raw connectivity.

        Reorders each tet for positive orientation, computes outward face
        planes, face adjacency, and volumes.
        """
        if dtype is None:
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        coords = np.asarray(coords, dtype=np.float64)
        tet2vert = np.array(tet2vert, dtype=np.int32, copy=True)
        if coords.ndim != 2 or coords.shape[1] != 3:
            raise ValueError(f"coords must be [V,3], got {coords.shape}")
        if tet2vert.ndim != 2 or tet2vert.shape[1] != 4:
            raise ValueError(f"tet2vert must be [E,4], got {tet2vert.shape}")

        # Positive orientation: swap two verts where the signed volume < 0.
        sv = _signed_volumes(coords, tet2vert)
        neg = sv < 0
        tet2vert[neg, 2], tet2vert[neg, 3] = (
            tet2vert[neg, 3].copy(),
            tet2vert[neg, 2].copy(),
        )
        volumes = _signed_volumes(coords, tet2vert)
        if np.any(volumes <= 0):
            bad = int(np.sum(volumes <= 0))
            raise ValueError(f"{bad} degenerate (zero-volume) tets in mesh")

        v = coords[tet2vert]  # [E,4,3]
        # Face opposite vertex f: other three vertices.
        fa = v[:, _FACE_OF_VERT]  # [E,4,3verts,3xyz]
        e1 = fa[:, :, 1] - fa[:, :, 0]
        e2 = fa[:, :, 2] - fa[:, :, 0]
        n = np.cross(e1, e2)  # [E,4,3]
        # Outward: n · (v_opp - face_point) must be negative.
        opp = v  # vertex f itself, [E,4,3]
        s = np.einsum("efc,efc->ef", n, opp - fa[:, :, 0])
        n = np.where((s > 0)[..., None], -n, n)
        norm = np.linalg.norm(n, axis=2, keepdims=True)
        n = n / norm
        offsets = np.einsum("efc,efc->ef", n, fa[:, :, 0])

        face_adj = _build_face_adjacency(tet2vert)

        return cls(
            coords=jnp.asarray(coords, dtype=dtype),
            tet2vert=jnp.asarray(tet2vert),
            face_normals=jnp.asarray(n, dtype=dtype),
            face_offsets=jnp.asarray(offsets, dtype=dtype),
            face_adj=jnp.asarray(face_adj),
            volumes=jnp.asarray(volumes, dtype=dtype),
        )

    # -- queries ---------------------------------------------------------
    @property
    def nelems(self) -> int:
        return int(self.tet2vert.shape[0])

    @property
    def nverts(self) -> int:
        return int(self.coords.shape[0])

    def centroids(self) -> jnp.ndarray:
        """Element centroids [E,3] (reference InitializeParticlesInElement0
        computes the centroid of element 0 this way, PumiTallyImpl.cpp:500-509)."""
        return jnp.mean(self.coords[self.tet2vert], axis=1)

    def bounding_box(self) -> Tuple[np.ndarray, np.ndarray]:
        c = np.asarray(self.coords)
        return c.min(axis=0), c.max(axis=0)

    def astype(self, dtype: Any) -> "TetMesh":
        return TetMesh(
            coords=self.coords.astype(dtype),
            tet2vert=self.tet2vert,
            face_normals=self.face_normals.astype(dtype),
            face_offsets=self.face_offsets.astype(dtype),
            face_adj=self.face_adj,
            volumes=self.volumes.astype(dtype),
        )
