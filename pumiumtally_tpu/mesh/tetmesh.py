"""Tetrahedral mesh with precomputed walk geometry.

TPU-native replacement for the Omega_h mesh layer (SURVEY.md §1 L1) and
the PUMIPic picparts wrapper (SURVEY.md §2.2). Where the reference asks
Omega_h for downward adjacency and simplex geometry on demand
(``ask_down(REGION, VERT)``, ``simplex_basis`` — reference
PumiTallyImpl.cpp:384-407), we precompute everything the walk kernel
needs ONCE on the host and ship it to HBM as flat arrays:

- ``coords[V,3]``        vertex coordinates
- ``tet2vert[E,4]``      tet connectivity (positively oriented)
- ``face_normals[E,4,3]`` unit OUTWARD normal of the face opposite each
                          local vertex
- ``face_offsets[E,4]``  plane offset: ``n · p`` for any point p on the face
- ``face_adj[E,4]``      neighbor tet across each face, −1 at the boundary
                          (replaces PUMIPic's adjacency search structures)
- ``volumes[E]``         tet volumes (reference NormalizeFlux,
                          PumiTallyImpl.cpp:382-409)
- ``walk_table[E,20]``   the three walk arrays packed into ONE row per
                          tet (normals | offsets | adj-as-float) so the
                          per-iteration gather in the walk kernel is a
                          single contiguous-row gather — ~2.6× faster on
                          TPU than three separate gathers. ``None`` when
                          the float dtype cannot represent every element
                          id exactly (f32 and E ≥ 2^24).

This turns the per-step ray/tet-face intersection into four dot products
and a gather — dense, static-shaped work that XLA vectorizes over the
whole particle batch (no per-particle pointer chasing as in the Kokkos
implementation).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Local face f is the face opposite local vertex f.
_FACE_OF_VERT = np.array(
    [[1, 2, 3], [0, 2, 3], [0, 1, 3], [0, 1, 2]], dtype=np.int32
)


def _exact_id_limit(dtype: Any) -> int:
    """Largest count of element ids exactly representable in ``dtype``
    (2^(mantissa bits + 1)): 2^24 for f32, 2^53 for f64, 2^8 for bf16."""
    return 2 ** (jnp.finfo(jnp.dtype(dtype)).nmant + 1)


# The packed walk-table row layout: one [WALK_TABLE_WIDTH]-float row per
# tet, gathered in a single op by the walk kernel (ops/walk.py slices it
# back with these constants — keep the two files in sync through them).
WALK_TABLE_NORMALS = slice(0, 12)  # 4 faces × 3 components
WALK_TABLE_OFFSETS = slice(12, 16)  # 4 face-plane offsets
WALK_TABLE_ADJ = slice(16, 20)  # 4 neighbor ids, as floats
WALK_TABLE_WIDTH = 20

# Two-tier layout (the bf16 select tier + full-precision refinement
# tier; docs/PERF_NOTES.md "Table precision tiers"). The SELECT tier is
# a half-width bf16 row holding only the face planes — adjacency ids
# cannot live in bf16 lanes (8 mantissa bits ⇒ exact only below 2^8).
# The REFINEMENT tier is a per-FACE table: row ``elem*4 + f`` holds
# ``(nx, ny, nz, off, adj)`` of face f in the working dtype, so
# recomputing the WINNING face's crossing exactly AND fetching its
# neighbor id costs ONE [WALK_PLANE_WIDTH]-row gather (20 B f32)
# instead of re-fetching the whole 80 B packed row plus an adjacency
# row. The adj lane carries the id as a float — exact below
# 2^(mantissa+1), the SAME ceiling the packed [E,20] layout already
# lives under — so the two-tier build refuses past it rather than
# silently corrupting neighbor ids.
WALK_TABLE_LO_NORMALS = slice(0, 12)  # bf16, 4 faces × 3 components
WALK_TABLE_LO_OFFSETS = slice(12, 16)  # bf16, 4 face-plane offsets
WALK_TABLE_LO_WIDTH = 16
WALK_PLANE_WIDTH = 5  # refinement row: (nx, ny, nz, off, adj) of ONE face


def _pack_walk_table(xp, normals, offsets, adj):
    """Assemble the [E,WALK_TABLE_WIDTH] row (xp: np or jnp namespace).
    Inputs must be float64 (or exact) so adj ids survive the cast."""
    ne = offsets.shape[0]
    row = xp.concatenate(
        [
            normals.reshape(ne, 12),
            offsets,
            adj.astype(xp.float64),
        ],
        axis=1,
    )
    assert row.shape[1] == WALK_TABLE_WIDTH
    return row


def pack_lo_table(xp, normals, offsets):
    """Assemble the bf16 SELECT tier: [E,WALK_TABLE_LO_WIDTH] rows of
    normals|offsets (xp: np or jnp namespace). bf16 rounding happens
    here, once, on the host-precision inputs."""
    ne = offsets.shape[0]
    row = xp.concatenate([normals.reshape(ne, 12), offsets], axis=1)
    assert row.shape[1] == WALK_TABLE_LO_WIDTH
    return jnp.asarray(row, dtype=jnp.bfloat16)


def pack_plane_table(xp, normals, offsets, adj, dtype):
    """Assemble the REFINEMENT tier: [E*4, WALK_PLANE_WIDTH] rows, one
    per (elem, face), holding (nx, ny, nz, off, adj) in ``dtype``.
    ``adj`` rows must carry ids exactly representable in ``dtype``
    (caller-checked via ``_exact_id_limit``) and must be float64 (or
    exact) on entry so they survive the cast, like the packed table."""
    ne = offsets.shape[0]
    row = xp.concatenate(
        [
            normals.reshape(ne * 4, 3),
            offsets.reshape(ne * 4, 1),
            adj.astype(xp.float64).reshape(ne * 4, 1),
        ],
        axis=1,
    )
    assert row.shape[1] == WALK_PLANE_WIDTH
    return jnp.asarray(row, dtype=dtype)


def _signed_volumes(coords: np.ndarray, tet2vert: np.ndarray) -> np.ndarray:
    v = coords[tet2vert]  # [E,4,3]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    return np.einsum("ei,ei->e", np.cross(a, b), c) / 6.0


def _build_face_adjacency(tet2vert: np.ndarray) -> np.ndarray:
    """face_adj[E,4]: tet across the face opposite local vertex f, or -1.

    Vectorized half-face matching: each tet contributes 4 faces keyed by
    their sorted global vertex triple; identical keys appearing twice are
    interior faces shared by two tets.
    """
    ne = tet2vert.shape[0]
    faces = tet2vert[:, _FACE_OF_VERT]  # [E,4,3]
    keys = np.sort(faces.reshape(-1, 3), axis=1)  # [4E,3]
    order = np.lexsort((keys[:, 2], keys[:, 1], keys[:, 0]))
    sk = keys[order]
    same = np.all(sk[1:] == sk[:-1], axis=1)
    # owning tet of each half-face, in sorted order
    owner = order // 4
    face_adj = np.full(ne * 4, -1, dtype=np.int32)
    lo = np.nonzero(same)[0]  # sk[lo] == sk[lo+1] → paired half-faces
    face_adj[order[lo]] = owner[lo + 1]
    face_adj[order[lo + 1]] = owner[lo]
    return face_adj.reshape(ne, 4)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class TetMesh:
    """Immutable tet mesh as a pytree of device arrays."""

    coords: Any  # [V,3] float
    tet2vert: Any  # [E,4] int32
    face_adj: Any  # [E,4] int32, -1 = boundary
    volumes: Any  # [E] float
    walk_table: Any = None  # [E,20] float: normals|offsets|adj, or None
    # Stored ONLY when walk_table is None (element ids not exactly
    # representable in the float dtype); otherwise face planes live
    # solely in walk_table and the properties below slice views out of
    # it — walk geometry is kept once in HBM, not twice.
    stored_face_normals: Any = None  # [E,4,3] float, unit outward
    stored_face_offsets: Any = None  # [E,4] float
    # Two-tier walk tables (both non-None, or both None): the bf16
    # SELECT tier gathered per crossing to pick the exit face, and the
    # full-precision per-face REFINEMENT tier gathered once for the
    # winning face only. When present, ``walk_table`` is dropped — the
    # refinement tier is then the full-precision source of truth the
    # face_normals/face_offsets properties derive from.
    walk_table_lo: Any = None  # [E,WALK_TABLE_LO_WIDTH] bf16
    walk_table_hi: Any = None  # [E*4,WALK_PLANE_WIDTH] working dtype

    @property
    def face_normals(self) -> Any:
        if self.stored_face_normals is not None:
            return self.stored_face_normals
        if self.walk_table is not None:
            ne = self.walk_table.shape[0]
            return self.walk_table[:, WALK_TABLE_NORMALS].reshape(ne, 4, 3)
        ne = self.walk_table_hi.shape[0] // 4
        return self.walk_table_hi.reshape(ne, 4, WALK_PLANE_WIDTH)[:, :, :3]

    @property
    def face_offsets(self) -> Any:
        if self.stored_face_offsets is not None:
            return self.stored_face_offsets
        if self.walk_table is not None:
            return self.walk_table[:, WALK_TABLE_OFFSETS]
        ne = self.walk_table_hi.shape[0] // 4
        return self.walk_table_hi.reshape(ne, 4, WALK_PLANE_WIDTH)[:, :, 3]

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        children = (
            self.coords,
            self.tet2vert,
            self.face_adj,
            self.volumes,
            self.walk_table,
            self.stored_face_normals,
            self.stored_face_offsets,
            self.walk_table_lo,
            self.walk_table_hi,
        )
        return children, None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- construction ---------------------------------------------------
    @classmethod
    def from_arrays(
        cls, coords: np.ndarray, tet2vert: np.ndarray, dtype: Any = None,
        force_unpacked: bool = False, table_dtype: str = "float32",
    ) -> "TetMesh":
        """Build a mesh (host-side precompute) from raw connectivity.

        Reorders each tet for positive orientation, computes outward face
        planes, face adjacency, and volumes. ``force_unpacked`` keeps
        the walk arrays separate (the layout meshes past the exact
        float-id limit fall back to) — for testing that path at small
        sizes. ``table_dtype="bfloat16"`` builds the two-tier walk
        tables (bf16 select tier + working-dtype per-face refinement
        tier) straight from the f64 intermediates instead of the packed
        f32 row table.
        """
        if dtype is None:
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        coords = np.asarray(coords, dtype=np.float64)
        tet2vert = np.array(tet2vert, dtype=np.int32, copy=True)
        if coords.ndim != 2 or coords.shape[1] != 3:
            raise ValueError(f"coords must be [V,3], got {coords.shape}")
        if tet2vert.ndim != 2 or tet2vert.shape[1] != 4:
            raise ValueError(f"tet2vert must be [E,4], got {tet2vert.shape}")

        # Positive orientation: swap two verts where the signed volume < 0.
        sv = _signed_volumes(coords, tet2vert)
        neg = sv < 0
        tet2vert[neg, 2], tet2vert[neg, 3] = (
            tet2vert[neg, 3].copy(),
            tet2vert[neg, 2].copy(),
        )
        volumes = _signed_volumes(coords, tet2vert)
        if np.any(volumes <= 0):
            bad = int(np.sum(volumes <= 0))
            raise ValueError(f"{bad} degenerate (zero-volume) tets in mesh")

        v = coords[tet2vert]  # [E,4,3]
        # Face opposite vertex f: other three vertices.
        fa = v[:, _FACE_OF_VERT]  # [E,4,3verts,3xyz]
        e1 = fa[:, :, 1] - fa[:, :, 0]
        e2 = fa[:, :, 2] - fa[:, :, 0]
        n = np.cross(e1, e2)  # [E,4,3]
        # Outward: n · (v_opp - face_point) must be negative.
        opp = v  # vertex f itself, [E,4,3]
        s = np.einsum("efc,efc->ef", n, opp - fa[:, :, 0])
        n = np.where((s > 0)[..., None], -n, n)
        norm = np.linalg.norm(n, axis=2, keepdims=True)
        n = n / norm
        offsets = np.einsum("efc,efc->ef", n, fa[:, :, 0])

        face_adj = _build_face_adjacency(tet2vert)

        # Packed per-tet walk row (see module docstring). Element ids are
        # stored in the float dtype; exact only below 2^(mantissa+1) —
        # past that the walk falls back to separate gathers.
        ne = tet2vert.shape[0]
        lo = hi = None
        if table_dtype == "bfloat16":
            # Two-tier tables from the f64 intermediates. The
            # refinement tier carries the winning face's neighbor id in
            # its float adj lane — same exactness ceiling as the packed
            # layout, enforced rather than silently corrupted.
            if ne >= _exact_id_limit(dtype):
                raise ValueError(
                    f"two-tier walk tables store neighbor ids in "
                    f"{np.dtype(dtype).name} refinement rows; {ne} "
                    f"elements exceed the exact-id limit "
                    f"{_exact_id_limit(dtype)}"
                )
            walk_table = None
            stored_n = stored_off = None
            lo = pack_lo_table(np, n, offsets)
            hi = pack_plane_table(np, n, offsets, face_adj, dtype)
        elif ne < _exact_id_limit(dtype) and not force_unpacked:
            walk_table = jnp.asarray(
                _pack_walk_table(np, n, offsets, face_adj), dtype=dtype
            )
            stored_n = stored_off = None
        else:
            walk_table = None
            stored_n = jnp.asarray(n, dtype=dtype)
            stored_off = jnp.asarray(offsets, dtype=dtype)

        return cls(
            coords=jnp.asarray(coords, dtype=dtype),
            tet2vert=jnp.asarray(tet2vert),
            face_adj=jnp.asarray(face_adj),
            volumes=jnp.asarray(volumes, dtype=dtype),
            walk_table=walk_table,
            stored_face_normals=stored_n,
            stored_face_offsets=stored_off,
            walk_table_lo=lo,
            walk_table_hi=hi,
        )

    # -- queries ---------------------------------------------------------
    @property
    def nelems(self) -> int:
        return int(self.tet2vert.shape[0])

    @property
    def nverts(self) -> int:
        return int(self.coords.shape[0])

    def centroids(self) -> jnp.ndarray:
        """Element centroids [E,3] (reference InitializeParticlesInElement0
        computes the centroid of element 0 this way, PumiTallyImpl.cpp:500-509)."""
        return jnp.mean(self.coords[self.tet2vert], axis=1)

    def bounding_box(self) -> Tuple[np.ndarray, np.ndarray]:
        c = np.asarray(self.coords)
        return c.min(axis=0), c.max(axis=0)

    def with_lowp_tables(self) -> "TetMesh":
        """This mesh with the two-tier walk tables (bf16 select tier +
        working-dtype refinement tier) in place of the packed f32 row
        table. Idempotent. The tiers are built from the current
        full-precision planes — when the mesh came from ``from_arrays``
        those are the f64-derived values rounded once to the working
        dtype, so a post-hoc conversion differs from a
        ``table_dtype="bfloat16"`` build only below working-dtype
        precision (invisible at bf16 granularity for the select tier).
        """
        if self.walk_table_lo is not None:
            return self
        dtype = self.volumes.dtype
        if self.tet2vert.shape[0] >= _exact_id_limit(dtype):
            raise ValueError(
                "two-tier walk tables store neighbor ids in "
                f"{jnp.dtype(dtype).name} refinement rows; "
                f"{self.tet2vert.shape[0]} elements exceed the exact-id "
                f"limit {_exact_id_limit(dtype)}"
            )
        # The planes need no f64 round-trip (bf16/working-dtype
        # rounding of the stored values IS the conversion); the adj
        # lane goes through f64 inside pack_plane_table so ids survive
        # the cast, like the packed-row rebuild in astype().
        fn = self.face_normals
        fo = self.face_offsets
        return TetMesh(
            coords=self.coords,
            tet2vert=self.tet2vert,
            face_adj=self.face_adj,
            volumes=self.volumes,
            walk_table=None,
            stored_face_normals=None,
            stored_face_offsets=None,
            walk_table_lo=pack_lo_table(jnp, fn, fo),
            walk_table_hi=pack_plane_table(jnp, fn, fo, self.face_adj,
                                           dtype),
        )

    def astype(self, dtype: Any) -> "TetMesh":
        ne = self.tet2vert.shape[0]
        if self.walk_table_lo is not None:
            # Two-tier meshes stay two-tier: the select tier is already
            # bf16 (re-rounding is the identity) and the refinement
            # tier converts directly — its adj lane holds integers
            # whose f32/f64 conversions are exact within the checked
            # id limit.
            if ne >= _exact_id_limit(dtype):
                raise ValueError(
                    f"cannot convert two-tier tables to "
                    f"{jnp.dtype(dtype).name}: {ne} elements exceed "
                    f"the exact-id limit {_exact_id_limit(dtype)}"
                )
            return TetMesh(
                coords=self.coords.astype(dtype),
                tet2vert=self.tet2vert,
                face_adj=self.face_adj,
                volumes=self.volumes.astype(dtype),
                walk_table=None,
                stored_face_normals=None,
                stored_face_offsets=None,
                walk_table_lo=self.walk_table_lo,
                walk_table_hi=self.walk_table_hi.astype(dtype),
            )
        # A mesh already in the unpacked layout stays unpacked: its ids
        # may exceed the new dtype's exact range too, and a
        # force_unpacked test mesh must not silently repack.
        if self.walk_table is not None and ne < _exact_id_limit(dtype):
            # Rebuild the table from f64 intermediates so adj ids stay
            # exact through the conversion (guarded by the limit check).
            walk_table = _pack_walk_table(
                jnp,
                self.face_normals.astype(jnp.float64),
                self.face_offsets.astype(jnp.float64),
                self.face_adj,
            ).astype(dtype)
            stored_n = stored_off = None
        else:
            walk_table = None
            stored_n = self.face_normals.astype(dtype)
            stored_off = self.face_offsets.astype(dtype)
        return TetMesh(
            coords=self.coords.astype(dtype),
            tet2vert=self.tet2vert,
            face_adj=self.face_adj,
            volumes=self.volumes.astype(dtype),
            walk_table=walk_table,
            stored_face_normals=stored_n,
            stored_face_offsets=stored_off,
        )
