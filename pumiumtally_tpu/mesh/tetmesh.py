"""Tetrahedral mesh with precomputed walk geometry.

TPU-native replacement for the Omega_h mesh layer (SURVEY.md §1 L1) and
the PUMIPic picparts wrapper (SURVEY.md §2.2). Where the reference asks
Omega_h for downward adjacency and simplex geometry on demand
(``ask_down(REGION, VERT)``, ``simplex_basis`` — reference
PumiTallyImpl.cpp:384-407), we precompute everything the walk kernel
needs ONCE on the host and ship it to HBM as flat arrays:

- ``coords[V,3]``        vertex coordinates
- ``tet2vert[E,4]``      tet connectivity (positively oriented)
- ``face_normals[E,4,3]`` unit OUTWARD normal of the face opposite each
                          local vertex
- ``face_offsets[E,4]``  plane offset: ``n · p`` for any point p on the face
- ``face_adj[E,4]``      neighbor tet across each face, −1 at the boundary
                          (replaces PUMIPic's adjacency search structures)
- ``volumes[E]``         tet volumes (reference NormalizeFlux,
                          PumiTallyImpl.cpp:382-409)
- ``walk_table[E,20]``   the three walk arrays packed into ONE row per
                          tet (normals | offsets | adj-as-float) so the
                          per-iteration gather in the walk kernel is a
                          single contiguous-row gather — ~2.6× faster on
                          TPU than three separate gathers. ``None`` when
                          the float dtype cannot represent every element
                          id exactly (f32 and E ≥ 2^24).

This turns the per-step ray/tet-face intersection into four dot products
and a gather — dense, static-shaped work that XLA vectorizes over the
whole particle batch (no per-particle pointer chasing as in the Kokkos
implementation).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Local face f is the face opposite local vertex f.
_FACE_OF_VERT = np.array(
    [[1, 2, 3], [0, 2, 3], [0, 1, 3], [0, 1, 2]], dtype=np.int32
)


def _exact_id_limit(dtype: Any) -> int:
    """Largest count of element ids exactly representable in ``dtype``
    (2^(mantissa bits + 1)): 2^24 for f32, 2^53 for f64, 2^8 for bf16."""
    return 2 ** (jnp.finfo(jnp.dtype(dtype)).nmant + 1)


# The packed walk-table row layout: one [WALK_TABLE_WIDTH]-float row per
# tet, gathered in a single op by the walk kernel (ops/walk.py slices it
# back with these constants — keep the two files in sync through them).
WALK_TABLE_NORMALS = slice(0, 12)  # 4 faces × 3 components
WALK_TABLE_OFFSETS = slice(12, 16)  # 4 face-plane offsets
WALK_TABLE_ADJ = slice(16, 20)  # 4 neighbor ids, as floats
WALK_TABLE_WIDTH = 20


def _pack_walk_table(xp, normals, offsets, adj):
    """Assemble the [E,WALK_TABLE_WIDTH] row (xp: np or jnp namespace).
    Inputs must be float64 (or exact) so adj ids survive the cast."""
    ne = offsets.shape[0]
    row = xp.concatenate(
        [
            normals.reshape(ne, 12),
            offsets,
            adj.astype(xp.float64),
        ],
        axis=1,
    )
    assert row.shape[1] == WALK_TABLE_WIDTH
    return row


def _signed_volumes(coords: np.ndarray, tet2vert: np.ndarray) -> np.ndarray:
    v = coords[tet2vert]  # [E,4,3]
    a = v[:, 1] - v[:, 0]
    b = v[:, 2] - v[:, 0]
    c = v[:, 3] - v[:, 0]
    return np.einsum("ei,ei->e", np.cross(a, b), c) / 6.0


def _build_face_adjacency(tet2vert: np.ndarray) -> np.ndarray:
    """face_adj[E,4]: tet across the face opposite local vertex f, or -1.

    Vectorized half-face matching: each tet contributes 4 faces keyed by
    their sorted global vertex triple; identical keys appearing twice are
    interior faces shared by two tets.
    """
    ne = tet2vert.shape[0]
    faces = tet2vert[:, _FACE_OF_VERT]  # [E,4,3]
    keys = np.sort(faces.reshape(-1, 3), axis=1)  # [4E,3]
    order = np.lexsort((keys[:, 2], keys[:, 1], keys[:, 0]))
    sk = keys[order]
    same = np.all(sk[1:] == sk[:-1], axis=1)
    # owning tet of each half-face, in sorted order
    owner = order // 4
    face_adj = np.full(ne * 4, -1, dtype=np.int32)
    lo = np.nonzero(same)[0]  # sk[lo] == sk[lo+1] → paired half-faces
    face_adj[order[lo]] = owner[lo + 1]
    face_adj[order[lo + 1]] = owner[lo]
    return face_adj.reshape(ne, 4)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class TetMesh:
    """Immutable tet mesh as a pytree of device arrays."""

    coords: Any  # [V,3] float
    tet2vert: Any  # [E,4] int32
    face_adj: Any  # [E,4] int32, -1 = boundary
    volumes: Any  # [E] float
    walk_table: Any = None  # [E,20] float: normals|offsets|adj, or None
    # Stored ONLY when walk_table is None (element ids not exactly
    # representable in the float dtype); otherwise face planes live
    # solely in walk_table and the properties below slice views out of
    # it — walk geometry is kept once in HBM, not twice.
    stored_face_normals: Any = None  # [E,4,3] float, unit outward
    stored_face_offsets: Any = None  # [E,4] float

    @property
    def face_normals(self) -> Any:
        if self.stored_face_normals is not None:
            return self.stored_face_normals
        ne = self.walk_table.shape[0]
        return self.walk_table[:, WALK_TABLE_NORMALS].reshape(ne, 4, 3)

    @property
    def face_offsets(self) -> Any:
        if self.stored_face_offsets is not None:
            return self.stored_face_offsets
        return self.walk_table[:, WALK_TABLE_OFFSETS]

    # -- pytree protocol ------------------------------------------------
    def tree_flatten(self):
        children = (
            self.coords,
            self.tet2vert,
            self.face_adj,
            self.volumes,
            self.walk_table,
            self.stored_face_normals,
            self.stored_face_offsets,
        )
        return children, None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- construction ---------------------------------------------------
    @classmethod
    def from_arrays(
        cls, coords: np.ndarray, tet2vert: np.ndarray, dtype: Any = None,
        force_unpacked: bool = False,
    ) -> "TetMesh":
        """Build a mesh (host-side precompute) from raw connectivity.

        Reorders each tet for positive orientation, computes outward face
        planes, face adjacency, and volumes. ``force_unpacked`` keeps
        the walk arrays separate (the layout meshes past the exact
        float-id limit fall back to) — for testing that path at small
        sizes.
        """
        if dtype is None:
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        coords = np.asarray(coords, dtype=np.float64)
        tet2vert = np.array(tet2vert, dtype=np.int32, copy=True)
        if coords.ndim != 2 or coords.shape[1] != 3:
            raise ValueError(f"coords must be [V,3], got {coords.shape}")
        if tet2vert.ndim != 2 or tet2vert.shape[1] != 4:
            raise ValueError(f"tet2vert must be [E,4], got {tet2vert.shape}")

        # Positive orientation: swap two verts where the signed volume < 0.
        sv = _signed_volumes(coords, tet2vert)
        neg = sv < 0
        tet2vert[neg, 2], tet2vert[neg, 3] = (
            tet2vert[neg, 3].copy(),
            tet2vert[neg, 2].copy(),
        )
        volumes = _signed_volumes(coords, tet2vert)
        if np.any(volumes <= 0):
            bad = int(np.sum(volumes <= 0))
            raise ValueError(f"{bad} degenerate (zero-volume) tets in mesh")

        v = coords[tet2vert]  # [E,4,3]
        # Face opposite vertex f: other three vertices.
        fa = v[:, _FACE_OF_VERT]  # [E,4,3verts,3xyz]
        e1 = fa[:, :, 1] - fa[:, :, 0]
        e2 = fa[:, :, 2] - fa[:, :, 0]
        n = np.cross(e1, e2)  # [E,4,3]
        # Outward: n · (v_opp - face_point) must be negative.
        opp = v  # vertex f itself, [E,4,3]
        s = np.einsum("efc,efc->ef", n, opp - fa[:, :, 0])
        n = np.where((s > 0)[..., None], -n, n)
        norm = np.linalg.norm(n, axis=2, keepdims=True)
        n = n / norm
        offsets = np.einsum("efc,efc->ef", n, fa[:, :, 0])

        face_adj = _build_face_adjacency(tet2vert)

        # Packed per-tet walk row (see module docstring). Element ids are
        # stored in the float dtype; exact only below 2^(mantissa+1) —
        # past that the walk falls back to separate gathers.
        ne = tet2vert.shape[0]
        if ne < _exact_id_limit(dtype) and not force_unpacked:
            walk_table = jnp.asarray(
                _pack_walk_table(np, n, offsets, face_adj), dtype=dtype
            )
            stored_n = stored_off = None
        else:
            walk_table = None
            stored_n = jnp.asarray(n, dtype=dtype)
            stored_off = jnp.asarray(offsets, dtype=dtype)

        return cls(
            coords=jnp.asarray(coords, dtype=dtype),
            tet2vert=jnp.asarray(tet2vert),
            face_adj=jnp.asarray(face_adj),
            volumes=jnp.asarray(volumes, dtype=dtype),
            walk_table=walk_table,
            stored_face_normals=stored_n,
            stored_face_offsets=stored_off,
        )

    # -- queries ---------------------------------------------------------
    @property
    def nelems(self) -> int:
        return int(self.tet2vert.shape[0])

    @property
    def nverts(self) -> int:
        return int(self.coords.shape[0])

    def centroids(self) -> jnp.ndarray:
        """Element centroids [E,3] (reference InitializeParticlesInElement0
        computes the centroid of element 0 this way, PumiTallyImpl.cpp:500-509)."""
        return jnp.mean(self.coords[self.tet2vert], axis=1)

    def bounding_box(self) -> Tuple[np.ndarray, np.ndarray]:
        c = np.asarray(self.coords)
        return c.min(axis=0), c.max(axis=0)

    def astype(self, dtype: Any) -> "TetMesh":
        ne = self.tet2vert.shape[0]
        # A mesh already in the unpacked layout stays unpacked: its ids
        # may exceed the new dtype's exact range too, and a
        # force_unpacked test mesh must not silently repack.
        if self.walk_table is not None and ne < _exact_id_limit(dtype):
            # Rebuild the table from f64 intermediates so adj ids stay
            # exact through the conversion (guarded by the limit check).
            walk_table = _pack_walk_table(
                jnp,
                self.face_normals.astype(jnp.float64),
                self.face_offsets.astype(jnp.float64),
                self.face_adj,
            ).astype(dtype)
            stored_n = stored_off = None
        else:
            walk_table = None
            stored_n = self.face_normals.astype(dtype)
            stored_off = self.face_offsets.astype(dtype)
        return TetMesh(
            coords=self.coords.astype(dtype),
            tet2vert=self.tet2vert,
            face_adj=self.face_adj,
            volumes=self.volumes.astype(dtype),
            walk_table=walk_table,
            stored_face_normals=stored_n,
            stored_face_offsets=stored_off,
        )
