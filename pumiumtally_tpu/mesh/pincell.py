"""Pincell mesh builder: a fuel cylinder inside a square pitch, extruded.

The reference's headline workload is an OpenMC pincell tallied on an
unstructured tet mesh (~10k tets, BASELINE.json configs[0-1]; the
reference obtains such meshes from Gmsh via msh2osh, README.md:115-125).
This builder produces that geometry natively: an O-grid — structured
radial rings inside the fuel cylinder, transition rings morphing from
the circle to the square cell boundary — extruded in z, every prism
split into 3 tets with the smallest-global-vertex diagonal rule
(Dompierre et al., "How to Subdivide Pyramids, Prisms and Hexahedra
into Tetrahedra"), which makes diagonals on shared quad faces agree
between neighboring prisms: the mesh is conforming by construction.

Returns raw (coords, tet2vert, region) arrays plus a convenience
``build_pincell`` that runs them through ``TetMesh.from_arrays`` (which
re-orients and validates every tet). ``region`` is 0 inside the fuel
radius and 1 outside (moderator) — the two-material split an OpenMC
pincell tally cares about.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from pumiumtally_tpu.mesh.tetmesh import TetMesh


def _square_point(theta: np.ndarray, half: float) -> np.ndarray:
    """Point on the axis-aligned square of half-width ``half`` along
    direction ``theta`` (the square's radial parametrization)."""
    c, s = np.cos(theta), np.sin(theta)
    m = np.maximum(np.abs(c), np.abs(s))
    return half * np.stack([c / m, s / m], axis=-1)


def _ogrid_2d(
    pitch: float,
    fuel_radius: float,
    n_theta: int,
    n_rings_fuel: int,
    n_rings_pad: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One cell's 2-D O-grid: (pts2[V2,2] pin-centered, tris[T,3],
    tri_region[T] 0 fuel / 1 moderator)."""
    if n_theta % 8:
        # The square's corners sit at 45°+k·90°; sector boundaries land
        # on them only when n_theta is a multiple of 8 — otherwise the
        # outer ring polygon cuts the corners off and the mesh no
        # longer fills the cell.
        raise ValueError("n_theta must be a multiple of 8")
    if 2 * fuel_radius >= pitch:
        raise ValueError("fuel diameter must be smaller than the pitch")
    if n_rings_fuel < 1 or n_rings_pad < 1:
        # Zero fuel rings mislabels the center fan, zero pad rings
        # drops the moderator (mesh no longer fills the cell).
        raise ValueError("n_rings_fuel and n_rings_pad must be >= 1")
    half = pitch / 2.0
    theta = np.arange(n_theta) * (2 * np.pi / n_theta)

    # 2-D O-grid vertices: center, then rings.
    pts2 = [np.zeros((1, 2))]
    ring_r = np.linspace(0.0, fuel_radius, n_rings_fuel + 1)[1:]
    for r in ring_r:
        pts2.append(np.stack([r * np.cos(theta), r * np.sin(theta)], axis=-1))
    sq = _square_point(theta, half)
    circ = fuel_radius * np.stack([np.cos(theta), np.sin(theta)], axis=-1)
    for s in np.linspace(0.0, 1.0, n_rings_pad + 1)[1:]:
        pts2.append((1.0 - s) * circ + s * sq)
    pts2 = np.concatenate(pts2, axis=0)
    nrings = n_rings_fuel + n_rings_pad

    def ring_vert(j: int, k: int) -> int:
        """2-D vertex index of ring j (1-based), sector k."""
        return 1 + (j - 1) * n_theta + (k % n_theta)

    # 2-D triangulation + per-triangle region (0 fuel / 1 moderator).
    tris = []
    tri_region = []
    for k in range(n_theta):  # center fan
        tris.append([0, ring_vert(1, k), ring_vert(1, k + 1)])
        tri_region.append(0)
    for j in range(1, nrings):
        reg = 0 if j < n_rings_fuel else 1
        for k in range(n_theta):
            a, b = ring_vert(j, k), ring_vert(j, k + 1)
            c, d = ring_vert(j + 1, k), ring_vert(j + 1, k + 1)
            tris.append([a, b, d])
            tris.append([a, d, c])
            tri_region.extend([reg, reg])
    return (
        pts2,
        np.asarray(tris, np.int64),
        np.asarray(tri_region, np.int64),
    )


def _extrude_prisms(
    pts2: np.ndarray,
    tris: np.ndarray,
    tri_labels: np.ndarray,  # [T, L] any per-triangle labels to replicate
    height: float,
    nz: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Extrude a 2-D triangulation into tets: every prism splits into 3
    by the smallest-GLOBAL-vertex diagonal rule (Dompierre et al.), so
    diagonals on shared quad faces agree between neighboring prisms —
    including prisms from different lattice cells — and the mesh is
    conforming by construction. Returns (coords, tet2vert, labels[E,L]).
    """
    if nz < 1:
        raise ValueError("nz must be >= 1")
    nv2 = pts2.shape[0]
    zs = np.linspace(0.0, height, nz + 1)
    coords = np.concatenate(
        [np.concatenate([pts2, np.full((nv2, 1), z)], axis=1) for z in zs],
        axis=0,
    )
    # All nz·T prisms at once (the per-prism Python loop was the
    # generation bottleneck at assembly scale — ~1M tets).
    tris = np.asarray(tris, np.int64)
    layers = np.arange(nz, dtype=np.int64)[:, None, None] * nv2
    bot = (tris[None, :, :] + layers).reshape(-1, 3)  # [P,3]
    v = np.concatenate([bot, bot + nv2], axis=1)  # [P,6]
    # Rotate so the globally smallest bottom/top pair is first.
    rot = np.argmin(np.minimum(v[:, 0:3], v[:, 3:6]), axis=1)  # [P]
    o = (rot[:, None] + np.arange(3)[None, :]) % 3  # [P,3]
    v = np.take_along_axis(v, np.concatenate([o, o + 3], axis=1), axis=1)
    # Diagonal choice on the far quad face (Dompierre rule).
    left = np.minimum(v[:, 1], v[:, 5]) < np.minimum(v[:, 2], v[:, 4])
    split_a = v[:, [0, 1, 2, 5,   0, 1, 5, 4,   0, 4, 5, 3]]
    split_b = v[:, [0, 1, 2, 4,   0, 4, 2, 5,   0, 4, 5, 3]]
    tets = np.where(left[:, None], split_a, split_b).reshape(-1, 4)
    labels = np.repeat(
        np.tile(np.asarray(tri_labels), (nz, 1)), 3, axis=0
    )
    return (
        np.asarray(coords, np.float64),
        tets.astype(np.int32),
        labels.astype(np.int32),
    )


def pincell_arrays(
    pitch: float = 1.26,
    fuel_radius: float = 0.4095,
    height: float = 1.0,
    n_theta: int = 16,
    n_rings_fuel: int = 3,
    n_rings_pad: int = 3,
    nz: int = 4,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(coords[V,3], tet2vert[E,4], region[E]) for a single pincell.

    n_theta sectors around the pin (multiple of 8 keeps the square's
    corners on sector boundaries), n_rings_fuel rings inside the fuel,
    n_rings_pad transition rings from the fuel surface to the square
    boundary, nz extruded layers. Tet count: 3*nz*n_theta*(2*(n_rings_
    fuel+n_rings_pad) - 1).
    """
    pts2, tris, tri_region = _ogrid_2d(
        pitch, fuel_radius, n_theta, n_rings_fuel, n_rings_pad
    )
    # The cell sits in [0,pitch]^2 x [0,height] (corner origin — shared
    # by every consumer; the O-grid itself is built pin-centered).
    coords, tets, labels = _extrude_prisms(
        pts2 + pitch / 2.0, tris, tri_region[:, None], height, nz
    )
    return coords, tets, labels[:, 0]


def lattice_arrays(
    nx: int,
    ny: int,
    pitch: float = 1.26,
    fuel_radius: float = 0.4095,
    height: float = 1.0,
    n_theta: int = 16,
    n_rings_fuel: int = 3,
    n_rings_pad: int = 3,
    nz: int = 4,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(coords[V,3], tet2vert[E,4], region[E], cell_id[E]) for an
    nx×ny pincell lattice (a fuel-assembly slab) in
    [0, nx·pitch]×[0, ny·pitch]×[0, height].

    The reference's larger benchmark configs tally assemblies of
    pincells on ~1M-tet unstructured meshes (BASELINE.json configs[1-2]
    scale); this builds that geometry natively. Every cell reuses one
    2-D O-grid pattern; coincident boundary vertices of neighboring
    cells are WELDED in 2-D (their coordinates agree to float rounding;
    the weld snaps them identical), and the single global extrusion
    applies the smallest-global-vertex prism rule, so shared faces —
    including cell-to-cell interfaces — triangulate identically from
    both sides: the assembly is conforming by construction.
    ``region`` is 0 fuel / 1 moderator; ``cell_id`` is j·nx+i.
    """
    if nx < 1 or ny < 1:
        raise ValueError("nx and ny must be >= 1")
    pts2, tris, tri_region = _ogrid_2d(
        pitch, fuel_radius, n_theta, n_rings_fuel, n_rings_pad
    )
    half = pitch / 2.0
    nv2 = pts2.shape[0]
    all_pts = []
    all_tris = []
    all_lab = []
    for j in range(ny):
        for i in range(nx):
            all_pts.append(pts2 + np.array([i * pitch + half,
                                            j * pitch + half]))
            off = (j * nx + i) * nv2
            all_tris.append(tris + off)
            all_lab.append(
                np.stack(
                    [tri_region,
                     np.full_like(tri_region, j * nx + i)],
                    axis=1,
                )
            )
    pts = np.concatenate(all_pts, axis=0)
    tris_all = np.concatenate(all_tris, axis=0)
    labels = np.concatenate(all_lab, axis=0)

    # Weld coincident 2-D vertices (cell-boundary points shared by
    # neighbors agree to ~1e-16·pitch; interior spacings are orders of
    # magnitude larger, so a coarse quantization cannot merge distinct
    # points). First occurrence's coordinates win → exactly identical
    # shared vertices.
    quant = np.round(pts / (pitch * 1e-9)).astype(np.int64)
    _, first, inverse = np.unique(
        quant, axis=0, return_index=True, return_inverse=True
    )
    welded = pts[np.sort(first)]
    # unique() orders by key; remap to first-occurrence order so vertex
    # numbering stays cell-major (keeps the extrusion rule stable).
    order = np.argsort(first)
    rank_of_unique = np.empty_like(order)
    rank_of_unique[order] = np.arange(order.shape[0])
    vmap = rank_of_unique[inverse]
    tris_w = vmap[tris_all]

    coords, tets, labels3 = _extrude_prisms(
        welded, tris_w, labels, height, nz
    )
    return coords, tets, labels3[:, 0], labels3[:, 1]


def build_lattice(
    nx: int,
    ny: int,
    pitch: float = 1.26,
    fuel_radius: float = 0.4095,
    height: float = 1.0,
    n_theta: int = 16,
    n_rings_fuel: int = 3,
    n_rings_pad: int = 3,
    nz: int = 4,
    dtype=None,
) -> Tuple[TetMesh, np.ndarray, np.ndarray]:
    """(TetMesh, region[E], cell_id[E]) — validated nx×ny assembly."""
    coords, tets, region, cell_id = lattice_arrays(
        nx, ny, pitch, fuel_radius, height, n_theta, n_rings_fuel,
        n_rings_pad, nz,
    )
    return TetMesh.from_arrays(coords, tets, dtype=dtype), region, cell_id


# The flagship benchmark geometry (BASELINE configs[0]: OpenMC pincell
# class, ~22k anisotropic tets): ONE definition consumed by bench.py
# and the experiment scripts, so every A/B measures the same mesh.
FLAGSHIP_PINCELL = dict(
    pitch=1.26, height=1.0, n_theta=32, n_rings_fuel=5, n_rings_pad=5,
    nz=12,
)


def build_pincell(
    pitch: float = 1.26,
    fuel_radius: float = 0.4095,
    height: float = 1.0,
    n_theta: int = 16,
    n_rings_fuel: int = 3,
    n_rings_pad: int = 3,
    nz: int = 4,
    dtype=None,
) -> Tuple[TetMesh, np.ndarray]:
    """(TetMesh, region[E]) — validated, walk-ready pincell mesh.

    NOTE: ``TetMesh.from_arrays`` preserves element order, so the
    region array indexes the mesh's elements directly.
    """
    coords, tets, region = pincell_arrays(
        pitch, fuel_radius, height, n_theta, n_rings_fuel, n_rings_pad, nz
    )
    return TetMesh.from_arrays(coords, tets, dtype=dtype), region
