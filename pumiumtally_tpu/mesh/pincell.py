"""Pincell mesh builder: a fuel cylinder inside a square pitch, extruded.

The reference's headline workload is an OpenMC pincell tallied on an
unstructured tet mesh (~10k tets, BASELINE.json configs[0-1]; the
reference obtains such meshes from Gmsh via msh2osh, README.md:115-125).
This builder produces that geometry natively: an O-grid — structured
radial rings inside the fuel cylinder, transition rings morphing from
the circle to the square cell boundary — extruded in z, every prism
split into 3 tets with the smallest-global-vertex diagonal rule
(Dompierre et al., "How to Subdivide Pyramids, Prisms and Hexahedra
into Tetrahedra"), which makes diagonals on shared quad faces agree
between neighboring prisms: the mesh is conforming by construction.

Returns raw (coords, tet2vert, region) arrays plus a convenience
``build_pincell`` that runs them through ``TetMesh.from_arrays`` (which
re-orients and validates every tet). ``region`` is 0 inside the fuel
radius and 1 outside (moderator) — the two-material split an OpenMC
pincell tally cares about.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from pumiumtally_tpu.mesh.tetmesh import TetMesh


def _square_point(theta: np.ndarray, half: float) -> np.ndarray:
    """Point on the axis-aligned square of half-width ``half`` along
    direction ``theta`` (the square's radial parametrization)."""
    c, s = np.cos(theta), np.sin(theta)
    m = np.maximum(np.abs(c), np.abs(s))
    return half * np.stack([c / m, s / m], axis=-1)


def pincell_arrays(
    pitch: float = 1.26,
    fuel_radius: float = 0.4095,
    height: float = 1.0,
    n_theta: int = 16,
    n_rings_fuel: int = 3,
    n_rings_pad: int = 3,
    nz: int = 4,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(coords[V,3], tet2vert[E,4], region[E]) for a single pincell.

    n_theta sectors around the pin (multiple of 8 keeps the square's
    corners on sector boundaries), n_rings_fuel rings inside the fuel,
    n_rings_pad transition rings from the fuel surface to the square
    boundary, nz extruded layers. Tet count: 3*nz*n_theta*(2*(n_rings_
    fuel+n_rings_pad) - 1).
    """
    if n_theta % 8:
        # The square's corners sit at 45°+k·90°; sector boundaries land
        # on them only when n_theta is a multiple of 8 — otherwise the
        # outer ring polygon cuts the corners off and the mesh no
        # longer fills the cell.
        raise ValueError("n_theta must be a multiple of 8")
    if 2 * fuel_radius >= pitch:
        raise ValueError("fuel diameter must be smaller than the pitch")
    if n_rings_fuel < 1 or n_rings_pad < 1 or nz < 1:
        # Zero fuel rings mislabels the center fan, zero pad rings
        # drops the moderator (mesh no longer fills the cell), zero
        # layers is no mesh at all.
        raise ValueError("n_rings_fuel, n_rings_pad, and nz must be >= 1")
    half = pitch / 2.0
    theta = np.arange(n_theta) * (2 * np.pi / n_theta)

    # 2-D O-grid vertices: center, then rings.
    pts2 = [np.zeros((1, 2))]
    ring_r = np.linspace(0.0, fuel_radius, n_rings_fuel + 1)[1:]
    for r in ring_r:
        pts2.append(np.stack([r * np.cos(theta), r * np.sin(theta)], axis=-1))
    sq = _square_point(theta, half)
    circ = fuel_radius * np.stack([np.cos(theta), np.sin(theta)], axis=-1)
    for s in np.linspace(0.0, 1.0, n_rings_pad + 1)[1:]:
        pts2.append((1.0 - s) * circ + s * sq)
    pts2 = np.concatenate(pts2, axis=0)
    nv2 = pts2.shape[0]
    nrings = n_rings_fuel + n_rings_pad

    def ring_vert(j: int, k: int) -> int:
        """2-D vertex index of ring j (1-based), sector k."""
        return 1 + (j - 1) * n_theta + (k % n_theta)

    # 2-D triangulation + per-triangle region (0 fuel / 1 moderator).
    tris = []
    tri_region = []
    for k in range(n_theta):  # center fan
        tris.append([0, ring_vert(1, k), ring_vert(1, k + 1)])
        tri_region.append(0)
    for j in range(1, nrings):
        reg = 0 if j < n_rings_fuel else 1
        for k in range(n_theta):
            a, b = ring_vert(j, k), ring_vert(j, k + 1)
            c, d = ring_vert(j + 1, k), ring_vert(j + 1, k + 1)
            tris.append([a, b, d])
            tris.append([a, d, c])
            tri_region.extend([reg, reg])
    tris = np.asarray(tris, np.int64)
    tri_region = np.asarray(tri_region, np.int64)

    # Extrude: layer l vertex = 2-D vertex + l*nv2. The cell sits in
    # [0,pitch]^2 x [0,height] (corner origin — shared by every
    # consumer; the O-grid itself is built pin-centered).
    pts2 = pts2 + half
    zs = np.linspace(0.0, height, nz + 1)
    coords = np.concatenate(
        [
            np.concatenate(
                [pts2, np.full((nv2, 1), z)], axis=1
            )
            for z in zs
        ],
        axis=0,
    )

    # Prism → 3 tets, smallest-vertex diagonal rule (conforming).
    tets = []
    region = []
    for layer in range(nz):
        lo = layer * nv2
        hi = (layer + 1) * nv2
        for t, reg in zip(tris, tri_region):
            v = np.array([lo + t[0], lo + t[1], lo + t[2],
                          hi + t[0], hi + t[1], hi + t[2]], np.int64)
            # Rotate so the globally smallest bottom/top pair is first.
            rot = int(np.argmin([min(v[0], v[3]), min(v[1], v[4]),
                                 min(v[2], v[5])]))
            order = [rot, (rot + 1) % 3, (rot + 2) % 3]
            v = v[order + [o + 3 for o in order]]
            if min(v[1], v[5]) < min(v[2], v[4]):
                new = [(v[0], v[1], v[2], v[5]),
                       (v[0], v[1], v[5], v[4]),
                       (v[0], v[4], v[5], v[3])]
            else:
                new = [(v[0], v[1], v[2], v[4]),
                       (v[0], v[4], v[2], v[5]),
                       (v[0], v[4], v[5], v[3])]
            tets.extend(new)
            region.extend([reg] * 3)
    return (
        np.asarray(coords, np.float64),
        np.asarray(tets, np.int32),
        np.asarray(region, np.int32),
    )


def build_pincell(
    pitch: float = 1.26,
    fuel_radius: float = 0.4095,
    height: float = 1.0,
    n_theta: int = 16,
    n_rings_fuel: int = 3,
    n_rings_pad: int = 3,
    nz: int = 4,
    dtype=None,
) -> Tuple[TetMesh, np.ndarray]:
    """(TetMesh, region[E]) — validated, walk-ready pincell mesh.

    NOTE: ``TetMesh.from_arrays`` preserves element order, so the
    region array indexes the mesh's elements directly.
    """
    coords, tets, region = pincell_arrays(
        pitch, fuel_radius, height, n_theta, n_rings_fuel, n_rings_pad, nz
    )
    return TetMesh.from_arrays(coords, tets, dtype=dtype), region
