from pumiumtally_tpu.mesh.tetmesh import TetMesh
from pumiumtally_tpu.mesh.box import build_box

__all__ = ["TetMesh", "build_box"]
