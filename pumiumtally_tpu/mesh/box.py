"""Structured box → tet mesh builder (Kuhn/Freudenthal decomposition).

TPU-native stand-in for ``Omega_h::build_box(world, OMEGA_H_SIMPLEX,
x,y,z, nx,ny,nz, false)``, which the reference test fixture uses to make
the 6-tet unit cube oracle mesh (reference
test/test_pumi_tally_impl_methods.cpp:34-35, 399-400).

Each grid cell is split into the 6 Kuhn simplices, one per permutation
of the axis order. The local ordering below reproduces the element
numbering the reference oracles depend on for the 1×1×1 unit cube:

- element 0 has centroid (0.5, 0.75, 0.25)       (test:83)
- the point (0.1, 0.4, 0.5) lies in element 2    (test:157-159)
- the +x ray at (y,z)=(0.4,0.5) crosses elements 2→3→4 with segment
  lengths 0.3 / 0.1 / 0.5                        (test:267-282)

Local tet k of a cell occupies the region where the coordinates sorted
by the k-th permutation are descending:

  0: y≥x≥z   1: y≥z≥x   2: z≥y≥x   3: z≥x≥y   4: x≥z≥y   5: x≥y≥z
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

from pumiumtally_tpu.mesh.tetmesh import TetMesh

# Corner index c = ix + 2*iy + 4*iz of the unit cell.
# Kuhn tet for permutation (p1,p2,p3): corners 0, e_{p1}, e_{p1}+e_{p2}, (1,1,1).
# Axis unit corners: x → 1, y → 2, z → 4.
_KUHN_CORNERS = np.array(
    [
        [0, 2, 3, 7],  # y≥x≥z  (y,x,z)
        [0, 2, 6, 7],  # y≥z≥x  (y,z,x)
        [0, 4, 6, 7],  # z≥y≥x  (z,y,x)
        [0, 4, 5, 7],  # z≥x≥y  (z,x,y)
        [0, 1, 5, 7],  # x≥z≥y  (x,z,y)
        [0, 1, 3, 7],  # x≥y≥z  (x,y,z)
    ],
    dtype=np.int32,
)


def box_arrays(
    lx: float,
    ly: float,
    lz: float,
    nx: int,
    ny: int,
    nz: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Raw (coords, tet2vert) for an nx×ny×nz grid box of size lx×ly×lz."""
    if min(nx, ny, nz) < 1:
        raise ValueError("grid divisions must be >= 1")
    xs = np.linspace(0.0, lx, nx + 1)
    ys = np.linspace(0.0, ly, ny + 1)
    zs = np.linspace(0.0, lz, nz + 1)
    # Vertex id = i + (nx+1)*(j + (ny+1)*k)
    zz, yy, xx = np.meshgrid(zs, ys, xs, indexing="ij")
    coords = np.stack([xx, yy, zz], axis=-1).reshape(-1, 3)

    i, j, k = np.meshgrid(
        np.arange(nx), np.arange(ny), np.arange(nz), indexing="ij"
    )
    i = i.transpose(2, 1, 0).reshape(-1)  # cell order: k-major, then j, then i
    j = j.transpose(2, 1, 0).reshape(-1)
    k = k.transpose(2, 1, 0).reshape(-1)

    def vid(di: np.ndarray, dj: np.ndarray, dk: np.ndarray) -> np.ndarray:
        return (i + di) + (nx + 1) * ((j + dj) + (ny + 1) * (k + dk))

    # Cell corner c (bit 0=x, 1=y, 2=z) → global vertex id, [ncells, 8]
    corners = np.stack(
        [vid((c >> 0) & 1, (c >> 1) & 1, (c >> 2) & 1) for c in range(8)],
        axis=1,
    )
    tets = corners[:, _KUHN_CORNERS]  # [ncells, 6, 4]
    return coords, tets.reshape(-1, 4).astype(np.int32)


def build_box(
    lx: float = 1.0,
    ly: float = 1.0,
    lz: float = 1.0,
    nx: int = 1,
    ny: int = 1,
    nz: int = 1,
    dtype: Any = None,
) -> TetMesh:
    coords, tet2vert = box_arrays(lx, ly, lz, nx, ny, nz)
    return TetMesh.from_arrays(coords, tet2vert, dtype=dtype)
