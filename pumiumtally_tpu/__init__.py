"""pumiumtally_tpu — TPU-native unstructured-mesh track-length tally framework.

A ground-up JAX/XLA re-design of the capabilities of PUMI-Tally
(reference: /root/reference, Fuad-HH/PumiUMTally): GPU-accelerated
track-length tallies for Monte Carlo neutral-particle transport on
tetrahedral meshes, re-architected for TPU hardware:

- the Kokkos device layer (reference PumiTallyImpl.cpp:159-193) becomes
  XLA: jitted kernels, ``jax.device_put`` staging, deterministic
  scatter-adds instead of ``Kokkos::atomic_add``;
- the PUMIPic adjacency-walk search (reference PumiTallyImpl.cpp:454)
  becomes a masked lock-step ``lax.while_loop`` over a precomputed
  packed walk table (a Pallas variant was analyzed and measured
  unprofitable — docs/PERF_NOTES.md);
- the MPI rank parallelism (reference PumiTallyImpl.cpp:111,145) becomes
  SPMD over a ``jax.sharding.Mesh``: particle batches sharded over the
  ``dp`` axis, per-element flux reduced with ``psum`` over ICI.

Public surface mirrors the reference's three-call protocol
(reference PumiTally.h:66-95): ``CopyInitialPosition`` /
``MoveToNextLocation`` / ``WriteTallyResults``.
"""

from pumiumtally_tpu.config import TallyConfig
from pumiumtally_tpu.mesh.tetmesh import TetMesh
from pumiumtally_tpu.mesh.box import build_box
from pumiumtally_tpu.mesh.pincell import build_lattice, build_pincell
from pumiumtally_tpu.api.tally import PumiTally, TallyTimes
from pumiumtally_tpu.api.partitioned import PartitionedPumiTally
from pumiumtally_tpu.api.streaming import StreamingPartitionedTally, StreamingTally
from pumiumtally_tpu.stats import BatchStatistics, TriggerResult, TriggerSpec
from pumiumtally_tpu.scoring import EnergyFilter, ScoringSpec, TimeFilter
from pumiumtally_tpu.resilience import CheckpointPolicy, resume_latest
from pumiumtally_tpu.sentinel import (
    EnginePoisonedError,
    HealthReport,
    SentinelPolicy,
)
from pumiumtally_tpu.service import (
    ServiceBusyError,
    ServiceDrainingError,
    SessionClosedError,
    SessionState,
    TallyService,
)

__version__ = "0.1.0"

__all__ = [
    "TallyConfig",
    "TetMesh",
    "build_box",
    "build_lattice",
    "build_pincell",
    "PumiTally",
    "PartitionedPumiTally",
    "StreamingPartitionedTally",
    "StreamingTally",
    "TallyTimes",
    "BatchStatistics",
    "TriggerResult",
    "TriggerSpec",
    "EnergyFilter",
    "ScoringSpec",
    "TimeFilter",
    "CheckpointPolicy",
    "resume_latest",
    "EnginePoisonedError",
    "HealthReport",
    "SentinelPolicy",
    "ServiceBusyError",
    "ServiceDrainingError",
    "SessionClosedError",
    "SessionState",
    "TallyService",
]
