from pumiumtally_tpu.io.vtk import write_vtk

__all__ = ["write_vtk"]
