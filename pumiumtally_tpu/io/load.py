"""Mesh loading dispatch (.msh Gmsh / .osh Omega_h).

The reference constructor takes an ``.osh`` path
(reference PumiTally.h:45-47, Omega_h::binary::read at
PumiTallyImpl.cpp:562); its README's tool flow converts Gmsh meshes with
``msh2osh`` (README.md:115-125). We accept both formats directly.
"""

from __future__ import annotations

from typing import Any

from pumiumtally_tpu.mesh.tetmesh import TetMesh


def load_mesh(path: str, dtype: Any = None) -> TetMesh:
    p = path.rstrip("/")
    if p.endswith(".msh"):
        from pumiumtally_tpu.io.gmsh import read_gmsh

        coords, tets = read_gmsh(p)
    elif p.endswith(".osh"):
        from pumiumtally_tpu.io.osh import read_osh

        coords, tets = read_osh(p)
    else:
        raise ValueError(
            f"unsupported mesh format: {path!r} (expected .msh or .osh)"
        )
    return TetMesh.from_arrays(coords, tets, dtype=dtype)
