"""Legacy VTK (ASCII) writer for tet meshes with cell data.

Replaces ``Omega_h::vtk::write_parallel`` (reference
PumiTallyImpl.cpp:415). The reference writes Omega_h's .vtu piece
directory; we write a single legacy-format ``.vtk`` file — readable by
ParaView/VisIt — carrying the same payload: the mesh plus "flux" and
"volume" cell arrays (reference tags added at PumiTallyImpl.cpp:407,414).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def write_vtk(
    path: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    cell_data: Optional[Dict[str, np.ndarray]] = None,
    point_data: Optional[Dict[str, np.ndarray]] = None,
    title: str = "pumiumtally_tpu flux result",
) -> None:
    coords = np.asarray(coords, dtype=np.float64)
    tet2vert = np.asarray(tet2vert, dtype=np.int64)
    nv, ne = coords.shape[0], tet2vert.shape[0]
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write("# vtk DataFile Version 3.0\n")
        f.write(title + "\n")
        f.write("ASCII\nDATASET UNSTRUCTURED_GRID\n")
        f.write(f"POINTS {nv} double\n")
        np.savetxt(f, coords, fmt="%.17g")
        f.write(f"CELLS {ne} {ne * 5}\n")
        cells = np.hstack([np.full((ne, 1), 4, dtype=np.int64), tet2vert])
        np.savetxt(f, cells, fmt="%d")
        f.write(f"CELL_TYPES {ne}\n")
        np.savetxt(f, np.full(ne, 10, dtype=np.int64), fmt="%d")  # VTK_TETRA
        if cell_data:
            f.write(f"CELL_DATA {ne}\n")
            for name, arr in cell_data.items():
                arr = np.asarray(arr, dtype=np.float64).reshape(-1)
                if arr.shape[0] != ne:
                    raise ValueError(
                        f"cell data {name!r} has {arr.shape[0]} values, "
                        f"need {ne}"
                    )
                f.write(f"SCALARS {name} double 1\nLOOKUP_TABLE default\n")
                np.savetxt(f, arr, fmt="%.17g")
        if point_data:
            f.write(f"POINT_DATA {nv}\n")
            for name, arr in point_data.items():
                arr = np.asarray(arr, dtype=np.float64).reshape(-1)
                if arr.shape[0] != nv:
                    raise ValueError(
                        f"point data {name!r} has {arr.shape[0]} values, "
                        f"need {nv}"
                    )
                f.write(f"SCALARS {name} double 1\nLOOKUP_TABLE default\n")
                np.savetxt(f, arr, fmt="%.17g")


def read_vtk_cell_scalars(path: str, name: str) -> np.ndarray:
    """Minimal reader for round-trip tests: pull one cell scalar array."""
    with open(path) as f:
        lines = f.read().splitlines()
    ncells = None
    for i, line in enumerate(lines):
        if line.startswith("CELL_DATA"):
            ncells = int(line.split()[1])
        if line.startswith(f"SCALARS {name} ") and ncells is not None:
            vals: list[float] = []
            j = i + 2  # skip LOOKUP_TABLE line
            while len(vals) < ncells:
                vals.extend(float(v) for v in lines[j].split())
                j += 1
            return np.array(vals[:ncells])
    raise KeyError(f"cell scalar {name!r} not found in {path}")
