"""VTK writers for tet meshes with cell data: legacy ``.vtk`` (binary
by default, ASCII on request) and XML ``.vtu`` (raw-appended binary).

Replaces ``Omega_h::vtk::write_parallel`` (reference
PumiTallyImpl.cpp:415). The reference writes Omega_h's ``.vtu`` piece
directory; we write either a single legacy-format ``.vtk`` file or a
single ``.vtu`` — both readable by ParaView/VisIt — carrying the same
payload: the mesh plus "flux" and "volume" cell arrays (reference tags
added at PumiTallyImpl.cpp:407,414).

Binary is the default because ASCII ``np.savetxt`` does not scale: a
1M-tet mesh is ~300 MB of text and minutes of formatting, vs seconds
for the raw-bytes paths (VERDICT round-1, "rank-aware / scalable
output").
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Optional

import numpy as np


def _prep(path, coords, tet2vert):
    coords = np.asarray(coords, dtype=np.float64)
    tet2vert = np.asarray(tet2vert, dtype=np.int64)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return coords, tet2vert


def _xml_name(name: str) -> str:
    """Escape a data-array name for interpolation into an XML attribute
    (a name containing '"', '<' or '&' would otherwise produce a file
    every reader rejects)."""
    from xml.sax.saxutils import escape

    return escape(name, {'"': "&quot;"})


def _check_len(name: str, arr: np.ndarray, n: int, kind: str) -> np.ndarray:
    arr = np.asarray(arr, dtype=np.float64).reshape(-1)
    if arr.shape[0] != n:
        raise ValueError(
            f"{kind} data {name!r} has {arr.shape[0]} values, need {n}"
        )
    return arr


def stats_cell_data(stats, volumes: np.ndarray) -> Dict[str, np.ndarray]:
    """Optional batch-statistics cell arrays for the tally writers
    (``stats`` is a ``pumiumtally_tpu.stats.BatchStatistics``):

    - ``flux_mean``: per-batch mean flux, volume-normalized exactly
      like the ``flux`` array (so flux == flux_mean * num_batches for
      a run whose batches all closed) — present from 1 closed batch;
    - ``rel_err``: relative error of the mean (dimensionless;
      volume normalization cancels) — present from 2 closed batches
      (the sample variance needs them). Unscored elements (zero mean,
      estimator ``inf``) write 0.0: the OpenMC statepoint convention,
      and a file of infs breaks most readers' color mapping.

    Returns {} when stats is None or has no closed batch, keeping the
    default payload byte-identical to the reference's flux+volume
    layout.
    """
    out: Dict[str, np.ndarray] = {}
    if stats is None or stats.num_batches < 1:
        return out
    vol = np.asarray(volumes, dtype=np.float64)
    out["flux_mean"] = np.asarray(stats.mean, dtype=np.float64) / vol
    if stats.num_batches >= 2:
        re = np.asarray(stats.rel_err, dtype=np.float64)
        out["rel_err"] = np.where(np.isfinite(re), re, 0.0)
    return out


def merge_cell_data(*groups: Optional[Dict[str, np.ndarray]]) -> dict:
    """Merge cell-data dicts for the tally writers, REFUSING name
    collisions: a plain ``{**a, **b}`` silently lets a later group
    shadow an earlier one — a scoring lane named ``flux_mean`` would
    overwrite the statistics array and the file would carry wrong data
    under a trusted name. Raises a ValueError naming the colliding
    array and both groups' positions instead. ``None`` groups are
    skipped."""
    out: dict = {}
    owner: dict = {}
    for gi, g in enumerate(groups):
        if not g:
            continue
        for name, arr in g.items():
            if name in out:
                raise ValueError(
                    f"cell-data array name collision: {name!r} appears "
                    f"in payload group {owner[name]} and again in group "
                    f"{gi} — rename one (a silent overwrite would ship "
                    "wrong data under a trusted array name)"
                )
            out[name] = arr
            owner[name] = gi
    return out


def health_field_data(report) -> Dict[str, np.ndarray]:
    """Sentinel health report as VTK FIELD arrays (``report`` is a
    ``pumiumtally_tpu.sentinel.HealthReport``): campaign-level scalars
    — audited/anomalous move counts, the anomaly-mask union, the worst
    conservation residual, straggler and overflow ladder outcomes —
    riding the same FIELD block as ``lost_particles`` in every writer
    (legacy leading FIELD, .vtu <FieldData>, every .pvtu piece), so a
    result file carries its own health record. Returns {} for None,
    keeping sentinel-off files byte-identical."""
    if report is None:
        return {}
    return report.as_field_data()


def write_vtk(
    path: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    cell_data: Optional[Dict[str, np.ndarray]] = None,
    point_data: Optional[Dict[str, np.ndarray]] = None,
    title: str = "pumiumtally_tpu flux result",
    ascii: bool = False,  # noqa: A002 — matches the VTK keyword
    field_data: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Write a legacy ``.vtk`` unstructured grid. Dispatches to the XML
    ``.vtu`` writer when ``path`` ends in ``.vtu``.

    Binary mode (default) emits the legacy BINARY encoding: the usual
    ASCII headers with big-endian raw payloads — seconds for a 1M-tet
    mesh. ``ascii=True`` restores the all-text variant.

    ``field_data`` holds DATASET-level scalar arrays (campaign
    metadata such as ``lost_particles`` — arbitrary length, not tied
    to cell/point counts); written as a leading ``FIELD FieldData``
    block in the legacy format and a ``<FieldData>`` element in
    ``.vtu``.
    """
    if path.endswith(".pvtu"):
        raise ValueError(
            ".pvtu (multi-piece parallel) output needs per-element "
            "ownership — use write_pvtu, or WriteTallyResults on a "
            "PartitionedPumiTally"
        )
    if path.endswith(".vtu"):
        if ascii:
            raise ValueError(
                ".vtu output is always raw-appended binary; use a .vtk "
                "path for the ASCII legacy format"
            )
        write_vtu(path, coords, tet2vert, cell_data, point_data,
                  title=title, field_data=field_data)
        return
    coords, tet2vert = _prep(path, coords, tet2vert)
    nv, ne = coords.shape[0], tet2vert.shape[0]
    cells = np.hstack([np.full((ne, 1), 4, dtype=np.int64), tet2vert])
    with open(path, "wb") as f:
        def w(s: str) -> None:
            f.write(s.encode("ascii"))

        w("# vtk DataFile Version 3.0\n")
        w(title + "\n")
        w(("ASCII" if ascii else "BINARY") + "\n")
        if field_data:
            # Dataset field data leads the geometry (the placement
            # vtkDataReader attaches to the dataset itself).
            w(f"FIELD FieldData {len(field_data)}\n")
            for name, arr in field_data.items():
                arr = np.asarray(arr, dtype=np.float64).reshape(-1)
                w(f"{name} 1 {arr.shape[0]} double\n")
                if ascii:
                    np.savetxt(f, arr, fmt="%.17g")
                else:
                    f.write(arr.astype(">f8").tobytes())
                    w("\n")
        w("DATASET UNSTRUCTURED_GRID\n")
        w(f"POINTS {nv} double\n")
        if ascii:
            np.savetxt(f, coords, fmt="%.17g")
        else:
            f.write(coords.astype(">f8").tobytes())
            w("\n")
        w(f"CELLS {ne} {ne * 5}\n")
        if ascii:
            np.savetxt(f, cells, fmt="%d")
        else:
            f.write(cells.astype(">i4").tobytes())
            w("\n")
        w(f"CELL_TYPES {ne}\n")
        if ascii:
            np.savetxt(f, np.full(ne, 10, dtype=np.int64), fmt="%d")
        else:
            f.write(np.full(ne, 10, dtype=">i4").tobytes())  # VTK_TETRA
            w("\n")
        for kind, n, data in (
            ("CELL_DATA", ne, cell_data), ("POINT_DATA", nv, point_data)
        ):
            if not data:
                continue
            w(f"{kind} {n}\n")
            for name, arr in data.items():
                arr = _check_len(name, arr, n, kind)
                w(f"SCALARS {name} double 1\nLOOKUP_TABLE default\n")
                if ascii:
                    np.savetxt(f, arr, fmt="%.17g")
                else:
                    f.write(arr.astype(">f8").tobytes())
                    w("\n")


def write_vtu(
    path: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    cell_data: Optional[Dict[str, np.ndarray]] = None,
    point_data: Optional[Dict[str, np.ndarray]] = None,
    title: str = "pumiumtally_tpu flux result",
    field_data: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Write an XML ``.vtu`` UnstructuredGrid with raw appended binary
    data (the same file family Omega_h's vtk::write_parallel emits as
    pieces, reference PumiTallyImpl.cpp:415), little-endian, UInt64
    headers — loadable by ParaView/VisIt/meshio."""
    coords, tet2vert = _prep(path, coords, tet2vert)
    nv, ne = coords.shape[0], tet2vert.shape[0]

    blocks: list = []  # (xml name, DataArray attrs, bytes)

    def add(name: str, arr: np.ndarray, vtype: str, ncomp: int) -> int:
        blocks.append((name, vtype, ncomp, np.ascontiguousarray(arr).tobytes()))
        return len(blocks) - 1

    add("Points", coords.astype("<f8"), "Float64", 3)
    add("connectivity", tet2vert.astype("<i8").reshape(-1), "Int64", 1)
    add("offsets", (4 * np.arange(1, ne + 1, dtype="<i8")), "Int64", 1)
    add("types", np.full(ne, 10, dtype="<u1"), "UInt8", 1)
    cell_names, point_names, field_names = [], [], []
    for name, arr in (cell_data or {}).items():
        cell_names.append(name)
        add(name, _check_len(name, arr, ne, "cell").astype("<f8"),
            "Float64", 1)
    for name, arr in (point_data or {}).items():
        point_names.append(name)
        add(name, _check_len(name, arr, nv, "point").astype("<f8"),
            "Float64", 1)
    for name, arr in (field_data or {}).items():
        field_names.append(name)
        add(name,
            np.asarray(arr, dtype=np.float64).reshape(-1).astype("<f8"),
            "Float64", 1)

    offsets = []
    off = 0
    for _, _, _, payload in blocks:
        offsets.append(off)
        off += 8 + len(payload)  # UInt64 byte-count header + payload

    def da(i: int, extra: str = "") -> str:
        name, vtype, ncomp, _ = blocks[i]
        comps = f' NumberOfComponents="{ncomp}"' if ncomp > 1 else ""
        return (
            f'<DataArray type="{vtype}" Name="{_xml_name(name)}"{comps} '
            f'format="appended" offset="{offsets[i]}"{extra}/>'
        )

    xml: list = []
    xml.append('<?xml version="1.0"?>')
    safe_title = title
    while "--" in safe_title:  # XML forbids '--' inside comments
        safe_title = safe_title.replace("--", "- -")
    xml.append(f"<!-- {safe_title} -->")
    xml.append(
        '<VTKFile type="UnstructuredGrid" version="1.0" '
        'byte_order="LittleEndian" header_type="UInt64">'
    )
    xml.append("<UnstructuredGrid>")
    if field_names:
        # Dataset-level field data (campaign metadata): lives on the
        # grid, outside any piece.
        xml.append("<FieldData>")
        nfield = 4 + len(cell_names) + len(point_names)
        for j, name in enumerate(field_names):
            i = nfield + j
            ntup = len(blocks[i][3]) // 8
            xml.append(da(i, extra=f' NumberOfTuples="{ntup}"'))
        xml.append("</FieldData>")
    xml.append(f'<Piece NumberOfPoints="{nv}" NumberOfCells="{ne}">')
    xml.append("<Points>")
    xml.append(da(0))
    xml.append("</Points>")
    xml.append("<Cells>")
    xml.append(da(1))
    xml.append(da(2))
    xml.append(da(3))
    xml.append("</Cells>")
    idx = 4
    xml.append("<CellData>")
    for _ in cell_names:
        xml.append(da(idx))
        idx += 1
    xml.append("</CellData>")
    xml.append("<PointData>")
    for _ in point_names:
        xml.append(da(idx))
        idx += 1
    xml.append("</PointData>")
    xml.append("</Piece>")
    xml.append("</UnstructuredGrid>")
    xml.append('<AppendedData encoding="raw">')
    with open(path, "wb") as f:
        f.write("\n".join(xml).encode())
        f.write(b"\n_")
        for _, _, _, payload in blocks:
            f.write(struct.pack("<Q", len(payload)))
            f.write(payload)
        f.write(b"\n</AppendedData>\n</VTKFile>\n")


def write_pvtu(
    path: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    owner: np.ndarray,
    cell_data: Optional[Dict[str, np.ndarray]] = None,
    title: str = "pumiumtally_tpu flux result",
    nparts: Optional[int] = None,
    field_data: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Parallel multi-piece output: one raw-appended ``.vtu`` per owner
    rank plus a ``.pvtu`` index referencing them — the TPU-native
    analogue of the reference's rank-aware ``Omega_h::vtk::write_parallel``
    (reference PumiTallyImpl.cpp:415). Each piece holds the elements a
    chip owns (with its vertices reindexed locally) and that chip's
    slice of every cell-data array, so a 1M-tet partitioned result
    writes as ndev independent pieces instead of one monolithic file.
    """
    if not path.endswith(".pvtu"):
        raise ValueError(f"write_pvtu needs a .pvtu path, got {path!r}")
    coords = np.asarray(coords, np.float64)
    tet2vert = np.asarray(tet2vert, np.int64)
    ne = tet2vert.shape[0]
    owner = np.asarray(owner, np.int64).reshape(-1)
    if owner.shape[0] != ne:
        raise ValueError(
            f"owner has {owner.shape[0]} entries for {ne} elements"
        )
    if ne and owner.min() < 0:
        raise ValueError(
            "owner ids must be non-negative: every element needs a "
            "piece (-1 sentinels would be silently dropped)"
        )
    cell_data = {
        name: _check_len(name, np.asarray(arr), ne, "cell")
        for name, arr in (cell_data or {}).items()
    }
    # Explicit nparts keeps one piece per RANK even when the trailing
    # ranks own zero elements (consumers enumerate pieces per rank).
    inferred = int(owner.max()) + 1 if ne else 1
    if nparts is None:
        nparts = inferred
    elif nparts < inferred:
        raise ValueError(
            f"nparts={nparts} but owner ids reach {inferred - 1}"
        )

    base = os.path.basename(path)[: -len(".pvtu")]
    outdir = os.path.dirname(os.path.abspath(path))
    piece_files = []
    for r in range(nparts):
        sel = np.flatnonzero(owner == r)
        tets_r = tet2vert[sel]
        verts_r = np.unique(tets_r)
        local = np.full(coords.shape[0], -1, np.int64)
        local[verts_r] = np.arange(verts_r.shape[0])
        piece = f"{base}_p{r}.vtu"
        piece_files.append(piece)
        write_vtu(
            os.path.join(outdir, piece),
            coords[verts_r],
            local[tets_r],
            cell_data={k: v[sel] for k, v in cell_data.items()},
            title=f"{title} (piece {r}/{nparts})",
            # Field data is dataset-global (not per-cell): replicated
            # into every piece so any single piece accounts for the
            # whole campaign.
            field_data=field_data,
        )

    xml = ['<?xml version="1.0"?>']
    xml.append(
        '<VTKFile type="PUnstructuredGrid" version="1.0" '
        'byte_order="LittleEndian" header_type="UInt64">'
    )
    xml.append('<PUnstructuredGrid GhostLevel="0">')
    xml.append("<PPoints>")
    xml.append('<PDataArray type="Float64" Name="Points" NumberOfComponents="3"/>')
    xml.append("</PPoints>")
    xml.append("<PCellData>")
    for name in cell_data:
        xml.append(f'<PDataArray type="Float64" Name="{_xml_name(name)}"/>')
    xml.append("</PCellData>")
    for piece in piece_files:
        xml.append(f'<Piece Source="{piece}"/>')
    xml.append("</PUnstructuredGrid>")
    xml.append("</VTKFile>")
    with open(path, "w") as f:
        f.write("\n".join(xml) + "\n")


# ---------------------------------------------------------------------------
# Round-trip readers (tests + downstream tooling)
# ---------------------------------------------------------------------------

def read_vtk_cell_scalars(path: str, name: str) -> np.ndarray:
    """Pull one cell scalar array from a legacy ``.vtk`` (ASCII or
    BINARY) or ``.vtu`` file written by this module."""
    if path.endswith(".vtu"):
        return _read_vtu_array(path, name)
    with open(path, "rb") as f:
        data = f.read()
    header_end = data.find(b"\n", data.find(b"\n") + 1)
    mode_line = data[header_end + 1: data.find(b"\n", header_end + 1)]
    if mode_line.strip() == b"ASCII":
        return _read_vtk_ascii_scalars(data.decode(), name)
    return _read_vtk_binary_scalars(data, name)


def _clean_errors(fn):
    """Truncated/corrupt files must fail with ValueError/KeyError, not
    raw parser exceptions (fuzz-found: IndexError from a cut ASCII
    stream, struct.error from a cut .vtu header, and a silently SHORT
    binary array)."""
    import functools

    @functools.wraps(fn)
    def wrapped(*a, **kw):
        try:
            return fn(*a, **kw)
        except (IndexError, struct.error) as e:
            raise ValueError(f"malformed VTK stream: {e!r}") from e

    return wrapped


def read_vtk_field_scalars(path: str, name: str) -> np.ndarray:
    """Pull one dataset-level FIELD array (see ``write_vtk``'s
    ``field_data``) from a legacy ``.vtk`` (ASCII or BINARY) or
    ``.vtu`` file written by this module."""
    if path.endswith(".vtu"):
        return _read_vtu_array(path, name)
    with open(path, "rb") as f:
        data = f.read()
    header_end = data.find(b"\n", data.find(b"\n") + 1)
    mode_line = data[header_end + 1: data.find(b"\n", header_end + 1)]
    return _read_vtk_field(data, name, ascii=mode_line.strip() == b"ASCII")


@_clean_errors
def _read_vtk_field(data: bytes, name: str, ascii: bool) -> np.ndarray:  # noqa: A002
    """Sequentially parse the leading ``FIELD FieldData`` block (each
    array must be walked to find the next one's header)."""
    marker = b"FIELD FieldData "
    p = data.find(marker)
    if p < 0:
        raise KeyError(f"field array {name!r} not found (no FIELD block)")
    eol = data.find(b"\n", p)
    narrays = int(data[p + len(marker): eol])
    pos = eol + 1
    for _ in range(narrays):
        eol = data.find(b"\n", pos)
        if eol < 0:
            raise ValueError("truncated FIELD array header")
        aname, ncomp, ntup, _dtype = data[pos:eol].decode("ascii").split()
        count = int(ncomp) * int(ntup)
        pos = eol + 1
        if ascii:
            vals: list = []
            while len(vals) < count:
                eol = data.find(b"\n", pos)
                if eol < 0:
                    raise ValueError("truncated FIELD ASCII values")
                vals.extend(float(v) for v in data[pos:eol].split())
                pos = eol + 1
            if aname == name:
                return np.array(vals[:count])
        else:
            payload = data[pos: pos + 8 * count]
            if len(payload) != 8 * count:
                raise ValueError(
                    f"truncated FIELD binary values for {aname!r}"
                )
            pos += 8 * count + 1  # trailing newline after the payload
            if aname == name:
                return np.frombuffer(payload, dtype=">f8").astype(
                    np.float64
                )
    raise KeyError(f"field array {name!r} not found")


@_clean_errors
def _read_vtk_ascii_scalars(text: str, name: str) -> np.ndarray:
    lines = text.splitlines()
    ncells = None
    for i, line in enumerate(lines):
        if line.startswith("CELL_DATA"):
            ncells = int(line.split()[1])
        if line.startswith(f"SCALARS {name} ") and ncells is not None:
            vals: list = []
            j = i + 2  # skip LOOKUP_TABLE line
            while len(vals) < ncells:
                vals.extend(float(v) for v in lines[j].split())
                j += 1
            if j - 1 == len(lines) - 1 and not text.endswith("\n"):
                # The final value came from a line with no trailing
                # newline: a truncation can cut digits off a number
                # that still parses ('47' -> '4') and is then
                # indistinguishable from real data. DELIBERATE
                # strictness: a complete third-party file that merely
                # lacks its final newline is rejected too — append one
                # to load it; silent corruption is the worse failure.
                raise ValueError(
                    "ASCII scalars end on an unterminated line — "
                    "truncated file? (if the file is complete, append "
                    "a trailing newline)"
                )
            return np.array(vals[:ncells])
    raise KeyError(f"cell scalar {name!r} not found")


@_clean_errors
def _read_vtk_binary_scalars(data: bytes, name: str) -> np.ndarray:
    marker = b"CELL_DATA "
    p = data.find(marker)
    if p < 0:
        raise KeyError(f"cell scalar {name!r} not found (no CELL_DATA)")
    eol = data.find(b"\n", p)
    ncells = int(data[p + len(marker): eol])
    tag = f"SCALARS {name} ".encode()
    q = data.find(tag, p)
    if q < 0:
        raise KeyError(f"cell scalar {name!r} not found")
    # Skip the SCALARS line and the LOOKUP_TABLE line — each newline
    # must exist (find() returning -1 would silently rewind start to
    # offset 0 and parse header bytes as data).
    nl1 = data.find(b"\n", q)
    if nl1 < 0:
        raise ValueError("truncated SCALARS header line")
    nl2 = data.find(b"\n", nl1 + 1)
    if nl2 < 0:
        raise ValueError("truncated LOOKUP_TABLE line")
    start = nl2 + 1
    payload = data[start: start + 8 * ncells]
    if len(payload) != 8 * ncells:
        raise ValueError(
            f"truncated binary scalars: {len(payload)} bytes for "
            f"{ncells} cells"
        )
    return np.frombuffer(payload, dtype=">f8").astype(np.float64)


@_clean_errors
def _read_vtu_array(path: str, name: str) -> np.ndarray:
    with open(path, "rb") as f:
        data = f.read()
    tag = f'Name="{_xml_name(name)}"'.encode()
    p = data.find(tag)
    if p < 0:
        raise KeyError(f"array {name!r} not found in {path}")
    # Parse the offset attribute from THIS DataArray element.
    off_tag = b'offset="'
    elem_start = data.rfind(b"<DataArray", 0, p)
    elem_end = data.find(b"/>", p)
    elem = data[elem_start:elem_end]
    o = elem.find(off_tag)
    offset = int(elem[o + len(off_tag): elem.find(b'"', o + len(off_tag))])
    base = data.find(b'<AppendedData encoding="raw">')
    if base < 0:
        raise ValueError("no raw AppendedData section in .vtu")
    base = data.find(b"_", base) + 1
    nbytes = struct.unpack("<Q", data[base + offset: base + offset + 8])[0]
    start = base + offset + 8
    payload = data[start: start + nbytes]
    if len(payload) != nbytes:
        raise ValueError(
            f"truncated .vtu payload: {len(payload)} of {nbytes} bytes"
        )
    return np.frombuffer(payload, dtype="<f8").copy()
