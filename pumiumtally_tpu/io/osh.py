"""Omega_h ``.osh`` binary directory reader.

The reference constructor takes this format (``Omega_h::binary::read``,
reference PumiTallyImpl.cpp:562). Planned: parse the directory-of-arrays
layout (zlib-compressed) for coords and REGION→VERT connectivity.
Until then this raises with a clear workaround (the ``.msh`` path).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def read_osh(path: str) -> Tuple[np.ndarray, np.ndarray]:
    raise NotImplementedError(
        f".osh reading not implemented yet ({path!r}); pass the Gmsh .msh "
        "source mesh instead, or convert with meshio"
    )
