"""Omega_h-style ``.osh`` binary directory read/write.

The reference constructor takes an ``.osh`` directory
(``Omega_h::binary::read``, reference PumiTallyImpl.cpp:562), produced
from Gmsh meshes by its ``msh2osh`` tool (reference README.md:115-125).
This module provides the same role for this framework: a compact binary
mesh directory our ``msh2osh`` CLI emits and the ``PumiTally``
constructor reads.

Layout (mirrors the structure of Omega_h's format — per-rank stream
files plus small ASCII metadata files in a directory — but is written
and versioned by THIS package; byte-exact decoding of files produced by
Omega_h itself cannot be validated in this environment, which has no
Omega_h build, so the reader detects them and directs the user to
re-convert from the Gmsh source):

    mesh.osh/
      nparts      ASCII int  — number of rank files (only 1 supported)
      format      ASCII      — "pumiumtally-osh <version>"
      0.osh       binary stream:
        magic     2 bytes    0xa1 0x1a  (as in Omega_h streams)
        endian    1 byte     0x01 little / 0x00 big
        version   int32
        dim       int32      must be 3
        nverts    int64
        ntets     int64
        coords    array      float64 [nverts*3]
        tets      array      int32   [ntets*4]

    array := dtype_code int8, count int64, compressed int8,
             payload_bytes int64, payload (zlib if compressed)
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Tuple

import numpy as np

_MAGIC = b"\xa1\x1a"
_VERSION = 1
_DTYPE_CODES = {np.dtype(np.float64): 0, np.dtype(np.int32): 1}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


def _write_array(f, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODES[arr.dtype]
    raw = arr.tobytes()
    comp = zlib.compress(raw, level=6)
    use_comp = len(comp) < len(raw)
    payload = comp if use_comp else raw
    f.write(struct.pack("<bqbq", code, arr.size, int(use_comp), len(payload)))
    f.write(payload)


def _read_array(f) -> np.ndarray:
    hdr = f.read(struct.calcsize("<bqbq"))
    code, count, compressed, nbytes = struct.unpack("<bqbq", hdr)
    if code not in _CODE_DTYPES:
        raise ValueError(
            "unrecognized array dtype code in .osh stream — this file "
            "appears to be written by Omega_h itself; re-convert the "
            "Gmsh source with `python -m pumiumtally_tpu.cli msh2osh`"
        )
    dtype = _CODE_DTYPES[code]
    payload = f.read(nbytes)
    raw = zlib.decompress(payload) if compressed else payload
    a = np.frombuffer(raw, dtype=dtype)
    if a.size != count:
        raise ValueError(f"corrupt .osh array: {a.size} values, expected {count}")
    return a


def write_osh(path: str, coords: np.ndarray, tet2vert: np.ndarray) -> None:
    """Write a single-part ``.osh`` directory."""
    coords = np.asarray(coords, np.float64)
    tet2vert = np.asarray(tet2vert, np.int32)
    if coords.ndim != 2 or coords.shape[1] != 3:
        raise ValueError(f"coords must be [V,3], got {coords.shape}")
    if tet2vert.ndim != 2 or tet2vert.shape[1] != 4:
        raise ValueError(f"tet2vert must be [E,4], got {tet2vert.shape}")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "nparts"), "w") as f:
        f.write("1\n")
    with open(os.path.join(path, "format"), "w") as f:
        f.write(f"pumiumtally-osh {_VERSION}\n")
    with open(os.path.join(path, "0.osh"), "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<biiqq", 1, _VERSION, 3,
                            coords.shape[0], tet2vert.shape[0]))
        _write_array(f, coords.reshape(-1))
        _write_array(f, tet2vert.reshape(-1))


def read_osh(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Read a ``.osh`` directory → (coords[V,3] f64, tet2vert[E,4] i32)."""
    if not os.path.isdir(path):
        raise ValueError(
            f"{path!r}: an .osh mesh is a DIRECTORY (as with Omega_h); "
            "got a non-directory path"
        )
    nparts_file = os.path.join(path, "nparts")
    if os.path.exists(nparts_file):
        with open(nparts_file) as f:
            nparts = int(f.read().strip())
        if nparts != 1:
            raise NotImplementedError(
                f"{path!r}: multi-part .osh ({nparts} parts) not supported; "
                "write a single-part mesh"
            )
    stream = os.path.join(path, "0.osh")
    if not os.path.exists(stream):
        raise ValueError(f"{path!r}: missing rank stream file 0.osh")
    with open(stream, "rb") as f:
        if f.read(2) != _MAGIC:
            raise ValueError(f"{path!r}: bad magic in 0.osh")
        fmt_file = os.path.join(path, "format")
        if not os.path.exists(fmt_file):
            raise ValueError(
                f"{path!r}: no `format` metadata — this looks like a file "
                "written by Omega_h itself, whose byte-level encoding this "
                "reader does not decode; re-convert the Gmsh source with "
                "`python -m pumiumtally_tpu.cli msh2osh`"
            )
        endian, version, dim, nverts, ntets = struct.unpack(
            "<biiqq", f.read(struct.calcsize("<biiqq"))
        )
        if endian != 1:
            raise NotImplementedError("big-endian .osh streams not supported")
        if version > _VERSION:
            raise ValueError(f"{path!r}: .osh version {version} too new")
        if dim != 3:
            raise ValueError(f"{path!r}: expected a 3D mesh, got dim={dim}")
        coords = _read_array(f).reshape(nverts, 3)
        tets = _read_array(f).reshape(ntets, 4)
    return np.asarray(coords, np.float64), np.asarray(tets, np.int32)
