"""Omega_h ``.osh`` binary directory read/write.

The reference constructor takes an ``.osh`` directory
(``Omega_h::binary::read``, reference PumiTallyImpl.cpp:562), produced
from Gmsh meshes by its ``msh2osh`` tool (reference README.md:115-125).
This module reads and writes that format directly, so a user coming
from the reference can point ``PumiTally`` at an existing ``.osh`` mesh
without re-running conversion.

Layout implemented here (reconstructed from the public Omega_h sources
— ``Omega_h_file.cpp`` for the stream framing, ``Omega_h_simplex.hpp``
for the canonical downward templates, ``Omega_h_align.hpp`` for the
alignment codes). There is no Omega_h build in this environment (no
network), so validation is: self-round-trip, structural sanity checks,
the ``tests/data/cube_omega*.osh`` fixtures — streams produced by an
INDEPENDENT byte-level writer (``tools/make_osh_fixture.py``) — and
fixtures from ``native/osh_writer.cpp``, a standalone C++ transcription
of the upstream writer's serialization logic. Agreement with bytes
from a genuine Omega_h binary remains unproven. Because the two layout
details that CANNOT be settled without one — byte order, and whether
the stream repeats the version the directory's ``version`` file
carries — are exactly the kind of systematic misreading that would
pass a self-round-trip, the reader AUTO-DETECTS both (see
``_read_stream_any``): it tries the upstream-protocol reading
(little-endian, version in the directory file only — ``Omega_h``
writes values natively and swaps only on big-endian CPUs, i.e. the
canonical stream is little-endian, and its in-stream version read is
gated on the version file being absent), then the transposed variants,
accepting the first that passes the strict structural checks below.
Every parse failure degrades to an actionable error:

    mesh.osh/
      nparts      ASCII int   — number of rank files
      version     ASCII int   — directory format version (absent in
                                old files; the stream then carries it)
      <rank>.osh  binary stream (canonically little-endian; all four
                  endian x version-location variants are accepted):
        magic     2 bytes     0xa1 0x1a
        [version  int32       only when the version file is absent]
        compress  int8        1 = arrays are zlib streams
        family    int8        0 = simplex        (version >= 7)
        dim       int8        must be 3
        comm_size int32
        comm_rank int32
        parting   int8
        nghost    int32
        hints     int8 have; if 1: int32 naxes, then naxes x 3 float64
        matched   int8                            (version >= 10)
        nverts    int32
        downward adjacency per dimension d = 1..dim:
          ab2b    int32 array  (entity -> facet ids, (d+1) per entity)
          codes   int8  array  (alignment codes; d > 1 only)
        tags per dimension d = 0..dim:
          ntags   int32
          each: name (int32 len + bytes), ncomps int8, type int8
                (0=int8, 2=int32, 3=int64, 5=float64), data array
        owners per dimension (comm_size > 1 only): ranks + idxs arrays

    array := int32 count, then (if compress) int64 zlib-byte-count +
             zlib payload, else raw payload.

Vertex coordinates come from the ``coordinates`` float64 tag on
dimension 0. Connectivity is stored as a chain of downward adjacencies
(tet->tri->edge->vert), NOT as tet->vert; this reader composes the
chain through VERTEX SETS — each triangle's three vertices appear in
exactly two of its edges, each tet's four vertices in exactly three of
its faces — which needs no alignment-code interpretation and is
insensitive to the one layout detail that cannot be validated without a
real Omega_h build (the rotation/flip bit packing). Vertex order within
a tet is irrelevant downstream: ``TetMesh.from_arrays`` re-orients
every tet by signed volume and rebuilds face adjacency from sorted
vertex triples.

Multi-part directories are merged through the ``global`` int64 tags
Omega_h writes on distributed meshes (vertices deduped by global id,
elements deduped likewise).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import BinaryIO, Dict, List, Optional, Tuple, Union

import numpy as np

_MAGIC = b"\xa1\x1a"
# Stream version our writer emits; the reader accepts 4..10 (gating the
# few layout differences it knows about) and errors on anything newer.
_WRITE_VERSION = 9
_MIN_VERSION = 4
_MAX_VERSION = 10

_TYPE_I8 = 0
_TYPE_I32 = 2
_TYPE_I64 = 3
_TYPE_F64 = 5
_TYPE_CODES = {_TYPE_I8: "i1", _TYPE_I32: "i4", _TYPE_I64: "i8",
               _TYPE_F64: "f8"}


def _type_dtype(typ: int, end: str) -> np.dtype:
    return np.dtype(end + _TYPE_CODES[typ])

# Canonical tet-face template (Omega_h_simplex.hpp simplex_down_template
# for (3,2)): face k's vertices as local tet vertex indices.
_TET_FACE_TEMPLATE = np.array(
    [[0, 2, 1], [0, 1, 3], [1, 2, 3], [2, 0, 3]], dtype=np.int64
)
# Triangle-edge template for (2,1): edge k connects verts (k, k+1 mod 3).
_TRI_EDGE_TEMPLATE = np.array([[0, 1], [1, 2], [2, 0]], dtype=np.int64)


class OshFormatError(ValueError):
    """A stream that does not parse as the Omega_h layout above."""


# ---------------------------------------------------------------------------
# Low-level stream helpers (big-endian, zlib arrays)
# ---------------------------------------------------------------------------

def _read_exact(f: BinaryIO, n: int) -> bytes:
    b = f.read(n)
    if len(b) != n:
        raise OshFormatError(
            f"truncated .osh stream: wanted {n} bytes, got {len(b)}"
        )
    return b


def _read_value(f: BinaryIO, fmt: str, end: str):
    fmt = end + fmt
    return struct.unpack(fmt, _read_exact(f, struct.calcsize(fmt)))[0]


# The writer emits the canonical (little-endian) byte order — Omega_h
# writes values natively and swaps only on big-endian CPUs.
_WRITE_END = "<"


def _write_value(f: BinaryIO, fmt: str, v) -> None:
    f.write(struct.pack(_WRITE_END + fmt, v))


def _remaining(f: BinaryIO) -> Optional[int]:
    """Bytes left in a seekable stream (None if not seekable) — bounds
    corrupt count fields before they drive giant allocations."""
    try:
        pos = f.tell()
        end = f.seek(0, 2)
        f.seek(pos)
        return end - pos
    except (OSError, AttributeError):  # pragma: no cover — pipes etc.
        return None


def _read_array(
    f: BinaryIO, typ: int, compressed: bool, end: str
) -> np.ndarray:
    dtype = _type_dtype(typ, end)
    count = _read_value(f, "i", end)
    if count < 0:
        raise OshFormatError(f"negative array count {count} in .osh stream")
    nbytes = count * dtype.itemsize
    left = _remaining(f)
    if compressed:
        zbytes = _read_value(f, "q", end)
        if zbytes < 0:
            raise OshFormatError("negative zlib byte count in .osh stream")
        # Plausibility bounds from the actual file size: a corrupt
        # count/zbytes field must produce a clean error, not a
        # multi-gigabyte allocation attempt. (zlib tops out around
        # ~1000:1 on real data; 4096 leaves margin.)
        if left is not None and (
            zbytes > left or nbytes > 4096 * max(left, 1)
        ):
            raise OshFormatError(
                f"array header implausible for the file size "
                f"(count={count}, zbytes={zbytes}, {left} bytes left)"
            )
        try:
            # Cap the DECOMPRESSED size too: a payload that inflates
            # past the declared count must error, not allocate.
            dec = zlib.decompressobj()
            raw = dec.decompress(_read_exact(f, zbytes), nbytes + 1)
            if len(raw) > nbytes or dec.unconsumed_tail:
                raise OshFormatError(
                    f"zlib payload inflates past the declared "
                    f"{nbytes} bytes"
                )
            raw += dec.flush()
        except zlib.error as e:
            # A corrupt payload must surface as the documented clean
            # error, not a raw zlib exception.
            raise OshFormatError(f"corrupt zlib array payload: {e}") from e
        if len(raw) != nbytes:
            raise OshFormatError(
                f"zlib payload decompressed to {len(raw)} bytes, "
                f"expected {nbytes}"
            )
    else:
        if left is not None and nbytes > left:
            raise OshFormatError(
                f"array header implausible for the file size "
                f"(count={count}, {left} bytes left)"
            )
        raw = _read_exact(f, nbytes)
    return np.frombuffer(raw, dtype=dtype).copy()


def _write_array(f: BinaryIO, arr: np.ndarray, typ: int,
                 compress: bool) -> None:
    arr = np.ascontiguousarray(arr, dtype=_type_dtype(typ, _WRITE_END))
    _write_value(f, "i", arr.size)
    raw = arr.tobytes()
    if compress:
        # Z_BEST_SPEED — the level the upstream writer passes to
        # compress2 (parseability does not depend on it, but byte
        # parity with native/osh_writer.cpp does).
        z = zlib.compress(raw, 1)
        _write_value(f, "q", len(z))
        f.write(z)
    else:
        f.write(raw)


def _read_string(f: BinaryIO, end: str) -> str:
    n = _read_value(f, "i", end)
    if not 0 <= n < 4096:
        raise OshFormatError(f"implausible string length {n} in .osh stream")
    return _read_exact(f, n).decode("utf-8")


def _write_string(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    _write_value(f, "i", len(b))
    f.write(b)


# ---------------------------------------------------------------------------
# Stream reader
# ---------------------------------------------------------------------------

def _read_meta(f: BinaryIO, version: int, end: str) -> Tuple[int, int, bool]:
    """Returns (dim, comm_size, compressed)."""
    compressed = bool(_read_value(f, "b", end))
    if version >= 7:
        family = _read_value(f, "b", end)
        if family != 0:
            raise OshFormatError(
                f"mesh family {family} is not simplex; only tet meshes "
                "are supported"
            )
    dim = _read_value(f, "b", end)
    comm_size = _read_value(f, "i", end)
    _comm_rank = _read_value(f, "i", end)
    _parting = _read_value(f, "b", end)
    _nghost = _read_value(f, "i", end)
    have_hints = _read_value(f, "b", end)
    if have_hints not in (0, 1):
        raise OshFormatError(f"implausible RIB hint flag {have_hints}")
    if have_hints:
        naxes = _read_value(f, "i", end)
        if not 0 <= naxes < 64:
            raise OshFormatError(f"implausible RIB hint axis count {naxes}")
        _read_exact(f, naxes * 3 * 8)
    if version >= 10:
        matched = _read_value(f, "b", end)
        if matched:
            raise OshFormatError("matched (periodic) meshes not supported")
    return dim, comm_size, compressed


def _read_tags(
    f: BinaryIO, nents: int, compressed: bool, end: str
) -> Dict[str, np.ndarray]:
    ntags = _read_value(f, "i", end)
    if not 0 <= ntags < 1024:
        raise OshFormatError(f"implausible tag count {ntags} in .osh stream")
    tags: Dict[str, np.ndarray] = {}
    for _ in range(ntags):
        name = _read_string(f, end)
        ncomps = _read_value(f, "b", end)
        typ = _read_value(f, "b", end)
        if typ not in _TYPE_CODES:
            raise OshFormatError(
                f"unknown tag data type {typ} for tag {name!r}"
            )
        if ncomps < 1:
            # Omega_h tags always have >= 1 component; a non-positive
            # count would bypass the size validation below and hand a
            # misaligned array to downstream consumers.
            raise OshFormatError(
                f"implausible component count {ncomps} for tag {name!r}"
            )
        data = _read_array(f, typ, compressed, end)
        if data.size != nents * ncomps:
            raise OshFormatError(
                f"tag {name!r}: {data.size} values for {nents} entities "
                f"x {ncomps} comps"
            )
        tags[name] = (
            data.reshape(nents, ncomps) if ncomps > 1 else data
        )
    return tags


def _compose_vertex_sets(
    down: np.ndarray, child_verts: np.ndarray, per: int
) -> np.ndarray:
    """Vertices of each entity from its facets' vertices: with ``per``
    facets each carrying the entity's vertices minus one, every vertex
    appears exactly ``per - 1`` times in the concatenation; sorting and
    striding recovers the unique set without alignment codes."""
    n = down.shape[0]
    if n == 0:  # a rank can own zero entities in a multi-part mesh
        return np.zeros((0, per), np.int64)
    stacked = child_verts[down].reshape(n, -1)  # [n, per*(per-1)]
    s = np.sort(stacked, axis=1)
    mult = per - 1
    sets = s[:, ::mult]
    # Validate the multiplicity structure (catches both corrupt files
    # and any misreading of the adjacency framing).
    expect = np.repeat(sets, mult, axis=1)
    if not np.array_equal(expect, s):
        raise OshFormatError(
            "downward adjacency does not compose to consistent vertex "
            "sets — the stream framing was misread or the file is corrupt"
        )
    return sets


def _read_stream(
    f: BinaryIO,
    version: Optional[int],
    version_in_stream: bool,
    end: str,
) -> dict:
    """Parse one <rank>.osh stream → dict with coords, tet2vert, and
    per-dimension tag dicts.

    ``version`` is the directory ``version`` file's value (None when
    absent); ``version_in_stream`` selects whether an int32 version
    follows the magic (upstream writes it there only for old files
    whose directories lack the version file); ``end`` is the struct
    byte-order character. ``_read_stream_any`` tries the variants.
    """
    if _read_exact(f, 2) != _MAGIC:
        raise OshFormatError("bad magic bytes (not an Omega_h stream)")
    if version_in_stream:
        version = _read_value(f, "i", end)
    if version is None:
        raise OshFormatError(
            "no version file in the directory and none read from the "
            "stream"
        )
    if not _MIN_VERSION <= version <= _MAX_VERSION:
        raise OshFormatError(
            f".osh stream version {version} outside supported range "
            f"[{_MIN_VERSION}, {_MAX_VERSION}]"
        )
    dim, comm_size, compressed = _read_meta(f, version, end)
    if dim != 3:
        raise OshFormatError(f"expected a 3D mesh, got dim={dim}")
    if not 1 <= comm_size < 2**20:
        raise OshFormatError(f"implausible comm size {comm_size}")
    nverts = _read_value(f, "i", end)
    if nverts < 0:
        raise OshFormatError(f"negative vertex count {nverts}")

    # Downward adjacency chain: edge2vert, tri2edge(+codes), tet2tri(+codes).
    ev2v = _read_array(f, _TYPE_I32, compressed, end)
    if ev2v.size % 2:
        raise OshFormatError("edge->vert adjacency not a multiple of 2")
    edge2vert = ev2v.reshape(-1, 2).astype(np.int64)
    fe2e = _read_array(f, _TYPE_I32, compressed, end)
    _ = _read_array(f, _TYPE_I8, compressed, end)  # tri codes
    if fe2e.size % 3:
        raise OshFormatError("tri->edge adjacency not a multiple of 3")
    tri2edge = fe2e.reshape(-1, 3).astype(np.int64)
    rf2f = _read_array(f, _TYPE_I32, compressed, end)
    _ = _read_array(f, _TYPE_I8, compressed, end)  # tet codes
    if rf2f.size % 4:
        raise OshFormatError("tet->tri adjacency not a multiple of 4")
    tet2tri = rf2f.reshape(-1, 4).astype(np.int64)

    # Index-range validation BEFORE any fancy indexing: a misframed or
    # corrupt stream must produce the clean format error, not a numpy
    # IndexError (and the variant auto-detection relies on clean
    # rejection of wrong framings).
    for arr, bound, what in (
        (edge2vert, nverts, "edge->vert"),
        (tri2edge, edge2vert.shape[0], "tri->edge"),
        (tet2tri, tri2edge.shape[0], "tet->tri"),
    ):
        if arr.size and (arr.min() < 0 or arr.max() >= bound):
            raise OshFormatError(
                f"{what} adjacency references entities outside "
                f"[0, {bound})"
            )

    nents = [nverts, edge2vert.shape[0], tri2edge.shape[0], tet2tri.shape[0]]
    tags: List[Dict[str, np.ndarray]] = []
    for d in range(4):
        tags.append(_read_tags(f, nents[d], compressed, end))
        if comm_size > 1:
            _ranks = _read_array(f, _TYPE_I32, compressed, end)
            _idxs = _read_array(f, _TYPE_I32, compressed, end)

    if "coordinates" not in tags[0]:
        raise OshFormatError("no `coordinates` tag on the vertices")
    coords = np.asarray(tags[0]["coordinates"], np.float64)
    if coords.ndim != 2 or coords.shape != (nverts, 3):
        raise OshFormatError(
            f"coordinates tag has shape {coords.shape}, "
            f"expected ({nverts}, 3)"
        )

    tri2vert = _compose_vertex_sets(tri2edge, edge2vert, 3)
    tet2vert = _compose_vertex_sets(tet2tri, tri2vert, 4)
    return {
        "coords": coords,
        "tet2vert": tet2vert.astype(np.int32),
        "tags": tags,
        "comm_size": comm_size,
    }


def _read_stream_any(f: BinaryIO, dir_version: Optional[int]) -> dict:
    """Parse a <rank>.osh stream, auto-detecting byte order and version
    location (the two layout details unprovable without a genuine
    Omega_h build — see the module docstring).

    Variant priority follows the upstream protocol: when the directory
    has a ``version`` file the stream does not repeat it (upstream only
    reads an in-stream version when the file is absent), and streams
    are canonically little-endian; the transposed variants cover both
    this package's earlier big-endian/in-stream output and the
    possibility that the upstream reading here is itself transposed.
    Wrong framings are rejected by hard structural checks (magic,
    version range, dim==3, simplex family, adjacency multiples,
    index ranges, tag sizes, and the vertex-set multiplicity
    composition), so false acceptance is not a practical concern —
    and every accepted variant yields the same arrays, since values
    are values once the framing is fixed.
    """
    if dir_version is not None:
        variants = [("<", False), (">", True), ("<", True), (">", False)]
    else:
        # No version file: the stream must carry the version.
        variants = [("<", True), (">", True)]
    errors = []
    for end, vin in variants:
        f.seek(0)
        try:
            return _read_stream(f, dir_version, vin, end)
        except OshFormatError as e:
            errors.append(
                f"[{'LE' if end == '<' else 'BE'}"
                f"{'/stream-version' if vin else ''}] {e}"
            )
    raise OshFormatError(
        "stream parses under no known layout variant: " + "; ".join(errors)
    )


# ---------------------------------------------------------------------------
# Stream writer (same layout; lets Omega_h users round-trip our output)
# ---------------------------------------------------------------------------

def _build_downward(tet2vert: np.ndarray):
    """Edges/tris + downward chain from tet connectivity, with canonical
    (sorted-key, first-appearance) entity numbering and alignment codes
    per the template conventions above."""
    tet2vert = np.asarray(tet2vert, np.int64)
    ne = tet2vert.shape[0]

    tri_keys = np.sort(tet2vert[:, _TET_FACE_TEMPLATE], axis=2).reshape(-1, 3)
    tri_uniq, tet2tri_flat = np.unique(
        tri_keys, axis=0, return_inverse=True
    )
    tet2tri = tet2tri_flat.reshape(ne, 4)
    # A triangle's stored vertex order: ascending (the unique key).
    tri2vert = tri_uniq  # [T,3] sorted

    edge_keys = np.sort(tri2vert[:, _TRI_EDGE_TEMPLATE], axis=2).reshape(-1, 2)
    edge_uniq, tri2edge_flat = np.unique(
        edge_keys, axis=0, return_inverse=True
    )
    tri2edge = tri2edge_flat.reshape(-1, 3)
    edge2vert = edge_uniq  # [Ed,2] sorted

    # Alignment codes (Omega_h_align.hpp: code = rotation << 1 | flip).
    # Edges stored ascending and triangle templates traverse (k, k+1):
    # the code is a flip bit when the template order descends.
    tri_edge_tmpl = tri2vert[:, _TRI_EDGE_TEMPLATE]  # [T,3,2]
    tri_codes = (tri_edge_tmpl[:, :, 0] > tri_edge_tmpl[:, :, 1]).astype(
        np.int8
    ).reshape(-1)
    # Tet faces: stored tri verts are ascending; compute (rotation,
    # flip) mapping stored order onto the face template order.
    face_tmpl = tet2vert[:, _TET_FACE_TEMPLATE]  # [E,4,3]
    stored = tri2vert[tet2tri]  # [E,4,3] ascending
    codes = np.zeros((ne, 4), np.int8)
    for rot in range(3):
        rolled = np.roll(stored, -rot, axis=2)
        match_f0 = np.all(rolled == face_tmpl, axis=2)
        flipped = rolled.copy()
        flipped[..., [1, 2]] = flipped[..., [2, 1]]
        match_f1 = np.all(flipped == face_tmpl, axis=2)
        codes = np.where(match_f0, np.int8(rot << 1), codes)
        codes = np.where(match_f1, np.int8((rot << 1) | 1), codes)
    return edge2vert, tri2edge, tri_codes, tet2tri, codes.reshape(-1)


def _write_stream(
    f: BinaryIO,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    compress: bool = True,
    comm_size: int = 1,
    comm_rank: int = 0,
    extra_tags: Optional[List[Dict[str, np.ndarray]]] = None,
) -> None:
    f.write(_MAGIC)
    # No in-stream version: the directory's `version` file carries it
    # (upstream moved it there at version 4 and only reads it from the
    # stream when the file is absent).
    _write_value(f, "b", int(compress))
    _write_value(f, "b", 0)  # family: simplex
    _write_value(f, "b", 3)  # dim
    _write_value(f, "i", comm_size)
    _write_value(f, "i", comm_rank)
    _write_value(f, "b", 0)  # parting (elem-based)
    _write_value(f, "i", 0)  # nghost_layers
    _write_value(f, "b", 0)  # no RIB hints
    _write_value(f, "i", coords.shape[0])  # nverts

    edge2vert, tri2edge, tri_codes, tet2tri, tet_codes = _build_downward(
        tet2vert
    )
    _write_array(f, edge2vert.reshape(-1), _TYPE_I32, compress)
    _write_array(f, tri2edge.reshape(-1), _TYPE_I32, compress)
    _write_array(f, tri_codes, _TYPE_I8, compress)
    _write_array(f, tet2tri.reshape(-1), _TYPE_I32, compress)
    _write_array(f, tet_codes, _TYPE_I8, compress)

    nents = [coords.shape[0], edge2vert.shape[0], tri2edge.shape[0],
             tet2tri.shape[0]]
    for d in range(4):
        tags: Dict[str, np.ndarray] = {}
        if d == 0:
            tags["coordinates"] = np.asarray(coords, np.float64)
        if extra_tags and extra_tags[d]:
            tags.update(extra_tags[d])
        _write_value(f, "i", len(tags))
        for name, data in tags.items():
            data = np.asarray(data)
            ncomps = 1 if data.ndim == 1 else data.shape[1]
            _write_string(f, name)
            _write_value(f, "b", ncomps)
            if data.dtype == np.float64:
                typ = _TYPE_F64
            elif data.dtype == np.int64:
                typ = _TYPE_I64
            elif data.dtype == np.int8:
                typ = _TYPE_I8
            else:
                typ = _TYPE_I32
            _write_value(f, "b", typ)
            _write_array(f, data.reshape(-1), typ, compress)
        if comm_size > 1:
            # Owners: this writer emits fully-owned parts (rank owns
            # every entity it stores) — merging goes through globals.
            _write_array(f, np.full(nents[d], comm_rank), _TYPE_I32,
                         compress)
            _write_array(f, np.arange(nents[d]), _TYPE_I32, compress)


# ---------------------------------------------------------------------------
# Directory-level API
# ---------------------------------------------------------------------------

# Tag names the writer itself emits; a user tag shadowing one would
# corrupt the multi-part merge or the coordinate read.
_RESERVED_TAGS = ("global", "coordinates")


def _normalize_tag(name: str, arr, nents: int) -> np.ndarray:
    """Validate a user tag: reserved names rejected, dtype mapped onto
    a stream-representable one with NO silent value change."""
    if name in _RESERVED_TAGS:
        raise ValueError(f"tag name {name!r} is reserved by the writer")
    a = np.asarray(arr)
    if a.shape[0] != nents:
        raise ValueError(
            f"element tag {name!r} has {a.shape[0]} values for "
            f"{nents} entities"
        )
    if a.dtype in (np.float64, np.int64, np.int32, np.int8):
        return a
    if a.dtype == np.bool_:
        return a.astype(np.int8)  # 0/1: exact
    if np.issubdtype(a.dtype, np.floating):
        widened = a.astype(np.float64)
        # f16/f32 → f64 is exact; longdouble → f64 may round.
        if a.dtype.itemsize > 8 and not np.array_equal(
            widened.astype(a.dtype), a, equal_nan=True
        ):
            raise ValueError(
                f"element tag {name!r} ({a.dtype}) does not fit float64 "
                "exactly; cast it yourself if the rounding is acceptable"
            )
        return widened
    if np.issubdtype(a.dtype, np.unsignedinteger):
        if a.dtype.itemsize == 8 and a.size and a.max() > np.iinfo(np.int64).max:
            raise ValueError(
                f"element tag {name!r} has uint64 values beyond int64 "
                "range; the .osh stream has no unsigned 64-bit type"
            )
        return a.astype(np.int64)  # in-range: exact
    if np.issubdtype(a.dtype, np.integer):
        return a.astype(np.int64)  # widening: exact
    raise ValueError(
        f"element tag {name!r} has unsupported dtype {a.dtype}; use a "
        "float, integer or bool array"
    )

def write_osh(
    path: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    nparts: int = 1,
    elem_tags: Optional[Dict[str, np.ndarray]] = None,
) -> None:
    """Write an ``.osh`` directory in the Omega_h layout.

    ``nparts > 1`` splits elements into contiguous blocks with
    per-part ``global`` tags (each part stores copies of the vertices
    it touches), exercising the same multi-part structure Omega_h
    writes for distributed meshes. ``elem_tags`` are per-element
    arrays written as dimension-3 tags (e.g. the ``class_id``
    material classification Omega_h meshes carry).
    """
    coords = np.asarray(coords, np.float64)
    tet2vert = np.asarray(tet2vert, np.int32)
    if coords.ndim != 2 or coords.shape[1] != 3:
        raise ValueError(f"coords must be [V,3], got {coords.shape}")
    if tet2vert.ndim != 2 or tet2vert.shape[1] != 4:
        raise ValueError(f"tet2vert must be [E,4], got {tet2vert.shape}")
    elem_tags = {
        name: _normalize_tag(name, arr, tet2vert.shape[0])
        for name, arr in (elem_tags or {}).items()
    }
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "nparts"), "w") as f:
        f.write(f"{nparts}\n")
    with open(os.path.join(path, "version"), "w") as f:
        f.write(f"{_WRITE_VERSION}\n")
    if nparts == 1:
        extra: List[Dict[str, np.ndarray]] = [{}, {}, {}, {}]
        if elem_tags:
            extra[3].update(
                {k: np.asarray(v) for k, v in elem_tags.items()}
            )
        with open(os.path.join(path, "0.osh"), "wb") as f:
            _write_stream(f, coords, tet2vert, extra_tags=extra)
        return
    ne = tet2vert.shape[0]
    bounds = np.linspace(0, ne, nparts + 1).astype(np.int64)
    # Vertices referenced by no tet (orphan nodes happen in Gmsh
    # exports) ride with rank 0 so the merged vertex globals stay dense
    # and the round trip is lossless.
    orphans = np.setdiff1d(
        np.arange(coords.shape[0], dtype=np.int64), np.unique(tet2vert)
    )
    for rank in range(nparts):
        sel = tet2vert[bounds[rank]:bounds[rank + 1]].astype(np.int64)
        vg = np.unique(sel)
        if rank == 0 and orphans.size:
            vg = np.union1d(vg, orphans)
        local = np.searchsorted(vg, sel.reshape(-1))
        extra: List[Dict[str, np.ndarray]] = [{}, {}, {}, {}]
        extra[0]["global"] = vg.astype(np.int64)
        extra[3]["global"] = np.arange(
            bounds[rank], bounds[rank + 1], dtype=np.int64
        )
        if elem_tags:
            extra[3].update({
                k: np.asarray(v)[bounds[rank]:bounds[rank + 1]]
                for k, v in elem_tags.items()
            })
        with open(os.path.join(path, f"{rank}.osh"), "wb") as f:
            _write_stream(
                f, coords[vg],
                local.reshape(sel.shape).astype(np.int32),
                comm_size=nparts, comm_rank=rank, extra_tags=extra,
            )


def read_osh(
    path: str, with_tags: bool = False
) -> Union[
    Tuple[np.ndarray, np.ndarray],
    Tuple[np.ndarray, np.ndarray, Dict[str, np.ndarray]],
]:
    """Read an ``.osh`` directory → (coords[V,3] f64, tet2vert[E,4] i32).

    Accepts both genuine Omega_h directories (single- or multi-part;
    multi-part needs the ``global`` tags Omega_h writes on distributed
    meshes) and directories written by this package's round-1 legacy
    format (kept for back-compat with existing converted meshes).

    ``with_tags=True`` additionally returns the per-ELEMENT tag arrays
    (dimension-3 tags except the structural ``global``), aligned with
    the returned element order — e.g. the ``class_id`` material
    classification ``msh2osh`` meshes carry, ready for
    ``utils.postprocess.label_totals``/``label_averages``. Legacy
    round-1 directories have no tags ({}).
    """
    if not os.path.isdir(path):
        raise ValueError(
            f"{path!r}: an .osh mesh is a DIRECTORY (as with Omega_h); "
            "got a non-directory path"
        )
    legacy = os.path.join(path, "format")
    if os.path.exists(legacy):
        coords, tets = _read_legacy(path)
        return (coords, tets, {}) if with_tags else (coords, tets)
    nparts_file = os.path.join(path, "nparts")
    nparts = 1
    if os.path.exists(nparts_file):
        with open(nparts_file) as f:
            nparts = int(f.read().strip())
    version_file = os.path.join(path, "version")
    dir_version: Optional[int] = None
    if os.path.exists(version_file):
        with open(version_file) as f:
            dir_version = int(f.read().strip())
    parts = []
    for rank in range(nparts):
        stream = os.path.join(path, f"{rank}.osh")
        if not os.path.exists(stream):
            raise ValueError(
                f"{path!r}: missing rank stream file {rank}.osh "
                f"(nparts={nparts})"
            )
        with open(stream, "rb") as f:
            try:
                parts.append(_read_stream_any(f, dir_version))
            except OshFormatError as e:
                raise ValueError(
                    f"{path!r}/{rank}.osh does not parse as an Omega_h "
                    f"stream ({e}); if this file predates the supported "
                    "versions, re-convert the Gmsh source with "
                    "`python -m pumiumtally_tpu.cli msh2osh`"
                ) from e
    if nparts == 1:
        p = parts[0]
        if with_tags:
            return p["coords"], p["tet2vert"], _elem_tags(p["tags"][3])
        return p["coords"], p["tet2vert"]
    merged = _merge_parts(parts, with_tags=with_tags)
    return merged


def _elem_tags(dim3_tags: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Element tags minus the structural ``global`` ids."""
    return {k: v for k, v in dim3_tags.items() if k != "global"}


def _merge_parts(
    parts: List[dict], with_tags: bool = False
) -> Union[
    Tuple[np.ndarray, np.ndarray],
    Tuple[np.ndarray, np.ndarray, Dict[str, np.ndarray]],
]:
    """Merge multi-part streams through their ``global`` id tags."""
    for i, p in enumerate(parts):
        if "global" not in p["tags"][0] or "global" not in p["tags"][3]:
            raise ValueError(
                f"multi-part .osh rank {i} lacks `global` id tags; "
                "cannot merge the distributed mesh"
            )
    vglob = np.concatenate(
        [np.asarray(p["tags"][0]["global"], np.int64) for p in parts]
    )
    vcoords = np.concatenate([p["coords"] for p in parts], axis=0)
    uniq_v, first = np.unique(vglob, return_index=True)
    if not np.array_equal(uniq_v, np.arange(uniq_v.size)):
        raise ValueError("multi-part .osh vertex globals are not dense")
    coords = vcoords[first]

    tets = []
    eglob = []
    for p in parts:
        gv = np.asarray(p["tags"][0]["global"], np.int64)
        tets.append(gv[p["tet2vert"]])
        eglob.append(np.asarray(p["tags"][3]["global"], np.int64))
    tet_all = np.concatenate(tets, axis=0)
    eg_all = np.concatenate(eglob)
    uniq_e, efirst = np.unique(eg_all, return_index=True)
    if not np.array_equal(uniq_e, np.arange(uniq_e.size)):
        raise ValueError("multi-part .osh element globals are not dense")
    out_tets = tet_all[efirst].astype(np.int32)
    if not with_tags:
        return coords, out_tets
    # Element tags present on EVERY part merge through the same
    # selection (dedup keeps the first part's copy of each element).
    names = set(_elem_tags(parts[0]["tags"][3]))
    for p in parts[1:]:
        names &= set(_elem_tags(p["tags"][3]))
    tags_out = {
        name: np.concatenate(
            [np.asarray(p["tags"][3][name]) for p in parts]
        )[efirst]
        for name in sorted(names)
    }
    return coords, out_tets, tags_out


# ---------------------------------------------------------------------------
# Legacy round-1 container (kept so previously converted meshes load)
# ---------------------------------------------------------------------------

_LEGACY_DTYPES = {0: np.dtype(np.float64), 1: np.dtype(np.int32)}


def _read_legacy(path: str) -> Tuple[np.ndarray, np.ndarray]:
    stream = os.path.join(path, "0.osh")
    if not os.path.exists(stream):
        raise ValueError(f"{path!r}: missing rank stream file 0.osh")
    with open(stream, "rb") as f:
        if f.read(2) != _MAGIC:
            raise ValueError(f"{path!r}: bad magic in legacy 0.osh")
        endian, version, dim, nverts, ntets = struct.unpack(
            "<biiqq", f.read(struct.calcsize("<biiqq"))
        )
        if endian != 1:
            raise NotImplementedError("big-endian legacy .osh not supported")
        if dim != 3:
            raise ValueError(f"{path!r}: expected a 3D mesh, got dim={dim}")
        coords = _read_legacy_array(f).reshape(nverts, 3)
        tets = _read_legacy_array(f).reshape(ntets, 4)
    return np.asarray(coords, np.float64), np.asarray(tets, np.int32)


def _read_legacy_array(f) -> np.ndarray:
    hdr = f.read(struct.calcsize("<bqbq"))
    code, count, compressed, nbytes = struct.unpack("<bqbq", hdr)
    if code not in _LEGACY_DTYPES:
        raise ValueError("unrecognized array dtype code in legacy .osh")
    payload = f.read(nbytes)
    raw = zlib.decompress(payload) if compressed else payload
    a = np.frombuffer(raw, dtype=_LEGACY_DTYPES[code])
    if a.size != count:
        raise ValueError(
            f"corrupt legacy .osh array: {a.size} values, expected {count}"
        )
    return a
