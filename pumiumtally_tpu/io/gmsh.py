"""Gmsh ``.msh`` reader (formats 2.2 and 4.1, ASCII and binary), tets only.

The reference's mesh pipeline is Gmsh → ``msh2osh`` → ``.osh``
(reference README.md:115-125); we read the Gmsh file directly and keep
an ``.osh`` reader separately for meshes already converted.
Only what the tally needs is parsed: node coordinates and 4-node
tetrahedra (Gmsh element type 4). Binary files follow the layouts in
Gmsh's MSH documentation: little/big endianness is detected from the
``$MeshFormat`` probe int; v2 stores int32 records, v4 stores size_t
(8-byte) tags with int32 block headers.
"""

from __future__ import annotations

import struct
from typing import List, Tuple

import numpy as np

# Node counts per Gmsh element type (type 4 = 4-node tetrahedron). The
# binary readers need these to SKIP non-tet blocks (the record stride
# depends on the node count), so the table carries the full standard
# set; a type outside it is unskippable and must error.
_NODES_PER_ELEM_TYPE = {
    1: 2, 2: 3, 3: 4, 4: 4, 5: 8, 6: 6, 7: 5, 8: 3, 9: 6, 10: 9,
    11: 10, 12: 27, 13: 18, 14: 14, 15: 1, 16: 8, 17: 20, 18: 15,
    19: 13, 20: 9, 21: 10, 22: 12, 23: 15, 24: 15, 25: 21, 26: 4,
    27: 5, 28: 6, 29: 20, 30: 35, 31: 56, 92: 64, 93: 125,
}


def _section(data: bytes, name: str) -> bytes:
    """Byte content between ``$name\\n`` and ``\\n$Endname``."""
    start_tag = b"$" + name.encode()
    p = data.find(start_tag)
    if p < 0:
        raise ValueError(f"missing ${name} section")
    p = data.find(b"\n", p) + 1
    q = data.find(b"$End" + name.encode(), p)
    if q < 0:
        raise ValueError(f"unterminated ${name} section")
    return data[p:q]


def read_gmsh(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Return (coords[V,3] float64, tet2vert[E,4] int32, 0-based)."""
    with open(path, "rb") as f:
        data = f.read()
    fmt = _section(data, "MeshFormat")
    head = fmt.split(b"\n")[0].split()
    if len(head) < 3:
        raise ValueError(f"{path}: malformed $MeshFormat")
    version = float(head[0])
    file_type = int(head[1])
    if 4.0 <= version < 4.1:
        # MSH 4.0 interleaves node tags with coordinates and orders
        # block headers differently; parsing it with the 4.1 layout
        # yields garbage tags and a misleading error.
        raise ValueError(
            f"{path}: MSH format {head[0].decode()} (4.0) not supported; "
            "re-export as 4.1 or 2.2"
        )
    try:
        if file_type == 0:
            text = data.decode("utf-8", "replace")
            sections = _text_sections(text)
            if version >= 4.0:
                return _parse_v4(sections)
            return _parse_v2(sections)
        # Binary: endianness from the probe int after the format line.
        nl = fmt.find(b"\n")
        probe = fmt[nl + 1: nl + 5]
        if len(probe) < 4:
            raise ValueError(f"{path}: truncated binary $MeshFormat")
        if struct.unpack("<i", probe)[0] == 1:
            end = "<"
        elif struct.unpack(">i", probe)[0] == 1:
            end = ">"
        else:
            raise ValueError(f"{path}: cannot determine binary endianness")
        if version >= 4.0:
            return _parse_v4_binary(data, end)
        return _parse_v2_binary(data, end)
    except (IndexError, KeyError, struct.error) as e:
        # Truncated/corrupt files must fail with the documented clean
        # error, not a raw parser exception (fuzz-found: a cut ASCII
        # $Nodes line raised bare IndexError; a cut-off section raised
        # bare KeyError).
        raise ValueError(f"{path}: malformed .msh stream: {e!r}") from e


def _text_sections(text: str) -> dict:
    lines = text.splitlines()
    sections = {}
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("$") and not line.startswith("$End"):
            name = line[1:]
            j = i + 1
            while j < len(lines) and lines[j].strip() != f"$End{name}":
                j += 1
            sections[name] = lines[i + 1: j]
            i = j + 1
        else:
            i += 1
    if "MeshFormat" not in sections:
        raise ValueError("not a Gmsh mesh (no $MeshFormat)")
    return sections


def _finish(coords: np.ndarray, ids: np.ndarray, tet_ids: np.ndarray):
    """Remap 1-based/sparse node tags to dense 0-based indices."""
    if tet_ids.size == 0:
        raise ValueError("no tetrahedra (type 4) found in mesh")
    order = np.argsort(ids)
    pos = np.searchsorted(ids[order], tet_ids.reshape(-1))
    if np.any(pos >= ids.size) or np.any(
        ids[order][np.clip(pos, 0, ids.size - 1)] != tet_ids.reshape(-1)
    ):
        raise ValueError("element references unknown node tag")
    remap = order[pos].reshape(tet_ids.shape)
    return coords, remap.astype(np.int32)


# ---------------------------------------------------------------------------
# ASCII
# ---------------------------------------------------------------------------

def _check_count(n, bound, what: str) -> int:
    """Validate a count field parsed from the stream: non-negative and
    plausible against the data actually present, so a corrupt header
    raises cleanly instead of allocating gigabytes or looping forever
    (fuzz-found classes)."""
    n = int(n)
    if n < 0 or n > bound:
        raise ValueError(
            f"implausible {what} count {n} (bound {bound}) in .msh stream"
        )
    return n


def _parse_v2(sections) -> Tuple[np.ndarray, np.ndarray]:
    nodes = sections["Nodes"]
    nn = _check_count(nodes[0], len(nodes), "node")
    ids = np.empty(nn, np.int64)
    coords = np.empty((nn, 3), np.float64)
    for k in range(nn):
        parts = nodes[1 + k].split()
        ids[k] = int(parts[0])
        coords[k] = [float(parts[1]), float(parts[2]), float(parts[3])]

    elems = sections["Elements"]
    ne = _check_count(elems[0], len(elems), "element")
    tets: List[List[int]] = []
    for k in range(ne):
        parts = elems[1 + k].split()
        etype = int(parts[1])
        if etype != 4:  # 4-node tetrahedron
            continue
        ntags = int(parts[2])
        vs = parts[3 + ntags: 7 + ntags]
        tets.append([int(v) for v in vs])
    return _finish(coords, ids, np.asarray(tets, np.int64))


def _parse_v4(sections) -> Tuple[np.ndarray, np.ndarray]:
    nodes = sections["Nodes"]
    header = nodes[0].split()
    num_blocks = _check_count(header[0], len(nodes), "node block")
    nn = _check_count(header[1], len(nodes), "node")
    ids = np.empty(nn, np.int64)
    coords = np.empty((nn, 3), np.float64)
    row, k = 1, 0
    for _ in range(num_blocks):
        bh = nodes[row].split()
        nblock = _check_count(bh[3], len(nodes), "node block size")
        row += 1
        for b in range(nblock):
            ids[k + b] = int(nodes[row + b])
        row += nblock
        for b in range(nblock):
            parts = nodes[row + b].split()
            coords[k + b] = [float(parts[0]), float(parts[1]), float(parts[2])]
        row += nblock
        k += nblock

    elems = sections["Elements"]
    header = elems[0].split()
    num_blocks = _check_count(header[0], len(elems), "element block")
    row = 1
    tets: List[List[int]] = []
    for _ in range(num_blocks):
        bh = elems[row].split()
        etype = int(bh[2])
        nblock = _check_count(bh[3], len(elems), "element block size")
        row += 1
        if etype == 4:
            for b in range(nblock):
                parts = elems[row + b].split()
                tets.append([int(v) for v in parts[1:5]])
        row += nblock
    return _finish(coords, ids, np.asarray(tets, np.int64))


# ---------------------------------------------------------------------------
# Binary
# ---------------------------------------------------------------------------

def _parse_v2_binary(data: bytes, end: str) -> Tuple[np.ndarray, np.ndarray]:
    sec = _section(data, "Nodes")
    nl = sec.find(b"\n")
    rec = np.dtype([("id", end + "i4"), ("xyz", end + "f8", (3,))])
    nn = _check_count(sec[:nl], len(sec) // rec.itemsize, "node")
    body = sec[nl + 1: nl + 1 + nn * rec.itemsize]
    nodes = np.frombuffer(body, dtype=rec, count=nn)
    ids = nodes["id"].astype(np.int64)
    coords = np.asarray(nodes["xyz"], np.float64)

    sec = _section(data, "Elements")
    nl = sec.find(b"\n")
    ne = _check_count(sec[:nl], len(sec) // 4, "element")
    off = nl + 1
    i4 = np.dtype(end + "i4")
    tets: List[np.ndarray] = []
    seen = 0
    while seen < ne:
        etype, nfollow, ntags = struct.unpack_from(end + "iii", sec, off)
        off += 12
        if etype not in _NODES_PER_ELEM_TYPE:
            raise ValueError(f"unsupported binary v2 element type {etype}")
        npn = _NODES_PER_ELEM_TYPE[etype]
        nfollow = _check_count(nfollow, (len(sec) - off) // 4, "block")
        ntags = _check_count(ntags, 1024, "tag")
        if nfollow == 0:
            # Spec-legal empty block: skip it. A pathological stream of
            # endless empty blocks still terminates — off advances 12
            # bytes per header until unpack_from runs out of section
            # and raises (wrapped into the clean ValueError).
            continue
        stride = 1 + ntags + npn
        block = np.frombuffer(
            sec, dtype=i4, count=nfollow * stride, offset=off
        ).reshape(nfollow, stride)
        off += nfollow * stride * 4
        if etype == 4:
            tets.append(block[:, 1 + ntags:].astype(np.int64))
        seen += nfollow
    all_tets = (
        np.concatenate(tets, axis=0) if tets else np.zeros((0, 4), np.int64)
    )
    return _finish(coords, ids, all_tets)


def _parse_v4_binary(data: bytes, end: str) -> Tuple[np.ndarray, np.ndarray]:
    sec = _section(data, "Nodes")
    off = 0
    num_blocks, nn, _minT, _maxT = struct.unpack_from(end + "4q", sec, off)
    off += 32
    num_blocks = _check_count(num_blocks, len(sec) // 20, "node block")
    nn = _check_count(nn, len(sec) // 32, "node")
    ids = np.empty(nn, np.int64)
    coords = np.empty((nn, 3), np.float64)
    k = 0
    for _ in range(num_blocks):
        _dim, _tag, parametric, nblock = struct.unpack_from(
            end + "iiiq", sec, off
        )
        off += 20
        if parametric:
            raise ValueError("parametric nodes not supported")
        nblock = _check_count(nblock, (len(sec) - off) // 32, "node block size")
        ids[k: k + nblock] = np.frombuffer(
            sec, dtype=end + "i8", count=nblock, offset=off
        )
        off += 8 * nblock
        coords[k: k + nblock] = np.frombuffer(
            sec, dtype=end + "f8", count=3 * nblock, offset=off
        ).reshape(nblock, 3)
        off += 24 * nblock
        k += nblock

    sec = _section(data, "Elements")
    off = 0
    num_blocks, _ne, _minT, _maxT = struct.unpack_from(end + "4q", sec, off)
    off += 32
    num_blocks = _check_count(num_blocks, len(sec) // 20, "element block")
    tets: List[np.ndarray] = []
    for _ in range(num_blocks):
        _dim, _tag, etype, nblock = struct.unpack_from(end + "iiiq", sec, off)
        off += 20
        if etype not in _NODES_PER_ELEM_TYPE:
            raise ValueError(f"unsupported binary v4 element type {etype}")
        stride = 1 + _NODES_PER_ELEM_TYPE[etype]
        nblock = _check_count(
            nblock, (len(sec) - off) // (8 * stride), "element block size"
        )
        block = np.frombuffer(
            sec, dtype=end + "i8", count=nblock * stride, offset=off
        ).reshape(nblock, stride)
        off += 8 * nblock * stride
        if etype == 4:
            tets.append(block[:, 1:].astype(np.int64))
    all_tets = (
        np.concatenate(tets, axis=0) if tets else np.zeros((0, 4), np.int64)
    )
    return _finish(coords, ids, all_tets)


def write_gmsh(
    path: str,
    coords: np.ndarray,
    tet2vert: np.ndarray,
    physical: np.ndarray | None = None,
) -> None:
    """Write a Gmsh MSH 2.2 ASCII file (tets only, 1-based node ids).

    The inverse of the v2 reader above — lets the mesh generators emit
    ``.msh`` for Gmsh-toolchain interop (the reference consumes Gmsh
    output, README.md:115-125; this writer produces it). ``physical``
    optionally carries a per-element integer id into the standard
    physical-group tag (how Gmsh meshes carry material classification).
    """
    coords = np.asarray(coords, np.float64)
    tets = np.asarray(tet2vert, np.int64) + 1
    phys = (
        np.zeros(tets.shape[0], np.int64)
        if physical is None
        else np.asarray(physical, np.int64).reshape(-1)
    )
    if phys.shape[0] != tets.shape[0]:
        raise ValueError(
            f"physical has {phys.shape[0]} values for {tets.shape[0]} tets"
        )
    lines = ["$MeshFormat", "2.2 0 8", "$EndMeshFormat",
             "$Nodes", str(coords.shape[0])]
    lines.extend(
        f"{i + 1} {x!r} {y!r} {z!r}"
        for i, (x, y, z) in enumerate(coords.tolist())
    )
    lines.extend(["$EndNodes", "$Elements", str(tets.shape[0])])
    lines.extend(
        f"{i + 1} 4 2 {int(p)} {int(p)} {a} {b} {c} {d}"
        for i, ((a, b, c, d), p) in enumerate(zip(tets.tolist(), phys.tolist()))
    )
    lines.extend(["$EndElements", ""])
    with open(path, "w") as f:
        f.write("\n".join(lines))
