"""Gmsh ``.msh`` ASCII reader (formats 2.2 and 4.1), tets only.

The reference's mesh pipeline is Gmsh → ``msh2osh`` → ``.osh``
(reference README.md:115-125); we read the Gmsh file directly and keep
an ``.osh`` reader separately for meshes already converted.
Only what the tally needs is parsed: node coordinates and 4-node
tetrahedra (Gmsh element type 4).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def read_gmsh(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """Return (coords[V,3] float64, tet2vert[E,4] int32, 0-based)."""
    with open(path) as f:
        lines = f.read().splitlines()
    sections = {}
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("$") and not line.startswith("$End"):
            name = line[1:]
            j = i + 1
            while j < len(lines) and lines[j].strip() != f"$End{name}":
                j += 1
            sections[name] = lines[i + 1 : j]
            i = j + 1
        else:
            i += 1
    if "MeshFormat" not in sections:
        raise ValueError(f"{path}: not a Gmsh mesh (no $MeshFormat)")
    version = float(sections["MeshFormat"][0].split()[0])
    if sections["MeshFormat"][0].split()[1] != "0":
        raise ValueError(f"{path}: binary .msh not supported; export ASCII")
    if version >= 4.0:
        return _parse_v4(sections)
    return _parse_v2(sections)


def _parse_v2(sections) -> Tuple[np.ndarray, np.ndarray]:
    nodes = sections["Nodes"]
    nn = int(nodes[0])
    ids = np.empty(nn, np.int64)
    coords = np.empty((nn, 3), np.float64)
    for k in range(nn):
        parts = nodes[1 + k].split()
        ids[k] = int(parts[0])
        coords[k] = [float(parts[1]), float(parts[2]), float(parts[3])]
    remap = {int(v): k for k, v in enumerate(ids)}

    elems = sections["Elements"]
    ne = int(elems[0])
    tets: List[List[int]] = []
    for k in range(ne):
        parts = elems[1 + k].split()
        etype = int(parts[1])
        if etype != 4:  # 4-node tetrahedron
            continue
        ntags = int(parts[2])
        vs = parts[3 + ntags : 7 + ntags]
        tets.append([remap[int(v)] for v in vs])
    if not tets:
        raise ValueError("no tetrahedra (type 4) found in mesh")
    return coords, np.asarray(tets, np.int32)


def _parse_v4(sections) -> Tuple[np.ndarray, np.ndarray]:
    nodes = sections["Nodes"]
    header = nodes[0].split()
    num_blocks, nn = int(header[0]), int(header[1])
    ids = np.empty(nn, np.int64)
    coords = np.empty((nn, 3), np.float64)
    row, k = 1, 0
    for _ in range(num_blocks):
        bh = nodes[row].split()
        nblock = int(bh[3])
        row += 1
        for b in range(nblock):
            ids[k + b] = int(nodes[row + b])
        row += nblock
        for b in range(nblock):
            parts = nodes[row + b].split()
            coords[k + b] = [float(parts[0]), float(parts[1]), float(parts[2])]
        row += nblock
        k += nblock
    remap = {int(v): i for i, v in enumerate(ids)}

    elems = sections["Elements"]
    header = elems[0].split()
    num_blocks = int(header[0])
    row = 1
    tets: List[List[int]] = []
    for _ in range(num_blocks):
        bh = elems[row].split()
        etype, nblock = int(bh[2]), int(bh[3])
        row += 1
        if etype == 4:
            for b in range(nblock):
                parts = elems[row + b].split()
                tets.append([remap[int(v)] for v in parts[1:5]])
        row += nblock
    if not tets:
        raise ValueError("no tetrahedra (type 4) found in mesh")
    return coords, np.asarray(tets, np.int32)
