"""Partitioned-mesh facade: the three-call protocol over element
ownership + particle migration (parallel/partition.py).

Same caller contract as ``PumiTally`` — staging, flying-zeroing side
effect, timing, VTK output are all inherited — but the device engine
shards the MESH (each chip owns a contiguous block of elements and only
its slice of the flux) instead of replicating it, and ships particles
between chips when they cross partition boundaries. This is the
TPU-native realization of the reference's latent multi-rank mode
(pumipic picparts + ``search(migrate)``, reference
PumiTallyImpl.cpp:530-539, 111; SURVEY.md §2.3 "mesh-partition
parallelism").

Use when the mesh (or the flux array) is too large to replicate per
chip, or to scale tally bandwidth: flux scatter-adds go to per-chip
owned slices with no cross-chip reduction at all.
"""

from __future__ import annotations

import time
import warnings
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from pumiumtally_tpu.api.tally import PumiTally, TallyConfig
from pumiumtally_tpu.io.vtk import write_pvtu
from pumiumtally_tpu.mesh.tetmesh import TetMesh
from pumiumtally_tpu.parallel.partition import PartitionedEngine


class PartitionedPumiTally(PumiTally):
    """Track-length tally with the tet mesh sharded across the device
    mesh (element ownership + particle migration)."""

    # The engine builds its own per-chip (possibly tiered) tables from
    # the partition — see PumiTally._replicated_mesh_walk.
    _replicated_mesh_walk = False

    def __init__(
        self,
        mesh: Union[TetMesh, str],
        num_particles: int = 100_000,
        config: Optional[TallyConfig] = None,
    ):
        t0 = time.perf_counter()
        mesh = self._init_common(mesh, num_particles, config)
        if self.device_mesh is None:
            # Single-device mode: mesh blocking without any multi-chip
            # setup. With walk_vmem_max_elems set this sub-splits the
            # whole mesh into VMEM-scale blocks on the one default
            # device — the block-local walk (vmem or gather kernel)
            # replaces the monolithic-table gather.
            from pumiumtally_tpu.parallel import make_device_mesh

            if (
                jax.device_count() > 1
                and jax.devices()[0].platform != "cpu"
            ):
                # A multi-chip host defaulting to one device is almost
                # always a forgotten TallyConfig.device_mesh — say so
                # instead of silently leaving (n-1) chips idle. CPU
                # "devices" are exempt: multiples of those are virtual
                # (xla_force_host_platform_device_count test rigs), not
                # idle hardware.
                warnings.warn(
                    f"PartitionedPumiTally: no device_mesh configured; "
                    f"running on 1 of the {jax.device_count()} available "
                    f"{jax.devices()[0].platform} devices. Pass "
                    "TallyConfig(device_mesh=make_device_mesh(n)) to "
                    "use them.",
                    stacklevel=2,  # point at the constructor call site
                )
            self.device_mesh = make_device_mesh(1)
        self.engine = PartitionedEngine(
            mesh,
            self.device_mesh,
            self.num_particles,
            capacity_factor=self.config.capacity_factor,
            tol=self._tol,
            max_iters=self._max_iters,
            max_rounds=self.config.max_migration_rounds,
            check_found_all=self.config.check_found_all,
            cond_every=self.config.resolved_cond_every(),
            min_window=self.config.resolved_min_window(),
            vmem_walk_max_elems=self.config.walk_vmem_max_elems,
            block_kernel=self.config.walk_block_kernel,
            partition_method=self.config.resolved_partition_method(),
            table_dtype=self._table_dtype,
            cap_frontier=self.config.cap_frontier,
        )
        jax.block_until_ready(self.engine.part.table)
        self.tally_times.initialization_time += time.perf_counter() - t0

    # -- dispatch hooks ---------------------------------------------------
    def _dispatch_localize(self, dest: jnp.ndarray):
        return self.engine.localize(dest)  # (found_all, n_exited)

    def _current_lost(self) -> int:
        """The engine's still-lost particle count (lazy device scalar,
        cached as a host int after the first fetch)."""
        return self.engine._n_lost

    def _dispatch_move(self, origins, dests, fly, w):
        # auto_continue applies here too: when the base class detects an
        # origin echo it hands back the device array that staged last
        # move's destinations (caller order), which this engine treats
        # exactly like freshly uploaded origins.
        return self.engine.move(origins, dests, fly, w)

    def WriteTallyResults(self, filename: Optional[str] = None) -> None:
        """Normalize and write results; a ``.pvtu`` filename writes one
        binary piece per chip (the elements it owns) plus the index
        file — the rank-aware output path of the reference
        (``vtk::write_parallel``, PumiTallyImpl.cpp:415). Any other
        extension falls through to the monolithic writers."""
        out = filename or self.config.output_filename
        if not out.endswith(".pvtu"):
            return super().WriteTallyResults(filename)
        t0 = time.perf_counter()
        # part.owner is at PART granularity; with the VMEM sub-split a
        # chip owns a contiguous run of blocks_per_chip parts — pieces
        # stay one-per-CHIP (the reference's rank-aware layout).
        owner = self.engine.part.owner // self.engine.blocks_per_chip
        write_pvtu(
            out,
            np.asarray(self.mesh.coords),
            np.asarray(self.mesh.tet2vert),
            owner,
            cell_data={
                "flux": np.asarray(self.normalized_flux()),
                "volume": np.asarray(self.mesh.volumes),
                "owner": owner.astype(np.float64),
                # Same optional statistics payload as the monolithic
                # writer (flux_mean / rel_err), split per piece like
                # every other cell array.
                **self._stats_vtk_cell_data(),
            },
            # Campaign-level leakage accounting, replicated into every
            # piece (field data is global, not per-cell).
            field_data=self._vtk_field_data(),
            nparts=int(self.device_mesh.devices.size),
        )
        self.tally_times.vtk_file_write_time += time.perf_counter() - t0
        self.tally_times.print_times()

    # -- state views (caller-visible order) -------------------------------
    @property
    def x(self):  # base class blocks on this after localization
        return self.engine.state["x"]

    @property
    def flux(self) -> jnp.ndarray:
        """Owned per-chip flux assembled into original element order."""
        return self.engine.flux_original()

    @property
    def positions(self) -> np.ndarray:
        return self.engine.positions()[: self.num_particles]

    @property
    def elem_ids(self) -> np.ndarray:
        return self.engine.elem_ids()[: self.num_particles]
